"""Protocol handlers: Filter, Bind, Inspect.

Reference: pkg/scheduler/{predicate,bind,inspect}.go +
gpushare-{predicate,bind,inspect}.go. The wire structs follow the k8s
scheduler extender v1 API (vendored reference types.go:258-302):

- ExtenderArgs{Pod, Nodes?, NodeNames?} -> ExtenderFilterResult{NodeNames,
  FailedNodes, Error} — with nodeCacheCapable:true the scheduler sends only
  NodeNames (types.go:258-267), but the Nodes fallback is handled for
  non-cache-capable deployments.
- ExtenderBindingArgs{PodName, PodNamespace, PodUID, Node} ->
  ExtenderBindingResult{Error}.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any

from tpushare import contract
from tpushare.cache import (
    AllocationError, AlreadyBoundError, BindInFlightError,
    ClaimConflictError, SchedulerCache)
from tpushare.cache.nodeinfo import no_fit_reason, request_from_pod
from tpushare.contract import pod as podlib
from tpushare.core.placement import fragmentation, utilization_pct
from tpushare.core.topology import ADJ_SCALE
from tpushare.extender.metrics import LATENCY_BUCKETS, Registry
from tpushare.extender.wirecache import WireEncoded
from tpushare.ha.sharding import SHARD_CONFLICTS
from tpushare.k8s.breaker import OPEN as BREAKER_IS_OPEN
from tpushare.k8s.client import ApiError
from tpushare.k8s.informer import LISTER_REQUESTS
from tpushare.k8s.retry import DeadlineExceeded
from tpushare.k8s.singleflight import Singleflight
from tpushare.k8s.stats import api_origin
from tpushare.metrics import Counter, LabeledCounter
from tpushare.obs.trace import TRACER
from tpushare.qos.drf import admission_would_exceed, dominant_shares
from tpushare.qos.tiers import ENV_DRF_CAP, pod_tier, tier_rank
from tpushare.qos.tiers import effective_overcommit as \
    qos_effective_overcommit

log = logging.getLogger("tpushare.extender")

# process-wide (the CLAIM_CAS_RETRIES pattern; attached to the registry
# by register_cache_gauges): the fault-containment observability set.
BIND_DEADLINE_EXCEEDED = Counter(
    "tpushare_bind_deadline_exceeded_total",
    "Binds abandoned because the per-request deadline expired before "
    "the apiserver writes could complete (alert: the scheduler is "
    "giving up on webhook calls; check breaker_state and retry totals)")
BIND_FASTFAIL = Counter(
    "tpushare_bind_fastfail_total",
    "Binds refused immediately because the apiserver circuit was open "
    "(degraded mode: fail fast instead of burning the webhook timeout)")
DEGRADED_SERVES = LabeledCounter(
    "tpushare_degraded_serves_total",
    "Webhook calls served from the informer-warmed cache while the "
    "apiserver circuit was open (answers are bounded-stale; the bound "
    "is the informer staleness /readyz reports)",
    ("verb",))
# mesh-aware Prioritize: how hard adjacency pulls against the binpack
# leftover score. Guaranteed serving replicas get the full configured
# weight (their dp x tp collectives run every step, so ICI contiguity is
# throughput), burstable a majority share, best-effort a taste — a
# best-effort pod should soak fragments, not claim the pristine boxes.
_TIER_TOPO_FACTOR = {"guaranteed": 1.0, "burstable": 0.6,
                     "best-effort": 0.3}


def topo_weight(pod: dict[str, Any]) -> float:
    """Effective adjacency blend weight for this pod's tier: the
    ``TPUSHARE_TOPO_WEIGHT`` knob (default 0.5, clamped to [0, 1])
    scaled by the QoS tier factor. 0 disables the blend entirely."""
    try:
        w = float(os.environ.get("TPUSHARE_TOPO_WEIGHT", "0.5"))
    except ValueError:
        w = 0.5
    w = min(max(w, 0.0), 1.0)
    return w * _TIER_TOPO_FACTOR.get(pod_tier(pod), 0.3)


MESH_SHAPE_REJECTS = Counter(
    "tpushare_mesh_shape_rejects_total",
    "Filter calls rejected outright because the pod's mesh-shape "
    "annotation was malformed (bad grammar, non-positive axis, or a "
    "product that disagrees with the chip-count request). The pod "
    "stays Pending with a per-node FailedNodes reason naming the "
    "defect; fix the annotation and resubmit (alert: a template is "
    "stamping broken shapes)")


class FilterHandler:
    """Per-scheduling-attempt fit check over candidate nodes
    (reference Predicate.Handler, predicate.go:15-39)."""

    def __init__(self, cache: SchedulerCache, registry: Registry,
                 gang=None, breaker=None, staleness_fn=None,
                 tracer=None, explain=None, batcher=None,
                 wire=None) -> None:
        self._cache = cache
        self._gang = gang  # GangCoordinator | None
        # wire-plane cache (extender/wirecache.py): when the server front
        # end digest-decoded this request, the encoded reply is cached
        # under (digest, request signature, mutation stamp) and served as
        # raw bytes. None = always compute + dict-encode (direct callers).
        self._wire = wire
        # batched decision cycles (cache/batch.py BatchPlanner):
        # concurrently-arriving same-signature pods coalesce into one
        # multi-pod native solve; a member's Filter answers with its
        # assigned node only. None (or a disabled planner) = every pod
        # runs the single-pod path.
        self._batcher = batcher
        # degraded mode: when the apiserver circuit is open this verb
        # keeps answering from the informer-warmed cache — correct up to
        # the staleness bound staleness_fn reports — and the serve is
        # counted so operators can see how much traffic ran degraded
        self._breaker = breaker
        self._staleness_fn = staleness_fn
        # observability (obs/): Filter STARTS the pod's scheduling-cycle
        # trace, and every candidate verdict is recorded for
        # /inspect/explain. Defaults to the process tracer so directly
        # constructed handlers (bench, tests) trace too.
        self._tracer = tracer or TRACER
        self._explain = explain  # ExplainStore | None
        self._filter_total = registry.counter(
            "tpushare_filter_requests_total", "Filter webhook calls")
        self._filter_latency = registry.histogram(
            "tpushare_filter_seconds", "Filter latency", LATENCY_BUCKETS)

    def handle(self, args: dict[str, Any],
               wire_ctx=None) -> dict[str, Any] | WireEncoded:
        with api_origin("filter"):
            return self._handle(args, wire_ctx)

    def _handle(self, args: dict[str, Any],
                wire_ctx=None) -> dict[str, Any] | WireEncoded:
        t0 = time.perf_counter()
        self._filter_total.inc()
        pod = args.get("Pod") or {}
        pod_key = podlib.pod_cache_key(pod)
        trace = self._tracer.begin_cycle(pod_key, pod)
        with self._tracer.root_span(trace, "filter") as sp:
            result = self._filter(args, pod, pod_key, trace, sp, wire_ctx)
            if isinstance(result, WireEncoded):
                sp.set_tags(ok=result.ok, failed=result.failed,
                            wire=result.outcome)
            else:
                sp.set_tags(ok=len(result["NodeNames"]),
                            failed=len(result["FailedNodes"]))
        self._filter_latency.observe(
            time.perf_counter() - t0,
            exemplar=trace.trace_id if trace else None)
        return result

    def _filter(self, args: dict[str, Any], pod: dict[str, Any],
                pod_key: str, trace, sp,
                wire_ctx=None) -> dict[str, Any] | WireEncoded:
        if self._breaker is not None and \
                self._breaker.state == BREAKER_IS_OPEN:
            DEGRADED_SERVES.inc("filter")
            sp.set_tag("degraded", True)
            stale = self._staleness_fn() if self._staleness_fn else None
            log.debug("filter: serving degraded from cache (apiserver "
                      "circuit open; staleness bound %s s)",
                      f"{stale:.1f}" if stale is not None else "unknown")
        node_names = args.get("NodeNames")
        if node_names is None:
            items = (args.get("Nodes") or {}).get("items") or []
            node_names = [n.get("metadata", {}).get("name", "")
                          for n in items]
        trace_id = trace.trace_id if trace else None

        def audit(nodes: dict[str, dict[str, Any]]) -> None:
            if self._explain is not None:
                self._explain.record_filter(pod_key, pod, trace_id, nodes)

        # gang members route through the coordinator: exactly one host
        # (the planned one for this member's rank) comes back, so the
        # default scheduler cannot diverge from the gang geometry
        # (docs/designs/multihost-gang.md protocol step 1)
        if self._gang is not None:
            try:
                membership = podlib.gang_membership(pod)
            except ValueError as e:
                return {"NodeNames": [], "FailedNodes": {},
                        "Error": str(e)}
            if membership is not None:
                gid, size, rank = membership
                sp.set_tag("gang", gid)
                hosts, reason = self._gang.filter_hosts(
                    pod, trace_id=trace_id)
                hosts = [h for h in hosts if h in set(node_names)]
                failed = {} if hosts else {
                    n: reason or "not the planned gang host"
                    for n in node_names if n}
                if hosts and self._explain is not None:
                    # every member's explain record points at the
                    # LEADER's trace (one solve planned the whole
                    # gang; followers are memo reads off that plan)
                    info = self._gang.plan_info(gid)
                    self._explain.record_gang(
                        pod_key, pod, trace_id,
                        leader_trace_id=(info or {}).get(
                            "leader_trace_id") or trace_id,
                        gang_id=gid, size=size, rank=rank,
                        node=hosts[0])
                else:
                    audit({n: {"verdict": "ok",
                               "reason": "planned gang host"}
                           for n in hosts}
                          | {n: {"verdict": "rejected", "reason": r}
                             for n, r in failed.items()})
                log.debug("filter gang %s: -> %s",
                          podlib.pod_key(pod), hosts)
                return {"NodeNames": hosts, "FailedNodes": failed,
                        "Error": ""}
        ok_nodes: list[str] = []
        failed: dict[str, str] = {}
        verdicts: dict[str, dict[str, Any]] = {}
        # strict_mesh: a malformed mesh-shape annotation is a user error
        # the author can fix, so Filter rejects every node with a distinct
        # reason instead of silently scheduling shape-blind. Later verbs
        # stay lenient — a pod that failed here never reaches them, and
        # leniency keeps eviction/accounting paths total.
        try:
            req = request_from_pod(pod, strict_mesh=True)
        except ValueError as e:
            MESH_SHAPE_REJECTS.inc()
            reason = f"invalid mesh-shape annotation: {e}"
            audit({n: {"verdict": "rejected", "reason": reason}
                   for n in node_names if n})
            log.warning("filter %s: %s", podlib.pod_key(pod), reason)
            return {"NodeNames": [],
                    "FailedNodes": {n: reason for n in node_names if n},
                    "Error": ""}
        node_names = [n for n in node_names if n]
        if req is not None and req.hbm_mib > 0:
            oc = qos_effective_overcommit()
            if oc > 1.0:
                # QoS-active fleet (TPUSHARE_QOS_OVERCOMMIT > 1 and the
                # evictor healthy): every tpushare pod takes the tiered
                # per-candidate path — best-effort may borrow idle HBM
                # up to total*oc, guaranteed/burstable count evictable
                # best-effort usage as headroom. This deliberately
                # bypasses memo/native/index/batcher/wirecache: those
                # layers reason about PHYSICAL free HBM, and serving a
                # tier-adjusted verdict from a tier-blind cache is how
                # byte-honesty dies. At oc == 1.0 (the default) this
                # branch never runs and the fast paths are untouched.
                return self._filter_qos(pod, pod_key, req, node_names,
                                        sp, audit)
        if req is not None and self._batcher is not None \
                and self._batcher.enabled:
            # batched decision cycles: same-signature pods arriving
            # within the window share ONE multi-pod solve; a covered
            # member answers with exactly its assigned node (the gang
            # shape — the extender may return any subset) and its
            # speculative placement is already stashed for Prioritize/
            # Bind. A None result = run the ordinary path below.
            spec = self._batcher.submit(pod, req, node_names, trace_id)
            if spec is not None:
                sp.set_tags(batch_size=spec.batch_size,
                            batch="leader" if spec.leader else "member",
                            batch_leader_trace=spec.leader_trace_id)
                # the audit must never show a batched pod as computed:
                # record_batch writes the membership record AND the
                # single source=batched filter verdict in one notify
                if self._explain is not None:
                    self._explain.record_batch(
                        pod_key, pod, trace_id,
                        leader_trace_id=spec.leader_trace_id,
                        size=spec.batch_size, node=spec.node)
                log.debug("filter %s: batched -> %s (k=%d)",
                          podlib.pod_key(pod), spec.node,
                          spec.batch_size)
                return {"NodeNames": [spec.node], "FailedNodes": {},
                        "Error": ""}
        wire, wire_key, wire_hit = self._wire, None, None
        if wire is not None and wire_ctx is not None and req is not None \
                and (self._batcher is None or not self._batcher.enabled):
            # response cache: same digest + same request signature + no
            # cache mutation since => byte-identical verdict. Batched
            # deployments bypass (a hit would dodge the batch window).
            wire_key = req  # frozen dataclass: the signature IS the key
            wire_hit = wire.lookup(wire_ctx, "filter", wire_key)
            if wire_hit is not None and not wire.verify:
                wire.served_hit("filter")
                if self._explain is not None:
                    self._explain.record_wire(
                        pod_key, pod, trace_id, "filter",
                        ok=wire_hit.ok, candidates=wire_hit.ok
                        + wire_hit.failed)
                log.debug("filter %s: wirecache hit (%d ok / %d failed)",
                          podlib.pod_key(pod), wire_hit.ok, wire_hit.failed)
                return wire_hit
        if req is None:
            # not a tpushare pod: nothing to check (handler shouldn't even
            # be consulted thanks to managedResources, but be permissive)
            for name in node_names:
                try:
                    self._cache.get_node_info(name)
                except ApiError as e:
                    failed[name] = f"node unavailable: {e}"
                    verdicts[name] = {"verdict": "rejected",
                                      "reason": failed[name]}
                    continue
                ok_nodes.append(name)
                verdicts[name] = {"verdict": "ok",
                                  "reason": "no TPU request to check"}
        else:
            # one memoized native call evaluates the candidates that
            # survive the memo + eqclass join + capacity-index prune
            # (hot loops #1+#2 of SURVEY §3.2 fused, then made sublinear
            # in fleet size) — Prioritize and Bind reuse this exact pass
            prov: dict[str, str] = {}
            scores, errors = self._cache.score_nodes(pod, req, node_names,
                                                     provenance=prov)
            for name in node_names:
                src = prov.get(name)
                if name in errors:
                    failed[name] = errors[name]
                    verdicts[name] = {"verdict": "rejected",
                                      "reason": errors[name],
                                      "source": src}
                elif scores.get(name) is not None:
                    ok_nodes.append(name)
                    verdicts[name] = {"verdict": "ok",
                                      "score": scores[name],
                                      "source": src}
                else:
                    # the WIRE verdict is identical either way (the
                    # index only prunes certain no-fits), but the audit
                    # stays truthful: a pruned node was never visited,
                    # and the bucket that excluded it is recorded
                    failed[name] = no_fit_reason(req, name)
                    if src and src.startswith("pruned:"):
                        verdicts[name] = {"verdict": "skipped",
                                          "reason": "index-pruned",
                                          "bucket": src.split(":", 1)[1],
                                          "source": "index"}
                    else:
                        verdicts[name] = {"verdict": "rejected",
                                          "reason": failed[name],
                                          "source": src}
        audit(verdicts)
        log.debug("filter %s: %d ok / %d failed",
                  podlib.pod_key(pod), len(ok_nodes), len(failed))
        if wire_key is not None:
            # transient fetch failures ("node unavailable: ...") are never
            # memoized — the node's recovery would not bump the stamp
            cacheable = not any(r.startswith("node unavailable:")
                                for r in failed.values())
            wire_ctx.pod_key, wire_ctx.pod = pod_key, pod
            return wire.finish_filter(wire_ctx, wire_key, ok_nodes, failed,
                                      cacheable=cacheable,
                                      expected=wire_hit)
        return {"NodeNames": ok_nodes, "FailedNodes": failed, "Error": ""}

    def _filter_qos(self, pod: dict[str, Any], pod_key: str, req,
                    node_names: list[str], sp, audit) -> dict[str, Any]:
        """Tiered per-candidate Filter (QoS active, ISSUE 17): DRF
        tenant cap first, then NodeInfo.assume_qos per candidate. Plain
        dict return — no wirecache finish, no memoized placement hint
        (Bind re-searches fresh under the node lock, where the same
        tier-adjusted views are applied atomically)."""
        tier = pod_tier(pod)
        sp.set_tags(qos_tier=tier)
        ok_nodes: list[str] = []
        failed: dict[str, str] = {}
        verdicts: dict[str, dict[str, Any]] = {}
        ns = podlib.pod_namespace(pod)
        if admission_would_exceed(self._cache, ns, req.chip_count,
                                  req.hbm_mib * req.chip_count):
            reason = (f"namespace {ns} dominant share (chips or HBM) "
                      f"would exceed the tenant DRF cap ({ENV_DRF_CAP})")
            for name in node_names:
                failed[name] = reason
                verdicts[name] = {"verdict": "rejected", "reason": reason,
                                  "source": "qos-drf"}
            audit(verdicts)
            log.debug("filter %s: DRF cap rejection (ns=%s)",
                      podlib.pod_key(pod), ns)
            return {"NodeNames": [], "FailedNodes": failed, "Error": ""}
        for name in node_names:
            try:
                info = self._cache.get_node_info(name)
            except ApiError as e:
                failed[name] = f"node unavailable: {e}"
                verdicts[name] = {"verdict": "rejected",
                                  "reason": failed[name], "source": "qos"}
                continue
            ok, reason = info.assume_qos(pod)
            if ok:
                ok_nodes.append(name)
                verdicts[name] = {"verdict": "ok", "source": "qos"}
            else:
                failed[name] = reason
                verdicts[name] = {"verdict": "rejected", "reason": reason,
                                  "source": "qos"}
        audit(verdicts)
        log.debug("filter %s (qos tier=%s): %d ok / %d failed",
                  podlib.pod_key(pod), tier, len(ok_nodes), len(failed))
        return {"NodeNames": ok_nodes, "FailedNodes": failed, "Error": ""}


class PrioritizeHandler:
    """The extender ``prioritize`` verb: rank filter-passing nodes so the
    default scheduler packs tightly instead of spreading.

    The extender API supports a prioritizeVerb next to filter/bind
    (ExtenderConfig.PrioritizeVerb, /root/reference/vendor/k8s.io/
    kubernetes/pkg/scheduler/api/types.go:183-188); the reference never
    registers one, so its cross-node packing quality is whatever the
    default scheduler's generic spreading produces. tpushare ranks by the
    same tightest-fit policy its simulator proves out
    (sim/simulator.py::_policy_binpack): the node whose best placement
    leaves the least free HBM on the chosen chips scores highest, driving
    the fleet toward the >=90% utilization north star.

    Returns a HostPriorityList ([{"Host", "Score"}], scores 0..10 =
    MaxExtenderPriority); the scheduler adds Score x weight to each node.
    """

    MAX_PRIORITY = 10  # k8s MaxExtenderPriority

    def __init__(self, cache: SchedulerCache, registry: Registry,
                 breaker=None, tracer=None, explain=None,
                 wire=None, forecast=None) -> None:
        self._cache = cache
        self._wire = wire  # wire-plane response cache, like Filter
        self._breaker = breaker  # degraded-mode accounting, like Filter
        self._tracer = tracer or TRACER  # joins the cycle Filter opened
        self._explain = explain  # ExplainStore | None
        # fragmentation-pressure forecast (defrag/forecast.py): under
        # stranded-gap pressure, low-tier pods are steered toward
        # already-fragmented nodes so pristine boxes stay whole. None or
        # TPUSHARE_FRAG_WEIGHT=0 keeps this path byte-identical.
        self._forecast = forecast
        self._prioritize_total = registry.counter(
            "tpushare_prioritize_requests_total", "Prioritize webhook calls")
        self._prioritize_latency = registry.histogram(
            "tpushare_prioritize_seconds", "Prioritize latency",
            LATENCY_BUCKETS)

    def handle(self, args: dict[str, Any],
               wire_ctx=None) -> list[dict[str, Any]] | WireEncoded:
        with api_origin("prioritize"):
            return self._handle(args, wire_ctx)

    def _handle(self, args: dict[str, Any],
                wire_ctx=None) -> list[dict[str, Any]] | WireEncoded:
        t0 = time.perf_counter()
        self._prioritize_total.inc()
        pod = args.get("Pod") or {}
        pod_key = podlib.pod_cache_key(pod)
        trace = self._tracer.join_or_begin(pod_key, pod)
        with self._tracer.root_span(trace, "prioritize") as sp:
            out = self._prioritize(args, pod, pod_key, trace, sp, wire_ctx)
        self._prioritize_latency.observe(
            time.perf_counter() - t0,
            exemplar=trace.trace_id if trace else None)
        return out

    def _prioritize(self, args: dict[str, Any], pod: dict[str, Any],
                    pod_key: str, trace, sp,
                    wire_ctx=None) -> list[dict[str, Any]] | WireEncoded:
        if self._breaker is not None and \
                self._breaker.state == BREAKER_IS_OPEN:
            DEGRADED_SERVES.inc("prioritize")
            sp.set_tag("degraded", True)
        node_names = args.get("NodeNames")
        if node_names is None:
            items = (args.get("Nodes") or {}).get("items") or []
            node_names = [n.get("metadata", {}).get("name", "")
                          for n in items]
        node_names = [n for n in node_names if n]
        req = request_from_pod(pod)
        forecast = self._forecast
        f_eff = forecast.weight(pod) \
            if forecast is not None and req is not None else 0.0
        frag_nodes = forecast.fragmented_nodes() if f_eff > 0.0 \
            else frozenset()
        wire, wire_key, wire_hit = self._wire, None, None
        if wire is not None and wire_ctx is not None and req is not None:
            wire_key = req
            wire_hit = wire.lookup(wire_ctx, "prioritize", wire_key)
            # under frag pressure the blend drifts with the fleet's
            # stranded-gap trend, so a byte-replay of an earlier ranking
            # would serve stale bias: compute fresh instead
            if wire_hit is not None and not wire.verify \
                    and f_eff <= 0.0:
                wire.served_hit("prioritize")
                if wire_hit.best is not None:
                    # keep Bind's seed hint warm exactly like a computed
                    # pass would (the hint is stamp-revalidated there)
                    self._cache.memo_best_placement(pod, req, wire_hit.best)
                sp.set_tags(candidates=wire_hit.count, best=wire_hit.best,
                            wire="hit")
                if self._explain is not None:
                    self._explain.record_wire(
                        pod_key, pod, trace.trace_id if trace else None,
                        "prioritize", best=wire_hit.best,
                        candidates=wire_hit.count)
                return wire_hit
        had_errors = False
        raw: dict[str, int | None] = {}  # name -> leftover score (lower=tighter)
        # mesh-shape pods: score_nodes also surfaces each node's best-box
        # adjacency quality (0..ADJ_SCALE, same stamps as the scores) so
        # the ranking below can trade binpack tightness against ICI
        # contiguity. None for everyone else — the shape-blind path is
        # byte-identical.
        adjacency: dict[str, int] | None = \
            {} if req is not None and req.mesh_shape is not None else None
        if req is not None:
            # the memoized fleet pass: when Filter just ran for this pod
            # (the normal webhook sequence), this is a pure dict read —
            # zero native scans, zero snapshot assembly
            scores, errors = self._cache.score_nodes(pod, req, node_names,
                                                     adjacency=adjacency)
            had_errors = bool(errors)
            for name in node_names:
                raw[name] = None if name in errors else scores.get(name)
        w_eff = topo_weight(pod) if adjacency else 0.0
        fitting = [s for s in raw.values() if s is not None]
        lo, hi = (min(fitting), max(fitting)) if fitting else (0, 0)
        out = []
        best_name: str | None = None
        for name in node_names:
            s = raw.get(name)
            if req is None:
                score = 0  # nothing to say about non-tpushare pods
            elif s is None:
                score = 0  # no placement (filter should have removed it)
            elif hi == lo:
                score = self.MAX_PRIORITY
            else:
                # tightest (lowest leftover) -> 10, loosest -> 0
                score = round(self.MAX_PRIORITY * (hi - s) / (hi - lo))
            if s is not None and w_eff > 0.0:
                adj = adjacency.get(name)  # type: ignore[union-attr]
                if adj is not None and adj >= 0:
                    # tier-weighted blend: binpack pulls toward tight
                    # nodes, adjacency toward mesh-congruent boxes; the
                    # tier factor decides who wins the argument
                    p_adj = self.MAX_PRIORITY * adj / ADJ_SCALE
                    score = round((1.0 - w_eff) * score + w_eff * p_adj)
            if s is not None and f_eff > 0.0:
                # binpack-vs-scatter blend: under fragmentation
                # pressure, steer this pod toward nodes that are
                # ALREADY fragmented (soak the holes) so pristine
                # contiguous boxes stay whole for the gangs that need
                # them — every hole filled upstream is a migration the
                # rebalancer never has to buy
                p_frag = self.MAX_PRIORITY if name in frag_nodes else 0
                score = round((1.0 - f_eff) * score + f_eff * p_frag)
            if s is not None and best_name is None:
                best_name = name  # ties resolve to the first, like max()
            elif s is not None and s < raw[best_name]:  # type: ignore[index]
                best_name = name
            out.append({"Host": name, "Score": score})
        if w_eff > 0.0 or f_eff > 0.0:
            # Bind's seed hint must chase the node the scheduler will
            # actually pick — the blended top, not the binpack top
            ranked = [h for h in out if raw.get(h["Host"]) is not None]
            if ranked:
                top = max(h["Score"] for h in ranked)
                best_name = next(h["Host"] for h in ranked
                                 if h["Score"] == top)
        if req is not None and best_name is not None:
            # pre-compute the chip selection for the top-ranked node: the
            # scheduler's weighted choice almost always lands there, and
            # Bind then seeds allocate from this instead of re-searching
            self._cache.memo_best_placement(pod, req, best_name)
        sp.set_tags(candidates=len(node_names), best=best_name)
        if self._explain is not None:
            self._explain.record_prioritize(
                pod_key, pod, trace.trace_id if trace else None,
                {h["Host"]: h["Score"] for h in out}, best_name)
        if wire_key is not None:
            wire_ctx.pod_key, wire_ctx.pod = pod_key, pod
            return wire.finish_prioritize(wire_ctx, wire_key, out,
                                          best_name,
                                          cacheable=not had_errors
                                          and f_eff <= 0.0,
                                          expected=wire_hit)
        return out


class PreemptHandler:
    """The extender ``preempt`` verb — victim refinement the reference
    never implemented (ExtenderConfig.PreemptVerb, reference vendored
    types.go:183,219-254; the reference registers only filter + bind).

    kube-scheduler's preemption phase picks victims per node against the
    SCALAR extended resource, which has exactly the blind spot the whole
    extender exists to fix (designs.md:13,34,42 — node-level free is not
    chip-level free): its victim set can free plenty of node HBM without
    making any single chip (or contiguous sub-slice) able to host the
    preemptor. This verb re-checks each candidate node's victims against
    the per-chip cache and returns, per node, a 1-minimal victim subset
    that actually makes the pod placeable — or drops the node from the
    candidate map entirely when no eviction helps, steering preemption
    toward nodes where it works.

    Wire shapes (types.go:219-254): ExtenderPreemptionArgs{Pod,
    NodeNameToMetaVictims} with nodeCacheCapable:true (MetaPod carries
    only UID; resolved via the cache's known-pods registry), or
    NodeNameToVictims with full pod objects otherwise. The reply is
    always the meta form, as the scheduler expects from cache-capable
    extenders. NumPDBViolations is passed through unchanged: shrinking
    the victim set can only remove violations, so the scheduler's count
    is a safe upper bound (per-victim PDB attribution is not on the
    wire).

    Shrink soundness: kube-scheduler does NOT re-run its filters after
    an extender edits a victim set — it evicts exactly what the reply
    names. Its own victim selection satisfied EVERY constraint (CPU,
    memory, pod count, affinity), so dropping victims is only safe when
    TPU fit is provably the sole binding constraint: the preemptor
    requests nothing but the managed TPU resources and carries no
    affinity terms. Otherwise this handler VALIDATES but never shrinks —
    the node is kept (full victim set) or dropped, so a CPU-bottlenecked
    preemptor can never be stranded by a TPU-only refinement.
    """

    def __init__(self, cache: SchedulerCache, registry: Registry) -> None:
        self._cache = cache
        self._preempt_total = registry.counter(
            "tpushare_preempt_requests_total", "Preempt webhook calls")
        self._preempt_nodes_dropped = registry.counter(
            "tpushare_preempt_nodes_dropped_total",
            "Candidate nodes dropped because no victim set makes the "
            "preemptor fit per-chip")
        self._preempt_node_errors = registry.counter(
            "tpushare_preempt_node_errors_total",
            "Candidate nodes skipped because the node lookup failed "
            "(apiserver/cache error — NOT a capacity verdict)")
        self._preempt_latency = registry.histogram(
            "tpushare_preempt_seconds", "Preempt latency", LATENCY_BUCKETS)

    def _victim_order(self, victims: dict[str, Any], meta: bool,
                      preemptor: dict[str, Any] | None = None
                      ) -> list[str]:
        """Victim UIDs, cheapest eviction first.

        When every victim's priority resolves (full pods on the wire, or
        UIDs found in the known-pods registry), sort by (QoS tier rank,
        priority) — best-effort victims go before burstable before
        guaranteed, lowest priority first within a tier, stable within
        ties. When ``preemptor`` is given (only when shrinking is
        allowed — see _handle), victims at a strictly HIGHER tier than
        the preemptor are excluded outright: preemption escalates by
        tier, and a best-effort pod must never cost a guaranteed pod
        its reservation (ISSUE 17 isolation invariant). On a fleet that
        never sets the tier annotation every pod is burstable, so
        nothing is excluded and the order is exactly the legacy
        priority order.

        When ANY victim is unresolvable (meta form during controller
        watch lag), sorting or tier-filtering with guessed defaults
        could put a priority-100 pod ahead of a priority-0 one — instead
        fall back to REVERSING the scheduler's own list, which
        kube-scheduler builds highest-priority-first, so reversed order
        is still cheapest-first without inventing priorities.
        """
        entries = (victims or {}).get("Pods") or []
        cand: list[tuple[int, int, str]] = []
        unresolved = False
        for p in entries:
            if meta:
                uid = (p or {}).get("UID", "")
                pobj = self._cache.pod_by_key(uid)
            else:
                uid = podlib.pod_cache_key(p or {})
                pobj = p or {}
            if not uid:
                continue
            if pobj is None:
                unresolved = True
                cand.append((0, 0, uid))
                continue
            prio = (pobj.get("spec") or {}).get("priority") or 0
            cand.append((tier_rank(pod_tier(pobj)), prio, uid))
        if unresolved:
            return [uid for _, _, uid in reversed(cand)]
        if preemptor is not None:
            pr = tier_rank(pod_tier(preemptor))
            cand = [t for t in cand if t[0] <= pr]
        cand.sort(key=lambda t: (t[0], t[1]))
        return [uid for _, _, uid in cand]

    @staticmethod
    def _tpu_only(pod: dict[str, Any]) -> bool:
        """True when TPU fit is provably the pod's only binding
        scheduling constraint that evicting a victim could relieve: no
        unmanaged resource requests (main AND init containers, pod
        overhead), no host ports (freed only by evicting the holder), no
        (anti-)affinity, no topology spread constraints."""
        spec = pod.get("spec") or {}
        if spec.get("affinity") or spec.get("topologySpreadConstraints"):
            return False
        managed = {contract.RESOURCE_HBM, contract.RESOURCE_COUNT}
        for name in spec.get("overhead") or {}:
            if name not in managed:
                return False
        for c in (spec.get("containers") or []) + \
                (spec.get("initContainers") or []):
            res = c.get("resources") or {}
            for kind in ("limits", "requests"):
                for name in res.get(kind) or {}:
                    if name not in managed:
                        return False
            for port in c.get("ports") or []:
                if port.get("hostPort"):
                    return False
        return True

    def handle(self, args: dict[str, Any]) -> dict[str, Any]:
        with api_origin("preempt"):
            pod = args.get("Pod") or {}
            trace = TRACER.join_or_begin(podlib.pod_cache_key(pod), pod)
            with TRACER.root_span(trace, "preempt") as sp:
                out = self._handle(args)
                sp.set_tag("nodes_kept",
                           len(out.get("NodeNameToMetaVictims") or {}))
            return out

    def _handle(self, args: dict[str, Any]) -> dict[str, Any]:
        t0 = time.perf_counter()
        self._preempt_total.inc()
        pod = args.get("Pod") or {}
        meta_map = args.get("NodeNameToMetaVictims")
        source = meta_map if meta_map is not None \
            else (args.get("NodeNameToVictims") or {})
        shrink = self._tpu_only(pod)
        result: dict[str, Any] = {}
        for node_name, victims in source.items():
            # tier exclusion rides the shrink gate: dropping a victim
            # from the reply is only sound when this extender is allowed
            # to edit the set at all (see class docstring)
            order = self._victim_order(
                victims, meta_map is not None,
                preemptor=pod if shrink else None)
            try:
                info = self._cache.get_node_info(node_name)
            except ApiError as e:
                log.warning("preempt %s: node %s unavailable: %s",
                            podlib.pod_key(pod), node_name, e)
                self._preempt_node_errors.inc()
                continue
            subset = info.victims_to_fit(pod, order)
            if subset is None:
                # even evicting every candidate leaves no chip/sub-slice
                # for the preemptor: preempting here would be pure damage
                self._preempt_nodes_dropped.inc()
                continue
            # [] means TPU fit holds even with every victim still
            # present — the scheduler preempted for a constraint this
            # extender cannot see (max-pods, stale cache, ...). A
            # zero-victim reply would make the scheduler nominate the
            # node and evict NOBODY, looping the pod Pending forever;
            # fall back to the scheduler's own (whole-constraint) victim
            # choice. Eviction is monotone for TPU fit, so the full set
            # still satisfies this extender's dimension.
            kept = subset if shrink and subset else order
            if not kept and (victims or {}).get("Pods"):
                # tier escalation excluded EVERY victim (all at a higher
                # tier than the preemptor): an empty-victim reply would
                # nominate the node and evict nobody, looping the pod
                # Pending — drop the node instead
                self._preempt_nodes_dropped.inc()
                continue
            result[node_name] = {
                "Pods": [{"UID": u} for u in kept],
                "NumPDBViolations":
                    (victims or {}).get("NumPDBViolations", 0),
            }
        self._preempt_latency.observe(time.perf_counter() - t0)
        log.debug("preempt %s: %d/%d candidate nodes kept (shrink=%s)",
                  podlib.pod_key(pod), len(result), len(source), shrink)
        return {"NodeNameToMetaVictims": result}


class BindHandler:
    """The delegated bind verb: choose chips, annotate, bind
    (reference Bind.Handler -> gpusharingbinding, gpushare-bind.go:22-43)."""

    def __init__(self, cache: SchedulerCache, cluster,
                 registry: Registry, ha_claims: bool = False,
                 gang=None, pod_lister=None, breaker=None,
                 tracer=None, explain=None, sharding=None) -> None:
        self._cache = cache
        self._cluster = cluster
        self._ha_claims = ha_claims
        # active-active mode (ha/sharding.py): per-bind claim decision —
        # a shard-owned (and revalidated) node binds lock-free, foreign
        # spillover keeps the claim CAS. Overrides ha_claims per node.
        self._sharding = sharding
        self._gang = gang  # GangCoordinator | None
        # observability: Bind joins (or opens) the pod's cycle trace,
        # CLOSES it on exit, and stamps the trace context into the
        # placement annotations so the device plugin's Allocate joins
        # the same trace across the process boundary
        self._tracer = tracer or TRACER
        self._explain = explain  # ExplainStore | None
        # degraded mode: an open apiserver circuit makes every bind
        # write doomed — refuse up front (distinct error, ~0 ms) instead
        # of reserving chips, failing the writes, and rolling back while
        # the scheduler's webhook timeout burns
        self._breaker = breaker
        # watch-warmed pod store (k8s/informer.py): bind-path pod reads
        # are answered locally, with the apiserver GET kept only as the
        # miss/UID-mismatch fallback — coalesced so duplicate deliveries
        # of the same bind share one round-trip
        self._pod_lister = pod_lister
        self._sf = Singleflight()
        self.bind_total = registry.counter(
            "tpushare_bind_requests_total", "Bind webhook calls")
        self.bind_failures = registry.counter(
            "tpushare_bind_failures_total", "Failed binds")
        self.bind_latency = registry.histogram(
            "tpushare_bind_seconds",
            "Schedule-to-bind latency (the BASELINE p50<50ms metric)",
            LATENCY_BUCKETS)
        self.claim_conflicts = registry.counter(
            "tpushare_ha_claim_conflicts_total",
            "Binds refused by a concurrent replica's node claim (HA "
            "backpressure; sustained growth = replicas fighting over "
            "the same nodes)")

    def _claims_for(self, node: str, gang: bool) -> bool:
        """Whether THIS bind needs the per-node claim CAS. Without
        sharding: the static ha_claims flag (active-passive). With
        sharding: an owned+revalidated node skips the CAS (outcome
        ``owned`` — the restored plain path, including the whole fleet
        on a single-replica ring), anything else keeps it (``spillover``).
        A gang bind reserves across MULTIPLE nodes, so it only goes
        lock-free when one replica owns the entire fleet (ring of 1)."""
        if self._sharding is None:
            return self._ha_claims
        if gang:
            solo = self._sharding.is_live() and \
                len(self._sharding.members()) == 1
            SHARD_CONFLICTS.inc("owned" if solo else "spillover")
            return not solo
        if self._sharding.owns_for_bind(node):
            SHARD_CONFLICTS.inc("owned")
            return False
        SHARD_CONFLICTS.inc("spillover")
        return True

    def handle(self, args: dict[str, Any],
               forwarded_from: str | None = None) -> dict[str, Any]:
        with api_origin("bind"):
            return self._handle(args, forwarded_from)

    def _handle(self, args: dict[str, Any],
                forwarded_from: str | None = None) -> dict[str, Any]:
        ns = args.get("PodNamespace", "default")
        name = args.get("PodName", "")
        uid = args.get("PodUID", "")
        node = args.get("Node", "")
        pod_key = uid or f"{ns}/{name}"
        trace = self._tracer.join_or_begin(pod_key)
        audit: dict[str, Any] = {}
        with self._tracer.root_span(trace, "bind") as sp:
            sp.set_tag("node", node)
            if forwarded_from:
                # owner forwarding (ha/forward.py): which replica the
                # kube-scheduler originally hit before the peer hop
                sp.set_tag("forwarded_from", forwarded_from)
            if self._breaker is not None:
                sp.set_tag("breaker", self._breaker.state)
            result = self._bind(args, ns, name, uid, node, trace, sp,
                                audit)
            err = result.get("Error") or ""
            sp.set_tag("error", err)
            if audit.get("chip_ids") is not None:
                sp.set_tag("chip_ids", audit["chip_ids"])
        outcome = "bound" if not err else "bind_failed"
        if self._explain is not None:
            self._explain.record_bind(
                pod_key, {"metadata": {"namespace": ns, "name": name,
                                       "uid": uid}},
                trace.trace_id if trace else None, node, outcome,
                error=audit.get("reason") or err or None,
                chip_ids=audit.get("chip_ids"))
        self._tracer.finish(pod_key, outcome)
        return result

    def _bind(self, args: dict[str, Any], ns: str, name: str, uid: str,
              node: str, trace, sp,
              audit: dict[str, Any]) -> dict[str, Any]:
        t0 = time.perf_counter()
        self.bind_total.inc()
        trace_id = trace.trace_id if trace else None
        if self._breaker is not None and \
                self._breaker.state == BREAKER_IS_OPEN:
            # fail fast with a DISTINCT error: the scheduler re-binds
            # after its own timeout, by which time the breaker's probe
            # may have closed the circuit. No failure event (the event
            # POST would fail-fast too) and no chip reservation churn.
            BIND_FASTFAIL.inc()
            self.bind_failures.inc()
            self.bind_latency.observe(time.perf_counter() - t0,
                                      exemplar=trace_id)
            audit["reason"] = ("breaker fast-fail: apiserver circuit "
                              "open (degraded mode)")
            log.warning("bind %s/%s -> %s refused fast: apiserver "
                        "circuit open", ns, name, node)
            return {"Error":
                    f"degraded: apiserver circuit open; bind of "
                    f"{ns}/{name} refused without burning the webhook "
                    f"timeout (retry after breaker reset)"}
        err: Exception | None = None
        placement = None
        bound_node = ""
        try:
            pod = self._get_pod(ns, name, uid)
            try:
                membership = (podlib.gang_membership(pod)
                              if self._gang is not None else None)
            except ValueError as e:
                raise AllocationError(str(e)) from None
            # the annotation half of the trace: Allocate (device plugin,
            # usually another process) reads this back and joins the
            # SAME trace id — the placement handoff channel doubles as
            # the trace-context carrier
            trace_ann = ({contract.ANN_TRACE_CONTEXT: trace_id}
                         if trace_id else None)
            if membership is not None:
                # gang member: all-or-nothing slice placement through
                # the coordinator (reserve-everywhere on first member,
                # planned-replay for the rest)
                placement = self._gang.bind_member(
                    pod, node, self._cluster,
                    ha_claims=self._claims_for(node, gang=True),
                    extra_annotations=trace_ann)
            else:
                info = self._cache.get_node_info(node)
                # the stamped form threads the hint's node generation
                # into allocate, which re-checks it UNDER the node lock:
                # a speculative (batch-solved) placement invalidated by
                # a concurrent mutation demotes to a fresh search there
                hint, hint_stamp, hint_spec = \
                    self._cache.placement_hint_stamped(pod, node)
                placement = info.allocate(
                    pod, self._cluster,
                    ha_claims=self._claims_for(node, gang=False),
                    hint=hint, hint_stamp=hint_stamp,
                    hint_speculative=hint_spec,
                    extra_annotations=trace_ann)
            audit["chip_ids"] = list(placement.chip_ids)
            self._cache.forget_memo(pod)
            if self._sharding is not None and membership is None:
                # our own bind moved the node's stamp; tell the
                # revalidation check so it isn't mistaken for a
                # straggler write from the previous shard owner
                self._sharding.note_bound(node)
        except AlreadyBoundError as e:
            err = e
            bound_node = podlib.pod_node_name(pod)
        except BindInFlightError as e:
            # benign concurrent-duplicate race: the winner is mid-bind.
            # Fail this request (outcome unknown here) but emit no failure
            # event — a FailedScheduling for a pod the winner is about to
            # bind successfully would mislead operators.
            self.bind_failures.inc()
            log.info("bind %s/%s -> %s refused: %s", ns, name, node, e)
            return {"Error": str(e)}
        except ClaimConflictError as e:
            # benign HA backpressure: the scheduler retries; no
            # FailedScheduling-style event, but counted for operators
            self.claim_conflicts.inc()
            if self._sharding is not None:
                SHARD_CONFLICTS.inc("cas_lost")
            self.bind_failures.inc()
            log.info("bind %s/%s -> %s refused: %s", ns, name, node, e)
            return {"Error": str(e)}
        except (AllocationError, ApiError) as e:
            self.bind_failures.inc()
            if isinstance(e, DeadlineExceeded) or \
                    isinstance(getattr(e, "__cause__", None),
                               DeadlineExceeded):
                # the deadline tripped mid-write (possibly wrapped into
                # an AllocationError by the rollback path): the headline
                # "every bind resolves within its deadline" counter
                BIND_DEADLINE_EXCEEDED.inc()
            err = e
        finally:
            # latency observed on EVERY exit (including unexpected
            # exceptions and the early returns above) and BEFORE event
            # emission: the event POST is its own apiserver round-trip and
            # must not skew the BASELINE p50/p99
            self.bind_latency.observe(time.perf_counter() - t0,
                                      exemplar=trace_id)
        if isinstance(err, AlreadyBoundError):
            if bound_node == node:
                # duplicate delivery (webhook retry / HA replica race lost
                # to ourselves): the pod is bound exactly as requested —
                # idempotent success, not a failure
                log.info("bind %s/%s -> %s: already bound there "
                         "(duplicate delivery)", ns, name, node)
                return {"Error": ""}
            # bound to a DIFFERENT node: real conflict, but the pod IS
            # scheduled — fail the request without a FailedScheduling event
            self.bind_failures.inc()
            log.warning("bind %s/%s -> %s refused: already bound to %s",
                        ns, name, node, bound_node)
            return {"Error": str(err)}
        if err is not None:
            log.warning("bind %s/%s -> %s failed: %s", ns, name, node, err)
            self._emit_event(
                ns, name, uid, "Warning", "TPUShareBindFailed",
                f"tpushare bind to {node} failed: {err}")
            return {"Error": str(err)}
        log.info("bind %s/%s -> %s ok", ns, name, node)
        self._emit_event(
            ns, name, uid, "Normal", "TPUShareBound",
            f"Successfully assigned {ns}/{name} to {node} "
            f"chips {list(placement.chip_ids)}")
        return {"Error": ""}

    def _emit_event(self, ns: str, name: str, uid: str, etype: str,
                    reason: str, message: str) -> None:
        """Best-effort pod Event (the reference wires an EventRecorder but
        never emits, controller.go:63-67 / SURVEY §5.5 — operators get
        nothing from `kubectl describe pod` there).

        Reasons are tpushare-specific (TPUShareBound / TPUShareBindFailed)
        rather than the scheduler's Scheduled / FailedScheduling: in a real
        cluster the default kube-scheduler records its own events around
        the extender's bind webhook, and duplicating its reasons would
        double every line in `kubectl describe`."""
        try:
            self._cluster.create_event(ns, {
                "metadata": {"generateName": f"{name}."},
                "type": etype,
                "reason": reason,
                "message": message,
                "involvedObject": {
                    "kind": "Pod", "namespace": ns, "name": name,
                    "uid": uid,
                },
                "source": {"component": "tpushare-scheduler-extender"},
            })
        except Exception as e:  # noqa: BLE001 — events must never block binds
            log.debug("event emit failed for %s/%s: %s", ns, name, e)

    def _get_pod(self, ns: str, name: str, uid: str) -> dict[str, Any]:
        """Fetch with UID recheck (reference getPod, gpushare-bind.go:45-70):
        lister first; apiserver GET only on a miss or when the lister's
        copy carries a different UID (watch lag across a delete/recreate).
        The fallback is singleflight-coalesced, so a retry storm for one
        pod costs one round-trip."""
        if self._pod_lister is not None:
            pod = self._pod_lister.get(ns, name)
            if pod is not None and (not uid or podlib.pod_uid(pod) == uid):
                LISTER_REQUESTS.inc("pods", "hit")
                return pod
            LISTER_REQUESTS.inc("pods", "miss")
        pod = self._sf.do(f"get_pod/{ns}/{name}",
                          lambda: self._cluster.get_pod(ns, name))
        if uid and podlib.pod_uid(pod) != uid:
            raise AllocationError(
                f"pod {ns}/{name} UID changed (got {podlib.pod_uid(pod)}, "
                f"scheduler sent {uid})")
        return pod


class InspectHandler:
    """Read-only allocation report (reference Inspect.Handler,
    inspect.go:8-69), consumed by the tpushare-inspect CLI."""

    def __init__(self, cache: SchedulerCache) -> None:
        self._cache = cache

    def handle(self, node_name: str | None = None) -> dict[str, Any]:
        tree = self._cache.describe()
        if node_name:
            nodes = [n for n in tree["nodes"] if n["name"] == node_name]
            if not nodes:
                return {"error": f"node {node_name} not found in cache"}
            return nodes[0]
        # engine health rides along: "is this extender actually running
        # the native scan, and if not, why" — the silent-fallback
        # regression the availability satellite exists to catch
        from tpushare.core import native as native_engine
        tree["native_engine"] = native_engine.describe()
        return tree


def register_cache_gauges(registry: Registry, cache: SchedulerCache) -> None:
    """Scrape-time gauges over the allocation cache: per-node utilization and
    fragmentation — the BASELINE headline metrics."""

    def per_node() -> list[tuple[str, float]]:
        out = []
        for name in cache.node_names():
            info = cache.get_node_info(name)
            views = info.snapshot()
            out.append((f'{{node="{name}",metric="utilization_pct"}}',
                        round(utilization_pct(views), 4)))
            out.append((f'{{node="{name}",metric="fragmentation"}}',
                        round(fragmentation(views), 4)))
        return out

    registry.gauge_func(
        "tpushare_node_hbm", "Per-node HBM utilization %% and fragmentation",
        per_node)

    from tpushare.cache.cache import (
        EQCLASS_SHARES, MEMO_DELTA_INVALIDATIONS, MEMO_NODE_SCORES,
        MEMO_REQUESTS, MEMO_STALE_SERVES)
    from tpushare.cache.index import (
        INDEX_CANDIDATE_RATIO, INDEX_PRUNED, INDEX_STALE_SERVES)
    from tpushare.cache.nodeinfo import CLAIM_CAS_RETRIES
    from tpushare.core.native import engine as _native
    from tpushare.k8s.informer import (
        INFORMER_EVENTS, INFORMER_RELISTS, LISTER_REQUESTS as _LISTER)
    from tpushare.k8s.retry import (
        DEADLINE_EXCEEDED_TOTAL, RETRY_ATTEMPTS, RETRY_BUDGET_EXHAUSTED)
    from tpushare.k8s.singleflight import SINGLEFLIGHT_TOTAL
    from tpushare.k8s.stats import APISERVER_REQUESTS

    registry.register(CLAIM_CAS_RETRIES)
    # crash-restart reconciliation: adopt-or-GC attribution after a
    # replica dies in the patch->bind gap (controller/recovery.py)
    from tpushare.controller.recovery import RECOVERY_ADOPTED, RECOVERY_GC

    registry.register(RECOVERY_ADOPTED)
    registry.register(RECOVERY_GC)
    # fault-containment set: retry volume, budget exhaustion, deadline
    # hits, degraded serves — what docs/ops.md says to alert on
    registry.register(RETRY_ATTEMPTS)
    registry.register(RETRY_BUDGET_EXHAUSTED)
    registry.register(DEADLINE_EXCEEDED_TOTAL)
    registry.register(BIND_DEADLINE_EXCEEDED)
    registry.register(BIND_FASTFAIL)
    registry.register(DEGRADED_SERVES)
    # the read-path observability set: apiserver round-trips per verb,
    # lister hit/miss, memo hit/miss, singleflight coalescing — the
    # counters that PROVE the hot path stays off the apiserver
    registry.register(APISERVER_REQUESTS)
    registry.register(_LISTER)
    registry.register(MEMO_REQUESTS)
    registry.register(SINGLEFLIGHT_TOTAL)
    registry.register(INFORMER_EVENTS)
    registry.register(INFORMER_RELISTS)
    # fleet-scale set: per-node memo delta invalidation (reuse rate under
    # concurrent binds), the stale-serve self-check, and the native
    # engine's availability/fallback story
    registry.register(MEMO_NODE_SCORES)
    registry.register(MEMO_DELTA_INVALIDATIONS)
    registry.register(MEMO_STALE_SERVES)
    # sublinear-filtering set: index pruning volume + candidate ratio,
    # the index-verify tripwire, and eqclass scan sharing — the
    # counters that prove Filter stopped paying O(fleet)
    registry.register(INDEX_PRUNED)
    registry.register(INDEX_CANDIDATE_RATIO)
    registry.register(INDEX_STALE_SERVES)
    registry.register(EQCLASS_SHARES)
    registry.register(_native.NATIVE_FLEET_SCANS)
    registry.register(_native.NATIVE_FALLBACKS)
    # batched-cycles set (ABI v4): end-to-end cycle calls by engine (a
    # sustained v3/python share on a current build = the silent-fallback
    # regression the cycle tier-1 guard reds on), window coalescing
    # volume, and per-pod batch outcomes incl. revalidation demotions
    from tpushare.cache.batch import BATCH_SOLVES, BATCH_WINDOW_PODS

    registry.register(_native.CYCLE_CALLS)
    registry.register(_native.BATCH_NATIVE_SOLVES)
    registry.register(BATCH_SOLVES)
    registry.register(BATCH_WINDOW_PODS)
    # gang-solve set (ABI v5): one-shot cross-host solves by outcome
    # (pruned = the adjacency tier skipped a solve entirely) and member
    # binds by seed source (a rising demoted share = heavy mutation
    # between solve and bind)
    from tpushare.cache.gang import GANG_MEMBERS, GANG_SOLVES

    registry.register(GANG_SOLVES)
    registry.register(GANG_MEMBERS)
    # mesh-aware placement set (ABI v7): topo scoring passes by engine
    # (a sustained python share on a current build = the v7 entry is
    # missing — stale .so) and Filter rejections of malformed
    # mesh-shape annotations (a nonzero rate = a pod template is
    # stamping broken shapes; the FailedNodes reason names the defect)
    registry.register(_native.TOPO_SCORES)
    registry.register(MESH_SHAPE_REJECTS)
    registry.gauge_func(
        "tpushare_native_engine_available",
        "1 when the C++ placement engine is loaded, 0 when scans run "
        "the Python fallback (check g++/numpy; see "
        "tpushare_native_fallback_total for the reason)",
        lambda: [("", 1.0 if _native.available() else 0.0)])
    # observability set (obs/): cycle-trace accounting, the metric-
    # registry cardinality guard, and the device plugin's Allocate
    # phase histogram (meaningful when plugin and extender share a
    # process — dev mode, tests, bench; the production DaemonSet scrapes
    # its own copy)
    from tpushare.deviceplugin.plugin import ALLOCATE_SECONDS
    from tpushare.metrics import METRIC_SERIES_CLAMPED
    from tpushare.obs.trace import TRACES_TOTAL

    registry.register(TRACES_TOTAL)
    registry.register(METRIC_SERIES_CLAMPED)
    registry.register(ALLOCATE_SECONDS)
    # wire-plane set (extender/wirecache.py + the k8s transport): digest
    # and response cache outcomes, the stale-serve tripwire, candidate-
    # list sizes, pipelined-bind leg outcomes, and keep-alive pool reuse
    from tpushare.cache.nodeinfo import BIND_PIPELINE
    from tpushare.extender.wirecache import (
        WIRE_CANDIDATES, WIRE_DIGEST, WIRE_RESPONSES, WIRE_STALE_SERVES)
    from tpushare.k8s.stats import CONN_POOL_REQUESTS

    registry.register(WIRE_DIGEST)
    registry.register(WIRE_RESPONSES)
    registry.register(WIRE_STALE_SERVES)
    registry.register(WIRE_CANDIDATES)
    registry.register(BIND_PIPELINE)
    registry.register(CONN_POOL_REQUESTS)
    # native wire table (extender/nativewire.py, ABI v6): GIL-released
    # serve outcomes + probe latency. A growing `fallback` series under
    # a steady digest-hit load means the table is being invalidated
    # faster than it resyncs — see docs/ops.md.
    from tpushare.extender.nativewire import (
        WIRE_NATIVE_PROBE_SECONDS, WIRE_NATIVE_SERVES)
    registry.register(WIRE_NATIVE_SERVES)
    registry.register(WIRE_NATIVE_PROBE_SECONDS)
    # fleet black box (obs/blackbox.py, ABI v8): ring events drained by
    # instrumented call + outcome, and the producer-side overflow drop
    # counter — the ring's loud-never-corrupt contract in one series
    from tpushare.obs.blackbox import BLACKBOX_DROPPED, BLACKBOX_EVENTS
    registry.register(BLACKBOX_EVENTS)
    registry.register(BLACKBOX_DROPPED)

    # QoS tiers (tpushare/qos/, ISSUE 17): eviction outcomes, the
    # guaranteed-isolation page counter, the borrowed-HBM gauge, and the
    # per-tenant DRF dominant share. All flat zero / empty on a fleet
    # with TPUSHARE_QOS_OVERCOMMIT unset.
    from tpushare.chaos.invariants import QOS_GUARANTEED_VIOLATIONS
    from tpushare.qos.pressure import QOS_EVICTIONS
    registry.register(QOS_EVICTIONS)
    registry.register(QOS_GUARANTEED_VIOLATIONS)

    def qos_oversub() -> list[tuple[str, float]]:
        out = []
        for name in cache.node_names():
            info = cache.peek_node(name)
            if info is None:
                continue
            u = info.qos_usage()
            out.append((f'{{node="{name}"}}',
                        float(u["oversubscribed_hbm_mib"])))
        return out

    registry.gauge_func(
        "tpushare_qos_oversubscribed_hbm_mib",
        "Per-node HBM granted beyond physical chip capacity (borrowed "
        "by best-effort pods under the QoS overcommit bound). Sustained "
        "growth alongside rising eviction rate is a capacity incident "
        "(docs/ops.md)",
        qos_oversub)

    def tenant_share() -> list[tuple[str, float]]:
        return [(f'{{namespace="{ns}"}}', round(s, 6))
                for ns, s in sorted(dominant_shares(cache).items())]

    registry.gauge_func(
        "tpushare_tenant_dominant_share",
        "Per-namespace dominant-resource share of the fleet (max of "
        "chips fraction and HBM fraction — the DRF coordinate the "
        "TPUSHARE_QOS_DRF_CAP admission cap is enforced against)",
        tenant_share)
    register_build_info(registry)


def register_build_info(registry: Registry) -> None:
    """``tpushare_build_info``: the which-build-is-this gauge (value
    always 1; the information is the labels — the standard Prometheus
    build-info idiom, joinable against any other series)."""
    import platform

    import tpushare
    from tpushare.core.native import engine as _native

    def info() -> list[tuple[str, float]]:
        abi = _native.abi_version()
        labels = (f'{{version="{tpushare.__version__}",'
                  f'python="{platform.python_version()}",'
                  f'native_abi="{abi if abi is not None else "none"}"}}')
        return [(labels, 1.0)]

    registry.gauge_func(
        "tpushare_build_info",
        "Build/runtime identity (value is always 1; read the labels: "
        "tpushare version, python version, native engine ABI)",
        info)
