"""Selector/event-loop HTTP front end for the extender.

ThreadingHTTPServer spends a thread per *connection*: the kube-scheduler
keeps long-lived keep-alive connections to its extenders, so every idle
connection pins a thread, and under a bind storm the accept path, socket
reads and GIL-released native solves all fight for the same pool of
oversubscribed threads. This front end splits the two concerns:

- **one event-loop thread** owns every socket — accept, read, parse and
  write are all non-blocking and multiplexed through a selector, so ten
  thousand idle keep-alive connections cost one thread and zero wakeups;
- **a bounded worker pool** runs the request handlers (which may block on
  apiserver writes, native solves, or a peer forward hop) and hands the
  finished response bytes back to the loop over a queue + self-pipe
  wakeup. Workers never touch a socket.

The HTTP surface is deliberately minimal — request line, headers,
Content-Length bodies, HTTP/1.1 keep-alive with ``Connection: close``
honored — which is exactly what the kube-scheduler webhook, the peer
forward transport and the ops tooling speak. No chunked request bodies
(the webhook never sends them; a Transfer-Encoding request gets 501).

Lock discipline (tests/test_lock_order_lint.py): ``self._done_lock`` is
the only lock — it guards the finished-response queue and the in-flight
counter for a few instructions at a time and is NEVER held across a
handler call, a socket operation, or a forward hop.

Two steady-state fast paths ride the loop thread (ISSUE 16):

- **native wire probe**: when a ``native_wire`` table is attached
  (extender/nativewire.py), freshly read bytes are offered to one
  GIL-released C call before the Python parser ever runs. A digest-hit
  Filter/Prioritize request is answered by a memcpy of pre-encoded
  response bytes — no header dict, no pool hop. Everything the probe
  is not positive about falls through to the Python path unchanged.
- **batched writes**: worker responses drained on one selector wake are
  coalesced into the connection buffers first and flushed once per
  connection (``TPUSHARE_WRITE_BATCH=0`` restores flush-per-response),
  so a storm of small verdicts costs one ``send()`` per connection per
  wake instead of one per response.

``TPUSHARE_REUSEPORT=1`` binds the listener with ``SO_REUSEPORT`` where
the platform has it: N independent server processes then share ONE
port with kernel-balanced accepts (no port probing, no userspace
proxy). Replicas must be verdict-equivalent — the kube-scheduler does
not care which replica answers, which is exactly the sharded-replica
deployment contract (docs/ops.md).
"""

from __future__ import annotations

import logging
import os
import selectors
import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from http.client import responses as _REASONS
from typing import Callable

log = logging.getLogger("tpushare.extender.http")

DEFAULT_HTTP_WORKERS = 8


def http_workers() -> int:
    try:
        return max(1, int(os.environ.get("TPUSHARE_HTTP_WORKERS",
                                         DEFAULT_HTTP_WORKERS)))
    except ValueError:
        return DEFAULT_HTTP_WORKERS


_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 64 * 1024 * 1024  # a 50k-node Nodes list is ~20 MiB


class _Conn:
    __slots__ = ("sock", "inbuf", "outbuf", "busy", "close_after",
                 "closed", "verify_expected")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.busy = False         # a request is in flight in the pool
        self.close_after = False  # close once outbuf drains
        self.closed = False
        self.verify_expected: bytes | None = None  # TPUSHARE_WIRE_VERIFY


class SelectorHTTPServer:
    """Event-loop acceptor + bounded worker pool.

    ``handle_get(path)`` / ``handle_post(path, body, headers)`` return
    ``(status, payload_bytes, content_type)`` and run on pool threads.
    """

    def __init__(self, host: str, port: int,
                 handle_get: Callable, handle_post: Callable,
                 max_workers: int | None = None,
                 native_wire=None) -> None:
        self.host, self.port = host, port
        self._handle_get = handle_get
        self._handle_post = handle_post
        self.max_workers = max_workers or http_workers()
        # duck-typed NativeWireTable (extender/nativewire.py) — this
        # module stays import-free of the wire plane
        self._native = native_wire
        self._write_batch = os.environ.get(
            "TPUSHARE_WRITE_BATCH", "1") != "0"
        self.reuseport_active = False
        self._sel = selectors.DefaultSelector()
        self._listener: socket.socket | None = None
        self._pool: ThreadPoolExecutor | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._stopped = threading.Event()
        # worker -> loop handoff: finished (conn, response) pairs plus
        # the in-flight count, guarded for a few instructions at a time
        self._done_lock = threading.Lock()
        self._done: list[tuple[_Conn, bytes]] = []
        self._inflight = 0
        self._conns: set[_Conn] = set()  # loop-thread only
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)

    # -- observability (the front-end gauges) ---------------------------------

    def open_connections(self) -> int:
        return len(self._conns)

    def busy_workers(self) -> int:
        with self._done_lock:
            return self._inflight

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> int:
        """Bind, start the loop thread + pool; returns the bound port."""
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if os.environ.get("TPUSHARE_REUSEPORT", "") == "1" \
                and hasattr(socket, "SO_REUSEPORT"):
            # N replica processes share ONE listening port; the kernel
            # balances accepts across them. Only meaningful with an
            # explicit --port (with port 0 each replica gets its own
            # ephemeral port and nothing is shared).
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            self.reuseport_active = True
        lst.bind((self.host, self.port))
        lst.listen(256)
        lst.setblocking(False)
        self.port = lst.getsockname()[1]
        self._listener = lst
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_workers,
            thread_name_prefix="tpushare-http-worker")
        self._sel.register(lst, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._thread = threading.Thread(
            target=self._loop, name="tpushare-http-loop", daemon=True)
        self._thread.start()
        return self.port

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def shutdown(self) -> None:
        self._stop.set()
        self._wakeup()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._stopped.set()

    def server_close(self) -> None:
        pass  # sockets are closed by the loop on shutdown

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    # -- event loop (the only thread that touches sockets) --------------------

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                for key, _ in self._sel.select(timeout=0.5):
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except BlockingIOError:
                            pass
                        self._drain_done()
                    else:
                        self._service(key.data)
        finally:
            for conn in list(self._conns):
                self._close(conn)
            if self._listener is not None:
                try:
                    self._sel.unregister(self._listener)
                except (KeyError, ValueError):
                    pass
                self._listener.close()
            self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            # same rationale as the threaded front end: Nagle + delayed
            # ACK stalls keep-alive webhook round-trips ~40ms
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            self._conns.add(conn)
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _interest(self, conn: _Conn) -> None:
        if conn.closed:
            return
        ev = selectors.EVENT_READ
        if conn.outbuf:
            ev |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, ev, conn)
        except (KeyError, ValueError, OSError):
            self._close(conn)

    def _service(self, conn: _Conn) -> None:
        # read whatever is there (also how we learn about a hangup)
        try:
            while True:
                chunk = conn.sock.recv(65536)
                if not chunk:
                    if not conn.busy and not conn.outbuf:
                        self._close(conn)
                    else:
                        conn.close_after = True
                    break
                conn.inbuf += chunk
        except BlockingIOError:
            pass
        except OSError:
            self._close(conn)
            return
        if conn.closed:
            return
        if not conn.busy and self._native is not None:
            self._native_serve(conn)
        if not conn.busy:
            self._try_dispatch(conn)
        if conn.outbuf:
            self._flush(conn)

    def _native_serve(self, conn: _Conn) -> None:
        """Serve pipelined digest-hit requests GIL-released, coalescing
        their responses into one outbuf (flushed once by _service). Any
        non-hit leaves the buffer untouched for _try_dispatch — the
        probe never consumes bytes it did not answer."""
        nat = self._native
        if not nat.enabled:
            return
        while conn.inbuf and conn.verify_expected is None:
            rc, resp, consumed = nat.probe_request(conn.inbuf)
            if rc != 1:  # PROBE_HIT
                return
            if nat.verify:
                # don't serve: pin the native bytes and let the Python
                # path recompute this request — _work compares the two
                # (the TPUSHARE_WIRE_VERIFY stale tripwire)
                conn.verify_expected = resp
                return
            del conn.inbuf[:consumed]
            conn.outbuf += resp

    def _flush(self, conn: _Conn) -> None:
        try:
            # memoryview avoids copying the whole buffer per send() —
            # at 50k-node responses (~1 MiB) the copy dominated _flush
            with memoryview(conn.outbuf) as mv:
                n = conn.sock.send(mv)
            del conn.outbuf[:n]
        except BlockingIOError:
            pass
        except OSError:
            self._close(conn)
            return
        if not conn.outbuf and conn.close_after:
            self._close(conn)
            return
        self._interest(conn)
        if not conn.outbuf and not conn.busy:
            # a pipelined request may be buffered; offer it to the
            # native probe first, exactly like a fresh read
            if self._native is not None:
                self._native_serve(conn)
            if not conn.busy:
                self._try_dispatch(conn)
            if conn.outbuf:
                self._flush(conn)  # natively served bytes

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        self._conns.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- request parsing + dispatch -------------------------------------------

    def _try_dispatch(self, conn: _Conn) -> None:
        head_end = conn.inbuf.find(b"\r\n\r\n")
        if head_end < 0:
            if len(conn.inbuf) > _MAX_HEADER_BYTES:
                self._reject(conn, 431, "headers too large")
            return
        head = bytes(conn.inbuf[:head_end]).decode("latin-1")
        lines = head.split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            self._reject(conn, 400, "malformed request line")
            return
        method, path, version = parts
        headers: dict[str, str] = {}
        for line in lines[1:]:
            name, sep, value = line.partition(":")
            if sep:
                headers[name.strip().title()] = value.strip()
        if headers.get("Transfer-Encoding"):
            self._reject(conn, 501, "chunked bodies unsupported")
            return
        try:
            length = int(headers.get("Content-Length", 0))
        except ValueError:
            self._reject(conn, 400, "bad Content-Length")
            return
        if length > _MAX_BODY_BYTES:
            self._reject(conn, 413, "body too large")
            return
        total = head_end + 4 + length
        if len(conn.inbuf) < total:
            return  # body still arriving
        body = bytes(conn.inbuf[head_end + 4:total])
        del conn.inbuf[:total]
        wants_close = headers.get("Connection", "").lower() == "close" \
            or version == "HTTP/1.0"
        conn.close_after = conn.close_after or wants_close
        conn.busy = True
        with self._done_lock:
            self._inflight += 1
        self._pool.submit(self._work, conn, method, path, body, headers)

    def _reject(self, conn: _Conn, status: int, reason: str) -> None:
        conn.close_after = True
        conn.outbuf += _response(status, reason.encode(), "text/plain",
                                 close=True)
        self._flush(conn)

    # -- worker side (never touches sockets) ----------------------------------

    def _work(self, conn: _Conn, method: str, path: str, body: bytes,
              headers: dict[str, str]) -> None:
        try:
            if method == "GET":
                status, data, ctype = self._handle_get(path)
            elif method == "POST":
                status, data, ctype = self._handle_post(path, body, headers)
            else:
                status, data, ctype = 405, b"method not allowed", \
                    "text/plain"
        except Exception as e:  # noqa: BLE001 — the socket must answer
            log.error("%s %s crashed in worker: %s", method, path, e)
            status, data, ctype = 500, b'{"error": "internal error"}', \
                "application/json"
        resp = _response(status, data, ctype, close=conn.close_after)
        expected = conn.verify_expected
        if expected is not None:
            conn.verify_expected = None
            if self._native is not None:
                self._native.check_verify(expected, resp)
        with self._done_lock:
            self._done.append((conn, resp))
            self._inflight -= 1
        self._wakeup()

    def _drain_done(self) -> None:
        with self._done_lock:
            done, self._done = self._done, []
        if self._write_batch:
            # coalesce: append every finished response first, then one
            # flush per connection per wake — a verdict storm costs one
            # send() per connection instead of one per response
            for conn, resp in done:
                if conn.closed:
                    continue
                conn.busy = False
                conn.outbuf += resp
            seen = set()
            for conn, _ in done:
                if conn.closed or id(conn) in seen:
                    continue
                seen.add(id(conn))
                self._flush(conn)
            return
        for conn, resp in done:
            if conn.closed:
                continue
            conn.busy = False
            conn.outbuf += resp
            self._flush(conn)


def _response(status: int, data: bytes, content_type: str,
              close: bool = False) -> bytes:
    reason = _REASONS.get(status, "OK")
    head = (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(data)}\r\n")
    if close:
        head += "Connection: close\r\n"
    return head.encode("latin-1") + b"\r\n" + data
