"""Extender entry point (reference cmd/main.go:53-106).

In-cluster production mode:

    python -m tpushare.extender --port 39999

Development mode against an in-memory cluster (no kubeconfig needed):

    python -m tpushare.extender --fake-nodes "n1:4x16000:2x2" --port 0

Env config mirrors the reference: LOG_LEVEL (main.go:57-66), PORT
(main.go:70-73), THREADNESS worker count (main.go:128-132 — stubbed to 1
there, real here).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.handlers import register_cache_gauges
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer


def parse_fake_nodes(spec: str):
    """``name:CHIPSxHBM[:MESH[:SLICE@ORIGIN]]`` comma-separated, e.g.
    ``n1:4x16000:2x2`` or (a v5e-16 host) ``h0:4x16000:2x2:slc0@0x2``."""
    from tpushare.k8s import FakeCluster
    fc = FakeCluster()
    for item in filter(None, (s.strip() for s in spec.split(","))):
        parts = item.split(":")
        if len(parts) < 2:
            raise ValueError(f"bad node spec {item!r}")
        name = parts[0]
        chips_s, _, hbm_s = parts[1].partition("x")
        mesh = parts[2] if len(parts) > 2 else None
        slice_id = slice_origin = None
        if len(parts) > 3:
            slice_id, sep, slice_origin = parts[3].partition("@")
            if not sep or not slice_id or not slice_origin:
                raise ValueError(f"bad slice spec in {item!r} "
                                 "(want SLICE@ORIGIN, e.g. slc0@0x2)")
        fc.add_tpu_node(name, chips=int(chips_s),
                        hbm_per_chip_mib=int(hbm_s), mesh=mesh,
                        slice_id=slice_id, slice_origin=slice_origin)
    return fc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tpushare-extender")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("PORT", "39999")))
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--fake-nodes", default=None,
                    help="run against an in-memory cluster: 'n1:4x16000:2x2,...'")
    ap.add_argument("--apiserver", default=None,
                    help="explicit apiserver base URL (e.g. kubectl proxy)")
    ap.add_argument("--kubeconfig", default=None,
                    help="out-of-cluster kubeconfig path (default: "
                         "$KUBECONFIG, else in-cluster SA; reference "
                         "cmd/main.go:24-38)")
    ap.add_argument("--workers", type=int,
                    default=int(os.environ.get("THREADNESS", "1")))
    ap.add_argument("--reuseport", action="store_true",
                    default=os.environ.get("TPUSHARE_REUSEPORT", "") == "1",
                    help="bind the listener with SO_REUSEPORT so N "
                         "replica processes share ONE port with "
                         "kernel-balanced accepts (requires an explicit "
                         "--port; no-op where the platform lacks it)")
    ap.add_argument("--ha", action="store_true",
                    default=os.environ.get("ENABLE_HA", "") == "true",
                    help="run Lease-based leader election; only the leader "
                         "serves Bind (multi-replica deployments)")
    args = ap.parse_args(argv)
    if args.reuseport:
        # the httpserver front end reads the env knob at bind time; the
        # flag is the operator-facing spelling of the same switch
        os.environ["TPUSHARE_REUSEPORT"] = "1"

    # structured JSON logging with the active trace id in every line
    # (obs/logging.py; TPUSHARE_LOG_FORMAT=plain for the dev format)
    from tpushare.obs.logging import setup as setup_logging
    setup_logging(os.environ.get("LOG_LEVEL", "info"))
    log = logging.getLogger("tpushare.main")

    if args.fake_nodes:
        cluster = parse_fake_nodes(args.fake_nodes)
        log.info("running with FakeCluster: %s", args.fake_nodes)
    else:
        from tpushare.k8s.incluster import InClusterClient
        if args.apiserver:
            cluster = InClusterClient(base_url=args.apiserver)
        else:
            cluster = InClusterClient.autodetect(kubeconfig=args.kubeconfig)

    # (native engine warmup happens inside ExtenderServer start/serve)
    # every apiserver round-trip is counted per (verb, origin) — the
    # tpushare_apiserver_requests_total series on /metrics is how an
    # operator verifies the hot path stays off the apiserver
    from tpushare.k8s.stats import CountingCluster
    cluster = CountingCluster(cluster)
    # write-path fault containment (docs/ops.md): a circuit breaker over
    # every request/response verb plus deadline-bounded retries with
    # exponential backoff. Counting sits INSIDE so every real attempt is
    # one counted round-trip (write amplification stays observable), and
    # watches bypass both layers (their healing is reconnect+relist).
    from tpushare.k8s.breaker import CircuitBreaker, harden
    from tpushare.k8s.retry import RetryPolicy
    breaker = CircuitBreaker(
        failure_threshold=int(os.environ.get(
            "TPUSHARE_BREAKER_THRESHOLD", "5")),
        reset_timeout_s=float(os.environ.get(
            "TPUSHARE_BREAKER_RESET_S", "5.0")))
    cluster = harden(cluster, breaker=breaker, policy=RetryPolicy(
        max_attempts=int(os.environ.get("TPUSHARE_RETRY_BUDGET", "4"))))
    # read-path informer: watch-warmed pod/node listers serve Bind's pod
    # fetch and the cache's lazy node fetch, so the scheduling hot path
    # issues no synchronous apiserver reads (fallback on miss only)
    from tpushare.k8s.informer import Informer
    informer = Informer(cluster).start()
    cache = SchedulerCache(cluster, node_lister=informer.nodes)
    controller = Controller(
        cluster, cache, workers=args.workers,
        resync_seconds=float(os.environ.get("TPUSHARE_RESYNC_S", "30.0")))
    replayed = controller.build_cache()
    log.info("cache built: %d pods replayed", replayed)
    controller.start()

    # The replayed cache (and everything imported above it) is the
    # process's permanent heap. Move it out of the cyclic collector's
    # view: gen-2 sweeps otherwise walk the whole cache and were
    # measured at >100 ms on a bench-sized fleet — long enough to blow a
    # single bind's latency from 8 ms to ~70 ms when a collection lands
    # mid-request (the r3 ha_p99 tail; docs/perf.md "HA p99"). The
    # standard big-static-heap pattern: collect what's garbage now,
    # freeze the survivors.
    import gc
    gc.collect()
    gc.freeze()

    elector = None
    sharding = None
    shard_replicas = int(os.environ.get("TPUSHARE_SHARD_REPLICAS", "0")
                         or 0)
    if shard_replicas > 0:
        # active-active: every replica renews its own membership lease
        # and owns a consistent-hash shard of the fleet — supersedes the
        # single-leader gate (docs/ops.md: TPUSHARE_SHARD_REPLICAS /
        # TPUSHARE_SHARD_VNODES)
        import socket as socketlib

        from tpushare.ha import ShardMembership
        identity = f"{socketlib.gethostname()}-{os.getpid()}"
        # a rebalance hands this replica foreign-scheduled nodes: resync
        # so their claims/placements are re-read before lock-free binds
        sharding = ShardMembership(
            cluster, identity, cache=cache,
            lease_duration=float(os.environ.get(
                "TPUSHARE_SHARD_LEASE_S", "15.0")),
            renew_period=float(os.environ.get(
                "TPUSHARE_SHARD_RENEW_S", "5.0")),
            on_rebalance=controller.resync_once)
        # started AFTER the server binds: the peer URL advertised in the
        # shard lease (owner forwarding, ha/forward.py) needs the real
        # bound port, which --port 0 only yields at server.start()
        log.info("ha: active-active sharding enabled (identity %s, "
                 "%d vnodes)", identity, sharding.vnodes)
    elif args.ha:
        import socket as socketlib

        from tpushare.ha import LeaderElector
        identity = f"{socketlib.gethostname()}-{os.getpid()}"
        # on takeover, resync so the new leader binds against fresh state
        elector = LeaderElector(
            cluster, identity,
            on_started_leading=controller.resync_once)
        elector.start()
        log.info("ha: leader election enabled (identity %s)", identity)

    registry = Registry()
    server = ExtenderServer(cache, cluster, registry,
                            host=args.host, port=args.port,
                            allow_debug_seed=bool(args.fake_nodes),
                            elector=elector, informer=informer,
                            breaker=breaker, sharding=sharding)
    register_cache_gauges(registry, cache)
    # abandoned-gang expiry rides the controller's 30 s anti-entropy
    # heartbeat (docs/designs/multihost-gang.md protocol step 5)
    controller.resync_hooks.append(server.gang.gc)
    # crash-restart reconciliation (controller/recovery.py): one pass
    # now — a replica restarting mid-storm adopts what a dead
    # incarnation bound and reclaims what it half-bound — then again on
    # every resync heartbeat, which bounds the orphan window
    from tpushare.controller.recovery import reconcile_once
    recovery_stale_s = float(os.environ.get(
        "TPUSHARE_RECOVERY_STALE_S", "15.0"))
    reconcile_once(cluster, cache, stale_after_s=recovery_stale_s)
    controller.resync_hooks.append(lambda: reconcile_once(
        cluster, cache, stale_after_s=recovery_stale_s))

    stop = threading.Event()

    def on_signal(signum, _frame):
        # second signal forces exit (reference signals/signal.go:16-30)
        if stop.is_set():
            sys.exit(1)
        stop.set()
        server.stop()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    port = server.start()
    if sharding is not None:
        adv = os.environ.get("TPUSHARE_ADVERTISE_URL", "")
        if not adv:
            import socket as socketlib
            adv_host = args.host
            if adv_host in ("0.0.0.0", "::"):
                adv_host = socketlib.gethostname()
            adv = f"http://{adv_host}:{port}"
        sharding.advertise_url = adv
        sharding.start()
    print(f"tpushare extender ready on {args.host}:{port}", flush=True)
    stop.wait()
    if sharding is not None:
        sharding.stop()
    if elector is not None:
        elector.stop()
    controller.stop()
    informer.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
