"""Wire-plane cache: digest-keyed candidate decode + pre-encoded replies.

The in-memory layers of the hot path are sublinear or native, but the
webhook *wire* path still pays full per-request Python cost: every
Filter/Prioritize POST re-parses a fleet-size ``NodeNames`` JSON list
(50k strings per call at wind-tunnel scale) and re-encodes a fleet-size
result. The scheduler sends the SAME candidate list every cycle of a
storm, so both costs are almost pure waste — the bytes on the wire are
identical request after request.

Three layers, all keyed off the raw bytes:

- **candidate-set digest cache** — locate the ``"NodeNames": [...]``
  byte-span in the raw body without parsing it (``bytes.rfind`` runs at
  C speed; the remainder of the body is parsed with the span spliced to
  ``null``, which doubles as a guard that the located span really was
  the top-level value). blake2b of the span keys a small LRU of
  previously parsed, ``sys.intern``-ed name lists: a digest hit decodes
  a fleet-size request without creating a single name string.
- **response cache + fragment encoder** — per digest entry, the encoded
  ``ExtenderFilterResult`` / ``HostPriorityList`` bytes are cached under
  ``(verb, request signature, cache mutation stamp)``. Any cache/ring
  mutation bumps the stamp (SchedulerCache.mutation_stamp), so a hit is
  only served while the fleet state that produced it is untouched —
  byte-identical to recomputing, which ``TPUSHARE_WIRE_VERIFY=1``
  enforces by recomputing every hit and counting mismatches in
  ``tpushare_wire_stale_serves_total`` (serving the fresh truth).
  Misses encode through an interned name->fragment table, skipping the
  per-call fleet-size ``json.dumps``.
- the encoded bytes reproduce ``json.dumps`` byte-for-byte (default
  separators, default ensure_ascii), so turning the layer off
  (``TPUSHARE_NO_WIRECACHE=1``) can never change what is on the wire.

Locking: ONE lock guards the digest map and the per-entry response
tables. It is never held across a parse, a solve, or an encode — lookup
and store are dict operations; everything expensive happens outside.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from collections import OrderedDict
from typing import Any

from tpushare.metrics import Counter, Histogram, LabeledCounter

WIRE_DIGEST = LabeledCounter(
    "tpushare_wire_digest_total",
    "Candidate-set digest cache outcomes on the webhook decode path "
    '("hit": fleet list reused without parsing; "miss": parsed once and '
    'cached; "bypass": request shape not digestable — absent/odd '
    "NodeNames, non-list span, or the layer is disabled)",
    ("outcome",))
WIRE_RESPONSES = LabeledCounter(
    "tpushare_wire_responses_total",
    "Pre-encoded response cache outcomes by webhook verb "
    '("hit": cached bytes served under an unchanged mutation stamp; '
    '"encoded": fragment-encoded fresh and cached; "bypass": verdict '
    "not cacheable — transient node-fetch errors, gang/batched pods, "
    "or no TPU request)",
    ("verb", "outcome"))
WIRE_STALE_SERVES = Counter(
    "tpushare_wire_stale_serves_total",
    "Wirecache verify-mode mismatches (TPUSHARE_WIRE_VERIFY=1): a digest "
    "or response hit whose recomputed truth differed — the truth was "
    "served. Any nonzero value is a bug in the stamp protocol.")
WIRE_CANDIDATES = Histogram(
    "tpushare_wire_candidates",
    "Candidate-list length per digest-decoded Filter/Prioritize request "
    "(the fleet-size work the digest cache removes on a hit)",
    (16, 128, 1024, 8192, 20000, 50000, 100000))

_KEY = b'"NodeNames"'
_WS = b" \t\r\n"
# scores are 0..MaxExtenderPriority (10): pre-encode the whole range
_INT_FRAGS = {i: str(i).encode() for i in range(11)}


def _find_span(raw: bytes) -> tuple[int, int] | None:
    """Byte range of the ``[...]`` array value of the LAST ``"NodeNames"``
    key in ``raw``, or None. rfind because the fleet list is marshaled
    last in ExtenderArgs; a spoofed earlier occurrence (e.g. inside a pod
    annotation string) either fails the splice guard in decode() or IS
    the top-level value. A ``]`` inside a name makes the span invalid
    JSON (unterminated string), which the miss-path parse rejects — so a
    span that parses is exactly the array."""
    i = raw.rfind(_KEY)
    if i < 0:
        return None
    j, n = i + len(_KEY), len(raw)
    while j < n and raw[j] in _WS:
        j += 1
    if j >= n or raw[j] != 0x3A:  # ':'
        return None
    j += 1
    while j < n and raw[j] in _WS:
        j += 1
    if j >= n or raw[j] != 0x5B:  # '['
        return None
    k = raw.find(b"]", j)
    if k < 0:
        return None
    return j, k + 1


class WireEncoded:
    """A handler result already encoded to wire bytes (hit or fragment-
    encoded miss). The server front end sends ``body`` verbatim instead
    of ``json.dumps``-ing a dict; the counts carry what the trace span
    and audit record need without re-parsing."""

    __slots__ = ("body", "ok", "failed", "best", "count", "outcome")

    def __init__(self, body: bytes, *, ok: int = 0, failed: int = 0,
                 best: str | None = None, count: int = 0,
                 outcome: str = "encoded") -> None:
        self.body = body
        self.ok, self.failed = ok, failed
        self.best, self.count = best, count
        self.outcome = outcome


class _Entry:
    __slots__ = ("names", "responses")

    def __init__(self, names: list[str]) -> None:
        self.names = names
        # (verb, request signature) -> (mutation stamp, WireEncoded)
        self.responses: dict[tuple, tuple[int, WireEncoded]] = {}


class _Ctx:
    """Per-request decode context: the digest entry plus the mutation
    stamps read at lookup time, BEFORE the handler computed — a store
    under a pre-compute stamp can only ever be too conservative.

    When a native wire table is attached, ``span_digest``/``rem_digest``
    carry the request's exact-byte identity (the NodeNames span and the
    body remainder, each BLAKE2b-128): ``_finish`` syncs the freshly
    encoded response into the native table under those keys, so the
    NEXT byte-identical request can be served GIL-released.

    ``pod_key``/``pod`` are set by the handler before finish so the
    black-box digest map (obs/blackbox.DIGEST_MAP) can attribute future
    native hits of these digests to the pod they serve."""

    __slots__ = ("entry", "stamps", "span_digest", "rem_digest",
                 "pod_key", "pod")

    def __init__(self, entry: _Entry) -> None:
        self.entry = entry
        self.stamps: dict[tuple, int] = {}
        self.span_digest: bytes | None = None
        self.rem_digest: bytes | None = None
        self.pod_key: str | None = None
        self.pod: Any = None


class WireCache:
    MAX_DIGESTS = 64       # distinct candidate sets kept decoded
    MAX_RESPONSES = 16     # per digest: (verb, sig) response variants
    MAX_FRAGMENTS = 200_000  # interned name/reason byte fragments

    def __init__(self, cache, *, enabled: bool | None = None,
                 verify: bool | None = None) -> None:
        self._cache = cache  # needs .mutation_stamp() -> int
        if enabled is None:
            enabled = os.environ.get("TPUSHARE_NO_WIRECACHE", "") != "1"
        if verify is None:
            verify = os.environ.get("TPUSHARE_WIRE_VERIFY", "") == "1"
        self.enabled = enabled
        self.verify = verify
        self._entries: OrderedDict[bytes, _Entry] = OrderedDict()
        self._frags: dict[str, bytes] = {}
        self._lock = threading.Lock()
        # optional NativeWireTable (extender/nativewire.py), attached by
        # the server; _finish delta-syncs fresh encodes into it
        self.native = None

    # -- decode ----------------------------------------------------------

    def decode(self, raw: bytes) -> tuple[Any, _Ctx | None]:
        """Parse one Filter/Prioritize body; digest-hit requests reuse
        the cached interned name list and decode only the (small)
        remainder. Raises json.JSONDecodeError exactly like a plain
        ``json.loads`` would — the caller's 400 path is unchanged."""
        if not raw:
            return {}, None
        if not self.enabled:
            return json.loads(raw), None
        span = _find_span(raw)
        if span is None:
            WIRE_DIGEST.inc("bypass")
            return json.loads(raw), None
        s, e = span
        digest = hashlib.blake2b(raw[s:e], digest_size=16).digest()
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
        try:
            args = json.loads(b"".join((raw[:s], b"null", raw[e:])))
        except json.JSONDecodeError:
            # the scan found "]" early (a ] inside a name string): the
            # splice chopped mid-value. The BODY may still be fine —
            # only the shortcut failed, so fall back to a plain parse
            WIRE_DIGEST.inc("bypass")
            return json.loads(raw), None
        if not (isinstance(args, dict) and "NodeNames" in args
                and args["NodeNames"] is None):
            # the located span was not the top-level NodeNames value
            # (spoofed key inside a string, nested object, ...): the
            # splice didn't null it out, so fall back to a plain parse
            WIRE_DIGEST.inc("bypass")
            return json.loads(raw), None
        if entry is None:
            try:
                names = json.loads(raw[s:e])
            except json.JSONDecodeError:
                WIRE_DIGEST.inc("bypass")
                return json.loads(raw), None
            if not isinstance(names, list):
                WIRE_DIGEST.inc("bypass")
                args["NodeNames"] = names
                return args, None
            names = [sys.intern(n) if type(n) is str else n for n in names]
            entry = _Entry(names)
            with self._lock:
                cur = self._entries.setdefault(digest, entry)
                if cur is not entry:
                    entry = cur  # lost a benign race: reuse the winner
                else:
                    while len(self._entries) > self.MAX_DIGESTS:
                        self._entries.popitem(last=False)
            WIRE_DIGEST.inc("miss")
        else:
            if self.verify:
                truth = json.loads(raw).get("NodeNames")
                if truth != entry.names:
                    WIRE_STALE_SERVES.inc()
                    WIRE_DIGEST.inc("hit")
                    args["NodeNames"] = truth
                    return args, None  # serve the truth, skip the entry
            WIRE_DIGEST.inc("hit")
        WIRE_CANDIDATES.observe(len(entry.names))
        args["NodeNames"] = entry.names  # shared: handlers never mutate it
        ctx = _Ctx(entry)
        native = self.native
        if native is not None and native.enabled:
            # exact-byte identity for the native table: span digest plus
            # a streamed digest of everything around the span. Identical
            # (span, remainder) digests mean the identical request body,
            # so the synced response answers it verbatim.
            ctx.span_digest = digest
            h = hashlib.blake2b(raw[:s], digest_size=16)
            h.update(raw[e:])
            ctx.rem_digest = h.digest()
        return args, ctx

    def occupancy(self) -> tuple[int, int]:
        """(digest entries, cached responses) — /inspect/wire reads
        the bookkeeping under the rank-6 lock like every other access."""
        with self._lock:
            return (len(self._entries),
                    sum(len(e.responses)
                        for e in self._entries.values()))

    # -- response cache --------------------------------------------------

    def lookup(self, ctx: _Ctx, verb: str, sig: tuple) -> WireEncoded | None:
        """Cached encoded response for (digest, verb, sig) at the CURRENT
        mutation stamp, else None. The stamp is read before returning —
        and remembered for the store — so a response computed now can
        never be served across a mutation that raced the compute."""
        key = (verb, sig)
        stamp = self._cache.mutation_stamp()
        ctx.stamps[key] = stamp
        with self._lock:
            rec = ctx.entry.responses.get(key)
        if rec is not None and rec[0] == stamp:
            return rec[1]
        return None

    def served_hit(self, verb: str) -> None:
        WIRE_RESPONSES.inc(verb, "hit")

    def finish_filter(self, ctx: _Ctx, sig: tuple, ok_nodes: list[str],
                      failed: dict[str, str], *, cacheable: bool,
                      expected: WireEncoded | None) -> WireEncoded:
        """Encode a freshly computed Filter verdict from fragments and
        (when cacheable) store it under the pre-compute stamp.
        ``expected`` is the verify-mode hit being double-checked."""
        body = self.encode_filter(ok_nodes, failed)
        enc = WireEncoded(body, ok=len(ok_nodes), failed=len(failed))
        return self._finish(ctx, ("filter", sig), enc, "filter",
                            cacheable, expected)

    def finish_prioritize(self, ctx: _Ctx, sig: tuple,
                          out: list[dict[str, Any]], best: str | None, *,
                          cacheable: bool,
                          expected: WireEncoded | None) -> WireEncoded:
        body = self.encode_prioritize(out)
        enc = WireEncoded(body, best=best, count=len(out))
        return self._finish(ctx, ("prioritize", sig), enc, "prioritize",
                            cacheable, expected)

    def _finish(self, ctx: _Ctx, key: tuple, enc: WireEncoded, verb: str,
                cacheable: bool, expected: WireEncoded | None) -> WireEncoded:
        if expected is not None:
            # verify mode recomputed a hit: a byte difference means the
            # stamp protocol failed to invalidate — count it, serve truth
            if expected.body != enc.body:
                WIRE_STALE_SERVES.inc()
            WIRE_RESPONSES.inc(verb, "hit")
        else:
            WIRE_RESPONSES.inc(verb, "encoded" if cacheable else "bypass")
        if cacheable:
            stamp = ctx.stamps.get(key)
            if stamp is not None:
                with self._lock:
                    resp = ctx.entry.responses
                    if len(resp) >= self.MAX_RESPONSES and key not in resp:
                        resp.clear()
                    resp[key] = (stamp, enc)
                # delta-sync the native table AFTER releasing self._lock
                # (rank 6): install takes the nativewire bookkeeping
                # lock (rank 7), never the reverse
                native = self.native
                if native is not None and ctx.rem_digest is not None:
                    native.install(ctx.span_digest, ctx.rem_digest,
                                   verb, stamp, enc.body)
                    if ctx.pod_key is not None:
                        # shadow the install in the black-box digest map:
                        # a future native hit of these exact digests
                        # serves THIS pod with THIS verdict, and the ring
                        # pump joins the event back here for the
                        # source=native explain record
                        from tpushare.obs.blackbox import DIGEST_MAP
                        DIGEST_MAP.register(
                            ctx.span_digest, ctx.rem_digest, verb, {
                                "pod_key": ctx.pod_key,
                                "pod": ctx.pod,
                                "ok": enc.ok if verb == "filter" else None,
                                "candidates": (enc.ok + enc.failed
                                               if verb == "filter"
                                               else enc.count),
                                "best": enc.best,
                                "stamp": stamp,
                                "digest": ctx.span_digest.hex(),
                            })
        return enc

    # -- fragment encoders (byte-identical to json.dumps defaults) ------

    def _frag(self, s: str) -> bytes:
        f = self._frags.get(s)
        if f is None:
            f = json.dumps(s).encode()
            if len(self._frags) >= self.MAX_FRAGMENTS:
                self._frags.clear()
            self._frags[s] = f
        return f

    def encode_filter(self, ok_nodes: list[str],
                      failed: dict[str, str]) -> bytes:
        frag = self._frag
        return b"".join((
            b'{"NodeNames": [', b", ".join(map(frag, ok_nodes)),
            b'], "FailedNodes": {',
            b", ".join(frag(n) + b": " + frag(r)
                       for n, r in failed.items()),
            b'}, "Error": ""}'))

    def encode_prioritize(self, out: list[dict[str, Any]]) -> bytes:
        frag = self._frag
        return b"[" + b", ".join(
            b'{"Host": ' + frag(h["Host"]) + b', "Score": '
            + (_INT_FRAGS.get(h["Score"]) or str(h["Score"]).encode())
            + b"}" for h in out) + b"]"
