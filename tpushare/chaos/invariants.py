"""Continuous invariant checkers for chaos drills.

The system's one unbreakable promise (PAPER.md designs: annotations are
the only channel) is that **apiserver truth never oversubscribes a
chip** — not at the end of a storm, at *every instant of it*. The cache
may transiently overcount (that only makes binds conservative); the
placements the apiserver holds must always sum within capacity.

:func:`oversubscription` checks one snapshot; :class:`InvariantMonitor`
runs it continuously from a sampler thread while a drill storms, and
also tracks the oldest pending placement so a drill can assert the
bounded-pending-age promise after healing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from tpushare import contract
from tpushare.contract import pod as podlib
from tpushare.metrics import Counter, LabeledCounter
from tpushare.qos.tiers import TIER_BEST_EFFORT, pod_tier

CHAOS_VIOLATIONS = LabeledCounter(
    "tpushare_chaos_invariant_violations_total",
    "Invariant violations observed by chaos-drill monitors, by check "
    '("oversubscription": a chip\'s summed live grants exceeded its '
    "HBM on apiserver truth). MUST stay 0 — nonzero is a real "
    "scheduler bug, not a chaos artifact",
    ("check",))

QOS_GUARANTEED_VIOLATIONS = Counter(
    "tpushare_qos_guaranteed_violations_total",
    "Sampled instants where a chip's summed non-best-effort grants "
    "exceeded its physical HBM on apiserver truth — a guaranteed/"
    "burstable reservation backed by borrowed memory. MUST stay 0; "
    "nonzero pages (docs/ops.md): QoS admission or the pressure "
    "evictor is broken, not merely slow")


def oversubscription(pods: list[dict[str, Any]], chip_hbm_mib: int
                     ) -> list[tuple[tuple[str, int], int]]:
    """Per-chip grant sums over BOUND live pods vs capacity.

    Returns ``[((node, chip), total_mib), ...]`` for every chip whose
    summed grants exceed ``chip_hbm_mib``. Unbound pods (half-bound
    placements mid-fault) hold nothing real and are skipped — they are
    the *recovery* reconciler's problem, not an oversubscription.
    """
    per: dict[tuple[str, int], int] = {}
    for pod in pods:
        if contract.is_complete_pod(pod):
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        for c in ids:
            per[(node, c)] = per.get((node, c), 0) + hbm
    return [(k, v) for k, v in sorted(per.items()) if v > chip_hbm_mib]


def qos_violations(pods: list[dict[str, Any]], chip_hbm_mib: int,
                   overcommit: float
                   ) -> tuple[list[tuple[tuple[str, int], int]],
                              list[tuple[tuple[str, int], int]]]:
    """Tier-aware per-chip checks over BOUND live pods.

    Returns ``(guaranteed_violations, overcommit_violations)``:

    - a *guaranteed violation* is a chip whose summed non-best-effort
      grants exceed physical ``chip_hbm_mib`` — someone's reservation
      is backed by borrowed memory;
    - an *overcommit violation* is a chip whose TOTAL grant sum exceeds
      ``chip_hbm_mib * overcommit`` — admission blew the declared
      borrow bound.

    The legacy :func:`oversubscription` checker would flag intended
    best-effort borrowing (total > physical) as a violation, so QoS
    drills use this pair instead; non-QoS drills keep the strict one.
    """
    total: dict[tuple[str, int], int] = {}
    non_be: dict[tuple[str, int], int] = {}
    for pod in pods:
        if contract.is_complete_pod(pod):
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        tier = pod_tier(pod)
        for c in ids:
            total[(node, c)] = total.get((node, c), 0) + hbm
            if tier != TIER_BEST_EFFORT:
                non_be[(node, c)] = non_be.get((node, c), 0) + hbm
    bound = int(chip_hbm_mib * overcommit)
    return (
        [(k, v) for k, v in sorted(non_be.items()) if v > chip_hbm_mib],
        [(k, v) for k, v in sorted(total.items()) if v > bound],
    )


class InvariantMonitor:
    """Samples apiserver truth continuously while a drill storms.

    ``list_pods`` is any zero-arg callable returning the current pod
    list (a FakeCluster method, or an InClusterClient against the stub
    apiserver). Sampling errors are tolerated and counted — during a
    brownout the monitor's own reads fail too, by design — but at least
    one *successful* sample is required for a drill to claim coverage.
    """

    def __init__(self, list_pods: Callable[[], list[dict[str, Any]]],
                 chip_hbm_mib: int, *, interval_s: float = 0.005) -> None:
        self._list_pods = list_pods
        self._chip_hbm_mib = chip_hbm_mib
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._violations: list[tuple[tuple[str, int], int]] = []
        self._samples = 0
        self._errors = 0
        self._max_pending_age_s = 0.0
        self._pending_since: dict[str, float] = {}

    def _sample(self) -> None:
        try:
            pods = self._list_pods()
        except Exception:  # noqa: BLE001 — brownouts hit us too
            with self._lock:
                self._errors += 1
            return
        bad = oversubscription(pods, self._chip_hbm_mib)
        now = time.monotonic()
        seen_pending: set[str] = set()
        for pod in pods:
            if contract.is_complete_pod(pod) or \
                    (pod.get("spec") or {}).get("nodeName"):
                continue
            if contract.chip_ids_from_annotations(pod) is None:
                continue
            key = podlib.pod_cache_key(pod)
            seen_pending.add(key)
        with self._lock:
            self._samples += 1
            for key in list(self._pending_since):
                if key not in seen_pending:
                    del self._pending_since[key]
            for key in seen_pending:
                since = self._pending_since.setdefault(key, now)
                self._max_pending_age_s = max(self._max_pending_age_s,
                                              now - since)
            if bad:
                self._violations.extend(bad)
        for _ in bad:
            CHAOS_VIOLATIONS.inc("oversubscription")

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sample()
            self._stop.wait(self._interval_s)

    def start(self) -> "InvariantMonitor":
        self._thread = threading.Thread(target=self._run,
                                        name="chaos-invariants",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> dict[str, Any]:
        """Stop sampling, take one final sample, return the verdict."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._sample()
        return self.report()

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "samples": self._samples,
                "sample_errors": self._errors,
                "oversubscription": list(self._violations),
                "max_pending_age_s": self._max_pending_age_s,
            }


class QosInvariantMonitor:
    """The tier-aware sampler for QoS drills: continuously asserts the
    guaranteed-reservation invariant and the overcommit bound on
    apiserver truth (:func:`qos_violations`), instead of the strict
    total<=capacity check a non-overcommitted fleet uses. Same
    lifecycle and verdict shape as :class:`InvariantMonitor`."""

    def __init__(self, list_pods: Callable[[], list[dict[str, Any]]],
                 chip_hbm_mib: int, overcommit: float, *,
                 interval_s: float = 0.005) -> None:
        self._list_pods = list_pods
        self._chip_hbm_mib = chip_hbm_mib
        self._overcommit = overcommit
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._guaranteed: list[tuple[tuple[str, int], int]] = []
        self._overcommitted: list[tuple[tuple[str, int], int]] = []
        self._samples = 0
        self._errors = 0

    def _sample(self) -> None:
        try:
            pods = self._list_pods()
        except Exception:  # noqa: BLE001 — brownouts hit us too
            with self._lock:
                self._errors += 1
            return
        bad_g, bad_oc = qos_violations(pods, self._chip_hbm_mib,
                                       self._overcommit)
        with self._lock:
            self._samples += 1
            if bad_g:
                self._guaranteed.extend(bad_g)
            if bad_oc:
                self._overcommitted.extend(bad_oc)
        for _ in bad_g:
            QOS_GUARANTEED_VIOLATIONS.inc()
            CHAOS_VIOLATIONS.inc("qos_guaranteed")
        for _ in bad_oc:
            CHAOS_VIOLATIONS.inc("qos_overcommit_bound")

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sample()
            self._stop.wait(self._interval_s)

    def start(self) -> "QosInvariantMonitor":
        self._thread = threading.Thread(target=self._run,
                                        name="qos-invariants",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> dict[str, Any]:
        """Stop sampling, take one final sample, return the verdict."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._sample()
        return self.report()

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "samples": self._samples,
                "sample_errors": self._errors,
                "guaranteed_violations": list(self._guaranteed),
                "overcommit_violations": list(self._overcommitted),
            }
