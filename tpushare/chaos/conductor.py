"""The chaos conductor: replays a sim fault schedule against a fleet.

``tpushare/sim/traces.py::synth_faults`` produces one seeded, sorted
schedule of :class:`~tpushare.sim.traces.FaultEvent` objects. The sim
engines consume it to model faults; this module is the third consumer —
it maps the same events onto *actions against a running fleet*, so the
wind tunnel and the real stack are falsified by the identical storm:

==================  =====================================================
event kind          fleet action (via the target adapter)
==================  =====================================================
``node_down``       partition the node (NotReady; ``lose_pods`` kills
                    its running pods too — a hard crash)
``node_up``         heal the partition
``degrade``         shrink the node's healthy chip set (the device
                    plugin's unhealthy-configmap channel)
``brownout_start``  apiserver brownout: sever watches, 503 node verbs
``brownout_end``    heal the brownout
``replica_crash``   kill one extender replica (mid-bind, if it can)
``replica_restart`` bring the replica back (cold start + recovery pass)
==================  =====================================================

The conductor owns only pacing and dispatch. *What* a "replica" or a
"node" is — an in-process stack over a FakeCluster, or a real OS
process against the wire-format stub apiserver — lives in the target
adapter (see :class:`~tpushare.chaos.drill.HermeticFleet` for the
hermetic one; the multi-process harness in tests/test_chaos_fleet.py
builds the real-process one). Event times are sim-units; the conductor
compresses them by ``seconds_per_unit`` so a 10-unit schedule can storm
a test fleet in half a second.

A target implements a subset of the action methods; events with no
matching method are counted as skipped, not errors, so one schedule
drives targets of different fidelity.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable

from tpushare.metrics import LabeledCounter

log = logging.getLogger("tpushare.chaos")

CHAOS_FAULTS = LabeledCounter(
    "tpushare_chaos_faults_injected_total",
    "Fault events the chaos conductor injected into a fleet, by kind "
    "(a drill that injected nothing proved nothing — bench's chaos "
    "section asserts this is nonzero)",
    ("kind",))

# event kind -> (target method, args builder)
_DISPATCH: dict[str, tuple[str, Callable[[Any], tuple]]] = {
    "node_down": ("node_down", lambda ev: (ev.node, ev.lose_pods)),
    "node_up": ("node_up", lambda ev: (ev.node,)),
    "degrade": ("degrade", lambda ev: (ev.node, ev.chips)),
    "brownout_start": ("brownout_start", lambda ev: ()),
    "brownout_end": ("brownout_end", lambda ev: ()),
    "replica_crash": ("replica_crash", lambda ev: (ev.replica,)),
    "replica_restart": ("replica_restart", lambda ev: (ev.replica,)),
}


class ChaosConductor:
    """Paces a fault schedule onto a target adapter.

    ``run`` is synchronous (callers wanting a background storm wrap it
    in a thread); it returns per-kind applied/skipped counts so a drill
    can assert the storm it asked for is the storm it got.
    """

    def __init__(self, target: Any, *, seconds_per_unit: float = 0.05,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        if seconds_per_unit <= 0:
            raise ValueError("seconds_per_unit must be positive")
        self.target = target
        self.seconds_per_unit = seconds_per_unit
        self._clock = clock
        self._sleep = sleep

    def run(self, schedule: Iterable[Any]) -> dict[str, int]:
        """Apply every event at its compressed wall-clock offset.

        Returns ``{kind: applied_count, ..., "skipped": n}``. An action
        that raises is logged and counted as skipped — the conductor
        must outlive the faults it causes (a brownout that 503s the
        conductor's own probe is working as intended).
        """
        start = self._clock()
        applied: dict[str, int] = {"skipped": 0}
        for ev in schedule:
            deadline = start + ev.time * self.seconds_per_unit
            delay = deadline - self._clock()
            if delay > 0:
                self._sleep(delay)
            method, argsfn = _DISPATCH[ev.kind]
            fn = getattr(self.target, method, None)
            if fn is None:
                applied["skipped"] += 1
                continue
            try:
                fn(*argsfn(ev))
            except Exception as e:  # noqa: BLE001 — the storm goes on
                log.warning("chaos: %s at t=%.2f failed: %s",
                            ev.kind, ev.time, e)
                applied["skipped"] += 1
                continue
            applied[ev.kind] = applied.get(ev.kind, 0) + 1
            CHAOS_FAULTS.inc(ev.kind)
        return applied
