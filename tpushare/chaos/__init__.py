"""Fault-domain chaos tooling: the conductor that replays sim fault
schedules against a live fleet, the continuous invariant monitors, the
hermetic drill bench.py and tier-1 both run (ISSUE 13 tentpole b), and
the live-migration drill (mid-move crashes against whole-slice moves).
"""

from tpushare.chaos.conductor import CHAOS_FAULTS, ChaosConductor
from tpushare.chaos.drill import (
    HermeticFleet,
    assert_drill_invariants,
    run_hermetic_drill,
)
from tpushare.chaos.invariants import (
    CHAOS_VIOLATIONS,
    InvariantMonitor,
    oversubscription,
)
from tpushare.chaos.migration_drill import (
    assert_migration_drill_invariants,
    half_moved_slices,
    run_migration_drill,
)

__all__ = [
    "CHAOS_FAULTS",
    "CHAOS_VIOLATIONS",
    "ChaosConductor",
    "HermeticFleet",
    "InvariantMonitor",
    "assert_drill_invariants",
    "assert_migration_drill_invariants",
    "half_moved_slices",
    "oversubscription",
    "run_hermetic_drill",
    "run_migration_drill",
]
