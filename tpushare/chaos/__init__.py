"""Fault-domain chaos tooling: the conductor that replays sim fault
schedules against a live fleet, the continuous invariant monitors, and
the hermetic drill bench.py and tier-1 both run (ISSUE 13 tentpole b).
"""

from tpushare.chaos.conductor import CHAOS_FAULTS, ChaosConductor
from tpushare.chaos.drill import (
    HermeticFleet,
    assert_drill_invariants,
    run_hermetic_drill,
)
from tpushare.chaos.invariants import (
    CHAOS_VIOLATIONS,
    InvariantMonitor,
    oversubscription,
)

__all__ = [
    "CHAOS_FAULTS",
    "CHAOS_VIOLATIONS",
    "ChaosConductor",
    "HermeticFleet",
    "InvariantMonitor",
    "assert_drill_invariants",
    "oversubscription",
    "run_hermetic_drill",
]
