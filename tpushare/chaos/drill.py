"""The hermetic chaos drill: one seeded fault schedule, a real in-process
fleet, continuous invariants, and a verdict dict.

This is the FakeCluster-backed target for the conductor — the whole
scheduler stack (SchedulerCache + Controller + Filter/Bind handlers
behind the hardened client, two replicas of it) runs against one shared
in-memory apiserver while :class:`~tpushare.chaos.conductor.ChaosConductor`
replays a ``synth_faults`` schedule onto it:

- ``node_down``/``node_up``   -> node-scoped partition (``lose_pods``
  additionally fails the node's running pods — a hard host crash);
- ``degrade``                 -> the device plugin's unhealthy-chip
  configmap, shrinking the schedulable chip set;
- ``brownout_*``              -> sever every watch stream + partition
  every node (apiserver-wide 503s on the bind path);
- ``replica_crash``           -> stop one replica's stack *after* it
  stamps placement annotations on a victim pod it never binds — the
  exact half-bound state a real crash in the patch->bind gap leaves;
- ``replica_restart``         -> cold-start a fresh stack (build_cache
  from truth + ``reconcile_once``), the production startup sequence.

Used by tests/test_chaos_fleet.py (tier-1) and bench.py's ``chaos``
section; both assert the same self-checks on the returned dict: zero
oversubscription at every sampled instant, zero cache-vs-truth drift
after healing, every half-bound orphan adopted-or-GC'd within the
bounded recovery window, and a storm that actually stormed.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.chaos.conductor import ChaosConductor
from tpushare.chaos.invariants import InvariantMonitor, oversubscription
from tpushare.contract.constants import (
    UNHEALTHY_CM_KEY,
    UNHEALTHY_CM_NAMESPACE,
    UNHEALTHY_CM_PREFIX,
)
from tpushare.controller import Controller
from tpushare.controller.recovery import (
    RECOVERY_ADOPTED,
    RECOVERY_GC,
    reconcile_once,
)
from tpushare.k8s import CircuitBreaker, FakeCluster, RetryPolicy, harden
from tpushare.sim import FaultSpec, synth_faults

HBM_PER_CHIP = 16000


def _make_pod(name: str, hbm: int) -> dict[str, Any]:
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {}},
        "spec": {"containers": [{"name": "c0", "resources": {
            "limits": {"aliyun.com/tpu-hbm": str(hbm)}}}]},
        "status": {"phase": "Pending"},
    }


class _Replica:
    """One in-process extender stack over the shared fake apiserver,
    with the production wiring: hardened client, controller heartbeat,
    recovery pass at startup and on every resync."""

    def __init__(self, fc: FakeCluster, seed: int, resync_s: float,
                 stale_after_s: float) -> None:
        self._fc = fc
        self._seed = seed
        self._resync_s = resync_s
        self._stale_after_s = stale_after_s
        self.alive = False
        self._build()

    def _build(self) -> None:
        from tpushare.extender.handlers import BindHandler, FilterHandler
        from tpushare.extender.metrics import Registry
        cluster = harden(
            self._fc,
            breaker=CircuitBreaker(failure_threshold=4,
                                   reset_timeout_s=0.05),
            policy=RetryPolicy(max_attempts=3, base_s=0.002, cap_s=0.01,
                               rng=random.Random(self._seed)))
        self.cluster = cluster
        self.cache = SchedulerCache(cluster)
        self.ctl = Controller(cluster, self.cache,
                              resync_seconds=self._resync_s)
        self.ctl.build_cache()
        # the production startup sequence (extender/__main__.py): one
        # recovery pass now, then one on every resync heartbeat
        reconcile_once(cluster, self.cache,
                       stale_after_s=self._stale_after_s)
        self.ctl.resync_hooks.append(lambda: reconcile_once(
            cluster, self.cache, stale_after_s=self._stale_after_s))
        self.ctl.start()
        registry = Registry()
        self.fil = FilterHandler(self.cache, registry)
        # two independent replicas bind concurrently: the per-node claim
        # CAS (ha_claims) is what keeps apiserver truth single-writer —
        # the drill proved its absence oversubscribes within seconds
        self.binder = BindHandler(self.cache, cluster, registry,
                                  ha_claims=True)
        self.alive = True

    def crash(self, victim_name: str | None = None) -> None:
        """Die the worst way: placement annotations stamped on a pod
        that never gets bound, then the whole stack stops cold."""
        self.alive = False
        if victim_name is not None:
            try:
                self._fc.create_pod(_make_pod(victim_name, 1024))
                ann = contract.placement_annotations(
                    [0], 1024, HBM_PER_CHIP, now_ns=time.time_ns())
                self._fc.patch_pod("default", victim_name,
                                   {"metadata": {"annotations": ann}})
            except Exception:  # noqa: BLE001 — mid-brownout crash, fine
                pass
        self.ctl.stop()

    def restart(self) -> None:
        if not self.alive:
            self._build()


class HermeticFleet:
    """The conductor target: sim fault kinds mapped onto FakeCluster
    chaos primitives and in-process replica crash/restart."""

    def __init__(self, fc: FakeCluster, node_names: list[str],
                 replicas: list[_Replica]) -> None:
        self._fc = fc
        self._names = node_names
        self._replicas = replicas
        self._crashes = 0

    # -- node faults ---------------------------------------------------------

    def node_down(self, idx: int, lose_pods: bool) -> None:
        name = self._names[idx % len(self._names)]
        self._fc.partition(name)
        if lose_pods:
            for pod in self._fc.list_pods(node_name=name):
                if not contract.is_complete_pod(pod):
                    self._fc.set_pod_phase(
                        pod["metadata"]["namespace"],
                        pod["metadata"]["name"], "Failed")

    def node_up(self, idx: int) -> None:
        self._fc.heal(self._names[idx % len(self._names)])

    def degrade(self, idx: int, chips: tuple[int, ...]) -> None:
        name = self._names[idx % len(self._names)]
        self._fc.set_configmap(
            UNHEALTHY_CM_NAMESPACE, UNHEALTHY_CM_PREFIX + name,
            {UNHEALTHY_CM_KEY: ",".join(str(c) for c in chips)})

    # -- apiserver brownout --------------------------------------------------

    def brownout_start(self) -> None:
        self._fc.break_watches()
        for name in self._names:
            self._fc.partition(name)

    def brownout_end(self) -> None:
        self._fc.heal()

    # -- replica faults ------------------------------------------------------

    def replica_crash(self, idx: int) -> None:
        rep = self._replicas[idx % len(self._replicas)]
        if rep.alive and sum(r.alive for r in self._replicas) > 1:
            self._crashes += 1
            rep.crash(victim_name=f"victim-{self._crashes}")

    def replica_restart(self, idx: int) -> None:
        self._replicas[idx % len(self._replicas)].restart()

    def heal_all(self) -> None:
        self._fc.heal()
        for rep in self._replicas:
            rep.restart()


def run_hermetic_drill(*, seed: int = 1234, n_nodes: int = 3,
                       n_pods: int = 24, hours: float = 20.0,
                       seconds_per_unit: float = 0.05,
                       stale_after_s: float = 0.2,
                       resync_s: float = 0.1,
                       threads: int = 4) -> dict[str, Any]:
    """One full drill; returns the verdict for self-checks.

    Deterministic in its *schedule* (seeded synth_faults + seeded
    retries); thread interleavings vary, which is the point — the
    invariants must hold on every interleaving.
    """
    fc = FakeCluster()
    names = [f"n{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM_PER_CHIP,
                        mesh="2x2")
    replicas = [_Replica(fc, seed + i, resync_s, stale_after_s)
                for i in range(2)]
    fleet = HermeticFleet(fc, names, replicas)
    schedule = synth_faults(FaultSpec(
        hours=hours, n_nodes=n_nodes, chips_per_node=4,
        node_crashes=1, notready_windows=1, degradations=1,
        brownouts=1, replica_crashes=1, replicas=2,
        mean_outage=3.0, seed=seed))
    monitor = InvariantMonitor(fc.list_pods, HBM_PER_CHIP,
                               interval_s=0.003).start()
    gc_before = RECOVERY_GC.total()
    adopted_before = RECOVERY_ADOPTED.total()

    conductor = ChaosConductor(fleet, seconds_per_unit=seconds_per_unit)
    applied: dict[str, int] = {}
    storm = threading.Thread(
        target=lambda: applied.update(conductor.run(schedule)),
        name="chaos-conductor", daemon=True)
    storm.start()

    storm_end = time.monotonic() + hours * seconds_per_unit + 10.0

    def schedule_pod(pod: dict[str, Any]) -> bool:
        ns, name = pod["metadata"]["namespace"], pod["metadata"]["name"]
        attempt = 0
        while time.monotonic() < storm_end:
            reps = [r for r in replicas if r.alive]
            if not reps:
                time.sleep(0.01)
                continue
            rep = reps[attempt % len(reps)]
            try:
                res = rep.fil.handle({"Pod": pod, "NodeNames": names})
                nodes = res["NodeNames"]
                if nodes:
                    out = rep.binder.handle({
                        "PodNamespace": ns, "PodName": name,
                        "PodUID": pod["metadata"]["uid"],
                        "Node": nodes[attempt % len(nodes)]})
                    if out["Error"] == "":
                        return True
            except Exception:  # noqa: BLE001 — brownout/crash races
                pass
            attempt += 1
            time.sleep(0.004)
        return False

    pods = [fc.create_pod(_make_pod(f"d{i}", 2048)) for i in range(n_pods)]
    with ThreadPoolExecutor(threads) as ex:
        results = list(ex.map(schedule_pod, pods))
    storm.join(timeout=hours * seconds_per_unit + 30.0)

    # -- healing: lift everything, then measure the recovery window ----------
    heal_t0 = time.monotonic()
    fleet.heal_all()

    def half_bound_left() -> list[str]:
        out = []
        for pod in fc.list_pods():
            if contract.is_complete_pod(pod) or \
                    (pod.get("spec") or {}).get("nodeName"):
                continue
            if contract.chip_ids_from_annotations(pod) is not None:
                out.append(pod["metadata"]["name"])
        return out

    # the bound: stale_after_s + one resync heartbeat + scheduling slack
    window_bound_s = stale_after_s + resync_s + 5.0
    while half_bound_left() and \
            time.monotonic() - heal_t0 < window_bound_s:
        time.sleep(0.01)
    recovery_window_s = time.monotonic() - heal_t0

    # any pod the storm stranded binds now, against a healthy fleet
    retried = [schedule_pod(pods[i]) for i, ok in enumerate(results)
               if not ok]
    placed = sum(1 for ok in results if ok) + sum(1 for ok in retried
                                                 if ok)

    # -- drift audit: every surviving cache vs apiserver truth ---------------
    truth_per_chip: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        for c in ids:
            truth_per_chip[(node, c)] = \
                truth_per_chip.get((node, c), 0) + hbm
    drift: list[tuple] = []
    for i, rep in enumerate(replicas):
        rep.ctl.resync_once()
        rep.ctl.drain(timeout=10.0)
        tree = rep.cache.describe()
        for node in tree["nodes"]:
            for chip in node["chips"]:
                want = truth_per_chip.get((node["name"], chip["idx"]), 0)
                if chip["used_hbm_mib"] != want:
                    drift.append((i, node["name"], chip["idx"],
                                  chip["used_hbm_mib"], want))
        rep.ctl.stop()

    verdict = monitor.stop()
    verdict.update({
        "placed": placed,
        "n_pods": n_pods,
        "faults_applied": applied,
        "faults_total": len(schedule),
        "recovery": {
            "adopted": RECOVERY_ADOPTED.total() - adopted_before,
            "gc": RECOVERY_GC.total() - gc_before,
        },
        "half_bound_left": half_bound_left(),
        "recovery_window_s": recovery_window_s,
        "window_bound_s": window_bound_s,
        "drift": drift,
        "final_oversubscription": oversubscription(fc.list_pods(),
                                                   HBM_PER_CHIP),
    })
    return verdict


def assert_drill_invariants(r: dict[str, Any]) -> None:
    """The self-checks bench.py and the tier-1 test share."""
    assert r["samples"] > 0, "the monitor never sampled truth"
    assert not r["oversubscription"], \
        f"oversubscription under faults: {r['oversubscription'][:3]}"
    assert not r["final_oversubscription"], \
        f"oversubscription after heal: {r['final_oversubscription'][:3]}"
    assert not r["drift"], \
        f"cache != apiserver truth after healing: {r['drift'][:5]}"
    assert not r["half_bound_left"], \
        f"half-bound orphans survived recovery: {r['half_bound_left']}"
    assert r["recovery_window_s"] <= r["window_bound_s"], \
        f"recovery blew its bound: {r['recovery_window_s']:.2f}s"
    assert r["placed"] == r["n_pods"], \
        f"{r['n_pods'] - r['placed']} pods never bound"
    injected = sum(v for k, v in r["faults_applied"].items()
                   if k != "skipped")
    assert injected > 0, "the storm injected nothing; it proved nothing"
    assert r["faults_applied"].get("replica_crash", 0) >= 1
    assert r["faults_applied"].get("brownout_start", 0) >= 1
    assert r["recovery"]["gc"] >= 1, \
        "the crash left no half-bound orphan for recovery to reclaim"
