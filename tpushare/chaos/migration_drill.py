"""The live-migration chaos drill: slice moves under mid-move crashes.

Hermetic, like :mod:`tpushare.chaos.drill` — a real SchedulerCache +
GangCoordinator + DefragPlanner/Executor stack over one FakeCluster —
but the storm here is surgical: a multi-host gang is fragmented into a
planned whole-slice move and the drill kills the migration at the worst
instants a real fleet produces:

- ``crash_checkpoint`` — the victim's serve replica dies while its
  state is being checkpointed (``checkpointer.save`` raises). This is
  strictly before any apiserver write, so the move must abort with the
  slice byte-identically untouched on its source chips.
- ``crash_midplace``  — the executor's apiserver write fails after the
  slice is evicted and PART of it is re-placed (the replacement
  ``create_pod`` for a non-leader rank raises). The rollback must
  reassemble the whole slice on its ORIGINAL chips.

Both are run after one ``completed`` control move, with the
:class:`~tpushare.chaos.invariants.InvariantMonitor` sampling apiserver
truth throughout. The verdict the self-checks enforce is the tentpole's
acceptance line: ZERO oversubscription at every sampled instant and
ZERO half-moved slices — at no point does any gang have members
straddling two plans, and a failed move always converges back to the
source geometry.

Used by tests/test_chaos_migration.py (tier-1) and bench.py's
``migration`` section.
"""

from __future__ import annotations

import copy
import json
import time
from typing import Any

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.gang import GangCoordinator
from tpushare.chaos.invariants import InvariantMonitor, oversubscription
from tpushare.contract import pod as podlib
from tpushare.defrag.executor import DefragExecutor
from tpushare.defrag.migration import Migrator
from tpushare.defrag.planner import ANN_MOVABLE, DefragPlanner
from tpushare.k8s import FakeCluster

HBM_PER_CHIP = 16000
GANG_HBM = 8000  # per chip: half HBM, so solos can share and fragment


def _gang_pod(name: str, rank: int, gang_id: str = "g1") -> dict[str, Any]:
    return {
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {
                         contract.ANN_GANG: gang_id,
                         contract.ANN_GANG_SIZE: "8",
                         contract.ANN_GANG_RANK: str(rank),
                         contract.ANN_TOPOLOGY: "2x4",
                         ANN_MOVABLE: "true",
                     }},
        "spec": {"containers": [{"name": "c", "resources": {"limits": {
            contract.RESOURCE_COUNT: "4",
            # per-device semantics: every gang chip must offer this much
            contract.RESOURCE_HBM: str(GANG_HBM),
        }}}]},
    }


def _solo_pod(name: str, node: str, chips: list[int],
              hbm: int) -> dict[str, Any]:
    ann = contract.placement_annotations(chips, hbm, HBM_PER_CHIP)
    return {
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}", "annotations": ann},
        "spec": {"nodeName": node,
                 "containers": [{"name": "c", "resources": {"limits": {
                     contract.RESOURCE_HBM: str(hbm)}}}]},
        "status": {"phase": "Running"},
    }


class _Frontend:
    """A serve-loop stand-in that actually tracks the pause window."""

    def __init__(self) -> None:
        self.paused = False
        self.pauses = 0

    def pause(self, timeout: float) -> bool:
        self.paused = True
        self.pauses += 1
        return True

    def resume(self) -> None:
        self.paused = False


class _Checkpointer:
    """Counts saves/restores; arms a one-shot crash on a chosen pod —
    the serve replica dying mid-checkpoint."""

    def __init__(self) -> None:
        self.saved: list[str] = []
        self.restored: list[str] = []
        self.crash_on_save: str | None = None

    def save(self, pod: dict[str, Any], move: Any) -> None:
        name = podlib.pod_name(pod)
        if self.crash_on_save == name:
            self.crash_on_save = None
            raise RuntimeError("serve replica crashed mid-checkpoint")
        self.saved.append(name)

    def restore(self, pod: dict[str, Any], move: Any) -> None:
        self.restored.append(podlib.pod_name(pod))


class _FlakyCluster:
    """FakeCluster passthrough with a one-shot create_pod fault — the
    scheduler's apiserver write dying after eviction, mid-placement.
    One-shot on purpose: the executor's ROLLBACK writes must succeed,
    exactly like a real apiserver that returned one 500."""

    def __init__(self, fc: FakeCluster) -> None:
        self._fc = fc
        self.fail_create_for: str | None = None

    def __getattr__(self, attr: str) -> Any:
        return getattr(self._fc, attr)

    def create_pod(self, pod: dict[str, Any]) -> dict[str, Any]:
        name = podlib.pod_name(pod)
        if self.fail_create_for == name:
            self.fail_create_for = None
            raise RuntimeError("apiserver write lost mid-placement")
        return self._fc.create_pod(pod)


def half_moved_slices(pods: list[dict[str, Any]]) -> list[str]:
    """Gang ids whose live members are torn — a rank missing or
    unbound, no stamped plan anywhere, members stamped with DIFFERENT
    plans, or any member placed somewhere the stamped plan does not
    say (the half-recomposed ``TPU_PROCESS_BOUNDS`` state the
    tentpole's all-or-nothing guarantee forbids). Only the first-bound
    member necessarily carries ``ANN_GANG_PLAN``; every member's actual
    (host, chips) must appear in that one plan."""
    gangs: dict[str, dict[int, dict[str, Any]]] = {}
    for p in pods:
        try:
            gm = podlib.gang_membership(p)
        except ValueError:
            continue
        if gm is not None:
            gangs.setdefault(gm[0], {})[gm[2]] = p
    torn = []
    for gid, members in sorted(gangs.items()):
        plans = set()
        placements = []
        ok = True
        for _rank, p in sorted(members.items()):
            node = podlib.pod_node_name(p)
            chips = podlib.chip_ids_from_annotations(p)
            if not node or chips is None:
                ok = False
                break
            placements.append((node, tuple(sorted(chips))))
            raw = podlib.annotations(p).get(contract.ANN_GANG_PLAN)
            if raw:
                plans.add(raw)
        if ok and len(plans) == 1:
            try:
                rows = json.loads(next(iter(plans)))["members"]
                want = {(r["host"], tuple(sorted(r["chips"])))
                        for r in rows}
            except (ValueError, KeyError, TypeError):
                want = None
            ok = (want is not None and len(rows) == len(members)
                  and all(pl in want for pl in placements))
        else:
            ok = False
        if not ok:
            torn.append(gid)
    return torn


class _Rig:
    """One fresh fleet: two 2-host slices, the gang bound on slc0,
    one solo filler fragmenting the gang's leader host."""

    def __init__(self) -> None:
        fc = FakeCluster()
        for sid, hosts in (("slc0", ("a0", "a1")),
                           ("slc1", ("b0", "b1"))):
            for host, origin in zip(hosts, ("0x0", "0x2")):
                fc.add_tpu_node(host, chips=4,
                                hbm_per_chip_mib=HBM_PER_CHIP,
                                mesh="2x2", slice_id=sid,
                                slice_origin=origin)
        self.fc = fc
        self.cluster = _FlakyCluster(fc)
        self.cache = SchedulerCache(fc)
        self.cache.build_cache()
        self.gang = GangCoordinator(self.cache)
        now_ns = time.time_ns
        self.member_names = []
        for rank in (0, 1):
            pod = fc.create_pod(_gang_pod(f"g1p{rank}", rank))
            hosts, err = self.gang.filter_hosts(pod, now_ns=now_ns)
            assert err == "" and hosts, f"gang filter failed: {err}"
            self.gang.bind_member(pod, hosts[0], fc, now_ns=now_ns)
            name = podlib.pod_name(pod)
            # what the controller's watch would do after the bind: hand
            # the bound incarnation to the cache so pod_by_key resolves
            self.cache.add_or_update_pod(fc.get_pod("default", name))
            self.member_names.append(name)
        # fragment the leader's host: one solo fills a chip, leaving
        # the node's shareable chips non-contiguous on the 2x2 mesh
        leader = fc.get_pod("default", self.member_names[0])
        lhost = podlib.pod_node_name(leader)
        solo = fc.create_pod(_solo_pod("filler", lhost, [0],
                                       HBM_PER_CHIP - GANG_HBM))
        self.cache.add_or_update_pod(solo)
        self.frontends = {n: _Frontend() for n in self.member_names}
        self.ckpt = _Checkpointer()
        self.migrator = Migrator(
            checkpointer=self.ckpt,
            frontend_for=lambda p: self.frontends.get(podlib.pod_name(p)))
        self.planner = DefragPlanner(self.cache, gang=self.gang,
                                     cluster=fc)
        self.executor = DefragExecutor(self.cache, self.cluster,
                                       budget=8, migrator=self.migrator)

    def member_pods(self) -> list[dict[str, Any]]:
        return [self.fc.get_pod("default", n) for n in self.member_names]

    def snapshot(self) -> list[str]:
        """Canonical placement state of every gang member, for
        byte-level unchanged/rolled-back assertions."""
        out = []
        for p in self.member_pods():
            out.append(json.dumps({
                "node": podlib.pod_node_name(p),
                "annotations": podlib.annotations(p),
            }, sort_keys=True))
        return out


def _run_scenario(kind: str) -> dict[str, Any]:
    rig = _Rig()
    monitor = InvariantMonitor(rig.fc.list_pods, HBM_PER_CHIP,
                               interval_s=0.002).start()
    plan = rig.planner.plan(4)
    result: dict[str, Any] = {"kind": kind,
                              "slice_moves_planned": len(plan.slice_moves)}
    if not plan.slice_moves:
        monitor.stop()
        result["error"] = "planner produced no slice move"
        return result
    smove = plan.slice_moves[0]
    before = rig.snapshot()
    source_nodes = sorted({m.source for m in smove.members})
    if kind == "crash_checkpoint":
        rig.ckpt.crash_on_save = rig.member_names[1]
    elif kind == "crash_midplace":
        # the replacement create for the non-leader rank: by then the
        # whole slice is evicted and the leader already re-placed
        rig.cluster.fail_create_for = rig.member_names[1]
    out = rig.executor.execute_slice_move(smove)
    # let the monitor take at least one post-move sample
    time.sleep(0.01)
    verdict = monitor.stop()
    pods = rig.fc.list_pods()
    result.update({
        "outcome": out["outcome"],
        "error": out.get("error"),
        "samples": verdict["samples"],
        "oversubscription": verdict["oversubscription"],
        "final_oversubscription": oversubscription(pods, HBM_PER_CHIP),
        "half_moved": half_moved_slices(pods),
        "member_nodes": sorted({podlib.pod_node_name(p)
                                for p in rig.member_pods()}),
        "paused_left": [n for n, fe in rig.frontends.items()
                        if fe.paused],
        "checkpoints": len(rig.ckpt.saved),
        "restores": len(rig.ckpt.restored),
    })
    if kind == "completed":
        result["moved_off_source"] = \
            not (set(result["member_nodes"]) & set(source_nodes))
    else:
        result["rolled_back_identical"] = rig.snapshot() == before
    return result


def run_migration_drill() -> dict[str, Any]:
    """All three scenarios on fresh fleets; returns the verdict dict
    for :func:`assert_migration_drill_invariants`."""
    return {kind: _run_scenario(kind)
            for kind in ("completed", "crash_checkpoint",
                         "crash_midplace")}


def assert_migration_drill_invariants(r: dict[str, Any]) -> None:
    """The self-checks bench.py and the tier-1 test share: the
    acceptance line is zero oversubscription and zero half-moved
    slices on EVERY scenario, crash or not."""
    for kind, s in r.items():
        assert s.get("slice_moves_planned"), \
            f"{kind}: planner produced no slice move"
        assert s["samples"] > 0, f"{kind}: the monitor never sampled"
        assert not s["oversubscription"], \
            f"{kind}: oversubscription mid-move: {s['oversubscription'][:3]}"
        assert not s["final_oversubscription"], \
            f"{kind}: oversubscription after: {s['final_oversubscription'][:3]}"
        assert not s["half_moved"], \
            f"{kind}: half-moved slices: {s['half_moved']}"
        assert not s["paused_left"], \
            f"{kind}: serve loops left paused: {s['paused_left']}"
    assert r["completed"]["outcome"] == "completed"
    assert r["completed"]["moved_off_source"], \
        "the control move never left the source slice"
    assert r["completed"]["restores"] == 2, \
        "a completed slice move must restore every member"
    for kind in ("crash_checkpoint", "crash_midplace"):
        assert r[kind]["outcome"] == "failed", \
            f"{kind}: expected a failed move, got {r[kind]['outcome']}"
        assert r[kind]["rolled_back_identical"], \
            f"{kind}: the slice did not return to its source geometry"
