"""The QoS chaos drill: eviction under pressure, storms included.

The hermetic drill (:mod:`tpushare.chaos.drill`) proves the strict
no-oversubscription invariant on a single-class fleet. This drill
proves the *tiered* contract on an oversubscribed one: best-effort
scavengers borrow beyond physical HBM, guaranteed demand then lands on
the borrowed chips, and the pressure monitor pays the debt down by
evicting the borrowers — while a seeded fault schedule (NotReady
window + apiserver brownout) storms the same fleet and a
:class:`~tpushare.chaos.invariants.QosInvariantMonitor` samples
apiserver truth continuously. The verdict it must return:

- **zero guaranteed violations** at every sampled instant — no chip's
  non-best-effort grant sum ever exceeds physical HBM;
- **zero overcommit violations** — no chip's total grant sum ever
  exceeds ``physical * overcommit``;
- **borrowing actually happened** (chips over physical after the
  best-effort fill) and **eviction actually fired** (completed
  evictions >= 1, within the window budget) — a drill that never
  oversubscribed or never evicted proved nothing;
- **zero drift** between every surviving cache and apiserver truth
  after healing.

The same tiered contention is replayed through the discrete-event sim
(:func:`tpushare.sim.qos.run_qos_sim`) by the tier-1 test, so the wind
tunnel and the live stack are falsified against the same invariants.

Deterministic in its *schedule* (seeded synth_faults + seeded retries);
thread interleavings vary, which is the point.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.chaos.conductor import ChaosConductor
from tpushare.chaos.drill import HermeticFleet
from tpushare.chaos.invariants import QosInvariantMonitor, qos_violations
from tpushare.controller import Controller
from tpushare.k8s import CircuitBreaker, FakeCluster, RetryPolicy, harden
from tpushare.qos.pressure import QOS_EVICTIONS, QosPressureMonitor
from tpushare.qos.tiers import (
    ENV_OVERCOMMIT,
    TIER_BEST_EFFORT,
    TIER_GUARANTEED,
    clear_degraded,
)
from tpushare.sim import FaultSpec, synth_faults

HBM_PER_CHIP = 16000


def _tier_pod(name: str, hbm: int, tier: str) -> dict[str, Any]:
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {contract.ANN_QOS_TIER: tier}},
        "spec": {"containers": [{"name": "c0", "resources": {
            "limits": {"aliyun.com/tpu-hbm": str(hbm)}}}]},
        "status": {"phase": "Pending"},
    }


def _truth_oversubscribed(fc: FakeCluster) -> list[tuple]:
    """Chips whose TOTAL grant sum on apiserver truth exceeds physical
    HBM — the intended borrow state, counted as evidence that the drill
    actually oversubscribed (NOT as a violation)."""
    per: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        for c in ids:
            per[(node, c)] = per.get((node, c), 0) + hbm
    return [(k, v) for k, v in sorted(per.items()) if v > HBM_PER_CHIP]


def run_qos_drill(*, seed: int = 77, n_nodes: int = 2,
                  overcommit: float = 2.0,
                  evict_budget: int = 6, evict_window_s: float = 60.0,
                  hours: float = 8.0, seconds_per_unit: float = 0.05,
                  threads: int = 4) -> dict[str, Any]:
    """One full tiered drill; returns the verdict for self-checks.

    Phases (all while the seeded storm runs): best-effort scavengers
    fill and oversubscribe the fleet; then guaranteed + burstable
    demand arrives and must be admitted against reclaimable headroom,
    triggering budget-governed pressure evictions of the borrowers.
    """
    prev_env = os.environ.get(ENV_OVERCOMMIT)
    os.environ[ENV_OVERCOMMIT] = str(overcommit)
    clear_degraded()
    ev_before = {o: QOS_EVICTIONS.get(TIER_BEST_EFFORT, o)
                 for o in ("completed", "failed", "demoted",
                           "skipped_budget", "skipped_backoff",
                           "skipped_inflight")}
    try:
        return _run(seed, n_nodes, overcommit, evict_budget,
                    evict_window_s, hours, seconds_per_unit, threads,
                    ev_before)
    finally:
        if prev_env is None:
            os.environ.pop(ENV_OVERCOMMIT, None)
        else:
            os.environ[ENV_OVERCOMMIT] = prev_env
        clear_degraded()


def _run(seed, n_nodes, overcommit, evict_budget, evict_window_s,
         hours, seconds_per_unit, threads, ev_before) -> dict[str, Any]:
    from concurrent.futures import ThreadPoolExecutor

    from tpushare.extender.handlers import BindHandler, FilterHandler
    from tpushare.extender.metrics import Registry

    fc = FakeCluster()
    names = [f"n{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM_PER_CHIP,
                        mesh="2x2")
    cluster = harden(
        fc,
        breaker=CircuitBreaker(failure_threshold=4, reset_timeout_s=0.05),
        policy=RetryPolicy(max_attempts=3, base_s=0.002, cap_s=0.01,
                           rng=random.Random(seed)))
    cache = SchedulerCache(cluster)
    ctl = Controller(cluster, cache, resync_seconds=0.1)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    fil = FilterHandler(cache, registry)
    binder = BindHandler(cache, cluster, registry)
    pressure = QosPressureMonitor(cache, cluster, budget=evict_budget,
                                  window_s=evict_window_s,
                                  backoff_s=0.05, interval_s=0.01)
    pressure.start()
    qmon = QosInvariantMonitor(fc.list_pods, HBM_PER_CHIP, overcommit,
                               interval_s=0.003).start()

    # the governor's high-water mark, sampled from outside: the proof
    # that an eviction storm stayed within its declared budget
    max_window_used = [0]
    sampler_stop = threading.Event()

    def _sample_budget() -> None:
        while not sampler_stop.is_set():
            used = pressure.budget_state()["used_in_window"]
            max_window_used[0] = max(max_window_used[0], used)
            sampler_stop.wait(0.004)

    sampler = threading.Thread(target=_sample_budget,
                               name="qos-budget-sampler", daemon=True)
    sampler.start()

    # storm: one NotReady window + one apiserver brownout, seeded —
    # the faults most likely to wedge an evictor (deletes 503) or
    # stale a cache mid-admission
    schedule = synth_faults(FaultSpec(
        hours=hours, n_nodes=n_nodes, chips_per_node=4,
        node_crashes=0, notready_windows=1, degradations=0,
        brownouts=1, replica_crashes=0, replicas=1,
        mean_outage=1.5, seed=seed))
    conductor = ChaosConductor(HermeticFleet(fc, names, []),
                               seconds_per_unit=seconds_per_unit)
    applied: dict[str, int] = {}
    storm = threading.Thread(
        target=lambda: applied.update(conductor.run(schedule)),
        name="qos-chaos-conductor", daemon=True)
    storm.start()
    storm_end = time.monotonic() + hours * seconds_per_unit + 10.0

    def schedule_pod(pod: dict[str, Any]) -> bool:
        ns, name = pod["metadata"]["namespace"], pod["metadata"]["name"]
        attempt = 0
        while time.monotonic() < storm_end:
            try:
                res = fil.handle({"Pod": pod, "NodeNames": names})
                nodes = res["NodeNames"]
                if nodes:
                    out = binder.handle({
                        "PodNamespace": ns, "PodName": name,
                        "PodUID": pod["metadata"]["uid"],
                        "Node": nodes[attempt % len(nodes)]})
                    if out["Error"] == "":
                        return True
            except Exception:  # noqa: BLE001 — brownout races
                pass
            attempt += 1
            time.sleep(0.004)
        return False

    # phase A: best-effort scavengers borrow beyond physical. 8 x
    # 11000 MiB: binpack stacks two per chip (22000 > 16000 physical —
    # the borrow state the invariant monitor must NOT flag), leaving
    # 10000 MiB of under-the-bound headroom per borrowed chip that
    # phase B's guaranteed demand can only claim by eviction.
    be_pods = [fc.create_pod(_tier_pod(f"be-{i}", 11000,
                                       TIER_BEST_EFFORT))
               for i in range(8)]
    with ThreadPoolExecutor(threads) as ex:
        be_placed = sum(ex.map(schedule_pod, be_pods))
    oversub_after_fill = _truth_oversubscribed(fc)

    # phase B: guaranteed + burstable demand lands mid-storm — it must
    # be admitted against reclaimable best-effort headroom, and every
    # admission that pushes a chip past physical HBM must be paid down
    # by a budget-governed eviction.
    hi_pods = [fc.create_pod(_tier_pod(f"g-{i}", 8000, TIER_GUARANTEED))
               for i in range(10)]
    hi_pods += [fc.create_pod(_tier_pod(f"b-{i}", 4000, "burstable"))
                for i in range(4)]
    with ThreadPoolExecutor(threads) as ex:
        hi_results = list(ex.map(schedule_pod, hi_pods))
    storm.join(timeout=hours * seconds_per_unit + 30.0)

    # healing: lift every fault, retry anything the storm stranded,
    # let the evictor pay down any remaining pressure
    fc.heal()
    retried = [schedule_pod(hi_pods[i]) for i, ok in enumerate(hi_results)
               if not ok]
    hi_placed = sum(1 for ok in hi_results if ok) + \
        sum(1 for ok in retried if ok)
    settle_end = time.monotonic() + 5.0
    while time.monotonic() < settle_end:
        bad_g, _ = qos_violations(fc.list_pods(), HBM_PER_CHIP,
                                  overcommit)
        if not bad_g and pressure.scan_once() == 0:
            break
        time.sleep(0.02)

    # drift audit: cache vs apiserver truth after healing
    ctl.resync_once()
    ctl.drain(timeout=10.0)
    truth: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        for c in ids:
            truth[(node, c)] = truth.get((node, c), 0) + hbm
    drift: list[tuple] = []
    for node in cache.describe()["nodes"]:
        for chip in node["chips"]:
            want = truth.get((node["name"], chip["idx"]), 0)
            if chip["used_hbm_mib"] != want:
                drift.append((node["name"], chip["idx"],
                              chip["used_hbm_mib"], want))

    sampler_stop.set()
    sampler.join(timeout=2.0)
    pressure.stop()
    ctl.stop()
    verdict = qmon.stop()
    final_g, final_oc = qos_violations(fc.list_pods(), HBM_PER_CHIP,
                                       overcommit)
    evictions = {o: QOS_EVICTIONS.get(TIER_BEST_EFFORT, o) - before
                 for o, before in ev_before.items()}
    verdict.update({
        "overcommit": overcommit,
        "be_pods": len(be_pods),
        "be_placed": be_placed,
        "hi_pods": len(hi_pods),
        "hi_placed": hi_placed,
        "oversubscribed_after_fill": oversub_after_fill,
        "evictions": evictions,
        "evict_budget": evict_budget,
        "max_window_evictions": max_window_used[0],
        "budget_state": pressure.budget_state(),
        "faults_applied": applied,
        "faults_total": len(schedule),
        "final_guaranteed_violations": final_g,
        "final_overcommit_violations": final_oc,
        "drift": drift,
    })
    return verdict


def assert_qos_drill_invariants(r: dict[str, Any]) -> None:
    """The self-checks the tier-1 test and bench share: guaranteed
    isolation held at every sampled instant, borrowing and eviction
    both actually happened, the eviction storm stayed within budget,
    and the caches match truth after healing."""
    assert r["samples"] > 0, "the monitor never sampled truth"
    assert not r["guaranteed_violations"], \
        f"guaranteed reservation violated: {r['guaranteed_violations'][:3]}"
    assert not r["overcommit_violations"], \
        f"overcommit bound blown: {r['overcommit_violations'][:3]}"
    assert not r["final_guaranteed_violations"]
    assert not r["final_overcommit_violations"]
    assert r["oversubscribed_after_fill"], \
        "the fill never oversubscribed; the drill proved nothing"
    assert r["evictions"]["completed"] >= 1, \
        "pressure never triggered an eviction"
    assert r["max_window_evictions"] <= r["evict_budget"], \
        (f"eviction storm blew its budget: {r['max_window_evictions']} "
         f"> {r['evict_budget']}")
    assert not r["drift"], \
        f"cache != apiserver truth after healing: {r['drift'][:5]}"
    assert r["be_placed"] >= 1
    assert r["hi_placed"] == r["hi_pods"], \
        f"{r['hi_pods'] - r['hi_placed']} guaranteed/burstable pods " \
        "never bound"
    injected = sum(v for k, v in r["faults_applied"].items()
                   if k != "skipped")
    assert injected > 0, "the storm injected nothing; it proved nothing"
