"""Multi-host slice (gang) placement kernel (docs/designs/multihost-gang.md).

A v5e-16 is 4 hosts x (2x2) chips in one 4x4 ICI mesh; these tests pin
the slice model (host boxes tile the mesh, local<->global id mapping),
the gang selector's policy (compact shapes first; fewest hosts, then
tightest binpack), the all-or-nothing eligibility semantics, and a
policy duel showing why slice-awareness matters (the reference cannot
express any of this: its allocator stops at one node,
nodeinfo.go:312-363).
"""

import itertools

import pytest

from tpushare.core.chips import ChipView
from tpushare.core.placement import PlacementRequest
from tpushare.core.slice import (
    GangPlacement,
    HostBox,
    SliceTopology,
    fits_gang,
    select_gang,
)
from tpushare.core.topology import MeshTopology

HOSTS = ["h0", "h1", "h2", "h3"]


def v5e16() -> SliceTopology:
    return SliceTopology.from_host_grid((2, 2), (2, 2), HOSTS)


def host_views(slice_topo, used=None, unhealthy=(), hbm=16000):
    """Fresh per-host local snapshots; ``used`` maps (host, local_idx)
    -> used MiB, ``unhealthy`` is a set of (host, local_idx)."""
    used = used or {}
    views = {}
    for host, hb in slice_topo.hosts.items():
        local = MeshTopology(hb.shape)
        views[host] = [
            ChipView(i, local.coords(i), hbm,
                     used.get((host, i), 0),
                     healthy=(host, i) not in unhealthy)
            for i in range(local.num_chips)
        ]
    return views


# -- topology model ---------------------------------------------------------

def test_host_grid_construction_tiles_the_mesh():
    st = v5e16()
    assert st.mesh.shape == (4, 4)
    assert st.hosts["h0"].origin == (0, 0)
    assert st.hosts["h1"].origin == (0, 2)
    assert st.hosts["h2"].origin == (2, 0)
    assert st.hosts["h3"].origin == (2, 2)
    # every global coordinate maps to exactly one host
    owners = {st.host_of((r, c)) for r in range(4) for c in range(4)}
    assert owners == set(HOSTS)


def test_overlapping_host_boxes_rejected():
    mesh = MeshTopology((2, 2))
    with pytest.raises(ValueError, match="overlap"):
        SliceTopology(mesh, {"a": HostBox((0, 0), (2, 2)),
                             "b": HostBox((0, 0), (1, 1))})


def test_partial_tiling_rejected():
    mesh = MeshTopology((2, 2))
    with pytest.raises(ValueError, match="tile"):
        SliceTopology(mesh, {"a": HostBox((0, 0), (1, 2))})


def test_local_global_round_trip():
    st = v5e16()
    for host, hb in st.hosts.items():
        local = st.local_topology(host)
        for i in range(local.num_chips):
            g = tuple(o + c for o, c in zip(hb.origin, local.coords(i)))
            assert st.host_of(g) == host
            assert st.to_local(host, g) == local.coords(i)


# -- gang selection ---------------------------------------------------------

def test_single_host_gang_prefers_one_host():
    st = v5e16()
    # a 2x2 fits entirely inside any host box; the selector must not
    # straddle hosts when it can avoid it
    gp = select_gang(st, host_views(st), PlacementRequest(
        hbm_mib=8000, chip_count=4))
    assert gp is not None
    assert gp.box == (2, 2)
    assert gp.hosts_spanned == 1
    (host, p), = gp.per_host.items()
    assert p.chip_ids == (0, 1, 2, 3)  # the whole host box, local ids
    assert p.box == (2, 2) and p.origin == (0, 0)


def test_cross_host_gang_2x4_spans_exactly_two_hosts():
    st = v5e16()
    gp = select_gang(st, host_views(st), PlacementRequest(
        hbm_mib=8000, chip_count=8, topology=(2, 4)))
    assert gp is not None
    assert gp.hosts_spanned == 2
    # each host contributes its full 2x2 box, in local numbering
    for p in gp.per_host.values():
        assert p.box == (2, 2)
        assert p.chip_ids == (0, 1, 2, 3)


def test_full_slice_gang_takes_all_four_hosts():
    st = v5e16()
    gp = select_gang(st, host_views(st), PlacementRequest(
        hbm_mib=0, chip_count=16))  # exclusive whole-slice
    assert gp is not None
    assert gp.box == (4, 4)
    assert gp.hosts_spanned == 4
    assert sum(len(p.chip_ids) for p in gp.per_host.values()) == 16


def test_all_or_nothing_one_busy_chip_moves_the_box():
    st = v5e16()
    # h0 local chip 3 busy -> the 2x2 must land on another host
    views = host_views(st, used={("h0", 3): 16000})
    gp = select_gang(st, views, PlacementRequest(hbm_mib=16000,
                                                 chip_count=4))
    assert gp is not None
    assert gp.hosts_spanned == 1
    assert "h0" not in gp.per_host


def test_shape_degrades_like_single_host_selector():
    st = v5e16()
    # one chip busy on EVERY host (the four host-box corners at the
    # mesh's own corners + centers) blocks every 2x2 — but a fully-free
    # 1x4 row remains, and the selector degrades to it exactly like
    # select_chips_py does when the compact class is empty
    views = host_views(st, used={(h, 0): 16000 for h in HOSTS})
    gp = select_gang(st, views, PlacementRequest(
        hbm_mib=16000, chip_count=4))
    assert gp is not None
    assert gp.box in ((1, 4), (4, 1))


def test_all_or_nothing_no_fit_returns_none():
    st = v5e16()
    # pinned 2x2 (a sub-slice job): one busy chip per host kills every
    # 2x2 position on the 4x4 mesh -> all-or-nothing refusal
    views = host_views(st, used={(h, 0): 16000 for h in HOSTS})
    req = PlacementRequest(hbm_mib=16000, chip_count=4, topology=(2, 2))
    assert select_gang(st, views, req) is None
    assert not fits_gang(st, views, req)


def test_unhealthy_chip_blocks_its_boxes():
    st = v5e16()
    # a single unhealthy chip: no returned placement may contain it
    views = host_views(st, unhealthy={("h0", 0)})
    gp = select_gang(st, views, PlacementRequest(
        hbm_mib=1000, chip_count=4, topology=(2, 2)))
    assert gp is not None
    assert "h0" not in gp.per_host or 0 not in gp.per_host["h0"].chip_ids
    # and a slice with every chip unhealthy places nothing
    all_sick = host_views(st, unhealthy={(h, i)
                                         for h in HOSTS for i in range(4)})
    assert select_gang(st, all_sick, PlacementRequest(
        hbm_mib=1000, chip_count=4)) is None


def test_missing_host_snapshot_degrades_not_crashes():
    st = v5e16()
    views = host_views(st)
    del views["h3"]  # host down / unreported
    gp = select_gang(st, views, PlacementRequest(hbm_mib=8000,
                                                 chip_count=4))
    assert gp is not None and "h3" not in gp.per_host
    # a gang that NEEDS the missing host cannot place
    assert select_gang(st, views, PlacementRequest(
        hbm_mib=8000, chip_count=16, topology=(4, 4))) is None


def test_binpack_tie_break_prefers_tighter_host():
    st = v5e16()
    # h1 already carries co-tenants (but still fits): tighter leftover
    views = host_views(st, used={("h1", i): 8000 for i in range(4)})
    gp = select_gang(st, views, PlacementRequest(hbm_mib=4000,
                                                 chip_count=4))
    assert gp is not None
    assert list(gp.per_host) == ["h1"]


def test_sharing_gang_respects_per_chip_hbm():
    st = v5e16()
    views = host_views(st, used={("h0", i): 10000 for i in range(4)})
    # 8000 per chip no longer fits h0's chips (6000 free), must move
    gp = select_gang(st, views, PlacementRequest(hbm_mib=8000,
                                                 chip_count=4))
    assert gp is not None and "h0" not in gp.per_host


def test_scatter_rejected_for_gangs():
    st = v5e16()
    with pytest.raises(ValueError, match="scatter"):
        select_gang(st, host_views(st), PlacementRequest(
            hbm_mib=1000, chip_count=4, allow_scatter=True))


def test_v5p_3d_slice_gang():
    # 2x2x1 hosts of 2x2x4 chips -> 4x4x4 mesh (v5p-style 3-D)
    st = SliceTopology.from_host_grid((2, 2, 1), (2, 2, 4),
                                      ["a", "b", "c", "d"])
    assert st.mesh.shape == (4, 4, 4)
    gp = select_gang(st, host_views(st), PlacementRequest(
        hbm_mib=8000, chip_count=8))
    assert gp is not None
    assert gp.box in ((2, 2, 2), (1, 2, 4), (2, 1, 4), (2, 2, 2))
    # compactness-first: 2x2x2 is the most compact 8-chip box
    assert gp.box == (2, 2, 2)


def test_selector_matches_brute_force_on_random_states():
    # property check: the selector's (hosts, leftover, origin) minimum
    # equals exhaustive search over all eligible boxes of the winning
    # shape class
    import random
    rng = random.Random(7)
    st = v5e16()
    req = PlacementRequest(hbm_mib=6000, chip_count=4)
    for _ in range(40):
        used = {(h, i): rng.choice((0, 4000, 12000, 16000))
                for h in HOSTS for i in range(4)}
        views = host_views(st, used=used)
        got = select_gang(st, views, req)
        merged = st.global_view(views)
        # brute force over ALL shapes/positions
        best = None
        for box in st.mesh.box_shapes(4):
            found_in_class = False
            for origin in st.mesh.box_positions(box):
                coords = list(itertools.product(
                    *[range(o, o + b) for o, b in zip(origin, box)]))
                views_in = [merged[c] for c in coords]
                if any(v.free_hbm_mib < 6000 or not v.healthy
                       for v in views_in):
                    continue
                found_in_class = True
                hosts = {st.host_of(c) for c in coords}
                score = sum(v.free_hbm_mib - 6000 for v in views_in)
                key = (len(hosts), score, origin)
                if best is None or key < best[0]:
                    best = (key, box, origin)
            if found_in_class:
                break  # same compactness-first class policy
        if best is None:
            assert got is None
        else:
            assert got is not None
            assert (got.hosts_spanned, got.score, got.origin) == best[0]


# -- the policy payoff ------------------------------------------------------

def _place_single(st, views, host_order, spread: bool):
    """Place one 8000-MiB single-chip tenant host-locally: 'spread'
    mimics least-allocated scoring (reference default-scheduler
    behavior); packed uses min-free-that-fits on the slice."""
    cands = []
    for hi, host in enumerate(host_order):
        for v in views[host]:
            if v.free_hbm_mib >= 8000:
                cands.append((hi, host, v))
    if not cands:
        return None
    if spread:
        # least-allocated, host-rotating tie-break (k8s default-scheduler
        # spreading behavior): equal-free chips alternate hosts
        hi, host, v = max(cands, key=lambda hv: (hv[2].free_hbm_mib,
                                                 -hv[2].idx))
    else:
        # min-free-that-fits, same-host-first (the slice-aware binpack)
        hi, host, v = min(cands, key=lambda hv: (hv[2].free_hbm_mib,
                                                 hv[0], hv[2].idx))
    views[host] = [c if c.idx != v.idx else
                   c.with_used(c.used_hbm_mib + 8000)
                   for c in views[host]]
    return host


def test_policy_duel_gang_aware_beats_host_local():
    st = v5e16()
    results = {}
    for policy in ("spread", "pack"):
        views = host_views(st)
        placed = 0
        for _ in range(6):  # six single-chip co-tenants arrive first
            if _place_single(st, views, HOSTS, spread=(policy == "spread")):
                placed += 1
        assert placed == 6
        gangs = 0
        while True:  # then 2x2 whole-chip gangs until the slice is full
            gp = select_gang(st, views, PlacementRequest(
                hbm_mib=0, chip_count=4, topology=(2, 2)))
            if gp is None:
                break
            for host, p in gp.per_host.items():
                taken = set(p.chip_ids)
                views[host] = [c if c.idx not in taken else
                               c.with_used(c.total_hbm_mib)
                               for c in views[host]]
            gangs += 1
        results[policy] = gangs
    # spreading scatters 6 tenants over 6+ chips across all hosts and
    # strands the slice for whole-chip gangs; packing doubles them up
    # onto 3 chips and keeps clean 2x2 boxes available
    assert results["pack"] > results["spread"], results
    assert results["spread"] == 0
    assert results["pack"] >= 2


# -- discrete-event slice sim (docs/designs/multihost-gang.md "payoff") -----

def test_slice_sim_pack_beats_spread_on_aggregate():
    from tpushare.sim.simulator import run_slice_sim, synth_slice_trace

    agg = {"spread": [0.0, 0.0], "pack": [0.0, 0.0]}  # [wait, util]
    for seed in range(8):
        trace = synth_slice_trace(n_pods=150, seed=seed, arrival_rate=1.0)
        for policy in agg:
            r = run_slice_sim(trace, policy)
            # every gang eventually places (departures retry the queue)
            assert r["never_placed"] == 0
            agg[policy][0] += r["gang_mean_wait"]
            agg[policy][1] += r["util_pct"]
    # slice-aware packing strictly wins the aggregate on BOTH axes:
    # gangs wait less and the slice runs fuller
    assert agg["pack"][0] < agg["spread"][0], agg
    assert agg["pack"][1] > agg["spread"][1], agg


def test_slice_sim_cross_host_gangs_actually_place():
    from tpushare.sim.simulator import run_slice_sim, synth_slice_trace

    trace = synth_slice_trace(n_pods=80, seed=1)
    r = run_slice_sim(trace, "pack")
    # the trace contains 2x4 gangs, which cannot fit any single 2x2
    # host — admission of ALL gangs proves cross-host placement works
    assert r["gangs_total"] > 0
    assert r["gang_admission_pct"] == 100.0
