"""Mesh topology unit tests (tpushare/core/topology.py)."""

import pytest

from tpushare.core.topology import MeshTopology


def test_coords_index_roundtrip_2d():
    t = MeshTopology((4, 4))
    assert t.num_chips == 16
    for i in range(16):
        assert t.index(t.coords(i)) == i
    assert t.coords(0) == (0, 0)
    assert t.coords(1) == (0, 1)  # last axis fastest (row-major)
    assert t.coords(4) == (1, 0)


def test_coords_index_roundtrip_3d():
    t = MeshTopology((2, 2, 2))
    for i in range(8):
        assert t.index(t.coords(i)) == i


def test_invalid_shapes():
    with pytest.raises(ValueError):
        MeshTopology(())
    with pytest.raises(ValueError):
        MeshTopology((4, 0))
    with pytest.raises(IndexError):
        MeshTopology((2, 2)).coords(4)
    with pytest.raises(IndexError):
        MeshTopology((2, 2)).index((2, 0))


def test_box_shapes_compact_first():
    t = MeshTopology((4, 4))
    shapes = t.box_shapes(4)
    assert shapes[0] == (2, 2)  # square beats 1x4/4x1
    assert set(shapes) == {(2, 2), (1, 4), (4, 1)}
    assert t.box_shapes(16) == [(4, 4)]
    # count that doesn't fit any box
    assert t.box_shapes(32) == []


def test_box_shapes_3d():
    t = MeshTopology((2, 2, 4))
    shapes = t.box_shapes(8)
    assert shapes[0] == (2, 2, 2)
    assert (1, 2, 4) in shapes


def test_box_positions_and_chips():
    t = MeshTopology((4, 4))
    pos = t.box_positions((2, 2))
    assert len(pos) == 9  # 3x3 origins
    chips = t.box_chips((1, 1), (2, 2))
    assert chips == [t.index((1, 1)), t.index((1, 2)),
                     t.index((2, 1)), t.index((2, 2))]


def test_neighbors_mesh_edges():
    t = MeshTopology((4, 4))
    corner = t.index((0, 0))
    assert sorted(t.neighbors(corner)) == sorted(
        [t.index((0, 1)), t.index((1, 0))])
    middle = t.index((1, 1))
    assert len(t.neighbors(middle)) == 4


def test_from_label_and_back():
    assert MeshTopology.from_label("4x4").shape == (4, 4)
    assert MeshTopology.from_label("2x2x4").shape == (2, 2, 4)
    assert MeshTopology((2, 4)).label() == "2x4"
    with pytest.raises(ValueError):
        MeshTopology.from_label("fourbyfour")


def test_for_chip_count_default_shapes():
    assert MeshTopology.for_chip_count(16).shape == (4, 4)
    assert MeshTopology.for_chip_count(8).shape == (2, 4)
    assert MeshTopology.for_chip_count(4).shape == (2, 2)
    assert MeshTopology.for_chip_count(1).shape == (1,)
    assert MeshTopology.for_chip_count(7).shape == (7,)  # prime -> 1-D
