"""Mesh topology unit tests (tpushare/core/topology.py)."""

import pytest

from tpushare.core.topology import MeshTopology


def test_coords_index_roundtrip_2d():
    t = MeshTopology((4, 4))
    assert t.num_chips == 16
    for i in range(16):
        assert t.index(t.coords(i)) == i
    assert t.coords(0) == (0, 0)
    assert t.coords(1) == (0, 1)  # last axis fastest (row-major)
    assert t.coords(4) == (1, 0)


def test_coords_index_roundtrip_3d():
    t = MeshTopology((2, 2, 2))
    for i in range(8):
        assert t.index(t.coords(i)) == i


def test_invalid_shapes():
    with pytest.raises(ValueError):
        MeshTopology(())
    with pytest.raises(ValueError):
        MeshTopology((4, 0))
    with pytest.raises(IndexError):
        MeshTopology((2, 2)).coords(4)
    with pytest.raises(IndexError):
        MeshTopology((2, 2)).index((2, 0))


def test_box_shapes_compact_first():
    t = MeshTopology((4, 4))
    shapes = t.box_shapes(4)
    assert shapes[0] == (2, 2)  # square beats 1x4/4x1
    assert set(shapes) == {(2, 2), (1, 4), (4, 1)}
    assert t.box_shapes(16) == [(4, 4)]
    # count that doesn't fit any box
    assert t.box_shapes(32) == []


def test_box_shapes_3d():
    t = MeshTopology((2, 2, 4))
    shapes = t.box_shapes(8)
    assert shapes[0] == (2, 2, 2)
    assert (1, 2, 4) in shapes


def test_box_positions_and_chips():
    t = MeshTopology((4, 4))
    pos = t.box_positions((2, 2))
    assert len(pos) == 9  # 3x3 origins
    chips = t.box_chips((1, 1), (2, 2))
    assert chips == [t.index((1, 1)), t.index((1, 2)),
                     t.index((2, 1)), t.index((2, 2))]


def test_neighbors_mesh_edges():
    t = MeshTopology((4, 4))
    corner = t.index((0, 0))
    assert sorted(t.neighbors(corner)) == sorted(
        [t.index((0, 1)), t.index((1, 0))])
    middle = t.index((1, 1))
    assert len(t.neighbors(middle)) == 4


def test_from_label_and_back():
    assert MeshTopology.from_label("4x4").shape == (4, 4)
    assert MeshTopology.from_label("2x2x4").shape == (2, 2, 4)
    assert MeshTopology((2, 4)).label() == "2x4"
    with pytest.raises(ValueError):
        MeshTopology.from_label("fourbyfour")


def test_for_chip_count_default_shapes():
    assert MeshTopology.for_chip_count(16).shape == (4, 4)
    assert MeshTopology.for_chip_count(8).shape == (2, 4)
    assert MeshTopology.for_chip_count(4).shape == (2, 2)
    assert MeshTopology.for_chip_count(1).shape == (1,)
    assert MeshTopology.for_chip_count(7).shape == (7,)  # prime -> 1-D


# -- HostMesh: the inter-node adjacency model (ABI v5 gang solve) ----------


def _hm(grid, hbox=(2, 2)):
    from tpushare.core.topology import HostMesh
    n = 1
    for d in grid:
        n *= d
    return HostMesh(grid, hbox, tuple(f"h{i}" for i in range(n)))


def test_host_mesh_ordering_matches_slice_topology():
    """HostMesh.hosts is row-major over the host grid — the SAME order
    SliceTopology.from_host_grid assigns tile origins, so host-level
    and chip-level coordinates compose without translation."""
    from tpushare.core.slice import SliceTopology

    hm = _hm((2, 3))
    st = SliceTopology.from_host_grid((2, 3), (2, 2), list(hm.hosts))
    for name in hm.hosts:
        assert hm.chip_origin(name) == st.hosts[name].origin


def test_host_mesh_from_layout_roundtrip():
    from tpushare.core.topology import HostMesh

    layout = {
        "a": ((0, 0), (2, 2)), "b": ((0, 2), (2, 2)),
        "c": ((2, 0), (2, 2)), "d": ((2, 2), (2, 2)),
    }
    hm = HostMesh.from_layout(layout)
    assert hm.grid == (2, 2)
    assert hm.hbox == (2, 2)
    assert hm.hosts == ("a", "b", "c", "d")


@pytest.mark.parametrize("layout,why", [
    ({}, "empty"),
    ({"a": ((0, 0), (2, 2)), "b": ((0, 2), (1, 4))}, "non-uniform boxes"),
    ({"a": ((0, 0), (2, 2)), "b": ((0, 1), (2, 2))}, "unaligned origin"),
    ({"a": ((0, 0), (2, 2)), "b": ((0, 0), (2, 2))},
     "duplicate origin"),
    ({"a": ((0, 0), (2, 2)), "b": ((2, 2), (2, 2))},
     "hole at (0,2)/(2,0)"),
])
def test_host_mesh_from_layout_rejects_bad_tilings(layout, why):
    from tpushare.core.topology import HostMesh

    with pytest.raises(ValueError):
        HostMesh.from_layout(layout)


def _brute_best_box(grid, weights):
    """Reference enumeration for best_eligible_box: every shape x
    position x cell (the pre-v5 implementation, O(hosts^3))."""
    import itertools

    from tpushare.core.topology import MeshTopology

    gm = MeshTopology(grid)
    best = 0
    for shape in itertools.product(*[range(1, d + 1) for d in grid]):
        for origin in gm.box_positions(shape):
            total = 0
            for c in itertools.product(
                    *[range(o, o + s) for o, s in zip(origin, shape)]):
                w = weights[gm.index(c)]
                if w <= 0:
                    total = -1
                    break
                total += w
            best = max(best, total)
    return best


def test_best_eligible_box_matches_brute_force_2d():
    """The O(hosts) maximal-rectangle fast path must be exactly the
    shapes x positions enumeration it replaced."""
    import random

    rng = random.Random(13)
    for _ in range(300):
        grid = (rng.randint(1, 6), rng.randint(1, 6))
        hm = _hm(grid)
        weights = [rng.choice([0, 0, 1, 2, 4]) for _ in hm.hosts]
        by_host = dict(zip(hm.hosts, weights))
        assert hm.best_eligible_box(by_host.__getitem__) == \
            _brute_best_box(grid, weights), (grid, weights)


def test_best_eligible_box_3d_fallback():
    """Non-2-d grids keep the enumeration path."""
    import random

    from tpushare.core.topology import HostMesh

    rng = random.Random(29)
    grid = (2, 2, 3)
    hm = HostMesh(grid, (1, 2, 2), tuple(f"h{i}" for i in range(12)))
    for _ in range(50):
        weights = [rng.choice([0, 1, 4]) for _ in hm.hosts]
        by_host = dict(zip(hm.hosts, weights))
        assert hm.best_eligible_box(by_host.__getitem__) == \
            _brute_best_box(grid, weights), weights


def test_best_eligible_box_zero_and_full():
    hm = _hm((2, 4))
    assert hm.best_eligible_box(lambda h: 0) == 0
    assert hm.best_eligible_box(lambda h: 4) == 32  # whole grid
