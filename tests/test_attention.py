"""Pallas flash-attention kernel parity tests (interpret mode on CPU)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpushare.workloads.attention import (
    attention_reference, flash_attention)
from tpushare.workloads.model import PRESETS, forward, init_params


def rand_qkv(key, B=2, H=4, S=128, D=64, dtype=jnp.bfloat16):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), dtype)
    k = jax.random.normal(kk, (B, H, S, D), dtype)
    v = jax.random.normal(kv, (B, H, S, D), dtype)
    return q, k, v


def assert_close(a, b, atol=2e-2):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        atol=atol, rtol=2e-2)


@pytest.mark.tpu_kernel
def test_flash_matches_reference_causal():
    q, k, v = rand_qkv(jax.random.key(0))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, causal=True)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    assert_close(out, ref)


@pytest.mark.tpu_kernel
def test_flash_matches_reference_multiblock():
    # 3 query blocks -> exercises the online-softmax recurrence across
    # blocks, not just the single-block degenerate case
    q, k, v = rand_qkv(jax.random.key(1), S=384)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert_close(out, attention_reference(q, k, v, causal=True))


@pytest.mark.tpu_kernel
def test_flash_handles_unaligned_seq():
    # S=100 pads to 128: padded keys must be masked, padded queries dropped
    q, k, v = rand_qkv(jax.random.key(2), S=100)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.shape == q.shape
    assert_close(out, attention_reference(q, k, v, causal=True))


@pytest.mark.tpu_kernel
def test_flash_non_causal():
    q, k, v = rand_qkv(jax.random.key(3), S=160)
    out = flash_attention(q, k, v, causal=False, interpret=True)
    assert_close(out, attention_reference(q, k, v, causal=False))


def test_flash_rejects_bad_shapes():
    q, k, v = rand_qkv(jax.random.key(4), D=64)
    big = jnp.repeat(q, 4, axis=-1)  # D=256
    with pytest.raises(ValueError, match="head_dim"):
        flash_attention(big, jnp.repeat(k, 4, -1), jnp.repeat(v, 4, -1))
    with pytest.raises(ValueError, match="matching q/k"):
        flash_attention(q, k[:, :, :64], v[:, :, :64], causal=True)
    with pytest.raises(ValueError, match="must share"):
        flash_attention(q, k[..., :32], v[..., :32])  # head_dim mismatch


@pytest.mark.tpu_kernel
def test_flash_grads_match_reference():
    # custom VJP (blockwise backward from the LSE residual) vs autodiff
    # through the einsum reference, fp32 so tolerances are tight
    q, k, v = rand_qkv(jax.random.key(7), S=200, dtype=jnp.float32)
    f = lambda q, k, v: jnp.sum(jnp.sin(
        flash_attention(q, k, v, causal=True, interpret=True)))
    g = lambda q, k, v: jnp.sum(jnp.sin(
        attention_reference(q, k, v, causal=True)))
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.tpu_kernel
def test_flash_grads_non_causal_unaligned():
    q, k, v = rand_qkv(jax.random.key(8), S=100, dtype=jnp.float32)
    f = lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=False, interpret=True) ** 2)
    g = lambda q, k, v: jnp.sum(
        attention_reference(q, k, v, causal=False) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def _pallas_bwd_vs_autodiff(S, causal, dtype=jnp.float32, bq=None, bk=None,
                            key=9, tol=2e-4):
    """The hand-written Pallas backward kernels (the compiled-TPU path,
    normally unreachable in interpret mode) vs einsum autodiff."""
    from tpushare.workloads.attention import _flash_bwd_pallas, _flash_call

    q, k, v = rand_qkv(jax.random.key(key), S=S, dtype=dtype)
    do = jax.random.normal(jax.random.key(key + 1), q.shape, dtype)
    _, ref_vjp = jax.vjp(
        lambda q, k, v: attention_reference(q, k, v, causal), q, k, v)
    ref = ref_vjp(do)
    out, lse = _flash_call(q, k, v, causal, True, bq, bk)
    got = _flash_bwd_pallas(q, k, v, out, lse, do, causal, interpret=True,
                            block_q=bq, block_kv=bk)
    for name, a, b in zip(("dq", "dk", "dv"), got, ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=tol, rtol=tol, err_msg=f"{name} S={S} causal={causal}")


@pytest.mark.tpu_kernel
def test_pallas_backward_causal():
    _pallas_bwd_vs_autodiff(S=256, causal=True)


@pytest.mark.tpu_kernel
def test_pallas_backward_non_causal():
    _pallas_bwd_vs_autodiff(S=256, causal=False)


@pytest.mark.tpu_kernel
def test_pallas_backward_ragged_padding():
    # S=300 pads to 384: padded-query lanes must self-zero in dk/dv (the
    # +1e30 lse clamp) and padded-key rows are sliced — both kernels'
    # padding reasoning is load-bearing here
    _pallas_bwd_vs_autodiff(S=300, causal=True)
    _pallas_bwd_vs_autodiff(S=300, causal=False)


@pytest.mark.tpu_kernel
def test_pallas_backward_unequal_tiles():
    # block_q != block_kv exercises i_start/last diagonal arithmetic in
    # both grid orders
    _pallas_bwd_vs_autodiff(S=512, causal=True, bq=128, bk=256)
    _pallas_bwd_vs_autodiff(S=512, causal=True, bq=256, bk=128)


@pytest.mark.tpu_kernel
def test_pallas_backward_bf16():
    _pallas_bwd_vs_autodiff(S=384, causal=True, dtype=jnp.bfloat16,
                            tol=6e-2)


@pytest.mark.tpu_kernel
def test_train_step_with_flash_config():
    from tpushare.workloads.model import make_train_step
    cfg = dataclasses.replace(PRESETS["llama-tiny"], attn="flash")
    params = init_params(cfg, jax.random.key(9))
    tx, step = make_train_step(cfg)
    tokens = jax.random.randint(jax.random.key(10), (2, 16), 0, cfg.vocab)
    params, opt, loss = jax.jit(step)(params, tx.init(params), tokens)
    assert jnp.isfinite(loss)


@pytest.mark.tpu_kernel
def test_model_forward_flash_matches_einsum():
    cfg = PRESETS["llama-tiny"]
    params = init_params(cfg, jax.random.key(5))
    tokens = jax.random.randint(jax.random.key(6), (2, 48), 0, cfg.vocab)
    ref = forward(params, tokens, cfg)
    flash_cfg = dataclasses.replace(cfg, attn="flash")
    out = forward(params, tokens, flash_cfg)
    # same weights, same tokens: top-1 predictions should agree nearly
    # everywhere despite bf16 accumulation-order differences
    agree = (jnp.argmax(ref, -1) == jnp.argmax(out, -1)).mean()
    assert float(agree) >= 0.95


@pytest.mark.tpu_kernel
def test_flash_gqa_matches_expanded_reference():
    """GQA-native call (small kv heads) == reference on expanded heads."""
    B, H, Hkv, S, D = 2, 8, 2, 192, 32
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, Hkv, S, D), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    g = H // Hkv
    ref = attention_reference(q, jnp.repeat(k, g, axis=1),
                              jnp.repeat(v, g, axis=1), causal=True)
    assert out.shape == (B, H, S, D)
    assert_close(out, ref)


@pytest.mark.tpu_kernel
def test_flash_gqa_backward_matches_expanded_autodiff():
    B, H, Hkv, S, D = 1, 4, 2, 128, 16
    kq, kk, kv = jax.random.split(jax.random.key(8), 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(kv, (B, Hkv, S, D), jnp.float32)
    w = jax.random.normal(jax.random.key(9), (B, H, S, D), jnp.float32)
    g = H // Hkv

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(
            q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1),
            causal=True) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3,
                                   err_msg=f"d{name}")


def test_flash_rejects_nondividing_kv_heads():
    q, k, v = rand_qkv(jax.random.key(10), H=6)
    with pytest.raises(ValueError, match="kv heads dividing"):
        flash_attention(q, k[:, :4], v[:, :4], interpret=True)


@pytest.mark.tpu_kernel
def test_window_attention_matches_reference():
    # sliding window: multi-block S with a window smaller than, equal to,
    # and non-aligned with the block size
    for S, W in ((384, 128), (384, 100), (256, 1), (512, 512)):
        q, k, v = rand_qkv(jax.random.key(40), S=S, dtype=jnp.float32)
        out = flash_attention(q, k, v, causal=True, window=W,
                              interpret=True)
        ref = attention_reference(q, k, v, causal=True, window=W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"S={S} W={W}")


@pytest.mark.tpu_kernel
def test_window_floor_skip_and_relocated_init():
    # geometry chosen so j_start > 0: bq=256, bk=128, S=640, W=300 ->
    # q block i=2 (rows 512..639) has floor 512-299=213 -> j_start=1.
    # An off-by-one in j_start (skipping a visible block, or stale
    # m/l/acc because _init never fired) fails parity here
    q, k, v = rand_qkv(jax.random.key(44), S=640, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=True, window=300,
                          interpret=True, block_q=256, block_kv=128)
    ref = attention_reference(q, k, v, causal=True, window=300)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.tpu_kernel
def test_window_attention_ragged_and_unequal_tiles():
    q, k, v = rand_qkv(jax.random.key(41), S=300, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=True, window=77, interpret=True,
                          block_q=128, block_kv=256)
    ref = attention_reference(q, k, v, causal=True, window=77)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.tpu_kernel
def test_window_attention_grads():
    q, k, v = rand_qkv(jax.random.key(42), S=300, dtype=jnp.float32)
    f = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
        q, k, v, causal=True, window=77, interpret=True)))
    g = lambda q, k, v: jnp.sum(jnp.sin(attention_reference(
        q, k, v, causal=True, window=77)))
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_window_requires_causal_and_positive():
    q, k, v = rand_qkv(jax.random.key(43))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=64, interpret=True)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=True, window=0, interpret=True)


@pytest.mark.tpu_kernel
def test_pallas_backward_gqa_grouped_grid():
    """The dkdv kernel's grouped (B, H_kv, j, i, g) grid vs autodiff on
    expanded heads — GQA gradients sum per group IN the grid, no K/V
    expansion."""
    from tpushare.workloads.attention import _flash_bwd_pallas, _flash_call

    for B, H, Hkv, S, causal in ((1, 4, 2, 256, True), (1, 4, 1, 256, False),
                                 (2, 8, 2, 300, True)):
        ks = jax.random.split(jax.random.key(70 + H + S), 4)
        q = jax.random.normal(ks[0], (B, H, S, 32), jnp.float32)
        k = jax.random.normal(ks[1], (B, Hkv, S, 32), jnp.float32)
        v = jax.random.normal(ks[2], (B, Hkv, S, 32), jnp.float32)
        do = jax.random.normal(ks[3], (B, H, S, 32), jnp.float32)
        g = H // Hkv

        def ref_fn(q, k, v):
            return attention_reference(q, jnp.repeat(k, g, 1),
                                       jnp.repeat(v, g, 1), causal)

        _, ref_vjp = jax.vjp(ref_fn, q, k, v)
        ref = ref_vjp(do)
        out, lse = _flash_call(q, k, v, causal, True, None, None)
        got = _flash_bwd_pallas(q, k, v, out, lse, do, causal,
                                interpret=True)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
                err_msg=f"{name} H{H}/{Hkv} S{S} causal={causal}")


@pytest.mark.tpu_kernel
def test_pallas_backward_windowed():
    """Window support in BOTH backward grid orders: the dq kernel's
    relocated init/floor skip (j_start > 0 at bq=256/bk=128/W=300) and
    the dkdv kernel's upper-i visibility cut (bq=128/bk=256/W=100),
    against autodiff of the windowed reference."""
    from tpushare.workloads.attention import _flash_bwd_pallas, _flash_call

    for S, W, bq, bk in ((384, 100, 128, 128), (640, 300, 256, 128),
                         (640, 100, 128, 256), (300, 77, None, None)):
        ks = jax.random.split(jax.random.key(80 + S + W), 4)
        q = jax.random.normal(ks[0], (1, 4, S, 32), jnp.float32)
        k = jax.random.normal(ks[1], (1, 2, S, 32), jnp.float32)
        v = jax.random.normal(ks[2], (1, 2, S, 32), jnp.float32)
        do = jax.random.normal(ks[3], (1, 4, S, 32), jnp.float32)

        def ref_fn(q, k, v, W=W):
            return attention_reference(q, jnp.repeat(k, 2, 1),
                                       jnp.repeat(v, 2, 1), True, window=W)

        _, ref_vjp = jax.vjp(ref_fn, q, k, v)
        ref = ref_vjp(do)
        out, lse = _flash_call(q, k, v, True, True, bq, bk, window=W)
        got = _flash_bwd_pallas(q, k, v, out, lse, do, True,
                                interpret=True, block_q=bq, block_kv=bk,
                                window=W)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
                err_msg=f"{name} S={S} W={W} bq={bq} bk={bk}")


# -- pipelined forward (VPU/MXU overlap, VERDICT r3 item 4) -----------------
# The pipelined kernel must be BIT-IDENTICAL to the step kernel in
# interpret mode: same operations on the same values in the same
# online-softmax order — only issue order differs (compute of block j
# overlaps consume of block j-1 through the double-buffered scratch).

def _pipe_vs_step(S, causal=True, window=None, dtype=jnp.float32,
                  Hkv=2, D=64, bq=128, bk=128):
    kq, kk, kv2 = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(kq, (1, 4, S, D), dtype)
    k = jax.random.normal(kk, (1, Hkv, S, D), dtype)
    v = jax.random.normal(kv2, (1, Hkv, S, D), dtype)
    a = flash_attention(q, k, v, causal=causal, window=window,
                        block_q=bq, block_kv=bk, fwd_impl="step")
    b = flash_attention(q, k, v, causal=causal, window=window,
                        block_q=bq, block_kv=bk, fwd_impl="pipelined")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.tpu_kernel
def test_pipelined_bit_identical_causal():
    _pipe_vs_step(S=256)


@pytest.mark.tpu_kernel
def test_pipelined_bit_identical_non_causal():
    _pipe_vs_step(S=256, causal=False)


@pytest.mark.tpu_kernel
def test_pipelined_bit_identical_ragged_bf16():
    _pipe_vs_step(S=300, dtype=jnp.bfloat16)


@pytest.mark.tpu_kernel
def test_pipelined_bit_identical_windowed():
    # window floor > 0 exercises the shifted j_start/init interplay
    _pipe_vs_step(S=384, window=96)


@pytest.mark.tpu_kernel
def test_pipelined_bit_identical_unequal_tiles():
    _pipe_vs_step(S=384, bq=256, bk=128)
    _pipe_vs_step(S=384, bq=128, bk=256)


@pytest.mark.tpu_kernel
def test_pipelined_gqa_single_kv_head():
    _pipe_vs_step(S=256, Hkv=1)


@pytest.mark.tpu_kernel
def test_pipelined_grads_route_through_same_vjp():
    # the forward variant only changes the primal kernel; the custom
    # VJP (lse residual) must serve both identically
    q, k, v = rand_qkv(jax.random.key(10), 1, 2, 256, 64, jnp.float32)
    w = jax.random.normal(jax.random.key(11), q.shape, jnp.float32)

    def loss(impl):
        return lambda q: jnp.sum(flash_attention(
            q, k, v, causal=True, fwd_impl=impl) * w)

    ga = jax.grad(loss("step"))(q)
    gb = jax.grad(loss("pipelined"))(q)
    np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))


@pytest.mark.tpu_kernel
def test_fwd_impl_env_and_validation(monkeypatch):
    from tpushare.workloads.attention import _resolve_flash_fwd
    q, k, v = rand_qkv(jax.random.key(12), 1, 2, 128, 64, jnp.float32)
    with pytest.raises(ValueError, match="fwd_impl"):
        flash_attention(q, k, v, fwd_impl="warp")
    # env is honored (output equality can't see this — the variants are
    # bit-identical by design — so assert the resolution itself)
    monkeypatch.setenv("TPUSHARE_FLASH_FWD", "pipelined")
    assert _resolve_flash_fwd(None) == "pipelined"
    out = flash_attention(q, k, v, causal=True)
    ref = flash_attention(q, k, v, causal=True, fwd_impl="step")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    monkeypatch.setenv("TPUSHARE_FLASH_FWD", "hexagonal")
    with pytest.raises(ValueError, match="TPUSHARE_FLASH_FWD"):
        _resolve_flash_fwd(None)
