"""ViT encoder family (workloads/vit.py).

Key claims under test: the patch embedding written as reshape+matmul is
EXACTLY the stride-p conv (proved against lax.conv_general_dilated),
the flash kernel's non-causal path drops in for the einsum attention,
and the megatron tp sharding computes the same logits as the unsharded
forward on a dp x tp mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import pytest

from tpushare.workloads.vit import (
    PRESETS_VIT, ViTConfig, init_vit_params, make_vit_train_step,
    patchify, vit_forward, vit_param_specs)

CFG = PRESETS_VIT["vit-tiny"].validate()
PARAMS = init_vit_params(CFG, jax.random.key(0))
IMAGES = jax.random.normal(jax.random.key(1), (2, 32, 32, 3),
                           jnp.float32)


def test_forward_shape_and_finiteness():
    logits = jax.jit(lambda p, x: vit_forward(p, x, CFG))(PARAMS, IMAGES)
    assert logits.shape == (2, CFG.classes)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_patch_embed_is_exactly_the_strided_conv():
    # reshape+matmul == lax.conv_general_dilated with the same weights
    # laid out as a [p, p, C, d] kernel and stride p — the claim that
    # lets the patch embed hit the MXU as one matmul
    p, d = CFG.patch, CFG.d_model
    x = IMAGES.astype(CFG.dtype)
    via_matmul = patchify(x, CFG) @ PARAMS["patch_embed"]
    kernel = PARAMS["patch_embed"].reshape(p, p, CFG.channels, d)
    via_conv = jax.lax.conv_general_dilated(
        x, kernel, window_strides=(p, p), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    via_conv = via_conv.reshape(x.shape[0], -1, d)
    np.testing.assert_allclose(np.asarray(via_matmul, np.float32),
                               np.asarray(via_conv, np.float32),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.tpu_kernel
def test_flash_attention_drop_in():
    import dataclasses
    cfg_f = dataclasses.replace(CFG, attn="flash").validate()
    le = jax.jit(lambda p, x: vit_forward(p, x, CFG))(PARAMS, IMAGES)
    lf = jax.jit(lambda p, x: vit_forward(p, x, cfg_f))(PARAMS, IMAGES)
    # S=17 (16 patches + CLS): ragged, non-causal — the kernel's padded
    # lanes and full-visibility path both in play
    np.testing.assert_allclose(np.asarray(le), np.asarray(lf),
                               atol=5e-2, rtol=5e-2)


def test_train_step_overfits_a_tiny_batch():
    labels = jnp.array([3, 7], jnp.int32)
    tx, train_step = make_vit_train_step(CFG, learning_rate=3e-3)
    params = init_vit_params(CFG, jax.random.key(2))
    opt = tx.init(params)
    step = jax.jit(train_step)
    first = None
    for _ in range(8):
        params, opt, loss = step(params, opt, IMAGES, labels)
        first = float(loss) if first is None else first
    assert bool(jnp.isfinite(loss))
    assert float(loss) < first  # learning, not just running


def test_dp_tp_sharded_forward_matches_unsharded():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
    p_sh = jax.tree.map(lambda s: NamedSharding(mesh, s),
                        vit_param_specs(CFG),
                        is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(PARAMS, p_sh)
    images = jax.device_put(IMAGES,
                            NamedSharding(mesh, P("dp", None, None,
                                                  None)))
    sharded = jax.jit(lambda p, x: vit_forward(p, x, CFG))(params,
                                                           images)
    plain = jax.jit(lambda p, x: vit_forward(p, x, CFG))(PARAMS, IMAGES)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(plain),
                               atol=3e-2, rtol=3e-2)


def test_config_validation_and_geometry():
    assert CFG.n_patches == 16 and CFG.seq == 17
    b16 = PRESETS_VIT["vit-b16"]
    assert b16.n_patches == 196 and b16.seq == 197
    import pytest
    with pytest.raises(AssertionError):
        ViTConfig(image=30, patch=8).validate()
