"""Owner-forwarding tests (ha/forward.py + the server routing hook).

Three layers:

1. two complete in-process replica stacks over one FakeCluster, with the
   peer address book cross-wired the way the lease listing would build
   it — the happy path (a bind landing off-owner hops once and the owner
   binds lock-free), the mid-rebalance ownership disagreement (the loop
   guard stops a second hop and the bind degrades to the claim CAS), and
   the dead-peer path (transport failure -> per-peer breaker -> local
   CAS, never a lost bind);
2. router-level decision checks that need no HTTP at all;
3. (slow) a 2-process end-to-end storm over the stub apiserver with a
   replica kill mid-storm and the apiserver-truth zero-oversubscription
   audit — real processes, the topology bench.py shard_scaleout --procs
   measures.
"""

import json
import threading
import time
import urllib.request
from types import SimpleNamespace

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.server import ExtenderServer
from tpushare.ha.forward import FORWARD_HEADER, ForwardRouter
from tpushare.ha.sharding import (
    SHARD_CONFLICTS, SHARD_FORWARDS, ShardMembership)
from tpushare.k8s import FakeCluster


def post(url, body, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def forwards():
    return {o: SHARD_FORWARDS.get(o)
            for o in ("forwarded", "served", "loop_fallback",
                      "peer_failed")}


def conflicts():
    return {o: SHARD_CONFLICTS.get(o)
            for o in ("owned", "spillover", "cas_lost")}


def delta(before, after):
    return {k: after[k] - before[k] for k in after}


@pytest.fixture
def duo():
    """Two full replica stacks ('ra', 'rb') over one FakeCluster, ring
    applied directly (deterministic, no renewal threads) and peer URLs
    cross-wired."""
    fc = FakeCluster()
    for i in range(8):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16000,
                        mesh="2x2")
    reps = {}
    for ident in ("ra", "rb"):
        cache = SchedulerCache(fc)
        ctl = Controller(fc, cache)
        ctl.build_cache()
        ctl.start()
        sm = ShardMembership(fc, ident, cache=cache)
        sm._apply_membership(["ra", "rb"])
        server = ExtenderServer(cache, fc, host="127.0.0.1", port=0,
                                sharding=sm)
        port = server.start()
        reps[ident] = SimpleNamespace(
            cache=cache, ctl=ctl, sm=sm, server=server,
            base=f"http://127.0.0.1:{port}")
    reps["ra"].sm._peers = {"rb": reps["rb"].base}
    reps["rb"].sm._peers = {"ra": reps["ra"].base}
    yield fc, reps
    for r in reps.values():
        r.server.stop()
        r.ctl.stop()


def _node_owned_by(reps, owner):
    sm = reps["ra"].sm
    return next(n for n in (f"n{i}" for i in range(8))
                if sm.owner_of(n) == owner)


def test_offshard_bind_forwards_to_owner_and_binds_lock_free(duo):
    fc, reps = duo
    node = _node_owned_by(reps, "rb")
    pod = fc.create_pod(make_pod(hbm=2000, name="fw-happy"))
    f0, c0 = forwards(), conflicts()
    status, result = post(f"{reps['ra'].base}/tpushare-scheduler/bind", {
        "PodName": "fw-happy", "PodNamespace": "default",
        "PodUID": pod["metadata"]["uid"], "Node": node})
    assert status == 200 and not result.get("Error"), result
    assert fc.get_pod("default", "fw-happy")["spec"]["nodeName"] == node
    df, dc = delta(f0, forwards()), delta(c0, conflicts())
    # exactly one hop: ra forwarded, rb served...
    assert df["forwarded"] == 1 and df["served"] == 1, df
    assert df["loop_fallback"] == 0 and df["peer_failed"] == 0, df
    # ...and the owner bound LOCK-FREE — the spillover CAS the forward
    # exists to eliminate never ran
    assert dc["owned"] == 1 and dc["spillover"] == 0, dc


def test_midrebalance_disagreement_degrades_to_cas_no_pingpong(duo):
    fc, reps = duo
    # rb's view is one rebalance ahead: a third member joined, so for
    # some nodes ra still routes to rb while rb already routes elsewhere
    reps["rb"].sm._apply_membership(["ra", "rb", "rc"])
    # a live (but bogus) rc peer URL proves the LOOP GUARD — not a
    # missing address — is what stops the second hop
    reps["rb"].sm._peers = {"ra": reps["ra"].base,
                            "rc": "http://127.0.0.1:1"}
    ra_sm, rb_sm = reps["ra"].sm, reps["rb"].sm
    node = next(n for n in (f"n{i}" for i in range(8))
                if ra_sm.owner_of(n) == "rb"
                and rb_sm.owner_of(n) != "rb")
    pod = fc.create_pod(make_pod(hbm=2000, name="fw-loop"))
    f0, c0 = forwards(), conflicts()
    status, result = post(f"{reps['ra'].base}/tpushare-scheduler/bind", {
        "PodName": "fw-loop", "PodNamespace": "default",
        "PodUID": pod["metadata"]["uid"], "Node": node})
    assert status == 200 and not result.get("Error"), result
    assert fc.get_pod("default", "fw-loop")["spec"]["nodeName"] == node
    df, dc = delta(f0, forwards()), delta(c0, conflicts())
    # one hop, then the guard: rb did NOT forward on to rc (no
    # ping-pong), it served locally through the claim-CAS spillover path
    assert df["forwarded"] == 1 and df["loop_fallback"] == 1, df
    assert df["served"] == 0 and df["peer_failed"] == 0, df
    assert dc["spillover"] == 1 and dc["cas_lost"] == 0, dc


def test_dead_peer_fails_fast_into_local_cas(duo):
    fc, reps = duo
    # rb's advertised address is dead (nothing listens on port 9);
    # after 3 transport failures the per-peer breaker opens and later
    # forwards are refused with zero connection attempts
    reps["ra"].sm._peers = {"rb": "http://127.0.0.1:9"}
    node = _node_owned_by(reps, "rb")
    f0, c0 = forwards(), conflicts()
    for i in range(4):
        pod = fc.create_pod(make_pod(hbm=1000, name=f"fw-dead-{i}"))
        status, result = post(
            f"{reps['ra'].base}/tpushare-scheduler/bind", {
                "PodName": f"fw-dead-{i}", "PodNamespace": "default",
                "PodUID": pod["metadata"]["uid"], "Node": node})
        # availability invariant: a forward must never lose the bind
        assert status == 200 and not result.get("Error"), (i, result)
        assert fc.get_pod("default", f"fw-dead-{i}") \
            ["spec"]["nodeName"] == node
    df, dc = delta(f0, forwards()), delta(c0, conflicts())
    assert df["peer_failed"] == 4 and df["forwarded"] == 0, df
    # every bind fell back to the claim CAS and won it
    assert dc["spillover"] == 4 and dc["cas_lost"] == 0, dc


def test_peer_breaker_recovers_when_dead_peer_comes_back(duo):
    """The other half of the dead-peer story (ISSUE 13 satellite): the
    per-peer breaker must not stay latched once the peer heals. rb's
    server dies, three binds trip ra's breaker into the local-CAS
    fallback; rb restarts ON THE SAME PORT (same PeerPool key, same
    breaker instance); after the reset timeout the half-open probe rides
    the next bind, succeeds, closes the breaker, and forwarding resumes."""
    from tpushare.ha.forward import ForwardRouter as _FR
    from tpushare.k8s.peer import PeerPool

    fc, reps = duo
    ra, rb = reps["ra"], reps["rb"]
    # a tight reset so the half-open probe happens inside the test; the
    # knobs are the point — production keeps the 2 s default
    ra.server.forwarder = _FR(
        ra.sm, pool=PeerPool(failure_threshold=3, reset_timeout_s=0.3),
        enabled=True)
    node = _node_owned_by(reps, "rb")
    rb_port = int(rb.base.rsplit(":", 1)[1])

    rb.server.stop()  # the peer dies; its lease (ring entry) lingers
    f0, c0 = forwards(), conflicts()
    for i in range(3):
        pod = fc.create_pod(make_pod(hbm=1000, name=f"fw-rec-{i}"))
        status, result = post(
            f"{ra.base}/tpushare-scheduler/bind", {
                "PodName": f"fw-rec-{i}", "PodNamespace": "default",
                "PodUID": pod["metadata"]["uid"], "Node": node})
        assert status == 200 and not result.get("Error"), (i, result)
    df, dc = delta(f0, forwards()), delta(c0, conflicts())
    assert df["peer_failed"] == 3 and df["forwarded"] == 0, df
    assert dc["spillover"] == 3, dc

    # rb comes back on the SAME port — the address book never changed,
    # so recovery is purely the breaker's half-open -> closed transition
    rb.server = ExtenderServer(rb.cache, fc, host="127.0.0.1",
                               port=rb_port, sharding=rb.sm)
    assert rb.server.start() == rb_port
    time.sleep(0.35)  # past reset_timeout_s: breaker arms a probe
    f0, c0 = forwards(), conflicts()
    for i in range(3):
        pod = fc.create_pod(make_pod(hbm=1000, name=f"fw-back-{i}"))
        status, result = post(
            f"{ra.base}/tpushare-scheduler/bind", {
                "PodName": f"fw-back-{i}", "PodNamespace": "default",
                "PodUID": pod["metadata"]["uid"], "Node": node})
        assert status == 200 and not result.get("Error"), (i, result)
        assert fc.get_pod("default", f"fw-back-{i}") \
            ["spec"]["nodeName"] == node
    df, dc = delta(f0, forwards()), delta(c0, conflicts())
    # all three forwarded (the first was the successful probe) and the
    # owner served them — no residual fallback on ra's side
    assert df["forwarded"] == 3 and df["served"] == 3, df
    assert df["peer_failed"] == 0 and df["loop_fallback"] == 0, df
    # rb's first bind stays on the claim CAS: ra's fallback binds during
    # the outage moved the node's generation stamp, so handover
    # revalidation re-arms once before promoting back to lock-free
    assert dc["spillover"] == 1 and dc["owned"] == 2, dc
    assert dc["cas_lost"] == 0, dc


def test_filter_stays_local_unless_cycle_forwarding_opted_in(duo):
    fc, reps = duo
    ra = reps["ra"]
    # find a pod name whose cycle key routes to rb
    name = next(f"cyc-{i}" for i in range(64)
                if ra.sm.owner_of(f"default/cyc-{i}") == "rb")
    pod = make_pod(hbm=2000, name=name)
    f0 = forwards()
    status, result = post(f"{ra.base}/tpushare-scheduler/filter", {
        "Pod": pod, "NodeNames": [f"n{i}" for i in range(8)]})
    assert status == 200 and result["NodeNames"]
    assert delta(f0, forwards())["forwarded"] == 0  # default: reads stay local
    # opt in: the pod's whole cycle now runs on its owner
    ra.server.forwarder = ForwardRouter(ra.sm, enabled=True, cycle=True)
    f0 = forwards()
    status, fwd_result = post(f"{ra.base}/tpushare-scheduler/filter", {
        "Pod": pod, "NodeNames": [f"n{i}" for i in range(8)]})
    assert status == 200
    assert delta(f0, forwards())["forwarded"] == 1
    assert fwd_result["NodeNames"] == result["NodeNames"]


# -- router-level decisions (no HTTP) -----------------------------------------

class _SM:
    def __init__(self, identity, owner, live=True, peers=None):
        self.identity = identity
        self._owner = owner
        self._live = live
        self._peers = peers or {}

    def is_live(self):
        return self._live

    def owner_of(self, key):
        return self._owner

    def peer_url(self, ident):
        return self._peers.get(ident)


def test_router_serves_when_not_live_or_unadvertised():
    bind = {"Node": "n1"}
    # not live: claim-CAS safety net, no routing
    r = ForwardRouter(_SM("ra", "rb", live=False), enabled=True)
    assert r.maybe_forward("bind", "/p", b"{}", bind, None) is None
    # owner never advertised a URL (mixed-version fleet): serve locally
    r = ForwardRouter(_SM("ra", "rb"), enabled=True)
    assert r.maybe_forward("bind", "/p", b"{}", bind, None) is None
    # own shard: serve locally
    r = ForwardRouter(_SM("ra", "ra"), enabled=True)
    assert r.maybe_forward("bind", "/p", b"{}", bind, None) is None


def test_router_guard_header_is_terminal():
    f0 = forwards()
    # guarded + ring agrees we own it -> served
    r = ForwardRouter(_SM("rb", "rb", peers={"ra": "http://x"}),
                      enabled=True)
    assert r.maybe_forward("bind", "/p", b"{}", {"Node": "n1"},
                          "ra") is None
    # guarded + ring disagrees -> loop_fallback, STILL no second hop
    r = ForwardRouter(_SM("rb", "rc", peers={"rc": "http://x"}),
                      enabled=True)
    assert r.maybe_forward("bind", "/p", b"{}", {"Node": "n1"},
                          "ra") is None
    df = delta(f0, forwards())
    assert df["served"] == 1 and df["loop_fallback"] == 1
    assert df["forwarded"] == 0


def test_router_disabled_by_knob():
    r = ForwardRouter(_SM("ra", "rb", peers={"rb": "http://x"}),
                      enabled=False)
    assert r.maybe_forward("bind", "/p", b"{}", {"Node": "n1"},
                          None) is None


# -- (slow) 2-process end-to-end storm over the stub apiserver ----------------

@pytest.mark.slow
def test_two_process_storm_with_kill_zero_oversubscription(tmp_path):
    import os
    import signal
    import subprocess
    import sys

    from tests.test_ha_storm import (
        assert_apiserver_invariants, seed_pod, wait_until)
    from tpushare.k8s.incluster import InClusterClient
    from tpushare.k8s.stubapi import StubApiServer

    GIB = 1024
    stub = StubApiServer().start()
    for i in range(6):
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"e{i}",
                         "labels": {"tpushare": "true",
                                    "tpushare.aliyun.com/mesh": "2x2"}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(4 * 16 * GIB),
                "aliyun.com/tpu-count": "4"}}})
    env = dict(os.environ,
               TPUSHARE_SHARD_REPLICAS="2",
               TPUSHARE_SHARD_LEASE_S="1.5",
               TPUSHARE_SHARD_RENEW_S="0.2",
               TPUSHARE_FLEETWATCH="0", TPUSHARE_DEFRAG="0",
               JAX_PLATFORMS="cpu")
    procs, bases = [], []
    try:
        for i in range(2):
            p = subprocess.Popen(
                [sys.executable, "-m", "tpushare.extender",
                 "--apiserver", stub.base_url,
                 "--host", "127.0.0.1", "--port", "0"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True)
            procs.append(p)
        for p in procs:
            line = ""
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if "ready on" in line:
                    break
            assert "ready on" in line, "extender never came up"
            bases.append("http://" + line.rsplit("on ", 1)[1].strip())

        def ring(base):
            with urllib.request.urlopen(f"{base}/inspect/ring",
                                        timeout=5) as r:
                return json.loads(r.read())

        # both replicas converge on a 2-member ring with peer addresses
        assert wait_until(
            lambda: all(len(ring(b).get("members", [])) == 2
                        and len(ring(b).get("peers", {})) == 2
                        for b in bases), timeout=30)

        client = InClusterClient(base_url=stub.base_url, timeout=10.0)
        names = [f"e{i}" for i in range(6)]
        pods = [seed_pod(stub, f"e2e-{i}", 2 * GIB) for i in range(20)]
        bound = {}

        def drive(pod, endpoints, attempts=40):
            meta = pod["metadata"]
            for a in range(attempts):
                base = endpoints[a % len(endpoints)]
                try:
                    _, flt = post(f"{base}/tpushare-scheduler/filter",
                                  {"Pod": pod, "NodeNames": names},
                                  timeout=5)
                    ok = flt.get("NodeNames") or []
                    if not ok:
                        return None
                    status, res = post(
                        f"{base}/tpushare-scheduler/bind", {
                            "PodName": meta["name"],
                            "PodNamespace": meta["namespace"],
                            "PodUID": meta.get("uid", ""),
                            "Node": ok[0]}, timeout=5)
                    if status == 200 and not res.get("Error"):
                        return ok[0]
                except OSError:
                    pass
                time.sleep(0.05)
            return None

        # first half of the storm across both replicas
        for pod in pods[:10]:
            node = drive(pod, bases)
            if node:
                bound[pod["metadata"]["name"]] = node
        # kill replica 0 mid-storm (SIGKILL: no lease abdication — the
        # survivor must expire it by TTL) and drain through the survivor
        procs[0].kill()
        for pod in pods[10:]:
            node = drive(pod, [bases[1]])
            if node:
                bound[pod["metadata"]["name"]] = node
        assert wait_until(
            lambda: len(ring(bases[1]).get("members", [])) == 1,
            timeout=15)
        assert len(bound) >= 18, f"storm bound only {len(bound)}/20"
        # the acceptance audit: apiserver truth shows zero chip
        # oversubscription across the replica-kill handoff
        per_chip = assert_apiserver_invariants(stub, client)
        assert sum(per_chip.values()) > 0
        for pod in client.list_pods():
            name = pod["metadata"]["name"]
            if name in bound:
                assert pod["spec"]["nodeName"] == bound[name]
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        stub.stop()
