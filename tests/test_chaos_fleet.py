"""The chaos conductor (ISSUE 13 tentpole b): one seeded fault schedule,
three consumers. tests/test_sim_faults.py proves the two sim engines
replay it byte-identically; this file proves the REAL stack survives it
— first hermetically (in-process fleet over a FakeCluster, tier-1),
then end-to-end (slow: real extender processes killed and restarted
against the wire-format stub apiserver), with the same invariants
monitored continuously: zero chip oversubscription on apiserver truth
at every sampled instant, zero residual drift after healing, bounded
recovery of every half-bound orphan."""

import json
import threading
import time
import urllib.request

import pytest

from tpushare.chaos import (
    ChaosConductor,
    assert_drill_invariants,
    run_hermetic_drill,
)
from tpushare.sim import FaultEvent, FaultSpec, synth_faults


# -- conductor dispatch + pacing (no fleet) ------------------------------------


class _Recorder:
    def __init__(self):
        self.calls = []

    def node_down(self, node, lose_pods):
        self.calls.append(("node_down", node, lose_pods))

    def node_up(self, node):
        self.calls.append(("node_up", node))

    def brownout_start(self):
        self.calls.append(("brownout_start",))

    def brownout_end(self):
        self.calls.append(("brownout_end",))

    def replica_crash(self, replica):
        raise RuntimeError("the crash crashed")  # conductor must survive


def test_conductor_dispatches_in_order_with_compressed_pacing():
    clock = [0.0]
    sleeps = []

    def fake_sleep(s):
        sleeps.append(round(s, 6))
        clock[0] += s

    rec = _Recorder()
    cond = ChaosConductor(rec, seconds_per_unit=0.1,
                          clock=lambda: clock[0], sleep=fake_sleep)
    applied = cond.run([
        FaultEvent(time=1.0, kind="node_down", node=2, lose_pods=True),
        FaultEvent(time=3.0, kind="brownout_start"),
        FaultEvent(time=5.0, kind="brownout_end"),
        FaultEvent(time=5.0, kind="node_up", node=2),
        # no 'degrade' method on the target -> skipped, not an error
        FaultEvent(time=6.0, kind="degrade", node=1, chips=(0,)),
        # the action raises -> logged + skipped, the storm goes on
        FaultEvent(time=7.0, kind="replica_crash", replica=0),
    ])
    assert rec.calls == [("node_down", 2, True), ("brownout_start",),
                         ("brownout_end",), ("node_up", 2)]
    # each event waits to its compressed offset (0.1 s per sim unit);
    # skipped events are still paced (the schedule's clock is shared)
    assert sleeps == [0.1, 0.2, 0.2, 0.1, 0.1]
    assert applied == {"node_down": 1, "brownout_start": 1,
                       "brownout_end": 1, "node_up": 1, "skipped": 2}


def test_conductor_rejects_nonpositive_time_scale():
    with pytest.raises(ValueError):
        ChaosConductor(_Recorder(), seconds_per_unit=0.0)


def test_synth_schedule_drives_the_conductor_end_to_end():
    """The generator and the conductor speak the same language: every
    kind synth_faults emits dispatches without a skip on a full target."""

    class _Full(_Recorder):
        def degrade(self, node, chips):
            self.calls.append(("degrade", node, chips))

        def replica_crash(self, replica):
            self.calls.append(("replica_crash", replica))

        def replica_restart(self, replica):
            self.calls.append(("replica_restart", replica))

    schedule = synth_faults(FaultSpec(
        hours=10.0, n_nodes=4, chips_per_node=4, node_crashes=1,
        notready_windows=1, degradations=1, brownouts=1,
        replica_crashes=1, replicas=2, mean_outage=2.0, seed=9))
    rec = _Full()
    clock = [0.0]

    def fake_sleep(s):
        clock[0] += s

    applied = ChaosConductor(rec, seconds_per_unit=0.01,
                             clock=lambda: clock[0],
                             sleep=fake_sleep).run(schedule)
    assert applied.pop("skipped") == 0
    assert sum(applied.values()) == len(schedule) == len(rec.calls)


# -- the hermetic drill (tier-1) -----------------------------------------------


def test_hermetic_drill_survives_the_seeded_storm():
    """The whole in-process fleet — two replicas, claim CAS, informers,
    recovery heartbeat — under the seeded schedule: crash, restart,
    brownout, partition, degrade. Every invariant, every interleaving."""
    assert_drill_invariants(run_hermetic_drill(seed=1234))


@pytest.mark.slow
def test_hermetic_drill_many_seeds():
    for seed in (7, 42, 20260805):
        assert_drill_invariants(run_hermetic_drill(seed=seed))


# -- (slow) the real-fleet conductor run ---------------------------------------


def _post(url, body, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


@pytest.mark.slow
def test_real_fleet_conductor_kill_restart_brownout(tmp_path):
    """The acceptance run: >=2 real extender processes against the stub
    apiserver; the conductor replays a seeded schedule that kills and
    RESTARTS a replica, severs watches + browns out the apiserver, and
    partitions a node — while a driver storms pods through whichever
    replica answers. Ends with zero chip oversubscription at every
    sampled instant, every placement bound, and the ring reconverged."""
    import os
    import signal
    import subprocess
    import sys

    from tests.test_ha_storm import (
        assert_apiserver_invariants, seed_pod, wait_until)
    from tpushare.chaos.invariants import InvariantMonitor
    from tpushare.k8s.incluster import InClusterClient
    from tpushare.k8s.stubapi import StubApiServer

    GIB = 1024
    stub = StubApiServer().start()
    node_names = [f"e{i}" for i in range(6)]
    for n in node_names:
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": n,
                         "labels": {"tpushare": "true",
                                    "tpushare.aliyun.com/mesh": "2x2"}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(4 * 16 * GIB),
                "aliyun.com/tpu-count": "4"}}})
    env = dict(os.environ,
               TPUSHARE_SHARD_REPLICAS="2",
               TPUSHARE_SHARD_LEASE_S="1.5",
               TPUSHARE_SHARD_RENEW_S="0.2",
               TPUSHARE_RESYNC_S="0.5",
               TPUSHARE_RECOVERY_STALE_S="1.0",
               TPUSHARE_FLEETWATCH="0", TPUSHARE_DEFRAG="0",
               JAX_PLATFORMS="cpu")

    procs: list = [None, None]
    bases: list = [None, None]

    def spawn(i):
        p = subprocess.Popen(
            [sys.executable, "-m", "tpushare.extender",
             "--apiserver", stub.base_url,
             "--host", "127.0.0.1", "--port", "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        deadline = time.monotonic() + 60
        line = ""
        while time.monotonic() < deadline:
            line = p.stdout.readline()
            if "ready on" in line:
                break
        assert "ready on" in line, f"replica {i} never came up"
        procs[i] = p
        bases[i] = "http://" + line.rsplit("on ", 1)[1].strip()

    class ProcessFleet:
        """Conductor target over real OS processes + the stub's chaos
        primitives. 'degrade' is unimplemented at this fidelity —
        the conductor counts it skipped."""

        def node_down(self, idx, lose_pods):
            stub.partition(node_names[idx % len(node_names)])

        def node_up(self, idx):
            stub.heal(node_names[idx % len(node_names)])

        def brownout_start(self):
            stub.break_watches()  # >=1 watch break, by construction
            for n in node_names:
                stub.partition(n)

        def brownout_end(self):
            stub.heal()

        def replica_crash(self, idx):
            i = idx % 2
            if procs[i] is not None and procs[i].poll() is None and \
                    (procs[1 - i] is not None
                     and procs[1 - i].poll() is None):
                procs[i].kill()  # SIGKILL: no abdication, no cleanup

        def replica_restart(self, idx):
            i = idx % 2
            if procs[i] is not None and procs[i].poll() is not None:
                spawn(i)  # cold start: build_cache + recovery pass

    try:
        for i in range(2):
            spawn(i)

        def ring(base):
            with urllib.request.urlopen(f"{base}/inspect/ring",
                                        timeout=5) as r:
                return json.loads(r.read())

        assert wait_until(
            lambda: all(len(ring(b).get("members", [])) == 2
                        for b in bases), timeout=30)

        client = InClusterClient(base_url=stub.base_url, timeout=10.0)
        monitor = InvariantMonitor(client.list_pods, 16 * GIB,
                                   interval_s=0.05).start()

        # a schedule with exactly the acceptance ingredients: one node
        # NotReady window, one brownout (watch sever + node 503s), one
        # replica SIGKILL + cold restart
        schedule = synth_faults(FaultSpec(
            hours=16.0, n_nodes=len(node_names), chips_per_node=4,
            node_crashes=1, notready_windows=0, degradations=0,
            brownouts=1, replica_crashes=1, replicas=2,
            mean_outage=3.0, seed=5))
        conductor = ChaosConductor(ProcessFleet(), seconds_per_unit=0.4)
        applied: dict = {}
        storm = threading.Thread(
            target=lambda: applied.update(conductor.run(schedule)),
            daemon=True)
        storm.start()

        pods = [seed_pod(stub, f"cx-{i}", 2 * GIB) for i in range(20)]
        bound: dict = {}

        def drive(pod, attempts=60):
            meta = pod["metadata"]
            for a in range(attempts):
                live = [b for i, b in enumerate(bases)
                        if procs[i] is not None
                        and procs[i].poll() is None]
                if not live:
                    time.sleep(0.2)
                    continue
                base = live[a % len(live)]
                try:
                    _, flt = _post(f"{base}/tpushare-scheduler/filter",
                                   {"Pod": pod, "NodeNames": node_names},
                                   timeout=5)
                    ok = flt.get("NodeNames") or []
                    if ok:
                        status, res = _post(
                            f"{base}/tpushare-scheduler/bind", {
                                "PodName": meta["name"],
                                "PodNamespace": meta["namespace"],
                                "PodUID": meta.get("uid", ""),
                                "Node": ok[a % len(ok)]}, timeout=5)
                        if status == 200 and not res.get("Error"):
                            return ok[a % len(ok)]
                except OSError:
                    pass
                time.sleep(0.1)
            return None

        for pod in pods:  # the storm rages while these bind
            node = drive(pod)
            if node:
                bound[pod["metadata"]["name"]] = node
        storm.join(timeout=60)
        assert applied.get("replica_crash", 0) >= 1, applied
        assert applied.get("replica_restart", 0) >= 1, applied
        assert applied.get("brownout_start", 0) >= 1, applied

        # healing: everything lifted, both replicas up, ring reconverges
        # within the lease TTL
        stub.heal()
        for i in range(2):
            if procs[i].poll() is not None:
                spawn(i)
        assert wait_until(
            lambda: all(len(ring(b).get("members", [])) == 2
                        for b in bases), timeout=30)
        # stragglers bind against the healthy fleet; the recovery
        # heartbeat (TPUSHARE_RECOVERY_STALE_S=1, TPUSHARE_RESYNC_S=0.5)
        # adopts-or-GCs anything a dead incarnation half-bound
        for pod in pods:
            if pod["metadata"]["name"] not in bound:
                node = drive(pod)
                if node:
                    bound[pod["metadata"]["name"]] = node
        assert len(bound) == 20, f"only {len(bound)}/20 ever bound"

        # half-bound orphans must evaporate within the bounded window
        def half_bound():
            from tpushare import contract
            out = []
            for pod in client.list_pods():
                if contract.is_complete_pod(pod) or \
                        (pod.get("spec") or {}).get("nodeName"):
                    continue
                if contract.chip_ids_from_annotations(pod) is not None:
                    out.append(pod["metadata"]["name"])
            return out

        assert wait_until(lambda: not half_bound(), timeout=10), \
            f"half-bound orphans survived: {half_bound()}"

        verdict = monitor.stop()
        assert verdict["samples"] > 10
        assert not verdict["oversubscription"], \
            verdict["oversubscription"][:3]
        # the acceptance audit on final apiserver truth
        per_chip = assert_apiserver_invariants(stub, client)
        assert sum(per_chip.values()) > 0
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            if p is not None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        stub.stop()
