"""Dual-replica HA bind storm over the stub apiserver (VERDICT r2 item 6).

Round 2's HA tests covered election mechanics and 503 gating; this module
runs TWO COMPLETE extender stacks (SchedulerCache + Controller +
ExtenderServer + LeaderElector, each over its own InClusterClient) against
one stub apiserver and storms them with concurrent binds:

1. mid-storm failover: the leader abdicates while binds are in flight and
   the fleet keeps scheduling through the new leader;
2. split-brain window: the leader is partitioned from the apiserver (its
   elector can't renew) while a second replica legitimately acquires the
   expired lease — for a moment BOTH believe they lead, and the same pods
   are bound through both at once. Exactly-one-wins must come from the
   apiserver (binding subresource 409s once nodeName is set), not from
   election luck.

The invariants asserted are the apiserver-state ones that survive any
cache divergence (controller resync reconciles caches from annotations):
every bound pod carries exactly one complete placement, per-chip grant
totals never exceed capacity, and no pod is placement-annotated on a node
other than the one it is bound to.

The reference lists HA as an unbuilt roadmap item (README.md:80).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.server import ExtenderServer
from tpushare.ha import LeaderElector
from tpushare.k8s.incluster import InClusterClient
from tpushare.k8s.stubapi import StubApiServer

GIB = 1024
NODES = 4
CHIPS = 4
HBM = 16 * GIB


class Replica:
    def __init__(self, stub, ident: str):
        self.ident = ident
        self.client = InClusterClient(base_url=stub.base_url, timeout=10.0)
        self.cache = SchedulerCache(self.client)
        self.controller = Controller(self.client, self.cache)
        self.controller.build_cache()
        self.controller.start()
        self.elector = LeaderElector(self.client, ident,
                                     lease_duration=0.8, renew_period=0.1,
                                     retry_period=0.05)
        self.elector.start()
        self.server = ExtenderServer(self.cache, self.client,
                                     host="127.0.0.1", port=0,
                                     elector=self.elector)
        self.base = (f"http://127.0.0.1:{self.server.start()}"
                     "/tpushare-scheduler")

    def stop(self):
        self.server.stop()
        self.elector.stop()
        self.controller.stop()


def post(base, path, body, timeout=10.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def wait_until(fn, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    stub = StubApiServer().start()
    for i in range(NODES):
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"s{i}",
                         "labels": {"tpushare": "true",
                                    "tpushare.aliyun.com/mesh": "2x2"}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(CHIPS * HBM),
                "aliyun.com/tpu-count": str(CHIPS)}}})
    a = Replica(stub, "ra")
    b = Replica(stub, "rb")
    assert wait_until(lambda: a.elector.is_leader()
                      or b.elector.is_leader())
    try:
        yield stub, a, b
    finally:
        a.stop()
        b.stop()
        stub.stop()


def seed_pod(stub, name: str, hbm_mib: int) -> dict:
    return stub.seed("pods", {
        "metadata": {"name": name, "namespace": "storm",
                     "annotations": {}},
        "spec": {"containers": [{"name": "c", "resources": {
            "limits": {"aliyun.com/tpu-hbm": str(hbm_mib)}}}]}})


def try_schedule(replicas, pod, node_names, attempts=80) -> str | None:
    """kube-scheduler's behavior across HA replicas: try one, and on 503 /
    error / timeout retry (the service would round-robin endpoints).

    The retry budget must comfortably cover a leader takeover: the real
    scheduler retries failed pods for minutes, while a loaded CI
    machine can stretch this rig's sub-second lease handoff past a few
    seconds — a skimpy budget here turns takeover jitter into test
    flakes (observed: 30 x 0.02 s gave up mid-failover).
    """
    name = pod["metadata"]["name"]
    for i in range(attempts):
        rep = replicas[i % len(replicas)]
        try:
            _, flt = post(rep.base, "/filter",
                          {"Pod": pod, "NodeNames": node_names}, timeout=5)
        except OSError:
            continue
        ok = flt.get("NodeNames") or []
        if not ok:
            return None
        status, result = post(rep.base, "/bind", {
            "PodName": name, "PodNamespace": "storm",
            "PodUID": pod["metadata"].get("uid", ""), "Node": ok[0]},
            timeout=5)
        if status == 200 and not result.get("Error"):
            return ok[0]
        time.sleep(0.05)
    return None


def assert_apiserver_invariants(stub, client):
    """The truths that must hold no matter which replica did what."""
    pods = client.list_pods()
    per_chip: dict[tuple[str, int], int] = {}
    for pod in pods:
        ids = contract.chip_ids_from_annotations(pod)
        node = pod.get("spec", {}).get("nodeName")
        if ids is None:
            continue
        assert node, (f"pod {pod['metadata']['name']} carries a placement "
                      "but is not bound")
        grant = contract.hbm_from_annotations(pod)
        assert grant > 0
        for c in ids:
            per_chip[(node, c)] = per_chip.get((node, c), 0) + grant
    for (node, c), used in per_chip.items():
        if used > HBM:
            detail = []
            for pod in pods:
                ids = contract.chip_ids_from_annotations(pod)
                if ids is not None and c in ids and \
                        pod.get("spec", {}).get("nodeName") == node:
                    detail.append(
                        (pod["metadata"]["name"],
                         contract.hbm_from_annotations(pod),
                         contract.assume_time_from_annotations(pod)))
            claims = client.get_node(node)["metadata"].get(
                "annotations", {}).get("tpushare.aliyun.com/claims")
            raise AssertionError(
                f"chip {node}/{c} oversubscribed: {used} > {HBM}; "
                f"pods={detail} claims={claims}")
    return per_chip


def test_storm_with_midflight_failover(cluster):
    stub, a, b = cluster
    replicas = [a, b]
    names = [f"s{i}" for i in range(NODES)]
    pods = [seed_pod(stub, f"storm-{i}", 2 * GIB) for i in range(36)]

    bound: dict[str, str] = {}
    lock = threading.Lock()
    failover_at = 12
    done = {"n": 0}

    def worker(chunk):
        for pod in chunk:
            node = try_schedule(replicas, pod, names)
            with lock:
                done["n"] += 1
                if node:
                    bound[pod["metadata"]["name"]] = node

    threads = [threading.Thread(target=worker, args=(pods[i::4],))
               for i in range(4)]
    for t in threads:
        t.start()
    # force failover while binds are in flight
    assert wait_until(lambda: done["n"] >= failover_at, timeout=30)
    leader = a if a.elector.is_leader() else b
    other = b if leader is a else a
    leader.elector.stop()  # abdicates mid-storm
    for t in threads:
        t.join(timeout=60)
    assert wait_until(other.elector.is_leader, timeout=5), \
        "failover must complete"

    # kube-scheduler retries pending pods indefinitely; the storm
    # workers' bounded budgets model only its fast path, and takeover
    # latency varies (the tight retry loops themselves starve the
    # elector thread of the GIL in-process). Model the scheduler's
    # retry horizon: whatever the storm left pending gets retried
    # against the surviving leader before judging the outcome.
    for pod in pods:
        name = pod["metadata"]["name"]
        if name not in bound:
            node = try_schedule([other], pod, names)
            if node:
                bound[name] = node

    # capacity: 4 nodes x 4 chips x 16 GiB / 2 GiB = 128 slots >> 36 pods.
    # Binds issued at the abdication instant may have burned retries on
    # both replicas; after the post-failover retry pass, a strong
    # majority must have landed.
    assert len(bound) >= 30, f"storm bound only {len(bound)}/36"
    per_chip = assert_apiserver_invariants(stub, a.client)
    # every bound pod's annotation node matches its binding
    for pod in a.client.list_pods():
        name = pod["metadata"]["name"]
        if name in bound:
            assert pod["spec"]["nodeName"] == bound[name]
    assert sum(per_chip.values()) == len(bound) * 2 * GIB


def test_split_brain_concurrent_binds_exactly_one_wins(cluster):
    stub, a, b = cluster
    names = [f"s{i}" for i in range(NODES)]
    # make A the leader deterministically
    if not a.elector.is_leader():
        b.elector.stop()
        assert wait_until(a.elector.is_leader, timeout=5)
        b.elector = LeaderElector(b.client, "rb", lease_duration=0.8,
                                  renew_period=0.1, retry_period=0.05)
        b.server._elector = b.elector
        b.elector.start()

    # Turn A into a ZOMBIE leader — the fencing hazard leases cannot
    # close: its election loop dies mid-term WITHOUT abdicating (process
    # pause / GC stall model), but its HTTP server keeps serving binds
    # on the stale belief. B legitimately acquires the expired lease, so
    # both replicas now accept binds concurrently. (A partitioned-but-
    # live elector steps down before the lease expires — tested in
    # test_ha.py — so a zombie is the only way this window opens, and
    # the claim CAS is the layer that must hold when it does.)
    a.elector._stop.set()
    a.elector._thread.join(timeout=2)

    class Zombie:
        identity = "ra"

        def is_leader(self):
            return True

    a.server._elector = Zombie()
    try:
        assert wait_until(b.elector.is_leader, timeout=5.0), \
            "B must take over the expired lease"

        # same pods, bound through BOTH replicas simultaneously
        pods = [seed_pod(stub, f"split-{i}", 4 * GIB) for i in range(8)]
        results: list[tuple[str, str, int, str]] = []
        rlock = threading.Lock()

        def bind_via(rep, pod):
            _, flt = post(rep.base, "/filter",
                          {"Pod": pod, "NodeNames": names}, timeout=5)
            ok = flt.get("NodeNames") or []
            if not ok:
                return
            status, result = post(rep.base, "/bind", {
                "PodName": pod["metadata"]["name"],
                "PodNamespace": "storm",
                "PodUID": pod["metadata"].get("uid", ""),
                "Node": ok[0]}, timeout=5)
            with rlock:
                results.append((pod["metadata"]["name"], rep.ident,
                                status, result.get("Error", ""), ok[0]))

        threads = []
        for pod in pods:
            threads.append(threading.Thread(target=bind_via, args=(a, pod)))
            threads.append(threading.Thread(target=bind_via, args=(b, pod)))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        a.server._elector = a.elector  # un-zombie for fixture teardown

    # exactly-one-wins comes from the apiserver: every pod is bound to
    # exactly one node with consistent annotations, chips within capacity
    assert_apiserver_invariants(stub, a.client)

    # a pod can lose on BOTH replicas in the same instant (claim
    # conflicts fail the late bind) — that is the safe outcome, and the
    # real scheduler simply retries unbound pods. Do the same.
    for pod in a.client.list_pods():
        name = pod["metadata"]["name"]
        if name.startswith("split-") and \
                not pod.get("spec", {}).get("nodeName"):
            assert contract.chip_ids_from_annotations(pod) is None, \
                f"{name} unbound but placement-annotated"
            try_schedule([b, a], pod, names)

    assert_apiserver_invariants(stub, a.client)
    bound = 0
    for pod in a.client.list_pods():
        name = pod["metadata"]["name"]
        if not name.startswith("split-"):
            continue
        node = pod.get("spec", {}).get("nodeName")
        if node:
            bound += 1
            assert contract.chip_ids_from_annotations(pod) is not None, \
                f"{name} bound without a placement"
    assert bound == 8, f"every split-brain pod must end bound once ({bound})"
    # two successes for one pod are legal ONLY as idempotent duplicates
    # (both replicas chose the same node; the loser saw AlreadyBound to
    # the node it requested). Success claims for DIFFERENT nodes would
    # mean the apiserver let both binds through.
    per_pod_nodes = {}
    for name, ident, status, err, node in results:
        if status == 200 and not err:
            per_pod_nodes.setdefault(name, set()).add(node)
    for name, nodes in per_pod_nodes.items():
        assert len(nodes) <= 1, \
            f"{name} bound successfully to different nodes: {nodes}"


def test_claim_conflict_metric_counts_ha_backpressure(cluster):
    """A bind refused by a concurrent replica's claim must increment
    tpushare_ha_claim_conflicts_total (and return a benign error, not a
    500-with-event)."""
    stub, a, b = cluster
    leader = a if a.elector.is_leader() else b
    # fill EVERY chip of s0 through the leader so any later choice on s0
    # overlaps a live claim
    for i in range(CHIPS):
        pod = seed_pod(stub, f"metric-fill-{i}", 16 * GIB)
        assert try_schedule([leader], pod, ["s0"]) == "s0"

    # a replica whose cache has NEVER seen those binds (no controller,
    # worst-case watch lag) serves a bind with a zombie-leader belief:
    # its filter passes on the stale cache and the claim CAS must refuse
    stale = Replica(stub, "rz")
    stale.controller.stop()
    stale.cache = SchedulerCache(stale.client)  # empty, watch-less
    stale.server.stop()
    stale.elector.stop()

    class Zombie:
        identity = "rz"

        def is_leader(self):
            return True

    stale.server = ExtenderServer(stale.cache, stale.client,
                                  host="127.0.0.1", port=0,
                                  elector=Zombie())
    base = f"http://127.0.0.1:{stale.server.start()}/tpushare-scheduler"
    try:
        pod2 = seed_pod(stub, "metric-victim", 16 * GIB)
        status, result = post(base, "/bind", {
            "PodName": "metric-victim", "PodNamespace": "storm",
            "PodUID": pod2["metadata"].get("uid", ""), "Node": "s0"})
        # bind failures are HTTP 500 + Error (reference routes.go:139-143);
        # "benign" means no FailedScheduling-style event, not a 200
        assert status == 500
        assert "claim" in result.get("Error", ""), result
        with urllib.request.urlopen(
                base.rsplit("/", 1)[0] + "/metrics", timeout=5) as r:
            metrics = r.read().decode()
        value = next(
            float(line.split()[-1]) for line in metrics.splitlines()
            if line.startswith("tpushare_ha_claim_conflicts_total"))
        assert value >= 1.0, metrics
        # and the victim pod is untouched (unbound, no placement)
        victim = stale.client.get_pod("storm", "metric-victim")
        assert not victim.get("spec", {}).get("nodeName")
        assert contract.chip_ids_from_annotations(victim) is None
    finally:
        stale.server.stop()


def test_gang_survives_leader_failover_midgang(cluster):
    # rank 0 binds through the leader; the leader dies before rank 1;
    # the SURVIVOR (fresh coordinator state) must recover the stamped
    # plan through the real HA stack and complete the gang on the
    # ORIGINAL geometry — docs/designs/multihost-gang.md recovery.
    stub, a, b = cluster
    # relabel the 4 stub nodes into one slice (2x2 hosts of 2x2 chips)
    for i, origin in enumerate(("0x0", "0x2", "2x0", "2x2")):
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"s{i}",
                         "labels": {
                             "tpushare": "true",
                             "tpushare.aliyun.com/mesh": "2x2",
                             contract.LABEL_SLICE: "slc0",
                             contract.LABEL_SLICE_ORIGIN: origin}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(CHIPS * HBM),
                "aliyun.com/tpu-count": str(CHIPS)}}})
    # the relabel may fall into the list->watch gap (the first watch
    # connects from "now"); the 30 s resync heals it in production —
    # trigger it directly here, then confirm both caches see the slice
    for r in (a, b):
        r.controller.resync_once()
    assert wait_until(lambda: all(
        getattr(r.cache.get_node_info("s0"), "slice_id", None) == "slc0"
        for r in (a, b)), timeout=5.0)

    def gang_pod(name, rank):
        return stub.seed("pods", {
            "metadata": {"name": name, "namespace": "storm",
                         "annotations": {
                             contract.ANN_GANG: "hag",
                             contract.ANN_GANG_SIZE: "8",
                             contract.ANN_GANG_RANK: str(rank),
                             contract.ANN_TOPOLOGY: "2x4"}},
            "spec": {"containers": [{"name": "c", "resources": {
                "limits": {"aliyun.com/tpu-count": "4"}}}]}})

    replicas = [a, b]
    names = [f"s{i}" for i in range(NODES)]

    p0 = gang_pod("hag-0", 0)
    host0 = leader = None
    for r in replicas:
        _, flt = post(r.base, "/filter", {"Pod": p0, "NodeNames": names})
        cands = flt.get("NodeNames") or []
        if not cands:
            continue
        status, bound = post(r.base, "/bind", {
            "PodName": "hag-0", "PodNamespace": "storm",
            "PodUID": p0["metadata"].get("uid", ""), "Node": cands[0]})
        if status == 200 and not bound.get("Error"):
            host0, leader = cands[0], r
            break
    assert leader is not None, "no replica bound gang rank 0"

    # the leader that bound rank 0 dies (coordinator state lost)
    survivor = b if leader is a else a
    leader.stop()
    assert wait_until(lambda: survivor.elector.is_leader(), timeout=10.0)
    # survivor's watch must see rank 0's placement before recovery
    assert wait_until(lambda: contract.chip_ids_from_annotations(
        survivor.client.get_pod("storm", "hag-0")) is not None,
        timeout=5.0)

    p1 = gang_pod("hag-1", 1)
    _, flt = post(survivor.base, "/filter",
                  {"Pod": p1, "NodeNames": names})
    assert flt.get("NodeNames"), flt
    (host1,) = flt["NodeNames"]
    assert host1 != host0  # original geometry's OTHER host
    status, bound = post(survivor.base, "/bind", {
        "PodName": "hag-1", "PodNamespace": "storm",
        "PodUID": p1["metadata"].get("uid", ""), "Node": host1})
    assert status == 200 and not bound.get("Error"), bound
    # both members fully placed, distinct hosts, 4 chips each
    for name in ("hag-0", "hag-1"):
        pod = survivor.client.get_pod("storm", name)
        ids = contract.chip_ids_from_annotations(pod)
        assert ids is not None and len(ids) == 4


def test_gang_filter_bind_interleaves_across_replicas_with_takeover(cluster):
    """VERDICT r4 item 5, HA leg: a 16-chip gang's four members race
    filter/bind through BOTH replicas from four threads while the
    initial leader abdicates mid-gang (takeover between reserve and the
    remaining binds). The stamped plan must keep every member on one
    geometry: all four bound, distinct hosts, disjoint full-host chip
    sets — regardless of which replica answered which member."""
    stub, a, b = cluster
    for i, origin in enumerate(("0x0", "0x2", "2x0", "2x2")):
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"s{i}",
                         "labels": {
                             "tpushare": "true",
                             "tpushare.aliyun.com/mesh": "2x2",
                             contract.LABEL_SLICE: "slc0",
                             contract.LABEL_SLICE_ORIGIN: origin}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(CHIPS * HBM),
                "aliyun.com/tpu-count": str(CHIPS)}}})
    for r in (a, b):
        r.controller.resync_once()
    assert wait_until(lambda: all(
        getattr(r.cache.get_node_info("s0"), "slice_id", None) == "slc0"
        for r in (a, b)), timeout=5.0)

    def gang_pod(name, rank):
        return stub.seed("pods", {
            "metadata": {"name": name, "namespace": "storm",
                         "annotations": {
                             contract.ANN_GANG: "igang",
                             contract.ANN_GANG_SIZE: "16",
                             contract.ANN_GANG_RANK: str(rank),
                             contract.ANN_TOPOLOGY: "4x4"}},
            "spec": {"containers": [{"name": "c", "resources": {
                "limits": {"aliyun.com/tpu-count": "4"}}}]}})

    pods = [gang_pod(f"igang-{r}", r) for r in range(4)]
    names = [f"s{i}" for i in range(NODES)]
    replicas = [a, b]
    bound_hosts: dict[int, str | None] = {}
    lock = threading.Lock()
    first_bound = threading.Event()

    def drive(rank):
        host = try_schedule(replicas, pods[rank], names, attempts=160)
        with lock:
            bound_hosts[rank] = host
        if host is not None:
            first_bound.set()

    threads = [threading.Thread(target=drive, args=(r,))
               for r in range(4)]
    for t in threads:
        t.start()
    # takeover mid-gang: once any member is bound, the current leader
    # abdicates (elector stopped, server kept answering — its remaining
    # binds must be refused as non-leader, not half-applied)
    assert first_bound.wait(timeout=30.0), "no member ever bound"
    leader = a if a.elector.is_leader() else b
    leader.elector.stop()
    for t in threads:
        t.join()

    assert all(h is not None for h in bound_hosts.values()), bound_hosts
    assert sorted(bound_hosts.values()) == sorted(names)  # 4 distinct
    # one geometry: every member sits on the FIRST stamped plan's host
    # for its rank, with its full-host chip set
    stamped = None
    for r in range(4):
        pod = (b if b.elector.is_leader() else a).client.get_pod(
            "storm", f"igang-{r}")
        plan = contract.gang_plan_from_annotations(pod)
        if plan is not None:
            stamped = plan
            break
    assert stamped is not None, "no member carries the stamped plan"
    plan_hosts = [m["host"] for m in stamped["members"]]
    seen_chips: dict[str, set] = {}
    for r in range(4):
        pod = (b if b.elector.is_leader() else a).client.get_pod(
            "storm", f"igang-{r}")
        ids = contract.chip_ids_from_annotations(pod)
        assert ids is not None and len(ids) == 4
        node = pod.get("spec", {}).get("nodeName")
        assert node == bound_hosts[r] == plan_hosts[r], (
            r, node, bound_hosts[r], plan_hosts[r])
        overlap = seen_chips.setdefault(node, set()) & set(ids)
        assert not overlap, (node, overlap)
        seen_chips[node] |= set(ids)
    assert_apiserver_invariants(stub, (b if b.elector.is_leader()
                                       else a).client)
