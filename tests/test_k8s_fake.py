"""FakeCluster semantics tests: the apiserver behaviors the scheduler relies on."""

import threading

import pytest

from tpushare.k8s import ApiError, FakeCluster
from tpushare.k8s.client import strategic_merge
from tests.test_contract import make_pod


def test_node_seeding_reports_aggregate_resources():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    node = fc.get_node("n1")
    assert node["status"]["allocatable"]["aliyun.com/tpu-hbm"] == "64000"
    assert node["status"]["allocatable"]["aliyun.com/tpu-count"] == "4"
    assert node["metadata"]["labels"]["tpushare.aliyun.com/mesh"] == "2x2"


def test_pod_crud_and_conflict():
    fc = FakeCluster()
    fc.create_pod(make_pod(hbm=1000, name="a"))
    with pytest.raises(ApiError) as e:
        fc.create_pod(make_pod(hbm=1000, name="a"))
    assert e.value.is_conflict
    with pytest.raises(ApiError) as e:
        fc.get_pod("default", "missing")
    assert e.value.is_not_found


def test_patch_merges_annotations_without_clobbering():
    fc = FakeCluster()
    fc.create_pod(make_pod(name="a", ann={"keep": "me"}))
    out = fc.patch_pod("default", "a",
                       {"metadata": {"annotations": {"new": "val"}}})
    assert out["metadata"]["annotations"] == {"keep": "me", "new": "val"}
    # None deletes (strategic merge semantics)
    out = fc.patch_pod("default", "a",
                       {"metadata": {"annotations": {"keep": None}}})
    assert out["metadata"]["annotations"] == {"new": "val"}


def test_bind_semantics():
    fc = FakeCluster()
    fc.add_tpu_node("n1", 1, 16000)
    created = fc.create_pod(make_pod(name="a"))
    with pytest.raises(ApiError):  # unknown node
        fc.bind_pod("default", "a", "ghost")
    with pytest.raises(ApiError) as e:  # uid precondition
        fc.bind_pod("default", "a", "n1", uid="wrong")
    assert e.value.is_conflict
    fc.bind_pod("default", "a", "n1", uid=created["metadata"]["uid"])
    assert fc.get_pod("default", "a")["spec"]["nodeName"] == "n1"
    with pytest.raises(ApiError) as e:  # double bind
        fc.bind_pod("default", "a", "n1")
    assert e.value.is_conflict


def test_resource_version_bumps():
    fc = FakeCluster()
    p1 = fc.create_pod(make_pod(name="a"))
    p2 = fc.patch_pod("default", "a", {"metadata": {"annotations": {"x": "1"}}})
    assert int(p2["metadata"]["resourceVersion"]) > \
        int(p1["metadata"]["resourceVersion"])


def test_watch_stream_delivers_lifecycle():
    fc = FakeCluster()
    stop = threading.Event()
    got = []

    def consume():
        for ev in fc.watch_pods(stop):
            got.append((ev.type, ev.object["metadata"]["name"]))
            if len(got) == 3:
                stop.set()

    t = threading.Thread(target=consume)
    t.start()
    fc.create_pod(make_pod(name="a"))
    fc.set_pod_phase("default", "a", "Succeeded")
    fc.delete_pod("default", "a")
    t.join(timeout=5)
    assert not t.is_alive()
    assert got == [("ADDED", "a"), ("MODIFIED", "a"), ("DELETED", "a")]


def test_watch_snapshot_isolated_from_store():
    fc = FakeCluster()
    stop = threading.Event()
    events = []
    t = threading.Thread(target=lambda: [
        (events.append(e), stop.set()) for e in fc.watch_pods(stop)])
    t.start()
    fc.create_pod(make_pod(name="a"))
    t.join(timeout=5)
    # mutating the delivered object must not corrupt the store
    events[0].object["metadata"]["name"] = "hacked"
    assert fc.get_pod("default", "a")["metadata"]["name"] == "a"


def test_strategic_merge_lists_replace():
    base = {"a": [1, 2], "b": {"c": 1}}
    assert strategic_merge(base, {"a": [3]}) == {"a": [3], "b": {"c": 1}}


def test_configmap_roundtrip():
    fc = FakeCluster()
    fc.set_configmap("kube-system", "unhealthy-tpu-n1", {"chips": "0,2"})
    cm = fc.get_configmap("kube-system", "unhealthy-tpu-n1")
    assert cm["data"]["chips"] == "0,2"
