"""Observability subsystem tests (ISSUE 4 tentpole acceptance).

The headline assertion: ONE bind exercised through the extender webhook
AND the device plugin yields ONE trace in /debug/traces containing
Filter, Prioritize, Bind and Allocate spans, joined across the
component boundary by the pod-annotation trace context — and
/inspect/explain/<pod> reports a per-node reason for every candidate
considered.
"""

import io
import json
import logging
import urllib.error
import urllib.request

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.deviceplugin import DevicePlugin, FakeEnumerator
from tpushare.extender.handlers import BindHandler, register_cache_gauges
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.k8s.stats import CountingCluster
from tpushare.obs import ExplainStore, FlightRecorder, Trace
from tpushare.obs.trace import TRACER


@pytest.fixture(autouse=True)
def fresh_tracer():
    """The tracer is process-global by design (every layer appends to
    the same traces); tests isolate by resetting around each one."""
    TRACER.enabled = True
    TRACER.reset()
    yield
    TRACER.enabled = True
    TRACER.reset()


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.add_tpu_node("n2", chips=2, hbm_per_chip_mib=8000)
    # CountingCluster: deployment parity — it is also what annotates
    # apiserver round-trips onto the active span
    cluster = CountingCluster(fc)
    cache = SchedulerCache(cluster)
    ctl = Controller(cluster, cache)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    server = ExtenderServer(cache, cluster, registry,
                            host="127.0.0.1", port=0)
    register_cache_gauges(registry, cache)
    port = server.start()
    yield fc, cache, server, f"http://127.0.0.1:{port}"
    server.stop()
    ctl.stop()


def post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, json.loads(r.read())


def run_cycle(fc, base, name="p", hbm=2000, node="n1"):
    """One full scheduler cycle over the webhook wire: filter ->
    prioritize -> bind to ``node``. Returns the created pod."""
    pod = fc.create_pod(make_pod(hbm=hbm, name=name))
    _, flt = post(f"{base}/tpushare-scheduler/filter",
                  {"Pod": pod, "NodeNames": ["n1", "n2"]})
    assert node in flt["NodeNames"]
    _, ranked = post(f"{base}/tpushare-scheduler/prioritize",
                     {"Pod": pod, "NodeNames": flt["NodeNames"]})
    assert {h["Host"] for h in ranked} == set(flt["NodeNames"])
    status, bind = post(f"{base}/tpushare-scheduler/bind", {
        "PodName": name, "PodNamespace": "default",
        "PodUID": pod["metadata"]["uid"], "Node": node})
    assert status == 200 and not bind.get("Error")
    return pod


# -- the tentpole acceptance test ---------------------------------------------

def test_single_bind_yields_one_trace_with_allocate_span(rig):
    fc, cache, server, base = rig
    pod = run_cycle(fc, base)
    bound = fc.get_pod("default", "p")
    ctx = bound["metadata"]["annotations"].get(contract.ANN_TRACE_CONTEXT)
    assert ctx, "bind must stamp the trace-context annotation"
    assert ctx.startswith(pod["metadata"]["uid"])

    # the device plugin (in production: another process on the node)
    # joins the SAME trace via the annotation channel
    plugin = DevicePlugin(fc, "n1", FakeEnumerator(4, 16000, "2x2"))
    result = plugin.allocate(hbm_mib=2000)
    assert result["pod"]["name"] == "p"
    assert result["trace_context"] == ctx

    status, dump = get(f"{base}/debug/traces")
    assert status == 200
    mine = [t for t in dump["traces"] if t["trace_id"] == ctx]
    assert len(mine) == 1, \
        f"expected ONE trace for the cycle, got {len(mine)}"
    trace = mine[0]
    names = [s["name"] for s in trace["spans"]]
    for phase in ("filter", "prioritize", "bind", "allocate"):
        assert phase in names, f"trace missing {phase} span: {names}"
    assert trace["outcome"] == "bound"
    # every span carries a duration; the cache scan child span rode along
    assert all(s["duration_ms"] is not None for s in trace["spans"])
    assert "cache.score_nodes" in names

    # the bind span recorded its apiserver round-trips as events
    bind_span = next(s for s in trace["spans"] if s["name"] == "bind")
    verbs = {e.get("verb") for e in bind_span.get("events", [])
             if e.get("event") == "api"}
    assert {"patch_pod", "bind_pod"} <= verbs
    assert bind_span["tags"]["node"] == "n1"
    assert bind_span["tags"]["chip_ids"]

    # the scan span says whether the memo served and which engine scanned
    scan = next(s for s in trace["spans"]
                if s["name"] == "cache.score_nodes")
    assert scan["tags"]["memo"] in ("hit", "miss")
    assert any(e.get("event") == "native_scan"
               for e in scan.get("events", []))


def test_explain_reports_every_candidate(rig):
    fc, cache, server, base = rig
    run_cycle(fc, base, name="exp", hbm=10000)  # n2's chips are 8000 MiB
    status, out = get(f"{base}/inspect/explain/default/exp")
    assert status == 200
    cycle = out["cycles"][-1]
    nodes = cycle["filter"]["nodes"]
    assert set(nodes) == {"n1", "n2"}, \
        "every candidate must get a verdict"
    assert nodes["n1"]["verdict"] == "ok"
    assert isinstance(nodes["n1"]["score"], int)
    assert nodes["n1"]["source"] in ("memo", "computed")
    # n2 (8000 MiB chips) can provably never host 10000 MiB: the
    # capacity index rejects it WITHOUT a visit, and the audit says so
    # truthfully — verdict skipped, with the excluding bucket recorded
    assert nodes["n2"]["verdict"] == "skipped"
    assert nodes["n2"]["reason"] == "index-pruned"
    assert "eligible_chips" in nodes["n2"]["bucket"]
    assert cycle["prioritize"]["best"] == "n1"
    assert cycle["bind"]["node"] == "n1"
    assert cycle["bind"]["outcome"] == "bound"
    assert cycle["bind"]["chip_ids"]
    # the cycle's trace id links the audit to /debug/traces
    assert cycle["trace_id"]

    # selector flexibility: bare name and uid both resolve
    for sel in ("exp", fc.get_pod("default", "exp")["metadata"]["uid"]):
        status, again = get(f"{base}/inspect/explain/{sel}")
        assert status == 200 and again["cycles"]
    # bare listing names the pod
    status, listing = get(f"{base}/inspect/explain")
    assert any(p["pod"].get("name") == "exp" for p in listing["pods"])
    # unknown pod -> 404 with a bounded-retention hint
    with pytest.raises(urllib.error.HTTPError) as e:
        get(f"{base}/inspect/explain/ghost-pod")
    assert e.value.code == 404


def test_explain_memo_provenance_on_second_cycle(rig):
    """Prioritize reuses Filter's scan via the memo; a second identical
    pod right after a bind shows the delta-invalidation AND
    equivalence-class story in the explain source fields: the bound
    node's stamp moved (recomputed), every untouched node is joined
    from the first pod's scan of the same request signature."""
    fc, cache, server, base = rig
    run_cycle(fc, base, name="p1", hbm=1000, node="n1")
    pod2 = fc.create_pod(make_pod(hbm=1000, name="p2"))
    post(f"{base}/tpushare-scheduler/filter",
         {"Pod": pod2, "NodeNames": ["n1", "n2"]})
    status, out = get(f"{base}/inspect/explain/default/p2")
    nodes = out["cycles"][-1]["filter"]["nodes"]
    # p1's bind mutated n1, so its class verdict is stale: recomputed.
    # n2 is untouched: p2 joins p1's scan instead of re-scanning.
    assert nodes["n1"]["source"] == "computed"
    assert nodes["n2"]["source"] == "eqclass"
    # same pod filtered again with nothing mutated: all served from
    # the pod's OWN memo (eqclass only fills pod-memo misses)
    post(f"{base}/tpushare-scheduler/filter",
         {"Pod": pod2, "NodeNames": ["n1", "n2"]})
    status, out = get(f"{base}/inspect/explain/default/p2")
    nodes = out["cycles"][-1]["filter"]["nodes"]
    assert all(v["source"] == "memo" for v in nodes.values())


def test_trace_superseded_and_finished_outcomes(rig):
    fc, cache, server, base = rig
    pod = fc.create_pod(make_pod(hbm=500, name="s"))
    body = {"Pod": pod, "NodeNames": ["n1", "n2"]}
    post(f"{base}/tpushare-scheduler/filter", body)
    post(f"{base}/tpushare-scheduler/filter", body)  # new cycle
    _, dump = get(f"{base}/debug/traces")
    superseded = [t for t in dump["traces"]
                  if t["outcome"] == "superseded"]
    assert len(superseded) == 1 and superseded[0]["cycle"] == 1
    post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "s", "PodNamespace": "default",
        "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    _, dump = get(f"{base}/debug/traces")
    bound = [t for t in dump["traces"] if t["outcome"] == "bound"]
    assert len(bound) == 1 and bound[0]["cycle"] == 2


def test_bind_failure_trace_and_explain(rig):
    fc, cache, server, base = rig
    pod = fc.create_pod(make_pod(hbm=99999, name="big"))
    with pytest.raises(urllib.error.HTTPError):
        post(f"{base}/tpushare-scheduler/bind", {
            "PodName": "big", "PodNamespace": "default",
            "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    _, dump = get(f"{base}/debug/traces")
    failed = [t for t in dump["traces"] if t["outcome"] == "bind_failed"]
    assert len(failed) == 1
    bind_span = next(s for s in failed[0]["spans"] if s["name"] == "bind")
    assert "no placement" in bind_span["tags"]["error"]
    _, out = get(f"{base}/inspect/explain/default/big")
    rec = out["cycles"][-1]["bind"]
    assert rec["outcome"] == "bind_failed"
    assert "no placement" in rec["error"]


def test_breaker_fastfail_recorded_in_explain():
    """A breaker-open refusal never reaches a node; the audit still says
    exactly why the bind failed."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=8000)
    cache = SchedulerCache(fc)
    cache.build_cache()

    class OpenBreaker:
        state = "open"

    explain = ExplainStore()
    handler = BindHandler(cache, fc, Registry(), breaker=OpenBreaker(),
                          explain=explain)
    out = handler.handle({"PodName": "x", "PodNamespace": "default",
                          "PodUID": "u-ff", "Node": "n1"})
    assert "circuit open" in out["Error"]
    rec = explain.get("u-ff")["cycles"][-1]["bind"]
    assert rec["outcome"] == "bind_failed"
    assert rec["error"].startswith("breaker fast-fail")
    # and the trace closed with the failure
    recorded = TRACER.recorder.traces()
    assert recorded and recorded[-1].outcome == "bind_failed"


def test_tracer_disabled_is_invisible(rig):
    fc, cache, server, base = rig
    TRACER.enabled = False
    run_cycle(fc, base, name="quiet")
    bound = fc.get_pod("default", "quiet")
    assert contract.ANN_TRACE_CONTEXT not in \
        bound["metadata"]["annotations"]
    _, dump = get(f"{base}/debug/traces")
    assert dump["recorded_total"] == 0 and dump["traces"] == []


def test_flight_recorder_ring_eviction_and_slow_pinning():
    rec = FlightRecorder(capacity=4, pinned_capacity=4, slow_ms=10.0)
    slow = Trace("slow-1", "slow", 1)
    slow.duration_ms = 25.0
    assert rec.record(slow) is True
    for i in range(10):
        fast = Trace(f"fast-{i}", "fast", 1)
        fast.duration_ms = 1.0
        assert rec.record(fast) is False
    dump = rec.dump()
    assert len(dump["traces"]) == 4  # ring rolled over
    assert dump["recorded_total"] == 11
    # the slow trace survived eviction via the pinned list
    assert [t["trace_id"] for t in dump["pinned"]] == ["slow-1"]
    assert rec.find("slow-1") is slow
    assert rec.find("fast-0") is None  # evicted
    assert rec.slowest(1)[0] is slow


def test_trace_metrics_exported(rig):
    fc, cache, server, base = rig
    run_cycle(fc, base, name="m")
    with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'tpushare_traces_total{outcome="recorded"}' in text
    assert "tpushare_allocate_seconds_bucket" in text  # registered


def test_remote_allocate_without_local_trace_records_own_trace():
    """Cross-process case: the plugin's process never opened the trace,
    so the Allocate span lands in a single-span trace under the SAME id
    (joinable offline on trace_id)."""
    TRACER.record_remote_span("uid-remote-7", "allocate", 3.2,
                              node="n9", chip_ids=[0])
    dump = TRACER.recorder.dump()
    assert len(dump["traces"]) == 1
    t = dump["traces"][0]
    assert t["trace_id"] == "uid-remote-7" and t["outcome"] == "remote"
    assert t["spans"][0]["name"] == "allocate"


def test_json_logger_stamps_trace_id():
    from tpushare.obs.logging import setup

    root = logging.getLogger()
    prev_handlers = root.handlers[:]
    prev_level = root.level
    buf = io.StringIO()
    handler = setup("INFO", json_format=True, stream=buf)
    try:
        trace = TRACER.begin_cycle("uid-log")
        with TRACER.root_span(trace, "filter"):
            logging.getLogger("tpushare.obs-test").info(
                "placing %s", "pod-a")
        logging.getLogger("tpushare.obs-test").warning("outside")
    finally:
        root.removeHandler(handler)
        for h in prev_handlers:
            root.addHandler(h)
        root.setLevel(prev_level)
    lines = [json.loads(l) for l in buf.getvalue().strip().splitlines()]
    inside = next(l for l in lines if l["msg"] == "placing pod-a")
    assert inside["trace_id"] == "uid-log-1"
    assert inside["level"] == "INFO"
    assert inside["logger"] == "tpushare.obs-test"
    outside = next(l for l in lines if l["msg"] == "outside")
    assert "trace_id" not in outside


def test_span_event_cap_bounds_memory():
    from tpushare.obs.trace import MAX_EVENTS_PER_SPAN, Span

    t = Trace("cap-1", "cap", 1)
    s = Span("bind")
    t.spans.append(s)
    for i in range(MAX_EVENTS_PER_SPAN + 50):
        s.annotate("api", verb="patch_pod", i=i)
    assert len(s.events) == MAX_EVENTS_PER_SPAN
    assert s.events_dropped == 50
    s.finish()
    d = s.to_dict(t)
    assert d["events_dropped"] == 50


def test_gang_members_share_leader_trace_in_explain():
    """Every gang member's explain record points at the LEADER's trace
    (one ABI v5 solve planned the whole gang) with source=gang — the
    audit must never present a follower as individually computed."""
    from tests.test_gang import gang_pod, make_slice_cluster

    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    server = ExtenderServer(cache, fc, Registry(),
                            host="127.0.0.1", port=0)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        nodes = ["s0h0", "s0h1", "s0h2", "s0h3", "lone"]
        for rank in (0, 1):
            pod = gang_pod(fc, f"gp{rank}", rank=rank)
            _, flt = post(f"{base}/tpushare-scheduler/filter",
                          {"Pod": pod, "NodeNames": nodes})
            assert len(flt["NodeNames"]) == 1, flt
        recs = []
        for name in ("gp0", "gp1"):
            status, out = get(f"{base}/inspect/explain/default/{name}")
            assert status == 200
            recs.append(out["cycles"][-1])
        leader, follower = recs
        # the leader's own trace IS the gang's planning trace
        assert leader["gang"]["leader_trace_id"] == leader["trace_id"]
        # the follower shares it (its own trace id differs)
        assert follower["gang"]["leader_trace_id"] == \
            leader["trace_id"]
        assert follower["trace_id"] != leader["trace_id"]
        for rank, rec in enumerate(recs):
            g = rec["gang"]
            assert g["source"] == "gang"
            assert g["gang_id"] == "g1" and g["rank"] == rank
            (verdict,) = rec["filter"]["nodes"].values()
            assert verdict["source"] == "gang"
            assert verdict["leader_trace_id"] == leader["trace_id"]
    finally:
        server.stop()
        ctl.stop()
