"""Native-engine sim loop (tpushare/sim/engine_loop.py): byte-identical
parity with the python spec path, knob invariance, conservation, and
the CLI/procs legs."""

import json

import pytest

from tpushare.sim import Fleet, TraceSpec, run_sim, synth_trace
from tpushare.sim.engine_loop import LoopKnobs, run_sim_native
from tpushare.sim.traces import DiurnalSpec, synth_diurnal


def _fleet(nodes=8):
    return Fleet.homogeneous(nodes, 4, 16384, (2, 2))


def _trace(seed=0, **kw):
    base = dict(n_pods=300, arrival_rate=4.0, mean_duration=30.0,
                multi_chip_fraction=0.3, seed=seed)
    base.update(kw)
    return synth_trace(TraceSpec(**base))


def _canon(report):
    return json.dumps(report.to_json(), sort_keys=True)


@pytest.mark.parametrize("seed", [0, 1, 7])
def test_scorecard_byte_identical_to_spec(seed):
    """The whole wind-tunnel claim: the native loop replays the exact
    binpack spec decisions — the full report (waits and all) is
    byte-identical, not merely close."""
    trace = _trace(seed)
    spec = run_sim(_fleet(), trace, "binpack")
    native, _stats = run_sim_native(_fleet(), trace)
    assert _canon(spec) == _canon(native)


def test_parity_under_saturation_and_pressure():
    trace = _trace(42, n_pods=300, arrival_rate=8.0, mean_duration=60.0)
    fleet_a, fleet_b = _fleet(3), _fleet(3)
    spec = run_sim(fleet_a, trace, "binpack")
    native, _ = run_sim_native(fleet_b, trace)
    assert spec.mean_wait > 0  # the pressure is real: pods queued
    assert _canon(spec) == _canon(native)


def test_parity_on_diurnal_trace():
    trace = synth_diurnal(DiurnalSpec(hours=1.0, period=1.0,
                                      base_rate=150.0, peak_rate=450.0,
                                      seed=5))
    spec = run_sim(_fleet(8), trace, "binpack")
    native, _ = run_sim_native(_fleet(8), trace)
    assert _canon(spec) == _canon(native)


@pytest.mark.parametrize("knobs", [
    LoopKnobs(index_scheme="pow2"),
    LoopKnobs(index_scheme="exact"),
    LoopKnobs(eqclass_lru=1),
    LoopKnobs(eqclass_lru=2, index_scheme="pow2"),
])
def test_throughput_knobs_never_change_decisions(knobs):
    """index_scheme and eqclass_lru are pure throughput knobs: any
    setting must reproduce the default-knob report byte-for-byte (the
    prune is superset-safe; eviction only refetches scores)."""
    trace = _trace(3)
    base, _ = run_sim_native(_fleet(), trace)
    tuned, _ = run_sim_native(_fleet(), trace, knobs)
    assert _canon(base) == _canon(tuned)


@pytest.mark.parametrize("knobs", [
    LoopKnobs(batch_window=0.2),
    LoopKnobs(scatter_util_pct=80.0),
    LoopKnobs(defrag_budget=2, defrag_period=5.0),
    LoopKnobs(batch_window=0.1, scatter_util_pct=70.0, defrag_budget=1),
])
def test_quality_knobs_conserve_pods(knobs):
    """Batching, scatter gating and defrag change WHICH placements
    happen, never the accounting: every pod is placed or pending, the
    report stays internally consistent, and the run is deterministic."""
    trace = _trace(2, n_pods=250, arrival_rate=6.0)
    r1, s1 = run_sim_native(_fleet(4), trace, knobs)
    r2, _ = run_sim_native(_fleet(4), trace, knobs)
    assert r1.placed + r1.never_placed == r1.pods
    assert 0 < r1.util_pct <= 100
    assert _canon(r1) == _canon(r2)
    assert s1["engine"] in ("native", "python-fallback")


def test_batch_window_coalesces_waves():
    """With a wide window and a bursty trace the loop must actually
    batch (the flush counter moves) — guarding against the window
    silently degenerating to per-pod waves."""
    trace = _trace(4, n_pods=200, arrival_rate=50.0)
    _, stats = run_sim_native(_fleet(), trace,
                              LoopKnobs(batch_window=0.5))
    assert stats["batch_groups"] > 0
    batched = stats["batch_pods_placed"] + stats["batch_pods_pending"]
    assert batched > stats["batch_groups"]  # >1 pod per group on average


def test_stats_expose_arena_delta_accounting():
    trace = _trace(0)
    _, stats = run_sim_native(_fleet(), trace)
    assert stats["knobs"] == {
        "batch_window": 0.0, "index_scheme": "off", "eqclass_lru": 32,
        "defrag_budget": 0, "defrag_period": 4.0,
        "scatter_util_pct": 0.0}
    arena = stats["arena"]
    assert arena["nodes"] == 8
    # the tentpole: events delta-update slots, they don't rebuild the
    # arena — appends stop at the initial fleet synthesis
    assert arena["slot_updates"] > 0
    assert stats["delta_refreshes"] > 0


def test_defrag_budget_actually_migrates():
    """A nonzero defrag budget on a churning, fragmented replay must
    perform live migrations (stats move) while conserving accounting."""
    trace = _trace(6, n_pods=300, arrival_rate=6.0, mean_duration=50.0,
                   multi_chip_fraction=0.4)
    report, stats = run_sim_native(_fleet(4), trace,
                                   LoopKnobs(defrag_budget=2,
                                             defrag_period=2.0))
    assert stats["defrag_passes"] > 0
    assert stats["defrag_moves"] > 0
    assert report.placed + report.never_placed == report.pods


def test_replay_once_native_equals_python():
    """The --procs determinism seam (satellite 1): one payload, both
    engines, same canonical scorecard string."""
    from tpushare.sim.procs import replay_once
    payload = {
        "nodes": 8, "chips": 4, "hbm": 16384, "mesh": [2, 2],
        "policy": "binpack", "preempt": "off",
        "spec": {"n_pods": 200, "arrival_rate": 4.0,
                 "mean_duration": 30.0, "multi_chip_fraction": 0.3,
                 "high_priority_fraction": 0.0, "seed": 9}}
    py = replay_once(dict(payload, engine="python"))
    nv = replay_once(dict(payload, engine="native"))
    legacy = replay_once(payload)  # absent key = python (old payloads)
    assert py == nv == legacy


def test_cli_engine_native_leg(capsys):
    from tpushare.sim.__main__ import main
    assert main(["--policy", "binpack"]) == 0
    spec = json.loads(capsys.readouterr().out)
    assert main(["--engine", "native", "--stats"]) == 0
    native = json.loads(capsys.readouterr().out)
    assert native.pop("engine") == "native"
    stats = native.pop("engine_stats")
    assert stats["arrivals"] == native["pods"]
    assert json.dumps(spec, sort_keys=True) == \
        json.dumps(native, sort_keys=True)


def test_cli_procs_native_leg(capsys):
    """Two spawned interpreters replaying through the native loop must
    byte-agree; small trace keeps the spawns cheap."""
    from tpushare.sim.__main__ import main
    rc = main(["--engine", "native", "--procs", "2", "--pods", "80"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["engine"] == "native"
    assert out["scorecards_identical"] is True


def test_cli_help_is_golden():
    """Satellite 6: the grouped --help text is pinned. Regenerate with
    COLUMNS=100 python -m tpushare.sim --help > tests/data/sim_help.txt
    when flags change ON PURPOSE."""
    import os
    import subprocess
    import sys
    here = os.path.dirname(__file__)
    want = open(os.path.join(here, "data", "sim_help.txt")).read()
    env = dict(os.environ, COLUMNS="100", JAX_PLATFORMS="cpu")
    got = subprocess.run(
        [sys.executable, "-m", "tpushare.sim", "--help"],
        capture_output=True, text=True, env=env, check=True).stdout
    assert got == want
    for group in ("trace:", "engine:", "sweep modes:", "output:"):
        assert group in got


def test_knob_validation():
    with pytest.raises(ValueError):
        LoopKnobs(index_scheme="bogus")
    with pytest.raises(ValueError):
        LoopKnobs(eqclass_lru=0)
    with pytest.raises(ValueError):
        LoopKnobs(batch_window=-0.1)
