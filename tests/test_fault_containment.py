"""Write-path fault containment: retry policy, circuit breaker, deadline
propagation, degraded mode, and the crash-consistency seams.

The reference ships zero fault injection and no write-retry policy
(SURVEY §5.3) — every transient 5xx/timeout is a terminal bind failure.
These tests pin down the containment layer's contracts:

- retryable-status classification (409 NEVER retried at transport level,
  429 honors Retry-After, 5xx/network within budget);
- deadline propagation (a bind never retries past the caller's patience);
- breaker state machine (closed -> open -> half-open -> closed) and the
  degraded-mode behavior of each scheduling verb while open;
- the transport layer's POST replay safety (k8s/incluster.py);
- crash-consistency: an interrupted bind is healed by rebind or by
  gc_stale_assignments + resync_once, and duplicate bind deliveries stay
  idempotent through breaker transitions.
"""

import http.client
import threading
import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import AllocationError, SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.handlers import BindHandler
from tpushare.extender.metrics import Registry
from tpushare.k8s import (
    ApiError,
    BreakerOpenError,
    ChaosCluster,
    CircuitBreaker,
    FakeCluster,
    RetryPolicy,
    RetryingCluster,
    harden,
    request_deadline,
)
from tpushare.k8s.breaker import CLOSED, HALF_OPEN, OPEN
from tpushare.k8s.retry import DeadlineExceeded, deadline_remaining


def no_sleep_policy(**kw) -> RetryPolicy:
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("base_s", 0.001)
    kw.setdefault("cap_s", 0.002)
    return RetryPolicy(**kw)


def cluster_with_node(name="n1", chips=4, hbm=16000, seed=0):
    fc = FakeCluster()
    fc.add_tpu_node(name, chips=chips, hbm_per_chip_mib=hbm)
    return fc, ChaosCluster(fc, seed=seed)


# -- retry policy -------------------------------------------------------------

def test_retry_heals_transient_5xx_within_budget():
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, no_sleep_policy(max_attempts=4))
    chaos.fail("get_node", status=503, times=3)
    assert cl.get_node("n1")["metadata"]["name"] == "n1"
    assert chaos.injected["get_node"] == 3


def test_retry_budget_exhaustion_surfaces_last_error():
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, no_sleep_policy(max_attempts=3))
    chaos.fail("get_node", status=500, times=None)
    with pytest.raises(ApiError) as ei:
        cl.get_node("n1")
    assert ei.value.status == 500
    # total attempts == budget, not budget + 1
    assert chaos.injected["get_node"] == 3


def test_409_is_never_retried_at_transport_level():
    """A conflict is a correctness signal (another writer moved the
    object); replaying the same body would overwrite the winner."""
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, no_sleep_policy())
    chaos.fail("patch_pod", status=409, times=None)
    fc.create_pod(make_pod(hbm=100, name="p"))
    with pytest.raises(ApiError) as ei:
        cl.patch_pod("default", "p", {"metadata": {}})
    assert ei.value.status == 409
    assert chaos.injected["patch_pod"] == 1  # exactly one attempt


def test_4xx_is_not_retried():
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, no_sleep_policy())
    chaos.fail("get_pod", status=404, times=None)
    with pytest.raises(ApiError):
        cl.get_pod("default", "nope")
    assert chaos.injected["get_pod"] == 1


def test_429_honors_retry_after_over_backoff_curve():
    fc, chaos = cluster_with_node()
    slept = []
    cl = RetryingCluster(chaos, RetryPolicy(
        max_attempts=3, base_s=50.0, cap_s=50.0,  # curve would sleep ~50s
        sleep=slept.append))
    chaos.fail("get_node", status=429, retry_after=0.2, times=1)
    cl.get_node("n1")
    assert slept == [0.2]


def test_network_error_status_0_is_retried():
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, no_sleep_policy())
    chaos.fail("get_node", status=0, times=2)
    assert cl.get_node("n1")["metadata"]["name"] == "n1"


# -- deadline propagation -----------------------------------------------------

def test_deadline_stops_retries_before_caller_gives_up():
    fc, chaos = cluster_with_node()
    slept = []
    cl = RetryingCluster(chaos, RetryPolicy(
        max_attempts=10, base_s=5.0, cap_s=5.0, sleep=slept.append))
    chaos.fail("get_node", status=503, times=None)
    t0 = time.monotonic()
    with request_deadline(0.05):
        with pytest.raises(DeadlineExceeded):
            cl.get_node("n1")
    # no multi-second sleep happened: the loop saw the 5s backoff would
    # outlive the 50ms deadline and gave up immediately
    assert time.monotonic() - t0 < 1.0
    assert slept == []


def test_nested_deadline_scopes_only_shorten():
    with request_deadline(10.0):
        outer = deadline_remaining()
        with request_deadline(0.01):
            inner = deadline_remaining()
            assert inner < 1.0
        with request_deadline(60.0):
            # inner scope cannot outlive the caller's patience
            assert deadline_remaining() <= outer
    assert deadline_remaining() is None


def test_deadline_exceeded_is_not_retryable_itself():
    from tpushare.k8s.retry import is_retryable
    assert not is_retryable(DeadlineExceeded("x"))
    assert not is_retryable(BreakerOpenError("x"))
    assert is_retryable(ApiError(503))
    assert is_retryable(ApiError(0))
    assert is_retryable(ApiError(429))
    assert not is_retryable(ApiError(409))
    assert not is_retryable(ApiError(404))


# -- circuit breaker ----------------------------------------------------------

def fast_breaker(**kw) -> CircuitBreaker:
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("reset_timeout_s", 0.05)
    kw.setdefault("probe_successes", 2)
    return CircuitBreaker(**kw)


def test_breaker_opens_on_consecutive_failures_and_fast_fails():
    fc, chaos = cluster_with_node()
    br = fast_breaker()
    cl = harden(chaos, breaker=br, policy=no_sleep_policy(max_attempts=1))
    chaos.fail("get_node", status=500, times=None)
    for _ in range(3):
        with pytest.raises(ApiError):
            cl.get_node("n1")
    assert br.state == OPEN
    injected_before = chaos.injected["get_node"]
    with pytest.raises(BreakerOpenError):
        cl.get_node("n1")
    # the fast-fail issued ZERO round-trips
    assert chaos.injected["get_node"] == injected_before


def test_breaker_half_open_probe_closes_on_success():
    fc, chaos = cluster_with_node()
    br = fast_breaker()
    cl = harden(chaos, breaker=br, policy=no_sleep_policy(max_attempts=1))
    chaos.fail("get_node", status=500, times=3)
    for _ in range(3):
        with pytest.raises(ApiError):
            cl.get_node("n1")
    assert br.state == OPEN
    time.sleep(0.06)
    assert br.state == HALF_OPEN
    cl.get_node("n1")
    cl.get_node("n1")
    assert br.state == CLOSED


def test_breaker_half_open_failure_reopens():
    br = fast_breaker()
    for _ in range(3):
        br.record_failure()
    assert br.state == OPEN
    time.sleep(0.06)
    assert br.state == HALF_OPEN
    assert br.allow()
    br.record_failure()
    assert br.state == OPEN


def test_409_counts_as_success_for_the_breaker():
    """404/409 are successful communication carrying a verdict — a storm
    of optimistic-lock losers must not open the circuit."""
    fc, chaos = cluster_with_node()
    br = fast_breaker(failure_threshold=2)
    cl = harden(chaos, breaker=br, policy=no_sleep_policy())
    chaos.fail("patch_node", status=409, times=None)
    for _ in range(6):
        with pytest.raises(ApiError):
            cl.patch_node("n1", {"metadata": {"resourceVersion": "x"}})
    assert br.state == CLOSED


def test_breaker_open_error_is_not_retried():
    fc, chaos = cluster_with_node()
    br = fast_breaker()
    cl = harden(chaos, breaker=br, policy=no_sleep_policy(max_attempts=8))
    for _ in range(3):
        br.record_failure()
    injected_before = chaos.injected["get_node"]
    with pytest.raises(BreakerOpenError):
        cl.get_node("n1")
    assert chaos.injected["get_node"] == injected_before


def test_watches_bypass_the_breaker():
    fc, chaos = cluster_with_node()
    br = fast_breaker()
    cl = harden(chaos, breaker=br)
    for _ in range(3):
        br.record_failure()
    assert br.state == OPEN
    stop = threading.Event()
    it = cl.watch_pods(stop)  # must NOT raise BreakerOpenError
    stop.set()
    assert it is not None


# -- chaos extensions (the harness the soak depends on) -----------------------

def test_chaos_fail_carries_retry_after():
    fc, chaos = cluster_with_node()
    chaos.fail("get_node", status=429, retry_after=7.5)
    with pytest.raises(ApiError) as ei:
        chaos.get_node("n1")
    assert ei.value.status == 429
    assert ei.value.retry_after == 7.5


def test_chaos_brownout_ramps_and_dies():
    fc, chaos = cluster_with_node()
    t = [0.0]
    chaos.brownout("get_node", seconds=10.0, peak=1.0,
                   clock=lambda: t[0])
    t[0] = 5.0  # crest: p == peak == 1.0 -> must fire
    with pytest.raises(ApiError):
        chaos.get_node("n1")
    assert chaos.injected["get_node"] == 1
    t[0] = 11.0  # window over: rule dead, calls pass, count unchanged
    chaos.get_node("n1")
    chaos.get_node("n1")
    assert chaos.injected["get_node"] == 1


def test_chaos_brownout_edges_are_quiet():
    fc, chaos = cluster_with_node(seed=5)
    t = [0.0]
    chaos.brownout("get_node", seconds=10.0, peak=0.9,
                   clock=lambda: t[0])
    # at t=0 the ramp is exactly 0: never fires
    for _ in range(50):
        chaos.get_node("n1")
    assert chaos.injected["get_node"] == 0


# -- transport replay safety (k8s/incluster.py) -------------------------------

class _DeadConn:
    """A reused connection that PASSES the recv-before-send staleness
    probe (its socket is real and quiet) and then dies mid-request: the
    probe-miss race window — a close racing the request itself — that
    the replay-safety rule exists for."""

    timeout = None

    def __init__(self):
        import socket
        self.sock, self._peer = socket.socketpair()

    def request(self, *a, **k):
        raise http.client.CannotSendRequest("died mid-request")

    def close(self):
        self.sock.close()
        self._peer.close()


class _GoodResp:
    status = 200
    will_close = True

    def read(self):
        return b"{}"

    def getheader(self, name):
        return None


class _GoodConn:
    sock = None
    timeout = None

    def __init__(self, log):
        self._log = log

    def request(self, method, path, body=None, headers=None):
        self._log.append(method)

    def getresponse(self):
        return _GoodResp()

    def close(self):
        pass


def _pool_with_stale_conn(replay_log):
    from tpushare.k8s.incluster import _ConnPool
    pool = _ConnPool("h", 80, False, None)
    pool._idle.append(_DeadConn())
    pool._new_conn = lambda timeout: _GoodConn(replay_log)
    return pool


def test_pool_replays_idempotent_verbs_on_stale_connection():
    for method in ("GET", "PUT", "PATCH", "DELETE"):
        log = []
        pool = _pool_with_stale_conn(log)
        status, data, retry_after = pool.request(method, "/x", None, {}, 1.0)
        assert status == 200 and log == [method]


def test_pool_never_replays_post_on_stale_connection():
    """The satellite fix: a binding/event POST whose response was lost
    may have LANDED — a blind transport resend would duplicate it. The
    ambiguous error surfaces and the retry policy (whose call sites
    tolerate duplicates) decides."""
    log = []
    pool = _pool_with_stale_conn(log)
    with pytest.raises(http.client.HTTPException):
        pool.request("POST", "/x", b"{}", {}, 1.0)
    assert log == []  # nothing was resent


# -- crash-consistency seams --------------------------------------------------

def test_interrupted_bind_with_failed_rollback_heals_on_rebind():
    """Bind interrupted between placement PATCH and binding POST, with
    the rollback ALSO failing (the extender 'crashed' mid-seam): the pod
    is left annotated-but-unbound, the cache holds nothing, and the
    scheduler's retry overwrites the stale annotations and binds
    cleanly."""
    fc, chaos = cluster_with_node()
    cache = SchedulerCache(chaos)
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    chaos.fail("bind_pod", status=500, times=1)
    chaos.fail("get_pod", status=500, times=None)  # rollback blocked
    with pytest.raises(AllocationError):
        info.allocate(pod, chaos)
    chaos.clear()
    stranded = fc.get_pod("default", "p")
    assert contract.chip_ids_from_annotations(stranded) is not None
    assert not stranded["spec"].get("nodeName")
    assert info.describe()["used_hbm_mib"] == 0  # reservation rolled back
    # the scheduler retries: the seam heals by overwrite
    placement = info.allocate(stranded, chaos)
    live = fc.get_pod("default", "p")
    assert live["spec"]["nodeName"] == "n1"
    assert contract.chip_ids_from_annotations(live) == placement.chip_ids
    assert info.describe()["used_hbm_mib"] == 2048


def _plugin_for(fc, node="n1", chips=4, hbm=16000):
    from tpushare.deviceplugin.enumerator import FakeEnumerator
    from tpushare.deviceplugin.plugin import DevicePlugin
    return DevicePlugin(fc, node, FakeEnumerator(chips, hbm))


def test_gc_plus_resync_heal_bound_never_started_placement():
    """A bound pod whose container start never reached Allocate holds
    its chips forever without gc; gc_stale_assignments reclaims the
    placement (CAS) and resync_once frees the chips in the cache."""
    fc, chaos = cluster_with_node()
    cache = SchedulerCache(chaos)
    ctl = Controller(chaos, cache)
    ctl.build_cache()
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="stuck"))
    info.allocate(pod, chaos)
    # deliver the bound pod to the cache the way the watch would (the
    # controller isn't started in this test)
    ctl._sync_pod("default/stuck")
    assert cache.describe()["used_hbm_mib"] == 2048
    plugin = _plugin_for(fc)
    # boundary timing: a placement exactly AT the window edge is kept
    # (<=), one past it is reclaimed. The annotation timestamp is ns.
    live = fc.get_pod("default", "stuck")
    t = contract.assume_time_from_annotations(live)
    age_s = (time.time_ns() - t) / 1e9
    assert plugin.gc_stale_assignments(
        max_pending_seconds=age_s + 30.0) == 0  # inside window: kept
    assert plugin.gc_stale_assignments(
        max_pending_seconds=0.0) == 1  # past window: reclaimed
    live = fc.get_pod("default", "stuck")
    assert contract.chip_ids_from_annotations(live) is None
    # resync observes the lost placement and frees the chips
    ctl.resync_once()
    # resync enqueues; process synchronously for determinism
    ctl._sync_pod("default/stuck")
    assert cache.describe()["used_hbm_mib"] == 0


def test_gc_loses_cas_race_to_late_allocate():
    """gc re-reads and CAS-PUTs; a late Allocate that flipped
    assigned=true in between must win (the placement stands)."""
    fc, chaos = cluster_with_node()
    cache = SchedulerCache(chaos)
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="racy"))
    info.allocate(pod, chaos)
    plugin = _plugin_for(fc)
    out = plugin.allocate(hbm_mib=2048)  # the late container start
    assert out["pod"]["name"] == "racy"
    assert plugin.gc_stale_assignments(max_pending_seconds=0.0) == 0
    live = fc.get_pod("default", "racy")
    assert contract.chip_ids_from_annotations(live) is not None
    assert contract.is_assigned(live)


def test_duplicate_bind_delivery_during_half_open_stays_idempotent():
    """A duplicate bind webhook delivery arriving while the breaker is
    half-open (recovering from a brownout) must be recognized as
    already-bound-as-requested: idempotent success, no second write
    storm, no failure event."""
    fc, chaos = cluster_with_node()
    br = fast_breaker()
    cl = harden(chaos, breaker=br,
                policy=no_sleep_policy(max_attempts=2))
    cache = SchedulerCache(cl)
    registry = Registry()
    binder = BindHandler(cache, cl, registry, breaker=br)
    pod = fc.create_pod(make_pod(hbm=2048, name="dup"))
    args = {"PodNamespace": "default", "PodName": "dup",
            "PodUID": pod["metadata"]["uid"], "Node": "n1"}
    assert binder.handle(args) == {"Error": ""}
    used_before = cache.describe()["used_hbm_mib"]
    # brownout trips the breaker, then cools into half-open
    for _ in range(3):
        br.record_failure()
    assert br.state == OPEN
    time.sleep(0.06)
    assert br.state == HALF_OPEN
    out = binder.handle(args)  # duplicate delivery
    assert out == {"Error": ""}  # idempotent success, not a failure
    assert cache.describe()["used_hbm_mib"] == used_before
    live = fc.get_pod("default", "dup")
    assert live["spec"]["nodeName"] == "n1"


def test_bind_fails_fast_with_distinct_error_while_open():
    fc, chaos = cluster_with_node()
    br = fast_breaker(reset_timeout_s=60.0)
    cl = harden(chaos, breaker=br)
    cache = SchedulerCache(cl)
    cache.build_cache()
    binder = BindHandler(cache, cl, Registry(), breaker=br)
    for _ in range(3):
        br.record_failure()
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    t0 = time.monotonic()
    out = binder.handle({"PodNamespace": "default", "PodName": "p",
                         "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    assert "circuit open" in out["Error"]
    assert time.monotonic() - t0 < 0.5  # no webhook-timeout burn
    # nothing was reserved or written
    assert cache.describe()["used_hbm_mib"] == 0
    assert not fc.get_pod("default", "p")["spec"].get("nodeName")


def test_bind_deadline_exceeded_counted_and_rolled_back():
    from tpushare.extender.handlers import BIND_DEADLINE_EXCEEDED
    fc, chaos = cluster_with_node()
    cl = RetryingCluster(chaos, RetryPolicy(
        max_attempts=5, base_s=5.0, cap_s=5.0, sleep=lambda s: None))
    cache = SchedulerCache(cl)
    cache.build_cache()
    binder = BindHandler(cache, cl, Registry())
    chaos.fail("bind_pod", status=503, times=None)
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    before = BIND_DEADLINE_EXCEEDED.value
    with request_deadline(0.05):
        out = binder.handle({"PodNamespace": "default", "PodName": "p",
                             "PodUID": pod["metadata"]["uid"],
                             "Node": "n1"})
    assert out["Error"]
    assert BIND_DEADLINE_EXCEEDED.value == before + 1
    # clean failure: reservation rolled back, annotations reverted
    assert cache.describe()["used_hbm_mib"] == 0
    live = fc.get_pod("default", "p")
    assert contract.chip_ids_from_annotations(live) is None


# -- /healthz + /readyz -------------------------------------------------------

def test_readyz_gates_on_cache_build_and_reports_degraded_state():
    import json
    import urllib.error
    import urllib.request

    from tpushare.extender.server import ExtenderServer
    from tpushare.k8s import Informer

    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16000)
    br = fast_breaker(reset_timeout_s=60.0)
    cl = harden(fc, breaker=br)
    informer = Informer(cl).start()
    cache = SchedulerCache(cl, node_lister=informer.nodes)
    srv = ExtenderServer(cache, cl, host="127.0.0.1", port=0,
                         informer=informer, breaker=br)
    port = srv.start()
    base = f"http://127.0.0.1:{port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz", timeout=5)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["ready"] is False and body["cache_built"] is False

        cache.build_cache()
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            body = json.loads(r.read())
        assert r.status == 200 and body["ready"] is True
        assert body["informer_synced"] is True
        assert body["breaker_state"] == "closed"
        assert body["informer_staleness_s"] is not None

        # liveness stays dumb: still 200 whatever the breaker says
        for _ in range(3):
            br.record_failure()
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert r.status == 200
        # readiness stays 200 too (degraded mode still serves Filter)
        # but reports the open circuit
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            body = json.loads(r.read())
        assert body["breaker_state"] == "open" and body["degraded"] is True

        # /metrics exposes the breaker gauge
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            exposed = r.read().decode()
        assert "tpushare_breaker_state 2.0" in exposed
    finally:
        srv.stop()
        informer.stop()
