"""WorkQueue contract tests (dedup, dirty-reprocess, retry backoff)."""

import threading
import time

from tpushare.controller import WorkQueue


def test_dedup_while_queued():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert q.get(0.1) == "a"
    assert q.get(0.1) == "b"
    assert q.get(0.05) is None


def test_dirty_reprocess_after_done():
    q = WorkQueue()
    q.add("a")
    key = q.get(0.1)
    q.add("a")  # re-added while processing -> must run again after done
    assert q.get(0.05) is None
    q.done(key)
    assert q.get(0.1) == "a"


def test_retry_backoff_and_cap():
    q = WorkQueue(base_delay=0.01, max_delay=0.05, max_retries=2)
    assert q.retry("k") is True
    t0 = time.monotonic()
    assert q.get(1.0) == "k"
    assert time.monotonic() - t0 >= 0.005
    q.done("k")
    assert q.retry("k") is True
    assert q.get(1.0) == "k"
    q.done("k")
    assert q.retry("k") is False  # cap reached -> dropped


def test_shutdown_unblocks_getters():
    q = WorkQueue()
    out = []
    t = threading.Thread(target=lambda: out.append(q.get()))
    t.start()
    q.shut_down()
    t.join(timeout=2)
    assert not t.is_alive() and out == [None]


def test_forget_resets_retry_count():
    q = WorkQueue(max_retries=1)
    assert q.retry("k") is True
    q.forget("k")
    assert q.retry("k") is True  # counter was reset
