"""Fleet-scale concurrency tests: sharded cache locks, per-node memo
delta invalidation, the parallel native fleet scan, and the native-path
regression guard.

The tentpole claims are only real if falsifiable:

- different pods' Filter/Prioritize/Bind proceed concurrently without a
  cache-wide lock — proven by a storm that must finish under a watchdog
  (no deadlock) with zero oversubscription on the FAKE APISERVER TRUTH
  (not the cache's own view);
- an allocate on node A invalidates only A's memoized score — proven by
  the delta-invalidation counters and by reuse staying > 0 under a storm
  of concurrent binds;
- no memoized score is ever served for a node state it was not computed
  from — proven under TPUSHARE_MEMO_VERIFY, which recomputes every
  memo-served score and counts disagreements;
- the sharded parallel scan returns bit-identical results to the serial
  single-call scan;
- the native engine (not the silent Python fallback) actually scored a
  fleet in this test session — the g++-regression tripwire.
"""

import threading
import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import (
    MEMO_DELTA_INVALIDATIONS, MEMO_NODE_SCORES, MEMO_REQUESTS,
    MEMO_STALE_SERVES, AllocationError, SchedulerCache)
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.extender.handlers import (
    BindHandler, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.k8s import FakeCluster

HBM = 16000


def fleet(n_nodes=4, chips=4, mesh="2x2"):
    fc = FakeCluster()
    names = [f"n{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=chips, hbm_per_chip_mib=HBM, mesh=mesh)
    return fc, names


def rig(fc):
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    return (cache,
            FilterHandler(cache, registry),
            PrioritizeHandler(cache, registry),
            BindHandler(cache, fc, registry))


# -- native-path regression guard (CI satellite) ------------------------------

def test_native_path_scored_a_fleet(native_engine):
    """Tier-1 tripwire: the native engine must be loadable AND actually
    score a fleet — a missing compiler silently degrading every scan to
    the O(nodes) Python fallback is a perf regression this test turns
    into a red build."""
    assert native_engine.available(), \
        "native engine unavailable (g++/.so build failed?) — fleet " \
        "scans would silently run the Python fallback; see " \
        "tpushare_native_fallback_total"
    assert native_engine.abi_version() is not None
    from tpushare.core.chips import ChipView
    from tpushare.core.placement import PlacementRequest
    from tpushare.core.topology import MeshTopology

    topo = MeshTopology((2, 2))
    node = ([ChipView(idx=i, coords=topo.coords(i), total_hbm_mib=HBM,
                      used_hbm_mib=0, healthy=True) for i in range(4)],
            topo)
    before = native_engine.NATIVE_FLEET_SCANS.get("score", "native") + \
        native_engine.NATIVE_FLEET_SCANS.get("score", "native_parallel")
    scores = native_engine.score_fleet([node] * 8,
                                       PlacementRequest(hbm_mib=1024))
    after = native_engine.NATIVE_FLEET_SCANS.get("score", "native") + \
        native_engine.NATIVE_FLEET_SCANS.get("score", "native_parallel")
    assert all(s is not None for s in scores)
    assert after == before + 1, \
        "fleet scan did not run on the native engine"


def test_native_cycle_scored_a_fleet(native_engine):
    """Tier-1 tripwire (ABI v4 sibling of test_native_path_scored_a_fleet):
    the loaded engine must carry the v4 end-to-end cycle entry point AND
    a SchedulerCache scoring pass must actually run it — cycles silently
    falling back to the v3 score-then-reselect path (stale .so, broken
    symbol binding) is a perf regression this test turns into a red
    build. TPUSHARE_NO_CYCLE remains the deliberate opt-out; this test
    asserts the DEFAULT path."""
    assert native_engine.available()
    abi = native_engine.abi_version()
    assert abi is not None and abi >= 4, \
        f"loaded .so is ABI {abi} (< 4): tpushare_cycle_fleet is " \
        f"missing and every cycle runs the v3 score-then-reselect path"
    assert native_engine.cycle_supported(), \
        "cycle_fleet symbol not bound — cycles silently run v3; see " \
        "tpushare_cycle_calls_total{engine}"

    fc, names = fleet(n_nodes=4)
    cache, flt, prio, _bind = rig(fc)
    pod = fc.create_pod(make_pod(hbm=2048))
    before = native_engine.CYCLE_CALLS.get("native")
    ok = flt.handle({"Pod": pod, "NodeNames": names})["NodeNames"]
    assert ok == names
    assert native_engine.CYCLE_CALLS.get("native") == before + 1, \
        "score_nodes did not run a native end-to-end cycle"
    # the cycle's placements seed Prioritize's best-placement memo with
    # ZERO extra engine calls — and the seed must match a from-scratch
    # selection of the same state
    prio.handle({"Pod": pod, "NodeNames": ok})
    hint, stamp, spec = cache.placement_hint_stamped(pod, ok[0])
    assert hint is not None and stamp is not None and spec is False
    from tpushare.core.placement import select_chips_py

    info = cache.get_node_info(ok[0])
    want = select_chips_py(info.snapshot(), info.topology,
                           request_from_pod(pod))
    assert (hint.chip_ids, hint.box, hint.origin, hint.score) == \
        (want.chip_ids, want.box, want.origin, want.score)


def test_no_cycle_escape_hatch_matches_default(native_engine, monkeypatch):
    """TPUSHARE_NO_CYCLE forces the v3 score-then-reselect path; the
    verdicts must be byte-identical to the default cycle path and the
    compatibility engine must be attributed in the cycle counter."""
    fc, names = fleet(n_nodes=6)
    pod = make_pod(hbm=4096)
    req = request_from_pod(pod)
    cache_a, flt_a, _p, _b = rig(fc)
    scores_a, errors_a = cache_a.score_nodes(pod, req, names)

    monkeypatch.setenv("TPUSHARE_NO_CYCLE", "1")
    v3_before = native_engine.CYCLE_CALLS.get("v3")
    cache_b = SchedulerCache(fc)
    cache_b.build_cache()
    scores_b, errors_b = cache_b.score_nodes(pod, req, names)
    assert native_engine.CYCLE_CALLS.get("v3") == v3_before + 1
    assert (scores_a, errors_a) == (scores_b, errors_b)


def test_parallel_scan_matches_serial(native_engine):
    """The sharded scan is a pure partition of the serial one: same
    fleet, same request -> identical scores and fit verdicts, with the
    parallel engine actually engaged (counter-verified)."""
    if not native_engine.available():
        pytest.skip("native engine unavailable")
    from tpushare.core.chips import ChipView
    from tpushare.core.placement import PlacementRequest
    from tpushare.core.topology import MeshTopology

    topo = MeshTopology((2, 2))
    nodes = []
    for i in range(1400):  # > 2 * _MIN_SHARD so sharding engages
        used = (i * 977) % HBM  # deterministic variety
        nodes.append((
            [ChipView(idx=j, coords=topo.coords(j), total_hbm_mib=HBM,
                      used_hbm_mib=(used + j * 1111) % HBM, healthy=True)
             for j in range(4)], topo))
    req = PlacementRequest(hbm_mib=4096, chip_count=4, topology=(2, 2))
    serial = native_engine.score_fleet(nodes, req, workers=1)
    par_before = native_engine.NATIVE_FLEET_SCANS.get(
        "score", "native_parallel")
    parallel = native_engine.score_fleet(nodes, req, workers=4)
    assert native_engine.NATIVE_FLEET_SCANS.get(
        "score", "native_parallel") == par_before + 1
    assert parallel == serial
    fits_serial = native_engine.fits_fleet(nodes, req, workers=1)
    fits_parallel = native_engine.fits_fleet(nodes, req, workers=4)
    assert fits_parallel == fits_serial
    assert fits_serial == [s is not None for s in serial]


# -- per-node memo: delta invalidation + LRU ---------------------------------

def test_delta_invalidation_spares_untouched_nodes():
    """An allocate on n1 must drop ONLY n1's memoized score: the next
    lookup reuses the other nodes and recomputes exactly one."""
    fc, names = fleet(n_nodes=4)
    cache, flt, prio, _ = rig(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="watcher"))
    flt.handle({"Pod": pod, "NodeNames": names})

    other = fc.create_pod(make_pod(hbm=4096, name="churn"))
    cache.get_node_info("n1").allocate(other, fc)

    inv0 = MEMO_DELTA_INVALIDATIONS.value
    reused0 = MEMO_NODE_SCORES.get("reused")
    computed0 = MEMO_NODE_SCORES.get("computed")
    scores, errors = cache.score_nodes(pod, request_from_pod(pod), names)
    assert not errors
    assert MEMO_DELTA_INVALIDATIONS.value - inv0 == 1
    assert MEMO_NODE_SCORES.get("reused") - reused0 == 3
    assert MEMO_NODE_SCORES.get("computed") - computed0 == 1
    # and the recomputed score reflects the allocate (tighter chip)
    assert scores["n1"] != scores["n0"]


def test_bind_watch_echo_is_not_a_mutation():
    """The informer echo of a bind this cache already applied (same
    chips, same HBM, confirmed) must be a no-op: a stamp bump here
    would invalidate the node's memo on EVERY bind and keep shard
    handover revalidation re-arming forever on any node that keeps
    receiving traffic. A pod whose annotations actually changed still
    syncs and bumps."""
    import copy

    fc, names = fleet(n_nodes=2)
    cache, flt, _p, _b = rig(fc)
    pod = fc.create_pod(make_pod(hbm=2048))
    flt.handle({"Pod": pod, "NodeNames": names})
    cache.get_node_info("n0").allocate(pod, fc)
    bound = fc.get_pod(pod["metadata"]["namespace"],
                       pod["metadata"]["name"])
    v0 = cache.peek_node("n0").version
    cache.add_or_update_pod(bound)  # watch echo of our own bind
    cache.add_or_update_pod(bound)  # controller resync, same state
    assert cache.peek_node("n0").version == v0
    # a REAL annotation change (repair/defrag rewrite) is a mutation
    changed = copy.deepcopy(bound)
    changed["metadata"]["annotations"][contract.ANN_HBM_POD] = "1024"
    cache.add_or_update_pod(changed)
    assert cache.peek_node("n0").version != v0


def test_removed_node_memoized_score_never_served():
    """A removed node's stamps can never validate again: the lookup
    recomputes (and here re-faults the node from the apiserver)."""
    fc, names = fleet(n_nodes=2)
    cache, flt, _, _ = rig(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="ghost"))
    cache.score_nodes(pod, request_from_pod(pod), names)
    cache.remove_node("n1")
    h0 = MEMO_REQUESTS.get("score", "hit")
    scores, errors = cache.score_nodes(pod, request_from_pod(pod), names)
    assert MEMO_REQUESTS.get("score", "hit") == h0  # not a full hit
    assert scores.get("n1") is not None  # re-faulted and re-scored


def test_memo_is_lru_hot_entry_survives_full_table():
    """Eviction at MEMO_CAP drops the LEAST RECENTLY USED entry, not the
    oldest-inserted: a hot pod that keeps scoring survives a flood of
    one-shot pods."""
    fc, names = fleet(n_nodes=1)
    cache, *_ = rig(fc)
    cache.MEMO_CAP = 8
    hot = fc.create_pod(make_pod(hbm=1024, name="hot"))
    req = request_from_pod(hot)
    cache.score_nodes(hot, req, names)
    for i in range(20):
        cold = fc.create_pod(make_pod(hbm=1024, name=f"cold{i}"))
        cache.score_nodes(cold, req, names)
        # the hot pod keeps getting scheduled-cycle traffic
        h0 = MEMO_REQUESTS.get("score", "hit")
        cache.score_nodes(hot, req, names)
        assert MEMO_REQUESTS.get("score", "hit") == h0 + 1, \
            f"hot entry evicted by cold flood at i={i} (FIFO, not LRU)"
    assert len(cache._memo) <= cache.MEMO_CAP


# -- cold-miss singleflight (bugfix satellite) --------------------------------

def test_cold_node_miss_issues_one_fetch_for_concurrent_threads():
    """N threads faulting the same cold node in must produce ONE
    apiserver fetch and ONE NodeInfo (the miss path is singleflighted
    end to end, not just per-burst on the GET)."""
    fc, names = fleet(n_nodes=1)
    fetches = []
    gate = threading.Event()

    class SlowCluster:
        def __getattr__(self, name):
            return getattr(fc, name)

        def get_node(self, name):
            fetches.append(name)
            gate.wait(5)  # hold the leader so all threads pile up
            return fc.get_node(name)

    cache = SchedulerCache(SlowCluster())
    infos = []
    threads = [threading.Thread(
        target=lambda: infos.append(cache.get_node_info("n0")))
        for _ in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # let every thread reach the miss path
    gate.set()
    for t in threads:
        t.join(5)
    assert len(fetches) == 1, f"cold miss issued {len(fetches)} fetches"
    assert len(infos) == 8
    assert all(i is infos[0] for i in infos), "duplicate NodeInfo built"


# -- the storm property test --------------------------------------------------

def _storm(n_nodes, n_workers, cycles, churn_iters):
    """N scheduler threads running full filter->prioritize->bind->
    terminate cycles against a shared cache while a churn thread
    allocates/releases out-of-band. Returns (binds, filter_latencies,
    overcommit_samples). Invariants asserted by the callers:
    completion under a watchdog (no deadlock), zero oversubscription on
    the fake apiserver truth at any sampled instant, zero stale-positive
    memo serves (TPUSHARE_MEMO_VERIFY), reuse rate > 0 (delta
    invalidation pays off under churn)."""
    fc, names = fleet(n_nodes=n_nodes)
    cache, flt, prio, bind = rig(fc)
    assert cache._verify_serves, "storm must run with TPUSHARE_MEMO_VERIFY"

    binds = [0] * n_workers
    filter_ms: list[float] = []
    filter_ms_lock = threading.Lock()
    errors: list[str] = []
    overcommit: list = []
    stop = threading.Event()

    def truth_sampler():
        while not stop.is_set():
            per: dict = {}
            for pod in fc.list_pods():
                if contract.is_complete_pod(pod):
                    continue
                node = pod["spec"].get("nodeName")
                ids = contract.chip_ids_from_annotations(pod)
                if not node or ids is None:
                    continue
                h = contract.hbm_from_annotations(pod)
                for c in ids:
                    per[(node, c)] = per.get((node, c), 0) + h
            for k, v in per.items():
                if v > HBM:
                    overcommit.append((k, v))
            time.sleep(0.002)

    def worker(w):
        try:
            for i in range(cycles):
                pod = fc.create_pod(make_pod(hbm=2048, name=f"w{w}-{i}"))
                t0 = time.perf_counter()
                ok = flt.handle({"Pod": pod, "NodeNames": names})
                with filter_ms_lock:
                    filter_ms.append((time.perf_counter() - t0) * 1e3)
                if not ok["NodeNames"]:
                    continue
                ranked = prio.handle({"Pod": pod,
                                      "NodeNames": ok["NodeNames"]})
                best = max(r["Score"] for r in ranked)
                node = next(r["Host"] for r in ranked
                            if r["Score"] == best)
                out = bind.handle({
                    "PodName": pod["metadata"]["name"],
                    "PodNamespace": "default",
                    "PodUID": pod["metadata"]["uid"], "Node": node})
                if out.get("Error"):
                    continue
                # terminate: release the chips so the storm sustains
                bound = fc.get_pod("default", pod["metadata"]["name"])
                cache.add_or_update_pod(bound)
                cache.remove_pod(bound)
                fc.delete_pod("default", pod["metadata"]["name"])
                binds[w] += 1
        except Exception as e:  # noqa: BLE001 — surfaced by the caller
            errors.append(f"worker {w}: {type(e).__name__}: {e}")

    def churn():
        try:
            for i in range(churn_iters):
                node = names[i % len(names)]
                pod = fc.create_pod(make_pod(hbm=4096, name=f"churn-{i}"))
                try:
                    cache.get_node_info(node).allocate(pod, fc)
                except AllocationError:
                    fc.delete_pod("default", f"churn-{i}")
                    continue
                bound = fc.get_pod("default", f"churn-{i}")
                cache.add_or_update_pod(bound)
                cache.remove_pod(bound)
                fc.delete_pod("default", f"churn-{i}")
        except Exception as e:  # noqa: BLE001
            errors.append(f"churn: {type(e).__name__}: {e}")

    sampler_t = threading.Thread(target=truth_sampler, daemon=True)
    sampler_t.start()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(n_workers)]
    threads.append(threading.Thread(target=churn, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)  # the no-deadlock watchdog
    alive = [t for t in threads if t.is_alive()]
    stop.set()
    sampler_t.join(timeout=5)
    assert not alive, "storm deadlocked: threads still alive at watchdog"
    assert not errors, f"storm raised: {errors[:3]}"
    return sum(binds), filter_ms, overcommit


@pytest.fixture()
def memo_verify(monkeypatch):
    monkeypatch.setenv("TPUSHARE_MEMO_VERIFY", "1")


def test_concurrent_scheduling_storm_invariants(memo_verify):
    """Tier-1 deterministic-size storm: 4 workers x 12 cycles + churn
    over 4 nodes. No deadlock, no oversubscription, no stale-positive
    serve, and delta invalidation reuses untouched-node scores."""
    stale0 = MEMO_STALE_SERVES.value
    reused0 = MEMO_NODE_SCORES.get("reused")
    binds, filter_ms, overcommit = _storm(
        n_nodes=4, n_workers=4, cycles=12, churn_iters=30)
    assert binds > 0
    assert not overcommit, \
        f"apiserver-truth oversubscription: {overcommit[:3]}"
    assert MEMO_STALE_SERVES.value == stale0, \
        "memo served a stale-positive score under churn"
    assert MEMO_NODE_SCORES.get("reused") > reused0, \
        "delta invalidation never reused an untouched node's score"


@pytest.mark.slow
def test_bind_storm_soak(memo_verify):
    """The soak sibling: more nodes, more workers, longer churn."""
    stale0 = MEMO_STALE_SERVES.value
    binds, filter_ms, overcommit = _storm(
        n_nodes=16, n_workers=8, cycles=40, churn_iters=200)
    assert binds > 50
    assert not overcommit, \
        f"apiserver-truth oversubscription: {overcommit[:3]}"
    assert MEMO_STALE_SERVES.value == stale0
