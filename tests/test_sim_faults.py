"""Fault-domain wind tunnel (ISSUE 13 tentpole a): the seeded fault
schedule is a first-class trace input, and BOTH sim engines — the
python spec path (run_sim) and the native engine loop — must produce
byte-identical reports under the identical schedule, extending the
PR-12 determinism proof into the faulted regime."""

import json

import pytest

from tpushare.sim import (
    FaultEvent, FaultSpec, Fleet, LoopKnobs, TraceSpec, run_sim,
    run_sim_native, synth_faults, synth_trace)


def _fleet(nodes=8):
    return Fleet.homogeneous(nodes, 4, 16384, (2, 2))


def _trace(seed=0, **kw):
    base = dict(n_pods=300, arrival_rate=4.0, mean_duration=30.0,
                multi_chip_fraction=0.3, seed=seed)
    base.update(kw)
    return synth_trace(TraceSpec(**base))


def _faults(seed=3, **kw):
    base = dict(hours=70.0, n_nodes=8, chips_per_node=4,
                node_crashes=2, notready_windows=1, degradations=1,
                brownouts=1, replica_crashes=1, mean_outage=6.0,
                seed=seed)
    base.update(kw)
    return synth_faults(FaultSpec(**base))


def _canon(report):
    return json.dumps(report.to_json(), sort_keys=True)


# -- the schedule itself ------------------------------------------------------

def test_synth_faults_is_deterministic_and_sorted():
    a = _faults(11)
    b = _faults(11)
    assert a == b
    assert a != _faults(12)
    assert all(x.time <= y.time for x, y in zip(a, a[1:]))
    kinds = {e.kind for e in a}
    assert {"node_down", "node_up", "degrade", "brownout_start",
            "brownout_end", "replica_crash", "replica_restart"} <= kinds


def test_fault_windows_are_paired_and_clamped():
    evs = _faults(7, node_crashes=3, notready_windows=2, brownouts=2,
                  replica_crashes=2)
    downs = sum(1 for e in evs if e.kind == "node_down")
    ups = sum(1 for e in evs if e.kind == "node_up")
    assert downs == ups == 5
    assert sum(1 for e in evs if e.kind == "brownout_start") == \
        sum(1 for e in evs if e.kind == "brownout_end") == 2
    assert all(0.0 <= e.time <= 70.0 for e in evs)


def test_fault_event_validation():
    with pytest.raises(ValueError):
        FaultEvent(time=1.0, kind="meteor_strike")
    with pytest.raises(ValueError):
        FaultEvent(time=-1.0, kind="node_down")
    with pytest.raises(ValueError):
        FaultSpec(hours=0.0)
    with pytest.raises(ValueError):
        FaultSpec(node_crashes=-1)


# -- engine parity under faults (the tentpole claim) --------------------------

@pytest.mark.parametrize("seed", [0, 1, 7])
def test_faulted_scorecard_byte_identical_to_spec(seed):
    """The whole point: the native loop replays the spec decisions
    through crashes, NotReady windows, degradations and brownouts —
    the full report is byte-identical, not merely close."""
    trace = _trace(seed)
    faults = _faults(seed + 100)
    spec = run_sim(_fleet(), trace, "binpack", faults=faults)
    native, _ = run_sim_native(_fleet(), trace, faults=faults)
    assert spec.faults_applied == len(faults)
    assert _canon(spec) == _canon(native)


def test_faulted_parity_under_saturation():
    """Small fleet + hot trace + pod-killing crashes: the pending queue
    and the restart churn are both busy, and parity must still hold."""
    trace = _trace(42, n_pods=300, arrival_rate=8.0, mean_duration=60.0)
    faults = _faults(9, n_nodes=3, node_crashes=3, mean_outage=10.0)
    spec = run_sim(_fleet(3), trace, "binpack", faults=faults)
    native, _ = run_sim_native(_fleet(3), trace, faults=faults)
    assert spec.fault_lost_pods > 0      # crashes actually killed pods
    assert spec.mean_wait > 0            # the pressure is real
    assert _canon(spec) == _canon(native)


def test_throughput_knobs_stay_invariant_under_faults():
    """index_scheme/eqclass_lru remain pure throughput knobs in the
    faulted regime: the max-free prune stays a conservative
    OVERestimate on downed/degraded nodes, so decisions never move."""
    trace = _trace(3)
    faults = _faults(5)
    base, _ = run_sim_native(_fleet(), trace, faults=faults)
    for knobs in (LoopKnobs(index_scheme="pow2"),
                  LoopKnobs(index_scheme="exact"),
                  LoopKnobs(eqclass_lru=1)):
        tuned, _ = run_sim_native(_fleet(), trace, knobs, faults=faults)
        assert _canon(base) == _canon(tuned)


def test_no_fault_schedule_is_the_identity():
    """faults=None and faults=[] replay exactly the pre-fault code
    path — the pinned no-fault golden cannot move."""
    trace = _trace(1)
    plain = run_sim(_fleet(), trace, "binpack")
    empty = run_sim(_fleet(), trace, "binpack", faults=[])
    assert plain.faults_applied == 0 and plain.fault_lost_pods == 0
    assert _canon(plain) == _canon(empty)
    native, _ = run_sim_native(_fleet(), trace, faults=None)
    assert _canon(plain) == _canon(native)


# -- fault semantics ----------------------------------------------------------

def test_node_crash_kills_and_restarts_pods():
    """One node, one crash window mid-trace: running pods die, restart
    from pending after the node returns, and nothing oversubscribes."""
    trace = [
        # two pods that will be running when the node dies at t=5
        *({"arrival": 1.0 + i, "duration": 100.0, "hbm_mib": 4096}
          for i in range(2)),
    ]
    from tpushare.sim.simulator import SimPod
    trace = [SimPod(**p) for p in trace]
    faults = [FaultEvent(time=5.0, kind="node_down", node=0,
                         lose_pods=True),
              FaultEvent(time=10.0, kind="node_up", node=0)]
    r = run_sim(_fleet(1), trace, "binpack", faults=faults)
    assert r.fault_lost_pods == 2
    # killed pods restarted after node_up: placed counts re-placements
    assert r.placed == 4 and r.never_placed == 0
    # the restart waits key to the ORIGINAL arrival (crash cost is in
    # the wait tail): the survivors waited (10 - arrival) = 9 and 8
    assert r.p99_wait >= 8.0
    assert abs(r.mean_wait - (9.0 + 8.0) / 4) < 1e-6
    native, _ = run_sim_native(_fleet(1), trace, faults=faults)
    assert _canon(r) == _canon(native)


def test_notready_window_blocks_placement_but_keeps_pods():
    trace = [_mk(1.0, 50.0), _mk(6.0, 5.0)]
    faults = [FaultEvent(time=5.0, kind="node_down", node=0),
              FaultEvent(time=20.0, kind="node_up", node=0)]
    r = run_sim(_fleet(1), trace, "binpack", faults=faults)
    assert r.fault_lost_pods == 0        # NotReady: pod 1 survives
    assert r.placed == 2
    # pod 2 arrived during the window and had to wait for node_up:
    # waits are 0 and 14, so the mean is 7
    assert abs(r.mean_wait - 7.0) < 1e-6
    native, _ = run_sim_native(_fleet(1), trace, faults=faults)
    assert _canon(r) == _canon(native)


def test_degrade_shrinks_the_chip_set_permanently():
    """Degrading every chip of a 1-node fleet strands all later
    arrivals; an exclusive-chip pod can never land on a degraded chip."""
    trace = [_mk(10.0, 5.0)]
    faults = [FaultEvent(time=1.0, kind="degrade", node=0,
                         chips=(0, 1, 2, 3))]
    r = run_sim(_fleet(1), trace, "binpack", faults=faults)
    assert r.placed == 0 and r.never_placed == 1
    native, _ = run_sim_native(_fleet(1), trace, faults=faults)
    assert _canon(r) == _canon(native)


def test_brownout_stalls_scheduling_until_heal():
    """Arrivals inside the brownout queue; the heal edge retries the
    backlog at the brownout_end instant exactly."""
    trace = [_mk(5.0, 2.0), _mk(6.0, 2.0)]
    faults = [FaultEvent(time=4.0, kind="brownout_start"),
              FaultEvent(time=9.0, kind="brownout_end")]
    r = run_sim(_fleet(1), trace, "binpack", faults=faults)
    assert r.placed == 2
    assert abs(r.mean_wait - 3.5) < 1e-6  # (9-5 + 9-6) / 2
    native, _ = run_sim_native(_fleet(1), trace, faults=faults)
    assert _canon(r) == _canon(native)


def test_overlapping_stall_windows_nest():
    """A replica crash inside a brownout: scheduling resumes only when
    BOTH windows close."""
    trace = [_mk(2.0, 1.0)]
    faults = [FaultEvent(time=1.0, kind="brownout_start"),
              FaultEvent(time=1.5, kind="replica_crash", replica=0),
              FaultEvent(time=3.0, kind="brownout_end"),
              FaultEvent(time=6.0, kind="replica_restart", replica=0)]
    r = run_sim(_fleet(1), trace, "binpack", faults=faults)
    assert abs(r.mean_wait - 4.0) < 1e-6  # placed at 6.0, arrived 2.0
    native, _ = run_sim_native(_fleet(1), trace, faults=faults)
    assert _canon(r) == _canon(native)


def _mk(arrival, duration, hbm=4096, **kw):
    from tpushare.sim.simulator import SimPod
    return SimPod(arrival=arrival, duration=duration, hbm_mib=hbm, **kw)
