"""Device plugin tests: enumeration, registration, Allocate rendezvous,
health reporting, socket transport, and the full extender->plugin handoff.
"""

import os
import threading

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.deviceplugin import DevicePlugin, FakeEnumerator
from tpushare.deviceplugin.enumerator import NativeEnumerator, _hbm_from_env
from tpushare.deviceplugin.plugin import AllocateError
from tpushare.deviceplugin.transport import SocketServer, call
from tpushare.k8s import FakeCluster


def rig(chips=4, hbm=16000, mesh="2x2", node="n1"):
    fc = FakeCluster()
    fc.add_tpu_node(node, chips=chips, hbm_per_chip_mib=hbm, mesh=mesh)
    enum = FakeEnumerator(chips, hbm, mesh)
    plugin = DevicePlugin(fc, node, enum)
    return fc, plugin


def place(fc, name, hbm, count=1, node="n1", now_ns=None):
    """Run the extender's bind path to produce a placed pod."""
    cache = SchedulerCache(fc)
    cache.build_cache()  # replay prior placements, or successive place()
    # calls each see an empty node and oversubscribe the first chip
    info = cache.get_node_info(node)
    pod = fc.create_pod(make_pod(hbm=hbm, count=count if count > 1 else 0,
                                 name=name))
    kwargs = {} if now_ns is None else {"now_ns": lambda: now_ns}
    info.allocate(pod, fc, **kwargs)
    return fc.get_pod("default", name)


# -- enumeration --------------------------------------------------------------

def test_fake_enumerator_shapes():
    e = FakeEnumerator(4, 16000, "2x2")
    chips = e.enumerate()
    assert [c.idx for c in chips] == [0, 1, 2, 3]
    assert chips[3].coords == (1, 1)
    with pytest.raises(ValueError):
        FakeEnumerator(4, 16000, "4x4")


def test_native_enumerator_fake_env(monkeypatch):
    monkeypatch.setenv("TPUSHARE_FAKE_CHIPS", "4")
    monkeypatch.setenv("TPUSHARE_HBM_MIB", "12345")
    native = NativeEnumerator()
    if not native.available():
        pytest.skip("native enumerator unavailable")
    chips = native.enumerate()
    assert len(chips) == 4
    assert all(c.hbm_mib == 12345 for c in chips)
    assert chips[0].device_path == "/dev/accel0"
    # chips can disappear between scans (health loop relies on this)
    monkeypatch.setenv("TPUSHARE_FAKE_CHIPS", "2")
    assert len(native.enumerate()) == 2


def test_hbm_generation_table(monkeypatch):
    monkeypatch.delenv("TPUSHARE_HBM_MIB", raising=False)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
    assert _hbm_from_env() == 95 * 1024
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e")
    assert _hbm_from_env() == 16 * 1024
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE")
    assert _hbm_from_env() == 16 * 1024


# -- registration -------------------------------------------------------------

def test_register_node_patches_resources_and_labels():
    fc = FakeCluster()
    # node exists but reports nothing yet (fresh kubelet)
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=1)
    plugin = DevicePlugin(fc, "n1", FakeEnumerator(4, 16000, "2x2"))
    plugin.register_node()
    node = fc.get_node("n1")
    assert node["status"]["allocatable"][contract.RESOURCE_HBM] == "64000"
    assert node["status"]["allocatable"][contract.RESOURCE_COUNT] == "4"
    assert node["metadata"]["labels"][contract.LABEL_MESH] == "2x2"


# -- allocate rendezvous ------------------------------------------------------

def test_allocate_matches_amount_and_injects_env():
    fc, plugin = rig()
    place(fc, "w1", hbm=2048)
    resp = plugin.allocate(hbm_mib=2048)
    assert resp["pod"]["name"] == "w1"
    env = resp["env"]
    assert env[contract.ENV_VISIBLE_CHIPS] == str(resp["chip_ids"][0])
    assert env[contract.ENV_HBM_LIMIT] == "2048"
    assert env[contract.ENV_HBM_CHIP_TOTAL] == "16000"
    assert env[contract.ENV_MEM_FRACTION] == f"{2048/16000:.4f}"
    assert resp["devices"] == [f"/dev/accel{resp['chip_ids'][0]}"]
    # assigned flipped to true (designs.md:101)
    assert contract.is_assigned(fc.get_pod("default", "w1"))
    # second allocate re-matches the assigned pod idempotently (kubelet
    # calls once per container and may retry dropped responses)
    again = plugin.allocate(hbm_mib=2048)
    assert again["pod"]["name"] == "w1" and again["env"] == env
    # but an amount nothing on the node explains still fails
    with pytest.raises(AllocateError):
        plugin.allocate(hbm_mib=4096)


def test_allocate_stamps_qos_tier_env():
    # the container learns its own tier (runtime hint for in-process
    # throttling); unannotated pods land on the burstable default
    fc, plugin = rig()
    cache = SchedulerCache(fc)
    be = fc.create_pod(make_pod(
        hbm=2048, name="be", ann={contract.ANN_QOS_TIER: "best-effort"}))
    cache.get_node_info("n1").allocate(be, fc)
    resp = plugin.allocate(pod_uid=be["metadata"]["uid"])
    assert resp["env"][contract.ENV_QOS_TIER] == "best-effort"

    plain = fc.create_pod(make_pod(hbm=2048, name="plain"))
    cache.build_cache()
    cache.get_node_info("n1").allocate(plain, fc)
    resp2 = plugin.allocate(pod_uid=plain["metadata"]["uid"])
    assert resp2["env"][contract.ENV_QOS_TIER] == "burstable"


def test_allocate_tie_broken_by_assume_time_then_uid():
    fc, plugin = rig()
    place(fc, "late", hbm=2048, now_ns=2000)
    place(fc, "early", hbm=2048, now_ns=1000)
    resp = plugin.allocate(hbm_mib=2048)
    assert resp["pod"]["name"] == "early"  # earliest assume-time wins
    resp2 = plugin.allocate(hbm_mib=2048)
    assert resp2["pod"]["name"] == "late"


def test_allocate_by_pod_uid():
    fc, plugin = rig()
    p1 = place(fc, "a", hbm=2048, now_ns=1)
    place(fc, "b", hbm=2048, now_ns=2)
    resp = plugin.allocate(pod_uid=p1["metadata"]["uid"])
    assert resp["pod"]["name"] == "a"


def test_allocate_multichip_env():
    fc, plugin = rig(chips=16, hbm=16000, mesh="4x4")
    place(fc, "mc", hbm=8000, count=4)
    resp = plugin.allocate(hbm_mib=8000)
    assert len(resp["chip_ids"]) == 4
    assert resp["env"][contract.ENV_VISIBLE_CHIPS] == \
        ",".join(str(i) for i in resp["chip_ids"])
    assert len(resp["devices"]) == 4


def test_allocate_exclusive_has_no_fraction_cap():
    fc, plugin = rig(chips=2, hbm=16000, mesh=None)
    cache = SchedulerCache(fc)
    pod = fc.create_pod(make_pod(count=1, name="excl"))
    cache.get_node_info("n1").allocate(pod, fc)
    resp = plugin.allocate(hbm_mib=None, pod_uid=pod["metadata"]["uid"])
    assert contract.ENV_MEM_FRACTION not in resp["env"]
    assert resp["env"][contract.ENV_HBM_LIMIT] == "16000"


def test_allocate_matches_per_container_amount():
    # kubelet allocates per CONTAINER: a two-container pod (1024 each) gets
    # Allocate(1024) calls while the annotation carries the pod sum 2048
    fc, plugin = rig()
    cache = SchedulerCache(fc)
    pod = make_pod(hbm=1024, name="mc2", containers=2)  # pod-level ask 2048
    pod = fc.create_pod(pod)
    cache.get_node_info("n1").allocate(pod, fc)
    resp = plugin.allocate(hbm_mib=1024)  # container-level amount
    assert resp["pod"]["name"] == "mc2"
    assert resp["env"][contract.ENV_HBM_LIMIT] == "2048"


def test_allocate_exclusive_matches_zero_amount():
    # count-only pods have no tpu-hbm limit: kubelet's tpu-count Allocate
    # carries no hbm amount (0)
    fc, plugin = rig(chips=2, hbm=16000, mesh=None)
    cache = SchedulerCache(fc)
    pod = fc.create_pod(make_pod(count=1, name="excl0"))
    cache.get_node_info("n1").allocate(pod, fc)
    resp = plugin.allocate(hbm_mib=0)
    assert resp["pod"]["name"] == "excl0"


def test_native_enumerator_keeps_device_numbers(monkeypatch):
    # ids must come from the device-node number so a vanished middle chip
    # doesn't shift the survivors' identities
    from tpushare.deviceplugin.enumerator import _idx_from_path
    assert _idx_from_path("/dev/accel3", default=9) == 3
    assert _idx_from_path("/dev/vfio/7", default=9) == 7
    assert _idx_from_path("/dev/weird", default=9) == 9


def test_health_writes_only_on_change():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    enum = ShrinkingEnumerator()
    plugin = DevicePlugin(fc, "n1", enum)
    plugin.check_health()
    rv1 = fc.get_configmap("kube-system", "unhealthy-tpu-n1")[
        "metadata"]["resourceVersion"]
    plugin.check_health()  # unchanged -> no write
    rv2 = fc.get_configmap("kube-system", "unhealthy-tpu-n1")[
        "metadata"]["resourceVersion"]
    assert rv1 == rv2
    enum.lost = {2}
    plugin.check_health()  # changed -> write
    cm = fc.get_configmap("kube-system", "unhealthy-tpu-n1")
    assert cm["data"]["chips"] == "2"
    assert cm["metadata"]["resourceVersion"] != rv1


def test_allocate_no_match_errors():
    fc, plugin = rig()
    place(fc, "w1", hbm=2048)
    with pytest.raises(AllocateError, match="no pending pod"):
        plugin.allocate(hbm_mib=4096)  # wrong amount


# -- health -------------------------------------------------------------------

class ShrinkingEnumerator(FakeEnumerator):
    def __init__(self):
        super().__init__(4, 16000, "2x2")
        self.lost: set = set()

    def enumerate(self):
        return [c for c in super().enumerate() if c.idx not in self.lost]


def test_health_writes_unhealthy_configmap():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    enum = ShrinkingEnumerator()
    plugin = DevicePlugin(fc, "n1", enum)
    assert plugin.check_health() == set()
    enum.lost = {1, 3}
    assert plugin.check_health() == {1, 3}
    cm = fc.get_configmap("kube-system", "unhealthy-tpu-n1")
    assert cm["data"]["chips"] == "1,3"
    # recovery clears the configmap
    enum.lost = set()
    plugin.check_health()
    assert fc.get_configmap(
        "kube-system", "unhealthy-tpu-n1")["data"]["chips"] == ""


def test_gc_counts_stale_pending_without_reclaim():
    fc, plugin = rig()
    place(fc, "stuck", hbm=2048, now_ns=1)  # placed at epoch -> ancient
    assert plugin.gc_stale_assignments(max_pending_seconds=1,
                                       reclaim=False) == 1
    plugin.allocate(hbm_mib=2048)
    assert plugin.gc_stale_assignments(max_pending_seconds=1,
                                       reclaim=False) == 0


def test_gc_reclaims_stale_placement_and_frees_chips():
    """VERDICT r1 item 7: a placed-but-never-started pod frees its chips
    after the window instead of holding them until termination."""
    from tpushare.cache import SchedulerCache
    from tpushare.controller import Controller

    fc, plugin = rig()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    place(fc, "stuck", hbm=2048, now_ns=1)
    ctl.build_cache()
    ctl.start()
    try:
        assert cache.get_node_info("n1").describe()["used_hbm_mib"] == 2048
        assert plugin.gc_stale_assignments(max_pending_seconds=1) == 1
        # annotations cleared on the apiserver...
        pod = fc.get_pod("default", "stuck")
        assert contract.chip_ids_from_annotations(pod) is None
        # ...a late Allocate now fails (chips may be re-granted elsewhere)
        with pytest.raises(AllocateError):
            plugin.allocate(hbm_mib=2048)
        # ...and the controller freed the chips in the extender cache
        import time as _t
        deadline = _t.monotonic() + 10
        while _t.monotonic() < deadline and \
                cache.get_node_info("n1").describe()["used_hbm_mib"] != 0:
            _t.sleep(0.02)
        assert cache.get_node_info("n1").describe()["used_hbm_mib"] == 0
    finally:
        ctl.stop()


def test_gc_reclaim_loses_cas_race_to_late_allocate():
    """If Allocate lands between the stale scan and the CAS PUT, the
    reclaim must lose and the placement must stand."""
    fc, plugin = rig()
    place(fc, "racy", hbm=2048, now_ns=1)

    real_get = fc.get_pod

    def get_then_allocate(ns, name):
        pod = real_get(ns, name)
        if name == "racy" and not contract.is_assigned(pod):
            # the kubelet's Allocate sneaks in after gc's freshness read
            fc.patch_pod(ns, name, contract.assigned_patch())
        return pod

    fc.get_pod = get_then_allocate
    try:
        plugin.gc_stale_assignments(max_pending_seconds=1)
    finally:
        fc.get_pod = real_get
    pod = fc.get_pod("default", "racy")
    # CAS lost: placement annotations intact, pod assigned
    assert contract.chip_ids_from_annotations(pod) is not None
    assert contract.is_assigned(pod)


# -- socket transport ---------------------------------------------------------

def test_socket_transport_roundtrip(tmp_path):
    fc, plugin = rig()
    place(fc, "w1", hbm=2048)
    sock = str(tmp_path / "dp.sock")
    server = SocketServer(plugin, sock)
    server.start()
    try:
        resp = call(sock, {"method": "list"})
        assert len(resp["chips"]) == 4
        resp = call(sock, {"method": "report"})
        assert resp["status"]["allocatable"][contract.RESOURCE_HBM] == "64000"
        resp = call(sock, {"method": "allocate", "hbm_mib": 2048})
        assert resp["pod"]["name"] == "w1"
        resp = call(sock, {"method": "allocate", "hbm_mib": 2048})
        assert resp["pod"]["name"] == "w1"  # idempotent rematch
        resp = call(sock, {"method": "allocate", "hbm_mib": 4096})
        assert "no pending pod" in resp["error"]
        resp = call(sock, {"method": "health"})
        assert resp["unhealthy"] == []
        resp = call(sock, {"method": "bogus"})
        assert "unknown method" in resp["error"]
    finally:
        server.stop()


# -- full extender -> device-plugin handoff -----------------------------------

def test_full_scheduling_to_runtime_cycle():
    """The complete designs.md lifecycle: filter-time fit, bind-time
    placement annotations, runtime Allocate matching, assigned flip."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    cache = SchedulerCache(fc)
    info = cache.get_node_info("n1")
    plugin = DevicePlugin(fc, "n1", FakeEnumerator(4, 16000, "2x2"))

    for i, hbm in enumerate([2000, 2000, 12000]):
        pod = fc.create_pod(make_pod(hbm=hbm, name=f"w{i}"))
        ok, _ = info.assume(pod)
        assert ok
        info.allocate(pod, fc, now_ns=lambda i=i: i)

    # kubelet starts containers in arbitrary order; amounts disambiguate,
    # ties resolve by assume time
    r3 = plugin.allocate(hbm_mib=12000)
    assert r3["pod"]["name"] == "w2"
    r1 = plugin.allocate(hbm_mib=2000)
    assert r1["pod"]["name"] == "w0"  # earlier assume-time
    r2 = plugin.allocate(hbm_mib=2000)
    assert r2["pod"]["name"] == "w1"
    # min-free-that-fits packs ALL three onto chip 0: the two 2000s share
    # it, then its remaining 12000 is the tightest fit for the big pod —
    # one chip fully utilized, three left pristine for future large pods
    assert r1["chip_ids"] == r2["chip_ids"] == r3["chip_ids"]
    node_desc = cache.get_node_info("n1").describe()
    packed = node_desc["chips"][r1["chip_ids"][0]]
    assert packed["used_hbm_mib"] == packed["total_hbm_mib"] == 16000
    assert cache.describe()["used_hbm_mib"] == 16000


def test_slice_labels_published():
    from tpushare.contract import LABEL_SLICE, LABEL_SLICE_ORIGIN
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    fc.add_tpu_node("h1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    plugin = DevicePlugin(fc, "h1", FakeEnumerator(4, 16000, "2x2"),
                          slice_id="slc0", slice_origin="0x2")
    plugin.register_node()
    labels = fc.get_node("h1")["metadata"]["labels"]
    assert labels[LABEL_SLICE] == "slc0"
    assert labels[LABEL_SLICE_ORIGIN] == "0x2"
    # and the scheduler side parses them back
    from tpushare.contract import node_slice
    assert node_slice(fc.get_node("h1")) == ("slc0", (0, 2))


def test_slice_labels_require_both_and_valid_origin():
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    fc.add_tpu_node("h1", chips=4, hbm_per_chip_mib=16000)
    with pytest.raises(ValueError, match="together"):
        DevicePlugin(fc, "h1", FakeEnumerator(4, 16000, "2x2"),
                     slice_id="slc0")
    with pytest.raises(ValueError, match="coordinates"):
        DevicePlugin(fc, "h1", FakeEnumerator(4, 16000, "2x2"),
                     slice_id="slc0", slice_origin="left-top")
    # rank mismatch with the host mesh is caught at STARTUP — published
    # as-is it would silently disable the whole slice's gang scheduling
    # at the coordinator's rank check instead
    with pytest.raises(ValueError, match="matching this host's mesh"):
        DevicePlugin(fc, "h1", FakeEnumerator(4, 16000, "2x2"),
                     slice_id="slc0", slice_origin="02")


def test_slice_labels_cleared_when_unconfigured():
    from tpushare.contract import LABEL_SLICE, LABEL_SLICE_ORIGIN
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    fc.add_tpu_node("h1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    DevicePlugin(fc, "h1", FakeEnumerator(4, 16000, "2x2"),
                 slice_id="slc0", slice_origin="0x2").register_node()
    assert LABEL_SLICE in fc.get_node("h1")["metadata"]["labels"]
    # plugin restarts WITHOUT slice config: stale membership must go
    DevicePlugin(fc, "h1",
                 FakeEnumerator(4, 16000, "2x2")).register_node()
    labels = fc.get_node("h1")["metadata"]["labels"]
    assert LABEL_SLICE not in labels
    assert LABEL_SLICE_ORIGIN not in labels


# -- gang runtime env (VERDICT r4 item 4) -------------------------------------

def _gang_rig():
    """A bound 2-host gang on a slice fleet, plus a DevicePlugin on each
    member host — the runtime side of tests/test_gang.py's scheduling."""
    from tpushare.cache import SchedulerCache
    from tpushare.cache.gang import GangCoordinator
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    for name, origin in zip(("h00", "h02", "h20", "h22"),
                            ("0x0", "0x2", "2x0", "2x2")):
        fc.add_tpu_node(name, chips=4, hbm_per_chip_mib=16000, mesh="2x2",
                        slice_id="slc0", slice_origin=origin)
    cache = SchedulerCache(fc)
    cache.build_cache()
    gang = GangCoordinator(cache)
    pods = []
    for rank in (0, 1):
        pod = fc.create_pod({
            "metadata": {"name": f"gm{rank}", "namespace": "default",
                         "annotations": {
                             contract.ANN_GANG: "gj",
                             contract.ANN_GANG_SIZE: "8",
                             contract.ANN_GANG_RANK: str(rank),
                             contract.ANN_TOPOLOGY: "2x4",
                         }},
            "spec": {"hostname": f"gj-{rank}", "subdomain": "gj",
                     "containers": [{"name": "c", "resources": {
                         "limits": {contract.RESOURCE_COUNT: "4"}}}]},
        })
        pods.append(pod)
    hosts = []
    for pod in pods:
        (host,), why = gang.filter_hosts(pod)
        assert not why
        gang.bind_member(pod, host, fc)
        hosts.append(host)
    return fc, hosts


def test_allocate_injects_gang_runtime_env():
    fc, hosts = _gang_rig()
    for rank, host in enumerate(hosts):
        plugin = DevicePlugin(fc, host, FakeEnumerator(4, 16000, "2x2"))
        resp = plugin.allocate_exclusive(4)
        env = resp["env"]
        # identity
        assert env[contract.ENV_GANG_ID] == "gj"
        assert env[contract.ENV_GANG_SIZE] == "8"
        assert env[contract.ENV_PROCESS_ID] == str(rank)
        assert env[contract.ENV_CLOUD_TPU_TASK_ID] == str(rank)
        # geometry from the stamped plan (both members — rank 1's pod
        # carries no stamp itself; the plugin reads it off the peer)
        assert env[contract.ENV_GANG_BOX] == "2x4"
        assert env[contract.ENV_GANG_LOCAL_BOX] == "2x2"
        assert env[contract.ENV_NUM_PROCESSES] == "2"
        # libtpu sub-slice pair: 2x4 global over 2x2 locals = 1x2 grid
        assert env[contract.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] == "2,2,1"
        assert env[contract.ENV_TPU_PROCESS_BOUNDS] == "1,2,1"
        # member origin inside the gang box (rank 0 at 0x0, rank 1 at
        # 0x2 — slice-origin + host-local origin - gang origin)
        assert env[contract.ENV_GANG_MEMBER_ORIGIN] == \
            ("0x0" if rank == 0 else "0x2")
        # addresses via the hostname.subdomain convention
        port = contract.GANG_COORDINATOR_PORT
        assert env[contract.ENV_COORDINATOR_ADDRESS] == f"gj-0.gj:{port}"
        assert env[contract.ENV_TPU_PROCESS_ADDRESSES] == \
            f"gj-0.gj:{port},gj-1.gj:{port}"
        # the single-host env contract still holds alongside
        assert len(env[contract.ENV_VISIBLE_CHIPS].split(",")) == 4


def test_allocate_gang_env_degrades_without_plan_stamp():
    """A gang member whose plan stamp is unreachable still allocates,
    with identity env only (best-effort: never fail the Allocate)."""
    fc, hosts = _gang_rig()
    # strip the stamp from member 0 (simulates a stamped peer deleted
    # before this member's container started)
    p0 = fc.get_pod("default", "gm0")
    body = dict(p0)
    body["metadata"]["annotations"].pop(contract.ANN_GANG_PLAN)
    fc.replace_pod("default", "gm0", body)
    plugin = DevicePlugin(fc, hosts[1], FakeEnumerator(4, 16000, "2x2"))
    resp = plugin.allocate_exclusive(4)
    env = resp["env"]
    assert env[contract.ENV_GANG_ID] == "gj"
    assert env[contract.ENV_PROCESS_ID] == "1"
    assert contract.ENV_TPU_PROCESS_BOUNDS not in env
    assert contract.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS not in env
    assert contract.ENV_COORDINATOR_ADDRESS not in env


def test_allocate_gang_env_survives_stale_out_of_range_peer():
    """A lingering same-gang pod with an out-of-range rank (e.g. from a
    previous, larger incarnation of the job) must not break the
    best-effort contract: allocate still succeeds and the address list
    is still assembled from the in-range ranks."""
    fc, hosts = _gang_rig()
    fc.create_pod({
        "metadata": {"name": "stale", "namespace": "default",
                     "annotations": {
                         contract.ANN_GANG: "gj",
                         contract.ANN_GANG_SIZE: "8",
                         contract.ANN_GANG_RANK: "5",  # out of range
                     }},
        "spec": {"hostname": "gj-5", "subdomain": "gj",
                 "containers": [{"name": "c", "resources": {
                     "limits": {}}}]},
    })
    plugin = DevicePlugin(fc, hosts[0], FakeEnumerator(4, 16000, "2x2"))
    env = plugin.allocate_exclusive(4)["env"]
    port = contract.GANG_COORDINATOR_PORT
    assert env[contract.ENV_TPU_PROCESS_ADDRESSES] == \
        f"gj-0.gj:{port},gj-1.gj:{port}"


def test_allocate_non_gang_pod_gets_no_gang_env():
    fc, plugin = rig()
    place(fc, "w1", hbm=2048)
    env = plugin.allocate(hbm_mib=2048)["env"]
    assert contract.ENV_GANG_ID not in env
    assert contract.ENV_PROCESS_ID not in env
