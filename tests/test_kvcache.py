"""KV-cache decode path: equivalence with the cache-free reference decode."""

import numpy as np

import jax
import jax.numpy as jnp

import pytest

from tpushare.workloads.model import (
    PRESETS, forward, forward_cached, greedy_decode, greedy_decode_kv,
    init_kv_cache, init_params, quantize_int8)

CFG = PRESETS["llama-tiny"]


def test_prefill_logits_match_full_forward():
    params = init_params(CFG, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 11), 0, CFG.vocab)
    cache = init_kv_cache(CFG, 2, 11)
    logits_c, cache = forward_cached(params, tokens, cache, 0, CFG)
    logits = forward(params, tokens, CFG)
    np.testing.assert_allclose(np.asarray(logits_c), np.asarray(logits),
                               atol=1e-5, rtol=1e-5)


def test_incremental_matches_full_forward():
    # prefill 5 tokens, then feed 3 more one at a time; the last-token
    # logits must match a full forward over the whole 8-token sequence
    params = init_params(CFG, jax.random.key(2))
    tokens = jax.random.randint(jax.random.key(3), (2, 8), 0, CFG.vocab)
    cache = init_kv_cache(CFG, 2, 8)
    _, cache = forward_cached(params, tokens[:, :5], cache, 0, CFG)
    for i in range(5, 8):
        step_logits, cache = forward_cached(
            params, tokens[:, i:i + 1], cache, i, CFG)
    full = forward(params, tokens, CFG)
    np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                               np.asarray(full[:, -1]),
                               atol=1e-4, rtol=1e-4)


def test_greedy_decode_kv_matches_reference_decode():
    params = init_params(CFG, jax.random.key(4))
    prompt = jax.random.randint(jax.random.key(5), (2, 7), 0, CFG.vocab)
    ref = greedy_decode(params, prompt, 9, CFG)
    out = greedy_decode_kv(params, prompt, 9, CFG)
    assert (np.asarray(ref) == np.asarray(out)).all()


def test_greedy_decode_kv_int8():
    params = quantize_int8(init_params(CFG, jax.random.key(6)))
    prompt = jax.random.randint(jax.random.key(7), (1, 4), 0, CFG.vocab)
    ref = greedy_decode(params, prompt, 6, CFG)
    out = greedy_decode_kv(params, prompt, 6, CFG)
    assert (np.asarray(ref) == np.asarray(out)).all()


def test_greedy_decode_kv_jits():
    params = init_params(CFG, jax.random.key(8))
    prompt = jax.random.randint(jax.random.key(9), (1, 4), 0, CFG.vocab)
    fn = jax.jit(lambda p, t: greedy_decode_kv(p, t, 5, CFG))
    out = fn(params, prompt)
    assert out.shape == (1, 9)
    assert (np.asarray(out)[:, :4] == np.asarray(prompt)).all()


def test_windowed_decode_matches_recompute_path():
    """cfg.attn_window must flow into the KV-cached decode mask: the
    cached path and the full-recompute path define the same model."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, greedy_decode, greedy_decode_kv, init_params)

    cfg = dataclasses.replace(PRESETS["llama-tiny"], attn_window=12)
    params = init_params(cfg, jax.random.key(60))
    prompt = jax.random.randint(jax.random.key(61), (2, 24), 0, cfg.vocab)
    full = greedy_decode(params, prompt, 8, cfg)
    cached = greedy_decode_kv(params, prompt, 8, cfg)
    assert (full == cached).all(), "windowed decode diverged from spec"
    # and the window changes generation vs full causal on this prompt
    nocfg = dataclasses.replace(cfg, attn_window=None)
    baseline = greedy_decode(params, prompt, 8, nocfg)
    # (not guaranteed different for every prompt, but this seed is)
    assert not (full == baseline).all()


def test_int8_kv_cache_logits_close_to_bf16_cache():
    """kv_cache_dtype='int8' halves decode cache bandwidth; the honest
    numeric claim is LOGIT closeness on the same cache state (~1% of the
    logit range for per-(token, head) symmetric quantization). Sequence-
    level agreement is NOT asserted: an untrained random model has
    near-tie logits, so a single flipped argmax early in a decode
    cascades — a property of the random weights, not of the cache."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, forward_cached, init_kv_cache, init_params)

    base = PRESETS["llama-tiny"]
    params = init_params(base, jax.random.key(62))
    tokens = jax.random.randint(jax.random.key(63), (2, 16), 0, base.vocab)
    lf, _ = forward_cached(params, tokens, init_kv_cache(base, 2, 16),
                           0, base)
    q8cfg = dataclasses.replace(base, kv_cache_dtype="int8").validate()
    l8, _ = forward_cached(params, tokens, init_kv_cache(q8cfg, 2, 16),
                           0, q8cfg)
    span = float(lf.max() - lf.min())
    rel = float(jnp.max(jnp.abs(lf - l8))) / span
    assert rel < 0.03, f"int8 KV cache logit error {rel:.3f} of range"
    # most next-token predictions survive (== 1.0 observed on CPU, but a
    # backend/accumulation-order change can flip a near-tie argmax on a
    # RANDOM model — requiring perfection here would test the weights,
    # not the cache)
    assert float((jnp.argmax(lf, -1) == jnp.argmax(l8, -1)).mean()) >= 0.9
    # the int8 cache really is int8 (storage claim, not just numerics)
    cache = init_kv_cache(q8cfg, 2, 32)
    assert cache["k"].dtype == jnp.int8 and "ks" in cache


def test_int8_kv_cache_incremental_matches_prefill():
    """Chunked prefill + decode through the int8 cache must equal one-
    shot prefill (quantization is per-token, so chunking cannot change
    any stored value)."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, forward_cached, init_kv_cache, init_params)

    cfg = dataclasses.replace(PRESETS["llama-tiny"],
                              kv_cache_dtype="int8").validate()
    params = init_params(cfg, jax.random.key(64))
    tokens = jax.random.randint(jax.random.key(65), (1, 24), 0, cfg.vocab)
    one = forward_cached(params, tokens, init_kv_cache(cfg, 1, 24), 0, cfg)
    cache = init_kv_cache(cfg, 1, 24)
    l1, cache = forward_cached(params, tokens[:, :10], cache, 0, cfg)
    l2, cache = forward_cached(params, tokens[:, 10:], cache, 10, cfg)
    np.testing.assert_allclose(
        np.asarray(one[0][:, -1]), np.asarray(l2[:, -1]),
        atol=1e-3, rtol=1e-3)


def test_rolling_window_cache_matches_prompt_bounded():
    """The ring buffer (O(window) memory) must produce the same logits as
    the prompt-bounded windowed cache at every step — scripted token
    inputs (no argmax feedback), sequence long enough to wrap the ring
    twice."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, forward_cached, init_kv_cache, init_params)

    W = 8
    cfg = dataclasses.replace(PRESETS["llama-tiny"],
                              attn_window=W).validate()
    params = init_params(cfg, jax.random.key(70))
    total = 3 * W + 5   # wraps the W-slot ring twice
    tokens = jax.random.randint(jax.random.key(71), (1, total), 0,
                                cfg.vocab)

    flat = init_kv_cache(cfg, 1, total)
    ring = init_kv_cache(cfg, 1, W, rolling=True)
    # prefill 5 tokens, then scripted single-token steps
    lf, flat = forward_cached(params, tokens[:, :5], flat, 0, cfg)
    lr, ring = forward_cached(params, tokens[:, :5], ring, 0, cfg)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lr),
                               atol=2e-2, rtol=2e-2)
    for pos in range(5, total):
        tok = tokens[:, pos:pos + 1]
        lf, flat = forward_cached(params, tok, flat, pos, cfg)
        lr, ring = forward_cached(params, tok, ring, pos, cfg)
        np.testing.assert_allclose(
            np.asarray(lf), np.asarray(lr), atol=2e-2, rtol=2e-2,
            err_msg=f"ring diverged at position {pos}")


def test_rolling_decode_matches_prompt_bounded_decode():
    """greedy_decode_kv(rolling=True) == the prompt-bounded decode for a
    bf16 cache (identical visible sets by construction; the ring only
    changes WHERE keys live, not which are visible)."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, greedy_decode_kv, init_params)

    # fp32 so contraction-size accumulation differences (M=12 ring vs
    # M=30 flat) stay ~1e-6 and cannot flip the untrained model's
    # near-tie argmaxes — in bf16 one early flip cascades through the
    # greedy feedback and the comparison tests the weights, not the ring
    cfg = dataclasses.replace(PRESETS["llama-tiny"], attn_window=12,
                              dtype=jnp.float32).validate()
    params = init_params(cfg, jax.random.key(72))
    # prompt LONGER than the window: the FULL prompt is still prefilled
    # (in W-sized chunks) — early tokens shape deeper layers' hidden
    # states through the per-layer receptive-field growth even though
    # the window hides them from the final position directly
    prompt = jax.random.randint(jax.random.key(73), (2, 20), 0, cfg.vocab)
    flat = greedy_decode_kv(params, prompt, 10, cfg)
    ring = greedy_decode_kv(params, prompt, 10, cfg, rolling=True)
    assert (flat == ring).all(), "rolling decode diverged from flat"
    # short-run regression: total < window must still work (ring floors
    # at W slots rather than tripping init's sub-window rejection)
    short = greedy_decode_kv(params, prompt[:, :4], 3, cfg, rolling=True)
    assert short.shape == (2, 7)


def test_rolling_int8_cache_composes():
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, forward_cached, init_kv_cache, init_params)

    W = 8
    cfg = dataclasses.replace(PRESETS["llama-tiny"], attn_window=W,
                              kv_cache_dtype="int8").validate()
    params = init_params(cfg, jax.random.key(74))
    ring = init_kv_cache(cfg, 1, W, rolling=True)
    assert ring["k"].dtype == jnp.int8 and "pos" in ring
    tokens = jax.random.randint(jax.random.key(75), (1, 2 * W), 0,
                                cfg.vocab)
    l, ring = forward_cached(params, tokens[:, :4], ring, 0, cfg)
    for pos in range(4, 2 * W):
        l, ring = forward_cached(params, tokens[:, pos:pos + 1], ring,
                                 pos, cfg)
    assert bool(jnp.isfinite(l).all())


# -- flash prefill in the serving path (VERDICT r3 item 8) ------------------

def _cfg_pair(**extra):
    import dataclasses

    from tpushare.workloads.model import ModelConfig
    base = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=128, dtype=jnp.float32, **extra)
    return (dataclasses.replace(base, attn="einsum"),
            dataclasses.replace(base, attn="flash"))


@pytest.mark.tpu_kernel
def test_flash_prefill_matches_einsum_prefill():
    # prefill-from-zero is plain causal self-attention over the chunk,
    # so the fused kernel must reproduce the buffer einsum exactly (up
    # to kernel rounding); windowed variant included
    for extra in ({}, {"attn_window": 16}):
        cfg_e, cfg_f = _cfg_pair(**extra)
        p = init_params(cfg_e, jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 40), 0, 64)
        le, ce = forward_cached(p, toks, init_kv_cache(cfg_e, 2, 64),
                                jnp.asarray(0), cfg_e)
        lf, cf = forward_cached(p, toks, init_kv_cache(cfg_f, 2, 64),
                                jnp.asarray(0), cfg_f)
        np.testing.assert_allclose(np.asarray(le), np.asarray(lf),
                                   atol=1e-4, rtol=1e-4)
        # caches agree to kernel-rounding: layer n>1's k/v inherit the
        # previous layer's attention output, so flash-vs-einsum rounding
        # (~1e-6 fp32) propagates into the stored values — identity
        # holds only for layer 1, closeness for all
        for name in ce:
            np.testing.assert_allclose(np.asarray(ce[name]),
                                       np.asarray(cf[name]),
                                       atol=1e-4, rtol=1e-3)


@pytest.mark.tpu_kernel
def test_flash_prefill_decode_tokens_match():
    cfg_e, cfg_f = _cfg_pair(attn_window=16)
    p = init_params(cfg_e, jax.random.key(2))
    toks = jax.random.randint(jax.random.key(3), (2, 24), 0, 64)
    oe = greedy_decode_kv(p, toks, 8, cfg_e)
    of = greedy_decode_kv(p, toks, 8, cfg_f)
    np.testing.assert_array_equal(np.asarray(oe), np.asarray(of))


@pytest.mark.tpu_kernel
def test_flash_prefill_int8_cache_documented_semantics():
    # int8 cache: the flash prefill attends PRE-quantization k/v while
    # the einsum path reads the quantized buffer, so logits (and the
    # cached values of layers > 1, which inherit layer 1's divergence)
    # differ within quantization error — bounded, finite, and the
    # decode that follows still works end to end
    cfg_e, cfg_f = _cfg_pair(kv_cache_dtype="int8", attn_window=16)
    p = init_params(cfg_e, jax.random.key(4))
    toks = jax.random.randint(jax.random.key(5), (2, 24), 0, 64)
    le, _ce = forward_cached(p, toks, init_kv_cache(cfg_e, 2, 32),
                             jnp.asarray(0), cfg_e)
    lf, _cf = forward_cached(p, toks, init_kv_cache(cfg_f, 2, 32),
                             jnp.asarray(0), cfg_f)
    np.testing.assert_allclose(np.asarray(le), np.asarray(lf), atol=0.3)
    out = greedy_decode_kv(p, toks, 6, cfg_f)
    assert out.shape == (2, 30)


def test_flash_prefill_not_used_midstream_or_rolling():
    # mid-stream chunks and ring buffers keep the einsum core (their
    # masks are not plain causal); behavior must be identical under
    # either attn setting there
    cfg_e, cfg_f = _cfg_pair(attn_window=8)
    p = init_params(cfg_e, jax.random.key(6))
    toks = jax.random.randint(jax.random.key(7), (1, 30), 0, 64)
    oe = greedy_decode_kv(p, toks, 6, cfg_e, rolling=True)
    of = greedy_decode_kv(p, toks, 6, cfg_f, rolling=True)
    np.testing.assert_array_equal(np.asarray(oe), np.asarray(of))
