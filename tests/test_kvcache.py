"""KV-cache decode path: equivalence with the cache-free reference decode."""

import numpy as np

import jax
import jax.numpy as jnp

from tpushare.workloads.model import (
    PRESETS, forward, forward_cached, greedy_decode, greedy_decode_kv,
    init_kv_cache, init_params, quantize_int8)

CFG = PRESETS["llama-tiny"]


def test_prefill_logits_match_full_forward():
    params = init_params(CFG, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 11), 0, CFG.vocab)
    cache = init_kv_cache(CFG, 2, 11)
    logits_c, cache = forward_cached(params, tokens, cache, 0, CFG)
    logits = forward(params, tokens, CFG)
    np.testing.assert_allclose(np.asarray(logits_c), np.asarray(logits),
                               atol=1e-5, rtol=1e-5)


def test_incremental_matches_full_forward():
    # prefill 5 tokens, then feed 3 more one at a time; the last-token
    # logits must match a full forward over the whole 8-token sequence
    params = init_params(CFG, jax.random.key(2))
    tokens = jax.random.randint(jax.random.key(3), (2, 8), 0, CFG.vocab)
    cache = init_kv_cache(CFG, 2, 8)
    _, cache = forward_cached(params, tokens[:, :5], cache, 0, CFG)
    for i in range(5, 8):
        step_logits, cache = forward_cached(
            params, tokens[:, i:i + 1], cache, i, CFG)
    full = forward(params, tokens, CFG)
    np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                               np.asarray(full[:, -1]),
                               atol=1e-4, rtol=1e-4)


def test_greedy_decode_kv_matches_reference_decode():
    params = init_params(CFG, jax.random.key(4))
    prompt = jax.random.randint(jax.random.key(5), (2, 7), 0, CFG.vocab)
    ref = greedy_decode(params, prompt, 9, CFG)
    out = greedy_decode_kv(params, prompt, 9, CFG)
    assert (np.asarray(ref) == np.asarray(out)).all()


def test_greedy_decode_kv_int8():
    params = quantize_int8(init_params(CFG, jax.random.key(6)))
    prompt = jax.random.randint(jax.random.key(7), (1, 4), 0, CFG.vocab)
    ref = greedy_decode(params, prompt, 6, CFG)
    out = greedy_decode_kv(params, prompt, 6, CFG)
    assert (np.asarray(ref) == np.asarray(out)).all()


def test_greedy_decode_kv_jits():
    params = init_params(CFG, jax.random.key(8))
    prompt = jax.random.randint(jax.random.key(9), (1, 4), 0, CFG.vocab)
    fn = jax.jit(lambda p, t: greedy_decode_kv(p, t, 5, CFG))
    out = fn(params, prompt)
    assert out.shape == (1, 9)
    assert (np.asarray(out)[:, :4] == np.asarray(prompt)).all()


def test_windowed_decode_matches_recompute_path():
    """cfg.attn_window must flow into the KV-cached decode mask: the
    cached path and the full-recompute path define the same model."""
    import dataclasses

    from tpushare.workloads.model import (
        PRESETS, greedy_decode, greedy_decode_kv, init_params)

    cfg = dataclasses.replace(PRESETS["llama-tiny"], attn_window=12)
    params = init_params(cfg, jax.random.key(60))
    prompt = jax.random.randint(jax.random.key(61), (2, 24), 0, cfg.vocab)
    full = greedy_decode(params, prompt, 8, cfg)
    cached = greedy_decode_kv(params, prompt, 8, cfg)
    assert (full == cached).all(), "windowed decode diverged from spec"
    # and the window changes generation vs full causal on this prompt
    nocfg = dataclasses.replace(cfg, attn_window=None)
    baseline = greedy_decode(params, prompt, 8, nocfg)
    # (not guaranteed different for every prompt, but this seed is)
    assert not (full == baseline).all()
