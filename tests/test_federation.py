"""Cross-process metrics federation (ISSUE 19).

``--procs N`` runs N whole-server replicas behind one SO_REUSEPORT
socket; each keeps its own registry, so a bare ``/metrics`` scrape
undercounts the fleet by the replica factor. These tests pin the
properties that make the shared-memory federation segment a truthful
fix:

- **merge is arithmetic** — counters and per-series labeled counters
  sum, histogram bucket counts sum bucket-wise, gauges never federate;
- **the segment is the wire** — two publishers on one segment each see
  the other's snapshot merged with their own live registry;
- **death freezes, never loses** — a crashed replica's slot stops
  updating but its last snapshot keeps being merged (monotone counters:
  freezing loses the tail, never the history);
- **the HTTP surface holds** — ``/metrics/federated`` equals the sum of
  the per-replica registries, carries no gauge series, and degrades to
  the local registry when federation is disabled.
"""

import http.client
import json
import os

import pytest

from tpushare.cache import SchedulerCache
from tpushare.extender import federation as fedlib
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.metrics import (
    Histogram,
    Registry,
    expose_merged,
    merge_states,
)


def _registry(binds: float, hits_by_verb: dict[str, float],
              samples: list[float]) -> Registry:
    r = Registry()
    c = r.counter("t_binds_total", "binds")
    c.inc(binds)
    lc = r.labeled_counter("t_hits_total", "hits", ("verb",))
    for verb, n in hits_by_verb.items():
        lc.inc(verb, n=n)
    h = r.histogram("t_latency_seconds", "lat", buckets=(0.1, 1.0))
    for s in samples:
        h.observe(s)
    r.gauge_func("t_free_chips", "free", lambda: [("", 12.0)])
    return r


def test_merge_states_sums_counters_series_and_buckets():
    a = _registry(3, {"filter": 2, "bind": 1}, [0.05, 0.5])
    b = _registry(4, {"filter": 5}, [0.5, 5.0])
    merged = merge_states([a.federation_state(), b.federation_state()])
    assert merged["t_binds_total"]["value"] == 7
    series = {tuple(k): v for k, v in merged["t_hits_total"]["series"]}
    assert series == {("filter",): 7, ("bind",): 1}
    hist = merged["t_latency_seconds"]
    assert hist["counts"] == [1, 2, 1]  # [<=0.1, <=1.0, +Inf] summed
    assert hist["sum"] == pytest.approx(6.05)
    # gauges are per-process statements about one shared fleet: summing
    # them double-counts, so they must never enter the federation
    assert "t_free_chips" not in merged
    text = expose_merged(merged)
    assert "t_binds_total 7" in text
    assert 't_hits_total{verb="filter"} 7' in text
    assert "t_free_chips" not in text


def test_merge_skips_shape_conflicts_keeps_first():
    a = {"m": {"type": "counter", "help": "h", "value": 1.0}}
    b = {"m": {"type": "histogram", "help": "h", "buckets": [1.0],
               "counts": [1, 0], "sum": 0.5}}
    merged = merge_states([a, b])
    assert merged["m"]["type"] == "counter"
    assert merged["m"]["value"] == 1.0


def _segment(reg, path, **kw) -> fedlib.FederationSegment:
    return fedlib.FederationSegment(reg, port=0, path=path,
                                    nslots=4, slot_size=64 * 1024,
                                    period_s=60.0, **kw)


def test_two_publishers_one_segment_merge_to_the_sum(tmp_path):
    path = str(tmp_path / "fed.seg")
    ra = _registry(10, {"filter": 4}, [])
    rb = _registry(5, {"filter": 1, "bind": 2}, [])
    a, b = _segment(ra, path), _segment(rb, path)
    try:
        assert a.start() and b.start()
        assert a.slot != b.slot
        assert b.publish_once()
        merged, meta = a.merged_state()
        assert merged["t_binds_total"]["value"] == 15
        series = {tuple(k): v
                  for k, v in merged["t_hits_total"]["series"]}
        assert series == {("filter",): 5, ("bind",): 2}
        assert meta["replica_count"] == 2
        # the local registry is live: an un-published increment on the
        # ANSWERING replica is already in the merge
        ra.get("t_binds_total").inc(1)
        merged2, _ = a.merged_state()
        assert merged2["t_binds_total"]["value"] == 16
    finally:
        a.stop()
        b.stop()


def test_dead_replica_slot_is_frozen_but_still_merged(tmp_path):
    path = str(tmp_path / "fed.seg")
    parent = _segment(_registry(100, {}, []), path)
    try:
        assert parent.start()
        pid = os.fork()
        if pid == 0:  # the replica that will crash
            try:
                child = _segment(_registry(7, {"filter": 3}, []), path)
                child.start()  # claims its own slot, publishes once
            finally:
                os._exit(0)  # no stop(): die with the slot claimed
        _, status = os.waitpid(pid, 0)
        assert status == 0
        merged, meta = parent.merged_state()
        assert merged["t_binds_total"]["value"] == 107
        dead = [r for r in meta["replicas"] if not r["self"]]
        assert len(dead) == 1 and not dead[0]["alive"]
        # a third replica prefers an EMPTY slot over the frozen one, so
        # the dead history keeps merging as long as the segment has room
        late = _segment(_registry(1, {}, []), path)
        try:
            assert late.start()
            assert late.slot not in (parent.slot, dead[0]["slot"])
            merged3, _ = late.merged_state()
            assert merged3["t_binds_total"]["value"] == 108
        finally:
            late.stop()
    finally:
        parent.stop()


@pytest.fixture
def served(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSHARE_FEDERATION_PATH",
                       str(tmp_path / "srv.seg"))
    fc = FakeCluster()
    for i in range(4):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    srv = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = srv.start()
    yield srv, port
    srv.stop()


def _get(port: int, path: str) -> tuple[int, str, str]:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    ctype = r.getheader("Content-Type") or ""
    conn.close()
    return r.status, body, ctype


def test_federated_scrape_equals_registry_sum_no_gauges(served):
    srv, port = served
    assert srv.federation is not None  # the segment came up
    peer = _segment(_registry(41, {}, []), srv.federation.path)
    try:
        assert peer.start()
        status, body, ctype = _get(port, "/metrics/federated")
        assert status == 200
        assert "text/plain" in ctype
        assert "# TYPE t_binds_total counter" in body
        assert "t_binds_total 41" in body  # the peer's slot merged in
        # every federated value is the sum across replicas: the local
        # native-serve counter must match the live registry exactly
        local = srv.registry.get(
            "tpushare_wire_native_serves_total")
        if local is not None:
            fed_total = sum(v for line in body.splitlines()
                            if line.startswith(
                                "tpushare_wire_native_serves_total")
                            for v in [float(line.rsplit(" ", 1)[1])])
            assert fed_total == sum(local.snapshot().values())
        # gauges stay per-process: none may appear in the federated sum
        assert "tpushare_fleet_free_chips" not in body
        snap_status, snap_body, _ = _get(
            port, "/inspect/fleet?federated=1")
        assert snap_status == 200
        snap = json.loads(snap_body)
        assert snap["federation"]["replica_count"] >= 2
        assert snap["federation"]["merged_totals"]["t_binds_total"] == 41
    finally:
        peer.stop()


def test_disabled_federation_falls_back_to_local_scrape(
        tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSHARE_FEDERATION", "0")
    fc = FakeCluster()
    fc.add_tpu_node("n0", chips=4, hbm_per_chip_mib=16000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    srv = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = srv.start()
    try:
        assert srv.federation is None
        status, body, _ = _get(port, "/metrics/federated")
        assert status == 200  # same surface, local-only sum
        assert "# TYPE" in body
    finally:
        srv.stop()
