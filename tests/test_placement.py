"""Placement engine unit + property tests (tpushare/core/placement.py).

Includes the reference design-doc scenarios as golden cases:
- binpack example (designs.md §2.2): free {12207, 8138, 4069, 16276},
  request 8138 -> the 8138 device ("min free that fits").
- node-level vs device-level fit (designs.md §2.1 / README demo 2): 8138
  spread across two chips must NOT satisfy a single-chip 8138 request.
"""

import random

import pytest

from tpushare.core.chips import ChipView, node_chips
from tpushare.core.placement import (
    PlacementRequest, fits, select_chips_py, utilization_pct, fragmentation)
from tpushare.core.topology import MeshTopology


def mk(frees, total=16276, shape=None):
    topo = MeshTopology(shape) if shape else MeshTopology.for_chip_count(len(frees))
    chips = [ChipView(i, topo.coords(i), total, total - f)
             for i, f in enumerate(frees)]
    return chips, topo


def test_binpack_min_free_that_fits():
    chips, topo = mk([12207, 8138, 4069, 16276])
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=8138))
    assert p is not None and p.chip_ids == (1,)


def test_device_level_fit_rejects_spread_memory():
    # 8138 free in total, but 4069 + 4069 on two chips: no single chip fits.
    chips, topo = mk([4069, 4069])
    req = PlacementRequest(hbm_mib=8138)
    assert not fits(chips, topo, req)
    assert select_chips_py(chips, topo, req) is None


def test_single_chip_fit_accepts():
    chips, topo = mk([4069, 8138])
    req = PlacementRequest(hbm_mib=8138)
    assert fits(chips, topo, req)
    assert select_chips_py(chips, topo, req).chip_ids == (1,)


def test_zero_count_normalizes_to_one():
    req = PlacementRequest(hbm_mib=1024, chip_count=0)
    assert req.chip_count == 1


def test_empty_request_rejected():
    with pytest.raises(ValueError):
        PlacementRequest(hbm_mib=0, chip_count=0)
    with pytest.raises(ValueError):
        PlacementRequest(hbm_mib=-1)


def test_unhealthy_chip_skipped():
    chips, topo = mk([16276, 16276])
    chips[0] = ChipView(0, chips[0].coords, 16276, 0, healthy=False)
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=1024))
    assert p.chip_ids == (1,)
    chips[1] = ChipView(1, chips[1].coords, 16276, 0, healthy=False)
    assert select_chips_py(chips, topo, PlacementRequest(hbm_mib=1024)) is None


def test_exclusive_chips_require_empty():
    chips, topo = mk([16000, 16276])  # chip 0 has 276 MiB used
    req = PlacementRequest(hbm_mib=0, chip_count=1)
    p = select_chips_py(chips, topo, req)
    assert p.chip_ids == (1,)


def test_multichip_contiguous_2x2_on_v5e16():
    chips = node_chips(16, 16000, mesh_shape=(4, 4))
    topo = MeshTopology((4, 4))
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=8000, chip_count=4))
    assert p is not None and p.contiguous and p.box == (2, 2)
    coords = [topo.coords(i) for i in p.chip_ids]
    xs = {c[0] for c in coords}
    ys = {c[1] for c in coords}
    assert len(xs) == 2 and len(ys) == 2  # a real 2x2 block


def test_multichip_prefers_tighter_pack_within_shape():
    # two candidate 1x2 boxes on a 1x4 mesh; (2,3) have less free -> chosen
    chips, topo = mk([16000, 16000, 9000, 9000], shape=(1, 4))
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=8000, chip_count=2))
    assert p is not None and set(p.chip_ids) == {2, 3}


def test_multichip_contiguity_beats_scatter():
    # Free chips at mesh corners + one free 2-chip strip; contiguous wins.
    topo = MeshTopology((2, 2))
    chips = [
        ChipView(0, (0, 0), 16000, 0),
        ChipView(1, (0, 1), 16000, 12000),
        ChipView(2, (1, 0), 16000, 0),
        ChipView(3, (1, 1), 16000, 12000),
    ]
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=8000, chip_count=2))
    assert p.contiguous
    assert set(p.chip_ids) == {0, 2}  # the (0,0)-(1,0) column


def test_multichip_no_contiguous_no_scatter_fails():
    # diagonal free chips only; contiguity required -> no placement
    topo = MeshTopology((2, 2))
    chips = [
        ChipView(0, (0, 0), 16000, 0),
        ChipView(1, (0, 1), 16000, 12000),
        ChipView(2, (1, 0), 16000, 12000),
        ChipView(3, (1, 1), 16000, 0),
    ]
    req = PlacementRequest(hbm_mib=8000, chip_count=2)
    assert select_chips_py(chips, topo, req) is None
    assert not fits(chips, topo, req)
    # ...but scatter opt-in reproduces the reference fork's behavior
    req2 = PlacementRequest(hbm_mib=8000, chip_count=2, allow_scatter=True)
    p = select_chips_py(chips, topo, req2)
    assert p is not None and not p.contiguous and set(p.chip_ids) == {0, 3}
    assert fits(chips, topo, req2)


def test_topology_pin():
    chips = node_chips(16, 16000, mesh_shape=(4, 4))
    topo = MeshTopology((4, 4))
    req = PlacementRequest(hbm_mib=1000, chip_count=4, topology=(1, 4))
    p = select_chips_py(chips, topo, req)
    assert p.box == (1, 4)
    with pytest.raises(ValueError):
        PlacementRequest(hbm_mib=1, chip_count=4, topology=(2, 3))


def test_mesh_mismatch_falls_back_to_1d():
    # node reports 3 chips but claims a 2x2 mesh: placement still works
    topo = MeshTopology((2, 2))
    chips = [ChipView(i, (i,), 16000, 0) for i in range(3)]
    p = select_chips_py(chips, topo, PlacementRequest(hbm_mib=1000, chip_count=2))
    assert p is not None and len(p.chip_ids) == 2


def test_metrics():
    chips, _ = mk([8138, 16276], total=16276)
    assert utilization_pct(chips) == pytest.approx(25.0)
    assert fragmentation(chips) == pytest.approx(1 - 16276 / (8138 + 16276))
    full, _ = mk([0, 0])
    assert fragmentation(full) == 0.0
    assert utilization_pct([]) == 0.0


def test_property_never_oversubscribe_and_fit_select_agree():
    rng = random.Random(42)
    for trial in range(300):
        n = rng.choice([1, 2, 4, 8, 16])
        total = rng.choice([8192, 16276, 32768])
        shape = MeshTopology.for_chip_count(n).shape
        topo = MeshTopology(shape)
        chips = [
            ChipView(i, topo.coords(i), total,
                     rng.randrange(0, total + 1),
                     healthy=rng.random() > 0.1)
            for i in range(n)
        ]
        req = PlacementRequest(
            hbm_mib=rng.choice([0, 512, 2048, 8138, total]),
            chip_count=rng.choice([1, 1, 1, 2, 4]),
            allow_scatter=rng.random() < 0.5,
        )
        if req.hbm_mib == 0 and req.chip_count == 0:
            continue
        p = select_chips_py(chips, topo, req)
        assert fits(chips, topo, req) == (p is not None)
        if p is None:
            continue
        assert len(p.chip_ids) == req.chip_count
        assert len(set(p.chip_ids)) == req.chip_count
        for cid in p.chip_ids:
            c = chips[cid]
            assert c.healthy
            demand = req.chip_demand_mib(c.total_hbm_mib)
            # the invariant: selection never oversubscribes a chip
            assert c.used_hbm_mib + demand <= c.total_hbm_mib
