"""Deployment artifact tests: manifests parse, contracts line up, and the
host-mutation installer script actually rewrites a scheduler manifest."""

import json
import os
import shutil
import subprocess

import pytest
import yaml

from tpushare import contract

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_all(relpath):
    with open(os.path.join(REPO, relpath)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def test_all_config_manifests_parse():
    for name in os.listdir(os.path.join(REPO, "config")):
        if name.endswith((".yaml", ".yml")):
            assert load_all(f"config/{name}"), name
        elif name.endswith(".json"):
            with open(os.path.join(REPO, "config", name)) as f:
                assert json.load(f), name


def test_all_samples_parse_and_request_tpu():
    for name in sorted(os.listdir(os.path.join(REPO, "samples"))):
        if not name.endswith(".yaml"):
            continue
        docs = load_all(f"samples/{name}")
        for doc in docs:
            # workload controllers nest the pod spec under
            # spec.template; bare Pods (the gang sample's explicit
            # members) carry it directly
            spec = doc["spec"]
            tmpl = spec["template"]["spec"] if "template" in spec else spec
            if "containers" not in tmpl:
                continue  # supporting objects (e.g. the gang sample's
                # headless Service) carry no workload
            limits = tmpl["containers"][0]["resources"]["limits"]
            # sharing pods request tpu-hbm; exclusive whole-chip pods
            # (e.g. the gang sample) request tpu-count only — either
            # routes the pod to the extender via managedResources
            assert contract.RESOURCE_HBM in limits \
                or contract.RESOURCE_COUNT in limits, name


def test_policy_config_matches_contract():
    with open(os.path.join(REPO, "config/scheduler-policy-config.json")) as f:
        policy = json.load(f)
    ext = policy["extenders"][0]
    managed = {m["name"] for m in ext["managedResources"]}
    assert managed == {contract.RESOURCE_HBM, contract.RESOURCE_COUNT}
    assert ext["nodeCacheCapable"] is True
    assert ext["bindVerb"] == "bind" and ext["filterVerb"] == "filter"
    # modern config must manage the same resources
    (cfg,) = load_all("config/kube-scheduler-config.yaml")
    modern = {m["name"] for m in cfg["extenders"][0]["managedResources"]}
    assert modern == managed


def test_serving_sample_topology_annotation_is_consistent():
    (doc,) = load_all("samples/5-serving.yaml")
    meta = doc["spec"]["template"]["metadata"]
    ann = meta["annotations"][contract.ANN_TOPOLOGY]
    limits = doc["spec"]["template"]["spec"]["containers"][0]["resources"]["limits"]
    dims = [int(x) for x in ann.split("x")]
    count = limits["aliyun.com/tpu-count"]
    assert dims[0] * dims[1] == count


@pytest.fixture
def fake_host(tmp_path):
    """A pretend control-plane host's /etc/kubernetes."""
    k8s = tmp_path / "etc-kubernetes"
    (k8s / "manifests").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "config/kube-scheduler.yaml"),
                k8s / "manifests" / "kube-scheduler.yaml")
    # strip the pre-registered tpushare config to simulate a stock host
    manifest = k8s / "manifests" / "kube-scheduler.yaml"
    text = manifest.read_text().replace(
        "        - --config=/etc/kubernetes/tpushare/kube-scheduler-config.yaml\n",
        "")
    manifest.write_text(text)
    return k8s


def run_script(name, env):
    return subprocess.run(
        ["bash", os.path.join(REPO, "deployer/docker", name)],
        env={**os.environ, **env}, capture_output=True, text=True)


def test_install_script_registers_extender_idempotently(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    r = run_script("install-sched-extender-on-host.sh", env)
    assert r.returncode == 0, r.stderr
    manifest = (fake_host / "manifests" / "kube-scheduler.yaml").read_text()
    assert "--config=/etc/kubernetes/tpushare/kube-scheduler-config.yaml" in manifest
    (doc,) = yaml.safe_load_all(manifest)  # still valid YAML
    cfg = yaml.safe_load(
        (fake_host / "tpushare" / "kube-scheduler-config.yaml").read_text())
    assert cfg["extenders"][0]["nodeCacheCapable"] is True
    backups = list((fake_host / "manifests").glob("*.tpushare-backup-*"))
    assert len(backups) == 1
    # second run is a no-op (no duplicate flag, no second backup)
    r2 = run_script("install-sched-extender-on-host.sh", env)
    assert r2.returncode == 0 and "already registered" in r2.stdout
    assert manifest == (fake_host / "manifests" / "kube-scheduler.yaml").read_text()


def test_uninstall_script_restores_backup(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    original = (fake_host / "manifests" / "kube-scheduler.yaml").read_text()
    run_script("install-sched-extender-on-host.sh", env)
    r = run_script("uninstall-sched-extender-on-host.sh", env)
    assert r.returncode == 0, r.stderr
    assert (fake_host / "manifests" / "kube-scheduler.yaml").read_text() == original


def _render_helm(template_path: str, values: dict) -> str:
    """Minimal Helm-template renderer for the subset this chart uses
    ({{ .Values.x }}, {{- if }}/{{- end }}, toYaml|indent, |default) — the
    image has no helm binary, and parse-only checks would miss golang
    template typos inside the YAML."""
    import re

    def lookup(path):
        cur = values
        for part in path.split(".")[2:]:  # drop leading '' and 'Values'
            cur = cur[part]
        return cur

    text = open(template_path).read()

    # {{- /* comments */ -}}
    text = re.sub(r"\{\{-?\s*/\*.*?\*/\s*-?\}\}\n?", "", text, flags=re.S)

    # {{- if .Values.x }} ... {{- end }} (no nesting in this chart).
    # Like real Helm, `{{-` chomps the preceding whitespace — without
    # that an INDENTED if/end (inside an env: list, say) would leave
    # its indentation behind, gluing the next line mid-document.
    def if_repl(m):
        return m.group(2) if lookup(m.group(1)) else ""

    text = re.sub(
        r"[ \t]*\{\{-? if (\.Values[.\w]+) \}\}\n(.*?)[ \t]*\{\{-? end \}\}\n?",
        if_repl, text, flags=re.S)

    # {{ toYaml .Values.x | indent N }}
    def toyaml_repl(m):
        block = yaml.safe_dump(lookup(m.group(1)), default_flow_style=False)
        pad = " " * int(m.group(2))
        return "\n".join(pad + line for line in block.strip().split("\n"))

    text = re.sub(r"\{\{ toYaml (\.Values[.\w]+) \| indent (\d+) \}\}",
                  toyaml_repl, text)

    # {{ .Values.x | default Y }} and {{ .Values.x }}
    def value_repl(m):
        try:
            return str(lookup(m.group(1)))
        except KeyError:
            if m.group(2) is not None:
                return m.group(2)
            raise

    text = re.sub(r"\{\{ (\.Values[.\w]+)(?: \| default (\S+))? \}\}",
                  value_repl, text)
    assert "{{" not in text, f"unrendered template syntax in {template_path}"
    return text


def test_chart_templates_render_to_valid_manifests():
    """Every chart template renders against values.yaml into parseable,
    well-formed k8s objects (VERDICT r1 item 6: render-check the chart,
    including the new evictor/recover DaemonSets)."""
    chart = os.path.join(REPO, "deployer/chart/tpushare-installer")
    with open(os.path.join(chart, "values.yaml")) as f:
        values = yaml.safe_load(f)
    rendered = {}
    for name in sorted(os.listdir(os.path.join(chart, "templates"))):
        text = _render_helm(os.path.join(chart, "templates", name), values)
        docs = [d for d in yaml.safe_load_all(text) if d]
        assert docs, f"{name} rendered to nothing with default values"
        for d in docs:
            assert d.get("kind") and d.get("apiVersion"), name
        rendered[name] = docs

    evict = rendered["device-plugin-evictor.yaml"][0]
    assert evict["kind"] == "DaemonSet"
    spec = evict["spec"]["template"]["spec"]
    assert spec["nodeSelector"] == {"tpushare": "true"}
    assert "dp-evict-on-host.sh" in spec["containers"][0]["args"][0]

    recover = rendered["device-plugin-recover.yaml"][0]
    assert recover["kind"] == "DaemonSet"
    spec = recover["spec"]["template"]["spec"]
    assert spec["nodeSelector"] == {"tpushare": "false"}
    assert "dp-recover-on-host.sh" in spec["containers"][0]["args"][0]

    # value gates actually gate
    off = dict(values)
    off["evictStockDevicePlugin"] = False
    text = _render_helm(os.path.join(
        chart, "templates/device-plugin-evictor.yaml"), off)
    assert not [d for d in yaml.safe_load_all(text) if d]


def test_evict_and_recover_scripts(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    stock = fake_host / "manifests" / "stock-tpu-device-plugin.yaml"
    stock.write_text("kind: DaemonSet\n")
    r = run_script("dp-evict-on-host.sh", env)
    assert r.returncode == 0 and not stock.exists()
    assert (fake_host / "tpushare-parked" /
            "stock-tpu-device-plugin.yaml").exists()
    r = run_script("dp-recover-on-host.sh", env)
    assert r.returncode == 0 and stock.exists()


def test_chart_sharding_mode_wires_scaleout_env_and_rbac():
    """extender.sharding=true must render the active-active env block
    (shard count, forward knob, a podIP-derived advertise URL) and the
    ClusterRole must grant lease "list" — membership and peer forward
    addresses are DISCOVERED by listing the shard leases, so a chart
    without "list" deploys replicas that can never see each other."""
    chart = os.path.join(REPO, "deployer/chart/tpushare-installer")
    with open(os.path.join(chart, "values.yaml")) as f:
        values = yaml.safe_load(f)
    values["extender"]["sharding"] = True
    values["extender"]["replicas"] = 3
    text = _render_helm(
        os.path.join(chart, "templates", "extender.yaml"), values)
    docs = [d for d in yaml.safe_load_all(text) if d]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    lease_rule = next(r for r in role["rules"]
                      if "leases" in r["resources"])
    assert "list" in lease_rule["verbs"]
    dep = next(d for d in docs if d["kind"] == "Deployment")
    env = {e["name"]: e for e in
           dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["TPUSHARE_SHARD_REPLICAS"]["value"] == "3"
    assert env["TPUSHARE_FORWARD"]["value"] == "1"
    assert env["POD_IP"]["valueFrom"]["fieldRef"]["fieldPath"] == \
        "status.podIP"
    # hostNetwork: podIP == host IP, container port == peer port, so
    # the advertised URL is replica-reachable as rendered
    assert dep["spec"]["template"]["spec"]["hostNetwork"] is True
    assert env["TPUSHARE_ADVERTISE_URL"]["value"] == \
        "http://$(POD_IP):12345"
    # and the block actually gates: default values render WITHOUT it
    values["extender"]["sharding"] = False
    text = _render_helm(
        os.path.join(chart, "templates", "extender.yaml"), values)
    assert "TPUSHARE_SHARD_REPLICAS" not in text


def test_chart_wires_qos_knobs_everywhere():
    """ISSUE 17: the QoS env knobs must reach both consumers — the
    extender (admission + pressure monitor) and the device plugin
    (container env stamping sized against the same overcommit bound) —
    and the evictor DaemonSet's manifest path / re-park interval must
    be values-driven (non-kubeadm hosts relocate /etc/kubernetes)."""
    chart = os.path.join(REPO, "deployer/chart/tpushare-installer")
    with open(os.path.join(chart, "values.yaml")) as f:
        values = yaml.safe_load(f)

    text = _render_helm(
        os.path.join(chart, "templates", "extender.yaml"), values)
    dep = next(d for d in yaml.safe_load_all(text)
               if d and d["kind"] == "Deployment")
    env = {e["name"]: e.get("value") for e in
           dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["TPUSHARE_QOS_OVERCOMMIT"] == "1.25"
    assert env["TPUSHARE_QOS_EVICT_BUDGET"] == "4"
    assert env["TPUSHARE_QOS_EVICT_WINDOW_S"] == "60"
    assert env["TPUSHARE_QOS_EVICT_BACKOFF_S"] == "120"
    assert env["TPUSHARE_QOS_DRF_CAP"] == "1.0"

    text = _render_helm(
        os.path.join(chart, "templates", "device-plugin.yaml"), values)
    ds = next(d for d in yaml.safe_load_all(text)
              if d and d["kind"] == "DaemonSet")
    env = {e["name"]: e.get("value") for e in
           ds["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["TPUSHARE_QOS_OVERCOMMIT"] == "1.25"

    values["evictor"] = {"hostManifestsDir": "/srv/kubernetes",
                         "intervalSeconds": 60}
    text = _render_helm(os.path.join(
        chart, "templates", "device-plugin-evictor.yaml"), values)
    ds = next(d for d in yaml.safe_load_all(text)
              if d and d["kind"] == "DaemonSet")
    spec = ds["spec"]["template"]["spec"]
    assert "sleep 60" in spec["containers"][0]["args"][0]
    assert spec["volumes"][0]["hostPath"]["path"] == "/srv/kubernetes"

def test_chart_wires_topo_knobs_into_extender():
    """ISSUE 18: the mesh-aware placement knobs must reach the extender
    env — topoWeight drives Prioritize's adjacency blend, noTopoScore
    is the byte-identical shape-blind escape hatch — and must be
    values-driven so an operator can retune without editing templates."""
    chart = os.path.join(REPO, "deployer/chart/tpushare-installer")
    with open(os.path.join(chart, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["topo"] == {"topoWeight": "0.5", "noTopoScore": "0"}

    text = _render_helm(
        os.path.join(chart, "templates", "extender.yaml"), values)
    dep = next(d for d in yaml.safe_load_all(text)
               if d and d["kind"] == "Deployment")
    env = {e["name"]: e.get("value") for e in
           dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["TPUSHARE_TOPO_WEIGHT"] == "0.5"
    assert env["TPUSHARE_NO_TOPO_SCORE"] == "0"

    values["topo"] = {"topoWeight": "1.0", "noTopoScore": "1"}
    text = _render_helm(
        os.path.join(chart, "templates", "extender.yaml"), values)
    dep = next(d for d in yaml.safe_load_all(text)
               if d and d["kind"] == "Deployment")
    env = {e["name"]: e.get("value") for e in
           dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["TPUSHARE_TOPO_WEIGHT"] == "1.0"
    assert env["TPUSHARE_NO_TOPO_SCORE"] == "1"
