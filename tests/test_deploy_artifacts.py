"""Deployment artifact tests: manifests parse, contracts line up, and the
host-mutation installer script actually rewrites a scheduler manifest."""

import json
import os
import shutil
import subprocess

import pytest
import yaml

from tpushare import contract

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_all(relpath):
    with open(os.path.join(REPO, relpath)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def test_all_config_manifests_parse():
    for name in os.listdir(os.path.join(REPO, "config")):
        if name.endswith((".yaml", ".yml")):
            assert load_all(f"config/{name}"), name
        elif name.endswith(".json"):
            with open(os.path.join(REPO, "config", name)) as f:
                assert json.load(f), name


def test_all_samples_parse_and_request_tpu():
    for name in sorted(os.listdir(os.path.join(REPO, "samples"))):
        if not name.endswith(".yaml"):
            continue
        docs = load_all(f"samples/{name}")
        for doc in docs:
            tmpl = doc["spec"]["template"]["spec"]
            limits = tmpl["containers"][0]["resources"]["limits"]
            assert contract.RESOURCE_HBM in limits, name


def test_policy_config_matches_contract():
    with open(os.path.join(REPO, "config/scheduler-policy-config.json")) as f:
        policy = json.load(f)
    ext = policy["extenders"][0]
    managed = {m["name"] for m in ext["managedResources"]}
    assert managed == {contract.RESOURCE_HBM, contract.RESOURCE_COUNT}
    assert ext["nodeCacheCapable"] is True
    assert ext["bindVerb"] == "bind" and ext["filterVerb"] == "filter"
    # modern config must manage the same resources
    (cfg,) = load_all("config/kube-scheduler-config.yaml")
    modern = {m["name"] for m in cfg["extenders"][0]["managedResources"]}
    assert modern == managed


def test_serving_sample_topology_annotation_is_consistent():
    (doc,) = load_all("samples/5-serving.yaml")
    meta = doc["spec"]["template"]["metadata"]
    ann = meta["annotations"][contract.ANN_TOPOLOGY]
    limits = doc["spec"]["template"]["spec"]["containers"][0]["resources"]["limits"]
    dims = [int(x) for x in ann.split("x")]
    count = limits["aliyun.com/tpu-count"]
    assert dims[0] * dims[1] == count


@pytest.fixture
def fake_host(tmp_path):
    """A pretend control-plane host's /etc/kubernetes."""
    k8s = tmp_path / "etc-kubernetes"
    (k8s / "manifests").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "config/kube-scheduler.yaml"),
                k8s / "manifests" / "kube-scheduler.yaml")
    # strip the pre-registered tpushare config to simulate a stock host
    manifest = k8s / "manifests" / "kube-scheduler.yaml"
    text = manifest.read_text().replace(
        "        - --config=/etc/kubernetes/tpushare/kube-scheduler-config.yaml\n",
        "")
    manifest.write_text(text)
    return k8s


def run_script(name, env):
    return subprocess.run(
        ["bash", os.path.join(REPO, "deployer/docker", name)],
        env={**os.environ, **env}, capture_output=True, text=True)


def test_install_script_registers_extender_idempotently(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    r = run_script("install-sched-extender-on-host.sh", env)
    assert r.returncode == 0, r.stderr
    manifest = (fake_host / "manifests" / "kube-scheduler.yaml").read_text()
    assert "--config=/etc/kubernetes/tpushare/kube-scheduler-config.yaml" in manifest
    (doc,) = yaml.safe_load_all(manifest)  # still valid YAML
    cfg = yaml.safe_load(
        (fake_host / "tpushare" / "kube-scheduler-config.yaml").read_text())
    assert cfg["extenders"][0]["nodeCacheCapable"] is True
    backups = list((fake_host / "manifests").glob("*.tpushare-backup-*"))
    assert len(backups) == 1
    # second run is a no-op (no duplicate flag, no second backup)
    r2 = run_script("install-sched-extender-on-host.sh", env)
    assert r2.returncode == 0 and "already registered" in r2.stdout
    assert manifest == (fake_host / "manifests" / "kube-scheduler.yaml").read_text()


def test_uninstall_script_restores_backup(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    original = (fake_host / "manifests" / "kube-scheduler.yaml").read_text()
    run_script("install-sched-extender-on-host.sh", env)
    r = run_script("uninstall-sched-extender-on-host.sh", env)
    assert r.returncode == 0, r.stderr
    assert (fake_host / "manifests" / "kube-scheduler.yaml").read_text() == original


def test_evict_and_recover_scripts(fake_host):
    env = {"HOST_K8S_DIR": str(fake_host)}
    stock = fake_host / "manifests" / "stock-tpu-device-plugin.yaml"
    stock.write_text("kind: DaemonSet\n")
    r = run_script("dp-evict-on-host.sh", env)
    assert r.returncode == 0 and not stock.exists()
    assert (fake_host / "tpushare-parked" /
            "stock-tpu-device-plugin.yaml").exists()
    r = run_script("dp-recover-on-host.sh", env)
    assert r.returncode == 0 and stock.exists()
