"""Live defragmentation (ISSUE 9): the repack rebalancer.

Claims under test, bottom-up:

- the PURE planning core finds the docs/pd.md §1.3 diagonal
  fragmentation, ranks victims by contiguous gain, keeps a plan's moves
  pairwise disjoint, and (with ``per_node``) clears a node that takes
  two evictions;
- the LIVE planner only ever victimizes pods that opted in via the
  ``tpushare.aliyun.com/movable`` annotation, and pins every move to
  both nodes' (epoch, counter) stamps;
- the executor relocates a restore-mode victim end to end with ZERO
  cache/apiserver drift, a CONCURRENT BIND between planning and
  execution demotes the move (the acceptance-criteria race, proven
  here), the budget/backoff governor bounds disruption, and a failed
  restore rolls the victim back to its source;
- the controller's ``run_once`` + ``/inspect/defrag`` serve the whole
  story over HTTP.
"""

import json
import urllib.request

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.core.chips import ChipView
from tpushare.core.placement import PlacementRequest
from tpushare.core.topology import MeshTopology
from tpushare.defrag import (
    ANN_MOVABLE, DEFRAG_DEMOTIONS, DEFRAG_FREED, DEFRAG_MOVES,
    DefragController, DefragExecutor, DefragPlanner, NodeState, Victim,
    plan_moves)
from tpushare.defrag.planner import worst_tier
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.obs.fleetwatch import CACHE_DRIFT, FleetWatch

HBM = 16384
TOPO = MeshTopology((2, 2))


# -- fixtures -----------------------------------------------------------------

def _fleet(n_nodes=2):
    fc = FakeCluster()
    for i in range(n_nodes):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache


def _pin(fc, cache, node, name, chips, hbm, movable=None):
    """Apiserver-backed placement on EXPLICIT chips (the fh-frag
    construction: pods pinned to mesh corners), annotation-movable or
    not. The uid (= the planner's pod_key) encodes the name so tests
    can map a move back to its victim."""
    ann = contract.placement_annotations(list(chips), hbm, HBM)
    if movable is not None:
        ann[ANN_MOVABLE] = movable
    created = fc.create_pod(make_pod(hbm=hbm, name=name, node=node,
                                     uid=f"uid-{name}", ann=ann))
    cache.add_or_update_pod(created)
    return created


def _frag_fleet(movable="true"):
    """n0 with both 2x2 corners occupied (2 free chips, no contiguous
    pair — one stranded chip at every tier), n1 empty."""
    fc, cache = _fleet()
    _pin(fc, cache, "n0", "corner-a", [0], HBM, movable=movable)
    _pin(fc, cache, "n0", "corner-b", [3], HBM, movable=movable)
    return fc, cache


def _drift_delta(fn):
    before = CACHE_DRIFT.snapshot()
    result = fn()
    after = CACHE_DRIFT.snapshot()
    return result, {k: after[k] - before.get(k, 0.0)
                    for k in after if after[k] != before.get(k, 0.0)}


def _moves_delta(fn):
    before = DEFRAG_MOVES.snapshot()
    result = fn()
    after = DEFRAG_MOVES.snapshot()
    return result, {k[0]: after[k] - before.get(k, 0.0)
                    for k in after if after[k] != before.get(k, 0.0)}


def _apiserver_chip_usage(fc, node):
    """Per-chip HBM committed on ``node`` according to apiserver truth
    alone (placement annotations of bound pods) — the oversubscription
    oracle. ANN_HBM_POD is the per-chip ask (reference per-device
    semantics: every chip in ANN_CHIP_IDS offers the full amount)."""
    usage = [0] * 4
    for pod in fc.list_pods(node_name=node):
        ann = (pod.get("metadata") or {}).get("annotations") or {}
        ids = ann.get(contract.ANN_CHIP_IDS)
        if not ids:
            continue
        for cid in json.loads(ids):
            usage[int(cid)] += int(ann.get(contract.ANN_HBM_POD) or 0)
    return usage


# -- pure planning core -------------------------------------------------------

def _views(used):
    return [ChipView(i, TOPO.coords(i), HBM, u, True)
            for i, u in enumerate(used)]


def _diag_state(name="s0", stamp=(0, 7)):
    victims = [
        Victim(pod_key="a", chip_ids=(0,), per_chip_mib=HBM,
               request=PlacementRequest(hbm_mib=HBM)),
        Victim(pod_key="b", chip_ids=(3,), per_chip_mib=HBM,
               request=PlacementRequest(hbm_mib=HBM)),
    ]
    return NodeState(name=name, stamp=stamp, topo=TOPO, hbm_per_chip=HBM,
                     views=_views([HBM, 0, 0, HBM]), victims=victims)


def _always_solve(target="t0", stamp=(0, 1)):
    """A solve callback with an infinite supply of chips on ``target``
    (fresh ids per call, so claims never collide)."""
    next_chip = [0]

    def solve(req, exclude, claimed):
        from tpushare.core.placement import Placement
        ids = tuple(range(next_chip[0], next_chip[0] + req.chip_count))
        next_chip[0] += req.chip_count
        return target, Placement(chip_ids=ids, box=None, origin=None,
                                 score=0), stamp
    return solve


def test_worst_tier_sees_the_diagonal_gap():
    tier, gap, contig = worst_tier(_diag_state())
    assert gap == 1 and contig == 1  # 2 eligible chips, no adjacent pair


def test_plan_moves_resolves_one_corner_by_default():
    plan = plan_moves([_diag_state()], _always_solve(), max_moves=4)
    assert len(plan.moves) == 1  # per_node=1: stamps move once per pass
    m = plan.moves[0]
    assert m.source == "s0" and m.target == "t0"
    assert m.source_stamp == (0, 7) and m.target_stamp == (0, 1)
    assert m.gain_chips == 1  # corner leaves -> an adjacent pair appears
    assert plan.fragmented_nodes == 1 and plan.stranded_chips_before == 1


def test_plan_moves_per_node_clears_both_corners():
    plan = plan_moves([_diag_state()], _always_solve(), max_moves=4,
                      per_node=2)
    assert [m.pod_key for m in plan.moves] == ["a", "b"]
    # second victim's gain is computed with the first already lifted:
    # corner a opens a pair (1->2), corner b then opens the full 2x2
    assert [m.gain_chips for m in plan.moves] == [1, 2]


def test_plan_moves_skips_immovable_and_nonpositive_gain():
    st = _diag_state()
    st.victims = [Victim(pod_key="a", chip_ids=(0,), per_chip_mib=HBM,
                         request=PlacementRequest(hbm_mib=HBM),
                         movable=False)]
    assert plan_moves([st], _always_solve(), max_moves=4).moves == []
    # a victim on an already-eligible chip frees nothing contiguous
    st2 = _diag_state()
    st2.victims = [Victim(pod_key="c", chip_ids=(1,), per_chip_mib=1,
                          request=PlacementRequest(hbm_mib=1))]
    assert plan_moves([st2], _always_solve(), max_moves=4).moves == []


def test_plan_moves_budget_and_claim_disjointness():
    states = [_diag_state("s0"), _diag_state("s1")]
    plan = plan_moves(states, _always_solve(), max_moves=1)
    assert len(plan.moves) == 1
    # two sources, one shared target: the claims must not overlap
    plan2 = plan_moves(states, _always_solve(), max_moves=4)
    seen = set()
    for m in plan2.moves:
        ids = set(m.placement.chip_ids)
        assert not (ids & seen)
        seen |= ids


def test_plan_moves_skips_sources_already_targeted():
    # two equally fragmented nodes (name tiebreak puts s0 first); the
    # solver lands s0's victim ON s1 -> s1 must not then be planned as
    # a source (its stamp will move when that move executes)
    from tpushare.core.placement import Placement
    states = [_diag_state("s0"), _diag_state("s1")]

    def solve(req, exclude, claimed):
        return "s1", Placement(chip_ids=(1,), box=None, origin=None,
                               score=0), (0, 9)
    plan = plan_moves(states, solve, max_moves=4)
    assert [m.source for m in plan.moves] == ["s0"]


# -- live planner -------------------------------------------------------------

def test_live_planner_only_victimizes_movable_pods():
    fc, cache = _fleet()
    _pin(fc, cache, "n0", "corner-a", [0], HBM, movable="true")
    _pin(fc, cache, "n0", "corner-b", [3], HBM)  # no annotation
    planner = DefragPlanner(cache)
    states = planner.collect_states()
    assert [s.name for s in states] == ["n0"]
    assert len(states[0].victims) == 1  # the unannotated pod is off-limits
    assert states[0].victims[0].pod_key == "uid-corner-a"
    assert states[0].victims[0].mode == "restore"


def test_live_planner_emits_stamped_moves():
    fc, cache = _frag_fleet()
    planner = DefragPlanner(cache)
    plan = planner.plan(max_moves=4)
    assert len(plan.moves) == 1
    m = plan.moves[0]
    assert m.source == "n0" and m.target == "n1"
    assert m.source_stamp == cache.peek_node("n0").version
    assert m.target_stamp == cache.peek_node("n1").version
    assert m.gain_chips == 1 and m.mode == "restore"


def test_live_planner_drain_annotation_selects_drain_mode():
    fc, cache = _frag_fleet(movable="drain")
    plan = DefragPlanner(cache).plan(max_moves=4)
    assert plan.moves and plan.moves[0].mode == "drain"


def test_live_planner_quiet_on_unfragmented_fleet():
    fc, cache = _fleet()
    _pin(fc, cache, "n0", "pair", [0, 1], 4096, movable="true")
    planner = DefragPlanner(cache)
    assert planner.collect_states() == []
    assert planner.plan(max_moves=4).moves == []


# -- executor: the move, the race, the governor -------------------------------

def test_restore_move_relocates_victim_with_zero_drift():
    fc, cache = _frag_fleet()
    plan = DefragPlanner(cache).plan(max_moves=4)
    executor = DefragExecutor(cache, fc, budget=4)
    freed0 = DEFRAG_FREED.value
    (results, moves_delta), drift = _drift_delta(
        lambda: _moves_delta(lambda: executor.execute(plan)))
    assert [r["outcome"] for r in results] == ["completed"]
    assert moves_delta == {"completed": 1.0}
    assert DEFRAG_FREED.value == freed0 + 1
    # apiserver truth: the victim now lives on n1, contiguous pair free
    moved = plan.moves[0]
    name = moved.pod_key.removeprefix("uid-")
    bound = fc.get_pod("default", name)
    assert bound["spec"]["nodeName"] == "n1"
    assert not any(u > HBM for u in _apiserver_chip_usage(fc, "n1"))
    # cache truth agrees: a 2-chip contiguous ask on n0 now fits
    from tpushare.core.placement import select_chips_py
    req = PlacementRequest(hbm_mib=1, chip_count=2, topology=(1, 2))
    info = cache.get_node_info("n0")
    assert select_chips_py(info.snapshot(), info.topology, req) is not None
    # and the continuous auditor sees NO divergence after the move
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    _, drift2 = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert drift == {} and drift2 == {}


def test_concurrent_bind_demotes_the_move():
    """The acceptance-criteria race: a bind lands on the TARGET between
    planning and execution. The stamp pin must demote the move — the
    victim stays put, nothing oversubscribes."""
    fc, cache = _frag_fleet()
    plan = DefragPlanner(cache).plan(max_moves=4)
    assert plan.moves
    # concurrent bind: a pod takes chips on n1, bumping its stamp
    info = cache.get_node_info("n1")
    racer = fc.create_pod(make_pod(hbm=HBM, name="racer"))
    info.allocate(racer, fc)
    cache.add_or_update_pod(fc.get_pod("default", "racer"))
    assert cache.peek_node("n1").version != plan.moves[0].target_stamp
    executor = DefragExecutor(cache, fc, budget=4)
    demote0 = DEFRAG_DEMOTIONS.value
    (results, moves_delta), drift = _drift_delta(
        lambda: _moves_delta(lambda: executor.execute(plan)))
    assert [r["outcome"] for r in results] == ["demoted"]
    assert moves_delta == {"demoted": 1.0}
    assert DEFRAG_DEMOTIONS.value == demote0 + 1
    # nothing moved, nothing oversubscribed, no drift
    assert fc.get_pod("default", "corner-a")["spec"]["nodeName"] == "n0"
    assert fc.get_pod("default", "corner-b")["spec"]["nodeName"] == "n0"
    assert not any(u > HBM for u in _apiserver_chip_usage(fc, "n1"))
    assert drift == {}


def test_concurrent_source_mutation_also_demotes():
    fc, cache = _frag_fleet()
    plan = DefragPlanner(cache).plan(max_moves=4)
    # the SOURCE mutates instead: the victim's neighbour departs
    gone = fc.get_pod("default", "corner-b")
    fc.delete_pod("default", "corner-b")
    cache.remove_pod(gone)
    results = DefragExecutor(cache, fc, budget=4).execute(plan)
    assert [r["outcome"] for r in results] == ["demoted"]


def test_budget_governor_and_backoff():
    fc, cache = _frag_fleet()
    now = [1000.0]
    executor = DefragExecutor(cache, fc, budget=1, window_s=60.0,
                              backoff_s=30.0, time_fn=lambda: now[0])
    plan = DefragPlanner(cache).plan(max_moves=4)
    stale = plan.moves[0]
    # consume the window's only slot (demoted still spends it: admission
    # precedes revalidation by design — a hot window stays bounded)
    _pin(fc, cache, "n1", "bump", [2], 1024)
    r1 = executor.execute_move(stale)
    r2 = executor.execute_move(stale)
    assert r1["outcome"] == "demoted"
    assert r2["outcome"] == "skipped_budget"
    # window rolls: the same move is admitted (and demoted) again
    now[0] += 61.0
    assert executor.execute_move(stale)["outcome"] == "demoted"
    state = executor.budget_state()
    assert state["budget"] == 1 and state["used_in_window"] == 1
    assert state["inflight_nodes"] == []


def test_failed_restore_rolls_back_and_backs_off():
    fc, cache = _frag_fleet()
    plan = DefragPlanner(cache).plan(max_moves=4)
    move = plan.moves[0]
    real_create = fc.create_pod

    def failing_create(pod):
        if not (pod.get("spec") or {}).get("nodeName"):
            raise RuntimeError("apiserver says no")  # the replacement
        return real_create(pod)
    fc.create_pod = failing_create
    now = [0.0]
    executor = DefragExecutor(cache, fc, budget=4, backoff_s=30.0,
                              time_fn=lambda: now[0])
    try:
        (results, moves_delta), drift = _drift_delta(
            lambda: _moves_delta(lambda: executor.execute(plan)))
    finally:
        fc.create_pod = real_create
    assert [r["outcome"] for r in results] == ["failed"]
    assert moves_delta == {"failed": 1.0}
    # rolled back: the victim is back on its source, fully accounted
    name = move.pod_key.removeprefix("uid-")
    assert fc.get_pod("default", name)["spec"]["nodeName"] == "n0"
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    _, drift2 = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert drift == {} and drift2 == {}
    # both touched nodes are in backoff: the next attempt is skipped
    retry = DefragPlanner(cache).plan(max_moves=4)
    assert retry.moves
    assert executor.execute_move(retry.moves[0])["outcome"] \
        == "skipped_backoff"
    # backoff expires with time, not with luck
    now[0] += 31.0
    assert executor.budget_state()["backoff_nodes"] == []


def test_drain_move_deletes_without_replacement():
    fc, cache = _frag_fleet(movable="drain")
    plan = DefragPlanner(cache).plan(max_moves=4)
    results = DefragExecutor(cache, fc, budget=4).execute(plan)
    assert [r["outcome"] for r in results] == ["completed"]
    name = plan.moves[0].pod_key.removeprefix("uid-")
    try:
        gone = fc.get_pod("default", name) is None
    except Exception:  # noqa: BLE001 — fake may raise on missing pods
        gone = True
    assert gone  # drained: the workload controller owns the successor


def test_checkpoint_hook_runs_before_eviction():
    from tpushare.contract.pod import pod_name, pod_namespace
    fc, cache = _frag_fleet()
    plan = DefragPlanner(cache).plan(max_moves=4)
    calls = []

    def hook(pod, move):
        # at hook time the victim must still be bound and accounted
        calls.append(fc.get_pod(pod_namespace(pod), pod_name(pod))
                     ["spec"]["nodeName"])

    executor = DefragExecutor(cache, fc, budget=4, checkpoint_hook=hook)
    results = executor.execute(plan)
    assert [r["outcome"] for r in results] == ["completed"]
    assert calls == ["n0"]


# -- controller + /inspect/defrag ---------------------------------------------

def test_controller_run_once_and_snapshot():
    fc, cache = _frag_fleet()
    ctl = DefragController(cache, cluster=fc, period_s=0)
    summary = ctl.run_once()
    assert summary["executed"] == 1
    assert summary["outcomes"] == ["completed"]
    snap = ctl.snapshot()
    assert snap["running"] is False and snap["passes"] == 1
    assert snap["plan"]["moves"][0]["source"] == "n0"
    assert snap["plan"]["moves"][0]["tier"]  # tier label rendered
    assert snap["recent_moves"][0]["outcome"] == "completed"
    assert snap["budget"]["budget"] == ctl.executor.budget
    assert snap["counters"]["freed_chips_total"] >= 1
    # the L of 3 free chips left behind is still 1 stranded (no 1x3 box
    # in a 2x2 mesh): a second pass moves the other corner, the third
    # finds the fleet clean and plans nothing
    assert ctl.run_once()["outcomes"] == ["completed"]
    assert ctl.run_once()["executed"] == 0
    assert ctl.snapshot()["passes"] == 3


def test_inspect_defrag_endpoint(monkeypatch):
    monkeypatch.setenv("TPUSHARE_DEFRAG", "0")  # no background thread
    fc, cache = _frag_fleet()
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    try:
        server.defrag.run_once()
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/inspect/defrag",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["passes"] == 1
        assert snap["plan"]["moves"][0]["target"] == "n1"
        assert snap["counters"]["moves_total"].get("completed", 0) >= 1
        # prefixed route too (kube-ecosystem tooling hits the prefix)
        with urllib.request.urlopen(
                f"{base}/tpushare-scheduler/inspect/defrag",
                timeout=10) as r:
            assert json.loads(r.read())["passes"] == 1
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "tpushare_defrag_plans_total" in text
        assert "tpushare_defrag_moves_total" in text
        assert "tpushare_defrag_demotions_total" in text
        assert "tpushare_defrag_freed_chips_total" in text
    finally:
        server.stop()


def test_defrag_opt_out_env():
    fc, cache = _fleet()
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    import os
    old = os.environ.get("TPUSHARE_DEFRAG")
    os.environ["TPUSHARE_DEFRAG"] = "0"
    try:
        port = server.start()
        assert server.defrag._thread is None  # opted out, never started
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/inspect/defrag", timeout=10) as r:
            assert json.loads(r.read())["running"] is False
    finally:
        if old is None:
            os.environ.pop("TPUSHARE_DEFRAG", None)
        else:
            os.environ["TPUSHARE_DEFRAG"] = old
        server.stop()
