"""Incident journal (ISSUE 19): record, survive a crash, replay.

The journal's whole value is that an arbitrary production window can be
re-driven through the wind tunnel LATER, deterministically. These tests
pin the three properties that make that trustworthy:

- **byte-identical replay** — a randomized hermetic storm, journaled
  and replayed twice through ``python -m tpushare.sim --replay``,
  produces the same bytes both times (no wall clock, no randomness on
  the replay path);
- **crash tolerance** — a torn tail line (crash mid-write) and a
  corrupted middle line (bit rot) are both skipped by the reader; the
  journal stays readable and replayable;
- **bounded disk** — rotation keeps one predecessor, so the directory
  never outgrows ~max_bytes no matter how long the stream runs.
"""

import json
import os
import random

import pytest

from tests.test_contract import make_pod
from tpushare.obs.journal import (
    SCHEMA,
    DecisionJournal,
    pod_spec_fields,
    read_journal,
)
from tpushare.sim.replay import replay_journal

FLEET = {"n_nodes": 4, "chips_per_node": 4, "hbm_per_chip_mib": 16000,
         "mesh": [2, 2]}


def storm(journal: DecisionJournal, seed: int, n: int = 60) -> None:
    """A hermetic decision stream: n pods filtered, most admitted, the
    admitted ones bound — the same shapes the explain store emits."""
    rng = random.Random(seed)
    for i in range(n):
        pod = make_pod(hbm=256 * rng.randrange(1, 8),
                       count=rng.choice([0, 0, 1, 2]),
                       name=f"s-{i}", uid=f"uid-s-{i}")
        key = f"default/s-{i}"
        ok = rng.random() < 0.8
        journal.decision_recorded("filter", key, pod, {
            "ok": 4 if ok else 0, "candidates": 4,
            "source": rng.choice(["computed", "native", "wirecache"]),
            "stamp": i})
        if ok:
            journal.decision_recorded("bind", key, pod, {
                "node": f"n{rng.randrange(4)}", "outcome": "bound"})


@pytest.fixture
def recorded(tmp_path):
    jdir = str(tmp_path / "journal")
    j = DecisionJournal(jdir, fleet_info=FLEET)
    storm(j, seed=7)
    j.flush()
    j.stop()
    return jdir


def test_journal_records_verify_and_replay_is_byte_identical(recorded):
    recs = list(read_journal(recorded))
    assert recs[0]["kind"] == "header"
    assert recs[0]["schema"] == SCHEMA
    assert recs[0]["fleet"] == FLEET
    decisions = [r for r in recs if r["kind"] == "decision"]
    assert len(decisions) > 60  # filters + binds
    assert all("spec" in r for r in decisions)  # the replay join holds
    out1 = replay_journal(recorded)
    out2 = replay_journal(recorded)
    assert json.dumps(out1, sort_keys=True) == \
        json.dumps(out2, sort_keys=True)
    assert out1["mode"] == "replay"
    assert out1["records"] == len(decisions)
    assert out1["recorded"]["pods"] == 60
    assert out1["replay"]["pods"] == 60
    assert out1["fleet"]["n_nodes"] == 4
    # the diff compares the two admission rates explicitly
    assert out1["diff"]["recorded_admission_rate"] == \
        out1["recorded"]["admission_rate"]


def test_replay_cli_round_trips_byte_identically(recorded, capsys):
    from tpushare.sim.__main__ import main
    assert main(["--replay", recorded]) == 0
    first = capsys.readouterr().out
    assert main(["--replay", recorded]) == 0
    second = capsys.readouterr().out
    assert first == second
    body = json.loads(first)
    assert body["mode"] == "replay" and body["policy"] == "binpack"


def test_replay_cli_rejects_conflicting_trace_knobs(recorded):
    from tpushare.sim.__main__ import main
    with pytest.raises(SystemExit):
        main(["--replay", recorded, "--pods", "50"])


def test_crash_mid_write_truncated_tail_is_skipped(recorded):
    files = sorted(os.listdir(recorded))
    path = os.path.join(recorded, files[-1])
    whole = len(list(read_journal(recorded)))
    with open(path, "rb") as f:
        data = f.read()
    # crash mid-write: the tail line loses its last 10 bytes
    with open(path, "wb") as f:
        f.write(data[:-10])
    recs = list(read_journal(recorded))
    assert len(recs) == whole - 1  # exactly the torn line dropped
    replay_journal(recorded)  # still replayable


def test_corrupted_middle_line_fails_crc_and_is_skipped(recorded):
    files = sorted(os.listdir(recorded))
    path = os.path.join(recorded, files[-1])
    with open(path, "rb") as f:
        lines = f.read().splitlines(keepends=True)
    whole = len(list(read_journal(recorded)))
    mid = len(lines) // 2
    # flip a digit inside the record: still valid JSON, CRC now wrong
    for a, b in ((b"1", b"2"), (b"3", b"4"), (b"7", b"8")):
        corrupted = lines[mid].replace(a, b, 1)
        if corrupted != lines[mid]:
            break
    assert corrupted != lines[mid]
    lines[mid] = corrupted
    with open(path, "wb") as f:
        f.write(b"".join(lines))
    assert len(list(read_journal(recorded))) == whole - 1


def test_rotation_bounds_disk_to_max_bytes(tmp_path):
    jdir = str(tmp_path / "bounded")
    j = DecisionJournal(jdir, max_mb=0.05, fleet_info=FLEET)  # 50 KiB
    for seed in range(8):
        storm(j, seed=seed, n=50)
        j.flush()
    j.stop()
    files = sorted(os.listdir(jdir))
    assert len(files) <= 2  # active + ONE predecessor
    total = sum(os.path.getsize(os.path.join(jdir, f)) for f in files)
    # each file is bounded by the rotate threshold (max_bytes/2) plus
    # the one flush batch that crossed it — two files stay ~max_bytes
    assert total <= int(0.05 * 1024 * 1024 * 2)
    # the surviving window still replays
    out = replay_journal(jdir)
    assert out["recorded"]["pods"] > 0


def test_unparseable_pod_never_kills_the_stream(tmp_path):
    j = DecisionJournal(str(tmp_path / "odd"), fleet_info=FLEET)
    assert pod_spec_fields(make_pod(hbm=64)) is not None
    assert pod_spec_fields(None) is None
    assert pod_spec_fields({"nospec": True}) is None
    # a contradictory mesh annotation raises inside the contract parser
    # ("2x4" covers 8 chips, the request asks for 1) — the journal
    # records the decision without a spec instead of dying
    bad = make_pod(hbm=128, count=1,
                   ann={"tpushare.aliyun.com/mesh-shape": "2x4"})
    j.decision_recorded("filter", "default/bad", bad, {"ok": 0,
                                                       "candidates": 0})
    j.flush()
    j.stop()
    decisions = [r for r in read_journal(str(tmp_path / "odd"))
                 if r["kind"] == "decision"]
    assert len(decisions) == 1
    assert decisions[0]["pod_key"] == "default/bad"
