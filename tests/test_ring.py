"""Consistent-hash ring unit tests (active-active sharding tentpole).

The ring is the map every replica must agree on: determinism across
processes, balance within the O(1/sqrt(vnodes)) envelope, and the
consistency property (a membership change moves only ~1/N of the fleet,
every moved node landing on the joining/leaving member's account).
"""

from tpushare.ha.ring import DEFAULT_VNODES, HashRing, stable_hash

NAMES = [f"node-{i}" for i in range(3000)]


def test_stable_hash_is_process_independent():
    # blake2b-64, not hash(): these exact values must hold under any
    # PYTHONHASHSEED or the replicas disagree on ownership
    assert stable_hash("node-0") == stable_hash("node-0")
    assert stable_hash("node-0") != stable_hash("node-1")
    assert 0 <= stable_hash("x") < 2 ** 64


def test_owner_deterministic_across_instances_and_member_order():
    r1 = HashRing(["rb", "ra", "rc"])
    r2 = HashRing(["ra", "rc", "rb"])  # construction order irrelevant
    assert r1.members == r2.members == ("ra", "rb", "rc")
    for n in NAMES[:200]:
        assert r1.owner(n) == r2.owner(n)


def test_empty_and_single_member_rings():
    empty = HashRing([])
    assert empty.owner("n") is None
    assert empty.leader() is None
    solo = HashRing(["only"], vnodes=1)
    assert all(solo.owner(n) == "only" for n in NAMES[:50])
    assert solo.leader() == "only"


def test_leader_is_lowest_member():
    assert HashRing(["rc", "ra", "rb"]).leader() == "ra"


def test_vnodes_balance_shards():
    ring = HashRing(["ra", "rb", "rc"], vnodes=DEFAULT_VNODES)
    sizes = ring.shard_sizes(NAMES)
    assert sum(sizes.values()) == len(NAMES)
    fair = len(NAMES) / 3
    for member, size in sizes.items():
        # 64 vnodes: expected imbalance O(1/sqrt(64)) ~ 12.5%; the
        # bound here is loose (2x) so the test pins the mechanism, not
        # the exact hash draw
        assert 0.75 * fair <= size <= 1.25 * fair, (member, sizes)


def test_membership_change_moves_about_one_nth():
    before = HashRing(["ra", "rb", "rc", "rd"])
    after = HashRing(["ra", "rb", "rc", "rd", "re"])
    moved = [n for n in NAMES if before.owner(n) != after.owner(n)]
    # a CONSISTENT hash: only the joiner's share moves...
    assert all(after.owner(n) == "re" for n in moved)
    # ...and that share is ~1/5 of the fleet, nowhere near a reshuffle
    assert 0.10 * len(NAMES) <= len(moved) <= 0.35 * len(NAMES), \
        len(moved)
    # leaving is symmetric: removing re hands its nodes back exactly
    back = HashRing(["ra", "rb", "rc", "rd"])
    for n in moved:
        assert back.owner(n) == before.owner(n)


def test_shard_sizes_and_describe():
    ring = HashRing(["ra", "rb"])
    sizes = ring.shard_sizes(["a", "b", "c"])
    assert set(sizes) == {"ra", "rb"}
    assert sum(sizes.values()) == 3
    d = ring.describe()
    assert d["members"] == ["ra", "rb"]
    assert d["points"] == 2 * d["vnodes"]
