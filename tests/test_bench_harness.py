"""The bench's TPU-subprocess discipline (VERDICT r3 item 2).

The rig's chip sits behind a single-client relay that wedges for hours
if a JAX client is SIGKILLed, and a wedged backend init blocks inside
the PJRT C call where SIGINT cannot be processed. These tests pin the
recovery protocol hermetically (no TPU involved): SIGINT first, wait
for self-exit second, abandon-running third — and never SIGKILL.
"""

import importlib.util
import os
import sys
import time

import pytest

_here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_here, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def test_runner_success_captures_stdout():
    rc, out, err, note = bench._run_tpu_subprocess(
        [sys.executable, "-c", "print('healthy')"], timeout_s=30)
    assert rc == 0
    assert "healthy" in out
    assert note == ""


def test_runner_sigint_interrupts_python_level_hang():
    # -S skips site processing: this rig's sitecustomize imports jax
    # (seconds of uninterruptible C), which under load can outlast the
    # SIGINT grace and flake the test — the runner's signal protocol is
    # what's under test here, not the rig's interpreter startup
    t0 = time.time()
    rc, out, err, note = bench._run_tpu_subprocess(
        [sys.executable, "-S", "-c", "import time; time.sleep(60)"],
        timeout_s=1.0, sigint_grace_s=10.0)
    assert rc is not None and rc != 0  # KeyboardInterrupt exit
    assert "SIGINT" in note
    assert time.time() - t0 < 30  # did not wait out the sleep


def test_runner_waits_out_sigint_immune_child():
    # a client blocked in a C call can't process SIGINT; the protocol
    # waits for its self-exit instead of SIGKILLing it (SIG_IGN models
    # the unprocessable-signal state hermetically)
    # bash sets the SIG_IGN disposition instantly (a python child can
    # be hit mid-interpreter-startup, before any handler is installed)
    rc, out, err, note = bench._run_tpu_subprocess(
        ["bash", "-c", "trap '' INT; sleep 3; echo 'late answer'"],
        timeout_s=0.5, sigint_grace_s=0.5, self_exit_wait_s=30.0)
    assert rc == 0
    assert "late answer" in out
    assert "self-exited" in note


def test_runner_abandons_never_kills():
    rc, out, err, note = bench._run_tpu_subprocess(
        ["bash", "-c", "trap '' INT; echo alive; sleep 15"],
        timeout_s=0.5, sigint_grace_s=0.3, self_exit_wait_s=0.0)
    assert rc is None  # abandoned, not reaped
    assert "NOT killed" in note
    # the abandoned child is genuinely still alive (not SIGKILLed):
    # its flushed stdout proves it ran; nothing reaped it
    assert "alive" in out


def test_probe_retries_once_then_succeeds(monkeypatch):
    # attempt 1's client EXITED (self-exit with the far end's error) —
    # the slot is free, so exactly one retry is made
    calls = []

    def fake_run(cmd, timeout_s, env=None, label="", self_exit_wait_s=0.0,
                 sigint_grace_s=20.0):
        calls.append(label)
        if len(calls) == 1:
            return 1, "", "RuntimeError: UNAVAILABLE", \
                f"{label}: blocked past SIGINT, self-exited rc=1"
        return 0, "tpu\n", "", ""

    monkeypatch.setattr(bench, "_run_tpu_subprocess", fake_run)
    monkeypatch.setenv("TPUSHARE_WEDGE_PAUSE", "0")
    probe = bench._probe_backend_resilient()
    assert probe["ok"] is True
    assert probe["summary"] == "tpu"
    assert len(calls) == 2


def test_probe_never_retries_past_a_still_alive_client(monkeypatch):
    # attempt 1 was ABANDONED (rc None: still blocked, still holding a
    # relay slot) — retrying would run two TPU clients concurrently,
    # so the probe must stop at one attempt
    calls = []

    def fake_run(cmd, timeout_s, env=None, label="", self_exit_wait_s=0.0,
                 sigint_grace_s=20.0):
        calls.append(label)
        return None, "", "", f"{label}: hung — NOT killed"

    monkeypatch.setattr(bench, "_run_tpu_subprocess", fake_run)
    monkeypatch.setenv("TPUSHARE_WEDGE_PAUSE", "0")
    probe = bench._probe_backend_resilient()
    assert probe["ok"] is False
    assert len(calls) == 1
    assert "NOT killed" in probe["summary"]


def test_probe_two_failures_is_error_with_both_attempts(monkeypatch):
    def fake_run(cmd, timeout_s, env=None, label="", self_exit_wait_s=0.0,
                 sigint_grace_s=20.0):
        return 1, "", "RuntimeError: UNAVAILABLE: TPU backend setup", ""

    monkeypatch.setattr(bench, "_run_tpu_subprocess", fake_run)
    monkeypatch.setenv("TPUSHARE_WEDGE_PAUSE", "0")
    probe = bench._probe_backend_resilient()
    assert probe["ok"] is False
    assert "UNAVAILABLE" in probe["summary"]
    assert len(probe["attempts"]) == 2


def test_probe_real_jax_subprocess_healthy_path():
    # end-to-end with a REAL jax-importing subprocess. The default probe
    # cmd must not run in tests: this rig's sitecustomize pins
    # jax_platforms to the real backend in every fresh interpreter (env
    # vars are not enough), so the hermetic path forces CPU in-process,
    # exactly like tests/conftest.py does
    probe = bench._probe_backend_resilient(probe_cmd=[
        sys.executable, "-c",
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "print(jax.default_backend())"])
    assert probe["ok"] is True, probe
    assert probe["summary"] == "cpu"


def test_onchip_failed_probe_is_skipped_env(monkeypatch):
    """A wedged/unreachable tunnel is an ENVIRONMENT verdict: the
    on-chip section reports skipped_env instead of error, so a wedged
    rig cannot redden hermetic+wire results it says nothing about
    (BENCH_r05's bench_check_failures: 1 was exactly this)."""
    monkeypatch.setattr(
        bench, "_probe_backend_resilient",
        lambda: {"ok": False,
                 "summary": "jax backend init failed/hung (1 attempt)",
                 "attempts": ["attempt 1: rc=None probe1: hung"]})
    out = bench.onchip_tests()
    assert out["status"] == "skipped_env"
    assert "environment" in out["summary"]
    assert "init failed/hung" in out["summary"]


def test_onchip_midsuite_wedge_is_skipped_env(monkeypatch):
    """A suite that times out after a HEALTHY probe is the documented
    mid-suite tunnel wedge (docs/perf.md runbook): also environment,
    with the abandon note preserved for diagnosis."""
    monkeypatch.setattr(bench, "_probe_backend_resilient",
                        lambda: {"ok": True, "summary": "tpu",
                                 "attempts": ["attempt 1: ok"]})
    monkeypatch.setattr(
        bench, "_run_tpu_subprocess",
        lambda *a, **kw: (None, "", "", "tests_tpu: hung >10s, SIGINT "
                          "unprocessed — left running; NOT killed"))
    out = bench.onchip_tests(timeout_s=10)
    assert out["status"] == "skipped_env"
    assert "NOT killed" in out["summary"]


def test_preflight_hang_maps_to_skipped_env_in_bounded_time(monkeypatch):
    # the BENCH_r03 wedge: init hangs at the very first touch, blocked
    # where SIGINT cannot be processed. The preflight must convert that
    # into a skipped_env verdict in bounded WALL-CLOCK time — measured
    # here against a real SIGINT-immune subprocess (the wedge
    # signature), not a monkeypatched stub. (A client that DOES die on
    # SIGINT after the deadline self-resolved — that shape falls
    # through to the patient machinery instead, by design.)
    monkeypatch.setenv("TPUSHARE_PREFLIGHT_TIMEOUT", "0.5")
    t0 = time.monotonic()
    probe = bench._probe_backend_resilient(probe_cmd=[
        sys.executable, "-c",
        "import signal, time\n"
        "signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "time.sleep(45)"])
    elapsed = time.monotonic() - t0
    assert elapsed < 20, f"preflight not bounded: {elapsed:.1f}s"
    assert probe["ok"] is False
    assert "preflight" in probe["summary"]
    assert probe["attempts"] and "preflight" in probe["attempts"][0]


def test_preflight_never_sigkills_a_blocked_client(monkeypatch):
    # a client blocked in the PJRT C call processes no signals at all;
    # the preflight must ABANDON it (rc None path), not SIGKILL it —
    # proven with a subprocess that ignores SIGINT/SIGTERM and writes a
    # liveness file after the probe has given up on it.
    import signal
    import subprocess
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        alive = os.path.join(td, "alive")
        code = (
            "import os, signal, sys, time\n"
            "signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "time.sleep(8)\n"  # outlives the 5 s SIGINT grace: rc None
            f"open({alive!r}, 'w').write('still here')\n")
        monkeypatch.setenv("TPUSHARE_PREFLIGHT_TIMEOUT", "0.3")
        probe = bench._probe_backend_resilient(
            probe_cmd=[sys.executable, "-c", code])
        assert probe["ok"] is False
        assert "NOT killed" in probe["summary"]
        # the abandoned client survived the probe and self-exited on
        # its own schedule — a SIGKILL would have left no liveness file
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not os.path.exists(alive):
            time.sleep(0.1)
        assert os.path.exists(alive), "blocked client was killed"


def test_preflight_healthy_backend_skips_patient_machinery(monkeypatch):
    # a healthy backend answers the preflight; the patient attempts
    # (and their wedge-waits) must never run
    calls = []

    def fake_run(cmd, timeout_s, env=None, label="", self_exit_wait_s=0.0,
                 sigint_grace_s=20.0):
        calls.append((label, timeout_s, self_exit_wait_s))
        return 0, "tpu\n", "", ""

    monkeypatch.setattr(bench, "_run_tpu_subprocess", fake_run)
    probe = bench._probe_backend_resilient()
    assert probe["ok"] is True and probe["summary"] == "tpu"
    assert [c[0] for c in calls] == ["preflight"]
    # and the preflight itself never waits for a self-exit: bounded
    assert calls[0][2] == 0.0
