"""Preempt verb: per-chip victim refinement.

The reference never implements ExtenderConfig.PreemptVerb (vendored
types.go:183,219-254) — kube-scheduler's scalar victim selection has the
same node-level-vs-device-level blind spot its Filter has
(designs.md:13,34,42), so a victim set can free plenty of aggregate HBM
while no single chip (or contiguous sub-slice) becomes able to host the
preemptor. These tests cover the refinement core (NodeInfo.victims_to_fit)
and the wire handler (meta + full victim forms).
"""

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.extender.handlers import PreemptHandler
from tpushare.extender.metrics import Registry
from tpushare.k8s import FakeCluster


def _cluster(chips=2, hbm=8192, mesh=None):
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=chips, hbm_per_chip_mib=hbm, mesh=mesh)
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache, cache.get_node_info("n1")


def _bind(fc, info, name, hbm, count=0, priority=0):
    pod = make_pod(hbm=hbm, count=count, name=name)
    pod["spec"]["priority"] = priority
    pod = fc.create_pod(pod)
    info.allocate(pod, fc)
    return fc.get_pod("default", name)


def _chips_of(pod):
    return contract.chip_ids_from_annotations(pod)


# -- refinement core ----------------------------------------------------------

def test_minimal_subset_frees_one_chip():
    # chip0: 4+2 used (free 2), chip1: 6 used (free 2) -> a 4 GiB pod
    # fits nowhere; evicting only the 2 GiB pod frees chip0 to 4 — the
    # 1-minimal answer
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    v2 = _bind(fc, info, "v2", 6144, priority=10)
    assert _chips_of(v3) == _chips_of(v1)  # binpack co-placed with v1
    preemptor = make_pod(hbm=4096, name="high")
    order = [p["metadata"]["uid"] for p in (v3, v1, v2)]  # lowest prio first
    subset = info.victims_to_fit(preemptor, order)
    assert subset == [v3["metadata"]["uid"]]


def test_priority_order_prefers_cheapest_eviction():
    # both 6 GiB victims would individually free a chip; the LOWER
    # priority one must be chosen
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 6144, priority=0)
    v2 = _bind(fc, info, "v2", 6144, priority=100)
    preemptor = make_pod(hbm=4096, name="high")
    subset = info.victims_to_fit(
        preemptor, [v1["metadata"]["uid"], v2["metadata"]["uid"]])
    assert subset == [v1["metadata"]["uid"]]


def test_none_when_no_victim_set_suffices():
    # the non-victim 6 GiB occupant caps chip0 free at 2; chip1's
    # occupant is not a candidate either -> refinement must say "drop
    # this node", not return a useless victim list
    fc, cache, info = _cluster()
    _bind(fc, info, "keep0", 6144, priority=1000)
    small = _bind(fc, info, "small", 2048, priority=0)
    _bind(fc, info, "keep1", 6144, priority=1000)
    preemptor = make_pod(hbm=4096, name="high")
    assert info.victims_to_fit(preemptor, [small["metadata"]["uid"]]) is None


def test_empty_subset_when_pod_already_fits():
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 2048)
    preemptor = make_pod(hbm=4096, name="high")
    assert info.victims_to_fit(preemptor, [v1["metadata"]["uid"]]) == []


def test_contiguous_subslice_preemption():
    # 2x2 mesh, every chip holds a 6 GiB pod. A 2-chip preemptor needs a
    # CONTIGUOUS pair: evicting the diagonal (0,3) frees 2 chips that are
    # useless together; refinement must end on an adjacent pair and prune
    # the diagonal leftovers
    fc, cache, info = _cluster(chips=4, mesh="2x2")
    pods = [_bind(fc, info, f"v{i}", 6144, priority=i * 10)
            for i in range(4)]
    by_chip = {_chips_of(p)[0]: p for p in pods}
    assert sorted(by_chip) == [0, 1, 2, 3]
    preemptor = make_pod(hbm=8192, count=2, name="high")
    # eviction preference: chips 0, 3 (the useless diagonal) first
    order = [by_chip[0]["metadata"]["uid"], by_chip[3]["metadata"]["uid"],
             by_chip[1]["metadata"]["uid"], by_chip[2]["metadata"]["uid"]]
    subset = info.victims_to_fit(preemptor, order)
    assert subset is not None
    freed = sorted(_chips_of(by_chip_pod)[0]
                   for by_chip_pod in pods
                   if by_chip_pod["metadata"]["uid"] in subset)
    # a 1-minimal set freeing an adjacent pair (0,1 after pruning 3)
    assert freed == [0, 1]
    assert len(subset) == 2


def test_victims_not_on_this_node_free_nothing():
    fc, cache, info = _cluster()
    _bind(fc, info, "v1", 6144)
    _bind(fc, info, "v2", 6144)
    preemptor = make_pod(hbm=4096, name="high")
    # a UID the node has never seen cannot help
    assert info.victims_to_fit(preemptor, ["ghost-uid"]) is None


# -- wire handler -------------------------------------------------------------

def _handler(cache):
    return PreemptHandler(cache, Registry())


def test_handler_meta_victims_roundtrip():
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    _bind(fc, info, "v2", 6144, priority=10)
    # controller sync would do this; tests drive the cache directly
    for name in ("v1", "v2", "v3"):
        cache.add_or_update_pod(fc.get_pod("default", name))
    preemptor = make_pod(hbm=4096, name="high")
    args = {
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]},
                            {"UID": v3["metadata"]["uid"]}],
                   "NumPDBViolations": 1},
        },
    }
    out = _handler(cache).handle(args)
    got = out["NodeNameToMetaVictims"]["n1"]
    assert got["Pods"] == [{"UID": v3["metadata"]["uid"]}]
    assert got["NumPDBViolations"] == 1  # passed through (upper bound)


def test_handler_full_victims_form():
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    _bind(fc, info, "v2", 6144, priority=10)
    preemptor = make_pod(hbm=4096, name="high")
    args = {
        "Pod": preemptor,
        "NodeNameToVictims": {
            "n1": {"Pods": [v1, v3], "NumPDBViolations": 0},
        },
    }
    out = _handler(cache).handle(args)
    # reply is ALWAYS the meta form (nodeCacheCapable contract)
    assert out["NodeNameToMetaVictims"]["n1"]["Pods"] == [
        {"UID": v3["metadata"]["uid"]}]


def test_handler_drops_hopeless_node_and_counts_it():
    fc, cache, info = _cluster()
    _bind(fc, info, "keep0", 6144, priority=1000)
    small = _bind(fc, info, "small", 2048, priority=0)
    _bind(fc, info, "keep1", 6144, priority=1000)
    cache.add_or_update_pod(fc.get_pod("default", "small"))
    reg = Registry()
    h = PreemptHandler(cache, reg)
    out = h.handle({
        "Pod": make_pod(hbm=4096, name="high"),
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": small["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert out["NodeNameToMetaVictims"] == {}
    assert "tpushare_preempt_nodes_dropped_total 1" in reg.expose()


def test_handler_unknown_node_dropped():
    fc, cache, info = _cluster()
    out = _handler(cache).handle({
        "Pod": make_pod(hbm=4096, name="high"),
        "NodeNameToMetaVictims": {
            "ghost-node": {"Pods": [{"UID": "u"}], "NumPDBViolations": 0},
        },
    })
    assert out["NodeNameToMetaVictims"] == {}


def test_no_shrink_when_preemptor_needs_unmanaged_resources():
    # kube-scheduler never re-validates after the extender edits a victim
    # set, so a CPU-requesting preemptor must get the FULL victim list
    # back (validated for TPU feasibility), never a TPU-minimal subset
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    for name in ("v1", "v3"):
        cache.add_or_update_pod(fc.get_pod("default", name))
    preemptor = make_pod(hbm=4096, name="high")
    preemptor["spec"]["containers"][0]["resources"]["requests"] = {
        "cpu": "8"}
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]},
                            {"UID": v3["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    got = {p["UID"] for p in out["NodeNameToMetaVictims"]["n1"]["Pods"]}
    assert got == {v1["metadata"]["uid"], v3["metadata"]["uid"]}


def test_no_shrink_when_preemptor_has_affinity():
    fc, cache, info = _cluster()
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    for name in ("v1", "v3"):
        cache.add_or_update_pod(fc.get_pod("default", name))
    preemptor = make_pod(hbm=4096, name="high")
    preemptor["spec"]["affinity"] = {"podAntiAffinity": {}}
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]},
                            {"UID": v3["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert len(out["NodeNameToMetaVictims"]["n1"]["Pods"]) == 2


def test_hopeless_node_dropped_even_without_shrink():
    fc, cache, info = _cluster()
    _bind(fc, info, "keep0", 6144, priority=1000)
    small = _bind(fc, info, "small", 2048, priority=0)
    _bind(fc, info, "keep1", 6144, priority=1000)
    cache.add_or_update_pod(fc.get_pod("default", "small"))
    preemptor = make_pod(hbm=4096, name="high")
    preemptor["spec"]["containers"][0]["resources"]["requests"] = {
        "cpu": "8"}
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": small["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert out["NodeNameToMetaVictims"] == {}


def test_watch_lag_never_selects_unresolvable_victims():
    # A victim whose pod object has not synced also has no known
    # placement (add_or_update_pod registers both atomically), so it
    # frees nothing and can never be selected for eviction — lag
    # degrades to "no refinement possible", never to "evict the
    # priority-100 pod because its priority guessed as 0". (The
    # reversed-scheduler-order fallback in _victim_order is
    # defense-in-depth on top of this invariant.)
    fc, cache, info = _cluster()
    v_hi = _bind(fc, info, "hi", 6144, priority=100)   # chip A
    v_lo = _bind(fc, info, "lo", 6144, priority=0)     # chip B
    lagged = SchedulerCache(fc)
    lagged.get_node_info("n1")  # node known, pods not yet synced
    h = PreemptHandler(lagged, Registry())
    out = h.handle({
        "Pod": make_pod(hbm=4096, name="high"),
        # scheduler convention: highest priority first
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v_hi["metadata"]["uid"]},
                            {"UID": v_lo["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    got = out["NodeNameToMetaVictims"]["n1"]["Pods"]
    # the lagged cache sees no placements -> victims_to_fit says "fits
    # with no eviction", which contradicts the scheduler's verdict, so
    # the handler DEFERS: the scheduler's own full victim set comes back
    # unchanged (its choice, made with full information) rather than a
    # blind refinement or a zero-victim reply
    assert {e["UID"] for e in got} == {v_hi["metadata"]["uid"],
                                       v_lo["metadata"]["uid"]}


def test_node_error_metric_distinct_from_dropped():
    fc, cache, info = _cluster()
    reg = Registry()
    h = PreemptHandler(cache, reg)
    h.handle({
        "Pod": make_pod(hbm=4096, name="high"),
        "NodeNameToMetaVictims": {
            "ghost-node": {"Pods": [{"UID": "u"}], "NumPDBViolations": 0},
        },
    })
    exposed = reg.expose()
    assert "tpushare_preempt_node_errors_total 1" in exposed
    assert "tpushare_preempt_nodes_dropped_total 0" in exposed


def test_initcontainer_cpu_blocks_shrink():
    # unmanaged resources hiding in initContainers (or overhead/hostPort)
    # must gate the shrink exactly like main-container cpu
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    for name in ("v1", "v3"):
        cache.add_or_update_pod(fc.get_pod("default", name))
    preemptor = make_pod(hbm=4096, name="high")
    preemptor["spec"]["initContainers"] = [
        {"name": "init", "resources": {"requests": {"cpu": "8"}}}]
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]},
                            {"UID": v3["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert len(out["NodeNameToMetaVictims"]["n1"]["Pods"]) == 2


def test_hostport_blocks_shrink():
    fc, cache, info = _cluster()
    v3 = _bind(fc, info, "v3", 2048, priority=0)
    v1 = _bind(fc, info, "v1", 4096, priority=5)
    for name in ("v1", "v3"):
        cache.add_or_update_pod(fc.get_pod("default", name))
    preemptor = make_pod(hbm=4096, name="high")
    preemptor["spec"]["containers"][0]["ports"] = [
        {"containerPort": 8080, "hostPort": 8080}]
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]},
                            {"UID": v3["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert len(out["NodeNameToMetaVictims"]["n1"]["Pods"]) == 2


def test_zero_victim_result_falls_back_to_scheduler_set():
    # the scheduler preempted, so SOMETHING blocked scheduling; if the
    # TPU dimension says "fits with no eviction", the blocker is a
    # constraint this extender cannot see (max-pods, stale cache). A
    # zero-victim reply would nominate the node and evict nobody,
    # looping the preemptor Pending forever — the scheduler's own victim
    # choice must be kept instead
    fc, cache, info = _cluster()
    v1 = _bind(fc, info, "v1", 2048, priority=0)
    cache.add_or_update_pod(fc.get_pod("default", "v1"))
    preemptor = make_pod(hbm=4096, name="high")  # fits per-chip already
    out = _handler(cache).handle({
        "Pod": preemptor,
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": v1["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert out["NodeNameToMetaVictims"]["n1"]["Pods"] == [
        {"UID": v1["metadata"]["uid"]}]
