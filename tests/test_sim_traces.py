"""Diurnal wind-tunnel trace generator: determinism, rate integrals,
tier mix, spike placement (tpushare/sim/traces.py)."""

import math

import pytest

from tpushare.sim.traces import (
    DEFAULT_TIERS, DiurnalSpec, PodTier, SpikeWindow, expected_arrivals,
    rate_at, synth_diurnal, synth_fleet)


def _spec(**kw):
    base = dict(hours=6.0, period=6.0, base_rate=200.0, peak_rate=600.0,
                seed=11)
    base.update(kw)
    return DiurnalSpec(**base)


def test_seeded_determinism():
    a, b = synth_diurnal(_spec()), synth_diurnal(_spec())
    assert len(a) == len(b) > 0
    assert [(p.arrival, p.duration, p.request.hbm_mib, p.request.chip_count,
             p.request.topology, p.priority) for p in a] == \
           [(p.arrival, p.duration, p.request.hbm_mib, p.request.chip_count,
             p.request.topology, p.priority) for p in b]
    assert all(p.arrival <= q.arrival for p, q in zip(a, a[1:]))
    # a different seed must actually change the realization
    c = synth_diurnal(_spec(seed=12))
    assert [(p.arrival, p.duration) for p in c] != \
           [(p.arrival, p.duration) for p in a]


def test_arrival_count_matches_rate_integral():
    """The thinning sampler's realized count must track the analytic
    integral of rate_at over the horizon (law of large numbers: a few
    thousand arrivals → well within 10%)."""
    spec = _spec()
    trace = synth_diurnal(spec)
    want = expected_arrivals(spec)
    assert want > 1000  # the bound below is vacuous on tiny traces
    assert abs(len(trace) - want) / want < 0.10


def test_rate_at_trough_and_peak():
    spec = _spec()
    assert rate_at(spec, 0.0) == pytest.approx(spec.base_rate)
    assert rate_at(spec, spec.period / 2) == pytest.approx(spec.peak_rate)
    mid = rate_at(spec, spec.period / 4)
    assert spec.base_rate < mid < spec.peak_rate


def test_tier_mix_proportions():
    """Realized tier shares must match the configured weights — the
    sweep's pressure profile depends on the mix being honest."""
    trace = synth_diurnal(_spec(hours=12.0, period=12.0))
    assert len(trace) > 3000
    by_shape = {}
    for p in trace:
        key = (p.request.hbm_mib, p.request.chip_count,
               p.request.topology)
        by_shape[key] = by_shape.get(key, 0) + 1
    total = len(trace)
    for tier in DEFAULT_TIERS:
        key = (tier.hbm_mib, tier.chip_count, tier.topology)
        got = by_shape.get(key, 0) / total
        assert got == pytest.approx(tier.weight, abs=0.04), tier.name


def test_tier_durations_track_mean():
    trace = synth_diurnal(_spec(hours=12.0, period=12.0))
    by_shape = {}
    for p in trace:
        key = (p.request.hbm_mib, p.request.chip_count,
               p.request.topology)
        by_shape.setdefault(key, []).append(p.duration)
    for tier in DEFAULT_TIERS:
        durs = by_shape[(tier.hbm_mib, tier.chip_count, tier.topology)]
        mean = sum(durs) / len(durs)
        assert abs(mean - tier.mean_duration) / tier.mean_duration < 0.25


def test_spike_windows_land_where_configured():
    """Arrivals inside a configured spike window must be denser than
    the same-width windows either side of it."""
    spike = SpikeWindow(start=2.0, duration=0.5, multiplier=3.0)
    spec = _spec(spikes=(spike,))
    trace = synth_diurnal(spec)

    def count(lo, hi):
        return sum(1 for p in trace if lo <= p.arrival < hi)

    inside = count(2.0, 2.5)
    before = count(1.5, 2.0)
    after = count(2.5, 3.0)
    # multiplier 3x against a smooth sinusoid: the window must clearly
    # dominate both neighbors, not just edge them out
    assert inside > 2.0 * before
    assert inside > 2.0 * after
    # and the analytic integral agrees the spike adds mass
    flat = expected_arrivals(_spec())
    assert expected_arrivals(spec) > flat * 1.05


def test_expected_arrivals_is_an_integral():
    """Doubling the horizon of a periodic spec doubles the expected
    count; scaling both rates scales it linearly."""
    one = expected_arrivals(_spec(hours=6.0))
    two = expected_arrivals(_spec(hours=12.0))
    assert two == pytest.approx(2 * one, rel=1e-6)
    hot = expected_arrivals(_spec(base_rate=400.0, peak_rate=1200.0))
    assert hot == pytest.approx(2 * one, rel=1e-6)


def test_spec_validation():
    with pytest.raises(ValueError):
        DiurnalSpec(hours=0.0)
    with pytest.raises(ValueError):
        DiurnalSpec(base_rate=-1.0)
    with pytest.raises(ValueError):
        DiurnalSpec(peak_rate=10.0, base_rate=20.0)
    with pytest.raises(ValueError):
        DiurnalSpec(tiers=())
    with pytest.raises(ValueError):
        DiurnalSpec(tiers=(PodTier("bad", -1.0, 1024, 1, None, 1.0),))


def test_default_tier_weights_are_a_distribution():
    assert math.isclose(sum(t.weight for t in DEFAULT_TIERS), 1.0)
    assert all(t.weight > 0 for t in DEFAULT_TIERS)


def test_synth_fleet_geometry():
    fleet = synth_fleet(32)
    assert len(fleet.nodes) == 32
    node = fleet.nodes[0]
    assert len(node.used) == 4
    assert node.hbm == 16384
