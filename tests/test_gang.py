"""Multi-host gang placement through the full extender stack.

docs/designs/multihost-gang.md protocol, executed over real HTTP against
a FakeCluster v5e-16 (4 slice-labeled 2x2 hosts): Filter answers each
member with exactly its planned host, the first Bind reserves EVERY
member's share all-or-nothing and stamps the plan, later Binds replay
from it, and abandonment releases the reserved shares. The reference
cannot express any of this (single-node allocator, nodeinfo.go:312-363).
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.gang import (GANG_MEMBERS, GangCoordinator,
                                 GangError)
from tpushare.controller import Controller
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster

HOSTS = ["s0h0", "s0h1", "s0h2", "s0h3"]
ORIGINS = ["0x0", "0x2", "2x0", "2x2"]


def make_slice_cluster() -> FakeCluster:
    fc = FakeCluster()
    for name, origin in zip(HOSTS, ORIGINS):
        fc.add_tpu_node(name, chips=4, hbm_per_chip_mib=16000, mesh="2x2",
                        slice_id="slc0", slice_origin=origin)
    # plus an unrelated single-host node: gangs must never land on it
    fc.add_tpu_node("lone", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    return fc


def gang_pod(fc, name, rank, size=8, hbm=0, count=4, topology="2x4",
             gang_id="g1"):
    pod = {
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {
                         contract.ANN_GANG: gang_id,
                         contract.ANN_GANG_SIZE: str(size),
                         contract.ANN_GANG_RANK: str(rank),
                         contract.ANN_TOPOLOGY: topology,
                     }},
        "spec": {"containers": [{"name": "c", "resources": {"limits": {
            contract.RESOURCE_COUNT: str(count),
            **({contract.RESOURCE_HBM: str(hbm * count)} if hbm else {}),
        }}}]},
    }
    return fc.create_pod(pod)


@pytest.fixture
def rig():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    server = ExtenderServer(cache, fc, Registry(), host="127.0.0.1",
                            port=0)
    port = server.start()
    yield fc, cache, server, f"http://127.0.0.1:{port}/tpushare-scheduler"
    server.stop()
    ctl.stop()


def post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def all_nodes():
    return HOSTS + ["lone"]


def test_gang_filter_returns_exactly_the_planned_host(rig):
    fc, cache, server, base = rig
    p0 = gang_pod(fc, "gp0", rank=0)
    _, out = post(f"{base}/filter", {"Pod": p0, "NodeNames": all_nodes()})
    assert out["Error"] == ""
    assert len(out["NodeNames"]) == 1
    assert out["NodeNames"][0] in HOSTS  # never the unlabeled node
    # rank 1 gets the OTHER host of the 2x4 placement
    p1 = gang_pod(fc, "gp1", rank=1)
    _, out1 = post(f"{base}/filter", {"Pod": p1,
                                      "NodeNames": all_nodes()})
    assert len(out1["NodeNames"]) == 1
    assert out1["NodeNames"][0] != out["NodeNames"][0]


def test_gang_bind_end_to_end_two_members(rig):
    fc, cache, server, base = rig
    pods = [gang_pod(fc, f"gp{r}", rank=r) for r in (0, 1)]
    hosts = []
    for r, pod in enumerate(pods):
        _, flt = post(f"{base}/filter", {"Pod": pod,
                                         "NodeNames": all_nodes()})
        (host,) = flt["NodeNames"]
        status, bound = post(f"{base}/bind", {
            "PodName": pod["metadata"]["name"], "PodNamespace": "default",
            "PodUID": pod["metadata"]["uid"], "Node": host})
        assert status == 200 and not bound.get("Error"), bound
        hosts.append(host)
    assert len(set(hosts)) == 2
    # placement annotations landed, incl. the plan on the FIRST member
    first = fc.get_pod("default", "gp0")
    second = fc.get_pod("default", "gp1")
    plan = contract.gang_plan_from_annotations(first)
    assert plan is not None and plan["id"] == "g1"
    assert contract.gang_plan_from_annotations(second) is None
    for pod_obj in (first, second):
        ids = contract.chip_ids_from_annotations(pod_obj)
        assert ids is not None and len(ids) == 4
        ann = pod_obj["metadata"]["annotations"]
        assert ann[contract.ANN_GANG] == "g1"
    # both hosts' chips are fully occupied (exclusive 2x2 each)
    for host in hosts:
        info = cache.get_node_info(host)
        views = info.snapshot()
        assert all(v.free_hbm_mib == 0 for v in views)
    # the coordinator dropped the fully-bound plan
    assert server.gang._plans == {}


def test_first_bind_reserves_every_members_share(rig):
    fc, cache, server, base = rig
    p0 = gang_pod(fc, "gp0", rank=0)
    _, flt = post(f"{base}/filter", {"Pod": p0, "NodeNames": all_nodes()})
    (host0,) = flt["NodeNames"]
    status, bound = post(f"{base}/bind", {
        "PodName": "gp0", "PodNamespace": "default",
        "PodUID": p0["metadata"]["uid"], "Node": host0})
    assert status == 200 and not bound.get("Error"), bound
    # the UNBOUND member's host is already reserved: an exclusive
    # single-host pod no longer fits ANY slice host (the other two hosts
    # are free, but the gang took one and reserved another... find the
    # reserved one via the plan)
    plan = contract.gang_plan_from_annotations(
        fc.get_pod("default", "gp0"))
    partner = next(m["host"] for m in plan["members"]
                   if m["host"] != host0)
    info = cache.get_node_info(partner)
    assert all(v.free_hbm_mib == 0 for v in info.snapshot()), \
        "partner host's share must be reserved before its bind arrives"


def test_gang_no_fit_is_all_or_nothing(rig):
    fc, cache, server, base = rig
    # occupy one chip on every host: no 2x4 exists anywhere
    for i, host in enumerate(HOSTS):
        single = fc.create_pod({
            "metadata": {"name": f"t{i}", "namespace": "default",
                         "annotations": {}},
            "spec": {"containers": [{"name": "c", "resources": {
                "limits": {contract.RESOURCE_COUNT: "1"}}}]}})
        status, bound = post(f"{base}/bind", {
            "PodName": f"t{i}", "PodNamespace": "default",
            "PodUID": single["metadata"]["uid"], "Node": host})
        assert status == 200 and not bound.get("Error")
    p0 = gang_pod(fc, "gp0", rank=0)
    _, out = post(f"{base}/filter", {"Pod": p0, "NodeNames": all_nodes()})
    assert out["NodeNames"] == []
    assert "all-or-nothing" in json.dumps(out["FailedNodes"])
    # and nothing got reserved anywhere
    for host in HOSTS:
        info = cache.get_node_info(host)
        reserved = sum(1 for v in info.snapshot()
                       if v.used_hbm_mib not in (0, v.total_hbm_mib))
        assert reserved == 0


def test_bind_to_unplanned_node_refused(rig):
    fc, cache, server, base = rig
    p0 = gang_pod(fc, "gp0", rank=0)
    _, flt = post(f"{base}/filter", {"Pod": p0, "NodeNames": all_nodes()})
    (planned,) = flt["NodeNames"]
    wrong = next(h for h in HOSTS if h != planned)
    status, bound = post(f"{base}/bind", {
        "PodName": "gp0", "PodNamespace": "default",
        "PodUID": p0["metadata"]["uid"], "Node": wrong})
    assert bound.get("Error"), bound
    assert "planned onto" in bound["Error"]


def test_malformed_gang_annotations_error_at_filter(rig):
    fc, cache, server, base = rig
    pod = fc.create_pod({
        "metadata": {"name": "bad", "namespace": "default",
                     "annotations": {contract.ANN_GANG: "gX"}},
        "spec": {"containers": [{"name": "c", "resources": {
            "limits": {contract.RESOURCE_COUNT: "4"}}}]}})
    _, out = post(f"{base}/filter", {"Pod": pod,
                                     "NodeNames": all_nodes()})
    assert "gang" in out["Error"]


def test_gang_gc_releases_abandoned_shares():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    gang = GangCoordinator(cache)
    clock = [1_000_000_000]
    p0 = gang_pod(fc, "gp0", rank=0)
    gang.bind_member(p0, gang.filter_hosts(p0)[0][0], fc,
                     now_ns=lambda: clock[0])
    # rank 1 never binds; its share stays reserved until the TTL
    clock[0] += GangCoordinator.PLAN_TTL_NS + 1
    assert gang.gc(now_ns=lambda: clock[0]) == 1
    # the partner's share is free again; the bound member keeps its
    bound_host = fc.get_pod("default", "gp0")["spec"]["nodeName"]
    for host in HOSTS:
        info = cache.get_node_info(host)
        free = sum(v.free_hbm_mib for v in info.snapshot())
        if host == bound_host:
            assert free == 0
        else:
            assert free == 4 * 16000, host


def test_gang_rollback_when_a_share_cannot_reserve():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    p0 = gang_pod(fc, "gp0", rank=0, gang_id="g2")
    # deterministic plan->reserve race: pin the plan the coordinator
    # will use, then steal the second member's chips BEFORE bind — the
    # exact "slice state moved since planning" window
    gang = GangCoordinator(cache)
    plan_preview = gang._compute_plan("g2", p0, 8, 1)
    victim_host, victim_chips, _b, _o = plan_preview.members[1]
    first_host = plan_preview.members[0][0]
    gang._compute_plan = lambda *a, **k: plan_preview
    cache.get_node_info(victim_host).reserve_planned(
        "foreign", victim_chips, 16000)
    with pytest.raises(GangError, match="all-or-nothing"):
        gang.bind_member(p0, first_host, fc, now_ns=lambda: 2)
    # the FIRST member's reservation was rolled back: all-or-nothing
    finfo = cache.get_node_info(first_host)
    assert all(v.used_hbm_mib == 0 for v in finfo.snapshot())
    # and no plan was retained
    assert gang._plans == {}


def test_gc_keeps_partial_plan_geometry_for_late_members():
    # ranks 0 binds, rank 1 stalls past TTL: gc releases rank 1's
    # reservation but KEEPS the plan — the late bind must land on the
    # ORIGINAL geometry, not a fresh plan inconsistent with rank 0
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    gang = GangCoordinator(cache)
    clock = [1_000_000_000]
    p0 = gang_pod(fc, "gp0", rank=0)
    gang.bind_member(p0, gang.filter_hosts(p0)[0][0], fc,
                     now_ns=lambda: clock[0])
    plan = gang._plans["g1"]
    partner_host, partner_chips = plan.members[1][0], plan.members[1][1]
    clock[0] += GangCoordinator.PLAN_TTL_NS + 1
    assert gang.gc(now_ns=lambda: clock[0]) == 1
    assert "g1" in gang._plans  # partially bound: geometry retained
    info = cache.get_node_info(partner_host)
    assert all(v.used_hbm_mib == 0 for v in info.snapshot())
    # the late member binds to the original host, re-reserving on demand
    p1 = gang_pod(fc, "gp1", rank=1)
    placement = gang.bind_member(p1, partner_host, fc,
                                 now_ns=lambda: clock[0])
    assert placement.chip_ids == partner_chips
    assert gang._plans == {}  # fully bound -> dropped


def test_topology_pin_mismatch_sanitized_not_500():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    gang = GangCoordinator(cache)
    # gang-size 8 with a 2x2 pin (product 4): the pin is ignored, the
    # gang still plans (matching request_from_pod's single-host policy)
    p0 = gang_pod(fc, "gp0", rank=0, size=8, topology="2x2")
    hosts, reason = gang.filter_hosts(p0)
    assert hosts and reason == ""


def test_chip_rebuild_preserves_gang_reservation():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    gang = GangCoordinator(cache)
    clock = [1_000_000_000]
    p0 = gang_pod(fc, "gp0", rank=0)
    gang.bind_member(p0, gang.filter_hosts(p0)[0][0], fc,
                     now_ns=lambda: clock[0])
    plan = gang._plans["g1"]
    partner_host, partner_chips = plan.members[1][0], plan.members[1][1]
    info = cache.get_node_info(partner_host)
    # device plugin restarts the partner host with a different chip
    # count -> rebuild; the gang's reservation must survive AS a
    # reservation (a confirmed entry could never be released)
    node = dict(fc.get_node(partner_host))
    node["status"] = {"capacity": {
        contract.RESOURCE_HBM: str(8 * 16000),
        contract.RESOURCE_COUNT: "8"}}
    assert info.update_node(node) is True
    # TTL expiry can still release it
    clock[0] += GangCoordinator.PLAN_TTL_NS + 1
    gang.gc(now_ns=lambda: clock[0])
    free = sum(v.free_hbm_mib for v in info.snapshot())
    assert free == 8 * 16000, "reservation must release after rebuild"


def test_plan_recovery_after_coordinator_restart():
    # rank 0 binds through coordinator A (plan stamped on the pod);
    # coordinator B (fresh state — HA takeover or extender restart)
    # must bind rank 1 to the ORIGINAL geometry recovered from the
    # stamp, never a fresh plan
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    a = GangCoordinator(cache)
    p0 = gang_pod(fc, "gp0", rank=0)
    a.bind_member(p0, a.filter_hosts(p0)[0][0], fc, now_ns=lambda: 1)
    partner_host, partner_chips = (a._plans["g1"].members[1][0],
                                   a._plans["g1"].members[1][1])

    b = GangCoordinator(cache)  # fresh coordinator, no in-memory plan
    p1 = gang_pod(fc, "gp1", rank=1)
    placement = b.bind_member(p1, partner_host, fc, now_ns=lambda: 2)
    assert placement.chip_ids == partner_chips
    # recovery marked rank 0 bound from its annotations: the recovered
    # plan completed and was dropped
    assert b._plans == {}
    # both pods visibly placed, same gang
    for name in ("gp0", "gp1"):
        assert contract.chip_ids_from_annotations(
            fc.get_pod("default", name)) is not None


def test_recovery_refuses_rebinding_a_bound_rank():
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    a = GangCoordinator(cache)
    p0 = gang_pod(fc, "gp0", rank=0)
    host0 = a.filter_hosts(p0)[0][0]
    a.bind_member(p0, host0, fc, now_ns=lambda: 1)

    b = GangCoordinator(cache)
    dup = gang_pod(fc, "gp0b", rank=0)  # another pod claiming rank 0
    with pytest.raises(GangError, match="already bound"):
        b.bind_member(dup, host0, fc, now_ns=lambda: 2)


def test_filter_recovers_stamped_plan_after_takeover():
    # rank 0 bound via coordinator A and OCCUPIES its chips; a fresh
    # coordinator's Filter for rank 1 must answer from the stamped
    # geometry — a fresh full-gang plan may not even exist anymore
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    Controller(fc, cache).build_cache()
    a = GangCoordinator(cache)
    p0 = gang_pod(fc, "gp0", rank=0)
    a.bind_member(p0, a.filter_hosts(p0)[0][0], fc, now_ns=lambda: 1)
    partner_host = a._plans["g1"].members[1][0]

    b = GangCoordinator(cache)
    p1 = gang_pod(fc, "gp1", rank=1)
    hosts, reason = b.filter_hosts(p1)
    assert hosts == [partner_host], reason
    # and the recovered plan is authoritative in-memory now
    assert "g1" in b._plans and 0 in b._plans["g1"].bound


def test_finished_gang_does_not_block_resubmission():
    # a completed gang's Succeeded pods linger with their stamp; a new
    # gang under the SAME id must re-plan fresh, not recover the corpse
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    a = GangCoordinator(cache)
    olds = []
    for r in (0, 1):
        p = gang_pod(fc, f"old{r}", rank=r)
        a.bind_member(p, a.filter_hosts(p)[0][0], fc, now_ns=lambda: 1)
        olds.append(p)
    # gang finishes: pods go Succeeded (chips release via the normal
    # pod lifecycle — simulate both)
    for p in olds:
        stored = fc.get_pod("default", p["metadata"]["name"])
        stored["status"] = {"phase": "Succeeded"}
        fc.replace_pod("default", p["metadata"]["name"], stored)
        cache.remove_pod(stored)

    b = GangCoordinator(cache)  # restarted coordinator
    p0 = gang_pod(fc, "new0", rank=0)
    hosts, reason = b.filter_hosts(p0)
    assert hosts, reason  # re-planned fresh, not "already bound"
    placement = b.bind_member(p0, hosts[0], fc, now_ns=lambda: 2)
    assert placement.chip_ids


# -- ABI v5 one-shot solve: escape hatch identity + demotion race ----------

def _direct_rig():
    """Coordinator over a fresh slice fleet, no HTTP (byte-level pod
    comparisons must not pick up tracer/server annotations)."""
    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache, GangCoordinator(cache, fc)


def _drive_gang_direct(fc, gang, now_ns, gang_id="g1"):
    names = []
    for rank in (0, 1):
        pod = gang_pod(fc, f"{gang_id}p{rank}", rank=rank,
                       gang_id=gang_id)
        hosts, err = gang.filter_hosts(pod, now_ns=now_ns)
        assert err == "" and len(hosts) == 1, err
        gang.bind_member(pod, hosts[0], fc, now_ns=now_ns)
        names.append(pod["metadata"]["name"])
    return names


def test_no_gang_solve_escape_hatch_is_byte_identical():
    """TPUSHARE_NO_GANG_SOLVE restores the sequential (pre-v5)
    plan-at-bind flow; with a pinned clock the apiserver-visible
    member placements must be byte-for-byte identical to the one-shot
    path — annotations, chip ids, stamped plan JSON, timestamps."""
    now_ns = lambda: 1_700_000_000_000_000_000

    def run(no_gang_solve):
        old = os.environ.pop("TPUSHARE_NO_GANG_SOLVE", None)
        if no_gang_solve:
            os.environ["TPUSHARE_NO_GANG_SOLVE"] = "1"
        try:
            fc, cache, gang = _direct_rig()
            names = _drive_gang_direct(fc, gang, now_ns)
            return [json.dumps(
                fc.get_pod("default", n)["metadata"]["annotations"],
                sort_keys=True) for n in names]
        finally:
            os.environ.pop("TPUSHARE_NO_GANG_SOLVE", None)
            if old is not None:
                os.environ["TPUSHARE_NO_GANG_SOLVE"] = old

    assert run(False) == run(True)


def test_demotion_race_demotes_exactly_the_mutated_member():
    """Between the leader's Filter-time solve and the first Bind, one
    planned host's stamp moves (same occupancy). The in-lock stamp
    revalidation must demote EXACTLY that member to the per-chip walk
    — the untouched member keeps its walk-free promotion — and the
    final placements must not oversubscribe any chip."""
    fc, cache, gang = _direct_rig()
    p0 = gang_pod(fc, "gp0", rank=0)
    hosts, err = gang.filter_hosts(p0)
    assert err == ""
    planned = gang.plan_info("g1")["hosts"]
    assert len(planned) == 2

    # bump ONLY the stamp of the rank-1 host: allocate+release a
    # sharing pod — occupancy is exactly what the solve saw, but the
    # node's (epoch, counter) generation moved
    bump = fc.create_pod({
        "metadata": {"name": "bump", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"limits": {
            contract.RESOURCE_HBM: str(4096)}}}]}})
    info = cache.get_node_info(planned[1])
    info.allocate(bump, fc)
    bound = fc.get_pod("default", "bump")
    cache.add_or_update_pod(bound)
    cache.remove_pod(bound)
    fc.delete_pod("default", "bump")

    base = GANG_MEMBERS.snapshot()
    gang.bind_member(p0, hosts[0], fc)
    assert gang.plan_info("g1")["demoted"] == [1]
    p1 = gang_pod(fc, "gp1", rank=1)
    hosts1, err = gang.filter_hosts(p1)
    assert err == "" and hosts1 == [planned[1]]
    gang.bind_member(p1, hosts1[0], fc)
    snap = GANG_MEMBERS.snapshot()

    def delta(source):
        return snap.get((source,), 0.0) - base.get((source,), 0.0)

    assert delta("demoted") == 1
    assert delta("planned") == 1
    # no chip is claimed twice across the fleet (apiserver truth)
    claimed = set()
    for pod in fc.list_pods():
        ids = contract.chip_ids_from_annotations(pod)
        if ids is None:
            continue
        node = pod["spec"].get("nodeName", "")
        for c in ids:
            assert (node, c) not in claimed, (node, c)
            claimed.add((node, c))
