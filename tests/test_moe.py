"""Mixture-of-experts FFN: routing, capacity, sharding, model integration.

Runs on the virtual 8-device CPU mesh from conftest. The packed
capacity-routed implementation is checked against the dense reference
(which computes every expert for every token), the capacity-drop semantics
are checked directly, and the "ep"-sharded pjit path must agree bit-for-bit
in expectation with the single-device run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpushare.workloads.moe import (
    MoEConfig, expert_load, init_moe_params, moe_ffn, moe_ffn_reference,
    moe_param_specs)


def _mk(cfg, key=0, tokens=32):
    params = init_moe_params(cfg, jax.random.key(key))
    x = jax.random.normal(jax.random.key(key + 1),
                          (tokens, cfg.d_model), jnp.float32)
    return params, x


def test_matches_dense_reference_when_nothing_drops():
    cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2,
                    capacity_factor=8.0, dtype=jnp.float32)
    params, x = _mk(cfg)
    y, aux = jax.jit(lambda p, x: moe_ffn(p, x, cfg))(params, x)
    ref = moe_ffn_reference(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux) > 0.0


def test_top1_routing_selects_single_expert():
    cfg = MoEConfig(d_model=8, d_ff=16, n_experts=4, top_k=1,
                    capacity_factor=8.0, dtype=jnp.float32)
    params, x = _mk(cfg, key=3, tokens=16)
    y, _ = moe_ffn(params, x, cfg)
    ref = moe_ffn_reference(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_capacity_drops_zero_token_output():
    # capacity_factor tiny -> C=1: each expert takes exactly one token slot;
    # every later token routed to a full expert contributes zero.
    cfg = MoEConfig(d_model=8, d_ff=16, n_experts=2, top_k=1,
                    capacity_factor=1e-9, dtype=jnp.float32)
    assert cfg.capacity(64) == 1
    params, x = _mk(cfg, key=5, tokens=64)
    y, _ = moe_ffn(params, x, cfg)
    load = np.asarray(expert_load(params, x, cfg))
    # at most n_experts tokens can produce nonzero output
    nonzero = int(np.sum(np.any(np.abs(np.asarray(y)) > 0, axis=-1)))
    assert nonzero <= cfg.n_experts
    assert int(load.sum()) == 64


def test_batched_leading_dims():
    cfg = MoEConfig(d_model=8, d_ff=16, n_experts=4, top_k=2,
                    capacity_factor=4.0, dtype=jnp.float32)
    params = init_moe_params(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 6, 8), jnp.float32)
    y, _ = moe_ffn(params, x, cfg)
    assert y.shape == (2, 6, 8)
    flat, _ = moe_ffn(params, x.reshape(-1, 8), cfg)
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 8),
                               np.asarray(flat), rtol=1e-6, atol=1e-6)


def test_ep_sharded_matches_unsharded():
    cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2,
                    capacity_factor=4.0, dtype=jnp.float32)
    params, x = _mk(cfg, key=7, tokens=64)
    y_ref, aux_ref = moe_ffn(params, x, cfg)

    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "ep"))
    specs = moe_param_specs()
    p_sh = jax.tree.map(lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
                        params, specs, is_leaf=lambda v: isinstance(v, P))
    x_sh = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    y, aux = jax.jit(lambda p, x: moe_ffn(p, x, cfg))(p_sh, x_sh)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_grad_flows_through_router_and_experts():
    cfg = MoEConfig(d_model=8, d_ff=16, n_experts=4, top_k=2,
                    capacity_factor=4.0, dtype=jnp.float32)
    params, x = _mk(cfg, key=11, tokens=16)

    def loss(p):
        y, aux = moe_ffn(p, x, cfg)
        return jnp.sum(y * y) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for name in ("wg", "w1", "w3", "w2"):
        g = np.asarray(grads[name], np.float32)
        assert np.isfinite(g).all(), name
        assert np.abs(g).max() > 0, f"zero grad for {name}"


# -- model-family integration -------------------------------------------------

def test_moe_model_forward_and_train_step():
    from tpushare.workloads.model import (
        PRESETS, forward_with_aux, init_params, make_train_step)
    cfg = PRESETS["llama-moe-tiny"]
    params = init_params(cfg, jax.random.key(0))
    assert params["layers"]["w1"].shape == (2, 4, 64, 128)  # [L, E, d, f]
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab)
    logits, aux = jax.jit(
        lambda p, t: forward_with_aux(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) >= 1.0 - 1e-5  # Switch aux is >=1 at its optimum

    tx, step = make_train_step(cfg)
    opt_state = tx.init(params)
    p2, _, loss = jax.jit(step)(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # expert weights actually trained
    delta = np.abs(np.asarray(p2["layers"]["w1"], np.float32)
                   - np.asarray(params["layers"]["w1"], np.float32))
    assert delta.max() > 0


def test_moe_model_sharded_ep_mesh():
    from tpushare.workloads.model import (
        PRESETS, batch_spec, init_params, make_train_step, param_specs)
    cfg = PRESETS["llama-moe-tiny"]
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs).reshape(2, 2, 2), ("dp", "tp", "ep"))
    params = init_params(cfg, jax.random.key(0))
    specs = param_specs(cfg)
    sharding = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda v: isinstance(v, P))
    params = jax.device_put(params, sharding)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab)
    tokens = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    tx, step = make_train_step(cfg)
    opt_state = tx.init(params)
    params, opt_state, loss = jax.jit(step)(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # GSPMD normalizes specs by trimming trailing Nones; the expert axis
    # (dim 1) must still be sharded over "ep"
    out_spec = tuple(params["layers"]["w1"].sharding.spec)
    assert out_spec[:2] == (None, "ep"), out_spec


def test_moe_kv_cache_decode_still_works():
    from tpushare.workloads.model import (
        PRESETS, greedy_decode, greedy_decode_kv, init_params)
    cfg = PRESETS["llama-moe-tiny"]
    # exact kv/non-kv equality for MoE requires dropless routing: with
    # capacity_factor >= E/top_k every expert can hold all T tokens, so the
    # cache-free path's re-routing (incl. padding positions) drops nothing
    # (see greedy_decode_kv docstring). Deterministic, not seed luck.
    assert cfg.moe_capacity_factor >= cfg.moe_experts / cfg.moe_top_k
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(2), (2, 8), 0, cfg.vocab)
    out_kv = greedy_decode_kv(params, prompt, 4, cfg)
    out_ref = greedy_decode(params, prompt, 4, cfg)
    np.testing.assert_array_equal(np.asarray(out_kv), np.asarray(out_ref))
