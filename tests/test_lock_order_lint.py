"""Lock-order lint (CI satellite of the sublinear-filtering PR).

The cache's concurrency story depends on one documented rule — lock
order **gang -> stripe -> node -> memo -> index**, with `_pods_lock` a
terminal leaf — enforced by review only until now. This is a simple AST
pass over ``tpushare/cache/``, ``tpushare/core/native/``,
``tpushare/controller/``, ``tpushare/defrag/`` and ``tpushare/ha/``
that finds
every syntactically NESTED lock acquisition (``with <lock>:`` inside
``with <lock>:`` in the same function) and asserts the ranks strictly
increase, so a new lock (like the capacity index's) cannot silently
introduce an inversion.

Deliberately simple: cross-function acquisition chains (method A holds
a lock and calls method B which takes another) are invisible to this
pass — those are covered by the storm/soak deadlock watchdogs. What
this red-lines is the cheap-to-catch case: a directly nested ``with``
in the wrong order, and any NEW lock-like attribute that nobody added
to the rank table (unknown locks fail the lint until classified).
"""

import ast
import os
import re

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
SCOPES = (
    os.path.join(ROOT, "tpushare", "cache"),
    # the pure scoring layer (topology.py, placement.py): lock-free by
    # design — any lock that ever appears here must be classified
    os.path.join(ROOT, "tpushare", "core"),
    os.path.join(ROOT, "tpushare", "core", "native"),
    os.path.join(ROOT, "tpushare", "controller"),
    os.path.join(ROOT, "tpushare", "defrag"),
    os.path.join(ROOT, "tpushare", "ha"),
    os.path.join(ROOT, "tpushare", "extender"),
    os.path.join(ROOT, "tpushare", "sim"),
    os.path.join(ROOT, "tpushare", "chaos"),
    os.path.join(ROOT, "tpushare", "qos"),
    # fleet black box (ISSUE 19): the observability layer grew real
    # locks (ring pump, decision journal, federation slots) — scan it
    os.path.join(ROOT, "tpushare", "obs"),
)

# (file basename, with-expression prefix) -> rank. Nested acquisitions
# must strictly increase in rank. Leaf locks get high ranks so nothing
# may be acquired inside them. Locks in unrelated domains (the native
# engine's loader/pool/arena locks) never legally nest with the cache
# chain OR each other, which distinct ranks + "no nesting exists"
# encode for free.
RANKS = {
    ("sharding.py", "self._lock"): 1,       # ring membership (leftmost of
    # all: guards only the members/ring/pending bookkeeping and is NEVER
    # held across a solve, a bind, or a lease renewal — the renew loop
    # does its apiserver I/O lock-free and swaps the ring by reference)
    ("batch.py", "self._lock"): 2,          # batch-window table (leftmost:
    # guards only the pending-window dict and is NEVER held across the
    # solve or any cache/node call — the leader pops its window first)
    ("gang.py", "self._lock"): 5,           # gang coordinator
    # gang solve (ISSUE 15): the slice-catalog bookkeeping lock — guards
    # ONLY the cached _SliceState list + its build timestamp, and is
    # NEVER held across a solve, a node lock, or the coordinator lock
    # (test_state_lock_never_held_across_a_solve enforces the solve
    # half); sits between the coordinator lock and the stripes so a
    # catalog-read under the coordinator lock stays legal
    ("gang.py", "self._state_lock"): 9,
    ("wirecache.py", "self._lock"): 6,      # wire digest map (leftmost
    # family: guards only the digest->entry OrderedDict bookkeeping and
    # is NEVER held across a parse, a solve, or any cache/node call —
    # decode copies the entry reference out and releases before work)
    # zero-Python steady state (ISSUE 16): the native wire table's
    # bookkeeping lock — guards table lifecycle (create/destroy/clear/
    # stats) and the install call only, and is NEVER held across a
    # probe (test_native_table_lock_never_held_across_a_probe enforces
    # that half: the selector loop probes lock-free against the C
    # table's own mutex). One above the wirecache's rank 6 because the
    # only legal chain is _finish -> install: the wirecache lock is
    # released first today, but a future finish-under-lock install must
    # stay 6 -> 7 and the reverse must red-line.
    ("nativewire.py", "self._lock"): 7,
    ("cache.py", "self._stripes.for_key"): 10,   # node-map stripes
    ("index.py", "self._flush_lock"): 15,   # whole-flush serialization
    ("nodeinfo.py", "self._lock"): 20,      # per-node chip state
    ("cache.py", "self._memo_lock"): 30,    # placement + eqclass memos
    ("index.py", "self._lock"): 40,         # capacity index (rightmost)
    # adjacency tier (ISSUE 15): per host-group gang-capacity caps —
    # rightmost of the cache chain; acquired only AFTER or WITHOUT the
    # index lock (gang_prune reads caps under it, recomputes outside)
    ("index.py", "self._adj_lock"): 41,
    ("cache.py", "self._pods_lock"): 90,    # known-pods leaf
    ("engine.py", "_lock"): 60,             # native loader
    ("engine.py", "_pool_lock"): 61,        # scan pool
    ("engine.py", "self._lock"): 62,        # FleetArena
    # sim engine loop (ISSUE 12): arena bookkeeping lock — guards only
    # the signature-table install/evict and the snapshot counters, and
    # is NEVER held across an arena call (cycle/score/_sync take the
    # FleetArena's own 62-ranked lock), so it must sit BELOW 62 to keep
    # a loop-holds-lock -> arena-call nesting legal if one ever appears
    ("engine_loop.py", "self._lock"): 55,
    # defrag (ISSUE 9): both are LEFTMOST like the batch window lock —
    # pure bookkeeping (budget/backoff/in-flight; inspect state), never
    # held across a solve, an eviction, or any cache/node call. The
    # planner holds nothing at all.
    ("executor.py", "self._lock"): 3,       # defrag budget governor
    ("rebalancer.py", "self._lock"): 4,     # defrag inspect state
    # frag forecast (ISSUE 20): trend-deque bookkeeping only — NEVER
    # held across a fleetwatch read (pressure()/fragmented_nodes() poll
    # last_sample OUTSIDE it), a solve, or any cache call; a leaf like
    # _pods_lock so nothing may ever be acquired inside it
    ("forecast.py", "self._lock"): 92,
    # controller: the informer's seen-set and the workqueue condition
    # never nest with the cache chain (handlers are called lock-free)
    # or with each other today; seen-set < queue so a future requeue-
    # under-seen-set would pass and the reverse would red-line
    # extender front end (ISSUE 11): the selector server's ONLY lock —
    # guards the worker->loop done-list handoff and the inflight
    # counter, and is never held across a handler, a socket op, or a
    # forward. A leaf like _pods_lock: nothing may be acquired inside it.
    ("httpserver.py", "self._done_lock"): 91,
    ("controller.py", "self._seen_lock"): 6,
    ("controller.py", "self._queue._lock"): 7,
    ("workqueue.py", "self._lock"): 7,      # the same Condition object
    # chaos (ISSUE 13): the invariant monitor's sample-counter lock —
    # pure bookkeeping (violation list, pending ages), NEVER held across
    # a cluster list or any cache call; leftmost like the other
    # bookkeeping locks so a future monitor-under-cache nesting red-lines
    ("invariants.py", "self._lock"): 8,
    # QoS (ISSUE 17): the pressure monitor's budget/backoff/in-flight
    # bookkeeping lock — leftmost like the defrag governor it copies,
    # NEVER held across an eviction, a node lock, or a solve
    # (test_pressure_lock_never_held_across_an_eviction enforces the
    # eviction half)
    ("pressure.py", "self._lock"): 8,
    # fleet black box (ISSUE 19) — the ring pump's lifecycle lock and
    # the digest map's LRU lock share one key: both are pure
    # bookkeeping, NEVER held across a ring drain, a histogram observe,
    # or an explain/recorder call (the drain loop runs entirely
    # lock-free; test_blackbox_and_journal_locks_never_held_across_
    # drain_or_flush enforces that half)
    ("blackbox.py", "self._lock"): 8,
    # decision journal: the ONLY legal obs nesting is flush's
    # io -> buffer handoff (swap the buffer out under the inner lock,
    # write to disk under the outer one alone), so the io lock must
    # rank strictly below the buffer lock
    ("journal.py", "self._io_lock"): 50,
    ("journal.py", "self._lock"): 51,
    # metrics federation: seqlock slot bookkeeping + publish — never
    # held across an apiserver call or any other lock; the mmap write
    # under it is wait-free by design (readers retry, never block)
    ("federation.py", "self._lock"): 8,
    # explain/fleetwatch/recorder: terminal leaves like _pods_lock —
    # observers are notified OUTSIDE the explain lock, the scorecard
    # and flight recorder guard only their own deques/counters
    ("explain.py", "self._lock"): 93,
    ("fleetwatch.py", "self._lock"): 94,
    ("recorder.py", "self._lock"): 95,
}

_LOCKISH = re.compile(r"(?:^|[._])(?:[a-z_]*lock[a-z_]*)(?:$|\()|for_key\(")


def _with_expr_key(node: ast.expr) -> str:
    """Normalized prefix of a with-item expression: attribute/name
    chain, with call arguments stripped ('self._stripes.for_key')."""
    if isinstance(node, ast.Call):
        node = node.func
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _walk(path, fname, body, stack, problems):
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs later, NOT under the outer lock
            _walk(path, fname, node.body, [], problems)
            continue
        if isinstance(node, ast.With):
            inner = list(stack)
            for item in node.items:
                keystr = _with_expr_key(item.context_expr)
                src = ast.unparse(item.context_expr)
                if not _LOCKISH.search(src):
                    continue  # TRACER.span(...) etc: not a lock
                rank = RANKS.get((fname, keystr))
                assert rank is not None, (
                    f"{path}:{node.lineno}: unclassified lock "
                    f"acquisition 'with {src}:' — add ({fname!r}, "
                    f"{keystr!r}) to RANKS in the documented order "
                    f"(gang -> stripe -> node -> memo -> index)")
                if inner and rank <= inner[-1][0]:
                    problems.append(
                        f"{path}:{node.lineno}: 'with {src}:' "
                        f"(rank {rank}) acquired while holding "
                        f"{inner[-1][1]} (rank {inner[-1][0]}) — "
                        f"violates gang -> stripe -> node -> memo -> "
                        f"index")
                inner = inner + [(rank, keystr)]
            _walk(path, fname, node.body, inner, problems)
            continue
        for child_body in (getattr(node, "body", None),
                           getattr(node, "orelse", None),
                           getattr(node, "finalbody", None)):
            if isinstance(child_body, list):
                _walk(path, fname, child_body, stack, problems)
        for handler in getattr(node, "handlers", []) or []:
            _walk(path, fname, handler.body, stack, problems)


def _lint_tree() -> tuple[list[str], int]:
    problems: list[str] = []
    seen_locks = 0
    for scope in SCOPES:
        for fn in sorted(os.listdir(scope)):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(scope, fn)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            _walk(path, fn, tree.body, [], problems)
            src = open(path).read()
            seen_locks += len(re.findall(r"with (?:self\.)?_\w*lock", src))
    return problems, seen_locks


def test_lock_acquisitions_follow_documented_order():
    problems, seen = _lint_tree()
    assert seen >= 10, "the lint saw almost no lock acquisitions — " \
        "the scan or the regex rotted"
    assert not problems, "lock-order violations:\n" + "\n".join(problems)


def test_state_lock_never_held_across_a_solve():
    """The gang planner's catalog lock (_state_lock) is documented as
    NEVER held across a solve — the one-shot gang solve walks every
    member host's node lock, so holding planner bookkeeping state
    across it would couple catalog reads to fleet-wide solve latency
    (and invite cross-function inversions the nesting lint can't see).
    AST check: no call whose name smells like a solve/build appears
    inside a ``with self._state_lock:`` block in gang.py."""
    path = os.path.join(ROOT, "tpushare", "cache", "gang.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    banned = re.compile(
        r"solve|select_gang|_build_catalog|sync|snapshot\b.*node")
    problems: list[str] = []

    def scan_calls(body):
        for n in body:
            for sub in ast.walk(n) if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
                if isinstance(sub, ast.Call):
                    src = ast.unparse(sub.func)
                    if banned.search(src):
                        problems.append(
                            f"gang.py:{sub.lineno}: '{src}(...)' called "
                            "under self._state_lock — the catalog lock "
                            "must never be held across a solve")

    def walk(body, held):
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(n.body, False)
                continue
            if isinstance(n, ast.With):
                holds = held or any(
                    _with_expr_key(i.context_expr) == "self._state_lock"
                    for i in n.items)
                if holds:
                    scan_calls(n.body)
                walk(n.body, holds)
                continue
            for cb in (getattr(n, "body", None),
                       getattr(n, "orelse", None),
                       getattr(n, "finalbody", None)):
                if isinstance(cb, list):
                    walk(cb, held)
            for h in getattr(n, "handlers", []) or []:
                walk(h.body, held)

    walk(tree.body, False)
    assert not problems, "\n".join(problems)


def test_native_table_lock_never_held_across_a_probe():
    """The native wire table's bookkeeping lock (nativewire.py
    self._lock, rank 7) is documented as NEVER held across a probe —
    the probe is the serve path's single GIL-released call, and a
    worker-side install holding bookkeeping state across it would stall
    every connection behind one sync. AST check: no call whose name
    smells like a probe appears inside a ``with self._lock:`` block in
    nativewire.py."""
    path = os.path.join(ROOT, "tpushare", "extender", "nativewire.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    banned = re.compile(r"probe")
    problems: list[str] = []

    def scan_calls(body):
        for n in body:
            for sub in ast.walk(n) if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
                if isinstance(sub, ast.Call):
                    src = ast.unparse(sub.func)
                    if banned.search(src):
                        problems.append(
                            f"nativewire.py:{sub.lineno}: '{src}(...)' "
                            "called under self._lock — the table lock "
                            "must never be held across a probe")

    def walk(body, held):
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(n.body, False)
                continue
            if isinstance(n, ast.With):
                holds = held or any(
                    _with_expr_key(i.context_expr) == "self._lock"
                    for i in n.items)
                if holds:
                    scan_calls(n.body)
                walk(n.body, holds)
                continue
            for cb in (getattr(n, "body", None),
                       getattr(n, "orelse", None),
                       getattr(n, "finalbody", None)):
                if isinstance(cb, list):
                    walk(cb, held)
            for h in getattr(n, "handlers", []) or []:
                walk(h.body, held)

    walk(tree.body, False)
    assert not problems, "\n".join(problems)


def test_pressure_lock_never_held_across_an_eviction():
    """The QoS pressure monitor's bookkeeping lock (pressure.py
    self._lock, rank 8) is documented as NEVER held across an eviction,
    a node lock, or a solve — an eviction is apiserver I/O plus cache
    mutation, and budget bookkeeping held across it would serialize the
    fleet's admission paths behind one slow delete. AST check: no call
    whose name smells like an eviction/delete/solve/cache-walk appears
    inside a ``with self._lock:`` block in pressure.py."""
    path = os.path.join(ROOT, "tpushare", "qos", "pressure.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    banned = re.compile(
        r"evict|delete_pod|remove_pod|solve|peek_node|pressure_victim"
        r"|scan_node|scan_once")
    problems: list[str] = []

    def scan_calls(body):
        for n in body:
            for sub in ast.walk(n) if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
                if isinstance(sub, ast.Call):
                    src = ast.unparse(sub.func)
                    if banned.search(src):
                        problems.append(
                            f"pressure.py:{sub.lineno}: '{src}(...)' "
                            "called under self._lock — the budget lock "
                            "must never be held across an eviction")

    def walk(body, held):
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(n.body, False)
                continue
            if isinstance(n, ast.With):
                holds = held or any(
                    _with_expr_key(i.context_expr) == "self._lock"
                    for i in n.items)
                if holds:
                    scan_calls(n.body)
                walk(n.body, holds)
                continue
            for cb in (getattr(n, "body", None),
                       getattr(n, "orelse", None),
                       getattr(n, "finalbody", None)):
                if isinstance(cb, list):
                    walk(cb, held)
            for h in getattr(n, "handlers", []) or []:
                walk(h.body, held)

    walk(tree.body, False)
    assert not problems, "\n".join(problems)


def test_no_defrag_lock_held_across_a_checkpoint_or_restore():
    """Live migration (ISSUE 20): a checkpoint save is DURABLE-blocking
    jax/orbax IO and a restore is worse — any defrag-layer lock held
    across either would serialize the whole budget governor (and every
    admission path that consults it) behind one slow checkpoint. AST
    check over every file in tpushare/defrag/: no call whose name
    smells like checkpoint/restore/session/eviction work appears inside
    a ``with self._lock:`` block."""
    banned = re.compile(
        r"checkpoint|save|restore|\bbegin\b|commit|abort|pause|resume"
        r"|evict|delete_pod|create_pod|allocate|solve|session"
        r"|last_sample|sample_fleet|plan_relocation|list_pods")
    scope = os.path.join(ROOT, "tpushare", "defrag")
    problems: list[str] = []

    def scan_calls(fname, body):
        for n in body:
            for sub in ast.walk(n) if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
                if isinstance(sub, ast.Call):
                    src = ast.unparse(sub.func)
                    if banned.search(src):
                        problems.append(
                            f"{fname}:{sub.lineno}: '{src}(...)' called "
                            "under self._lock — no defrag lock may be "
                            "held across checkpoint/restore/move work")

    def walk(fname, body, held):
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(fname, n.body, False)
                continue
            if isinstance(n, ast.With):
                holds = held or any(
                    _with_expr_key(i.context_expr) == "self._lock"
                    for i in n.items)
                if holds:
                    scan_calls(fname, n.body)
                walk(fname, n.body, holds)
                continue
            for cb in (getattr(n, "body", None),
                       getattr(n, "orelse", None),
                       getattr(n, "finalbody", None)):
                if isinstance(cb, list):
                    walk(fname, cb, held)
            for h in getattr(n, "handlers", []) or []:
                walk(fname, h.body, held)

    for fn in sorted(os.listdir(scope)):
        if not fn.endswith(".py"):
            continue
        path = os.path.join(scope, fn)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        walk(fn, tree.body, False)
    assert not problems, "\n".join(problems)


def test_blackbox_and_journal_locks_never_held_across_drain_or_flush():
    """The black box's locks (ISSUE 19) are documented as NEVER held
    across the work they schedule: the ring pump's lifecycle lock must
    not be held across a drain or a consumer (histogram observe,
    explain record, recorder pin) — the drain loop is the path that
    keeps the native ring from overflowing, and bookkeeping held across
    it would stall producers into drop-on-full; the journal's buffer
    lock must not be held across a disk write — decision_recorded runs
    on webhook worker threads, and fsync latency under the buffer lock
    would put disk stalls on the serve path. AST check: no call whose
    name smells like a drain/consumer (blackbox.py) or a disk op
    (journal.py) appears inside a ``with self._lock:`` block."""
    cases = [
        ("obs", "blackbox.py",
         re.compile(r"drain|observe|record|lookup|flush|urlopen|request"),
         "the pump lock must never be held across a drain or a "
         "consumer call"),
        ("obs", "journal.py",
         re.compile(r"write|flush|_rotate|unlink|drain|urlopen|request"),
         "the buffer lock must never be held across a disk write"),
    ]
    problems: list[str] = []
    for pkg, fname, banned, why in cases:
        path = os.path.join(ROOT, "tpushare", pkg, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)

        def scan_calls(body):
            for n in body:
                for sub in ast.walk(n) if not isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
                    if isinstance(sub, ast.Call):
                        src = ast.unparse(sub.func)
                        if banned.search(src):
                            problems.append(
                                f"{fname}:{sub.lineno}: '{src}(...)' "
                                f"called under self._lock — {why}")

        def walk(body, held):
            for n in body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk(n.body, False)
                    continue
                if isinstance(n, ast.With):
                    holds = held or any(
                        _with_expr_key(i.context_expr) == "self._lock"
                        for i in n.items)
                    if holds:
                        scan_calls(n.body)
                    walk(n.body, holds)
                    continue
                for cb in (getattr(n, "body", None),
                           getattr(n, "orelse", None),
                           getattr(n, "finalbody", None)):
                    if isinstance(cb, list):
                        walk(cb, held)
                for h in getattr(n, "handlers", []) or []:
                    walk(h.body, held)

        walk(tree.body, False)
    assert not problems, "\n".join(problems)


def test_reuseport_listener_setup_is_lock_free():
    """SO_REUSEPORT replica startup (httpserver.start) must take no
    locks: N replicas bind the shared port concurrently, and a lock in
    the bind path would only ever be process-local — it could not order
    anything across replicas, so its presence would be a bug waiting to
    look like a fix. The accept path owns its sockets single-threaded;
    the server's one lock (_done_lock, rank 91) belongs to the
    worker->loop handoff exclusively."""
    path = os.path.join(ROOT, "tpushare", "extender", "httpserver.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    offenders: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in ("start", "_accept"):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    src = ast.unparse(item.context_expr)
                    if _LOCKISH.search(src):
                        offenders.append(
                            f"httpserver.py:{sub.lineno}: 'with {src}:'"
                            f" inside {node.name}() — listener setup "
                            "and accept must stay lock-free")
    assert not offenders, "\n".join(offenders)


def test_topo_scoring_path_takes_no_locks():
    """The mesh-aware scoring path (ISSUE 18) must stay lock-free: the
    ABI v7 fleet scan releases the GIL, so a lock held across
    ``cycle_fleet_topo`` (or inside the pure adjacency scorer) would
    serialize every Prioritize behind one bookkeeping mutex — the exact
    cost the one-pass design exists to avoid. AST check: no ``with
    <lock>:`` anywhere in topology.py, and none inside engine.py's topo
    entry points."""
    offenders: list[str] = []

    path = os.path.join(ROOT, "tpushare", "core", "topology.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.With):
            for item in node.items:
                src = ast.unparse(item.context_expr)
                if _LOCKISH.search(src):
                    offenders.append(
                        f"topology.py:{node.lineno}: 'with {src}:' — "
                        "the adjacency scorer is pure and lock-free")

    path = os.path.join(ROOT, "tpushare", "core", "native", "engine.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    topo_fns = {"cycle_fleet_topo", "_py_cycle_topo", "_topo_cycle_fn"}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in topo_fns:
            continue
        topo_fns.discard(node.name)
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    src = ast.unparse(item.context_expr)
                    if _LOCKISH.search(src):
                        offenders.append(
                            f"engine.py:{sub.lineno}: 'with {src}:' "
                            f"inside {node.name}() — no lock may be "
                            "held across the v7 topo scan")
    assert not topo_fns, f"topo entry points renamed? missing {topo_fns}"
    assert not offenders, "\n".join(offenders)


def test_lint_actually_detects_an_inversion():
    """The lint must be falsifiable: a synthetic memo-inside-node →
    node nesting in cache.py terms must red-line."""
    bad = (
        "def f(self):\n"
        "    with self._memo_lock:\n"
        "        with self._stripes.for_key('x'):\n"
        "            pass\n")
    problems: list[str] = []
    _walk("synthetic.py", "cache.py", ast.parse(bad).body, [], problems)
    assert problems and "violates" in problems[0]
