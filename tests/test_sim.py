"""Capacity simulator: determinism, invariants, and policy ordering."""

import json

from tpushare.core.topology import MeshTopology
from tpushare.sim import Fleet, TraceSpec, run_sim, synth_trace
from tpushare.sim.simulator import SimPod, _is_contiguous_box


def _fleet():
    return Fleet.homogeneous(2, 16, 16384, (4, 4))


def _trace(**kw):
    return synth_trace(TraceSpec(n_pods=200, arrival_rate=3.0,
                                 multi_chip_fraction=0.3, seed=42, **kw))


def test_trace_is_deterministic():
    a, b = _trace(), _trace()
    assert a == b
    assert len(a) == 200
    assert all(p.arrival <= q.arrival for p, q in zip(a, a[1:]))


def test_run_is_deterministic_and_complete():
    r1 = run_sim(_fleet(), _trace(), "binpack")
    r2 = run_sim(_fleet(), _trace(), "binpack")
    assert r1.to_json() == r2.to_json()
    assert r1.placed + r1.never_placed == r1.pods
    assert 0 < r1.util_pct <= 100
    assert r1.peak_util_pct <= 100


def test_fleet_drains_after_run():
    f = _fleet()
    run_sim(f, _trace(), "binpack")
    assert f.used_hbm == 0


def test_binpack_never_violates_contiguity_reference_does():
    rb = run_sim(_fleet(), _trace(), "binpack")
    rr = run_sim(_fleet(), _trace(), "reference")
    assert rb.contig_violations == 0
    assert rr.contig_violations > 0  # scatter breaks topology pins


def test_binpack_wins_under_saturation():
    """Placement policy only moves utilization when the fleet queues; on
    a saturated single-host trace binpack must beat both alternatives on
    time-weighted utilization, makespan, and mean wait."""
    sat = synth_trace(TraceSpec(n_pods=300, arrival_rate=8.0,
                                mean_duration=60.0,
                                multi_chip_fraction=0.3, seed=42))

    def saturated(policy):
        return run_sim(Fleet.homogeneous(1, 16, 16384, (4, 4)), sat, policy)

    rb, rr, rw = (saturated(p) for p in ("binpack", "reference", "worstfit"))
    assert rb.util_pct > rr.util_pct
    assert rb.util_pct > rw.util_pct
    assert rb.makespan < min(rr.makespan, rw.makespan)
    assert rb.mean_wait < min(rr.mean_wait, rw.mean_wait)


def test_underloaded_fleet_utilization_ties_but_frag_differs():
    """Sanity on the metric itself: with no queueing, util is fixed by
    the trace (placement can't change when work runs), while
    fragmentation still reflects placement quality."""
    rb = run_sim(_fleet(), _trace(), "binpack")
    rw = run_sim(_fleet(), _trace(), "worstfit")
    assert abs(rb.util_pct - rw.util_pct) < 1e-6
    assert rb.frag_time_weighted < rw.frag_time_weighted


def test_is_contiguous_box():
    topo = MeshTopology((4, 4))
    # chips 0,1,4,5 = rows 0-1 x cols 0-1
    assert _is_contiguous_box(topo, (0, 1, 4, 5), (2, 2))
    assert _is_contiguous_box(topo, (5, 4, 1, 0), (2, 2))  # order-free
    assert not _is_contiguous_box(topo, (0, 1, 4, 8), (2, 2))
    assert not _is_contiguous_box(topo, (0, 3, 12, 15), (2, 2))  # corners
    assert _is_contiguous_box(topo, (0, 1, 2, 3), (1, 4))
    assert not _is_contiguous_box(topo, (0, 1, 2, 3), (4, 1))


def test_cli_prints_one_json_per_policy(capsys):
    from tpushare.sim.__main__ import main
    assert main(["--nodes", "2", "--chips", "4", "--mesh", "2x2",
                 "--pods", "50", "--policy", "all"]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 3
    for line in lines:
        rep = json.loads(line)
        assert rep["placed"] + rep["never_placed"] == 50


def test_preemption_refined_beats_scalar_victim_selection():
    """The preempt verb's quantitative story: node-level (scalar) victim
    arithmetic evicts pods that don't make the preemptor placeable —
    per-chip refinement never does, and serves high-priority arrivals
    faster for it."""
    trace = synth_trace(TraceSpec(n_pods=300, arrival_rate=4.0,
                                  high_priority_fraction=0.2, seed=0))

    def run(mode):
        return run_sim(Fleet.homogeneous(4, 4, 16384, (2, 2)), trace,
                       "binpack", preempt=mode)

    off, scalar, refined = run("off"), run("scalar"), run("refined")
    assert off.evictions == 0
    # scalar's blind spot is real on this trace: a majority-free node in
    # aggregate that still can't host the request per-chip
    assert scalar.wasted_evictions > 0
    # the verb's guarantee: an eviction happens only when a concrete
    # placement was proven, so none are ever wasted
    assert refined.wasted_evictions == 0
    # and priority traffic is served strictly better than waiting
    assert refined.hp_mean_wait < off.hp_mean_wait
    assert refined.hp_mean_wait <= scalar.hp_mean_wait
    # no oversubscription ever (try_place asserts), and the fleet drains
    for r in (off, scalar, refined):
        assert r.never_placed == 0


def test_preemption_evicted_pods_restart_and_finish():
    """Evicted victims return to the pending queue and complete later —
    nothing is lost, nothing double-frees."""
    fleet = Fleet.homogeneous(1, 2, 8192)
    trace = [
        SimPod(arrival=0.0, duration=100.0, hbm_mib=6144, priority=0),
        SimPod(arrival=1.0, duration=100.0, hbm_mib=6144, priority=0),
        SimPod(arrival=2.0, duration=10.0, hbm_mib=6144, priority=100),
    ]
    r = run_sim(fleet, trace, "binpack", preempt="refined")
    assert r.evictions == 1
    assert r.wasted_evictions == 0
    assert r.placed == 4          # 3 pods + 1 re-placement of the victim
    assert r.never_placed == 0
    assert fleet.used_hbm == 0    # everything drained cleanly


def test_wasted_eviction_victims_do_not_starve():
    """A failed (wasted) preemption must still retry the pending queue:
    the victims' cancelled departures are the only remaining heap events,
    so without the retry they would starve forever on a free fleet."""
    fleet = Fleet.homogeneous(1, 2, 4096)
    trace = [
        SimPod(arrival=0.0, duration=50.0, hbm_mib=3500, priority=0),
        SimPod(arrival=1.0, duration=50.0, hbm_mib=3500, priority=0),
        # aggregate arithmetic accepts (2x4096 total) but no chip can
        # ever host 5000 MiB -> scalar evicts both victims for nothing
        SimPod(arrival=2.0, duration=10.0, hbm_mib=5000, priority=100),
    ]
    r = run_sim(fleet, trace, "binpack", preempt="scalar")
    assert r.wasted_evictions == 2
    assert r.never_placed == 1          # only the impossible pod
    assert fleet.used_hbm == 0          # victims re-placed AND finished


def test_sharded_run_preserves_the_scorecard():
    """Active-active sharding changes who HANDLES a bind, never its
    verdict: replaying the standard trace against 1/2/4 simulated shard
    owners must produce byte-identical scorecards, with only the
    owned/spillover split varying (~(N-1)/N spillover for round-robin
    handling)."""
    from tpushare.sim.simulator import run_sim_sharded
    trace = synth_trace(TraceSpec(n_pods=200, seed=3))
    results = []
    for shards in (1, 2, 4):
        fleet = Fleet.homogeneous(8, 4, 16384, (2, 2))
        report, stats = run_sim_sharded(fleet, trace, "binpack",
                                        shards=shards)
        results.append((report.to_json(), stats))
    base = results[0][0]
    for rep, stats in results:
        assert rep["scorecard"] == base["scorecard"]
        assert rep["placed"] == base["placed"]
        assert rep["frag_time_weighted"] == base["frag_time_weighted"]
        n = stats["shards"]
        assert stats["owned_binds"] + stats["spillover_binds"] \
            == rep["placed"]
        assert sum(stats["shard_sizes"].values()) == 8
        if n == 1:
            assert stats["spillover_binds"] == 0
        else:
            # round-robin handling: ~1/N of binds land on their owner
            assert 0 < stats["spillover_rate"] < 1


def test_cli_shards_leg_emits_identical_scorecards(capsys):
    from tpushare.sim.__main__ import main
    assert main(["--nodes", "4", "--pods", "60", "--shards", "2"]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 3  # shard counts 1, 2, 4
    reps = [json.loads(l) for l in lines]
    assert [r["sharding"]["shards"] for r in reps] == [1, 2, 4]
    for r in reps:
        assert r["scorecard"] == reps[0]["scorecard"]


def test_procs_replay_is_deterministic_across_interpreters():
    """--procs (ISSUE 11): the same replay in SPAWNED interpreters must
    produce byte-identical canonical output — the cross-process
    determinism a sharded production fleet silently depends on."""
    from tpushare.sim.procs import replay_once, run_procs
    payload = {"nodes": 2, "chips": 4, "hbm": 16384, "mesh": [2, 2],
               "policy": "binpack", "preempt": "off",
               "spec": {"n_pods": 40, "arrival_rate": 3.0,
                        "mean_duration": 40.0,
                        "multi_chip_fraction": 0.3,
                        "high_priority_fraction": 0.0, "seed": 42}}
    # in-process reference twice: the canonical rendering is stable
    assert replay_once(payload) == replay_once(payload)
    out = run_procs(payload, 2)
    assert out["scorecards_identical"] is True
    assert out["procs"] == 2 and out["pods_per_proc"] == 40
    assert out["aggregate_placements_per_sec"] > 0
    # the gate is honest about what this box can assert
    import os
    assert out["speedup_asserted"] == ((os.cpu_count() or 1) >= 2)


def test_cli_procs_leg_emits_report_and_gates_on_divergence(capsys):
    from tpushare.sim.__main__ import main
    assert main(["--nodes", "2", "--chips", "4", "--mesh", "2x2",
                 "--pods", "30", "--procs", "2"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["mode"] == "procs"
    assert out["scorecards_identical"] is True
    assert set(out["scorecard"]) == {"time_weighted_util_pct",
                                     "rejection_rate",
                                     "p99_pending_age_s"}
