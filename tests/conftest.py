"""Test harness config.

JAX-facing tests (workloads, __graft_entry__) run on a virtual 8-device CPU
mesh so multi-chip sharding is exercised hermetically, per the driver's
dry-run contract. The env vars must be set before the first jax import.
"""

import os
import sys

# Force CPU even when the session environment points JAX at real TPU
# hardware (JAX_PLATFORMS=axon, registered by a sitecustomize hook that
# imports jax BEFORE this file runs — env vars alone are therefore too
# late). Backends initialize lazily, so flipping the config here still
# works. The test suite must be hermetic and fast; only bench.py runs on
# the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env setup, before any backend init)

jax.config.update("jax_platforms", "cpu")

# repo root on sys.path so `import tpushare` works without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Skip ``tpu_kernel``-marked tests when JAX is pinned to CPU.

    These tests exercise pallas kernels / TPU collectives that have no
    CPU lowering; on this harness they would fail for lack of hardware,
    not for a code bug. Skipping (rather than deselecting) keeps them
    visible in the run header so a lost test shows up as a count drop.
    """
    if jax.default_backend() != "cpu":
        return
    skip = pytest.mark.skip(reason="tpu_kernel: no TPU backend "
                                   "(JAX_PLATFORMS=cpu)")
    for item in items:
        if "tpu_kernel" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def native_engine():
    """The C++ placement engine, compiled/loaded ONCE per test session
    (warmup() pays the g++ build and ctypes setup here, off every
    individual test's clock). Tests that REQUIRE the native path — not
    the Python fallback — take this fixture and assert on it, so a
    broken compiler fails loudly instead of silently testing the slow
    path."""
    from tpushare.core.native import engine
    engine.warmup()
    return engine
