"""Test harness config.

JAX-facing tests (workloads, __graft_entry__) run on a virtual 8-device CPU
mesh so multi-chip sharding is exercised hermetically, per the driver's
dry-run contract. The env vars must be set before the first jax import.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# repo root on sys.path so `import tpushare` works without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
