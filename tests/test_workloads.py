"""Workload tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8).

Covers: forward correctness properties, int8 quantization fidelity, dp x tp
sharded training step (the multichip path the driver dry-runs), greedy
decoding, and HBM gating env derivation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpushare.contract import constants as c
from tpushare.workloads.hbm import apply_hbm_gating
from tpushare.workloads.model import (
    PRESETS, batch_spec, forward, greedy_decode, init_params, loss_fn,
    make_train_step, param_specs, quant_specs, quantize_int8)

CFG = PRESETS["llama-tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_forward_shapes_and_finite(params):
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, CFG.vocab)
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(params, tokens)
    assert logits.shape == (2, 16, CFG.vocab)
    assert bool(jnp.isfinite(logits).all())


def test_forward_is_causal(params):
    """Changing a future token must not affect earlier logits."""
    t1 = jax.random.randint(jax.random.key(2), (1, 12), 0, CFG.vocab)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % CFG.vocab)
    l1 = forward(params, t1, CFG)
    l2 = forward(params, t2, CFG)
    np.testing.assert_allclose(np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_int8_quantization_close_to_bf16(params):
    tokens = jax.random.randint(jax.random.key(3), (1, 8), 0, CFG.vocab)
    ref = forward(params, tokens, CFG)
    qp = quantize_int8(params)
    # int8 params really are int8
    assert qp["layers"]["wq"]["int8"].dtype == jnp.int8
    out = forward(qp, tokens, CFG)
    # logits stay well-correlated (top-1 agreement on most positions)
    agree = (jnp.argmax(ref, -1) == jnp.argmax(out, -1)).mean()
    assert float(agree) >= 0.75


def test_int8_halves_weight_bytes(params):
    def nbytes(tree):
        return sum(x.nbytes for x in jax.tree.leaves(tree))
    plain = nbytes(params["layers"])
    quant = nbytes(quantize_int8(params)["layers"])
    assert quant < plain * 0.62  # int8 + fp32 scales vs bf16


def test_loss_decreases_under_training(params):
    tx, train_step = make_train_step(CFG, learning_rate=1e-2)
    step = jax.jit(train_step)
    tokens = jax.random.randint(jax.random.key(4), (4, 16), 0, CFG.vocab)
    p = params
    opt_state = tx.init(p)
    first = None
    for _ in range(5):
        p, opt_state, loss = step(p, opt_state, tokens)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_sharded_train_step_on_dp_tp_mesh(params):
    """The real multichip path: dp=2 x tp=4 over 8 virtual devices."""
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "tp"))
    specs = param_specs(CFG)
    shard = lambda tree, spec_tree: jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                           is_leaf=lambda x: isinstance(x, P)))
    p = shard(params, specs)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, CFG.vocab)
    tokens = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    tx, train_step = make_train_step(CFG)
    opt_state = tx.init(p)
    step = jax.jit(train_step)
    p2, opt2, loss = step(p, opt_state, tokens)
    assert bool(jnp.isfinite(loss))
    # params keep their tp sharding after the update
    wq_shard = p2["layers"]["wq"].sharding
    assert wq_shard.spec == specs["layers"]["wq"]
    # sharded loss equals single-device loss (same math, just distributed)
    ref_loss = loss_fn(params, np.asarray(tokens), CFG)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-2)


def test_sharded_int8_forward_on_mesh(params):
    devices = np.array(jax.devices()[:8]).reshape(1, 8)
    mesh = Mesh(devices, ("dp", "tp"))
    qp = quantize_int8(params)
    qspecs = quant_specs(param_specs(CFG))
    qp = jax.device_put(
        qp, jax.tree.map(lambda s: NamedSharding(mesh, s), qspecs,
                         is_leaf=lambda x: isinstance(x, P)))
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(qp, tokens)
    assert bool(jnp.isfinite(logits).all())


def test_greedy_decode_extends_prompt(params):
    prompt = jax.random.randint(jax.random.key(6), (2, 4), 0, CFG.vocab)
    out = jax.jit(lambda p, t: greedy_decode(p, t, 6, CFG))(params, prompt)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    # decoding is deterministic
    out2 = greedy_decode(params, prompt, 6, CFG)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# -- hbm gating ---------------------------------------------------------------

def test_gating_derives_fraction_and_preallocate():
    env = {c.ENV_HBM_LIMIT: "2048", c.ENV_HBM_CHIP_TOTAL: "16384"}
    applied = apply_hbm_gating(env)
    assert env[c.ENV_MEM_FRACTION] == "0.1250"
    assert env["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"
    assert applied[c.ENV_MEM_FRACTION] == "0.1250"


def test_gating_respects_plugin_injected_fraction():
    env = {c.ENV_HBM_LIMIT: "2048", c.ENV_HBM_CHIP_TOTAL: "16384",
           c.ENV_MEM_FRACTION: "0.0999"}
    apply_hbm_gating(env)
    assert env[c.ENV_MEM_FRACTION] == "0.0999"  # operator/plugin wins


def test_gating_noop_for_whole_chip_and_missing_env():
    env = {c.ENV_HBM_LIMIT: "16384", c.ENV_HBM_CHIP_TOTAL: "16384"}
    assert apply_hbm_gating(env) == {}
    assert apply_hbm_gating({}) == {}


def test_gating_pins_process_bounds_for_visible_chips():
    env = {c.ENV_VISIBLE_CHIPS: "0,3"}
    applied = apply_hbm_gating(env)
    assert applied["TPU_PROCESS_BOUNDS"] == "1,1,1"
    # operator-set bounds win
    env2 = {c.ENV_VISIBLE_CHIPS: "0,3", "TPU_PROCESS_BOUNDS": "2,2,1"}
    assert "TPU_PROCESS_BOUNDS" not in apply_hbm_gating(env2)


@pytest.mark.tpu_kernel
def test_attn_window_config_flash_matches_einsum():
    """cfg.attn_window must produce the same model outputs through both
    attention backends (the einsum mask and the flash kernel's window
    block classes are independent implementations of the same spec)."""
    import dataclasses

    from tpushare.workloads.model import PRESETS, forward, init_params

    base = dataclasses.replace(PRESETS["llama-tiny"], attn_window=24)
    params = init_params(base, jax.random.key(50))
    tokens = jax.random.randint(jax.random.key(51), (2, 48), 0, base.vocab)
    ref = forward(params, tokens, base)                       # einsum
    flash_cfg = dataclasses.replace(base, attn="flash")
    out = forward(params, tokens, flash_cfg)
    agree = (jnp.argmax(ref, -1) == jnp.argmax(out, -1)).mean()
    assert float(agree) >= 0.95
    # and the window genuinely changes the computation vs full causal
    full = forward(params, tokens,
                   dataclasses.replace(base, attn_window=None))
    assert float(jnp.max(jnp.abs(full - ref))) > 1e-3


@pytest.mark.tpu_kernel
def test_player_modes_run():
    # the player is what sample pods actually execute; all three modes
    # must drive end to end on the hermetic mesh (train = gang member,
    # sp ring = long-context member, default forward = sharing tenant)
    from tpushare.workloads import player

    assert player.main(["--steps", "1", "--mode", "train",
                        "--batch", "1", "--seq", "32"]) == 0
    assert player.main(["--steps", "1", "--sp", "ring",
                        "--batch", "1", "--seq", "128"]) == 0
    assert player.main(["--steps", "1", "--batch", "1",
                        "--seq", "32"]) == 0
