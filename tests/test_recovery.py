"""Crash-restart reconciliation (controller/recovery.py): a replica
dying in the patch->bind gap leaves half-bound pods — placement
annotations stamped by a dead incarnation, never bound. The reconciler
must adopt what the dead incarnation DID bind and GC what it only
half-bound, within a bounded window, with every action attributed via
tpushare_recovery_{adopted,gc}_total{kind}."""

import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller, reconcile_once
from tpushare.controller.recovery import RECOVERY_ADOPTED, RECOVERY_GC
from tpushare.k8s import FakeCluster
from tpushare.k8s.client import ApiError

S = 1_000_000_000  # ns per second


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache


def half_bound(fc, name="orphan", stamp_ns=1_000 * S, chips=(0, 1),
               hbm=4000, extra_ann=None):
    """A pod as a crashed replica leaves it: placement annotations
    patched (per-attempt assume-time stamp included), bind never ran."""
    ann = contract.placement_annotations(list(chips), hbm, 16000,
                                         now_ns=stamp_ns)
    ann.update(extra_ann or {})
    return fc.create_pod(make_pod(hbm=hbm, name=name, ann=ann))


class _Hooked:
    """Cluster wrapper that lets one verb misbehave mid-reconcile —
    the races a real fleet produces between LIST and the CAS."""

    def __init__(self, inner, **hooks):
        self._inner = inner
        self._hooks = hooks

    def __getattr__(self, name):
        if name in self._hooks:
            return self._hooks[name]
        return getattr(self._inner, name)


# -- GC: the half-bound orphan ------------------------------------------------

def test_half_bound_pod_is_gcd_after_window(rig):
    fc, cache = rig
    half_bound(fc, stamp_ns=1_000 * S)
    before = RECOVERY_GC.get("half_bound")
    out = reconcile_once(fc, cache, now_ns=1_100 * S, stale_after_s=15.0)
    assert out == {"adopted": 0, "gc": 1}
    assert RECOVERY_GC.get("half_bound") == before + 1
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) is None
    assert contract.assume_time_from_annotations(fresh) == 0
    # nothing ever entered the cache: the chips are free for real
    assert cache.get_node_info("n1").describe()["used_hbm_mib"] == 0


def test_half_bound_pod_inside_window_untouched(rig):
    """The bounded grace: a stamp younger than stale_after_s is a LIVE
    allocate mid-flight — the reconciler must not race it."""
    fc, cache = rig
    half_bound(fc, stamp_ns=1_000 * S)
    out = reconcile_once(fc, cache, now_ns=1_010 * S, stale_after_s=15.0)
    assert out == {"adopted": 0, "gc": 0}
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) == (0, 1)


def test_unstamped_half_bound_is_gcd_as_malformed(rig):
    fc, cache = rig
    ann = contract.placement_annotations([2], 4000, 16000, now_ns=1)
    del ann[contract.ANN_ASSUME_TIME]
    fc.create_pod(make_pod(hbm=4000, name="unstamped", ann=ann))
    before = RECOVERY_GC.get("unstamped")
    out = reconcile_once(fc, cache, now_ns=1_000 * S)
    assert out["gc"] == 1
    assert RECOVERY_GC.get("unstamped") == before + 1


def test_assigned_pod_is_never_reclaimed(rig):
    """assigned=true means the device plugin granted real chips — a
    missing nodeName then is NOT the reconciler's call to undo."""
    fc, cache = rig
    half_bound(fc, stamp_ns=1_000 * S,
               extra_ann={contract.ANN_ASSIGNED: "true"})
    out = reconcile_once(fc, cache, now_ns=2_000 * S)
    assert out == {"adopted": 0, "gc": 0}
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) == (0, 1)


# -- adoption: what the dead incarnation DID finish ---------------------------

def test_bound_pod_unknown_to_cache_is_adopted(rig):
    """A pod bound by a dead replica AFTER our build_cache replay: the
    watch gap means only reconciliation can account it."""
    fc, cache = rig
    ann = contract.placement_annotations([3], 4000, 16000, now_ns=1)
    pod = fc.create_pod(make_pod(hbm=4000, name="ghost", phase="Running",
                                 node="n1", ann=ann))
    before = RECOVERY_ADOPTED.get("bound")
    out = reconcile_once(fc, cache, now_ns=1_000 * S)
    assert out == {"adopted": 1, "gc": 0}
    assert RECOVERY_ADOPTED.get("bound") == before + 1
    assert cache.known_pod(pod["metadata"]["uid"])
    assert cache.get_node_info("n1").describe()["used_hbm_mib"] == 4000
    # idempotent: the second pass finds nothing to do
    assert reconcile_once(fc, cache, now_ns=1_000 * S) == \
        {"adopted": 0, "gc": 0}


def test_late_bind_mid_reconcile_is_adopted_not_gcd(rig):
    """The bind lands between our LIST and the re-read: the fresh GET
    shows a nodeName, so the pod is adopted — reclaim would have
    orphaned a live placement."""
    fc, cache = rig
    pod = half_bound(fc, stamp_ns=1_000 * S)

    def get_pod(ns, name):
        cur = fc.get_pod(ns, name)
        if not cur["spec"].get("nodeName"):
            fc.bind_pod(ns, name, "n1")
            cur = fc.get_pod(ns, name)
        return cur

    before = RECOVERY_ADOPTED.get("late_bind")
    out = reconcile_once(_Hooked(fc, get_pod=get_pod), cache,
                         now_ns=2_000 * S)
    assert out == {"adopted": 1, "gc": 0}
    assert RECOVERY_ADOPTED.get("late_bind") == before + 1
    assert cache.known_pod(pod["metadata"]["uid"])
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) == (0, 1)


def test_restamped_pod_is_a_live_replacement(rig):
    """A live replica re-placed the pod (new assume-time stamp) between
    LIST and GET: the stale stamp we judged no longer exists, so the
    pass must leave the new placement alone."""
    fc, cache = rig
    half_bound(fc, stamp_ns=1_000 * S)
    fc.patch_pod("default", "orphan", contract.placement_patch(
        contract.placement_annotations([2, 3], 4000, 16000,
                                       now_ns=1_999 * S)))
    snapshot = fc.get_pod("default", "orphan")

    def get_pod(ns, name):
        return snapshot  # the re-read sees the re-stamped pod

    out = reconcile_once(_Hooked(fc, get_pod=get_pod), cache,
                         now_ns=2_000 * S)
    assert out["gc"] == 0


def test_gc_cas_race_loses_safely(rig):
    """replace_pod 409s (a concurrent mutation won): the placement
    stands, nothing is counted, the pass does not die."""
    fc, cache = rig
    half_bound(fc, stamp_ns=1_000 * S)

    def replace_pod(ns, name, body):
        raise ApiError(409, "lost the race")

    before = RECOVERY_GC.get("half_bound")
    out = reconcile_once(_Hooked(fc, replace_pod=replace_pod), cache,
                         now_ns=2_000 * S)
    assert out == {"adopted": 0, "gc": 0}
    assert RECOVERY_GC.get("half_bound") == before
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) == (0, 1)


def test_list_failure_skips_the_pass(rig):
    fc, cache = rig

    def list_pods():
        raise ApiError(503, "brownout")

    out = reconcile_once(_Hooked(fc, list_pods=list_pods), cache)
    assert out == {"adopted": 0, "gc": 0}


# -- the bounded window, end to end -------------------------------------------

def test_recovery_window_is_bounded_by_the_resync_heartbeat(rig):
    """Wired as a resync hook (extender/__main__.py does exactly this),
    a half-bound orphan survives at most stale_after_s + one heartbeat:
    drive one heartbeat and watch it heal."""
    fc, cache = rig
    ctl = Controller(fc, cache)
    ctl.resync_hooks.append(lambda: reconcile_once(
        fc, cache, stale_after_s=0.05))
    half_bound(fc, stamp_ns=time.time_ns() - S)  # stamped 1 s ago
    ctl.resync_once()
    fresh = fc.get_pod("default", "orphan")
    assert contract.chip_ids_from_annotations(fresh) is None
