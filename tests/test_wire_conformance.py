"""kube-scheduler wire conformance (VERDICT r2 item 5).

Every other HTTP test in this repo drives the extender with requests built
from the repo's own helpers — they share the repo's assumptions about the
wire format and can't catch a casing/shape mismatch that would brick a
real kube-scheduler. The fixtures here are authored FROM THE GO SOURCE of
the scheduler's extender client instead (the vendored structs in
/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/api/types.go:
258-302, marshaled per encoding/json's rules):

- The extender structs carry NO json tags, so Go emits their exact field
  names: ``Pod``, ``Nodes``, ``NodeNames``, ``FailedNodes``, ``Error``,
  ``PodName``, ``PodNamespace``, ``PodUID``, ``Node``, ``Host``,
  ``Score`` (the later k8s.io/kube-scheduler/extender/v1 package kept the
  same names for wire compatibility).
- Nil pointer fields have no ``omitempty``, so a nodeCacheCapable
  scheduler really POSTs ``"Nodes": null`` alongside ``NodeNames`` — the
  literal fixtures keep those nulls.
- The embedded v1.Pod/v1.NodeList marshal with their lowercase v1 tags
  (``metadata``/``spec``/``status``, ``creationTimestamp: null``), and
  resource quantities are strings.
- Go's json.Unmarshal on the response is case-insensitive but the
  canonical names above are asserted exactly, plus Go-side type rules
  (Score must decode into an int; HostPriorityList is a bare JSON array).

Also covered: the scheduler's HTTPTimeout firing mid-bind (types.go:199 —
the client gives up while the extender is still writing) must leave the
system consistent: the bind completes exactly once and the scheduler's
retry gets an idempotent success.

ENVIRONMENT LIMITATION (kept on the books deliberately): this image has
no Go toolchain (``which go`` fails), so no REAL ``encoding/json``
marshal of the vendored structs has ever been exchanged with the live
extender. The fixtures here and the machine-derived schema
(tests/tools/gen_wire_schema.py → tests/fixtures/extender_wire_schema.json,
drift-checked) are the honest ceiling of a Go-less image. If a Go
toolchain ever appears: ``go run`` a one-file client that marshals
ExtenderArgs/ExtenderBindingArgs against a live extender, commit the
captured exchange as a fixture here, and assert byte-level compatibility.
"""

import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.add_tpu_node("n2", chips=2, hbm_per_chip_mib=8000)
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    server = ExtenderServer(cache, fc, Registry(), host="127.0.0.1", port=0)
    port = server.start()
    yield fc, cache, f"http://127.0.0.1:{port}/tpushare-scheduler"
    server.stop()
    ctl.stop()


def post_raw(url: str, body: str, timeout: float = 5.0):
    """POST a LITERAL byte body (no repo-side JSON re-encoding)."""
    req = urllib.request.Request(
        url, data=body.encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


# A v1.Pod exactly as client-go marshals one (lowercase tags, null
# creationTimestamp, quantity strings). Seeded into the FakeCluster AND
# embedded verbatim in the filter fixture, as the scheduler does.
GO_POD = """{
  "metadata": {
    "name": "wire-pod",
    "namespace": "default",
    "uid": "c3a3e1f2-0001-4a5b-9c8d-aabbccddeeff",
    "creationTimestamp": null,
    "annotations": {}
  },
  "spec": {
    "containers": [
      {
        "name": "main",
        "image": "example/jax-serve:latest",
        "resources": {
          "limits": {
            "aliyun.com/tpu-hbm": "8000"
          },
          "requests": {
            "aliyun.com/tpu-hbm": "8000"
          }
        }
      }
    ]
  },
  "status": {}
}"""

# ExtenderArgs from a nodeCacheCapable=true scheduler: Nodes is a nil
# pointer -> literal null on the wire (no omitempty, types.go:258-267).
FILTER_ARGS_CACHE_CAPABLE = (
    '{"Pod":' + GO_POD + ',"Nodes":null,"NodeNames":["n1","n2"]}')

# ExtenderArgs from a nodeCacheCapable=false scheduler: full v1.NodeList,
# NodeNames null (types.go:262-263).
FILTER_ARGS_FULL_NODES = ('{"Pod":' + GO_POD + ',"Nodes":{"metadata":{},'
                          '"items":['
                          '{"metadata":{"name":"n1","creationTimestamp":null},'
                          '"spec":{},"status":{}},'
                          '{"metadata":{"name":"n2","creationTimestamp":null},'
                          '"spec":{},"status":{}}]},"NodeNames":null}')

BIND_ARGS = ('{"PodName":"wire-pod","PodNamespace":"default",'
             '"PodUID":"c3a3e1f2-0001-4a5b-9c8d-aabbccddeeff",'
             '"Node":"n1"}')


def seed_wire_pod(fc: FakeCluster) -> None:
    pod = json.loads(GO_POD)
    fc.create_pod(pod)
    # FakeCluster may assign its own uid; force the fixture's
    stored = fc.get_pod("default", "wire-pod")
    stored["metadata"]["uid"] = pod["metadata"]["uid"]
    fc.replace_pod("default", "wire-pod", stored)


def test_filter_nodecachecapable_fixture(rig):
    fc, cache, base = rig
    seed_wire_pod(fc)
    status, result = post_raw(f"{base}/filter", FILTER_ARGS_CACHE_CAPABLE)
    assert status == 200
    # ExtenderFilterResult decodes field-for-field (types.go:273-285)
    assert set(result) <= {"Nodes", "NodeNames", "FailedNodes", "Error"}
    assert result["NodeNames"] == ["n1", "n2"]
    assert result["FailedNodes"] == {}
    assert result["Error"] == ""


def test_filter_full_nodelist_fixture(rig):
    fc, cache, base = rig
    seed_wire_pod(fc)
    status, result = post_raw(f"{base}/filter", FILTER_ARGS_FULL_NODES)
    assert status == 200
    # 8000 MiB fits a 16000-chip on n1 and an 8000-chip on n2
    assert result["NodeNames"] == ["n1", "n2"]


def test_filter_rejection_lands_in_failednodes(rig):
    fc, cache, base = rig
    big = GO_POD.replace('"8000"', '"12000"')
    pod = json.loads(big)
    pod["metadata"]["name"] = "wire-big"
    fc.create_pod(pod)
    args = ('{"Pod":' + big.replace("wire-pod", "wire-big")
            + ',"Nodes":null,"NodeNames":["n1","n2"]}')
    status, result = post_raw(f"{base}/filter", args)
    assert status == 200
    assert result["NodeNames"] == ["n1"]
    # FailedNodesMap: node name -> human-readable reason (types.go:270)
    assert list(result["FailedNodes"]) == ["n2"]
    assert isinstance(result["FailedNodes"]["n2"], str)
    assert result["FailedNodes"]["n2"]


def test_prioritize_hostprioritylist_shape(rig):
    fc, cache, base = rig
    seed_wire_pod(fc)
    status, result = post_raw(f"{base}/prioritize",
                              FILTER_ARGS_CACHE_CAPABLE)
    assert status == 200
    # HostPriorityList is a BARE array of {Host, Score} (types.go:303-310);
    # Score must decode into a Go int: JSON integer, no floats
    assert isinstance(result, list) and len(result) == 2
    for item in result:
        assert set(item) == {"Host", "Score"}
        assert isinstance(item["Score"], int)
        assert 0 <= item["Score"] <= 10  # MaxExtenderPriority
    assert {i["Host"] for i in result} == {"n1", "n2"}


def test_bind_fixture_roundtrip(rig):
    fc, cache, base = rig
    seed_wire_pod(fc)
    status, result = post_raw(f"{base}/bind", BIND_ARGS)
    assert status == 200
    assert set(result) <= {"Error"}
    assert result["Error"] == ""
    bound = fc.get_pod("default", "wire-pod")
    assert bound["spec"].get("nodeName") == "n1"
    anns = bound["metadata"]["annotations"]
    assert "tpushare.aliyun.com/chip-ids" in anns


def test_bind_failure_is_http_500_with_error(rig):
    fc, cache, base = rig
    # no such pod: the scheduler expects HTTP 500 + Error (routes.go:139-143
    # parity; httpExtender also checks result.Error)
    status, result = post_raw(f"{base}/bind", BIND_ARGS)
    assert status == 500
    assert isinstance(result["Error"], str) and result["Error"]


def test_bind_uid_mismatch_rejected(rig):
    fc, cache, base = rig
    seed_wire_pod(fc)
    stale = BIND_ARGS.replace("c3a3e1f2-0001", "deadbeef-9999")
    status, result = post_raw(f"{base}/bind", stale)
    assert status == 500
    assert "UID" in result["Error"] or "uid" in result["Error"]
    # the pod was NOT bound
    pod = fc.get_pod("default", "wire-pod")
    assert "tpushare.aliyun.com/chip-ids" not in \
        pod["metadata"].get("annotations", {})


def test_httptimeout_mid_bind_completes_once_and_retry_is_idempotent(rig):
    """ExtenderConfig.HTTPTimeout (types.go:199): the scheduler's client
    gives up mid-bind. The extender must finish the in-flight bind exactly
    once, and the scheduler's retry must get an idempotent success — not a
    double allocation, not a permanent failure."""
    fc, cache, base = rig
    seed_wire_pod(fc)

    real_bind = fc.bind_pod

    def slow_bind(*a, **kw):
        time.sleep(1.0)  # longer than the client's timeout below
        return real_bind(*a, **kw)

    fc.bind_pod = slow_bind
    try:
        with pytest.raises((TimeoutError, urllib.error.URLError,
                            socket.timeout)):
            post_raw(f"{base}/bind", BIND_ARGS, timeout=0.25)
        # the extender's handler thread is still running; wait for the
        # BIND (nodeName) — annotations land first in the 3-phase
        # allocate, so polling them would catch the bind still in flight
        deadline = time.time() + 10
        while time.time() < deadline:
            pod = fc.get_pod("default", "wire-pod")
            if pod.get("spec", {}).get("nodeName"):
                break
            time.sleep(0.05)
    finally:
        fc.bind_pod = real_bind

    pod = fc.get_pod("default", "wire-pod")
    assert pod["spec"].get("nodeName") == "n1", \
        "in-flight bind must complete despite the client hangup"
    anns = pod["metadata"]["annotations"]
    assert "tpushare.aliyun.com/chip-ids" in anns, \
        "in-flight bind must complete despite the client hangup"
    first_ids = anns["tpushare.aliyun.com/chip-ids"]

    # the scheduler retries after its timeout: idempotent success
    status, result = post_raw(f"{base}/bind", BIND_ARGS)
    assert status == 200 and result["Error"] == ""
    again = fc.get_pod("default", "wire-pod")
    assert again["metadata"]["annotations"][
        "tpushare.aliyun.com/chip-ids"] == first_ids, \
        "retry must not re-allocate"
    # exactly one grant accounted in the cache
    tree = cache.describe()
    assert tree["used_hbm_mib"] == 8000


# ExtenderPreemptionArgs from a nodeCacheCapable=true scheduler
# (types.go:225-232): NodeNameToVictims is a nil map -> literal null (no
# omitempty); NodeNameToMetaVictims carries MetaPod{UID} identifiers only
# (types.go:242-254). Field names are exact: the structs carry no json
# tags.
PREEMPT_ARGS_TEMPLATE = (
    '{"Pod":%s,"NodeNameToVictims":null,'
    '"NodeNameToMetaVictims":{"n2":{"Pods":[{"UID":"%s"},{"UID":"%s"}],'
    '"NumPDBViolations":0}}}')


def test_preempt_metavictims_fixture(rig):
    fc, cache, base = rig
    info = cache.get_node_info("n2")
    uids = []
    for name, hbm, prio in (("vict-a", 4000, 5), ("vict-b", 2000, 0)):
        pod = {
            "metadata": {"name": name, "namespace": "default",
                         "uid": f"c3a3e1f2-100{len(uids)}-4a5b-9c8d-"
                                "aabbccddeeff",
                         "annotations": {}},
            "spec": {"priority": prio, "containers": [
                {"name": "main", "resources": {
                    "limits": {"aliyun.com/tpu-hbm": str(hbm)}}}]},
            "status": {"phase": "Pending"},
        }
        pod = fc.create_pod(pod)
        info.allocate(pod, fc)
        cache.add_or_update_pod(fc.get_pod("default", name))
        uids.append(pod["metadata"]["uid"])
    # fill the second chip so the preemptor fits nowhere on n2
    filler = {
        "metadata": {"name": "filler", "namespace": "default",
                     "uid": "c3a3e1f2-2000-4a5b-9c8d-aabbccddeeff",
                     "annotations": {}},
        "spec": {"priority": 100, "containers": [
            {"name": "main", "resources": {
                "limits": {"aliyun.com/tpu-hbm": "6000"}}}]},
        "status": {"phase": "Pending"},
    }
    filler = fc.create_pod(filler)
    info.allocate(filler, fc)
    cache.add_or_update_pod(fc.get_pod("default", "filler"))

    # preemptor: TPU-only requests -> the shrink path is licensed
    preemptor = GO_POD.replace("wire-pod", "preemptor-pod").replace(
        '"8000"', '"4000"')
    body = PREEMPT_ARGS_TEMPLATE % (preemptor, uids[0], uids[1])
    status, out = post_raw(f"{base}/preempt", body)
    assert status == 200
    # Go-side decode: the reply must carry the EXACT canonical field
    # names; MetaVictims.Pods entries are {"UID": ...} objects
    assert set(out) >= {"NodeNameToMetaVictims"}
    node_map = out["NodeNameToMetaVictims"]
    assert "n2" in node_map
    got = node_map["n2"]
    assert set(got) == {"Pods", "NumPDBViolations"}
    assert isinstance(got["NumPDBViolations"], int)
    for entry in got["Pods"]:
        assert set(entry) == {"UID"}
    # and the refinement itself: evicting vict-b (2000, prio 0) frees
    # 4000 on its chip — the 1-minimal cheapest subset
    assert [e["UID"] for e in got["Pods"]] == [uids[1]]


def test_preempt_hopeless_node_omitted_from_reply(rig):
    fc, cache, base = rig
    preemptor = GO_POD.replace("wire-pod", "preemptor-pod")
    # victims the cluster has never seen free nothing; n2 (2x8000) cannot
    # host an 8000 pod... it can when empty — use a 9000 request instead
    preemptor = preemptor.replace('"8000"', '"9000"')
    body = ('{"Pod":' + preemptor + ',"NodeNameToVictims":null,'
            '"NodeNameToMetaVictims":{"n2":{"Pods":[{"UID":"ghost"}],'
            '"NumPDBViolations":0}}}')
    status, out = post_raw(f"{base}/preempt", body)
    assert status == 200
    assert out["NodeNameToMetaVictims"] == {}


# ---------------------------------------------------------------------------
# Machine-derived schema conformance (VERDICT r3 item 6). No Go
# toolchain exists in this image, so instead of a Go-marshaled exchange
# the schema itself is MACHINE-GENERATED: tests/tools/gen_wire_schema.py
# parses the extender struct definitions out of the vendored Go source
# and applies encoding/json's rules; the committed snapshot
# (tests/fixtures/extender_wire_schema.json) is what these tests check
# fixtures and live responses against — and the snapshot is itself
# regenerated from the Go source when the reference checkout is present,
# so it cannot drift into agreement with the implementation by hand.
# ---------------------------------------------------------------------------

import os as _os
import sys as _sys

_HERE = _os.path.dirname(_os.path.abspath(__file__))
_TYPES_GO = ("/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/"
             "api/types.go")


def _load_schema() -> dict:
    with open(_os.path.join(_HERE, "fixtures",
                            "extender_wire_schema.json")) as f:
        return json.load(f)


def _fields(schema: dict, struct: str) -> dict:
    return schema["structs"][struct]["fields"]


@pytest.mark.skipif(not _os.path.exists(_TYPES_GO),
                    reason="reference Go source not present")
def test_schema_snapshot_regenerates_from_go_source():
    _sys.path.insert(0, _os.path.join(_HERE, "tools"))
    try:
        from gen_wire_schema import parse_types_go
    finally:
        _sys.path.pop(0)
    with open(_TYPES_GO) as f:
        regenerated = parse_types_go(f.read())
    assert regenerated == _load_schema(), (
        "committed extender_wire_schema.json drifted from the Go "
        "source; re-run tests/tools/gen_wire_schema.py")


def test_fixture_requests_match_generated_schema():
    schema = _load_schema()
    for fixture, struct in (
            (FILTER_ARGS_CACHE_CAPABLE, "ExtenderArgs"),
            (FILTER_ARGS_FULL_NODES, "ExtenderArgs"),
            (BIND_ARGS, "ExtenderBindingArgs")):
        body = json.loads(fixture)
        fields = _fields(schema, struct)
        unknown = set(body) - set(fields)
        assert not unknown, f"{struct} fixture has non-Go keys {unknown}"
        # Go marshals every field unconditionally (none carry
        # omitempty): a fixture missing ANY field is a hand-authoring
        # error — nullable ones arrive as literal null, scalars as
        # their zero value
        for name, meta in fields.items():
            if meta["always_present"]:
                assert name in body, (
                    f"{struct} fixture omits {name}, which a real "
                    f"scheduler always sends (possibly null)")
    pre = json.loads(PREEMPT_ARGS_TEMPLATE % ("{}", "u1", "u2"))
    fields = _fields(schema, "ExtenderPreemptionArgs")
    assert set(pre) <= set(fields)
    victims = pre["NodeNameToMetaVictims"]["n2"]
    assert set(victims) <= set(_fields(schema, "MetaVictims"))
    assert set(victims["Pods"][0]) <= set(_fields(schema, "MetaPod"))


def test_live_responses_match_generated_schema(rig):
    fc, cache, base = rig
    schema = _load_schema()
    seed_wire_pod(fc)

    # filter: every ExtenderFilterResult key must be a Go field name
    status, out = post_raw(f"{base}/filter", FILTER_ARGS_CACHE_CAPABLE)
    assert status == 200
    fields = _fields(schema, "ExtenderFilterResult")
    assert set(out) <= set(fields), (
        f"filter reply keys {set(out) - set(fields)} would be DROPPED "
        "by the Go client's case-insensitive unmarshal at best")

    # prioritize: bare HostPriorityList array; Score must be a JSON
    # number (int in Go) — json.Unmarshal into int rejects strings
    status, ranked = post_raw(
        f"{base}/prioritize", FILTER_ARGS_CACHE_CAPABLE)
    assert status == 200
    hp_fields = _fields(schema, "HostPriority")
    assert isinstance(ranked, list)
    for entry in ranked:
        assert set(entry) == set(hp_fields)
        assert hp_fields["Score"]["json_number"]
        assert isinstance(entry["Score"], int)

    # bind: ExtenderBindingResult
    status, out = post_raw(f"{base}/bind", BIND_ARGS)
    assert status == 200
    assert set(out) <= set(_fields(schema, "ExtenderBindingResult"))

    # preempt: ExtenderPreemptionResult -> MetaVictims -> MetaPod
    body = (PREEMPT_ARGS_TEMPLATE
            % (GO_POD.replace("wire-pod", "pre-pod"), "u-a", "u-b"))
    status, out = post_raw(f"{base}/preempt", body)
    assert status == 200
    assert set(out) <= set(_fields(schema, "ExtenderPreemptionResult"))
    for victims in out.get("NodeNameToMetaVictims", {}).values():
        assert set(victims) <= set(_fields(schema, "MetaVictims"))
        for p in victims.get("Pods", []):
            assert set(p) <= set(_fields(schema, "MetaPod"))
