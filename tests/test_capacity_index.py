"""Sublinear-filtering tests: capacity index, request equivalence
classes, and the resident native fleet arena.

The tentpole claims, made falsifiable:

- the index NEVER wrongly prunes: indexed score_nodes output is
  byte-identical to the full-scan path across request shapes, the
  incremental summaries always equal a from-scratch rebuild under
  randomized churn, and TPUSHARE_INDEX_VERIFY counts zero stale prunes;
- pods with the same request signature share one fleet scan per
  generation window (a 50-identical-pod storm performs ~1-2 fleet
  scans' worth of per-node computes, the rest join), with zero stale
  placements against the fake-apiserver TRUTH after binding the storm
  (the chaos-soak oversubscription audit);
- the arena is a pure marshalling cache: identical scores to
  score_fleet, with delta slot updates (not re-packs) for mutated
  nodes, and correct subset scans / structural rebuilds / non-dense
  fallbacks.
"""

import random
import threading

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import (
    EQCLASS_SHARES, INDEX_PRUNED, INDEX_STALE_SERVES,
    MEMO_NODE_SCORES, MEMO_STALE_SERVES, AllocationError, SchedulerCache)
from tpushare.cache.index import max_box_size, summarize
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.core.chips import ChipView
from tpushare.core.placement import PlacementRequest
from tpushare.core.topology import MeshTopology
from tpushare.extender.handlers import (
    BindHandler, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.obs.explain import ExplainStore
from tpushare.k8s import FakeCluster

HBM = 16000
GIB = 1024


def fleet(n_nodes=4, chips=4, mesh="2x2"):
    fc = FakeCluster()
    names = [f"n{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=chips, hbm_per_chip_mib=HBM, mesh=mesh)
    return fc, names


def seed_filler(fc, node, name, chip_ids, hbm):
    """A bound pod with placement annotations, seeded on the fake
    apiserver so build_cache replays it into every cache identically."""
    pod = make_pod(hbm=hbm, name=name, node=node,
                   ann=contract.placement_annotations(
                       chip_ids, hbm, HBM))
    return fc.create_pod(pod)


# -- max_box_size: the geometric core -----------------------------------------

def brute_max_box(topo, elig):
    for size in range(topo.num_chips, 0, -1):
        for box in topo.box_shapes(size):
            for origin in topo.box_positions(box):
                if all(i in elig for i in topo.box_chips(origin, box)):
                    return size
    return 0


@pytest.mark.parametrize("shape", [(7,), (4, 4), (2, 4), (3, 5),
                                   (2, 2, 3)])
def test_max_box_size_matches_enumeration(shape):
    """Closed-form (run-length / max-rectangle) == brute-force box
    enumeration over random eligibility masks, every rank."""
    topo = MeshTopology(shape)
    rng = random.Random(hash(shape) & 0xffff)
    for trial in range(60):
        k = rng.randrange(topo.num_chips + 1)
        elig = frozenset(rng.sample(range(topo.num_chips), k))
        assert max_box_size(topo, elig) == brute_max_box(topo, elig), \
            f"shape {shape} eligible {sorted(elig)}"


# -- the property test: incremental index == from-scratch rebuild -------------

def test_index_agrees_with_rebuild_under_churn():
    """Randomized allocate/release/sync/health churn; after EVERY
    mutation batch the flushed index must agree with a from-scratch
    rebuild of each node's summary AND its bucket memberships
    (CapacityIndex.audit compares both)."""
    fc, names = fleet(n_nodes=3, chips=4, mesh="2x2")
    fc.add_tpu_node("n8", chips=8, hbm_per_chip_mib=HBM, mesh="2x4")
    names = names + ["n8"]
    cache = SchedulerCache(fc)
    cache.build_cache()
    rng = random.Random(7)
    live: list[tuple[str, str]] = []  # (node, pod name)
    for i in range(160):
        node = rng.choice(names)
        info = cache.get_node_info(node)
        op = rng.randrange(5)
        if op <= 1:  # allocate through the real bind path
            pod = fc.create_pod(make_pod(
                hbm=rng.choice([1000, 4000, 9000, 15000]),
                name=f"churn-{i}"))
            try:
                info.allocate(pod, fc)
                live.append((node, f"churn-{i}"))
            except AllocationError:
                fc.delete_pod("default", f"churn-{i}")
        elif op == 2 and live:  # terminate
            node, pname = live.pop(rng.randrange(len(live)))
            bound = fc.get_pod("default", pname)
            cache.get_node_info(node).remove_pod(bound)
            fc.delete_pod("default", pname)
        elif op == 3 and live:  # controller sync (remove+re-add)
            node, pname = rng.choice(live)
            bound = fc.get_pod("default", pname)
            cache.get_node_info(node).sync_pod(bound)
        else:  # health flips
            bad = set(rng.sample(range(info.chip_count),
                                 rng.randrange(info.chip_count + 1)))
            info.set_unhealthy(bad)
        if i % 7 == 0:
            cache._index.flush()
            problems = cache._index.audit()
            assert not problems, f"after op {i}: {problems[:3]}"
    cache._index.flush()
    assert not cache._index.audit()


def test_bucket_union_matches_per_name_verdicts():
    """candidates() (the bucket-union query) and prune_verdict (the
    per-name check) are the same predicate."""
    fc, names = fleet(n_nodes=12)
    for i, n in enumerate(names):
        if i % 3 == 0:
            seed_filler(fc, n, f"f{i}", [0, 1, 2, 3], 15000)
        elif i % 3 == 1:
            seed_filler(fc, n, f"f{i}", [0, 1], 8000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    cache._index.flush()
    for req in (PlacementRequest(hbm_mib=2 * GIB),
                PlacementRequest(hbm_mib=12000),
                PlacementRequest(hbm_mib=4000, chip_count=4),
                PlacementRequest(hbm_mib=0, chip_count=2),
                PlacementRequest(hbm_mib=9000, chip_count=2,
                                 allow_scatter=True)):
        by_name = {n for n in names
                   if cache._index.prune_verdict(n, req) is None}
        assert cache._index.candidates(req) == by_name, req


# -- pruning correctness: byte-identical to the full scan ---------------------

REQS = [
    PlacementRequest(hbm_mib=1 * GIB),
    PlacementRequest(hbm_mib=12000),             # sparse: most pruned
    PlacementRequest(hbm_mib=2000, chip_count=4),
    PlacementRequest(hbm_mib=2000, chip_count=4, topology=(2, 2)),
    PlacementRequest(hbm_mib=0, chip_count=1),   # exclusive
    PlacementRequest(hbm_mib=6000, chip_count=2, allow_scatter=True),
]


def _mixed_fleet():
    fc, names = fleet(n_nodes=24)
    for i, n in enumerate(names):
        if i % 4 == 0:
            seed_filler(fc, n, f"full-{i}", [0, 1, 2, 3], 15500)
        elif i % 4 == 1:
            seed_filler(fc, n, f"half-{i}", [0, 2], 10000)
        elif i % 4 == 2:
            seed_filler(fc, n, f"dust-{i}", [0, 1, 2, 3], 2000)
    return fc, names


def test_indexed_verdicts_byte_identical_to_full_scan():
    fc, names = _mixed_fleet()
    indexed = SchedulerCache(fc, index=True, eqclass=False)
    full = SchedulerCache(fc, index=False, eqclass=False)
    indexed.build_cache()
    full.build_cache()
    # a few unhealthy chips, mirrored into both caches
    for c in (indexed, full):
        c.get_node_info(names[5]).set_unhealthy({0, 1})
        c.get_node_info(names[7]).set_unhealthy({0, 1, 2, 3})
    pruned0 = INDEX_PRUNED.value
    for j, req in enumerate(REQS):
        pod_i = fc.create_pod(make_pod(hbm=1, name=f"pi{j}"))
        pod_f = fc.create_pod(make_pod(hbm=1, name=f"pf{j}"))
        got = indexed.score_nodes(pod_i, req, names)
        want = full.score_nodes(pod_f, req, names)
        assert got == want, f"req {req} diverged"
    assert INDEX_PRUNED.value > pruned0, \
        "the sparse requests never engaged the index"


def test_index_verify_mode_counts_zero_stale_prunes():
    fc, names = _mixed_fleet()
    cache = SchedulerCache(fc, verify_index=True, eqclass=False)
    cache.build_cache()
    stale0 = INDEX_STALE_SERVES.value
    pruned0 = INDEX_PRUNED.value
    for round_ in range(3):
        for j, req in enumerate(REQS):
            pod = fc.create_pod(make_pod(hbm=1, name=f"v{round_}-{j}"))
            cache.score_nodes(pod, req, names)
        # churn between rounds so summaries must re-derive
        churn = fc.create_pod(make_pod(hbm=3000, name=f"vc{round_}"))
        try:
            cache.get_node_info(names[round_]).allocate(churn, fc)
        except AllocationError:
            pass
    assert INDEX_PRUNED.value > pruned0
    assert INDEX_STALE_SERVES.value == stale0, \
        "the index pruned a node the full scan could place"


# -- equivalence classes ------------------------------------------------------

def test_eqclass_replica_storm_shares_one_scan():
    """50 identical pods filtering concurrently: at most ~2 fleet
    scans' worth of per-node computes (racing first scans), everything
    else joined from the signature class."""
    fc, names = fleet(n_nodes=16)
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    flt = FilterHandler(cache, registry)
    computed0 = MEMO_NODE_SCORES.get("computed")
    joined0 = EQCLASS_SHARES.get("joined")
    pods = [fc.create_pod(make_pod(hbm=2 * GIB, name=f"r{i}"))
            for i in range(50)]
    errs: list[str] = []

    def run(chunk):
        try:
            for pod in chunk:
                out = flt.handle({"Pod": pod, "NodeNames": names})
                assert len(out["NodeNames"]) == 16
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e))

    threads = [threading.Thread(target=run, args=(pods[:25],)),
               threading.Thread(target=run, args=(pods[25:],))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errs, errs
    computed = MEMO_NODE_SCORES.get("computed") - computed0
    joined = EQCLASS_SHARES.get("joined") - joined0
    # 50 pods x 16 nodes = 800 verdicts; two racing threads may both
    # pay the first fleet scan, everything after joins
    assert computed <= 2 * len(names), \
        f"storm paid {computed} per-node computes (> 2 fleet scans)"
    assert computed + joined == 50 * len(names)


def test_eqclass_storm_binds_with_zero_stale_placements(monkeypatch):
    """The 50-identical-pod storm bound end to end under BOTH verify
    oracles, then audited against the fake-apiserver truth: no chip
    oversubscribed, zero stale memo serves, zero stale prunes (the
    chaos-soak audit, eqclass + index engaged)."""
    monkeypatch.setenv("TPUSHARE_MEMO_VERIFY", "1")
    monkeypatch.setenv("TPUSHARE_INDEX_VERIFY", "1")
    fc, names = fleet(n_nodes=8)
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    flt = FilterHandler(cache, registry)
    prio = PrioritizeHandler(cache, registry)
    bind = BindHandler(cache, fc, registry)
    stale0 = MEMO_STALE_SERVES.value
    istale0 = INDEX_STALE_SERVES.value
    bound = 0
    for i in range(50):
        pod = fc.create_pod(make_pod(hbm=1500, name=f"s{i}"))
        ok = flt.handle({"Pod": pod, "NodeNames": names})["NodeNames"]
        assert ok, f"pod {i} found no node"
        ranked = prio.handle({"Pod": pod, "NodeNames": ok})
        best = max(r["Score"] for r in ranked)
        node = next(r["Host"] for r in ranked if r["Score"] == best)
        out = bind.handle({"PodName": f"s{i}", "PodNamespace": "default",
                           "PodUID": pod["metadata"]["uid"],
                           "Node": node})
        assert not out.get("Error"), out
        bound += 1
    # apiserver-truth audit (the chaos-soak invariant): per-(node,
    # chip) allocation summed from live pods' annotations
    per: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = pod["spec"].get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        h = contract.hbm_from_annotations(pod)
        for c in ids:
            per[(node, c)] = per.get((node, c), 0) + h
    over = {k: v for k, v in per.items() if v > HBM}
    assert bound == 50 and not over, f"oversubscribed: {over}"
    assert MEMO_STALE_SERVES.value == stale0
    assert INDEX_STALE_SERVES.value == istale0


# -- the resident fleet arena -------------------------------------------------

def _entry(key, stamp, used, topo, total=HBM, healthy=None, idxs=None):
    n = topo.num_chips if idxs is None else len(idxs)
    idxs = list(range(n)) if idxs is None else idxs
    chips = [ChipView(idx=idxs[j], coords=topo.coords(idxs[j])
                      if idxs[j] < topo.num_chips else (0,) * len(topo.shape),
                      total_hbm_mib=total, used_hbm_mib=used[j],
                      healthy=True if healthy is None else healthy[j])
             for j in range(n)]
    return (key, stamp, chips, topo)


def test_arena_parity_delta_and_subsets(native_engine):
    assert native_engine.available()
    topo = MeshTopology((2, 2))
    req = PlacementRequest(hbm_mib=2048, chip_count=2)
    entries = [_entry(f"a{i}", (1, 0), [(i * 997 + j * 311) % HBM
                                        for j in range(4)], topo)
               for i in range(20)]
    arena = native_engine.FleetArena()
    raw = [(c, t) for _k, _s, c, t in entries]
    assert arena.score(entries, req) == native_engine.score_fleet(raw, req)
    d = arena.describe()
    assert d["appends"] == 20 and d["slot_updates"] == 0
    # quiescent rescore: nothing repacks
    assert arena.score(entries, req) == native_engine.score_fleet(raw, req)
    assert arena.describe()["slot_updates"] == 0
    # one dirty slot -> exactly one in-place update, scores track it
    entries[3] = _entry("a3", (1, 1), [15000] * 4, topo)
    raw[3] = (entries[3][2], topo)
    assert arena.score(entries, req) == native_engine.score_fleet(raw, req)
    assert arena.describe()["slot_updates"] == 1
    # scattered subset scan (runs of non-consecutive slots)
    sub = [entries[i] for i in (1, 5, 6, 11, 19)]
    assert arena.score(sub, req) == native_engine.score_fleet(
        [(c, t) for _k, _s, c, t in sub], req)
    assert arena.describe()["slot_updates"] == 1  # subset cost no packs
    # structural change (chip count / mesh) retires + re-appends
    big = MeshTopology((2, 4))
    entries[5] = _entry("a5", (2, 0), [0] * 8, big)
    raw[5] = (entries[5][2], big)
    assert arena.score(entries, req) == native_engine.score_fleet(raw, req)
    d = arena.describe()
    assert d["appends"] == 21 and d["garbage_chips"] >= 4


def test_arena_nondense_and_exclusive_fallbacks(native_engine):
    topo = MeshTopology((2, 2))
    arena = native_engine.FleetArena()
    gappy = _entry("g", (1, 0), [0, 0, 0], topo, idxs=[0, 1, 3])
    dense = _entry("d", (1, 0), [0, 5000, 0, 0], topo)
    sick = _entry("s", (1, 0), [0, 0, 0, 0], topo,
                  healthy=[False, True, True, True])
    for req in (PlacementRequest(hbm_mib=4096),
                PlacementRequest(hbm_mib=0, chip_count=1),  # exclusive
                PlacementRequest(hbm_mib=1000, chip_count=4,
                                 topology=(2, 2))):
        got = arena.score([gappy, dense, sick], req)
        want = native_engine.score_fleet(
            [(e[2], e[3]) for e in (gappy, dense, sick)], req)
        assert got == want, req


def test_arena_compacts_after_mass_retirement(native_engine):
    big = MeshTopology((2, 4))
    req = PlacementRequest(hbm_mib=1024)
    arena = native_engine.FleetArena()
    entries = [_entry(f"c{i}", (1, 0), [0] * 8, big)
               for i in range(16)]
    arena.score(entries, req)
    # structurally shrink most of the fleet (device-plugin restarts with
    # fewer chips): retired rows exceed the garbage threshold -> compact
    small = MeshTopology((2, 2))
    entries = [_entry(f"c{i}", (2, 0), [0] * 4, small) if i < 12
               else entries[i] for i in range(16)]
    got = arena.score(entries, req)
    want = native_engine.score_fleet([(c, t) for _k, _s, c, t in entries],
                                     req)
    assert got == want
    d = arena.describe()
    assert d["repacks"] >= 1
    assert d["garbage_chips"] == 0


# -- the audit stays truthful -------------------------------------------------

def test_explain_records_index_pruned_nodes():
    from tpushare.cache.nodeinfo import no_fit_reason

    fc, names = fleet(n_nodes=4)
    cache = SchedulerCache(fc)
    cache.build_cache()
    explain = ExplainStore()
    flt = FilterHandler(cache, Registry(), explain=explain)
    pod = fc.create_pod(make_pod(hbm=20000, name="huge"))
    req = request_from_pod(pod)
    out = flt.handle({"Pod": pod, "NodeNames": names})
    # the WIRE reply is byte-identical to a full scan's
    assert out["NodeNames"] == []
    assert out["FailedNodes"] == {n: no_fit_reason(req, n) for n in names}
    # the AUDIT says what actually happened: never visited, and why
    rec = explain.get(pod["metadata"]["uid"])
    nodes = rec["cycles"][-1]["filter"]["nodes"]
    for n in names:
        assert nodes[n]["verdict"] == "skipped"
        assert nodes[n]["reason"] == "index-pruned"
        assert "eligible_chips" in nodes[n]["bucket"]
        assert nodes[n]["source"] == "index"


def test_no_index_knob_disables_pruning():
    fc, names = fleet(n_nodes=4)
    cache = SchedulerCache(fc, index=False)
    cache.build_cache()
    pruned0 = INDEX_PRUNED.value
    pod = fc.create_pod(make_pod(hbm=20000, name="huge2"))
    scores, errors = cache.score_nodes(pod, request_from_pod(pod), names)
    assert scores == {n: None for n in names} and not errors
    assert INDEX_PRUNED.value == pruned0


def test_summarize_nontpu_node_is_never_bucketed():
    """Zero-chip nodes keep their structural-error verdict: the index
    must not fold them into the no-fit bucket (the wire reason would
    silently change from 'not a TPU-share node' to 'no fit')."""
    topo = MeshTopology((1,))
    s = summarize((1, 0), [], topo, 0)
    assert s.non_tpu
    fc, names = fleet(n_nodes=1)
    fc.add_tpu_node("plain", chips=0, hbm_per_chip_mib=0)
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = fc.create_pod(make_pod(hbm=2000, name="q"))
    scores, errors = cache.score_nodes(pod, request_from_pod(pod),
                                       names + ["plain"])
    assert errors.get("plain") == "not a TPU-share node"
    assert scores.get(names[0]) is not None


# -- adjacency tier: gang_prune over host groups (ABI v5) ------------------


def _slice_fleet(grid=(2, 2), sid="slc"):
    fc = FakeCluster()
    names = []
    for i in range(grid[0]):
        for j in range(grid[1]):
            n = f"{sid}-h{i}x{j}"
            fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM, mesh="2x2",
                            slice_id=sid, slice_origin=f"{2*i}x{2*j}")
            names.append(n)
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache, names


def _slice_geometry(grid, names):
    from tpushare.core.slice import SliceTopology
    from tpushare.core.topology import HostMesh

    return (SliceTopology.from_host_grid(grid, (2, 2), names),
            HostMesh(grid, (2, 2), tuple(names)))


def test_gang_prune_never_prunes_a_feasible_gang():
    """Soundness property (the adjacency-tier analogue of the
    never-wrongly-prunes tentpole claim): whenever select_gang finds a
    placement on the slice's REAL state, gang_prune must say None.
    Randomized occupancy via real allocations through the cache."""
    from tpushare.core.slice import select_gang

    rng = random.Random(51)
    grid = (2, 4)
    fc, cache, names = _slice_fleet(grid)
    st, hmesh = _slice_geometry(grid, names)
    cache.index.register_group("slc", hmesh)
    pruned_any = 0
    for trial in range(120):
        # churn: a random allocate or release on a random host
        node = rng.choice(names)
        info = cache.get_node_info(node)
        if rng.random() < 0.6:
            pod = fc.create_pod(make_pod(
                hbm=rng.choice([2 * GIB, HBM]),
                count=rng.choice([0, 1]), name=f"f{trial}"))
            try:
                info.allocate(pod, fc)
            except AllocationError:
                fc.delete_pod("default", f"f{trial}")
        else:
            pods = fc.list_pods(node_name=node)
            if pods:
                victim = rng.choice(pods)
                cache.remove_pod(victim)
                fc.delete_pod(victim["metadata"]["namespace"],
                              victim["metadata"]["name"])
        for count, hbm in ((8, 0), (8, 2 * GIB), (4, HBM), (16, 0)):
            req = PlacementRequest(hbm_mib=hbm, chip_count=count,
                                   topology=None, allow_scatter=False)
            views = {n: cache.get_node_info(n).stamped_snapshot()[1]
                     for n in names}
            placeable = select_gang(st, views, req) is not None
            cache.index.flush()
            verdict = cache.index.gang_prune("slc", req)
            if placeable:
                assert verdict is None, (trial, count, hbm, verdict)
            elif verdict is not None:
                pruned_any += 1
    # the sweep must actually exercise the pruning side too
    assert pruned_any > 0


def test_gang_prune_full_slice_and_unknown_summary():
    fc, cache, names = _slice_fleet((2, 2))
    _st, hmesh = _slice_geometry((2, 2), names)
    cache.index.register_group("slc", hmesh)
    req = PlacementRequest(hbm_mib=0, chip_count=8, topology=None,
                           allow_scatter=False)
    cache.index.flush()
    assert cache.index.gang_prune("slc", req) is None  # empty: fits
    # exclusively fill every host -> certain no-fit at the top tier
    for n in names:
        pod = fc.create_pod(make_pod(count=4, name=f"x{n}"))
        cache.get_node_info(n).allocate(pod, fc)
    cache.index.flush()
    verdict = cache.index.gang_prune("slc", req)
    assert verdict is not None and "gang capacity" in verdict
    # unknown group: never prune
    assert cache.index.gang_prune("nope", req) is None
    # dropped group: never prune
    cache.index.drop_group("slc")
    assert cache.index.gang_prune("slc", req) is None
