"""/metrics exposition correctness + registry hardening (ISSUE 4
satellites): a strict Prometheus text-format checker over the FULL
extender output, the counter naming convention, histogram bucket
monotonicity, and the cardinality-bomb containment proof.
"""

import re
import urllib.request

import pytest

from tests.test_contract import make_pod
from tpushare import metrics as metricslib
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.handlers import register_cache_gauges
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.metrics import (
    METRIC_SERIES_CLAMPED, Histogram, LabeledCounter)

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.*)$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (counter|gauge|histogram|"
                      r"summary|untyped)$")
# one sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    rf"^({_NAME})"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" (-?[0-9.eE+-]+|NaN|[+-]Inf)$")
_LE_RE = re.compile(r'le="([^"]+)"')


def strict_parse(text: str) -> dict:
    """Parse Prometheus text format 0.0.4 STRICTLY: every sample line
    must match the grammar, every family must carry HELP+TYPE before
    its first sample, no family may be declared twice. Returns
    {family: {"type": ..., "samples": [(name, labels, value)]}}."""
    families: dict = {}
    current = None
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        m = _HELP_RE.match(line)
        if m:
            name = m.group(1)
            assert name not in families, \
                f"line {ln}: duplicate HELP for {name}"
            families[name] = {"type": None, "samples": [], "help":
                              m.group(2)}
            current = name
            continue
        m = _TYPE_RE.match(line)
        if m:
            name = m.group(1)
            assert name == current, \
                f"line {ln}: TYPE {name} without preceding HELP"
            assert families[name]["type"] is None, \
                f"line {ln}: duplicate TYPE for {name}"
            families[name]["type"] = m.group(2)
            continue
        assert not line.startswith("#"), f"line {ln}: stray comment"
        m = _SAMPLE_RE.match(line)
        assert m, f"line {ln}: malformed sample: {line!r}"
        sample_name = m.group(1)
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if family.endswith(suffix) and family[:-len(suffix)] \
                    in families:
                family = family[:-len(suffix)]
                break
        assert family in families, \
            f"line {ln}: sample {sample_name} has no HELP/TYPE family"
        assert families[family]["type"] is not None, \
            f"line {ln}: family {family} sampled before its TYPE"
        families[family]["samples"].append(
            (sample_name, m.group(2) or "", float(m.group(4))))
    return families


def check_conventions(families: dict) -> None:
    for name, fam in families.items():
        ftype = fam["type"]
        assert ftype is not None, f"{name}: no TYPE line"
        if ftype == "counter":
            assert name.endswith("_total"), \
                f"counter {name} violates the _total suffix convention"
        if ftype == "histogram":
            buckets = [(s, v) for s, labels, v in fam["samples"]
                       if s == f"{name}_bucket"
                       for s, v in [(labels, v)]]
            # bucket cumulative counts must be monotonically
            # nondecreasing in le order, ending at +Inf == _count
            les = []
            for labels, v in buckets:
                le = _LE_RE.search(labels).group(1)
                les.append((float("inf") if le == "+Inf" else float(le),
                            v))
            assert les, f"{name}: histogram with no buckets"
            values = [v for _, v in sorted(les, key=lambda t: t[0])]
            assert all(a <= b for a, b in zip(values, values[1:])), \
                f"{name}: bucket counts not monotonic: {values}"
            count = next(v for s, _l, v in fam["samples"]
                         if s == f"{name}_count")
            assert values[-1] == count, \
                f"{name}: +Inf bucket {values[-1]} != _count {count}"
            assert any(s == f"{name}_sum" for s, _l, _v in
                       fam["samples"]), f"{name}: missing _sum"


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    server = ExtenderServer(cache, fc, registry, host="127.0.0.1", port=0)
    register_cache_gauges(registry, cache)
    port = server.start()
    yield fc, registry, f"http://127.0.0.1:{port}"
    server.stop()
    ctl.stop()


def _scrape(base):
    with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
        return r.read().decode()


def test_full_exposition_is_strictly_parseable(rig):
    fc, registry, base = rig
    # drive one bind so histograms and labeled series are non-empty
    import json as _json
    pod = fc.create_pod(make_pod(hbm=2000, name="m"))
    req = urllib.request.Request(
        f"{base}/tpushare-scheduler/bind",
        data=_json.dumps({"PodName": "m", "PodNamespace": "default",
                          "PodUID": pod["metadata"]["uid"],
                          "Node": "n1"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    urllib.request.urlopen(req, timeout=5).read()
    families = strict_parse(_scrape(base))
    check_conventions(families)
    # the families the observability layer added are present and typed
    assert families["tpushare_build_info"]["type"] == "gauge"
    assert families["tpushare_traces_total"]["type"] == "counter"
    assert families["tpushare_bind_seconds"]["type"] == "histogram"


def test_build_info_labels(rig):
    import platform

    import tpushare

    fc, registry, base = rig
    text = _scrape(base)
    line = next(l for l in text.splitlines()
                if l.startswith("tpushare_build_info{"))
    assert f'version="{tpushare.__version__}"' in line
    assert f'python="{platform.python_version()}"' in line
    assert 'native_abi="' in line
    assert line.endswith(" 1.0")


def test_informer_staleness_gauge_scrapeable():
    """Staleness was /readyz-only; now it is a first-class gauge when an
    informer is wired."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=8000)
    from tpushare.k8s.informer import Informer
    informer = Informer(fc).start()
    try:
        cache = SchedulerCache(fc, node_lister=informer.nodes)
        cache.build_cache()
        registry = Registry()
        server = ExtenderServer(cache, fc, registry, host="127.0.0.1",
                                port=0, informer=informer)
        port = server.start()
        try:
            text = _scrape(f"http://127.0.0.1:{port}")
        finally:
            server.stop()
    finally:
        informer.stop()
    families = strict_parse(text)
    fam = families["tpushare_informer_staleness_seconds"]
    assert fam["type"] == "gauge"
    name, labels, value = fam["samples"][0]
    assert value >= 0.0


# -- registry hardening -------------------------------------------------------

def test_cardinality_bomb_is_refused():
    """Pod-name-shaped label abuse: 5000 distinct values must NOT become
    5000 series — the cap folds the overflow into one sentinel series
    and the clamp counter names the offender."""
    bomb = LabeledCounter("tpushare_test_bomb_total", "t", ("pod",),
                          max_series=64)
    clamped_before = METRIC_SERIES_CLAMPED.get("tpushare_test_bomb_total")
    for i in range(5000):
        bomb.inc(f"pod-{i}")
    series = bomb.snapshot()
    assert len(series) == 65  # 64 real + 1 _overflow
    assert series[("_overflow",)] == 5000 - 64
    assert METRIC_SERIES_CLAMPED.get("tpushare_test_bomb_total") \
        - clamped_before == 5000 - 64
    # the exposition stays bounded and parseable
    families = strict_parse(bomb.expose())
    assert len(families["tpushare_test_bomb_total"]["samples"]) == 65


def test_label_values_are_truncated_and_escaped():
    c = LabeledCounter("tpushare_test_escape_total", "t", ("v",))
    c.inc('bad"value\nwith\\stuff')
    c.inc("x" * 500)
    families = strict_parse(c.expose())
    samples = families["tpushare_test_escape_total"]["samples"]
    assert len(samples) == 2
    # truncated to the cap, not 500 chars
    assert all(len(labels) < 200 for _n, labels, _v in samples)


def test_histogram_quantile_estimate():
    h = Histogram("tpushare_test_seconds", "t", (0.01, 0.1, 1.0))
    assert h.quantile(0.5) is None
    for _ in range(90):
        h.observe(0.005)
    for _ in range(10):
        h.observe(0.5)
    p50 = h.quantile(0.5)
    assert 0.0 < p50 <= 0.01
    p99 = h.quantile(0.99)
    assert 0.1 < p99 <= 1.0


def test_histogram_exemplars_ride_the_json_side():
    h = Histogram("tpushare_test_ex_seconds", "t", (0.01, 1.0))
    h.observe(0.002, exemplar="uid-1-1")
    h.observe(0.5, exemplar="uid-2-1")
    h.observe(0.003)  # no exemplar: keeps the previous one
    ex = h.exemplars()
    assert ex["0.01"]["trace_id"] == "uid-1-1"
    assert ex["1.0"]["trace_id"] == "uid-2-1"
    # exposition carries NO exemplar syntax (strict 0.0.4)
    assert "#" not in h.expose().replace("# HELP", "").replace(
        "# TYPE", "")


def test_metric_series_clamped_is_in_default_registry():
    """The clamp counter itself must be scrapeable, or the bomb is
    contained silently."""
    registry = Registry()
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=8000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    register_cache_gauges(registry, cache)
    assert registry.get("tpushare_metric_series_clamped_total") \
        is metricslib.METRIC_SERIES_CLAMPED
