"""State-layer tests: NodeInfo assume/allocate, SchedulerCache replay.

Covers the reference's critical paths (SURVEY §3.2 filter, §3.3 bind,
§3.5 sync) against the FakeCluster, including the failure/rollback and
optimistic-conflict behaviors, and a concurrency stress proving the
assume/confirm redesign never oversubscribes.
"""

import threading

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import AllocationError, SchedulerCache
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.k8s import ApiError, FakeCluster


def cluster_with_node(chips=4, hbm=16000, mesh=None, name="n1"):
    fc = FakeCluster()
    fc.add_tpu_node(name, chips=chips, hbm_per_chip_mib=hbm, mesh=mesh)
    return fc


def test_request_from_pod_normalization():
    assert request_from_pod(make_pod()) is None
    r = request_from_pod(make_pod(hbm=2048))
    assert r.chip_count == 1 and r.hbm_mib == 2048
    r = request_from_pod(make_pod(count=2))
    assert r.exclusive and r.chip_count == 2
    r = request_from_pod(make_pod(hbm=1024, count=4,
                                  ann={contract.ANN_TOPOLOGY: "2x2"}))
    assert r.topology == (2, 2)
    # inconsistent topology pin is dropped, not fatal
    r = request_from_pod(make_pod(hbm=1024, count=4,
                                  ann={contract.ANN_TOPOLOGY: "3x1"}))
    assert r.topology is None


def test_allocate_writes_annotations_and_binds():
    fc = cluster_with_node()
    cache = SchedulerCache(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="p1"))
    info = cache.get_node_info("n1")
    ok, _ = info.assume(pod)
    assert ok
    placement = info.allocate(pod, fc, now_ns=lambda: 42)
    assert len(placement.chip_ids) == 1
    bound = fc.get_pod("default", "p1")
    assert bound["spec"]["nodeName"] == "n1"
    ann = bound["metadata"]["annotations"]
    assert ann[contract.ANN_HBM_POD] == "2048"
    assert ann[contract.ANN_ASSIGNED] == "false"
    assert ann[contract.ANN_ASSUME_TIME] == "42"
    assert contract.chip_ids_from_annotations(bound) == placement.chip_ids
    # cache reflects the usage
    d = info.describe()
    assert d["used_hbm_mib"] == 2048


def test_allocate_binpacks_onto_least_free_chip():
    fc = cluster_with_node(chips=2, hbm=16000)
    cache = SchedulerCache(fc)
    info = cache.get_node_info("n1")
    p1 = fc.create_pod(make_pod(hbm=10000, name="big"))
    info.allocate(p1, fc)
    p2 = fc.create_pod(make_pod(hbm=4000, name="small"))
    placement = info.allocate(p2, fc)
    # 6000 free on chip0 vs 16000 on chip1: small pod joins chip0
    big_ids = contract.chip_ids_from_annotations(fc.get_pod("default", "big"))
    assert placement.chip_ids == big_ids


def test_allocate_no_fit_raises():
    fc = cluster_with_node(chips=1, hbm=4000)
    info = SchedulerCache(fc).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=5000, name="p"))
    ok, reason = info.assume(pod)
    assert not ok and "no fit" in reason
    with pytest.raises(AllocationError):
        info.allocate(pod, fc)
    assert info.describe()["used_hbm_mib"] == 0


def test_allocate_rollback_on_bind_failure():
    fc = cluster_with_node()
    info = SchedulerCache(fc).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    # someone else binds it AFTER our (stale) copy was fetched
    fc.bind_pod("default", "p", "n1")
    with pytest.raises(AllocationError):
        info.allocate(pod, fc)
    # reservation fully rolled back
    assert info.describe()["used_hbm_mib"] == 0
    # and the losing attempt's annotation patch was reverted, so the pod
    # doesn't advertise a placement the cache never confirmed
    after = fc.get_pod("default", "p")
    assert contract.chip_ids_from_annotations(after) is None


def test_allocate_refuses_already_bound_pod():
    fc = cluster_with_node()
    info = SchedulerCache(fc).get_node_info("n1")
    fc.create_pod(make_pod(hbm=2048, name="p"))
    fc.bind_pod("default", "p", "n1")
    bound = fc.get_pod("default", "p")  # fresh copy shows the binding
    rv_before = bound["metadata"]["resourceVersion"]
    with pytest.raises(AllocationError, match="already bound"):
        info.allocate(bound, fc)
    # fail-fast: no write at all reached the apiserver
    assert fc.get_pod("default", "p")["metadata"]["resourceVersion"] == rv_before


def test_allocate_retries_patch_conflict_once():
    fc = cluster_with_node()

    class FlakyOnce:
        def __init__(self, inner):
            self._inner = inner
            self.failed = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def patch_pod(self, ns, name, patch):
            if not self.failed:
                self.failed = True
                raise ApiError(409, "simulated optimistic-lock conflict")
            return self._inner.patch_pod(ns, name, patch)

    flaky = FlakyOnce(fc)
    info = SchedulerCache(fc).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=1024, name="p"))
    placement = info.allocate(pod, flaky)
    assert placement is not None
    assert fc.get_pod("default", "p")["spec"]["nodeName"] == "n1"


def test_exclusive_chip_request_via_count_only():
    fc = cluster_with_node(chips=2, hbm=16000)
    info = SchedulerCache(fc).get_node_info("n1")
    shared = fc.create_pod(make_pod(hbm=100, name="shared"))
    info.allocate(shared, fc)
    excl = fc.create_pod(make_pod(count=1, name="excl"))
    placement = info.allocate(excl, fc)
    # must land on the untouched chip and consume it fully
    shared_ids = contract.chip_ids_from_annotations(
        fc.get_pod("default", "shared"))
    assert placement.chip_ids != shared_ids
    assert info.describe()["used_hbm_mib"] == 100 + 16000
    # a second exclusive pod no longer fits
    excl2 = fc.create_pod(make_pod(count=1, name="excl2"))
    ok, _ = info.assume(excl2)
    assert not ok


def test_unhealthy_chips_excluded():
    fc = cluster_with_node(chips=2, hbm=16000)
    info = SchedulerCache(fc).get_node_info("n1")
    info.set_unhealthy({0})
    pod = fc.create_pod(make_pod(hbm=1000, name="p"))
    placement = info.allocate(pod, fc)
    assert placement.chip_ids == (1,)
    info.set_unhealthy({0, 1})
    ok, _ = info.assume(fc.create_pod(make_pod(hbm=1000, name="q")))
    assert not ok


def test_multichip_allocation_contiguous():
    fc = cluster_with_node(chips=16, hbm=16000, mesh="4x4")
    info = SchedulerCache(fc).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=8000, count=4, name="p"))
    placement = info.allocate(pod, fc)
    assert placement.box == (2, 2)
    ann = fc.get_pod("default", "p")["metadata"]["annotations"]
    assert ann[contract.ANN_TOPOLOGY] == "2x2"
    assert info.describe()["used_hbm_mib"] == 4 * 8000


def test_build_cache_replays_annotations():
    fc = cluster_with_node(chips=4, hbm=16000)
    # pre-existing bound pod with placement annotations (extender restarted)
    ann = contract.placement_annotations([1, 2], 3000, 16000, now_ns=1)
    fc.create_pod(make_pod(hbm=3000, count=2, name="old", ann=ann,
                           phase="Running", node="n1"))
    # a completed pod must NOT hold chips
    fc.create_pod(make_pod(hbm=9999, name="done",
                           ann=contract.placement_annotations([0], 9999, 16000),
                           phase="Succeeded", node="n1"))
    cache = SchedulerCache(fc)
    assert cache.build_cache() == 1
    d = cache.describe()
    assert d["used_hbm_mib"] == 2 * 3000
    node = d["nodes"][0]
    assert node["chips"][1]["used_hbm_mib"] == 3000
    assert node["chips"][2]["used_hbm_mib"] == 3000


def test_remove_pod_frees_chips():
    fc = cluster_with_node()
    cache = SchedulerCache(fc)
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    info.allocate(pod, fc)
    bound = fc.get_pod("default", "p")
    cache.add_or_update_pod(bound)
    assert cache.known_pod(bound["metadata"]["uid"])
    cache.remove_pod(bound)
    assert info.describe()["used_hbm_mib"] == 0
    assert not cache.known_pod(bound["metadata"]["uid"])


def test_update_node_rebuild_preserves_assignments():
    fc = cluster_with_node(chips=2, hbm=16000)
    cache = SchedulerCache(fc)
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    info.allocate(pod, fc)
    cache.add_or_update_pod(fc.get_pod("default", "p"))
    # device plugin now reports 4 chips (e.g. after maintenance)
    grown = fc.add_tpu_node("n1-new", chips=4, hbm_per_chip_mib=16000)
    grown["metadata"]["name"] = "n1"
    cache.update_node(grown)
    assert info.chip_count == 4
    assert info.describe()["used_hbm_mib"] == 2048


def test_concurrent_allocations_never_oversubscribe():
    fc = cluster_with_node(chips=4, hbm=16000)
    info = SchedulerCache(fc).get_node_info("n1")
    pods = [fc.create_pod(make_pod(hbm=5000, name=f"p{i}"))
            for i in range(16)]
    results: list = [None] * len(pods)

    def run(i):
        try:
            results[i] = info.allocate(pods[i], fc)
        except AllocationError:
            results[i] = "denied"

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(pods))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    granted = [r for r in results if r != "denied" and r is not None]
    # 4 chips x floor(16000/5000)=3 pods -> at most 12 grants
    assert len(granted) == 12
    d = info.describe()
    for chip in d["nodes"][0]["chips"] if "nodes" in d else d["chips"]:
        assert chip["used_hbm_mib"] <= chip["total_hbm_mib"]
    assert d["used_hbm_mib"] == 12 * 5000


# -- HA claim lifecycle (per-node claim CAS, nodeinfo._claim_chips) -----------

def test_ha_claim_blocks_capacity_for_unseen_pods():
    """A claim from a bind this cache has NOT seen must charge capacity
    (the watch-lag window the claims exist for)."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=16384)
    # replica A binds a full-chip pod; replica B's cache never saw it
    cache_a = SchedulerCache(fc)
    cache_a.build_cache()
    pod = fc.create_pod(make_pod(hbm=16384, name="full"))
    cache_a.get_node_info("n1").allocate(pod, fc, ha_claims=True)

    cache_b = SchedulerCache(fc)  # fresh: no pods replayed, no watches
    pod2 = fc.create_pod(make_pod(hbm=16384, name="late"))
    with pytest.raises(AllocationError, match="claimed by concurrent"):
        cache_b.get_node_info("n1").allocate(pod2, fc, ha_claims=True)


def test_ha_claim_tombstone_frees_capacity_after_pod_leaves():
    """Once THIS cache has seen the pod leave (termination/reclaim), its
    still-fresh claim must stop charging — or freed chips stay blocked
    for the rest of the claim TTL (r3 review finding)."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=16384)
    cache = SchedulerCache(fc)
    cache.build_cache()
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=16384, name="big"))
    info.allocate(pod, fc, ha_claims=True)  # claim written, chip full

    # the pod terminates; the controller frees its chips in this cache
    cache.remove_pod(fc.get_pod("default", "big"))
    fc.delete_pod("default", "big")

    # a new full-chip pod must place IMMEDIATELY despite the live claim
    pod2 = fc.create_pod(make_pod(hbm=16384, name="next"))
    placement = info.allocate(pod2, fc, ha_claims=True)
    assert placement.chip_ids == (0,)


def test_ha_claim_failed_bind_releases_reservation_and_claim():
    """A claim-path refusal must roll back the phase-1 reservation (no
    capacity leak) and drop the claim so a later retry succeeds."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=16384)
    cache = SchedulerCache(fc)
    cache.build_cache()
    info = cache.get_node_info("n1")

    pod = fc.create_pod(make_pod(hbm=8192, name="w"))
    real_bind = fc.bind_pod

    def failing_bind(*a, **kw):
        raise ApiError(500, "bind exploded")

    fc.bind_pod = failing_bind
    try:
        with pytest.raises(AllocationError):
            info.allocate(pod, fc, ha_claims=True)
    finally:
        fc.bind_pod = real_bind

    # reservation rolled back: the full chip is available again
    assert info.snapshot()[0].free_hbm_mib == 16384
    # claim dropped: a fresh cache (worst-case watch lag) can place a
    # full-chip pod right away
    fresh = SchedulerCache(fc)
    pod2 = fc.create_pod(make_pod(hbm=16384, name="w2"))
    fresh.get_node_info("n1").allocate(pod2, fc, ha_claims=True)
