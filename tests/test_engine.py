"""Continuous-batching decode engine (workloads/engine.py).

Core claim under test: slot residency is invisible to numerics — a
request decodes the same tokens whether it runs alone or shares quanta
with arbitrary co-tenants, because each slot's lane IS the tested
single-stream forward_cached computation (vmapped), pad positions sit
beyond the position-mask watermark, and masked lanes contribute exactly
zero. The reference has no serving engine at all; the baseline here is
tpushare's own single-stream decoder.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpushare.workloads.engine import DecodeEngine, _bucket
from tpushare.workloads.model import (
    PRESETS, forward_cached, greedy_decode_kv, init_kv_cache,
    init_params, quantize_int8)

CFG = PRESETS["llama-tiny"]
PARAMS = init_params(CFG, jax.random.key(0))


def solo_reference(prompt, max_new, max_len, params=PARAMS, cfg=CFG):
    """Single-stream decode with the SAME cache geometry as the engine
    (buffer length determines fp reduction order, so parity claims must
    hold it fixed)."""
    cache = init_kv_cache(cfg, 1, max_len)
    logits, cache = forward_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cache,
        jnp.int32(0), cfg)
    toks = [int(jnp.argmax(logits[0, -1]))]
    pos = len(prompt)
    while len(toks) < max_new:
        logits, cache = forward_cached(
            params, jnp.asarray([[toks[-1]]], jnp.int32), cache,
            jnp.int32(pos), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
        pos += 1
    return toks


def test_solo_request_matches_greedy_decode_kv():
    # same buffer length as greedy_decode_kv's total => exact equality
    prompt = [3, 141, 59, 26, 53]
    eng = DecodeEngine(PARAMS, CFG, max_slots=2,
                       max_len=len(prompt) + 6)
    rid = eng.submit(prompt, max_new=6)
    out = eng.drain()
    ref = greedy_decode_kv(PARAMS, jnp.asarray(prompt, jnp.int32)[None],
                           6, CFG)
    assert out[rid] == [int(t) for t in np.asarray(ref[0, len(prompt):])]


def test_cotenants_do_not_perturb_each_other():
    # three ragged requests joining at different quanta decode exactly
    # what each decodes alone under the same cache geometry
    M = 48
    prompts = {"a": [5, 9], "b": [100, 2, 77, 31, 8, 4, 19],
               "c": [240] * 11}
    budgets = {"a": 9, "b": 4, "c": 7}
    eng = DecodeEngine(PARAMS, CFG, max_slots=4, max_len=M, quantum=3)
    rids = {k: eng.submit(prompts[k], budgets[k]) for k in ("a", "b")}
    out = dict(eng.run_quantum())      # a+b in flight (b may finish here)
    rids["c"] = eng.submit(prompts["c"], budgets["c"])  # ...c joins late
    out.update(eng.drain())
    for k in prompts:
        assert out[rids[k]] == solo_reference(prompts[k], budgets[k], M), k


def test_slots_recycle_and_gate():
    eng = DecodeEngine(PARAMS, CFG, max_slots=2, max_len=32, quantum=4)
    r1 = eng.submit([1, 2], 3)
    r2 = eng.submit([3], 3)
    with pytest.raises(RuntimeError, match="no free slot"):
        eng.submit([4], 2)
    done = eng.drain()
    assert set(done) == {r1, r2} and eng.free_slots == 2
    r3 = eng.submit([9, 9, 9], 2)      # recycled slot decodes correctly
    assert eng.drain()[r3] == solo_reference([9, 9, 9], 2, 32)


def test_eos_frees_slot_early():
    # pick the model's own first prediction as "eos": generation stops
    # at 1 token even though the budget allows 5
    prompt = [7, 7, 3]
    first = solo_reference(prompt, 1, 32)[0]
    eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                       eos_id=first)
    rid = eng.submit(prompt, max_new=5)
    out = eng.drain()
    assert out[rid] == [first] and eng.free_slots == 1


@pytest.mark.tpu_kernel
def test_per_request_eos_override():
    # stop tokens vary per request: one co-tenant stops at ITS second
    # prediction, the other (same prompt, engine-default eos) runs its
    # whole budget — and the compare target being per-slot state means
    # this works in the default static mode too
    prompt, n = [7, 7, 3], 5
    full = solo_reference(prompt, n, 32)
    second = full[1]
    eng = DecodeEngine(PARAMS, CFG, max_slots=2, max_len=32, quantum=2)
    r_stop = eng.submit(prompt, n, eos_id=second)
    r_full = eng.submit(prompt, n)
    out = eng.drain()
    assert out[r_stop] == full[:2]      # stopped at its own eos
    assert out[r_full] == full          # engine default (-1): no stop
    # prefill-time eos: a request whose FIRST token is its stop token
    # completes at submit
    r_instant = eng.submit(prompt, n, eos_id=full[0])
    assert eng.free_slots == 2
    assert eng.drain()[r_instant] == full[:1]


def test_budget_one_completes_at_submit():
    eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32)
    rid = eng.submit([1, 2, 3], max_new=1)
    assert eng.free_slots == 1          # never occupied a decode quantum
    out = eng.run_quantum()
    assert out == {rid: solo_reference([1, 2, 3], 1, 32)}


def test_int8_kv_cache_engine():
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    params = PARAMS
    eng = DecodeEngine(params, cfg, max_slots=2, max_len=32, quantum=2)
    ra = eng.submit([5, 6, 7], 4)
    rb = eng.submit([11], 4)
    out = eng.drain()
    assert out[ra] == solo_reference([5, 6, 7], 4, 32, params, cfg)
    assert out[rb] == solo_reference([11], 4, 32, params, cfg)


def test_validation():
    eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=16)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], 2)
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit([1] * 10, 8)
    with pytest.raises(ValueError, match="max_new"):
        eng.submit([1], 0)
    with pytest.raises(ValueError, match="MoE"):
        DecodeEngine(PARAMS, PRESETS["llama-moe-tiny"], 1, 16)
    assert [_bucket(n) for n in (1, 8, 9, 17)] == [8, 8, 16, 32]


def test_non_pow2_max_len_bucket_caps():
    # plen 17 rounds to bucket 32 > max_len 24: the bucket must cap at
    # the slot's KV buffer or the prefill cache write crashes
    M = 24
    eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=M)
    prompt = list(range(1, 18))          # 17 tokens, +4 new fits 24
    rid = eng.submit(prompt, 4)
    assert eng.drain()[rid] == solo_reference(prompt, 4, M)


def test_flash_prefill_config_parity():
    # cfg.attn="flash" routes the engine's bucketed prefill through the
    # fused kernel (forward_cached's prefill-from-zero path — serving's
    # time-to-first-token cost); outputs must match the einsum config.
    # fp32 configs: on bf16 the two paths differ by kernel rounding and
    # an untrained model's near-tie argmaxes would flake (the same
    # discipline as tests/test_kvcache.py's flash comparisons)
    cfg32 = dataclasses.replace(CFG, dtype=jnp.float32).validate()
    params32 = init_params(cfg32, jax.random.key(0))
    cfg_f = dataclasses.replace(cfg32, attn="flash").validate()
    prompt, n = [3, 141, 59, 7, 7, 7, 7, 7], 4
    ef = DecodeEngine(params32, cfg_f, max_slots=1, max_len=32)
    rf = ef.submit(prompt, n)
    ee = DecodeEngine(params32, cfg32, max_slots=1, max_len=32)
    re_ = ee.submit(prompt, n)
    assert ef.drain()[rf] == ee.drain()[re_]


def test_quantized_weights_engine():
    qparams = quantize_int8(PARAMS)
    eng = DecodeEngine(qparams, CFG, max_slots=2, max_len=32)
    rid = eng.submit([2, 4, 8], 3)
    assert eng.drain()[rid] == solo_reference([2, 4, 8], 3, 32, qparams)


def test_sampling_topk1_equals_greedy():
    # top-1 masking leaves one finite logit: categorical must pick it,
    # so temperature>0 + top_k=1 reproduces the greedy stream exactly
    prompt, n = [3, 141, 59], 6
    greedy = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32)
    rg = greedy.submit(prompt, n)
    sampled = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                           temperature=0.8, top_k=1)
    rs = sampled.submit(prompt, n)
    assert greedy.drain()[rg] == sampled.drain()[rs]


def test_sampling_is_reproducible_and_residency_independent():
    # the sample key is (seed, request id, position): with the same
    # submission order, a request draws the same stream whether it runs
    # alone or with co-tenants joining around it
    prompt, n = [9, 9, 2], 10
    kw = dict(temperature=1.5, top_k=8, seed=7)
    solo = DecodeEngine(PARAMS, CFG, max_slots=3, max_len=48, **kw)
    r_solo = solo.submit(prompt, n)         # rid 0
    out_solo = solo.drain()[r_solo]

    mixed = DecodeEngine(PARAMS, CFG, max_slots=3, max_len=48,
                         quantum=3, **kw)
    r_mix = mixed.submit(prompt, n)         # rid 0, same stream
    mixed.submit([44, 1], 5)
    mixed.run_quantum()
    mixed.submit([7] * 6, 4)                # joins mid-flight
    out_mix = mixed.drain()[r_mix]
    assert out_solo == out_mix
    assert len(out_solo) == n


def test_sampling_seed_changes_stream():
    prompt, n = [5, 80, 3], 16
    outs = []
    for seed in (0, 1):
        eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                           temperature=2.0, seed=seed)
        rid = eng.submit(prompt, n)
        outs.append(eng.drain()[rid])
    assert outs[0] != outs[1]


def test_random_ragged_traffic_invariants():
    # property-style churn: 12 ragged requests trickle into 3 slots
    # across many quanta. Invariants: every request completes exactly
    # once with exactly its budget (no eos configured), the pool drains
    # back to empty, and a sample of outputs is bitwise the solo stream
    import random
    rng = random.Random(0)
    M = 48
    eng = DecodeEngine(PARAMS, CFG, max_slots=3, max_len=M, quantum=2)
    pending = [([rng.randrange(1, CFG.vocab) for _ in
                 range(rng.randrange(1, 12))], rng.randrange(1, 9))
               for _ in range(12)]
    meta, results = {}, {}
    while pending or eng.resident:
        while eng.free_slots and pending and rng.random() < 0.7:
            prompt, budget = pending.pop()
            rid = eng.submit(list(prompt), budget)
            meta[rid] = (prompt, budget)
        done = eng.run_quantum()
        # exactly-once: a rid must never be reported by two quanta
        assert not (results.keys() & done.keys())
        results.update(done)
    assert set(results) == set(meta)
    assert eng.free_slots == 3 and eng.resident == 0
    for rid, toks in results.items():
        assert len(toks) == meta[rid][1], rid
    for rid in list(results)[::5]:      # spot-check parity
        prompt, budget = meta[rid]
        assert results[rid] == solo_reference(prompt, budget, M), rid


def test_streaming_hooks_cover_every_token_exactly_once():
    # peek_tokens right after submit + last_quantum_tokens per quantum
    # must reconstruct the final stream with no gaps or duplicates —
    # the contract serve.py's NDJSON streaming is built on
    eng = DecodeEngine(PARAMS, CFG, max_slots=2, max_len=32, quantum=3)
    rid = eng.submit([3, 141, 59], 8)
    seen = list(eng.peek_tokens(rid))     # the prefill's token
    assert len(seen) == 1
    final = None
    while final is None:
        done = eng.run_quantum()
        seen.extend(eng.last_quantum_tokens.get(rid, []))
        final = done.get(rid)
    assert seen == final == solo_reference([3, 141, 59], 8, 32)
    assert eng.peek_tokens(rid) is None   # reported => gone


def test_nucleus_tiny_p_equals_greedy():
    # top_p -> 0 keeps exactly the first-crossing (= highest-prob)
    # token, so sampling degenerates to argmax — the boundary that
    # proves the crossing token is INCLUDED in the nucleus
    prompt, n = [3, 141, 59], 6
    greedy = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32)
    rg = greedy.submit(prompt, n)
    nucleus = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                           temperature=1.3, top_p=1e-6)
    rn = nucleus.submit(prompt, n)
    assert greedy.drain()[rg] == nucleus.drain()[rn]


def test_nucleus_off_is_identical_to_plain_temperature():
    # top_p=1.0 must compile the exact same selection as no top_p arg
    prompt, n = [9, 9, 2], 10
    outs = []
    for kw in ({}, {"top_p": 1.0}):
        eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                           temperature=1.5, seed=11, **kw)
        rid = eng.submit(prompt, n)
        outs.append(eng.drain()[rid])
    assert outs[0] == outs[1]


def test_per_request_sampling_mixed_traffic():
    # one compiled program, mixed traffic: a no-override request in a
    # per-request engine decodes the EXACT greedy stream while a
    # sampled co-tenant shares its quanta
    prompt_g, prompt_s, n = [3, 141, 59], [9, 9, 2], 7
    eng = DecodeEngine(PARAMS, CFG, max_slots=2, max_len=32, quantum=3,
                       per_request_sampling=True)
    rg = eng.submit(prompt_g, n)                       # inherits temp 0
    rs = eng.submit(prompt_s, n, temperature=2.0, top_p=0.9)
    out = eng.drain()
    assert out[rg] == solo_reference(prompt_g, n, 32)  # bitwise greedy
    assert len(out[rs]) == n


def test_per_request_overrides_are_reproducible():
    prompt, n = [5, 80, 3], 8
    outs = []
    for _ in range(2):
        eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                           seed=5, per_request_sampling=True)
        rid = eng.submit(prompt, n, temperature=1.7, top_p=0.8)
        outs.append(eng.drain()[rid])
    assert outs[0] == outs[1] and len(outs[0]) == n


def test_per_request_engine_default_greedy_matches_static():
    prompt, n = [2, 4, 8], 5
    static = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32)
    rs = static.submit(prompt, n)
    dyn = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=32,
                       per_request_sampling=True)
    rd = dyn.submit(prompt, n)
    assert static.drain()[rs] == dyn.drain()[rd]


def test_per_request_overrides_rejected_on_static_engine():
    eng = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=16)
    with pytest.raises(ValueError, match="per_request_sampling"):
        eng.submit([1, 2], 2, temperature=1.0)
    with pytest.raises(ValueError, match="per_request_sampling"):
        eng.submit([1, 2], 2, top_p=0.5)
    dyn = DecodeEngine(PARAMS, CFG, max_slots=1, max_len=16,
                       per_request_sampling=True)
    with pytest.raises(ValueError, match="top_p"):
        dyn.submit([1, 2], 2, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="temperature"):
        dyn.submit([1, 2], 2, temperature=-1.0)
    # explicit nucleus directive at effective temperature 0 would be
    # silently greedy: refused, mirroring the static ctor guard
    with pytest.raises(ValueError, match="requires temperature"):
        dyn.submit([1, 2], 2, top_p=0.9)
    with pytest.raises(ValueError, match="requires temperature"):
        dyn.submit([1, 2], 2, temperature=0.0, top_p=0.9)


def test_static_greedy_program_compiles_no_sort():
    # the per-request mode's cost (a per-slot vocab sort every step) is
    # documented as opt-in; guard that the static greedy engine's
    # compiled quantum really contains no sort, and the dynamic one does
    def quantum_hlo(**kw):
        eng = DecodeEngine(PARAMS, CFG, max_slots=2, max_len=32, **kw)
        return eng._quantum_fn.lower(
            eng._cache, eng._pos, eng._last, eng._active,
            eng._remaining, eng._slot_keys, eng._slot_temp,
            eng._slot_topp, eng._slot_eos, 2).as_text()

    assert "sort(" not in quantum_hlo()
    assert "sort(" in quantum_hlo(per_request_sampling=True)


def test_sampling_validation():
    with pytest.raises(ValueError, match="temperature"):
        DecodeEngine(PARAMS, CFG, 1, 16, temperature=-0.1)
    with pytest.raises(ValueError, match="top_k"):
        DecodeEngine(PARAMS, CFG, 1, 16, top_k=CFG.vocab + 1)
    with pytest.raises(ValueError, match="top_p"):
        DecodeEngine(PARAMS, CFG, 1, 16, top_p=1.5)
    with pytest.raises(ValueError, match="top_p"):
        DecodeEngine(PARAMS, CFG, 1, 16, top_p=0.0)
    # top_k/top_p alone would silently greedy-decode: refuse the footgun
    with pytest.raises(ValueError, match="require"):
        DecodeEngine(PARAMS, CFG, 1, 16, top_k=8)
    with pytest.raises(ValueError, match="require"):
        DecodeEngine(PARAMS, CFG, 1, 16, top_p=0.9)


# -- rolling (ring) slots ------------------------------------------------------

ROLL_CFG = dataclasses.replace(CFG, attn_window=8)
ROLL_PARAMS = init_params(ROLL_CFG, jax.random.key(0))


def _greedy_rolling_ref(prompt, steps, params=ROLL_PARAMS, cfg=ROLL_CFG):
    """Solo rolling reference at matched ring geometry: total >= 2W makes
    greedy_decode_kv's ring exactly 2W — the engine's max_len in these
    tests — so position->slot layout (hence fp reduction order) is
    identical and parity is bitwise."""
    assert len(prompt) + steps >= 2 * cfg.attn_window
    buf = greedy_decode_kv(params, jnp.asarray(prompt, jnp.int32)[None],
                           steps, cfg, rolling=True)
    return [int(t) for t in np.asarray(buf)[0, len(prompt):]]


def test_rolling_engine_matches_greedy_rolling_under_churn():
    # 6 ragged requests through 3 rolling slots: prompts spanning
    # sub-window, window-straddling, and multi-chunk lengths; slots are
    # freed and re-used (churn) while co-tenants keep decoding
    W = ROLL_CFG.attn_window
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, ROLL_CFG.vocab, size=n).tolist()
               for n in (3, 5, 9, 13, 17, 21)]
    budgets = [13, 20, 9, 25, 14, 30]
    refs = [_greedy_rolling_ref(p, b) for p, b in zip(prompts, budgets)]
    eng = DecodeEngine(ROLL_PARAMS, ROLL_CFG, max_slots=3,
                       max_len=2 * W, rolling=True)
    rids, out, pending = {}, {}, list(range(len(prompts)))
    while pending or rids:
        while pending and eng.free_slots:
            i = pending.pop(0)
            rids[eng.submit(prompts[i], budgets[i])] = i
        for rid, toks in eng.run_quantum().items():
            out[rids.pop(rid)] = toks
    for i, ref in enumerate(refs):
        assert out[i] == ref, f"request {i} diverged from solo rolling"


def test_rolling_engine_generation_runs_past_the_ring():
    # the composition's whole point: generation 5x the buffer length
    # with cache HBM pinned at O(window) — and still bitwise the solo
    # rolling stream (prompt+generation cross the wraparound repeatedly)
    for kvd in ("bf16", "int8"):
        cfg = (dataclasses.replace(ROLL_CFG, kv_cache_dtype="int8")
               if kvd == "int8" else ROLL_CFG)
        params = (ROLL_PARAMS if kvd == "bf16"
                  else init_params(cfg, jax.random.key(0)))
        prompt = np.random.default_rng(1).integers(
            1, cfg.vocab, size=11).tolist()
        ref = _greedy_rolling_ref(prompt, 90, params, cfg)
        eng = DecodeEngine(params, cfg, max_slots=2, max_len=16,
                           rolling=True)
        rid = eng.submit(prompt, 90)
        assert eng.drain()[rid] == ref, kvd
        assert eng._cache["k"].shape[2] == 16  # ring never grew


def test_rolling_engine_prompt_longer_than_ring():
    # a prompt longer than the ring itself: chunked prefill ages early
    # keys out exactly like greedy_decode_kv's chunked prefill does
    prompt = np.random.default_rng(3).integers(
        1, ROLL_CFG.vocab, size=37).tolist()  # 37 > M = 16
    ref = _greedy_rolling_ref(prompt, 12)
    eng = DecodeEngine(ROLL_PARAMS, ROLL_CFG, max_slots=2, max_len=16,
                       rolling=True)
    rid = eng.submit(prompt, 12)
    assert eng.drain()[rid] == ref


def test_rolling_engine_validation():
    with pytest.raises(ValueError, match="attn_window"):
        DecodeEngine(PARAMS, CFG, 2, 64, rolling=True)  # no window
    with pytest.raises(ValueError, match="2\\*attn_window"):
        DecodeEngine(ROLL_PARAMS, ROLL_CFG, 2,
                     2 * ROLL_CFG.attn_window - 1, rolling=True)
    # rolling lifts the prompt+budget<=max_len bound instead of
    # enforcing it
    eng = DecodeEngine(ROLL_PARAMS, ROLL_CFG, 1, 16, rolling=True)
    rid = eng.submit(list(range(1, 30)), max_new=40)  # 29+40 >> 16
    assert len(eng.drain()[rid]) == 40
