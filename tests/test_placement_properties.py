"""Randomized property tests for the placement engine (SURVEY §7 stage 1:
"Property tests: never oversubscribe, fragmentation metrics").

A seeded multi-step simulation drives select_chips through thousands of
allocate/release cycles over random mesh shapes and asserts the invariants
the whole scheduler rests on. Runs through the public select_chips entry,
so whichever engine is live (C++ when buildable, else Python) is the one
being property-checked.
"""

import random

import pytest

from tpushare.core.chips import ChipView
from tpushare.core.placement import (
    PlacementRequest, fits, fragmentation, select_chips, utilization_pct)
from tpushare.core.topology import MeshTopology

MESHES = [(1,), (2,), (4,), (2, 2), (4, 2), (4, 4), (2, 2, 2)]


def fresh_chips(topo: MeshTopology, total: int) -> list[ChipView]:
    return [ChipView(i, topo.coords(i), total)
            for i in range(topo.num_chips)]


def random_request(rng: random.Random, total: int) -> PlacementRequest:
    if rng.random() < 0.15:
        return PlacementRequest(hbm_mib=0,
                                chip_count=rng.choice([1, 2, 4]))  # exclusive
    return PlacementRequest(
        hbm_mib=rng.choice([256, 1024, 2048, total // 2, total]),
        chip_count=rng.choice([1, 1, 1, 2, 4]),
        allow_scatter=rng.random() < 0.3,
    )


def is_axis_aligned_box(topo, ids, box, origin):
    return sorted(ids) == sorted(topo.box_chips(origin, box))


@pytest.mark.parametrize("seed", range(8))
def test_allocation_invariants_under_churn(seed):
    rng = random.Random(seed)
    total = 16000
    topo = MeshTopology(rng.choice(MESHES))
    chips = fresh_chips(topo, total)
    live: list[tuple[tuple[int, ...], int]] = []  # (chip_ids, per-chip demand)

    for step in range(400):
        if live and rng.random() < 0.4:
            ids, demand = live.pop(rng.randrange(len(live)))
            chips = [c.with_used(c.used_hbm_mib - demand)
                     if c.idx in ids else c for c in chips]
            continue

        req = random_request(rng, total)
        placement = select_chips(chips, topo, req)
        claims_fit = fits(chips, topo, req)
        if placement is None:
            # fits() may only be MORE permissive for scatter-able requests
            # (it counts eligible chips without contiguity); for contiguous
            # multi-chip it must agree exactly with the selector
            if req.chip_count > 1 and not req.allow_scatter:
                assert not claims_fit
            continue
        assert claims_fit, f"selector placed but fits()==False: {req}"

        # distinct chips, as many as requested
        assert len(set(placement.chip_ids)) == req.chip_count
        demand = req.chip_demand_mib(total)
        for cid in placement.chip_ids:
            c = chips[cid]
            assert c.healthy
            if req.exclusive:
                assert c.used_hbm_mib == 0
            # the load-bearing invariant: NEVER oversubscribe a chip
            assert c.used_hbm_mib + demand <= total
        # contiguity: a non-scatter multi-chip result is an axis-aligned box
        if placement.contiguous and req.chip_count > 1:
            assert is_axis_aligned_box(topo, placement.chip_ids,
                                       placement.box, placement.origin)
        elif req.chip_count > 1:
            assert req.allow_scatter  # scatter only when the pod opted in

        chips = [c.with_used(c.used_hbm_mib + demand)
                 if c.idx in placement.chip_ids else c for c in chips]
        live.append((placement.chip_ids, demand))

    # metrics stay in range whatever state churn produced
    assert 0.0 <= utilization_pct(chips) <= 100.0
    assert 0.0 <= fragmentation(chips) <= 1.0


@pytest.mark.parametrize("seed", range(4))
def test_unhealthy_chips_never_selected(seed):
    rng = random.Random(1000 + seed)
    topo = MeshTopology(rng.choice(MESHES))
    total = 8192
    bad = {i for i in range(topo.num_chips) if rng.random() < 0.4}
    chips = [ChipView(i, topo.coords(i), total, healthy=i not in bad)
             for i in range(topo.num_chips)]
    for _ in range(100):
        req = random_request(rng, total)
        p = select_chips(chips, topo, req)
        if p is not None:
            assert not (set(p.chip_ids) & bad)


def test_binpack_preserves_large_holes():
    # min-free-that-fits: small pods stack on the fullest chip that still
    # fits, keeping whole chips free for whole-chip pods (reference
    # allocateGPUID semantics, nodeinfo.go:283-286)
    topo = MeshTopology((4,))
    total = 16000
    chips = fresh_chips(topo, total)
    for _ in range(8):
        p = select_chips(chips, topo, PlacementRequest(hbm_mib=1000))
        chips = [c.with_used(c.used_hbm_mib + 1000)
                 if c.idx in p.chip_ids else c for c in chips]
    used = sorted(c.used_hbm_mib for c in chips)
    # all 8 small pods should have stacked onto one chip, not spread 2-each
    assert used == [0, 0, 0, 8000]
    # so a whole-chip pod still fits
    assert select_chips(chips, topo,
                        PlacementRequest(hbm_mib=0, chip_count=1)) is not None


def test_saturation_reaches_full_utilization():
    # deterministic greedy fill must reach 100% (no stranded capacity from
    # the selector's own decisions)
    topo = MeshTopology((4, 4))
    total = 16000
    chips = fresh_chips(topo, total)
    sizes = [8000, 4000, 2000, 1000, 500, 250, 125]
    progress = True
    while progress:
        progress = False
        for s in sizes:
            while True:
                p = select_chips(chips, topo, PlacementRequest(hbm_mib=s))
                if p is None:
                    break
                chips = [c.with_used(c.used_hbm_mib + s)
                         if c.idx in p.chip_ids else c for c in chips]
                progress = True
    free = sum(c.free_hbm_mib for c in chips)
    # only the sub-125-MiB remainder per chip may be left
    assert free <= 124 * len(chips)
    assert utilization_pct(chips) > 99.0
