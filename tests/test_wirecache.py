"""Wire-plane cache (ISSUE 14): digest decode, pre-encoded responses,
pipelined bind writes, and the keep-alive staleness probe.

The wirecache's whole contract is "invisible on the wire": with the
layer on, every byte leaving the extender must be identical to what a
plain json.loads/json.dumps path would produce, across arbitrary
request shapes AND arbitrary interleavings of cache mutations. The
parity property test here drives both configurations over the SAME
shared cache and compares bodies byte-for-byte; the poisoning tests
prove the TPUSHARE_WIRE_VERIFY tripwire actually fires (a watchdog
that cannot bark is decoration).
"""

import json
import random
import socket
import threading
import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import AllocationError, SchedulerCache
from tpushare.cache.nodeinfo import BIND_PIPELINE
from tpushare.extender.handlers import (
    BindHandler, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.extender.wirecache import (
    WIRE_DIGEST, WIRE_STALE_SERVES, WireCache, WireEncoded, _find_span)
from tpushare.k8s import ApiError, FakeCluster

HBM = 16000


def fleet(n_nodes=4, chips=4, mesh="2x2"):
    fc = FakeCluster()
    for i in range(n_nodes):
        fc.add_tpu_node(f"n{i}", chips=chips, hbm_per_chip_mib=HBM,
                        mesh=mesh)
    return fc, [f"n{i}" for i in range(n_nodes)]


def wire_rig(fc, **wire_kwargs):
    """(cache, wirecache, filter handler, prioritize handler) with the
    wire plane threaded exactly as ExtenderServer wires it."""
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    wire = WireCache(cache, **wire_kwargs)
    return (cache, wire,
            FilterHandler(cache, registry, wire=wire),
            PrioritizeHandler(cache, registry, wire=wire))


def body_for(pod, node_names):
    return json.dumps(
        {"Pod": pod, "Nodes": None, "NodeNames": node_names}).encode()


def serve(wire, fh, ph, verb, raw):
    """One webhook request through the same decode->handle->encode path
    ExtenderServer.handle_post takes; returns the response BYTES."""
    args, ctx = wire.decode(raw)
    handler = fh if verb == "filter" else ph
    out = handler.handle(args, wire_ctx=ctx)
    if isinstance(out, WireEncoded):
        return out.body
    return json.dumps(out).encode()


def serve_plain(fh, ph, verb, raw):
    """The reference path: plain parse, plain encode, no wire context."""
    out = (fh if verb == "filter" else ph).handle(json.loads(raw))
    assert not isinstance(out, WireEncoded)
    return json.dumps(out).encode()


# -- span scanner -------------------------------------------------------------

def test_find_span_locates_the_array():
    raw = b'{"Pod": {}, "NodeNames": ["a", "b"]}'
    s, e = _find_span(raw)
    assert raw[s:e] == b'["a", "b"]'


def test_find_span_tolerates_whitespace():
    raw = b'{"NodeNames"  :\n\t [ "a" ]}'
    s, e = _find_span(raw)
    assert json.loads(raw[s:e]) == ["a"]


@pytest.mark.parametrize("raw", [
    b'{"Pod": {}}',                        # key absent
    b'{"NodeNames": null}',                # not an array
    b'{"NodeNames": 3}',                   # not an array
    b'{"NodeNames": ["a"',                 # unterminated
])
def test_find_span_rejects_non_arrays(raw):
    span = _find_span(raw)
    if span is not None:
        s, e = span
        with pytest.raises(json.JSONDecodeError):
            json.loads(raw[s:e])


def test_decode_bypasses_bracket_inside_name():
    """A ] inside a node name makes the scanned span invalid JSON — the
    decode must fall back to a plain parse, not mis-split the list."""
    fc, _ = fleet(1)
    cache = SchedulerCache(fc)
    cache.build_cache()
    wire = WireCache(cache)
    weird = ["odd]name", "n0"]
    raw = json.dumps({"Pod": make_pod(hbm=100), "NodeNames": weird}).encode()
    args, ctx = wire.decode(raw)
    assert ctx is None  # bypass, never a poisoned entry
    assert args["NodeNames"] == weird


def test_decode_bypasses_spoofed_key_in_annotation():
    """"NodeNames" appearing INSIDE a string value must not hijack the
    digest path (rfind + splice guard)."""
    fc, names = fleet(2)
    cache = SchedulerCache(fc)
    cache.build_cache()
    wire = WireCache(cache)
    pod = make_pod(hbm=100, ann={"note": 'fake "NodeNames": ["x"] here'})
    # real NodeNames marshals after Pod (Go field order) — rfind wins
    raw = body_for(pod, names)
    args, ctx = wire.decode(raw)
    assert args["NodeNames"] == names
    assert ctx is not None
    # and when the spoof is the LAST occurrence (NodeNames absent), the
    # splice guard rejects it
    raw2 = json.dumps({"Pod": pod}).encode()
    args2, ctx2 = wire.decode(raw2)
    assert ctx2 is None
    assert "NodeNames" not in args2


def test_digest_hit_reuses_interned_list():
    fc, names = fleet(3)
    cache = SchedulerCache(fc)
    cache.build_cache()
    wire = WireCache(cache)
    raw = body_for(make_pod(hbm=100), names)
    a1, c1 = wire.decode(raw)
    a2, c2 = wire.decode(raw)
    assert c1 is not None and c2 is not None
    assert a2["NodeNames"] is a1["NodeNames"]  # the SAME list object
    snap = WIRE_DIGEST.snapshot()
    assert snap.get(("hit",), 0) >= 1


# -- byte parity (the tentpole acceptance property) ---------------------------

def test_wire_parity_randomized_shapes_and_mutations():
    """Property: wirecache on == wirecache off, byte for byte, across
    randomized request shapes interleaved with cache mutations (binds
    bump the mutation stamp; stale cached bytes must never be served)."""
    rng = random.Random(0x77173)
    fc, names = fleet(4)
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    wire = WireCache(cache)
    fh = FilterHandler(cache, registry, wire=wire)
    ph = PrioritizeHandler(cache, registry, wire=wire)
    # reference handlers over the SAME cache, no wire plane at all
    fh0 = FilterHandler(cache, registry)
    ph0 = PrioritizeHandler(cache, registry)
    bh = BindHandler(cache, fc, registry)

    bound = 0
    for step in range(60):
        shape = rng.random()
        candidates = rng.sample(names, rng.randint(1, len(names)))
        if rng.random() < 0.5:  # repeat lists exercise the digest hits
            candidates = names
        hbm = rng.choice([100, 1000, 4000, HBM // 2])
        pod = make_pod(hbm=hbm, name=f"q{step}", uid=f"uid-q{step}")
        raw = body_for(pod, candidates)
        verb = "filter" if rng.random() < 0.6 else "prioritize"
        got = serve(wire, fh, ph, verb, raw)
        want = serve_plain(fh0, ph0, verb, raw)
        assert got == want, (
            f"step {step} {verb}: wirecache bytes diverged\n"
            f"  wire : {got[:200]!r}\n  plain: {want[:200]!r}")
        if shape < 0.25 and bound < 8:
            # mutate the fleet mid-storm: a real bind through the full
            # handler (claims chips, bumps the mutation stamp)
            bp = make_pod(hbm=2000, name=f"b{bound}", uid=f"uid-b{bound}")
            fc.create_pod(bp)
            node = rng.choice(names)
            out = bh.handle({"PodNamespace": "default",
                             "PodName": f"b{bound}",
                             "PodUID": f"uid-b{bound}", "Node": node})
            assert not out.get("Error"), out
            bound += 1
            # post-mutation responses must reflect the new fleet state
            raw2 = body_for(make_pod(hbm=hbm, name=f"q{step}-post",
                                     uid=f"uid-q{step}p"), names)
            assert (serve(wire, fh, ph, "filter", raw2)
                    == serve_plain(fh0, ph0, "filter", raw2))
    assert bound > 0  # the interleaving actually happened
    snap = WIRE_DIGEST.snapshot()
    assert snap.get(("hit",), 0) > 0  # and the cache actually hit


def test_wire_parity_unicode_and_empty():
    fc, _ = fleet(1)
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    wire = WireCache(cache)
    fh = FilterHandler(cache, registry, wire=wire)
    ph = PrioritizeHandler(cache, registry, wire=wire)
    fh0 = FilterHandler(cache, registry)
    ph0 = PrioritizeHandler(cache, registry)
    pod = make_pod(hbm=100)
    for candidates in ([], ["n0"], ["unknown-node"],
                       ["n0", "nöde-ü", "名前"]):
        raw = body_for(pod, candidates)
        for verb in ("filter", "prioritize"):
            assert (serve(wire, fh, ph, verb, raw)
                    == serve_plain(fh0, ph0, verb, raw)), (verb, candidates)


# -- verify-mode tripwire -----------------------------------------------------

def test_poisoned_digest_caught_under_verify():
    """Corrupt a cached name list; TPUSHARE_WIRE_VERIFY must count the
    mismatch and serve the recomputed truth."""
    fc, names = fleet(3)
    cache, wire, fh, ph = wire_rig(fc, verify=True)
    raw = body_for(make_pod(hbm=100), names)
    wire.decode(raw)  # prime
    for entry in wire._entries.values():
        entry.names[0] = "poisoned-node"  # simulate a stamp-protocol bug
    before = WIRE_STALE_SERVES.value
    args, ctx = wire.decode(raw)
    assert WIRE_STALE_SERVES.value == before + 1
    assert ctx is None  # poisoned entry skipped
    assert args["NodeNames"] == names  # the truth, not the poison


def test_poisoned_response_caught_under_verify():
    fc, names = fleet(3)
    cache, wire, fh, ph = wire_rig(fc, verify=True)
    raw = body_for(make_pod(hbm=100, name="vp", uid="uid-vp"), names)
    want = serve(wire, fh, ph, "filter", raw)   # prime (encoded + stored)
    # corrupt every stored response body in place, keeping its stamp
    for entry in wire._entries.values():
        for key, (stamp, enc) in list(entry.responses.items()):
            entry.responses[key] = (
                stamp, WireEncoded(b'{"NodeNames": ["liar"], '
                                   b'"FailedNodes": {}, "Error": ""}',
                                   ok=1))
    before = WIRE_STALE_SERVES.value
    got = serve(wire, fh, ph, "filter", raw)
    assert WIRE_STALE_SERVES.value == before + 1
    assert got == want  # truth served, not the poisoned bytes


def test_clean_hits_are_not_flagged_under_verify():
    fc, names = fleet(3)
    cache, wire, fh, ph = wire_rig(fc, verify=True)
    raw = body_for(make_pod(hbm=100, name="cv", uid="uid-cv"), names)
    before = WIRE_STALE_SERVES.value
    first = serve(wire, fh, ph, "filter", raw)
    second = serve(wire, fh, ph, "filter", raw)
    assert first == second
    assert WIRE_STALE_SERVES.value == before  # zero stale serves


def test_mutation_stamp_invalidates_responses():
    fc, names = fleet(2)
    cache, wire, fh, ph = wire_rig(fc)
    pod = make_pod(hbm=100, name="ms", uid="uid-ms")
    raw = body_for(pod, names)
    serve(wire, fh, ph, "filter", raw)  # primes the response cache
    stamp0 = cache.mutation_stamp()
    # any allocate bumps the stamp...
    bp = make_pod(hbm=2000, name="msb", uid="uid-msb")
    fc.create_pod(bp)
    cache.get_node_info("n0").allocate(bp, fc)
    assert cache.mutation_stamp() != stamp0
    # ...so the next identical request re-encodes instead of hitting
    args, ctx = wire.decode(raw)
    from tpushare.cache.nodeinfo import request_from_pod
    req = request_from_pod(args["Pod"])
    assert wire.lookup(ctx, "filter", req) is None


def test_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TPUSHARE_NO_WIRECACHE", "1")
    fc, names = fleet(1)
    cache = SchedulerCache(fc)
    cache.build_cache()
    wire = WireCache(cache)
    assert not wire.enabled
    args, ctx = wire.decode(body_for(make_pod(hbm=100), names))
    assert ctx is None and args["NodeNames"] == names


# -- pipelined bind outcomes --------------------------------------------------

class FailingCluster:
    """FakeCluster proxy that fails selected verbs on demand."""

    def __init__(self, inner):
        self._inner = inner
        self.fail_patch = False
        self.fail_bind = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def patch_pod(self, ns, name, patch):
        if self.fail_patch:
            raise ApiError(500, "injected patch failure")
        return self._inner.patch_pod(ns, name, patch)

    def bind_pod(self, ns, name, node, uid=None):
        if self.fail_bind:
            raise ApiError(500, "injected bind failure")
        return self._inner.bind_pod(ns, name, node, uid=uid)


def chips_held(cache, node):
    info = cache.get_node_info(node)
    with info._lock:
        return sum(len(c.pod_uids) for c in info.chips)


def test_pipelined_bind_happy_path_counts_pipelined():
    fc, _ = fleet(1)
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2000, name="pp", uid="uid-pp")
    fc.create_pod(pod)
    before = BIND_PIPELINE.snapshot()
    cache.get_node_info("n0").allocate(pod, fc)
    after = BIND_PIPELINE.snapshot()
    assert after.get(("pipelined",), 0) == before.get(("pipelined",), 0) + 1
    bound = fc.get_pod("default", "pp")
    assert bound["spec"]["nodeName"] == "n0"
    assert contract.chip_ids_from_annotations(bound) is not None


def test_pipelined_bind_fail_rolls_back_chips():
    fc, _ = fleet(1)
    fail = FailingCluster(fc)
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2000, name="bf", uid="uid-bf")
    fc.create_pod(pod)
    fail.fail_bind = True
    with pytest.raises(AllocationError):
        cache.get_node_info("n0").allocate(pod, fail)
    assert chips_held(cache, "n0") == 0  # reservation rolled back
    fresh = fc.get_pod("default", "bf")
    assert not fresh["spec"].get("nodeName")
    # the annotation revert ran: no placement left behind
    assert contract.chip_ids_from_annotations(fresh) is None


def test_patch_fail_bind_ok_repairs_forward():
    """POST landed, PATCH lost: the pod IS bound — the allocator must
    confirm the chips (rollback would double-book) and heal the
    annotations asynchronously."""
    fc, _ = fleet(1)
    fail = FailingCluster(fc)
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2000, name="pf", uid="uid-pf")
    fc.create_pod(pod)
    fail.fail_patch = True
    before = BIND_PIPELINE.snapshot()
    placement = cache.get_node_info("n0").allocate(pod, fail)
    assert placement is not None  # forward-only: the bind SUCCEEDED
    after = BIND_PIPELINE.snapshot()
    assert (after.get(("bind_first_repair",), 0)
            == before.get(("bind_first_repair",), 0) + 1)
    assert chips_held(cache, "n0") > 0  # chips stay confirmed
    bound = fc.get_pod("default", "pf")
    assert bound["spec"]["nodeName"] == "n0"
    fail.fail_patch = False  # partition heals; the async repair lands
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if contract.chip_ids_from_annotations(
                fc.get_pod("default", "pf")) is not None:
            break
        time.sleep(0.02)
    repaired = fc.get_pod("default", "pf")
    assert tuple(contract.chip_ids_from_annotations(repaired)) == \
        tuple(placement.chip_ids)


def test_sequential_bind_optout(monkeypatch):
    monkeypatch.setenv("TPUSHARE_NO_PIPELINED_BIND", "1")
    fc, _ = fleet(1)
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2000, name="sq", uid="uid-sq")
    fc.create_pod(pod)
    before = BIND_PIPELINE.snapshot()
    cache.get_node_info("n0").allocate(pod, fc)
    after = BIND_PIPELINE.snapshot()
    assert (after.get(("sequential",), 0)
            == before.get(("sequential",), 0) + 1)
    assert after.get(("pipelined",), 0) == before.get(("pipelined",), 0)


# -- keep-alive staleness probe (satellite 1 regression) ----------------------

class _MiniServer:
    """Raw-socket HTTP/1.1 server: keep-alive by default, with switches
    to idle-close between requests or die mid-response."""

    def __init__(self):
        self.sock = socket.create_server(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.requests = []          # every request line + body received
        self.close_after_next = False   # respond, then close (idle close)
        self.die_mid_response = False   # read request, close WITHOUT reply
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self.sock.settimeout(0.2)
        conns = []
        while not self._stop:
            try:
                c, _ = self.sock.accept()
                c.settimeout(5.0)
                t = threading.Thread(target=self._serve, args=(c,),
                                     daemon=True)
                t.start()
                conns.append(c)
            except socket.timeout:
                continue
            except OSError:
                break
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _serve(self, c):
        buf = b""
        try:
            while not self._stop:
                while b"\r\n\r\n" not in buf:
                    chunk = c.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                head, _, rest = buf.partition(b"\r\n\r\n")
                clen = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        clen = int(line.split(b":")[1])
                while len(rest) < clen:
                    chunk = c.recv(65536)
                    if not chunk:
                        return
                    rest += chunk
                body, buf = rest[:clen], rest[clen:]
                self.requests.append((head.split(b"\r\n")[0].decode(),
                                      body))
                if self.die_mid_response:
                    c.close()
                    return
                payload = b'{"ok": true}'
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Type: application/json\r\n"
                          b"Content-Length: "
                          + str(len(payload)).encode() + b"\r\n\r\n"
                          + payload)
                if self.close_after_next:
                    self.close_after_next = False
                    c.close()
                    return
        except OSError:
            pass
        finally:
            try:
                c.close()
            except OSError:
                pass

    def stop(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def mini():
    srv = _MiniServer()
    yield srv
    srv.stop()


def _pool_for(srv):
    from tpushare.k8s.incluster import _ConnPool
    return _ConnPool("127.0.0.1", srv.port, False, None)


def test_post_reuses_keepalive_and_probe_heals_idle_close(mini):
    from tpushare.k8s.stats import CONN_POOL_REQUESTS
    pool = _pool_for(mini)
    status, _, _ = pool.request("POST", "/a", b"one", {}, 5.0)
    assert status == 200
    # server will close the connection right after the NEXT response
    mini.close_after_next = True
    status, _, _ = pool.request("POST", "/b", b"two", {}, 5.0)
    assert status == 200
    # give the FIN time to arrive so the probe can see it
    time.sleep(0.1)
    before = CONN_POOL_REQUESTS.snapshot()
    status, _, _ = pool.request("POST", "/c", b"three", {}, 5.0)
    assert status == 200
    after = CONN_POOL_REQUESTS.snapshot()
    # the probe caught the dead socket BEFORE the POST left: replaced,
    # not errored, and the request was sent exactly once
    assert (after.get(("stale_replaced",), 0)
            == before.get(("stale_replaced",), 0) + 1)
    assert [b for _, b in mini.requests] == [b"one", b"two", b"three"]
    # and the second request RODE THE KEEP-ALIVE (the original bug
    # forced a fresh connection per POST)
    reused = after.get(("reused",), 0) - before.get(("reused",), 0)
    assert reused >= 0  # third was fresh post-replacement; second reused
    full = CONN_POOL_REQUESTS.snapshot()
    assert full.get(("reused",), 0) >= 1


def test_post_midflight_death_still_raises_not_replays(mini):
    """The original stale-socket replay bug: a POST on a connection that
    dies AFTER the request left must surface the error — a blind resend
    could double-bind. The probe narrows the window; it must not have
    changed this rule."""
    pool = _pool_for(mini)
    assert pool.request("POST", "/a", b"one", {}, 5.0)[0] == 200
    mini.die_mid_response = True
    posts_before = len(mini.requests)
    with pytest.raises(OSError):
        pool.request("POST", "/b", b"two", {}, 5.0)
    # sent once, never replayed
    assert len(mini.requests) == posts_before + 1


def test_get_midflight_death_is_replayed_once(mini):
    from tpushare.k8s.stats import CONN_POOL_REQUESTS
    pool = _pool_for(mini)
    assert pool.request("GET", "/a", None, {}, 5.0)[0] == 200
    mini.die_mid_response = True
    before = CONN_POOL_REQUESTS.snapshot()

    def heal():
        time.sleep(0.05)
        mini.die_mid_response = False
    threading.Thread(target=heal, daemon=True).start()
    # the reused-socket failure on a replay-safe verb retries once on a
    # fresh connection (mini may or may not have healed by then; either
    # a 200 or the second death's error is acceptable — what matters is
    # the replay was ATTEMPTED and counted)
    try:
        pool.request("GET", "/b", None, {}, 5.0)
    except OSError:
        pass
    after = CONN_POOL_REQUESTS.snapshot()
    assert (after.get(("replayed",), 0)
            == before.get(("replayed",), 0) + 1)
