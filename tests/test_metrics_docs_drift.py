"""Docs drift check (ISSUE 4 satellite): every metric registered
anywhere in the tree must appear in docs/observability.md's catalog,
and every catalog row must correspond to a live metric — so the catalog
can be trusted during an incident, and deleting a metric forces the
docs update in the same PR.
"""

import os
import re

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
PKG = os.path.join(ROOT, "tpushare")
DOC = os.path.join(ROOT, "docs", "observability.md")

# a metric is born at a constructor call whose first argument is its
# name: Counter("tpushare_x", ...), registry.counter("tpushare_x", ...),
# LabeledCounter / Histogram / labeled_counter / histogram / gauge_func
_DEF_RE = re.compile(
    r"(?:\b(?:Counter|LabeledCounter|Histogram)|"
    r"\.(?:counter|labeled_counter|histogram|gauge_func))\(\s*"
    r"\"(tpushare_[a-z0-9_]+)\"")
_CATALOG_RE = re.compile(r"`(tpushare_[a-z0-9_]+)`")
_MARK_START = "<!-- metric-catalog-start -->"
_MARK_END = "<!-- metric-catalog-end -->"


def registered_metric_names() -> set[str]:
    names: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                names.update(_DEF_RE.findall(f.read()))
    return names


def documented_metric_names() -> set[str]:
    with open(DOC) as f:
        doc = f.read()
    assert _MARK_START in doc and _MARK_END in doc, \
        "docs/observability.md lost its metric-catalog markers"
    catalog = doc.split(_MARK_START, 1)[1].split(_MARK_END, 1)[0]
    return set(_CATALOG_RE.findall(catalog))


def test_every_registered_metric_is_documented():
    code = registered_metric_names()
    docs = documented_metric_names()
    assert code, "the metric scan found nothing — the regex rotted"
    # test-local metric names (constructed inside tests/) never enter
    # this scan: it walks tpushare/ only
    missing = sorted(code - docs)
    assert not missing, (
        f"metrics registered in code but absent from the "
        f"docs/observability.md catalog: {missing} — add a catalog row "
        "(name, type, labels, meaning, alert)")


def test_every_documented_metric_exists():
    code = registered_metric_names()
    docs = documented_metric_names()
    stale = sorted(docs - code)
    assert not stale, (
        f"metrics in the docs/observability.md catalog that no code "
        f"registers any more: {stale} — delete the stale rows")


def test_catalog_is_nonempty_and_covers_the_core_surface():
    docs = documented_metric_names()
    assert len(docs) >= 40
    for core in ("tpushare_bind_seconds", "tpushare_traces_total",
                 "tpushare_build_info",
                 "tpushare_informer_staleness_seconds",
                 "tpushare_metric_series_clamped_total",
                 "tpushare_allocate_seconds"):
        assert core in docs
