"""Pipeline parallelism: GPipe schedule parity against the sequential model.

The pipelined stack must be numerically equivalent to model.forward — same
decoder_layer body, same order — with the schedule only changing *where*
each layer runs. Runs on the conftest 8-device CPU mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpushare.workloads.model import (
    PRESETS, forward_with_aux, init_params, loss_fn)
from tpushare.workloads.pipeline import (
    make_pipelined_train_step, pipelined_forward, pipelined_forward_with_aux)


def _mesh(n, axis="pp"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _tokens(cfg, batch, seq=12, seed=1):
    return jax.random.randint(jax.random.key(seed), (batch, seq),
                              0, cfg.vocab)


@pytest.mark.tpu_kernel
def test_dense_parity_two_stages():
    cfg = PRESETS["llama-tiny"]
    params = init_params(cfg, jax.random.key(0))
    tokens = _tokens(cfg, batch=4)
    mesh = _mesh(2)
    got = jax.jit(lambda p, t: pipelined_forward(p, t, cfg, mesh))(
        params, tokens)
    want, _ = forward_with_aux(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.tpu_kernel
def test_dense_parity_four_stages_more_microbatches():
    cfg = dataclasses.replace(PRESETS["llama-tiny"], n_layers=4)
    params = init_params(cfg, jax.random.key(2))
    tokens = _tokens(cfg, batch=8, seed=3)
    mesh = _mesh(4)
    got = jax.jit(lambda p, t: pipelined_forward(
        p, t, cfg, mesh, microbatches=8))(params, tokens)
    want, _ = forward_with_aux(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.tpu_kernel
def test_moe_parity_dropless():
    cfg = PRESETS["llama-moe-tiny"]
    # dropless per microbatch (capacity_factor >= E/top_k), so routing is
    # per-token and microbatching cannot change the logits
    assert cfg.moe_capacity_factor >= cfg.moe_experts / cfg.moe_top_k
    params = init_params(cfg, jax.random.key(0))
    tokens = _tokens(cfg, batch=4, seed=5)
    mesh = _mesh(2)
    got, aux = jax.jit(lambda p, t: pipelined_forward_with_aux(
        p, t, cfg, mesh))(params, tokens)
    want, _ = forward_with_aux(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    assert np.isfinite(float(aux)) and float(aux) > 0


@pytest.mark.tpu_kernel
def test_gradients_match_sequential():
    cfg = PRESETS["llama-tiny"]
    params = init_params(cfg, jax.random.key(0))
    tokens = _tokens(cfg, batch=4, seed=7)
    mesh = _mesh(2)

    def pipe_loss(p, t):
        logits, _ = pipelined_forward_with_aux(p, t[:, :-1], cfg, mesh)
        targets = t[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, targets[..., None],
                                             axis=-1))

    g_pipe = jax.jit(jax.grad(pipe_loss))(params, tokens)
    g_seq = jax.grad(lambda p: loss_fn(p, tokens, cfg))(params)
    flat_p, _ = jax.tree.flatten(
        jax.tree.map(lambda a: np.asarray(a, np.float32), g_pipe))
    flat_s, _ = jax.tree.flatten(
        jax.tree.map(lambda a: np.asarray(a, np.float32), g_seq))
    for gp, gs in zip(flat_p, flat_s):
        np.testing.assert_allclose(gp, gs, rtol=5e-2, atol=5e-3)


@pytest.mark.tpu_kernel
def test_pipelined_train_step_learns():
    cfg = PRESETS["llama-tiny"]
    params = init_params(cfg, jax.random.key(0))
    tokens = _tokens(cfg, batch=4, seed=9)
    mesh = _mesh(2)
    tx, step = make_pipelined_train_step(cfg, mesh, learning_rate=1e-2)
    opt = tx.init(params)
    step = jax.jit(step)
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_rejects_indivisible_layers_and_batch():
    cfg = PRESETS["llama-tiny"]  # 2 layers
    params = init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="layers"):
        pipelined_forward(params, _tokens(cfg, 4), cfg,
                          _mesh(3))  # 2 % 3
    with pytest.raises(ValueError, match="microbatches"):
        pipelined_forward(params, _tokens(cfg, 3), cfg,
                          _mesh(2))  # batch 3 % 2 microbatches
