"""Randomized gang-coordinator chaos: capacity can never leak.

The single-node chaos suite (test_chaos.py) hammers allocate/reclaim on
one NodeInfo; this drives the GANG layer the same way: random gangs
(sizes, topologies, sharing/exclusive) bind member-by-member in random
interleavings, with random mid-gang abandonment, plan-TTL expiry, pod
deletions, and coordinator restarts (plan recovery) — asserting after
every step that no chip is oversubscribed, and at the end that a full
teardown returns the slice to pristine (the no-leak property that
matters for a long-lived extender).
"""

import random

import pytest

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.gang import GangCoordinator, GangError
from tpushare.cache.nodeinfo import AllocationError
from tpushare.controller import Controller
from tpushare.k8s import FakeCluster
from tpushare.k8s.client import ApiError

HOSTS = ["c0", "c1", "c2", "c3"]
HBM = 16000


def make_cluster():
    fc = FakeCluster()
    for name, origin in zip(HOSTS, ("0x0", "0x2", "2x0", "2x2")):
        fc.add_tpu_node(name, chips=4, hbm_per_chip_mib=HBM, mesh="2x2",
                        slice_id="slc", slice_origin=origin)
    return fc


def assert_no_oversubscription(cache):
    for host in HOSTS:
        for v in cache.get_node_info(host).snapshot():
            assert v.used_hbm_mib <= v.total_hbm_mib, (host, v)


def total_used(cache):
    return sum(v.used_hbm_mib for host in HOSTS
               for v in cache.get_node_info(host).snapshot())


@pytest.mark.parametrize("seed", range(6))
def test_gang_chaos_no_capacity_leak(seed):
    rng = random.Random(seed)
    fc = make_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    gang = GangCoordinator(cache)
    clock = [1_000_000_000]

    def now():
        return clock[0]

    live: dict[str, dict] = {}   # gang id -> {size, members: {rank: pod}}
    gang_n = 0

    def spawn_gang():
        nonlocal gang_n
        gang_n += 1
        gid = f"cg{gang_n}"
        size, topo = rng.choice(((4, "2x2"), (8, "2x4"), (8, None),
                                 (16, "4x4"), (4, None)))
        hbm = rng.choice((0, 4000, 8000))
        live[gid] = {"size": size, "topo": topo, "hbm": hbm,
                     "members": {}, "bound": {}}
        return gid

    def member_pod(gid, rank):
        spec = live[gid]
        per_host = 4  # every shape here tiles as <=4 chips per host
        ann = {contract.ANN_GANG: gid,
               contract.ANN_GANG_SIZE: str(spec["size"]),
               contract.ANN_GANG_RANK: str(rank)}
        if spec["topo"]:
            ann[contract.ANN_TOPOLOGY] = spec["topo"]
        limits = {contract.RESOURCE_COUNT: str(per_host)}
        if spec["hbm"]:
            limits[contract.RESOURCE_HBM] = str(spec["hbm"])
        return fc.create_pod({
            "metadata": {"name": f"{gid}-m{rank}", "namespace": "chaos",
                         "annotations": ann},
            "spec": {"containers": [{"name": "c", "resources":
                     {"limits": limits}}]}})

    def try_bind_next(gid):
        spec = live[gid]
        n_members = spec["size"] // 4
        unbound = [r for r in range(n_members)
                   if r not in spec["bound"]]
        if not unbound:
            return
        rank = rng.choice(unbound)
        pod = spec["members"].get(rank)
        if pod is None:
            pod = member_pod(gid, rank)
            spec["members"][rank] = pod
        hosts, _reason = gang.filter_hosts(pod, now_ns=now)
        if not hosts:
            return
        try:
            placement = gang.bind_member(pod, hosts[0], fc, now_ns=now)
            spec["bound"][rank] = (hosts[0], placement.chip_ids)
        except (GangError, AllocationError, ApiError):
            pass  # refusals are fine; invariants checked below

    def delete_gang(gid):
        spec = live.pop(gid)
        for rank, pod in spec["members"].items():
            name = pod["metadata"]["name"]
            try:
                stored = fc.get_pod("chaos", name)
            except ApiError:
                continue
            fc.delete_pod("chaos", name)
            if rank in spec["bound"]:
                cache.remove_pod(stored)  # what the watch would do

    for _ in range(60):
        op = rng.random()
        if op < 0.35 or not live:
            gid = spawn_gang()
            try_bind_next(gid)
        elif op < 0.75:
            try_bind_next(rng.choice(list(live)))
        elif op < 0.85:
            # abandon a gang mid-bind (pods deleted; reservations must
            # drain via the plan TTL)
            delete_gang(rng.choice(list(live)))
        elif op < 0.95:
            # time passes; expiry sweeps
            clock[0] += rng.choice((1, GangCoordinator.PLAN_TTL_NS + 1))
            gang.gc(now_ns=now)
        else:
            # coordinator restart: all in-memory plans lost; recovery
            # must rebuild from stamped annotations
            gang = GangCoordinator(cache)
        assert_no_oversubscription(cache)

    # teardown: delete every pod, expire every plan — the slice must
    # return to pristine. THE invariant: nothing leaks, ever.
    for gid in list(live):
        delete_gang(gid)
    clock[0] += 10 * GangCoordinator.PLAN_TTL_NS + 1
    gang.gc(now_ns=now)
    assert_no_oversubscription(cache)
    assert total_used(cache) == 0, (
        f"seed {seed}: {total_used(cache)} MiB leaked after teardown")
    assert gang._plans == {}
