"""Randomized gang-coordinator chaos: capacity can never leak.

The single-node chaos suite (test_chaos.py) hammers allocate/reclaim on
one NodeInfo; this drives the GANG layer the same way: random gangs
(sizes, topologies, sharing/exclusive) bind member-by-member in random
interleavings, with random mid-gang abandonment, plan-TTL expiry, pod
deletions, and coordinator restarts (plan recovery) — asserting after
every step that no chip is oversubscribed, and at the end that a full
teardown returns the slice to pristine (the no-leak property that
matters for a long-lived extender).
"""

import random
import threading
import time

import pytest

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.gang import GangCoordinator, GangError
from tpushare.cache.nodeinfo import AllocationError
from tpushare.controller import Controller
from tpushare.k8s import FakeCluster
from tpushare.k8s.client import ApiError

HOSTS = ["c0", "c1", "c2", "c3"]
HBM = 16000


def make_cluster():
    fc = FakeCluster()
    for name, origin in zip(HOSTS, ("0x0", "0x2", "2x0", "2x2")):
        fc.add_tpu_node(name, chips=4, hbm_per_chip_mib=HBM, mesh="2x2",
                        slice_id="slc", slice_origin=origin)
    return fc


def assert_no_oversubscription(cache):
    for host in HOSTS:
        for v in cache.get_node_info(host).snapshot():
            assert v.used_hbm_mib <= v.total_hbm_mib, (host, v)


def total_used(cache):
    return sum(v.used_hbm_mib for host in HOSTS
               for v in cache.get_node_info(host).snapshot())


@pytest.mark.parametrize("seed", range(6))
def test_gang_chaos_no_capacity_leak(seed):
    rng = random.Random(seed)
    fc = make_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    gang = GangCoordinator(cache)
    clock = [1_000_000_000]

    def now():
        return clock[0]

    live: dict[str, dict] = {}   # gang id -> {size, members: {rank: pod}}
    gang_n = 0

    def spawn_gang():
        nonlocal gang_n
        gang_n += 1
        gid = f"cg{gang_n}"
        size, topo = rng.choice(((4, "2x2"), (8, "2x4"), (8, None),
                                 (16, "4x4"), (4, None)))
        hbm = rng.choice((0, 4000, 8000))
        live[gid] = {"size": size, "topo": topo, "hbm": hbm,
                     "members": {}, "bound": {}}
        return gid

    def member_pod(gid, rank):
        spec = live[gid]
        per_host = 4  # every shape here tiles as <=4 chips per host
        ann = {contract.ANN_GANG: gid,
               contract.ANN_GANG_SIZE: str(spec["size"]),
               contract.ANN_GANG_RANK: str(rank)}
        if spec["topo"]:
            ann[contract.ANN_TOPOLOGY] = spec["topo"]
        limits = {contract.RESOURCE_COUNT: str(per_host)}
        if spec["hbm"]:
            limits[contract.RESOURCE_HBM] = str(spec["hbm"])
        return fc.create_pod({
            "metadata": {"name": f"{gid}-m{rank}", "namespace": "chaos",
                         "annotations": ann},
            "spec": {"containers": [{"name": "c", "resources":
                     {"limits": limits}}]}})

    def try_bind_next(gid):
        spec = live[gid]
        n_members = spec["size"] // 4
        unbound = [r for r in range(n_members)
                   if r not in spec["bound"]]
        if not unbound:
            return
        rank = rng.choice(unbound)
        pod = spec["members"].get(rank)
        if pod is None:
            pod = member_pod(gid, rank)
            spec["members"][rank] = pod
        hosts, _reason = gang.filter_hosts(pod, now_ns=now)
        if not hosts:
            return
        try:
            placement = gang.bind_member(pod, hosts[0], fc, now_ns=now)
            spec["bound"][rank] = (hosts[0], placement.chip_ids)
        except (GangError, AllocationError, ApiError):
            pass  # refusals are fine; invariants checked below

    def delete_gang(gid):
        spec = live.pop(gid)
        for rank, pod in spec["members"].items():
            name = pod["metadata"]["name"]
            try:
                stored = fc.get_pod("chaos", name)
            except ApiError:
                continue
            fc.delete_pod("chaos", name)
            if rank in spec["bound"]:
                cache.remove_pod(stored)  # what the watch would do

    for _ in range(60):
        op = rng.random()
        if op < 0.35 or not live:
            gid = spawn_gang()
            try_bind_next(gid)
        elif op < 0.75:
            try_bind_next(rng.choice(list(live)))
        elif op < 0.85:
            # abandon a gang mid-bind (pods deleted; reservations must
            # drain via the plan TTL)
            delete_gang(rng.choice(list(live)))
        elif op < 0.95:
            # time passes; expiry sweeps
            clock[0] += rng.choice((1, GangCoordinator.PLAN_TTL_NS + 1))
            gang.gc(now_ns=now)
        else:
            # coordinator restart: all in-memory plans lost; recovery
            # must rebuild from stamped annotations
            gang = GangCoordinator(cache)
        assert_no_oversubscription(cache)

    # teardown: delete every pod, expire every plan — the slice must
    # return to pristine. THE invariant: nothing leaks, ever.
    for gid in list(live):
        delete_gang(gid)
    clock[0] += 10 * GangCoordinator.PLAN_TTL_NS + 1
    gang.gc(now_ns=now)
    assert_no_oversubscription(cache)
    assert total_used(cache) == 0, (
        f"seed {seed}: {total_used(cache)} MiB leaked after teardown")
    assert gang._plans == {}


# -- directed storms (VERDICT r4 item 5) --------------------------------------
#
# The randomized walk above finds leaks by luck; these four aim at the
# exact windows cache/gang.py:383-447 was hardened for: competing gangs
# racing one slice's capacity, member death racing the plan TTL, late
# binds racing the orphan reconcile, and (in test_ha_storm.py) two HA
# replicas interleaving filter/bind with a takeover mid-gang.


def _rig():
    fc = make_cluster()
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    return fc, cache, GangCoordinator(cache)


def _gang_pod(fc, gid, rank, size, topo=None):
    ann = {contract.ANN_GANG: gid,
           contract.ANN_GANG_SIZE: str(size),
           contract.ANN_GANG_RANK: str(rank)}
    if topo:
        ann[contract.ANN_TOPOLOGY] = topo
    return fc.create_pod({
        "metadata": {"name": f"{gid}-m{rank}", "namespace": "chaos",
                     "annotations": ann},
        "spec": {"containers": [{"name": "c", "resources": {"limits": {
            contract.RESOURCE_COUNT: "4"}}}]}})


def _bind_all(gang, fc, pods, results, tag, now=None):
    kw = {} if now is None else {"now_ns": now}
    for pod in pods:
        try:
            hosts, why = gang.filter_hosts(pod, **kw)
            if not hosts:
                results[tag].append(("refused", why))
                continue
            gang.bind_member(pod, hosts[0], fc, **kw)
            results[tag].append(("bound", hosts[0]))
        except (GangError, AllocationError, ApiError) as e:
            results[tag].append(("error", str(e)))


@pytest.mark.parametrize("seed", range(4))
def test_competing_gangs_capacity_for_one(seed):
    """Two 16-chip gangs race one 16-chip slice from two threads, every
    member interleaving with the rival's. Exactly one gang ends fully
    bound; the loser holds NOTHING once its (never-bindable) plan
    expires."""
    rng = random.Random(seed)
    fc, cache, gang = _rig()
    results = {"g1": [], "g2": []}
    pods = {}
    for gid in ("g1", "g2"):
        pods[gid] = [_gang_pod(fc, gid, r, 16, "4x4") for r in range(4)]
        rng.shuffle(pods[gid])
    barrier = threading.Barrier(2)

    def race(gid):
        barrier.wait()
        _bind_all(gang, fc, pods[gid], results, gid)

    ts = [threading.Thread(target=race, args=(gid,))
          for gid in ("g1", "g2")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert_no_oversubscription(cache)
    full = [gid for gid in ("g1", "g2")
            if sum(1 for s, _ in results[gid] if s == "bound") == 4]
    assert len(full) == 1, results
    # winner owns the whole slice; the loser's members were all refused
    assert total_used(cache) == 16 * HBM
    loser = "g2" if full == ["g1"] else "g1"
    assert not any(s == "bound" for s, _ in results[loser]), results
    # the loser's plan (if any) holds no reservations after expiry
    clock = [10 * GangCoordinator.PLAN_TTL_NS]
    gang.gc(now_ns=lambda: clock[0])
    assert total_used(cache) == 16 * HBM  # winner untouched


@pytest.mark.parametrize("order", ["gc_first", "bind_first", "threaded"])
def test_member_death_races_plan_ttl(order):
    """Rank 0 binds, its pod dies, the plan TTL expires — while rank 1
    is still trying to bind. Every interleaving must end with: no
    oversubscription, rank 1 either bound on the ORIGINAL geometry or
    cleanly refused, and a full teardown leaking nothing."""
    fc, cache, gang = _rig()
    clock = [1_000_000_000]

    def now():
        return clock[0]

    p0 = _gang_pod(fc, "dg", 0, 8, "2x4")
    p1 = _gang_pod(fc, "dg", 1, 8, "2x4")
    (h0,), _ = gang.filter_hosts(p0, now_ns=now)
    gang.bind_member(p0, h0, fc, now_ns=now)
    plan_hosts = [m[0] for m in gang._plans["dg"].members]

    # rank 0's pod dies (eviction/node failure): watch removes it
    stored = fc.get_pod("chaos", "dg-m0")
    fc.delete_pod("chaos", "dg-m0")
    cache.remove_pod(stored)
    # the plan TTL fires around rank 1's late bind
    clock[0] += GangCoordinator.PLAN_TTL_NS + 1

    results = {"bind": [], "gc": []}

    def late_bind():
        _bind_all(gang, fc, [p1], results, "bind", now=now)

    def sweep():
        results["gc"].append(gang.gc(now_ns=now))

    if order == "gc_first":
        sweep(); late_bind()
    elif order == "bind_first":
        late_bind(); sweep()
    else:
        b = threading.Barrier(2)

        def run(fn):
            b.wait()
            fn()

        ts = [threading.Thread(target=run, args=(f,))
              for f in (late_bind, sweep)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert_no_oversubscription(cache)
    outcome = results["bind"][0]
    if outcome[0] == "bound":
        # late member landed on the original geometry, never elsewhere
        assert outcome[1] in plan_hosts
        stored = fc.get_pod("chaos", "dg-m1")
        assert len(contract.chip_ids_from_annotations(stored)) == 4
        fc.delete_pod("chaos", "dg-m1")
        cache.remove_pod(stored)
    # teardown: everything drains
    clock[0] += 10 * GangCoordinator.PLAN_TTL_NS + 1
    gang.gc(now_ns=now)
    assert total_used(cache) == 0
    assert gang._plans == {}


@pytest.mark.parametrize("seed", range(4))
def test_late_bind_races_orphan_reconcile(seed):
    """Coordinator restart mid-gang: the new coordinator sees rank 1's
    gang-keyed reservation as an orphan (no in-memory plan) while the
    late member binds THROUGH recovery concurrently. The reconcile may
    release the share; the recovering bind must then re-reserve on
    demand — never double-count, never strand rank 1."""
    rng = random.Random(seed)
    fc, cache, gang = _rig()
    p0 = _gang_pod(fc, "og", 0, 8, "2x4")
    p1 = _gang_pod(fc, "og", 1, 8, "2x4")
    (h0,), _ = gang.filter_hosts(p0)
    gang.bind_member(p0, h0, fc)
    used_after_first = total_used(cache)

    # restart: in-memory plans lost; rank 1's reservation survives in
    # the cache and is now an orphan from the NEW coordinator's view
    gang2 = GangCoordinator(cache)
    results = {"bind": [], "gc": []}
    b = threading.Barrier(2)

    def late_bind():
        b.wait()
        if rng.random() < 0.5:
            time.sleep(rng.random() * 0.01)
        _bind_all(gang2, fc, [p1], results, "bind")

    def reconcile():
        b.wait()
        for _ in range(3):
            results["gc"].append(gang2.gc())

    ts = [threading.Thread(target=f) for f in (late_bind, reconcile)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert_no_oversubscription(cache)
    outcome = results["bind"][0]
    assert outcome[0] == "bound", outcome  # recovery must not strand
    stored = fc.get_pod("chaos", "og-m1")
    ids = contract.chip_ids_from_annotations(stored)
    assert ids is not None and len(ids) == 4
    # exactly the gang's 8 chips accounted, before and after: the first
    # bind had already reserved BOTH members' shares (all-or-nothing),
    # so the released orphan share was re-reserved by rank 1's bind —
    # never double-counted, never lost
    assert used_after_first == 8 * HBM
    assert total_used(cache) == 8 * HBM
