"""Fault-injection + concurrency storms over the scheduling stack.

The reference's failure paths (bind rollback, optimistic-lock retry,
watch-loop restart) exist but are never exercised by tests — and it has no
fault injection at all (SURVEY §5.2/§5.3). These tests drive tpushare's
equivalents through a ChaosCluster: flaky/slow/conflicting apiserver calls
and dropped watch streams, under concurrent bind storms, asserting the
cache invariants that matter:

- chips are never oversubscribed, even transiently;
- every successful bind is consistent between cache and apiserver;
- every failed bind leaves no residue (no reservation leak, annotations
  reverted);
- the controller converges after watch streams die.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import AllocationError, SchedulerCache
from tpushare.cache.nodeinfo import NodeInfo
from tpushare.controller import Controller
from tpushare.extender.handlers import BindHandler, FilterHandler
from tpushare.extender.metrics import Registry
from tpushare.k8s import ApiError, ChaosCluster, FakeCluster


def chaos_with_node(chips=4, hbm=16000, mesh=None, name="n1", seed=0):
    fc = FakeCluster()
    fc.add_tpu_node(name, chips=chips, hbm_per_chip_mib=hbm, mesh=mesh)
    return fc, ChaosCluster(fc, seed=seed)


# -- the harness itself -------------------------------------------------------

def test_fail_rule_fires_and_expires():
    fc, chaos = chaos_with_node()
    chaos.fail("get_pod", status=503, times=2)
    fc.create_pod(make_pod(hbm=100, name="p"))
    for _ in range(2):
        with pytest.raises(ApiError) as ei:
            chaos.get_pod("default", "p")
        assert ei.value.status == 503
    assert chaos.get_pod("default", "p")["metadata"]["name"] == "p"
    assert chaos.injected["get_pod"] == 2


def test_delay_rule_slows_calls():
    fc, chaos = chaos_with_node()
    fc.create_pod(make_pod(hbm=100, name="p"))
    chaos.delay("get_pod", seconds=0.05, times=1)
    t0 = time.perf_counter()
    chaos.get_pod("default", "p")
    assert time.perf_counter() - t0 >= 0.05
    chaos.get_pod("default", "p")
    # rule consumed exactly once (no wall-clock upper bound: that flakes
    # on loaded runners)
    assert chaos.injected["get_pod"] == 1


def test_drop_watch_closes_stream():
    fc, chaos = chaos_with_node()
    chaos.drop_watch("pods", after=1)
    stop = threading.Event()
    it = chaos.watch_pods(stop)

    def create_later():
        # the fake's watch subscribes when the generator first runs, so
        # pods must be created after the consumer starts iterating
        time.sleep(0.1)
        fc.create_pod(make_pod(hbm=100, name="p1"))
        fc.create_pod(make_pod(hbm=100, name="p2"))

    threading.Thread(target=create_later, daemon=True).start()
    assert next(it).object["metadata"]["name"] == "p1"
    with pytest.raises(ApiError, match="stream dropped"):
        next(it)
    stop.set()
    assert chaos.injected["watch_pods"] == 1


def test_non_callables_and_clean_methods_pass_through():
    fc, chaos = chaos_with_node()
    assert chaos.list_nodes() == fc.list_nodes()


def test_stacked_fail_rules_take_turns():
    fc, chaos = chaos_with_node()
    fc.create_pod(make_pod(hbm=100, name="p"))
    chaos.fail("get_pod", status=500, times=1)
    chaos.fail("get_pod", status=409, times=1)
    statuses = []
    for _ in range(2):
        with pytest.raises(ApiError) as ei:
            chaos.get_pod("default", "p")
        statuses.append(ei.value.status)
    assert statuses == [500, 409]  # one fail per call, in order
    assert chaos.injected["get_pod"] == 2
    chaos.get_pod("default", "p")  # both spent


def test_fail_on_watch_method_rejected_at_declaration():
    _, chaos = chaos_with_node()
    with pytest.raises(ValueError, match="drop_watch"):
        chaos.fail("watch_pods")
    with pytest.raises(ValueError, match="drop_watch"):
        chaos.delay("watch_nodes", seconds=0.1)


def test_drop_watch_fires_on_quiet_stream():
    """after=0 must hang up immediately even when no events ever arrive."""
    _, chaos = chaos_with_node()
    chaos.drop_watch("pods", after=0)
    stop = threading.Event()
    with pytest.raises(ApiError, match="stream dropped"):
        next(chaos.watch_pods(stop))
    stop.set()


# -- bind-path faults ---------------------------------------------------------

def test_bind_failure_storm_leaves_no_residue():
    """Persistent bind 500s: every attempt fails, and afterwards the cache
    and apiserver look exactly as if nothing happened."""
    fc, chaos = chaos_with_node()
    cache = SchedulerCache(chaos)
    info = cache.get_node_info("n1")
    chaos.fail("bind_pod", status=500, times=None)
    for i in range(6):
        pod = fc.create_pod(make_pod(hbm=2048, name=f"p{i}"))
        with pytest.raises(AllocationError):
            info.allocate(pod, chaos)
    assert chaos.injected["bind_pod"] == 6
    assert info.describe()["used_hbm_mib"] == 0
    for i in range(6):
        live = fc.get_pod("default", f"p{i}")
        assert not live["spec"].get("nodeName")
        assert contract.chip_ids_from_annotations(live) is None


def test_conflict_retry_with_flaky_refetch_rolls_back(monkeypatch):
    """409 on patch, then 500 on the recheck fetch: the allocation must
    fail cleanly and release its reservation; a later retry succeeds.
    Sequential-mode contract: with pipelined writes the binding POST has
    already landed when the patch conflicts, so the protocol goes
    FORWARD instead (see the companion test below)."""
    monkeypatch.setenv("TPUSHARE_NO_PIPELINED_BIND", "1")
    fc, chaos = chaos_with_node()
    info = SchedulerCache(chaos).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    chaos.fail("patch_pod", status=409, times=1)
    chaos.fail("get_pod", status=500, times=1)
    with pytest.raises(AllocationError):
        info.allocate(pod, chaos)
    assert info.describe()["used_hbm_mib"] == 0
    placement = info.allocate(pod, chaos)  # chaos spent: clean retry wins
    assert placement is not None
    assert fc.get_pod("default", "p")["spec"]["nodeName"] == "n1"


def test_conflict_with_pipelined_bind_repatches_forward():
    """Same 409-on-patch fault under the default pipelined protocol: the
    uid-guarded binding POST has landed, so the pod is OURS — the
    conflict resolves with a refetch-free re-patch, not a rollback."""
    from tpushare.cache.nodeinfo import BIND_PIPELINE
    fc, chaos = chaos_with_node()
    info = SchedulerCache(chaos).get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2048, name="p"))
    base = BIND_PIPELINE.snapshot().get(("conflict_repatch",), 0)
    chaos.fail("patch_pod", status=409, times=1)
    placement = info.allocate(pod, chaos)
    assert placement is not None
    live = fc.get_pod("default", "p")
    assert live["spec"]["nodeName"] == "n1"
    assert contract.chip_ids_from_annotations(live) == placement.chip_ids
    assert info.describe()["used_hbm_mib"] == 2048
    assert BIND_PIPELINE.snapshot().get(("conflict_repatch",), 0) \
        == base + 1


def test_slow_patch_does_not_serialize_or_double_book(monkeypatch):
    """Two concurrent allocations on one node while patch_pod is slow:
    reservations (not the node lock) must prevent double-booking, and the
    binds must overlap rather than serialize behind the apiserver.
    Sequential mode: a pipelined bind's POST would bump the rv under the
    delayed PATCH and force a re-patch, doubling every allocate's patch
    cost and drowning the serialization signal this test measures."""
    monkeypatch.setenv("TPUSHARE_NO_PIPELINED_BIND", "1")
    fc, chaos = chaos_with_node(chips=2, hbm=16000)
    info = SchedulerCache(chaos).get_node_info("n1")
    # delay is deliberately large so the serialized case (>= 2x delay) and
    # the overlapped case (~1x delay) are separated by far more than
    # scheduler/GIL noise on a loaded runner
    delay = 0.5
    chaos.delay("patch_pod", seconds=delay, times=None)
    # both pods want >half a chip: correctness requires distinct chips
    pods = [fc.create_pod(make_pod(hbm=9000, name=f"p{i}"))
            for i in range(2)]
    t0 = time.perf_counter()
    with ThreadPoolExecutor(2) as ex:
        placements = list(ex.map(lambda p: info.allocate(p, chaos), pods))
    elapsed = time.perf_counter() - t0
    ids0, ids1 = placements[0].chip_ids, placements[1].chip_ids
    assert set(ids0).isdisjoint(ids1), "double-booked a chip"
    # overlapping: well under 2x the injected latency (the reference's
    # whole-Allocate lock would force >= 2*delay)
    assert elapsed < 2 * delay, f"binds serialized: {elapsed:.3f}s"
    assert info.describe()["used_hbm_mib"] == 18000


def test_concurrent_bind_storm_under_random_faults():
    """The big one: 24 pods through the real BindHandler from 8 threads
    against an apiserver that randomly 500s/409s/hangs up, with a sampler
    thread asserting no transient oversubscription. Everything must
    eventually bind (capacity suffices) and cache == apiserver."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.add_tpu_node("n2", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    chaos = ChaosCluster(fc, seed=1234)
    chaos.fail("patch_pod", status=500, probability=0.15, times=None)
    chaos.fail("patch_pod", status=409, probability=0.10, times=None)
    chaos.fail("bind_pod", status=500, probability=0.15, times=None)
    cache = SchedulerCache(chaos)
    registry = Registry()
    fil = FilterHandler(cache, registry)
    binder = BindHandler(cache, chaos, registry)

    n_pods, hbm = 24, 4000
    pods = [fc.create_pod(make_pod(hbm=hbm, name=f"p{i}"))
            for i in range(n_pods)]

    overcommit = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            for node in cache.describe()["nodes"]:
                for chip in node["chips"]:
                    if chip["used_hbm_mib"] > chip["total_hbm_mib"]:
                        overcommit.append(dict(chip))
            time.sleep(0.002)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()

    def schedule(pod):
        """Filter -> bind with retry, as the default scheduler would."""
        ns = pod["metadata"]["namespace"]
        name = pod["metadata"]["name"]
        for attempt in range(80):
            res = fil.handle({"Pod": pod, "NodeNames": ["n1", "n2"]})
            nodes = res["NodeNames"]
            if not nodes:
                time.sleep(0.005)
                continue
            out = binder.handle({
                "PodNamespace": ns, "PodName": name,
                "PodUID": pod["metadata"]["uid"],
                "Node": nodes[attempt % len(nodes)],
            })
            if out["Error"] == "":
                return True
            time.sleep(0.002)
        return False

    from tpushare.cache.nodeinfo import BIND_PIPELINE
    pipeline_before = BIND_PIPELINE.snapshot()
    with ThreadPoolExecutor(8) as ex:
        results = list(ex.map(schedule, pods))
    stop.set()
    sampler_t.join(timeout=2)

    assert all(results), f"{results.count(False)} pods never bound"
    assert not overcommit, f"transient oversubscription: {overcommit[:3]}"
    # the storm actually stormed
    assert chaos.injected["patch_pod"] + chaos.injected["bind_pod"] > 0

    # a pipelined bind whose PATCH leg lost to a fault repairs its
    # annotations asynchronously: heal the apiserver and wait for every
    # repair to resolve before auditing truth
    chaos.clear()

    def repairs_resolved() -> bool:
        now = BIND_PIPELINE.snapshot()

        def moved(k):
            return now.get((k,), 0) - pipeline_before.get((k,), 0)
        return moved("bind_first_repair") == (
            moved("repair_ok") + moved("repair_moot")
            + moved("repair_orphaned"))
    window_end = time.monotonic() + 8.0
    while time.monotonic() < window_end and not repairs_resolved():
        time.sleep(0.02)
    assert repairs_resolved(), \
        f"async annotation repairs unresolved: {BIND_PIPELINE.snapshot()}"
    # apiserver truth == cache accounting
    per_chip: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        node = pod["spec"].get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        assert node and ids, f"bound pod missing placement: {pod['metadata']}"
        for cid in ids:
            per_chip[(node, cid)] = per_chip.get((node, cid), 0) + hbm
    for (node, cid), used in per_chip.items():
        assert used <= 16000
    d = cache.describe()
    assert d["used_hbm_mib"] == n_pods * hbm
    for node in d["nodes"]:
        for chip in node["chips"]:
            assert chip["used_hbm_mib"] == per_chip.get(
                (node["name"], chip["idx"]), 0)


def test_concurrent_duplicate_bind_same_pod_single_winner():
    """Two threads bind the SAME pod concurrently while patch_pod is slow
    (widening the unlocked apiserver window): exactly one attempt wins,
    the loser is refused by the in-flight guard, and the loser's rollback
    must not erase the winner's reservation or annotations."""
    fc, chaos = chaos_with_node(chips=4, hbm=16000)
    info = SchedulerCache(chaos).get_node_info("n1")
    chaos.delay("patch_pod", seconds=0.3, times=None)
    pod = fc.create_pod(make_pod(hbm=2048, name="dup"))

    outcomes = []

    def attempt():
        try:
            outcomes.append(("ok", info.allocate(pod, chaos)))
        except AllocationError as e:
            outcomes.append(("err", str(e)))

    with ThreadPoolExecutor(2) as ex:
        list(ex.map(lambda f: f(), [attempt, attempt]))

    wins = [o for o in outcomes if o[0] == "ok"]
    errs = [o for o in outcomes if o[0] == "err"]
    assert len(wins) == 1 and len(errs) == 1, outcomes
    # winner's state intact: bound, annotated, exactly one pod's HBM used
    live = fc.get_pod("default", "dup")
    assert live["spec"]["nodeName"] == "n1"
    assert contract.chip_ids_from_annotations(live) == wins[0][1].chip_ids
    assert info.describe()["used_hbm_mib"] == 2048


# -- controller resilience ----------------------------------------------------

def test_controller_survives_watch_drops_and_converges():
    """Pod watch streams keep dying; completion events land anyway (via
    reconnect or resync) and the cache frees the chips."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16000)
    chaos = ChaosCluster(fc, seed=7)
    chaos.drop_watch("pods", after=1, times=5)
    cache = SchedulerCache(chaos)
    ctl = Controller(chaos, cache, resync_seconds=0.2)
    ctl.build_cache()
    ctl.start()
    try:
        info = cache.get_node_info("n1")
        pods = [fc.create_pod(make_pod(hbm=3000, name=f"p{i}"))
                for i in range(4)]
        for p in pods:
            info.allocate(p, chaos)
        assert info.describe()["used_hbm_mib"] == 12000
        for p in pods:
            fc.set_pod_phase("default", p["metadata"]["name"], "Succeeded")
        deadline = time.time() + 10
        while time.time() < deadline:
            if cache.describe()["used_hbm_mib"] == 0:
                break
            time.sleep(0.05)
        assert cache.describe()["used_hbm_mib"] == 0
        assert chaos.injected["watch_pods"] >= 1
    finally:
        ctl.stop()


# -- HA claim CAS under apiserver faults --------------------------------------

def test_ha_claims_storm_under_node_patch_chaos():
    """The per-node claim CAS (get_node + patch_node per bind) under
    intermittent apiserver failures: binds may fail, but reservations
    always roll back (no capacity leak), claims never strand a node
    unschedulable, and nothing oversubscribes."""
    fc = FakeCluster()
    fc.add_tpu_node("c1", chips=2, hbm_per_chip_mib=8192, mesh="2x1")
    chaos = ChaosCluster(fc, seed=11)
    cache = SchedulerCache(chaos)
    cache.build_cache()
    info = cache.get_node_info("c1")

    # intermittent 500s and 409s on the claim path + the pod writes
    chaos.fail("patch_node", status=500, times=None, probability=0.25)
    chaos.fail("get_node", status=503, times=None, probability=0.1)
    chaos.fail("patch_pod", status=500, times=None, probability=0.15)

    bound = 0
    for i in range(30):
        pod = fc.create_pod(make_pod(hbm=2048, name=f"cc-{i}"))
        try:
            info.allocate(pod, chaos, ha_claims=True)
            bound += 1
        except AllocationError:
            fc.delete_pod("default", f"cc-{i}")
    assert chaos.injected, "chaos injected nothing"
    assert bound > 0, "no bind survived the fault rates"

    # invariants: apiserver usage == cache usage == sum of bound pods
    used = 0
    for pod in fc.list_pods():
        ids = contract.chip_ids_from_annotations(pod)
        if ids is not None:
            assert pod["spec"].get("nodeName") == "c1"
            used += contract.hbm_from_annotations(pod) * len(ids)
    assert used == bound * 2048
    assert used <= 2 * 8192
    tree = cache.describe()
    assert tree["used_hbm_mib"] == used, "reservation leak after faults"

    # the node must still be schedulable once faults stop: claims from
    # failed attempts were dropped or will expire; free space is real.
    # Failed binds whose _drop_claim itself hit an injected fault leave
    # stale claims that are legitimately charged until CLAIM_TTL — so run
    # the post-storm allocate with a clock advanced past the TTL, which is
    # the real-world "once faults stop" condition (claims expire, capacity
    # returns). Without this the test is seed-fragile: ~half of seeds
    # leave a stale claim and the allocate throws ClaimConflictError even
    # though no capacity actually leaked.
    chaos.clear()
    free = 2 * 8192 - used
    if free >= 2048:
        pod = fc.create_pod(make_pod(hbm=2048, name="cc-after"))
        after_ttl = time.time_ns() + NodeInfo.CLAIM_TTL_NS + 1_000_000_000
        info.allocate(pod, chaos, now_ns=lambda: after_ttl, ha_claims=True)


# -- preempt verb under apiserver faults --------------------------------------

def test_preempt_node_lookup_fault_counts_error_not_dropped():
    """An apiserver fault during the preempt verb's node lookup must be
    reported as a node ERROR (apiserver blip), never as a hopeless-node
    drop (capacity verdict) — operators alert on the latter."""
    from tpushare.extender.handlers import PreemptHandler
    from tpushare.extender.metrics import Registry

    fc, chaos = chaos_with_node(chips=2, hbm=8192, name="c1")
    cache = SchedulerCache(chaos)
    cache.build_cache()
    info = cache.get_node_info("c1")
    victim = fc.create_pod(make_pod(hbm=6144, name="v1"))
    info.allocate(victim, chaos)
    cache.add_or_update_pod(fc.get_pod("default", "v1"))

    # un-warmed second node so the handler's get_node_info must hit the
    # (faulted) apiserver
    fc.add_tpu_node("c2", chips=2, hbm_per_chip_mib=8192)
    chaos.fail("get_node", status=503, times=None, probability=1.0)

    reg = Registry()
    h = PreemptHandler(cache, reg)
    out = h.handle({
        "Pod": make_pod(hbm=4096, name="high"),
        "NodeNameToMetaVictims": {
            "c1": {"Pods": [{"UID": victim["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
            "c2": {"Pods": [{"UID": victim["metadata"]["uid"]}],
                   "NumPDBViolations": 0},
        },
    })
    # c1 was already cached -> still refined despite the fault; c2's
    # lookup failed -> skipped as an error, not a drop
    assert "c1" in out["NodeNameToMetaVictims"]
    assert "c2" not in out["NodeNameToMetaVictims"]
    exposed = reg.expose()
    assert "tpushare_preempt_node_errors_total 1" in exposed
    assert "tpushare_preempt_nodes_dropped_total 0" in exposed
