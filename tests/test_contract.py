"""Golden tests for the resource/annotation contract (tpushare/contract)."""

import json
import uuid

import pytest

from tpushare import contract as c
from tpushare.contract import pod as podlib
from tpushare.contract import node as nodelib


def make_pod(hbm=0, count=0, ann=None, phase="Pending", node="",
             name="p1", namespace="default", uid=None, containers=1,
             deletion=False):
    if uid is None:
        uid = f"uid-{uuid.uuid4()}"  # k8s UIDs are always unique
    limits = {}
    if hbm:
        limits[c.RESOURCE_HBM] = str(hbm)
    if count:
        limits[c.RESOURCE_COUNT] = str(count)
    pod = {
        "metadata": {
            "name": name, "namespace": namespace, "uid": uid,
            "annotations": dict(ann or {}),
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {"name": f"c{i}", "resources": {"limits": dict(limits)}}
                for i in range(containers)
            ],
        },
        "status": {"phase": phase},
    }
    if deletion:
        pod["metadata"]["deletionTimestamp"] = "2026-07-29T00:00:00Z"
    return pod


def make_node(name="n1", hbm=0, count=0, mesh=None):
    node = {
        "metadata": {"name": name, "labels": {}},
        "status": {"allocatable": {}},
    }
    if hbm:
        node["status"]["allocatable"][c.RESOURCE_HBM] = str(hbm)
    if count:
        node["status"]["allocatable"][c.RESOURCE_COUNT] = str(count)
    if mesh:
        node["metadata"]["labels"][c.LABEL_MESH] = mesh
    return node


# -- resource requests -------------------------------------------------------

def test_hbm_request_sums_containers():
    # reference sums gpu-mem limits across containers (pod.go:154-163)
    pod = make_pod(hbm=2048, containers=2)
    assert c.pod_hbm_request(pod) == 4096


def test_chip_count_takes_max():
    # reference takes the max gpu-count across containers (pod.go:167-176)
    pod = make_pod(hbm=1024, count=4, containers=3)
    assert c.pod_chip_count_request(pod) == 4


def test_requests_absent_are_zero():
    pod = make_pod()
    assert c.pod_hbm_request(pod) == 0
    assert c.pod_chip_count_request(pod) == 0
    assert not c.is_tpushare_pod(pod)


def test_garbage_limit_values_read_as_zero():
    pod = make_pod(hbm=1024)
    pod["spec"]["containers"][0]["resources"]["limits"][c.RESOURCE_HBM] = "2Gi"
    assert c.pod_hbm_request(pod) == 0  # MiB integers only, by contract


def test_is_tpushare_pod():
    assert c.is_tpushare_pod(make_pod(hbm=512))
    assert c.is_tpushare_pod(make_pod(count=2))


# -- lifecycle ----------------------------------------------------------------

def test_complete_pod_phases():
    assert c.is_complete_pod(make_pod(phase="Succeeded"))
    assert c.is_complete_pod(make_pod(phase="Failed"))
    assert not c.is_complete_pod(make_pod(phase="Running"))
    assert c.is_complete_pod(make_pod(phase="Running", deletion=True))


def test_assigned_non_terminated():
    assert c.is_assigned_non_terminated(make_pod(phase="Running", node="n1"))
    assert not c.is_assigned_non_terminated(make_pod(phase="Running"))
    assert not c.is_assigned_non_terminated(
        make_pod(phase="Succeeded", node="n1"))


# -- annotation codec ---------------------------------------------------------

def test_placement_annotations_golden():
    ann = c.placement_annotations(
        chip_ids=[5, 0], hbm_mib=2048, chip_total_mib=16276,
        box=(2, 1), now_ns=123456789)
    assert ann == {
        "tpushare.aliyun.com/chip-ids": "[0, 5]",
        "tpushare.aliyun.com/hbm-pod": "2048",
        "tpushare.aliyun.com/hbm-chip": "16276",
        "tpushare.aliyun.com/assigned": "false",
        "tpushare.aliyun.com/assume-time": "123456789",
        "tpushare.aliyun.com/topology": "2x1",
    }
    patch = c.placement_patch(ann)
    assert patch == {"metadata": {"annotations": ann}}
    # round-trip through a pod
    pod = make_pod(hbm=2048, ann=ann)
    assert c.chip_ids_from_annotations(pod) == (0, 5)
    assert c.hbm_from_annotations(pod) == 2048
    assert c.assume_time_from_annotations(pod) == 123456789
    assert not c.is_assigned(pod)


def test_assigned_patch():
    assert c.assigned_patch() == {
        "metadata": {"annotations": {"tpushare.aliyun.com/assigned": "true"}}}


@pytest.mark.parametrize("raw", [
    "not json", "{}", "[1, -2]", '["a"]', "[true]", "[]", "3",
])
def test_malformed_chip_ids_decode_to_none(raw):
    pod = make_pod(ann={c.ANN_CHIP_IDS: raw})
    assert c.chip_ids_from_annotations(pod) is None


def test_malformed_numeric_annotations():
    pod = make_pod(ann={c.ANN_HBM_POD: "lots", c.ANN_ASSUME_TIME: "noon"})
    assert c.hbm_from_annotations(pod) == 0
    assert c.assume_time_from_annotations(pod) == 0


def test_topology_request_annotation():
    assert c.pod_topology_request(make_pod(ann={c.ANN_TOPOLOGY: "2x2"})) == (2, 2)
    assert c.pod_topology_request(make_pod(ann={c.ANN_TOPOLOGY: "junk"})) is None
    assert c.pod_topology_request(make_pod(ann={c.ANN_TOPOLOGY: "0x2"})) is None
    assert c.pod_topology_request(make_pod()) is None


def test_pod_key_and_identity():
    pod = make_pod(name="svc-1", namespace="prod", uid="u-9")
    assert podlib.pod_key(pod) == "prod/svc-1"
    assert podlib.pod_uid(pod) == "u-9"


# -- node accessors -----------------------------------------------------------

def test_node_capacity_and_sharing():
    node = make_node(hbm=65104, count=4)
    assert c.node_hbm_capacity(node) == 65104
    assert c.node_chip_count(node) == 4
    assert c.is_tpushare_node(node)
    assert not c.is_tpushare_node(make_node())


def test_node_mesh_label():
    node = make_node(hbm=65104, count=4, mesh="2x2")
    topo = c.node_mesh_topology(node)
    assert topo is not None and topo.shape == (2, 2)
    # stale label (claims 16 chips, node has 4) is ignored
    stale = make_node(hbm=65104, count=4, mesh="4x4")
    assert c.node_mesh_topology(stale) is None
    # malformed label behaves like no label
    bad = make_node(hbm=65104, count=4, mesh="2by2")
    assert c.node_mesh_topology(bad) is None
    assert c.node_mesh_topology(make_node(hbm=1)) is None


def test_node_capacity_fallback_when_no_allocatable():
    node = {"metadata": {"name": "n"},
            "status": {"capacity": {c.RESOURCE_HBM: "100"}}}
    assert c.node_hbm_capacity(node) == 100
    assert nodelib.node_name(node) == "n"
