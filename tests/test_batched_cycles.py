"""Batched decision cycles (ABI v4): property + protocol tests.

The multi-pod solve is only shippable if three things are falsifiable:

- **disjointness**: the k placements of one batch solve never share a
  chip on any node, across randomized fleets, meshes, occupancy and
  request shapes — and the native solve agrees with the Python
  fallback spec bit-for-bit;
- **stamp revalidation**: a node mutation between the solve and the
  bind demotes EXACTLY the affected member to the single-pod path
  (counted as ``revalidation_demoted``), while the untouched members'
  speculative placements survive;
- **apiserver truth**: a concurrent storm with batching enabled ends
  with zero oversubscription on the fake apiserver's annotations (the
  same audit the chaos soak applies), because speculative placements
  are only ever trusted after in-lock revalidation.

Plus the observability contract: a pod served from a batch solve is
visible as such in /inspect/explain (leader trace id, batch size,
``source: batched``) and is never presented as individually computed.
"""

import random
import threading

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.batch import (
    BATCH_SOLVES, BATCH_WINDOW_PODS, BatchPlanner)
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.core.chips import ChipView
from tpushare.core.native import engine as native_engine
from tpushare.core.placement import PlacementRequest
from tpushare.core.topology import MeshTopology
from tpushare.extender.handlers import (
    BindHandler, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.k8s import FakeCluster
from tpushare.obs import ExplainStore

HBM = 16384


def _random_fleet(rng, n_nodes):
    meshes = [(4,), (8,), (2, 2), (2, 4), (4, 4), (2, 2, 2)]
    nodes = []
    for _ in range(n_nodes):
        shape = rng.choice(meshes)
        topo = MeshTopology(shape)
        n = topo.num_chips
        nodes.append((
            [ChipView(idx=j, coords=topo.coords(j), total_hbm_mib=HBM,
                      used_hbm_mib=rng.choice(
                          [0, 0, 2048, 4096, 8192, HBM]),
                      healthy=rng.random() > 0.05)
             for j in range(n)], topo))
    return nodes


def _assert_disjoint(placed):
    seen: set[tuple[int, int]] = set()
    for node_pos, p in placed:
        for cid in p.chip_ids:
            assert (node_pos, cid) not in seen, (
                f"members share chip {cid} on node {node_pos}")
            seen.add((node_pos, cid))


def test_batch_solve_pairwise_disjoint_randomized(native_engine):
    """k placements from one solve are pairwise chip-disjoint on every
    node, for random fleets/meshes/occupancy and several request
    shapes — and the native solve equals the Python spec."""
    rng = random.Random(20260804)
    shapes = [
        PlacementRequest(hbm_mib=2048),
        PlacementRequest(hbm_mib=4096, chip_count=2),
        PlacementRequest(hbm_mib=1024, chip_count=4),
        PlacementRequest(hbm_mib=0, chip_count=1),  # exclusive
        PlacementRequest(hbm_mib=2048, chip_count=3,
                         allow_scatter=True),
    ]
    for trial in range(8):
        nodes = _random_fleet(rng, rng.randrange(3, 12))
        req = shapes[trial % len(shapes)]
        k = rng.randrange(2, 9)
        placed = native_engine.solve_batch(nodes, req, k)
        assert len(placed) <= k
        _assert_disjoint(placed)
        spec = native_engine._py_solve_batch(nodes, req, k)
        assert [(n, p.chip_ids, p.box, p.origin, p.score)
                for n, p in placed] == \
            [(n, p.chip_ids, p.box, p.origin, p.score)
             for n, p in spec], f"native/python divergence (trial {trial})"
        # every placement must be real: chips eligible on that node
        for node_pos, p in placed:
            chips, _topo = nodes[node_pos]
            by_idx = {c.idx: c for c in chips}
            for cid in p.chip_ids:
                c = by_idx[cid]
                assert c.healthy
                if req.hbm_mib == 0:
                    assert c.used_hbm_mib == 0
                else:
                    assert c.free_hbm_mib >= req.hbm_mib


def test_cache_solve_batch_disjoint_and_stamped():
    fc = FakeCluster()
    names = [f"b{i}" for i in range(6)]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    req = PlacementRequest(hbm_mib=2048)
    placed = cache.solve_batch(req, names, 8)
    assert len(placed) == 8
    seen = set()
    for node, p, stamp in placed:
        assert stamp == cache.get_node_info(node).version, \
            "stamp must be the generation the solve read"
        for cid in p.chip_ids:
            assert (node, cid) not in seen
            seen.add((node, cid))
    # untouched-node preference: 8 members over 6 nodes touches every
    # node before any node hosts a second (disjoint) member; the two
    # overflow members tie-break to the lowest node index
    per_node = {}
    for node, _p, _s in placed:
        per_node[node] = per_node.get(node, 0) + 1
    assert len(per_node) == 6
    assert sum(per_node.values()) == 8


def test_stamp_mutation_demotes_exactly_the_affected_member():
    """The revalidation protocol: after a batch solve, mutating node A
    demotes A's member at its seed lookup (counted revalidation_demoted)
    while B's member still rides its speculative placement."""
    fc = FakeCluster()
    for n in ("da", "db"):
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod_a, pod_b = make_pod(hbm=2048, name="ma"), \
        make_pod(hbm=2048, name="mb")
    req = request_from_pod(pod_a)
    placed = cache.solve_batch(req, ["da", "db"], 2)
    assert [n for n, _p, _s in placed] == ["da", "db"]
    for pod, (node, placement, stamp) in zip((pod_a, pod_b), placed):
        cache.stash_speculative(pod, req, node, placement, stamp)

    # concurrent mutation on da between the solve and member A's bind
    intruder = make_pod(hbm=1024, name="intruder")
    fc.create_pod(intruder)
    cache.get_node_info("da").allocate(intruder, fc)

    demoted0 = BATCH_SOLVES.get("revalidation_demoted")
    hint_a, stamp_a, spec_a = cache.placement_hint_stamped(pod_a, "da")
    assert hint_a is None, "mutated member must demote"
    assert BATCH_SOLVES.get("revalidation_demoted") == demoted0 + 1
    hint_b, stamp_b, spec_b = cache.placement_hint_stamped(pod_b, "db")
    assert hint_b is not None and spec_b is True, \
        "untouched member keeps its speculative placement"
    assert BATCH_SOLVES.get("revalidation_demoted") == demoted0 + 1, \
        "only the affected member may be demoted"


def test_allocate_in_lock_stamp_recheck_demotes():
    """The race window between placement_hint_stamped and the node lock
    is closed INSIDE allocate: a stale stamp passed in makes allocate
    re-search instead of trusting the speculative chips, and the bind
    still succeeds."""
    fc = FakeCluster()
    fc.add_tpu_node("ra", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2048, name="racy")
    req = request_from_pod(pod)
    (node, placement, stamp), = cache.solve_batch(req, ["ra"], 1)
    # mutate after the solve: the captured stamp is now stale
    intruder = make_pod(hbm=4096, name="squatter")
    fc.create_pod(intruder)
    cache.get_node_info("ra").allocate(intruder, fc)
    created = fc.create_pod(pod)
    demoted0 = BATCH_SOLVES.get("revalidation_demoted")
    out = cache.get_node_info("ra").allocate(
        created, fc, hint=placement, hint_stamp=stamp,
        hint_speculative=True)
    assert out is not None
    assert BATCH_SOLVES.get("revalidation_demoted") == demoted0 + 1


def _storm_rig(n_nodes, window_s, max_batch):
    fc = FakeCluster()
    names = [f"s{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    explain = ExplainStore()
    batcher = BatchPlanner(cache, window_s=window_s, max_batch=max_batch)
    flt = FilterHandler(cache, registry, explain=explain,
                        batcher=batcher)
    prio = PrioritizeHandler(cache, registry, explain=explain)
    bind = BindHandler(cache, fc, registry, explain=explain)
    return fc, names, cache, flt, prio, bind, explain


def _apiserver_truth_usage(fc):
    usage: dict[tuple[str, int], int] = {}
    for pod in fc.list_pods():
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        hbm = contract.hbm_from_annotations(pod)
        for cid in ids:
            usage[(node, cid)] = usage.get((node, cid), 0) + hbm
    return usage


def test_batched_storm_zero_oversubscription_on_apiserver_truth():
    """The chaos-soak audit with batching enabled: concurrent identical
    pods through the full webhook cycle, bound pods LEFT IN PLACE, and
    the fake apiserver's chip accounting must never exceed capacity.
    Speculation is only safe if revalidation holds under real races."""
    fc, names, cache, flt, prio, bind, _explain = _storm_rig(
        n_nodes=6, window_s=0.004, max_batch=8)
    errors: list[str] = []
    bound = []
    lock = threading.Lock()

    def worker(w):
        for i in range(6):
            pod = fc.create_pod(make_pod(
                hbm=2048, name=f"st-{w}-{i}", uid=f"uid-st-{w}-{i}"))
            ok = flt.handle({"Pod": pod, "NodeNames": names})
            if not ok["NodeNames"]:
                continue
            ranked = prio.handle({"Pod": pod,
                                  "NodeNames": ok["NodeNames"]})
            top = max(r["Score"] for r in ranked)
            node = next(r["Host"] for r in ranked if r["Score"] == top)
            out = bind.handle({
                "PodName": pod["metadata"]["name"],
                "PodNamespace": pod["metadata"]["namespace"],
                "PodUID": pod["metadata"]["uid"], "Node": node})
            if not out.get("Error"):
                with lock:
                    bound.append(pod["metadata"]["name"])

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "storm deadlocked"
    assert bound, "storm bound nothing"
    over = {k: v for k, v in _apiserver_truth_usage(fc).items()
            if v > HBM}
    assert not over, f"oversubscribed chips on apiserver truth: {over}"
    assert not errors


def test_explain_never_shows_batched_pod_as_computed():
    fc, names, cache, flt, prio, bind, explain = _storm_rig(
        n_nodes=4, window_s=0.01, max_batch=4)
    pods = [fc.create_pod(make_pod(hbm=2048, name=f"e{i}",
                                   uid=f"uid-e{i}"))
            for i in range(4)]
    results = [None] * 4

    def run(i):
        results[i] = flt.handle({"Pod": pods[i], "NodeNames": names})

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    batched = [i for i in range(4)
               if results[i] and len(results[i]["NodeNames"]) == 1]
    assert batched, "window did not coalesce (timing?)"
    leader_ids = set()
    for i in batched:
        rec = explain.get(f"default/e{i}")
        assert rec is not None
        cycle = rec["cycles"][-1]
        assert cycle.get("batch"), "batch membership missing"
        assert cycle["batch"]["size"] >= 2
        leader_ids.add(cycle["batch"]["leader_trace_id"])
        for verdict in cycle["filter"]["nodes"].values():
            assert verdict.get("source") == "batched"
            assert verdict.get("source") != "computed"
    assert len(leader_ids) == 1, \
        "members of one solve must share the leader trace id"


def test_speculative_scores_exempt_from_stale_serve_oracle(monkeypatch):
    """A same-node sibling's speculative score embeds the batch's
    disjointness (earlier members' chips left the pool), so a fresh
    recompute legitimately differs — the memo-verify oracle must not
    count that as a stale serve (its safety comes from stamp
    revalidation at bind, not score purity)."""
    from tpushare.cache import MEMO_STALE_SERVES

    monkeypatch.setenv("TPUSHARE_MEMO_VERIFY", "1")
    fc = FakeCluster()
    fc.add_tpu_node("vx", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    # asymmetric chips: a fresh single-pod select always picks the
    # tightest chip 0, so a sibling's different-chip score genuinely
    # disagrees with a recompute (not a vacuous all-equal case)
    squat = make_pod(hbm=4096, name="vsquat", uid="uid-vsquat",
                     node="vx",
                     ann=dict(contract.placement_annotations(
                         [0], 4096, HBM)))
    fc.create_pod(squat)
    cache.add_or_update_pod(squat)
    pods = [make_pod(hbm=2048, name=f"v{i}", uid=f"uid-v{i}")
            for i in range(3)]
    req = request_from_pod(pods[0])
    placed = cache.solve_batch(req, ["vx"], 3)
    assert len(placed) == 3  # all on one node, disjoint chips
    scores_seen = {p.score for _n, p, _s in placed}
    assert len(scores_seen) > 1, \
        "setup must produce genuinely divergent sibling scores"
    for pod, (node, placement, stamp) in zip(pods, placed):
        cache.stash_speculative(pod, req, node, placement, stamp)
    # members 2 and 3 carry scores a fresh single-pod select would not
    # produce; serving them under the verify oracle must not trip it
    stale0 = MEMO_STALE_SERVES.value
    for pod in pods:
        scores, errors = cache.score_nodes(pod, req, ["vx"])
        assert scores["vx"] is not None and not errors
    assert MEMO_STALE_SERVES.value == stale0


def test_lone_window_runs_solo_and_disabled_planner_is_free():
    fc = FakeCluster()
    fc.add_tpu_node("solo", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    pod = make_pod(hbm=2048, name="alone")
    req = request_from_pod(pod)
    solo0 = BATCH_SOLVES.get("solo")
    planner = BatchPlanner(cache, window_s=0.002, max_batch=8)
    assert planner.submit(pod, req, ["solo"]) is None
    assert BATCH_SOLVES.get("solo") == solo0 + 1
    disabled = BatchPlanner(cache, window_s=0)
    assert not disabled.enabled
    assert disabled.submit(pod, req, ["solo"]) is None
    assert BATCH_SOLVES.get("solo") == solo0 + 1, \
        "a disabled planner must not touch the counters"


def test_window_histogram_observes_batch_size():
    fc = FakeCluster()
    for i in range(4):
        fc.add_tpu_node(f"h{i}", chips=4, hbm_per_chip_mib=HBM,
                        mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    names = [f"h{i}" for i in range(4)]
    planner = BatchPlanner(cache, window_s=0.01, max_batch=3)
    count0 = BATCH_WINDOW_PODS.count
    pods = [make_pod(hbm=2048, name=f"w{i}", uid=f"uid-w{i}")
            for i in range(3)]
    req = request_from_pod(pods[0])
    out = [None] * 3

    def run(i):
        out[i] = planner.submit(pods[i], req, names)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert BATCH_WINDOW_PODS.count == count0 + 1
    assert all(o is not None for o in out), "full window covers everyone"
    assert {o.batch_size for o in out} == {3}
