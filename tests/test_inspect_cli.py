"""Inspect CLI tests: render golden tables from live extender output."""

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.inspect.cli import fetch, main, render_table
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster


@pytest.fixture
def live_env(capsys):
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=15000)
    fc.add_tpu_node("n2", chips=1, hbm_per_chip_mib=15000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=9000, name="worker"))
    info.allocate(pod, fc)
    # register in the pod index as the controller's sync loop would
    cache.add_or_update_pod(fc.get_pod("default", "worker"))
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    yield f"http://127.0.0.1:{port}", fc
    server.stop()


@pytest.fixture
def live(live_env):
    return live_env[0]


@pytest.fixture
def live_cluster(live_env):
    return live_env[1]


def test_cli_summary_table(live, capsys):
    assert main(["--endpoint", live]) == 0
    out = capsys.readouterr().out
    assert "NAME" in out and "n1" in out and "n2" in out
    # userguide.md:17-style cluster footer: 9000/45000 = 20%
    assert "Allocated/Total TPU HBM in Cluster: 9000/45000 MiB (20%)" in out


def test_cli_details_shows_pods(live, capsys):
    assert main(["--endpoint", live, "-d"]) == 0
    out = capsys.readouterr().out
    assert "default/worker=9000" in out
    assert "COORDS" in out


def test_cli_single_node(live, capsys):
    assert main(["--endpoint", live, "n1"]) == 0
    out = capsys.readouterr().out
    assert "n1" in out and "9000/30000" in out


def test_cli_unreachable_endpoint(capsys):
    assert main(["--endpoint", "http://127.0.0.1:1"]) == 1
    assert "cannot reach extender" in capsys.readouterr().err


def test_render_empty_cluster():
    out = render_table({"nodes": [], "used_hbm_mib": 0, "total_hbm_mib": 0})
    assert "Allocated/Total TPU HBM in Cluster: 0/0 MiB (-)" in out


def test_cli_fleet_subcommand(live, capsys):
    assert main(["--endpoint", live, "fleet"]) == 0
    out = capsys.readouterr().out
    assert "TIER" in out and "STRANDED" in out
    assert "drift auditor" in out and "scorecard" in out
    # --json emits the raw snapshot
    assert main(["--endpoint", live, "--json", "fleet"]) == 0
    import json as jsonlib
    snap = jsonlib.loads(capsys.readouterr().out)
    assert "tiers" in snap and "audit" in snap


def test_cli_defrag_subcommand(live, capsys, live_cluster):
    # before any pass: the endpoint serves, the renderer says so
    assert main(["--endpoint", live, "defrag"]) == 0
    out = capsys.readouterr().out
    assert "defrag:" in out and "no plan yet" in out
    assert "no moves executed yet" in out
    # --json emits the raw snapshot with the budget/counters schema
    assert main(["--endpoint", live, "--json", "defrag"]) == 0
    import json as jsonlib
    snap = jsonlib.loads(capsys.readouterr().out)
    assert snap["budget"]["budget"] >= 0
    assert "counters" in snap and "recent_moves" in snap


def test_cli_defrag_renders_a_real_pass(capsys):
    """A fragmented fleet through the REAL controller pass, rendered."""
    from tests.test_defrag import _frag_fleet
    fc, cache = _frag_fleet()
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    try:
        server.defrag.run_once()
        live = f"http://127.0.0.1:{port}"
        assert main(["--endpoint", live, "defrag"]) == 0
        out = capsys.readouterr().out
        assert "1 passes" in out
        assert "1 fragmented nodes" in out
        assert "n0" in out and "-> n1" in out
        assert "completed" in out
        assert "freed chips" in out
    finally:
        server.stop()


def test_cli_explain_and_traces_subcommands(live, capsys, live_cluster):
    import json as jsonlib
    import urllib.request

    fc = live_cluster
    pod = fc.create_pod(make_pod(hbm=1024, name="cli-pod"))

    def post(path, payload):
        req = urllib.request.Request(
            f"{live}/tpushare-scheduler{path}",
            data=jsonlib.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return jsonlib.loads(r.read())

    ok = post("/filter", {"Pod": pod, "NodeNames": ["n1", "n2"]})
    assert ok["NodeNames"]
    bind = post("/bind", {"PodName": "cli-pod", "PodNamespace": "default",
                          "PodUID": pod["metadata"]["uid"],
                          "Node": ok["NodeNames"][0]})
    assert not bind.get("Error")

    assert main(["--endpoint", live, "explain"]) == 0
    listing = jsonlib.loads(capsys.readouterr().out)
    assert any(p["pod"].get("name") == "cli-pod"
               for p in listing["pods"])
    assert main(["--endpoint", live, "explain", "default/cli-pod"]) == 0
    record = jsonlib.loads(capsys.readouterr().out)
    assert record["cycles"] and "filter" in record["cycles"][0]
    # unknown pod: clean error, not a traceback
    assert main(["--endpoint", live, "explain", "no/such"]) == 1
    assert "no decision record" in capsys.readouterr().err

    assert main(["--endpoint", live, "traces"]) == 0
    out = capsys.readouterr().out
    assert "recent traces" in out and "[bound]" in out
    assert main(["--endpoint", live, "--json", "-n", "1", "traces"]) == 0
    dump = jsonlib.loads(capsys.readouterr().out)
    assert len(dump["traces"]) <= 1


def test_cli_ring_disabled_mode(live, capsys):
    # no sharding wired: the endpoint still serves, the renderer says
    # which HA mode is actually running
    assert main(["--endpoint", live, "ring"]) == 0
    out = capsys.readouterr().out
    assert "sharding disabled" in out and "single-replica" in out
    assert main(["--endpoint", live, "--json", "ring"]) == 0
    import json as jsonlib
    snap = jsonlib.loads(capsys.readouterr().out)
    assert snap == {"enabled": False, "mode": "single-replica"}


@pytest.fixture
def live_sharded(capsys):
    from tpushare.ha import ShardMembership
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=15000)
    fc.add_tpu_node("n2", chips=1, hbm_per_chip_mib=15000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    sm = ShardMembership(fc, "ra", cache=cache)
    # membership applied directly (no renewal thread): deterministic
    # two-member ring for the golden rendering; rb's advertised peer
    # address as the lease listing would have discovered it
    sm._apply_membership(["ra", "rb"])
    sm._peers = {"rb": "http://127.0.0.1:40001"}
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0,
                            sharding=sm)
    port = server.start()
    yield f"http://127.0.0.1:{port}"
    server.stop()


def test_cli_ring_subcommand(live_sharded, capsys):
    assert main(["--endpoint", live_sharded, "ring"]) == 0
    out = capsys.readouterr().out
    assert "ring: 2 member(s)" in out
    assert "this replica: ra (live, ring leader)" in out
    assert "MEMBER" in out and "SHARD NODES" in out
    assert "leader,self" in out and "rb" in out
    assert "bind outcomes:" in out and "lock-free" in out
    # owner-forwarding surfaces: the peer address book column and the
    # per-outcome forward counters
    assert "PEER URL" in out and "http://127.0.0.1:40001" in out
    assert "forwards:" in out and "loop_fallback" in out
    # --json round-trips the raw snapshot schema
    assert main(["--endpoint", live_sharded, "--json", "ring"]) == 0
    import json as jsonlib
    snap = jsonlib.loads(capsys.readouterr().out)
    assert snap["members"] == ["ra", "rb"]
    assert snap["identity"] == "ra" and snap["live"] is True
    assert snap["ring_leader"] == "ra"
    assert sum(snap["shard_sizes"].values()) == 2
    assert set(snap["conflicts"]) == {"owned", "spillover", "cas_lost"}
    assert snap["peers"] == {"rb": "http://127.0.0.1:40001"}
    assert set(snap["forwards"]) == {
        "forwarded", "served", "loop_fallback", "peer_failed"}


def test_cli_gang_subcommand(capsys):
    """A live gang plan (rank 0 bound, rank 1 pending) rendered by
    `tpushare-inspect gang`, plus the --json raw snapshot."""
    from tests.test_gang import gang_pod, make_slice_cluster

    fc = make_slice_cluster()
    cache = SchedulerCache(fc)
    cache.build_cache()
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    live = f"http://127.0.0.1:{port}"
    try:
        import json as jsonlib
        import urllib.request

        def post(path, body):
            req = urllib.request.Request(
                f"{live}{path}", data=jsonlib.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=5) as r:
                return jsonlib.loads(r.read())

        pod = gang_pod(fc, "gp0", rank=0)
        flt = post("/tpushare-scheduler/filter", {
            "Pod": pod, "NodeNames": ["s0h0", "s0h1", "s0h2", "s0h3"]})
        (host,) = flt["NodeNames"]
        post("/tpushare-scheduler/bind", {
            "PodName": "gp0", "PodNamespace": "default",
            "PodUID": pod["metadata"]["uid"], "Node": host})

        assert main(["--endpoint", live, "gang"]) == 0
        out = capsys.readouterr().out
        assert "gang planner: 1 live plan(s)" in out
        assert "slice slc0: 4 host(s), host grid 2x2" in out
        assert "GANG" in out and "BOUND" in out and "g1" in out
        assert "1/2" in out  # one of two members bound
        # counters are process-global: assert presence, not counts
        assert "solves: " in out and "planned=" in out
        assert "member binds: " in out

        assert main(["--endpoint", live, "--json", "gang"]) == 0
        snap = jsonlib.loads(capsys.readouterr().out)
        assert snap["plans"][0]["gang_id"] == "g1"
        assert snap["plans"][0]["bound"] == [0]
        assert snap["catalog"][0]["slice"] == "slc0"
    finally:
        server.stop()


def test_cli_qos_subcommand_inactive(live, capsys):
    """ISSUE 17: with TPUSHARE_QOS_OVERCOMMIT unset the endpoint still
    serves — knobs show off, no oversubscription, empty eviction state."""
    import json as jsonlib

    assert main(["--endpoint", live, "qos"]) == 0
    out = capsys.readouterr().out
    assert "qos: overcommit 1.0 (off)" in out
    assert "no node oversubscribed" in out
    assert "evictions: 0/" in out
    assert "tenant dominant shares" in out  # the bound worker pod

    assert main(["--endpoint", live, "--json", "qos"]) == 0
    snap = jsonlib.loads(capsys.readouterr().out)
    assert snap["overcommit"] == 1.0
    assert snap["effective_overcommit"] == 1.0
    assert snap["evictor_degraded"] is False
    assert snap["oversubscribed_nodes"] == {}
    assert snap["fleet"]["by_tier_hbm_mib"] == {"burstable": 9000}
    assert snap["fleet"]["reclaimable_hbm_mib"] == 0
    assert snap["eviction"]["budget"] >= 1
    assert snap["tenant_dominant_share"]["default"] > 0


def test_cli_qos_subcommand_active(capsys, monkeypatch):
    """ISSUE 17: an oversubscribed fleet renders its borrow state — the
    best-effort tier row, the oversubscribed node, the DRF shares."""
    import json as jsonlib

    from tpushare.contract import ANN_QOS_TIER

    monkeypatch.setenv("TPUSHARE_QOS_OVERCOMMIT", "1.5")
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=10000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    info = cache.get_node_info("n1")
    be = make_pod(hbm=8000, name="scavenger", namespace="batch",
                  ann={ANN_QOS_TIER: "best-effort"})
    info.allocate(fc.create_pod(be), fc)
    cache.add_or_update_pod(fc.get_pod("batch", "scavenger"))
    gp = make_pod(hbm=6000, name="inference",
                  ann={ANN_QOS_TIER: "guaranteed"})
    info.allocate(fc.create_pod(gp), fc)
    cache.add_or_update_pod(fc.get_pod("default", "inference"))
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    try:
        live = f"http://127.0.0.1:{port}"
        assert main(["--endpoint", live, "qos"]) == 0
        out = capsys.readouterr().out
        assert "qos: overcommit 1.5 (active)" in out
        assert "best-effort" in out and "guaranteed" in out
        assert "n1: 4000 MiB over" in out
        assert "reclaimable (best-effort, evictable): 8000 MiB" in out
        assert "batch:" in out and "default:" in out

        assert main(["--endpoint", live, "--json", "qos"]) == 0
        snap = jsonlib.loads(capsys.readouterr().out)
        assert snap["fleet"]["by_tier_hbm_mib"] == {
            "best-effort": 8000, "guaranteed": 6000}
        assert snap["oversubscribed_nodes"] == {"n1": 4000}
        assert snap["tenant_dominant_share"]["batch"] == 1.0
    finally:
        server.stop()


def test_cli_journal_subcommand(live, capsys):
    """ISSUE 19: `tpushare-inspect journal` renders the black-box plane
    — ring pump health, journal state (disabled on this rig: the knob
    hint must say how to turn it on), federation slot."""
    import json as jsonlib

    assert main(["--endpoint", live, "journal"]) == 0
    out = capsys.readouterr().out
    from tpushare.core.native import engine as native_engine
    if native_engine.blackbox_supported():
        assert "black box: running" in out
        assert "pending in ring" in out
    else:
        assert "black box: UNSUPPORTED" in out
    assert "journal: disabled (set TPUSHARE_JOURNAL_DIR" in out
    assert "federation: slot" in out or "federation: disabled" in out

    assert main(["--endpoint", live, "--json", "journal"]) == 0
    snap = jsonlib.loads(capsys.readouterr().out)
    assert set(snap) == {"blackbox", "journal", "federation"}
    assert snap["journal"] == {"enabled": False}


def test_cli_journal_subcommand_recording(tmp_path, capsys, monkeypatch):
    """With TPUSHARE_JOURNAL_DIR set the rendering carries the recorded
    aggregate and the copy-pasteable replay command."""
    import json as jsonlib
    import urllib.request

    monkeypatch.setenv("TPUSHARE_JOURNAL_DIR", str(tmp_path / "jrn"))
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=15000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    try:
        live = f"http://127.0.0.1:{port}"
        pod = fc.create_pod(make_pod(hbm=1024, name="jp"))
        req = urllib.request.Request(
            f"{live}/tpushare-scheduler/filter",
            data=jsonlib.dumps({"Pod": pod,
                                "NodeNames": ["n1"]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert jsonlib.loads(r.read())["NodeNames"]
        server.journal.flush()
        assert main(["--endpoint", live, "journal"]) == 0
        out = capsys.readouterr().out
        assert "journal: " in out and "1 file(s)" in out
        assert "recorded: 1 pod(s) — 1 admitted" in out
        assert f"replay: python -m tpushare.sim --replay" in out
    finally:
        server.stop()


def test_cli_metrics_subcommand(live, capsys):
    """`tpushare-inspect metrics` prints the scrape verbatim; with
    --federated it prints the merged fleet-wide sum (counters and
    histograms only — gauges are per-process and stay out)."""
    assert main(["--endpoint", live, "metrics"]) == 0
    local = capsys.readouterr().out
    assert "# TYPE" in local

    assert main(["--endpoint", live, "--federated", "metrics"]) == 0
    fed = capsys.readouterr().out
    assert "# TYPE" in fed

    def types(text):
        return {ln.split()[-1] for ln in text.splitlines()
                if ln.startswith("# TYPE")}

    assert "gauge" in types(local)  # the local scrape has gauges...
    # ...the federated sum never does: gauges are per-process statements
    assert types(fed) <= {"counter", "histogram"}


def test_cli_wire_subcommand(live, capsys):
    """ISSUE 16: `tpushare-inspect wire` renders digest-table occupancy
    and the native hit rate from /inspect/wire."""
    import http.client
    import json as jsonlib

    # storm one filter twice over a keep-alive connection so the digest
    # cache, the response cache, and (where the engine built) the native
    # table all have something to show
    host, port = live.removeprefix("http://").split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=5)
    body = jsonlib.dumps({"Pod": make_pod(hbm=1000, name="wcli"),
                          "NodeNames": ["n1", "n2"]}).encode()
    for _ in range(3):
        conn.request("POST", "/tpushare-scheduler/filter", body,
                     {"Content-Type": "application/json"})
        assert conn.getresponse().read()
    conn.close()

    assert main(["--endpoint", live, "wire"]) == 0
    out = capsys.readouterr().out
    assert "wirecache: enabled" in out
    assert "digests" in out and "stale serves" in out
    assert "native table:" in out
    assert "serve outcomes: " in out or "DISABLED" in out

    assert main(["--endpoint", live, "--json", "wire"]) == 0
    snap = jsonlib.loads(capsys.readouterr().out)
    assert "wirecache" in snap and "native" in snap
    assert snap["wirecache"]["digests"] >= 1
    from tpushare.core.native import engine as native_engine
    if native_engine.wire_probe_supported():
        assert snap["native"]["enabled"] is True
        assert snap["native"]["probes"] >= 1
