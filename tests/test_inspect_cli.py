"""Inspect CLI tests: render golden tables from live extender output."""

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.inspect.cli import fetch, main, render_table
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster


@pytest.fixture
def live(capsys):
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=15000)
    fc.add_tpu_node("n2", chips=1, hbm_per_chip_mib=15000)
    cache = SchedulerCache(fc)
    cache.build_cache()
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=9000, name="worker"))
    info.allocate(pod, fc)
    # register in the pod index as the controller's sync loop would
    cache.add_or_update_pod(fc.get_pod("default", "worker"))
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    yield f"http://127.0.0.1:{port}"
    server.stop()


def test_cli_summary_table(live, capsys):
    assert main(["--endpoint", live]) == 0
    out = capsys.readouterr().out
    assert "NAME" in out and "n1" in out and "n2" in out
    # userguide.md:17-style cluster footer: 9000/45000 = 20%
    assert "Allocated/Total TPU HBM in Cluster: 9000/45000 MiB (20%)" in out


def test_cli_details_shows_pods(live, capsys):
    assert main(["--endpoint", live, "-d"]) == 0
    out = capsys.readouterr().out
    assert "default/worker=9000" in out
    assert "COORDS" in out


def test_cli_single_node(live, capsys):
    assert main(["--endpoint", live, "n1"]) == 0
    out = capsys.readouterr().out
    assert "n1" in out and "9000/30000" in out


def test_cli_unreachable_endpoint(capsys):
    assert main(["--endpoint", "http://127.0.0.1:1"]) == 1
    assert "cannot reach extender" in capsys.readouterr().err


def test_render_empty_cluster():
    out = render_table({"nodes": [], "used_hbm_mib": 0, "total_hbm_mib": 0})
    assert "Allocated/Total TPU HBM in Cluster: 0/0 MiB (-)" in out
