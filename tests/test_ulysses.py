"""All-to-all (Ulysses) sequence parallelism vs the exact reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpushare.workloads.attention import attention_reference
from tpushare.workloads.ringattention import ring_attention
from tpushare.workloads.ulysses import ulysses_attention


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _qkv(B=2, H=8, S=64, D=16, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(kq, (B, H, S, D), jnp.float32),
            jax.random.normal(kk, (B, H, S, D), jnp.float32),
            jax.random.normal(kv, (B, H, S, D), jnp.float32))


@pytest.mark.tpu_kernel
@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(n, causal):
    q, k, v = _qkv()
    mesh = _mesh(n)
    got = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.tpu_kernel
def test_agrees_with_ring_attention():
    q, k, v = _qkv(seed=3)
    mesh = _mesh(8)
    a2a = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
    ring = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(a2a), np.asarray(ring),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.tpu_kernel
def test_sharded_inputs_stay_sharded():
    q, k, v = _qkv(seed=5)
    mesh = _mesh(4)
    sh = NamedSharding(mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
    assert out.sharding.is_equivalent_to(sh, out.ndim)


def test_rejects_indivisible_shapes():
    mesh = _mesh(8)
    q, k, v = _qkv(H=4)  # 4 heads < 8 shards
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)
    q, k, v = _qkv(S=60)
    with pytest.raises(ValueError, match="seq len"):
        ulysses_attention(q, k, v, mesh)


@pytest.mark.tpu_kernel
def test_differentiable():
    q, k, v = _qkv(B=1, H=4, S=32, D=8, seed=7)
    mesh = _mesh(4)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.tpu_kernel
def test_ulysses_flash_matches_einsum_path():
    """attn='flash' (the fused-kernel TPU serving path; interpret mode
    here) must match the einsum spec path on the same sharded inputs."""
    mesh = _mesh(4)
    q, k, v = _qkv(S=128, seed=9)
    out_flash = ulysses_attention(q, k, v, mesh, causal=True, attn="flash")
    out_einsum = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out_flash),
                               np.asarray(out_einsum),
                               rtol=2e-2, atol=2e-2)
    with pytest.raises(ValueError, match="attn"):
        ulysses_attention(q, k, v, mesh, attn="nope")


@pytest.mark.tpu_kernel
def test_ulysses_window_matches_reference():
    """Sequence-parallel + sliding window: the all_to_all re-shard hands
    each device the FULL sequence, so the window applies unchanged; both
    local attention backends must match the windowed reference."""
    from tpushare.workloads.attention import attention_reference

    mesh = _mesh(8)
    B, H, S, D, W = 2, 8, 128, 16, 40
    ks = jax.random.split(jax.random.key(90), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    ref = attention_reference(q, k, v, causal=True, window=W)
    for attn in ("einsum", "flash"):
        out = jax.jit(lambda q, k, v, a=attn: ulysses_attention(
            q, k, v, mesh, causal=True, attn=a, window=W))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"attn={attn}")


@pytest.mark.tpu_kernel
def test_ulysses_gqa_native_matches_expanded_reference():
    """GQA-native Ulysses: the kv all_to_all moves the SMALL heads (1/G
    of the expanded bytes) and the per-device head blocks align exactly;
    both local backends must match the expanded-head reference."""
    from tpushare.workloads.attention import attention_reference

    mesh = _mesh(8)
    B, H, Hkv, S, D = 2, 16, 8, 128, 16
    ks = jax.random.split(jax.random.key(95), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32)
    g = H // Hkv
    ref = attention_reference(q, jnp.repeat(k, g, 1), jnp.repeat(v, g, 1),
                              causal=True)
    for attn in ("einsum", "flash"):
        out = jax.jit(lambda q, k, v, a=attn: ulysses_attention(
            q, k, v, mesh, causal=True, attn=a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"attn={attn}")


def test_ulysses_rejects_scarce_kv_heads():
    mesh = _mesh(8)
    ks = jax.random.split(jax.random.key(96), 3)
    q = jax.random.normal(ks[0], (1, 8, 64, 16), jnp.float32)
    k = jax.random.normal(ks[1], (1, 2, 64, 16), jnp.float32)  # 2 % 8 != 0
    v = jnp.zeros_like(k)
    with pytest.raises(ValueError, match="kv heads not divisible"):
        ulysses_attention(q, k, v, mesh)
