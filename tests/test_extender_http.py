"""Protocol-level tests: golden extender-API JSON over real HTTP.

SURVEY §4: "POST golden ExtenderArgs/ExtenderBindingArgs JSON at the HTTP
layer and assert on ExtenderFilterResult/ExtenderBindingResult". The server
runs on an ephemeral port against a FakeCluster; requests go through
urllib — the same path an unmodified kube-scheduler would take.
"""

import json
import urllib.error
import urllib.request

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.handlers import register_cache_gauges
from tpushare.extender.metrics import Registry
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.add_tpu_node("n2", chips=2, hbm_per_chip_mib=8000)
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    server = ExtenderServer(cache, fc, registry, host="127.0.0.1", port=0)
    register_cache_gauges(registry, cache)
    port = server.start()
    yield fc, cache, f"http://127.0.0.1:{port}"
    server.stop()
    ctl.stop()


def post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def get(url, as_json=True):
    with urllib.request.urlopen(url, timeout=5) as r:
        raw = r.read()
        return r.status, (json.loads(raw) if as_json else raw.decode())


def test_filter_golden(rig):
    fc, cache, base = rig
    pod = make_pod(hbm=10000, name="p")
    status, result = post(f"{base}/tpushare-scheduler/filter", {
        "Pod": pod, "NodeNames": ["n1", "n2", "ghost"]})
    assert status == 200
    assert result["NodeNames"] == ["n1"]  # n2 chips are 8000 MiB < 10000
    assert "n2" in result["FailedNodes"]
    assert "no fit" in result["FailedNodes"]["n2"]
    assert "ghost" in result["FailedNodes"]
    assert result["Error"] == ""


def test_filter_non_tpu_pod_passes_everything(rig):
    fc, cache, base = rig
    status, result = post(f"{base}/tpushare-scheduler/filter", {
        "Pod": make_pod(), "NodeNames": ["n1", "n2"]})
    assert status == 200
    assert result["NodeNames"] == ["n1", "n2"]


def test_filter_nodes_fallback_for_non_cache_capable(rig):
    fc, cache, base = rig
    status, result = post(f"{base}/tpushare-scheduler/filter", {
        "Pod": make_pod(hbm=100),
        "Nodes": {"items": [fc.get_node("n1")]}})
    assert status == 200 and result["NodeNames"] == ["n1"]


def test_prioritize_ranks_tightest_node_first(rig):
    """VERDICT r1 item 3: the prioritize verb ranks candidates by the
    tightest-fit binpack policy (leftover HBM on the chosen chips), so the
    default scheduler packs instead of spreading."""
    fc, cache, base = rig
    pod = make_pod(hbm=2000, name="p")
    status, ranked = post(f"{base}/tpushare-scheduler/prioritize", {
        "Pod": pod, "NodeNames": ["n1", "n2"]})
    assert status == 200
    scores = {h["Host"]: h["Score"] for h in ranked}
    # empty fleet: n2's 8000-MiB chips leave less leftover than n1's 16000
    assert scores["n2"] == 10 and scores["n1"] < scores["n2"]

    # fill one n1 chip down to a 2000-MiB hole -> n1 becomes the tightest
    big = fc.create_pod(make_pod(hbm=14000, name="filler"))
    post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "filler", "PodNamespace": "default",
        "PodUID": big["metadata"]["uid"], "Node": "n1"})
    status, ranked = post(f"{base}/tpushare-scheduler/prioritize", {
        "Pod": pod, "NodeNames": ["n1", "n2"]})
    scores = {h["Host"]: h["Score"] for h in ranked}
    assert scores["n1"] == 10 and scores["n2"] < scores["n1"]


def test_prioritize_non_tpu_pod_and_unknown_node(rig):
    fc, cache, base = rig
    status, ranked = post(f"{base}/tpushare-scheduler/prioritize", {
        "Pod": make_pod(), "NodeNames": ["n1", "ghost"]})
    assert status == 200
    assert ranked == [{"Host": "n1", "Score": 0},
                      {"Host": "ghost", "Score": 0}]
    # tpushare pod, unknown node scores 0 but stays in the list
    status, ranked = post(f"{base}/tpushare-scheduler/prioritize", {
        "Pod": make_pod(hbm=100), "NodeNames": ["ghost", "n1"]})
    scores = {h["Host"]: h["Score"] for h in ranked}
    assert scores["ghost"] == 0 and scores["n1"] == 10


def test_bind_golden_writes_annotations(rig):
    fc, cache, base = rig
    created = fc.create_pod(make_pod(hbm=2000, name="p"))
    status, result = post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "p", "PodNamespace": "default",
        "PodUID": created["metadata"]["uid"], "Node": "n1"})
    assert status == 200 and result["Error"] == ""
    bound = fc.get_pod("default", "p")
    assert bound["spec"]["nodeName"] == "n1"
    assert contract.chip_ids_from_annotations(bound) is not None
    assert contract.hbm_from_annotations(bound) == 2000


def test_bind_failure_returns_500(rig):
    fc, cache, base = rig
    created = fc.create_pod(make_pod(hbm=99999, name="toobig"))
    with pytest.raises(urllib.error.HTTPError) as e:
        post(f"{base}/tpushare-scheduler/bind", {
            "PodName": "toobig", "PodNamespace": "default",
            "PodUID": created["metadata"]["uid"], "Node": "n1"})
    assert e.value.code == 500
    body = json.loads(e.value.read())
    assert "no placement" in body["Error"]


def test_bind_emits_scheduled_and_failure_events(rig):
    """The extender owns the bind verb, so it emits the TPUShareBound /
    TPUShareBindFailed pod events (distinct reasons from the default scheduler's own) (the
    reference wires an EventRecorder but never emits — SURVEY §5.5)."""
    fc, cache, base = rig
    ok = fc.create_pod(make_pod(hbm=2000, name="evt-ok"))
    post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "evt-ok", "PodNamespace": "default",
        "PodUID": ok["metadata"]["uid"], "Node": "n1"})
    bad = fc.create_pod(make_pod(hbm=99999, name="evt-bad"))
    with pytest.raises(urllib.error.HTTPError):
        post(f"{base}/tpushare-scheduler/bind", {
            "PodName": "evt-bad", "PodNamespace": "default",
            "PodUID": bad["metadata"]["uid"], "Node": "n1"})
    events = fc.events
    sched = [e for e in events if e["reason"] == "TPUShareBound"]
    failed = [e for e in events if e["reason"] == "TPUShareBindFailed"]
    assert len(sched) == 1 and sched[0]["type"] == "Normal"
    assert sched[0]["involvedObject"]["name"] == "evt-ok"
    assert "chips" in sched[0]["message"]
    assert len(failed) == 1 and failed[0]["type"] == "Warning"
    assert failed[0]["involvedObject"]["name"] == "evt-bad"
    assert "no placement" in failed[0]["message"]


def test_duplicate_bind_is_idempotent_success(rig):
    """A re-delivered bind for a pod already bound to the requested node
    returns success (the pod IS scheduled as asked); a bind for a pod
    bound elsewhere fails, but without a failure event."""
    fc, cache, base = rig
    created = fc.create_pod(make_pod(hbm=1000, name="dup"))
    body = {"PodName": "dup", "PodNamespace": "default",
            "PodUID": created["metadata"]["uid"], "Node": "n1"}
    status, result = post(f"{base}/tpushare-scheduler/bind", body)
    assert status == 200 and result["Error"] == ""
    status, result = post(f"{base}/tpushare-scheduler/bind", body)  # again
    assert status == 200 and result["Error"] == ""
    # bound to a different node -> refused, but no Warning event
    with pytest.raises(urllib.error.HTTPError) as e:
        post(f"{base}/tpushare-scheduler/bind", {**body, "Node": "n2"})
    assert e.value.code == 500
    assert "already bound" in json.loads(e.value.read())["Error"]
    warnings = [ev for ev in fc.events
                if ev["reason"] == "TPUShareBindFailed"
                and ev["involvedObject"]["name"] == "dup"]
    assert warnings == []
    # exactly one bound event despite three bind calls
    sched = [ev for ev in fc.events if ev["reason"] == "TPUShareBound"
             and ev["involvedObject"]["name"] == "dup"]
    assert len(sched) == 1


def test_bind_uid_mismatch_rejected(rig):
    fc, cache, base = rig
    fc.create_pod(make_pod(hbm=100, name="p"))
    with pytest.raises(urllib.error.HTTPError) as e:
        post(f"{base}/tpushare-scheduler/bind", {
            "PodName": "p", "PodNamespace": "default",
            "PodUID": "stale-uid", "Node": "n1"})
    assert e.value.code == 500
    assert "UID changed" in json.loads(e.value.read())["Error"]


def test_inspect_tree_and_node(rig):
    fc, cache, base = rig
    created = fc.create_pod(make_pod(hbm=2000, name="p"))
    post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "p", "PodNamespace": "default",
        "PodUID": created["metadata"]["uid"], "Node": "n1"})
    status, tree = get(f"{base}/tpushare-scheduler/inspect")
    assert status == 200
    assert tree["used_hbm_mib"] == 2000
    assert {n["name"] for n in tree["nodes"]} == {"n1", "n2"}
    status, node = get(f"{base}/tpushare-scheduler/inspect/n1")
    assert status == 200 and node["mesh"] == "2x2"
    with pytest.raises(urllib.error.HTTPError) as e:
        get(f"{base}/tpushare-scheduler/inspect/ghost")
    assert e.value.code == 404


def test_version_healthz_metrics(rig):
    fc, cache, base = rig
    status, v = get(f"{base}/version")
    assert status == 200 and "version" in v
    status, h = get(f"{base}/healthz", as_json=False)
    assert status == 200 and h == "ok"
    # generate one bind so latency histogram is non-empty
    created = fc.create_pod(make_pod(hbm=500, name="m"))
    post(f"{base}/tpushare-scheduler/bind", {
        "PodName": "m", "PodNamespace": "default",
        "PodUID": created["metadata"]["uid"], "Node": "n1"})
    status, text = get(f"{base}/metrics", as_json=False)
    assert status == 200
    assert "tpushare_bind_requests_total 1.0" in text
    assert "tpushare_bind_seconds_bucket" in text
    assert 'tpushare_node_hbm{node="n1",metric="utilization_pct"}' in text


def test_malformed_json_is_400(rig):
    fc, cache, base = rig
    req = urllib.request.Request(
        f"{base}/tpushare-scheduler/filter", data=b"{not json",
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=5)
    assert e.value.code == 400


def test_unknown_routes_404(rig):
    fc, cache, base = rig
    for path in ["/nope", "/tpushare-scheduler/nope"]:
        with pytest.raises(urllib.error.HTTPError) as e:
            get(f"{base}{path}")
        assert e.value.code == 404


def test_debug_threads(rig):
    fc, cache, base = rig
    status, text = get(f"{base}/debug/threads", as_json=False)
    assert status == 200 and "tpushare-http" in text


def test_debug_heap(rig):
    """pprof /heap analogue (reference pkg/routes/pprof.go:10-22): first
    call arms tracemalloc, second returns allocation sites."""
    import tracemalloc

    fc, cache, base = rig
    try:
        status, text = get(f"{base}/debug/heap", as_json=False)
        assert status == 200
        status, text = get(f"{base}/debug/heap?top=5", as_json=False)
        assert status == 200 and "live traced heap" in text and "KiB" in text
    finally:
        tracemalloc.stop()  # don't tax the rest of the suite


def test_preempt_route_refines_victims(rig):
    fc, cache, base = rig
    # fill n2 (2 chips x 8000): v1 4000 + v3 2000 co-packed on one chip,
    # v2 6000 on the other -> a 4000 pod fits nowhere on n2
    info = cache.get_node_info("n2")
    uids = {}
    for name, hbm, prio in (("v1", 4000, 5), ("v3", 2000, 0),
                            ("v2", 6000, 10)):
        pod = make_pod(hbm=hbm, name=name)
        pod["spec"]["priority"] = prio
        pod = fc.create_pod(pod)
        info.allocate(pod, fc)
        uids[name] = pod["metadata"]["uid"]
        # deterministic priority resolution: don't race the controller's
        # async sync for the known-pods registry
        cache.add_or_update_pod(fc.get_pod("default", name))
    status, out = post(f"{base}/tpushare-scheduler/preempt", {
        "Pod": make_pod(hbm=4000, name="high"),
        "NodeNameToMetaVictims": {
            "n2": {"Pods": [{"UID": uids["v1"]}, {"UID": uids["v3"]}],
                   "NumPDBViolations": 0},
        },
    })
    assert status == 200
    assert out["NodeNameToMetaVictims"]["n2"]["Pods"] == [
        {"UID": uids["v3"]}]


def test_inspect_gang_route(rig):
    fc, cache, base = rig
    status, snap = get(f"{base}/tpushare-scheduler/inspect/gang")
    assert status == 200
    # full planner-snapshot schema, even on a gang-free fleet
    for key in ("plans", "provisional", "catalog", "solves", "members"):
        assert key in snap, key
    assert snap["plans"] == [] and snap["provisional"] == []
    # n1/n2 carry no slice labels: the catalog has no slices to solve
    assert snap["catalog"] == []
    # unprefixed alias serves the same snapshot (debug ergonomics)
    status2, snap2 = get(f"{base}/inspect/gang")
    assert status2 == 200 and snap2.keys() == snap.keys()
