"""The chaos soak: a sustained concurrent bind storm under a rolling
apiserver brownout (injected 5xx, 429 + Retry-After, latency, watch
drops), driven through the FULL fault-containment stack
(RetryingCluster -> BreakerCluster -> CountingCluster -> ChaosCluster).

Invariants asserted (ISSUE 2 acceptance):

1. no chip is ever oversubscribed, even transiently (sampler thread);
2. every bind webhook attempt resolves — success or clean failure —
   within its request deadline;
3. zero leaked placements after the storm + GC + resync: apiserver truth
   and cache accounting agree exactly;
4. apiserver write amplification stays within the configured retry
   budget (each logical write is attempted at most ``max_attempts``
   times; a bind attempt performs at most 3 logical pod writes: patch,
   bind, rollback-revert);
5. the storm actually stormed (injected fault counts are nonzero —
   a chaos test that injected nothing proves nothing).

The tier-1 variant is short and deterministic-seeded; the ``slow``
variant runs multiple rolling brownout waves for several seconds
(``pytest -m slow``).
"""

import json
import os
import random
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.defrag.planner import ANN_MOVABLE
from tpushare.extender.handlers import BindHandler, FilterHandler
from tpushare.extender.metrics import Registry
from tpushare.k8s import (
    ChaosCluster,
    CircuitBreaker,
    FakeCluster,
    RetryPolicy,
    harden,
    request_deadline,
)
from tpushare.k8s.stats import CountingCluster
from tpushare.metrics import LabeledCounter

HBM_PER_CHIP = 16000
POD_WRITE_VERBS = ("patch_pod", "bind_pod", "replace_pod")
# per bind attempt: placement PATCH + binding POST + (on failure) one
# rollback-revert PATCH
LOGICAL_WRITES_PER_ATTEMPT = 3


def _post_json(url: str, body: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def run_soak(*, seed: int, storm_s: float, n_pods: int, n_nodes: int = 3,
             threads: int = 8, deadline_s: float = 1.0,
             waves: int = 1, via_http: bool = False,
             migration: bool = False) -> dict:
    """One soak run; returns its telemetry for the variant's assertions.

    ``via_http=True`` (ISSUE 13 satellite) reruns the same storm through
    the real HTTP front end: an :class:`ExtenderServer` over the same
    hardened cluster, every filter/bind a real POST — so the selector
    event-loop server (the ``TPUSHARE_SERVER`` default, PR 11) sits
    inside the brownout blast radius instead of being bypassed.

    ``migration=True`` (ISSUE 20 satellite, requires ``via_http``) arms
    the live-migration rebalancer inside the same blast radius: every
    storm pod is movable, ``TPUSHARE_DEFRAG=1`` with a storm-rate
    period, so checkpoint-evict-restore moves race the bind storm AND
    the brownout — and the identical invariants must hold."""
    assert via_http or not migration, "migration soak runs over HTTP"
    fc = FakeCluster()
    names = [f"n{i}" for i in range(n_nodes)]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM_PER_CHIP,
                        mesh="2x2")
    chaos = ChaosCluster(fc, seed=seed)
    stats = LabeledCounter("soak_requests", "per-run", ("verb", "origin"))
    counting = CountingCluster(chaos, stats=stats)
    breaker = CircuitBreaker(failure_threshold=4, reset_timeout_s=0.05)
    policy = RetryPolicy(max_attempts=3, base_s=0.002, cap_s=0.01,
                         rng=random.Random(seed))
    cluster = harden(counting, breaker=breaker, policy=policy)
    cache = SchedulerCache(cluster)
    ctl = Controller(cluster, cache, resync_seconds=0.2)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    server = None
    if via_http:
        from tpushare.extender.server import ExtenderServer

        # pin TPUSHARE_SERVER to its default (the selector front end is
        # what this variant exists to storm) and keep the background
        # auditors out of the hermetic rig — except the defrag
        # rebalancer, which the migration variant deliberately arms at
        # storm rate so live moves contend with the bind storm
        saved = {k: os.environ.pop(k, None)
                 for k in ("TPUSHARE_SERVER", "TPUSHARE_FLEETWATCH",
                           "TPUSHARE_DEFRAG", "TPUSHARE_DEFRAG_PERIOD_S")}
        os.environ["TPUSHARE_FLEETWATCH"] = "0"
        os.environ["TPUSHARE_DEFRAG"] = "1" if migration else "0"
        if migration:
            os.environ["TPUSHARE_DEFRAG_PERIOD_S"] = "0.05"
        try:
            server = ExtenderServer(cache, cluster, registry,
                                    host="127.0.0.1", port=0,
                                    breaker=breaker,
                                    request_deadline_s=deadline_s)
            port = server.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        base = f"http://127.0.0.1:{port}/tpushare-scheduler"
        http_timeout = deadline_s + 10.0

        class _HttpFilter:
            """Same .handle() surface as FilterHandler, over the wire.
            A transport failure is an empty verdict — the storm loop
            retries, exactly as it does for a degraded direct serve."""

            def handle(self, args):
                try:
                    body = _post_json(base + "/filter", args, http_timeout)
                except OSError:
                    return {"NodeNames": []}
                return {"NodeNames": body.get("NodeNames") or []}

        class _HttpBind:
            def handle(self, args):
                try:
                    body = _post_json(base + "/bind", args, http_timeout)
                except OSError as e:
                    return {"Error": f"http transport: {e}"}
                return {"Error": body.get("Error") or ""}

        fil, binder = _HttpFilter(), _HttpBind()
    else:
        fil = FilterHandler(cache, registry, breaker=breaker)
        binder = BindHandler(cache, cluster, registry, breaker=breaker)

    # -- the storm: rolling brownout + 429s + latency + watch drops ----------
    wave_s = storm_s / waves
    for w in range(waves):
        # staggered waves so the apiserver browns out, recovers, and
        # browns out again — the breaker must open AND close repeatedly
        def delayed(method, delay, **kw):
            if delay <= 0:
                chaos.brownout(method, **kw)
            else:
                t = threading.Timer(delay, chaos.brownout,
                                    args=(method,), kwargs=kw)
                t.daemon = True
                t.start()
        for m in ("patch_pod", "bind_pod"):
            delayed(m, w * wave_s, seconds=wave_s, peak=0.6, status=503)
    chaos.fail("patch_pod", status=429, retry_after=0.005,
               probability=0.08, times=None)
    chaos.fail("bind_pod", status=0, probability=0.05, times=None)
    chaos.delay("bind_pod", seconds=0.005, probability=0.2, times=None)
    chaos.drop_watch("pods", after=2, times=3)

    overcommit: list = []
    deadline_violations: list = []
    stop = threading.Event()

    def sampler():
        """Continuously audits APISERVER TRUTH: per chip, the summed HBM
        of live bound pods must never exceed capacity — at any instant,
        not just at the end. (The cache is deliberately allowed to
        transiently OVERcount — e.g. a watch-lagged re-add of a pod that
        just completed — because overcounting only makes binds more
        conservative; the invariant that must never break is the real
        one, on the placements the apiserver holds.)"""
        while not stop.is_set():
            per: dict = {}
            for pod in fc.list_pods():
                if contract.is_complete_pod(pod):
                    continue
                node = pod["spec"].get("nodeName")
                ids = contract.chip_ids_from_annotations(pod)
                if not node or ids is None:
                    continue
                h = contract.hbm_from_annotations(pod)
                for c in ids:
                    per[(node, c)] = per.get((node, c), 0) + h
            for k, v in per.items():
                if v > HBM_PER_CHIP:
                    overcommit.append((k, v))
            time.sleep(0.002)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()

    attempts = [0]
    attempts_lock = threading.Lock()
    hbm = 2048
    # migration soak: every pod is movable, so the armed rebalancer may
    # checkpoint-evict-restore any of them mid-storm
    movable = {ANN_MOVABLE: "true"} if migration else None
    pods = [fc.create_pod(make_pod(hbm=hbm, name=f"s{i}", ann=movable))
            for i in range(n_pods)]
    storm_end = time.monotonic() + storm_s

    def schedule(pod) -> bool:
        """Filter -> bind with scheduler-style retries; every bind
        attempt runs under (and is timed against) its deadline."""
        ns, name = pod["metadata"]["namespace"], pod["metadata"]["name"]
        for attempt in range(400):
            res = fil.handle({"Pod": pod, "NodeNames": names})
            nodes = res["NodeNames"]
            if not nodes:
                if time.monotonic() > storm_end + 5.0:
                    return False
                time.sleep(0.003)
                continue
            with attempts_lock:
                attempts[0] += 1
            t0 = time.monotonic()
            with request_deadline(deadline_s):
                out = binder.handle({
                    "PodNamespace": ns, "PodName": name,
                    "PodUID": pod["metadata"]["uid"],
                    "Node": nodes[attempt % len(nodes)]})
            took = time.monotonic() - t0
            # generous slack for loaded runners: the invariant is "does
            # not burn the webhook timeout", not microsecond precision.
            # The HTTP variant measures the whole POST round-trip, which
            # also queues through the selector front end's handler pool
            # while the sampler/churner threads hold the GIL — give that
            # path wider slack or a loaded 1-core runner flakes on a
            # bind that the deadline machinery actually honored.
            slack = 3.0 if via_http else 1.0
            if took > deadline_s + slack:
                deadline_violations.append((name, took))
            if out["Error"] == "":
                return True
            time.sleep(0.002)
        return False

    # churner threads keep pod lifecycle turning over for the WHOLE
    # storm window (new pods created, bound pods completing and freeing
    # chips) — without them every pod binds in the storm's first
    # moments and the later brownout waves hit an idle scheduler
    churn_seq = [n_pods]
    churn_lock = threading.Lock()
    churn_rng = random.Random(seed ^ 0xC0FFEE)

    def churn():
        mine: list = []
        while time.monotonic() < storm_end:
            with churn_lock:
                i = churn_seq[0]
                churn_seq[0] += 1
            pod = fc.create_pod(make_pod(hbm=hbm, name=f"c{i}",
                                         ann=movable))
            if schedule(pod):
                mine.append(pod)
            if len(mine) >= 3:
                # complete the oldest: frees its chips mid-storm, so
                # the remove path churns under the same brownout
                done = mine.pop(0)
                fc.set_pod_phase("default", done["metadata"]["name"],
                                 "Succeeded")
            time.sleep(churn_rng.uniform(0.0, 0.01))

    try:
        churners = [threading.Thread(target=churn, daemon=True)
                    for _ in range(2)]
        for c in churners:
            c.start()
        with ThreadPoolExecutor(threads) as ex:
            results = list(ex.map(schedule, pods))
        for c in churners:
            c.join(timeout=storm_s + 30)
        # storm over: clear residual forever-rules so convergence and
        # the leak audit run against a healthy apiserver
        chaos.clear()
        retried = [schedule(pods[i]) for i, ok in enumerate(results)
                   if not ok]
        results = [ok for ok in results if ok] + retried
        # heal every churn pod the storm stranded (stranded annotations
        # on an unbound pod are healed by REBIND — the overwrite path —
        # not by gc, which only reclaims bound-never-started placements)
        for pod in fc.list_pods():
            if contract.is_complete_pod(pod) or \
                    pod["spec"].get("nodeName"):
                continue
            schedule(pod)
    finally:
        stop.set()
        sampler_t.join(timeout=2)
        if server is not None:
            server.stop()

    # -- post-storm healing: GC + resync, then audit -------------------------
    from tests.test_fault_containment import _plugin_for
    for n in names:
        # bound-but-never-started placements would be reclaimed here in
        # production; in the soak nothing is stale yet (all placements
        # are fresh), so gc must find nothing to kill
        _plugin_for(fc, node=n).gc_stale_assignments(
            max_pending_seconds=300.0)
    ctl.resync_once()
    ctl.drain(timeout=10.0)
    ctl.stop()

    # leak audit: apiserver truth == cache accounting, exactly. Pods
    # the churners completed keep their annotations but hold nothing —
    # their chips must be FREE (counting them would itself be the leak).
    per_chip: dict = {}
    leaked = []
    live_bound = 0
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = pod["spec"].get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if ids is None:
            continue
        if not node:
            leaked.append(pod["metadata"]["name"])
            continue
        live_bound += 1
        for cid in ids:
            per_chip[(node, cid)] = per_chip.get((node, cid), 0) + hbm
    tree = cache.describe()
    cache_mismatch = []
    for node in tree["nodes"]:
        for chip in node["chips"]:
            want = per_chip.get((node["name"], chip["idx"]), 0)
            if chip["used_hbm_mib"] != want:
                cache_mismatch.append(
                    (node["name"], chip["idx"], chip["used_hbm_mib"], want))

    writes = sum(v for (verb, _), v in stats.snapshot().items()
                 if verb in POD_WRITE_VERBS)
    defrag_state = None
    move_write_cap = 0
    if migration and server is not None:
        defrag_state = server.defrag.snapshot()
        acted = [m for m in defrag_state["recent_moves"]
                 if m.get("outcome") in ("completed", "failed")]
        # each acted-on move is a bounded extra write burst on top of
        # the bind-attempt budget: evict delete + replacement create +
        # placement patches, doubled again by a rollback, each leg
        # retried under the same policy (demoted moves write nothing)
        move_write_cap = 8 * len(acted) * policy.max_attempts
    return {
        "bound": sum(1 for ok in results if ok),
        "n_pods": n_pods,
        "attempts": attempts[0],
        "overcommit": overcommit,
        "deadline_violations": deadline_violations,
        "leaked": leaked,
        "cache_mismatch": cache_mismatch,
        "per_chip_max": max(per_chip.values(), default=0),
        "writes": writes,
        "write_cap": attempts[0] * LOGICAL_WRITES_PER_ATTEMPT
        * policy.max_attempts + move_write_cap,
        "injected": dict(chaos.injected),
        "used_total": tree["used_hbm_mib"],
        "live_bound": live_bound,
        "front_end": type(server._httpd).__name__ if server else None,
        "defrag": defrag_state,
    }


def _assert_invariants(r: dict) -> None:
    assert r["bound"] == r["n_pods"], \
        f"{r['n_pods'] - r['bound']} pods never bound: {r}"
    assert not r["overcommit"], \
        f"transient oversubscription: {r['overcommit'][:3]}"
    assert not r["deadline_violations"], \
        f"binds blew their deadline: {r['deadline_violations'][:5]}"
    assert not r["leaked"], f"leaked placements: {r['leaked']}"
    assert not r["cache_mismatch"], \
        f"cache != apiserver after resync: {r['cache_mismatch'][:5]}"
    assert r["per_chip_max"] <= HBM_PER_CHIP
    assert r["used_total"] == r["live_bound"] * 2048
    # write amplification within the retry budget
    assert r["writes"] <= r["write_cap"], \
        f"write amplification blew the budget: {r['writes']} > {r['write_cap']}"
    # the storm actually stormed
    injected = sum(r["injected"].values())
    assert injected > 0, "chaos injected nothing; the soak proved nothing"


def test_chaos_soak_fast_deterministic():
    """Tier-1 variant: one short brownout wave, fixed seed."""
    _assert_invariants(run_soak(seed=1234, storm_s=1.0, n_pods=16,
                                threads=6))


def test_chaos_soak_through_http_front_end():
    """ISSUE 13 satellite: the same storm, but every filter/bind is a
    real POST through the selector event-loop front end (the
    ``TPUSHARE_SERVER`` default, PR 11) — the HTTP layer is inside the
    brownout blast radius, and the invariants must hold unchanged."""
    r = run_soak(seed=4321, storm_s=1.0, n_pods=12, threads=6,
                 via_http=True)
    _assert_invariants(r)
    assert r["front_end"] == "SelectorHTTPServer", r["front_end"]


def test_chaos_soak_http_with_live_migration():
    """ISSUE 20 satellite: the HTTP storm with the live-migration
    rebalancer ARMED — movable pods, ``TPUSHARE_DEFRAG=1`` at a
    storm-rate period, so checkpoint-evict-restore moves run inside the
    brownout blast radius while binds race them. Every soak invariant
    (no transient oversubscription, no leaks, deadline + write budgets
    — with the bounded per-move write allowance) must hold unchanged;
    the HTTP deadline check reuses the widened 3.0 s slack, since a
    bind's POST can queue behind a move holding the same node."""
    r = run_soak(seed=2468, storm_s=1.0, n_pods=12, threads=6,
                 via_http=True, migration=True)
    _assert_invariants(r)
    assert r["front_end"] == "SelectorHTTPServer", r["front_end"]
    # the rebalancer actually ran inside the storm, and no move outcome
    # ever left the accounting torn (the invariants above prove that;
    # this proves the variant exercised the machinery at all)
    assert r["defrag"] is not None and r["defrag"]["passes"] > 0, \
        r["defrag"]


def _leg_partition_soak(fail_verb: str, seed: int) -> None:
    """ISSUE 14 satellite: the bind write path is now PIPELINED — the
    annotation PATCH and the binding POST are concurrently in flight —
    so partition exactly ONE leg mid-flight and hold the PR-13 sweep:
    zero oversubscription on apiserver truth at every instant, no pod
    left unbound with placement annotations, every bound-but-unannotated
    orphan resolved (repaired or loudly counted) within a bounded
    window, and cache == truth after resync."""
    from tpushare.cache.nodeinfo import BIND_PIPELINE

    fc = FakeCluster()
    names = ["n0", "n1"]
    for n in names:
        fc.add_tpu_node(n, chips=4, hbm_per_chip_mib=HBM_PER_CHIP,
                        mesh="2x2")
    chaos = ChaosCluster(fc, seed=seed)
    policy = RetryPolicy(max_attempts=3, base_s=0.002, cap_s=0.01,
                         rng=random.Random(seed))
    # the 8 dropped legs legitimately trip the breaker (5 consecutive
    # transport failures); scale its reset window down to this soak's
    # millisecond timescale like the retry policy above, or the
    # post-heal retries all fast-fail inside the production 5 s window
    cluster = harden(chaos, breaker=CircuitBreaker(reset_timeout_s=0.2),
                     policy=policy)
    cache = SchedulerCache(cluster)
    ctl = Controller(cluster, cache, resync_seconds=0.2)
    ctl.build_cache()
    ctl.start()
    registry = Registry()
    fil = FilterHandler(cache, registry)
    binder = BindHandler(cache, cluster, registry)
    pipeline_before = BIND_PIPELINE.snapshot()

    # the partition: ONE leg of the pipelined pair drops its transport
    # (status=0) for the first injections while the storm is in flight;
    # the OTHER leg keeps landing, which is exactly the partial-failure
    # state the pipelining introduced
    chaos.fail(fail_verb, status=0, times=8)

    overcommit: list = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            per: dict = {}
            for pod in fc.list_pods():
                if contract.is_complete_pod(pod):
                    continue
                node = pod["spec"].get("nodeName")
                ids = contract.chip_ids_from_annotations(pod)
                if not node or ids is None:
                    continue
                h = contract.hbm_from_annotations(pod)
                for c in ids:
                    per[(node, c)] = per.get((node, c), 0) + h
            for k, v in per.items():
                if v > HBM_PER_CHIP:
                    overcommit.append((k, v))
            time.sleep(0.001)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()

    hbm = 2048
    pods = [fc.create_pod(make_pod(hbm=hbm, name=f"lp{i}"))
            for i in range(8)]

    def schedule(pod) -> bool:
        ns, name = pod["metadata"]["namespace"], pod["metadata"]["name"]
        for attempt in range(60):
            # a pod our POST already bound mid-partition must not be
            # re-driven through the webhook: it IS placed
            fresh = fc.peek_pod(ns, name)
            if fresh is not None and fresh["spec"].get("nodeName"):
                return True
            res = fil.handle({"Pod": pod, "NodeNames": names})
            nodes = res["NodeNames"]
            if not nodes:
                time.sleep(0.003)
                continue
            with request_deadline(1.0):
                out = binder.handle({
                    "PodNamespace": ns, "PodName": name,
                    "PodUID": pod["metadata"]["uid"],
                    "Node": nodes[attempt % len(nodes)]})
            if out["Error"] == "":
                return True
            time.sleep(0.002)
        return False

    try:
        with ThreadPoolExecutor(4) as ex:
            results = list(ex.map(schedule, pods))
        chaos.clear()  # partition heals
        results = [ok or schedule(pods[i])
                   for i, ok in enumerate(results)]
    finally:
        stop.set()
        sampler_t.join(timeout=2)

    assert all(results), "pods never bound through the leg partition"
    assert sum(chaos.injected.values()) > 0, \
        "the partition injected nothing; this proved nothing"

    # bounded-window orphan resolution: every bind-first partial failure
    # must be RESOLVED (annotations repaired, found moot, or loudly
    # orphaned) — a repair stuck in flight past the window is a leak
    def repairs_resolved() -> bool:
        now = BIND_PIPELINE.snapshot()

        def moved(k):
            return now.get((k,), 0) - pipeline_before.get((k,), 0)
        return moved("bind_first_repair") == (
            moved("repair_ok") + moved("repair_moot")
            + moved("repair_orphaned"))
    window_end = time.monotonic() + 8.0
    while time.monotonic() < window_end and not repairs_resolved():
        time.sleep(0.02)
    assert repairs_resolved(), \
        f"async annotation repairs unresolved: {BIND_PIPELINE.snapshot()}"

    ctl.resync_once()
    ctl.drain(timeout=10.0)
    ctl.stop()

    assert not overcommit, f"oversubscription under leg partition: " \
        f"{overcommit[:3]}"
    # truth sweep: no unbound pod may carry placement annotations, and
    # bound+annotated pods must account for every cache-held chip
    per_chip: dict = {}
    for pod in fc.list_pods():
        if contract.is_complete_pod(pod):
            continue
        node = pod["spec"].get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if ids is None:
            continue  # an orphaned bound pod was already counted above
        assert node, \
            f"unbound pod {pod['metadata']['name']} kept annotations"
        for cid in ids:
            per_chip[(node, cid)] = per_chip.get((node, cid), 0) + hbm
    assert max(per_chip.values(), default=0) <= HBM_PER_CHIP
    tree = cache.describe()
    for node in tree["nodes"]:
        for chip in node["chips"]:
            want = per_chip.get((node["name"], chip["idx"]), 0)
            assert chip["used_hbm_mib"] == want, (
                node["name"], chip["idx"], chip["used_hbm_mib"], want)


def test_pipelined_bind_leg_partition_post_leg():
    """The binding POST leg is partitioned: the PATCH lands, the POST
    dies — the allocator must roll back and the retry must converge."""
    _leg_partition_soak("bind_pod", seed=140001)


def test_pipelined_bind_leg_partition_patch_leg():
    """The annotation PATCH leg is partitioned: the POST lands first —
    forward-only repair territory (a bound pod's chips must never be
    rolled back), healed asynchronously once the partition lifts."""
    _leg_partition_soak("patch_pod", seed=140002)


@pytest.mark.slow
def test_chaos_soak_rolling_brownout():
    """The full soak: three rolling brownout waves over several seconds,
    more pods, more threads — the breaker opens and recovers repeatedly
    while binds keep resolving within their deadlines."""
    r = run_soak(seed=20260804, storm_s=6.0, n_pods=48, n_nodes=4,
                 threads=10, waves=3)
    _assert_invariants(r)
    # the long storm must have exercised the containment layer hard
    assert r["injected"].get("patch_pod", 0) + \
        r["injected"].get("bind_pod", 0) > 20
