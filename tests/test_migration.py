"""ISSUE 20: live slice migration — checkpoint-driven repack.

Four layers of the tentpole under test:

- the chaos migration drill as a tier-1 gate: a whole-slice move under
  mid-move crashes (serve replica dying mid-checkpoint, apiserver write
  lost mid-placement) must leave ZERO oversubscription and ZERO
  half-moved slices, always converging back to the source geometry;
- pause-budget enforcement on a FAKE clock: a checkpoint that blows
  ``TPUSHARE_MIGRATE_PAUSE_BUDGET_S`` aborts the move before any
  apiserver write, with the serve loop resumed and no real sleeping;
- the all-or-nothing property: a planned slice move demotes WHOLE under
  randomized member-stamp churn (demote-don't-race) — no partial
  ``TPU_PROCESS_BOUNDS`` recomposition, zero writes, zero pauses;
- the FragForecast pressure scalar and the wind-tunnel A/B
  (``sweep_forecast``): forecast policy holds stranded capacity below
  target with strictly fewer migrations than react-only defrag.
"""

from __future__ import annotations

import random
import time

import pytest

from tpushare.chaos.migration_drill import (
    _Rig,
    _solo_pod,
    assert_migration_drill_invariants,
    half_moved_slices,
    run_migration_drill,
)
from tpushare.contract import pod as podlib
from tpushare.defrag.executor import DefragExecutor
from tpushare.defrag.forecast import FragForecast, frag_weight_knob
from tpushare.defrag.migration import (
    PAUSE_SECONDS,
    MigrationSession,
    Migrator,
    PauseBudgetExceeded,
)
from tpushare.metrics import Registry
from tpushare.sim.defrag import sweep_budgets, sweep_forecast


# -- the chaos drill, tier-1 --------------------------------------------------


def test_migration_drill_holds_tentpole_invariants():
    """Completed control move + both crash scenarios: zero
    oversubscription at every sampled instant, zero half-moved slices,
    crashes roll back byte-identically, no serve loop left paused."""
    assert_migration_drill_invariants(run_migration_drill())


# -- pause budget on a fake clock ---------------------------------------------


class _Clock:
    """Monotonic stand-in the checkpointer advances by hand."""

    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


class _Frontend:
    def __init__(self) -> None:
        self.paused = False
        self.pauses = 0

    def pause(self, timeout: float) -> bool:
        self.paused = True
        self.pauses += 1
        return True

    def resume(self) -> None:
        self.paused = False


class _SlowCheckpointer:
    """save() consumes fake-clock time — a checkpoint whose drain rate
    the budget must police."""

    def __init__(self, clock: _Clock, save_s: float) -> None:
        self._clock = clock
        self._save_s = save_s
        self.saves = 0
        self.restores = 0

    def save(self, pod, move) -> None:
        self._clock.now += self._save_s
        self.saves += 1

    def restore(self, pod, move) -> None:
        self.restores += 1


def test_session_over_budget_checkpoint_aborts_and_resumes():
    clock = _Clock()
    fe = _Frontend()
    ckpt = _SlowCheckpointer(clock, save_s=7.5)
    sess = MigrationSession({"metadata": {"name": "v"}}, move=None,
                            checkpointer=ckpt, frontend=fe,
                            budget_s=5.0, time_fn=clock)
    before = PAUSE_SECONDS.count
    with pytest.raises(PauseBudgetExceeded):
        sess.begin()
    # aborted strictly before restore, serve loop lifted, pause
    # published exactly once even through idempotent abort()s
    assert ckpt.saves == 1 and ckpt.restores == 0
    assert fe.pauses == 1 and not fe.paused
    assert PAUSE_SECONDS.count == before + 1
    sess.abort()
    sess.abort()
    assert PAUSE_SECONDS.count == before + 1


def test_session_under_budget_commits_and_observes_once():
    clock = _Clock()
    fe = _Frontend()
    ckpt = _SlowCheckpointer(clock, save_s=2.0)
    sess = MigrationSession({"metadata": {"name": "v"}}, move=None,
                            checkpointer=ckpt, frontend=fe,
                            budget_s=5.0, time_fn=clock)
    before = PAUSE_SECONDS.count
    sess.begin()
    assert fe.paused  # parked across the apiserver window
    sess.commit()
    assert ckpt.restores == 1 and not fe.paused
    assert PAUSE_SECONDS.count == before + 1
    assert sess.pause_s == pytest.approx(2.0)


def test_blown_pause_budget_rolls_slice_move_back_untouched():
    """Executor-level: the slice move fails with the gang byte-identical
    on its source chips, and the fake clock proves nobody slept."""
    rig = _Rig()
    clock = _Clock()
    slow = _SlowCheckpointer(clock, save_s=60.0)
    rig.migrator = Migrator(
        checkpointer=slow,
        frontend_for=lambda p: rig.frontends.get(podlib.pod_name(p)),
        budget_s=1.0, time_fn=clock)
    rig.executor = DefragExecutor(rig.cache, rig.cluster, budget=8,
                                  migrator=rig.migrator)
    plan = rig.planner.plan(4)
    assert plan.slice_moves, "planner produced no slice move"
    before = rig.snapshot()
    t0 = time.monotonic()
    out = rig.executor.execute_slice_move(plan.slice_moves[0])
    assert time.monotonic() - t0 < 5.0, "budget must not be slept out"
    assert out["outcome"] == "failed"
    assert "budget" in out["error"]
    assert rig.snapshot() == before
    assert slow.restores == 0
    assert not any(fe.paused for fe in rig.frontends.values())
    assert half_moved_slices(rig.fc.list_pods()) == []


# -- all-or-nothing under stamp churn -----------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_slice_move_all_or_nothing_under_stamp_churn(seed):
    """Between plan and execute, churn ONE random member's source or
    target node (any cache mutation bumps its generation stamp). The
    whole slice must demote with zero writes — never a partially
    recomposed TPU_PROCESS_BOUNDS."""
    rng = random.Random(seed)
    rig = _Rig()
    plan = rig.planner.plan(4)
    assert plan.slice_moves, "planner produced no slice move"
    smove = plan.slice_moves[0]
    member = rng.choice(smove.members)
    node = member.source if rng.random() < 0.5 else member.target
    before = rig.snapshot()
    # the churn: one unrelated pod lands on (or leaves) the node —
    # exactly what a concurrent bind does to a stamp
    churn = _solo_pod(f"churn-{seed}", node, [0], 64)
    rig.cache.add_or_update_pod(churn)
    out = rig.executor.execute_slice_move(smove)
    assert out["outcome"] == "demoted", \
        f"churned {node}, expected demotion, got {out}"
    # zero writes: no member touched, no session ever opened
    assert rig.snapshot() == before
    assert rig.ckpt.saved == [] and rig.ckpt.restored == []
    assert not any(fe.pauses for fe in rig.frontends.values())
    assert half_moved_slices(rig.fc.list_pods()) == []
    # every member still whole on its source geometry
    for p, m in zip(rig.member_pods(), smove.members):
        assert podlib.pod_node_name(p) == m.source
        assert podlib.chip_ids_from_annotations(p) == m.source_chip_ids


# -- the forecast -------------------------------------------------------------


def _sample(total=100_000, stranded=0, nodes=()):
    return {"total_hbm_mib": total,
            "tiers": {"best-effort": {"stranded_hbm_mib": stranded}},
            "top_fragmented": [{"node": n} for n in nodes]}


def test_forecast_pressure_zero_on_clean_fleet():
    f = FragForecast()
    assert f.pressure() == 0.0  # never sampled
    f.observe(_sample(stranded=0))
    assert f.pressure() == 0.0
    assert f.fragmented_nodes() == frozenset()


def test_forecast_level_and_slope():
    f = FragForecast()
    # 5% of fleet HBM stranded -> level 8 * 0.05 = 0.4, flat trend
    f.observe(_sample(stranded=5_000, nodes=("n3",)))
    assert f.pressure() == pytest.approx(0.4)
    assert f.fragmented_nodes() == frozenset({"n3"})
    # worsening trend adds the bounded slope boost on top of the level
    f2 = FragForecast()
    f2.observe(_sample(stranded=1_000))
    f2.observe(_sample(stranded=5_000))
    assert f2.pressure() == pytest.approx(0.4 + 8.0 * 0.04)
    # the boost saturates at _SLOPE_BOOST, the sum at 1.0
    f3 = FragForecast()
    f3.observe(_sample(stranded=0))
    f3.observe(_sample(stranded=50_000))
    assert f3.pressure() == 1.0


def _tier_pod(tier):
    from tpushare import contract
    return {"metadata": {"annotations": {contract.ANN_QOS_TIER: tier}}}


def test_forecast_weight_tier_ordering_and_escape_hatch(monkeypatch):
    f = FragForecast()
    f.observe(_sample(stranded=5_000))
    monkeypatch.setenv("TPUSHARE_FRAG_WEIGHT", "1.0")
    assert frag_weight_knob() == 1.0
    wg = f.weight(_tier_pod("guaranteed"))
    wb = f.weight(_tier_pod("burstable"))
    we = f.weight(_tier_pod("best-effort"))
    # best-effort soaks holes hardest, guaranteed keeps its binpack
    assert 0.0 < wg < wb < we <= 1.0
    # the escape hatch: knob 0 zeroes the blend for every tier
    monkeypatch.setenv("TPUSHARE_FRAG_WEIGHT", "0")
    assert f.weight(_tier_pod("best-effort")) == 0.0


def test_forecast_attach_registers_pressure_gauge():
    f = FragForecast()
    f.observe(_sample(stranded=5_000))
    reg = Registry()
    f.attach(reg)
    text = reg.expose()
    assert "tpushare_frag_pressure 0.4" in text


# -- the wind tunnel ----------------------------------------------------------


def test_sweep_forecast_fewer_migrations_below_target():
    """The tentpole's A/B on the default trace: the forecast policy
    performs STRICTLY fewer migrations than react-only defrag while
    holding average stranded capacity below the target."""
    r = sweep_forecast()
    v = r["verdict"]
    assert v["fewer_migrations"], v
    assert v["stranded_held_below_target"], v
    assert v["forecast_moves"] < v["react_moves"]
    # every forecast migration still pays a modeled pause
    fore = r["forecast"]
    assert fore["migration"]["pauses"] == fore["moves"]


def test_defrag_sim_frag_weight_zero_is_reference_policy():
    """frag_weight=0 must reproduce the pre-migration budget sweep
    exactly (the byte-identical escape hatch), with the migration
    telemetry riding along."""
    reports = sweep_budgets(budgets=(0, 2))
    control, repack = reports
    assert control["moves"] == 0 and control["frag_weight"] == 0.0
    # the seed-7 regression pin from the pre-forecast sweep
    assert repack["moves"] == 39
    assert repack["recovery_pct"] == pytest.approx(18.87, abs=0.01)
    for rep in reports:
        mig = rep["migration"]
        assert mig["pauses"] == rep["moves"]
        assert mig["aborted_over_budget"] == 0
        assert (mig["pause_p99_s"] >= mig["pause_p50_s"] >= 0.0)


def test_defrag_sim_pause_budget_aborts_over_budget_moves():
    """A pause budget below the modeled floor forbids every move: the
    sim aborts them all instead of clipping the pause."""
    r = sweep_forecast(pause_budget_s=0.01)
    fore = r["forecast"]
    assert fore["moves"] == 0
    assert fore["migration"]["aborted_over_budget"] > 0
    assert fore["migration"]["pauses"] == 0
