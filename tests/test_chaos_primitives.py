"""Chaos primitives (ISSUE 13 satellite): ``break_watches()`` and
node-scoped ``partition()`` on both hermetic backends (FakeCluster and
the wire-format StubApiServer), plus the direct informer proof — a
stream severed mid-storm heals by relist (tpushare_informer_relists_total
rises) and the lister ends byte-equal to cluster truth: no drift."""

import time

import pytest

from tests.test_contract import make_pod
from tpushare.k8s import FakeCluster
from tpushare.k8s.client import ApiError
from tpushare.k8s.incluster import InClusterClient
from tpushare.k8s.informer import INFORMER_RELISTS, Informer
from tpushare.k8s.stubapi import StubApiServer


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class _NoJitter:
    """Deterministic zero-backoff rng for the informer under test."""

    @staticmethod
    def uniform(_a, _b):
        return 0.0


# -- FakeCluster primitives ----------------------------------------------------


def test_break_watches_counts_and_severs_live_streams():
    fc = FakeCluster()
    assert fc.break_watches() == 0  # no streams, nothing severed
    informer = Informer(fc, rng=_NoJitter())
    informer.start()
    try:
        assert wait_until(lambda: sum(
            len(qs) for qs in fc._watchers.values()) == 2)
        assert fc.break_watches() == 2  # pods + nodes
    finally:
        informer.stop()


def test_partition_gates_node_verbs_and_heals():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.add_tpu_node("n2", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    fc.create_pod(make_pod(hbm=1000, name="p1"))
    fc.partition("n1")
    for op in (lambda: fc.get_node("n1"),
               lambda: fc.patch_node("n1", {"metadata": {}}),
               lambda: fc.bind_pod("default", "p1", "n1")):
        with pytest.raises(ApiError) as ei:
            op()
        assert ei.value.status == 503
    # the partition is node-scoped: the rest of the fleet is reachable
    assert fc.get_node("n2")["metadata"]["name"] == "n2"
    fc.bind_pod("default", "p1", "n2")
    fc.heal("n1")
    assert fc.get_node("n1")["metadata"]["name"] == "n1"


def test_heal_all_clears_every_partition():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=1, hbm_per_chip_mib=100)
    fc.add_tpu_node("n2", chips=1, hbm_per_chip_mib=100)
    fc.partition("n1")
    fc.partition("n2")
    fc.heal()
    assert {n["metadata"]["name"] for n in (fc.get_node("n1"),
                                            fc.get_node("n2"))} == \
        {"n1", "n2"}


# -- the informer sever proof (the satellite's point) --------------------------


def test_informer_sever_mid_storm_relists_and_converges():
    """Sever the watch streams while pods are landing: events queued
    behind the sever are LOST (the k8s watch API does not replay gaps),
    so only the backoff->relist path can re-ground the store. The
    relist counter must rise and the lister must end exactly equal to
    cluster truth."""
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    informer = Informer(fc, rng=_NoJitter())
    informer.start()
    before = (INFORMER_RELISTS.get("pods"), INFORMER_RELISTS.get("nodes"))
    try:
        fc.create_pod(make_pod(hbm=1000, name="pre", node="n1"))
        assert wait_until(lambda: informer.pods.get("default", "pre"))
        assert fc.break_watches() == 2
        # the storm keeps going while the streams are down: these events
        # race the sever and may be lost — relist is the only guarantee
        fc.add_tpu_node("n2", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
        for i in range(8):
            fc.create_pod(make_pod(hbm=500, name=f"mid{i}", node="n1"))
        fc.delete_pod("default", "pre")
        assert wait_until(lambda: INFORMER_RELISTS.get("pods") > before[0]
                          and INFORMER_RELISTS.get("nodes") > before[1])
        # convergence: the lister matches apiserver truth exactly
        truth = {(p["metadata"]["namespace"], p["metadata"]["name"])
                 for p in fc.list_pods()}
        assert wait_until(lambda: len(informer.pods) == len(truth) and all(
            informer.pods.get(ns, n) is not None for ns, n in truth))
        assert informer.pods.get("default", "pre") is None  # no drift
        assert set(informer.nodes.names()) == {"n1", "n2"}
        # the severed pods index healed too (on_node is the bind path)
        assert len(informer.pods.on_node("n1")) == 8
    finally:
        informer.stop()


# -- StubApiServer parity over the real wire -----------------------------------


@pytest.fixture
def stub():
    s = StubApiServer().start()
    yield s
    s.stop()


def test_stub_partition_gates_node_verbs_over_the_wire(stub):
    from tests.test_contract import make_node
    client = InClusterClient(base_url=stub.base_url, timeout=5.0)
    stub.seed("nodes", make_node("n1", hbm=64000, count=4))
    stub.seed("nodes", make_node("n2", hbm=64000, count=4))
    stub.seed("pods", make_pod(hbm=1000, name="p1", uid="u1"))
    stub.partition("n1")
    for op in (lambda: client.get_node("n1"),
               lambda: client.patch_node("n1", {"metadata": {
                   "labels": {"x": "y"}}}),
               lambda: client.bind_pod("default", "p1", "n1", uid="u1")):
        with pytest.raises(ApiError) as ei:
            op()
        assert ei.value.status == 503
    assert client.get_node("n2")["metadata"]["name"] == "n2"
    stub.heal("n1")
    assert client.get_node("n1")["metadata"]["name"] == "n1"
    client.bind_pod("default", "p1", "n1", uid="u1")
    assert stub.get("pods", "default/p1")["spec"]["nodeName"] == "n1"


def test_stub_break_watches_severs_then_stream_heals(stub):
    """break_watches() is the FakeCluster-parity verb: live streams are
    reset, the client reconnects, and post-sever events still arrive."""
    import threading

    client = InClusterClient(base_url=stub.base_url, timeout=5.0)
    events = []
    stop = threading.Event()
    t = threading.Thread(
        target=lambda: events.extend(client.watch_pods(stop)), daemon=True)
    t.start()
    try:
        assert wait_until(lambda: stub.watch_count() > 0)
        assert stub.break_watches() == 1
        stub.seed("pods", make_pod(name="after-sever"))
        assert wait_until(lambda: any(
            e.object["metadata"]["name"] == "after-sever" for e in events))
    finally:
        stop.set()
        t.join(timeout=5)
