"""Leader-election tests: single winner, failover, renewal, bind gating.

Short lease durations keep these fast; all timing waits are generous
upper bounds, not exact schedules.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.extender.server import ExtenderServer
from tpushare.ha import LeaderElector
from tpushare.k8s import ApiError, FakeCluster


def elector(fc, ident, **kw):
    kw.setdefault("lease_duration", 0.6)
    kw.setdefault("renew_period", 0.1)
    kw.setdefault("retry_period", 0.05)
    return LeaderElector(fc, ident, **kw)


def wait_until(fn, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.02)
    return False


def test_single_candidate_acquires():
    fc = FakeCluster()
    a = elector(fc, "a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        lease = fc.get_lease("kube-system", "tpushare-schd-extender")
        assert lease["spec"]["holderIdentity"] == "a"
    finally:
        a.stop()


def test_exactly_one_of_two_leads():
    fc = FakeCluster()
    a, b = elector(fc, "a"), elector(fc, "b")
    a.start()
    b.start()
    try:
        assert wait_until(lambda: a.is_leader() or b.is_leader())
        time.sleep(0.3)  # several renew cycles
        assert a.is_leader() != b.is_leader()  # never both
    finally:
        a.stop()
        b.stop()


def test_failover_on_leader_stop():
    fc = FakeCluster()
    a, b = elector(fc, "a"), elector(fc, "b")
    a.start()
    assert wait_until(a.is_leader)
    b.start()
    try:
        time.sleep(0.2)
        assert not b.is_leader()
        a.stop()  # abdicates (clears holder)
        assert wait_until(b.is_leader, timeout=3.0)
        lease = fc.get_lease("kube-system", "tpushare-schd-extender")
        assert lease["spec"]["holderIdentity"] == "b"
    finally:
        b.stop()


def test_takeover_after_expiry_without_abdication():
    fc = FakeCluster()
    a = elector(fc, "a")
    a.start()
    assert wait_until(a.is_leader)
    # simulate a crash: thread killed without releasing the lease
    a._stop.set()
    a._thread.join(timeout=2)
    b = elector(fc, "b")
    b.start()
    try:
        # b must wait out the lease duration, then win
        assert wait_until(b.is_leader, timeout=3.0)
    finally:
        b.stop()


def test_renewal_keeps_leadership():
    fc = FakeCluster()
    a = elector(fc, "a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        time.sleep(1.0)  # > lease_duration: only renewal explains survival
        assert a.is_leader()
    finally:
        a.stop()


def test_update_lease_conflict_semantics():
    fc = FakeCluster()
    fc.create_lease("kube-system", "l", {"holderIdentity": "x"})
    lease = fc.get_lease("kube-system", "l")
    rv = lease["metadata"]["resourceVersion"]
    fc.update_lease("kube-system", "l", {"holderIdentity": "y"},
                    resource_version=rv)
    with pytest.raises(ApiError) as e:  # stale rv loses
        fc.update_lease("kube-system", "l", {"holderIdentity": "z"},
                        resource_version=rv)
    assert e.value.is_conflict


def test_partitioned_leader_steps_down_after_renew_deadline():
    fc = FakeCluster()

    class Flaky:
        def __init__(self, inner):
            self._inner = inner
            self.partitioned = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def get_lease(self, ns, name):
            if self.partitioned:
                raise ApiError(0, "apiserver unreachable")
            return self._inner.get_lease(ns, name)

    flaky = Flaky(fc)
    a = elector(flaky, "a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        flaky.partitioned = True  # this replica alone loses the apiserver
        # must step down once it can't renew within lease_duration —
        # otherwise it would serve Bind alongside the next elected leader
        assert wait_until(lambda: not a.is_leader(), timeout=5.0)
    finally:
        a.stop()


def test_callback_exception_does_not_kill_election():
    fc = FakeCluster()
    boom = {"n": 0}

    def exploding_callback():
        boom["n"] += 1
        raise RuntimeError("callback boom")

    a = elector(fc, "a", on_started_leading=exploding_callback)
    a.start()
    try:
        assert wait_until(a.is_leader)
        time.sleep(0.5)  # several renew cycles after the exploding callback
        assert a.is_leader()  # election loop survived
        assert boom["n"] == 1
    finally:
        a.stop()


def test_non_leader_503_keeps_keepalive_connection_clean():
    # the 503 must drain the request body: on a reused HTTP/1.1 connection
    # leftover bytes would parse as the next request line
    import http.client

    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16000)
    cache = SchedulerCache(fc)
    cache.build_cache()

    class NeverLeader:
        identity = "r2"

        def is_leader(self):
            return False

    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0,
                            elector=NeverLeader())
    port = server.start()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        body = json.dumps({"PodName": "p", "PodNamespace": "default",
                           "PodUID": "u", "Node": "n1"})
        for _ in range(3):  # same connection, repeatedly
            conn.request("POST", "/tpushare-scheduler/bind", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 503
            assert "not the leader" in json.loads(resp.read())["Error"]
    finally:
        conn.close()
        server.stop()


def test_non_leader_replica_rejects_bind_serves_filter():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16000)
    cache = SchedulerCache(fc)
    cache.build_cache()

    class NeverLeader:
        identity = "replica-2"

        def is_leader(self):
            return False

    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0,
                            elector=NeverLeader())
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        # filter still served from the local cache
        req = urllib.request.Request(
            f"{base}/tpushare-scheduler/filter",
            data=json.dumps({"Pod": make_pod(hbm=100),
                             "NodeNames": ["n1"]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["NodeNames"] == ["n1"]
        # bind rejected with a retryable 503
        created = fc.create_pod(make_pod(hbm=100, name="p"))
        req = urllib.request.Request(
            f"{base}/tpushare-scheduler/bind",
            data=json.dumps({"PodName": "p", "PodNamespace": "default",
                             "PodUID": created["metadata"]["uid"],
                             "Node": "n1"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 503
        assert "not the leader" in json.loads(e.value.read())["Error"]
        # /version reports the HA state
        with urllib.request.urlopen(f"{base}/version", timeout=5) as r:
            v = json.loads(r.read())
        assert v["leader"] is False and v["identity"] == "replica-2"
    finally:
        server.stop()
