"""InClusterClient integration tests against a stub apiserver speaking the
real wire format (tpushare/k8s/stubapi.py).

This is the coverage VERDICT r1 called out as missing: the watch stream
parser (bookmarks, ERROR-410 restart, mid-stream disconnect reconnect),
strategic-merge PATCH, the pods/binding subresource, lease CAS, and
SA-token rotation — the exact code paths that only break against a real
apiserver (reference client-go behaviors, /root/reference/cmd/main.go:32-50)
— plus the full SchedulerCache + Controller + ExtenderServer stack driven
end to end over HTTP.
"""

import json
import threading
import time
import urllib.request

import pytest

from tests.test_contract import make_node, make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.server import ExtenderServer
from tpushare.k8s.client import ApiError
from tpushare.k8s.incluster import InClusterClient
from tpushare.k8s.stubapi import StubApiServer


@pytest.fixture
def stub():
    s = StubApiServer().start()
    yield s
    s.stop()


@pytest.fixture
def client(stub):
    return InClusterClient(base_url=stub.base_url, timeout=5.0)


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# -- CRUD + wire semantics -----------------------------------------------------


def test_crud_and_strategic_merge(stub, client):
    stub.seed("pods", make_pod(hbm=2048, name="p1", node="n1"))
    stub.seed("nodes", make_node("n1", hbm=64000, count=4))

    assert [p["metadata"]["name"] for p in client.list_pods()] == ["p1"]
    assert client.get_node("n1")["metadata"]["name"] == "n1"

    # strategic merge: annotations merge without clobbering siblings
    client.patch_pod("default", "p1", {"metadata": {"annotations": {"a": "1"}}})
    client.patch_pod("default", "p1", {"metadata": {"annotations": {"b": "2"}}})
    ann = client.get_pod("default", "p1")["metadata"]["annotations"]
    assert ann["a"] == "1" and ann["b"] == "2"

    # node status patch hits the /status subresource path
    client.patch_node("n1", {"status": {"capacity": {"x": "9"}}}, status=True)
    assert client.get_node("n1")["status"]["capacity"]["x"] == "9"

    # configmap PUT falls back to POST on 404, then updates in place
    client.put_configmap("kube-system", "cm1", {"k": "v1"})
    client.put_configmap("kube-system", "cm1", {"k": "v2"})
    assert client.get_configmap("kube-system", "cm1")["data"]["k"] == "v2"

    with pytest.raises(ApiError) as ei:
        client.get_pod("default", "ghost")
    assert ei.value.is_not_found

    # node-scoped LIST via apiserver fieldSelector (device-plugin hot path)
    stub.seed("pods", make_pod(name="other", node="n2"))
    assert {p["metadata"]["name"]
            for p in client.list_pods(node_name="n1")} == {"p1"}
    assert len(client.list_pods()) == 2


def test_binding_subresource_and_uid_conflict(stub, client):
    created = stub.seed("pods", make_pod(hbm=1, name="p1", uid="uid-a"))
    with pytest.raises(ApiError) as ei:
        client.bind_pod("default", "p1", "n1", uid="uid-WRONG")
    assert ei.value.is_conflict
    client.bind_pod("default", "p1", "n1", uid="uid-a")
    assert stub.get("pods", "default/p1")["spec"]["nodeName"] == "n1"
    # double bind is a conflict, like the real apiserver
    with pytest.raises(ApiError) as ei:
        client.bind_pod("default", "p1", "n2", uid="uid-a")
    assert ei.value.is_conflict
    del created


def test_lease_optimistic_concurrency(stub, client):
    lease = client.create_lease("kube-system", "tpushare-leader",
                                {"holderIdentity": "a"})
    rv = lease["metadata"]["resourceVersion"]
    # CAS with the right rv wins
    updated = client.update_lease("kube-system", "tpushare-leader",
                                  {"holderIdentity": "b"},
                                  resource_version=rv)
    assert updated["spec"]["holderIdentity"] == "b"
    # replaying the stale rv loses with 409 — the leader-election guard
    with pytest.raises(ApiError) as ei:
        client.update_lease("kube-system", "tpushare-leader",
                            {"holderIdentity": "c"}, resource_version=rv)
    assert ei.value.is_conflict


def test_bearer_token_rotation(tmp_path, stub):
    stub.token = "tok-v1"
    tok = tmp_path / "token"
    tok.write_text("tok-v1")
    client = InClusterClient(base_url=stub.base_url, timeout=5.0,
                             token_file=str(tok))
    stub.seed("nodes", make_node("n1"))
    assert client.get_node("n1")["metadata"]["name"] == "n1"

    # kubelet rotates the projected SA token; client must re-read per
    # request (incluster.py:_auth_header)
    stub.token = "tok-v2"
    with pytest.raises(ApiError) as ei:
        client.get_node("n1")
    assert ei.value.status == 401
    tok.write_text("tok-v2")
    assert client.get_node("n1")["metadata"]["name"] == "n1"


# -- watch protocol ------------------------------------------------------------


class WatchCollector:
    def __init__(self, client, stub, what="pods"):
        self.events = []
        self.stop = threading.Event()
        self._stub = stub
        watch = getattr(client, f"watch_{what}")
        self._thread = threading.Thread(
            target=lambda: self.events.extend(watch(self.stop)), daemon=True)

    def __enter__(self):
        self._thread.start()
        # watches start at the current rv (real apiserver semantics), so
        # wait for attachment before the test seeds objects
        assert wait_until(lambda: self._stub.watch_count() > 0)
        return self

    def __exit__(self, *exc):
        self.stop.set()
        self._thread.join(timeout=5)

    def names(self):
        return [e.object["metadata"]["name"] for e in self.events]


def test_watch_stream_with_bookmarks(stub, client):
    with WatchCollector(client, stub) as w:
        stub.seed("pods", make_pod(name="w1"))
        assert wait_until(lambda: "w1" in w.names())
        # BOOKMARK events advance rv but must not surface as WatchEvents
        stub.inject_bookmark()
        time.sleep(0.2)
        assert w.names() == ["w1"]
        client.patch_pod("default", "w1",
                         {"metadata": {"annotations": {"x": "1"}}})
        assert wait_until(lambda: len(w.events) == 2)
        assert w.events[1].type == "MODIFIED"


def test_watch_survives_410_gone(stub, client):
    stub.gone_on_next_watch()
    with WatchCollector(client, stub) as w:
        # first connection eats the ERROR 410 and reconnects fresh
        stub.seed("pods", make_pod(name="after-gone"))
        assert wait_until(lambda: "after-gone" in w.names())


def test_watch_survives_midstream_disconnect(stub, client):
    with WatchCollector(client, stub) as w:
        stub.seed("pods", make_pod(name="before"))
        assert wait_until(lambda: "before" in w.names())
        stub.drop_watch_connections()  # abrupt reset, no terminal chunk
        stub.seed("pods", make_pod(name="after"))
        assert wait_until(lambda: "after" in w.names())


def test_watch_resumes_from_rv_after_clean_close(stub, client):
    """Server ends each stream after 1 event; the client must resume from
    the last seen resourceVersion and lose nothing."""
    stub.close_watch_after(1)
    with WatchCollector(client, stub) as w:
        for i in range(3):
            stub.seed("pods", make_pod(name=f"p{i}"))
        assert wait_until(lambda: len(w.events) >= 3)
        assert w.names() == ["p0", "p1", "p2"]


def test_leader_election_over_the_wire(stub):
    """Two elector replicas CAS the same Lease through the stub apiserver:
    exactly one leads, and stopping it fails over to the other — the
    wire-level version of tests/test_ha.py's fake-cluster coverage."""
    from tpushare.ha.leaderelection import LeaderElector

    c1 = InClusterClient(base_url=stub.base_url, timeout=5.0)
    c2 = InClusterClient(base_url=stub.base_url, timeout=5.0)
    e1 = LeaderElector(c1, identity="r1", lease_duration=1.0,
                       renew_period=0.2, retry_period=0.05)
    e2 = LeaderElector(c2, identity="r2", lease_duration=1.0,
                       renew_period=0.2, retry_period=0.05)
    e1.start()
    e2.start()
    try:
        assert wait_until(lambda: e1.is_leader() ^ e2.is_leader())
        leader, follower = (e1, e2) if e1.is_leader() else (e2, e1)
        leader.stop()  # abdicates; follower must take over via lease CAS
        assert wait_until(follower.is_leader, timeout=15.0)
        lease = stub.get("leases", "kube-system/tpushare-schd-extender")
        assert lease is not None
        assert lease["spec"]["holderIdentity"] == follower.identity
    finally:
        e1.stop()
        e2.stop()


# -- the full stack over the wire ---------------------------------------------


def post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def test_full_stack_schedules_over_the_wire(stub, client):
    """SchedulerCache + Controller + ExtenderServer run against the stub
    exactly as they would against a real apiserver: filter + bind over
    HTTP, annotations and binding land via PATCH/POST, pod completion
    observed via the watch frees the chips."""
    stub.seed("nodes", make_node("n1", hbm=64000, count=4, mesh="2x2"))
    cache = SchedulerCache(client)
    ctl = Controller(client, cache, resync_seconds=1.0)
    ctl.build_cache()
    ctl.start()
    server = ExtenderServer(cache, client, host="127.0.0.1", port=0)
    port = server.start()
    base = f"http://127.0.0.1:{port}/tpushare-scheduler"
    try:
        pod = stub.seed("pods", make_pod(hbm=2000, name="w1", uid="uid-w1"))
        status, result = post(f"{base}/filter",
                              {"Pod": pod, "NodeNames": ["n1"]})
        assert status == 200 and result["NodeNames"] == ["n1"]

        status, result = post(f"{base}/bind", {
            "PodName": "w1", "PodNamespace": "default",
            "PodUID": "uid-w1", "Node": "n1"})
        assert status == 200 and result["Error"] == ""

        bound = stub.get("pods", "default/w1")
        assert bound["spec"]["nodeName"] == "n1"
        assert contract.hbm_from_annotations(bound) == 2000
        chip = (contract.chip_ids_from_annotations(bound) or [None])[0]
        assert chip is not None

        # inspect over the wire reflects the allocation
        with urllib.request.urlopen(f"{base}/inspect", timeout=5) as r:
            tree = json.loads(r.read())
        node = tree["nodes"][0]
        assert node["name"] == "n1"
        assert any(d["used_hbm_mib"] == 2000 for d in node["chips"])

        # pod completes -> watch event -> controller frees the chips
        done = json.loads(json.dumps(stub.get("pods", "default/w1")))
        done["status"]["phase"] = "Succeeded"
        with stub.state.lock:
            stub.state.commit("pods", "MODIFIED", done, "default/w1")
        assert wait_until(
            lambda: cache.get_node_info("n1").describe()["used_hbm_mib"] == 0)
    finally:
        server.stop()
        ctl.stop()
