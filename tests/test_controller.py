"""Sync-controller integration tests against the FakeCluster watch streams.

These mirror the reference's informer-driven lifecycle (SURVEY §3.5):
bind-time cache updates become durable, completed pods free chips without an
explicit deallocate, deletions clean up via the stashed copy, and the
unhealthy-chip configmap flows into the fit check.
"""

import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.controller.controller import parse_unhealthy
from tpushare.k8s import FakeCluster


@pytest.fixture
def rig():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    ctl.start()
    yield fc, cache, ctl
    ctl.stop()


def wait_until(fn, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.01)
    return False


def test_parse_unhealthy():
    assert parse_unhealthy({"chips": "0, 2,junk,5"}) == {0, 2, 5}
    assert parse_unhealthy({"chips": ""}) == set()
    assert parse_unhealthy(None) == set()
    assert parse_unhealthy({}) == set()


def test_bound_annotated_pod_enters_cache(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="p"))
    info.allocate(pod, fc)  # extender bind path writes annotations + binding
    assert wait_until(
        lambda: cache.known_pod(pod["metadata"]["uid"]))
    assert info.describe()["used_hbm_mib"] == 2000


def test_completed_pod_frees_chips(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="p"))
    info.allocate(pod, fc)
    assert wait_until(lambda: cache.known_pod(pod["metadata"]["uid"]))
    fc.set_pod_phase("default", "p", "Succeeded")
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 0)
    assert not cache.known_pod(pod["metadata"]["uid"])


def test_deleted_pod_cleans_cache_via_stash(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="p"))
    info.allocate(pod, fc)
    assert wait_until(lambda: cache.known_pod(pod["metadata"]["uid"]))
    fc.delete_pod("default", "p")
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 0)


def test_externally_annotated_pod_discovered(rig):
    # a pod bound+annotated by ANOTHER extender replica must enter the cache
    fc, cache, ctl = rig
    ann = contract.placement_annotations([3], 4000, 16000, now_ns=1)
    fc.create_pod(make_pod(hbm=4000, name="ext", phase="Running",
                           node="n1", ann=ann))
    info = cache.get_node_info("n1")
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 4000)
    assert info.describe()["chips"][3]["used_hbm_mib"] == 4000


def test_unhealthy_configmap_flows_to_fit_check(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    fc.set_configmap("kube-system", "unhealthy-tpu-n1", {"chips": "0,1,2,3"})
    assert wait_until(
        lambda: info.describe()["unhealthy_chips"] == [0, 1, 2, 3])
    ok, _ = info.assume(make_pod(hbm=100, name="q"))
    assert not ok
    # recovery: configmap cleared
    fc.set_configmap("kube-system", "unhealthy-tpu-n1", {"chips": ""})
    assert wait_until(lambda: info.describe()["unhealthy_chips"] == [])
    ok, _ = info.assume(make_pod(hbm=100, name="q"))
    assert ok


def test_unhealthy_configmap_loaded_at_startup():
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16000)
    fc.set_configmap("kube-system", "unhealthy-tpu-n1", {"chips": "1"})
    cache = SchedulerCache(fc)
    ctl = Controller(fc, cache)
    ctl.build_cache()
    assert cache.get_node_info("n1").describe()["unhealthy_chips"] == [1]


def test_irrelevant_update_not_processed(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="p"))
    info.allocate(pod, fc)
    assert wait_until(lambda: cache.known_pod(pod["metadata"]["uid"]))
    before = info.describe()["used_hbm_mib"]
    # label-only change: relevance filter must skip it (no phase change,
    # pod already known)
    fc.patch_pod("default", "p", {"metadata": {"labels": {"x": "y"}}})
    time.sleep(0.2)
    assert info.describe()["used_hbm_mib"] == before


def test_delete_then_recreate_same_name_frees_old_chips(rig):
    # StatefulSet pattern: web-0 deleted and instantly recreated (new UID).
    # The OLD pod's chips must be freed even though get_pod would find the
    # new pod under the same key.
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="web-0"))
    info.allocate(pod, fc)
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 2000)
    fc.delete_pod("default", "web-0")
    fc.create_pod(make_pod(hbm=2000, name="web-0"))  # new UID, Pending
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 0)


def test_resync_reconciles_missed_delete(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    pod = fc.create_pod(make_pod(hbm=2000, name="p"))
    info.allocate(pod, fc)
    # wait for the watch-driven sync to fully land (pod known), so no
    # in-flight event can double as the reconciler below
    assert wait_until(lambda: cache.known_pod(pod["metadata"]["uid"]))
    assert ctl.drain()
    # simulate a DELETED event lost during a watch gap: remove from the
    # store WITHOUT notifying watchers
    with fc._lock:
        fc._pods.pop("default/p")
    time.sleep(0.1)
    assert info.describe()["used_hbm_mib"] == 2000  # still leaked
    ctl.resync_once()
    assert wait_until(lambda: info.describe()["used_hbm_mib"] == 0)


def test_resync_clears_unhealthy_after_configmap_deletion(rig):
    fc, cache, ctl = rig
    info = cache.get_node_info("n1")
    fc.set_configmap("kube-system", "unhealthy-tpu-n1", {"chips": "0"})
    assert wait_until(lambda: info.describe()["unhealthy_chips"] == [0])
    # configmap deletion missed by the watch: resync reconciles
    with fc._lock:
        fc._configmaps.pop("kube-system/unhealthy-tpu-n1")
    ctl.resync_once()
    assert info.describe()["unhealthy_chips"] == []


def test_watch_loop_survives_stream_crash():
    # a watch stream that dies mid-flight must be restarted, not abandoned
    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=16000, mesh="2x2")

    class CrashyOnce:
        def __init__(self, inner):
            self._inner = inner
            self.crashed = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def watch_pods(self, stop):
            if not self.crashed:
                self.crashed = True
                raise ConnectionResetError("stream reset")
            return self._inner.watch_pods(stop)

    crashy = CrashyOnce(fc)
    cache = SchedulerCache(crashy)
    ctl = Controller(crashy, cache)
    ctl.build_cache()
    ctl.start()
    try:
        # wait for the crashed loop to reconnect (a live subscriber appears)
        assert wait_until(lambda: crashy.crashed and fc._watchers["pods"])
        info = cache.get_node_info("n1")
        pod = fc.create_pod(make_pod(hbm=2000, name="p"))
        info.allocate(pod, fc)
        # the restarted watch (second attempt) must deliver the sync
        assert wait_until(lambda: cache.known_pod(pod["metadata"]["uid"]))
    finally:
        ctl.stop()


def test_node_deletion_removes_nodeinfo(rig):
    fc, cache, ctl = rig
    cache.get_node_info("n1")
    with fc._lock:
        node = fc._nodes.pop("n1")
    fc._notify("nodes", "DELETED", node)
    assert wait_until(lambda: "n1" not in cache.node_names())
