"""Mesh-aware placement properties (ISSUE 18).

The adjacency scorer against brute-force enumeration, the 2-D
monotonicity law (and the 3-D counterexample that scopes it), native
ABI v7 topo-cycle parity with the Python spec on randomized fleets,
the mesh-shape annotation grammar, the Filter-side strict rejection,
and the serving workload's device-order composition.
"""

import itertools
import json
import random

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.core.chips import ChipView
from tpushare.core.native import engine as native_engine
from tpushare.core.placement import PlacementRequest, select_chips_py
from tpushare.core.topology import (
    ADJ_SCALE, MeshTopology, adjacency_quality, box_links, congruent,
    congruent_first, max_box_links, occupancy_adjacency)
from tpushare.extender.handlers import (
    MESH_SHAPE_REJECTS, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.k8s import FakeCluster
from tpushare.workloads.serve import compose_mesh_devices

HBM = 16384


# -- the scorer vs brute force ------------------------------------------------

def _grid_edges(shape):
    """Literal ICI link count: adjacent coordinate pairs of the box."""
    coords = list(itertools.product(*[range(d) for d in shape]))
    return sum(1 for a, b in itertools.combinations(coords, 2)
               if sum(abs(x - y) for x, y in zip(a, b)) == 1)


def _all_factorizations(n):
    """Every sorted dims tuple with product n (rank unconstrained)."""
    if n == 1:
        return {(1,)}
    out = set()

    def rec(remaining, start, dims):
        if remaining == 1:
            out.add(tuple(sorted(dims)))
            return
        d = start
        while d <= remaining:
            if remaining % d == 0:
                rec(remaining // d, d, dims + [d])
            d += 1

    rec(n, 2, [])
    return out


def test_box_links_is_the_grid_edge_count():
    shapes = [(1,), (4,), (2, 2), (1, 8), (2, 4), (3, 3), (2, 2, 2),
              (2, 3, 4), (1, 2, 3), (4, 4), (2, 2, 9)]
    for shape in shapes:
        assert box_links(shape) == _grid_edges(shape), shape


def test_max_box_links_vs_bruteforce():
    for n in range(1, 49):
        want = max((box_links(dims) for dims in _all_factorizations(n)),
                   default=0)
        assert max_box_links(n) == want, n


def test_2d_monotone_more_square_more_links():
    """Among 2-D boxes of equal area, squarer is strictly better:
    links(a, b) = 2n - a - b, so shrinking the perimeter always adds
    links. This is the law Prioritize's blend leans on for the 2-D
    node meshes the fleet actually runs."""
    for n in range(2, 65):
        pairs = sorted((a, n // a) for a in range(1, n + 1)
                       if n % a == 0 and a <= n // a)
        for (a1, b1), (a2, b2) in zip(pairs, pairs[1:]):
            assert box_links((a2, b2)) > box_links((a1, b1)), \
                (n, (a1, b1), (a2, b2))


def test_monotonicity_does_not_extend_to_3d():
    """The counterexample that scopes the law above to 2-D: at 36
    chips the squarest 2-D box (6x6, 60 links) LOSES to a 3-D
    factorization (2x2x9, 68 links). The normalizer must enumerate
    all ranks, not pick the squarest 2-D shape."""
    assert box_links((6, 6)) == 60
    assert box_links((2, 2, 9)) == 68
    assert max_box_links(36) >= 68 > box_links((6, 6))


def test_adjacency_quality_range_and_sentinels():
    assert adjacency_quality(0, None) == -1
    assert adjacency_quality(-3, (2, 2)) == -1
    assert adjacency_quality(1, None) == ADJ_SCALE  # single chip
    assert adjacency_quality(4, None) == 0          # scatter
    for n in range(2, 33):
        for dims in _all_factorizations(n):
            q = adjacency_quality(n, dims)
            assert 0 <= q <= ADJ_SCALE, (n, dims)
    # the best factorization (and only it) scores ADJ_SCALE
    assert adjacency_quality(4, (2, 2)) == ADJ_SCALE
    assert adjacency_quality(4, (1, 4)) == 750_000
    # for 8 chips the 3-D cube (2,2,2) with 12 links is the normalizer,
    # so even the best 2-D box only scores 10/12
    assert adjacency_quality(8, (2, 4)) == 10 * ADJ_SCALE // 12
    assert adjacency_quality(8, (1, 8)) == 7 * ADJ_SCALE // 12


def test_occupancy_adjacency_boxes_holes_translation():
    assert occupancy_adjacency([]) == -1
    assert occupancy_adjacency([(0, 0)]) == ADJ_SCALE
    # a 2x2 box anywhere in the mesh scores its box quality
    square = [(0, 0), (0, 1), (1, 0), (1, 1)]
    assert occupancy_adjacency(square) == ADJ_SCALE
    shifted = [(r + 3, c + 5) for r, c in square]
    assert occupancy_adjacency(shifted) == ADJ_SCALE
    # a row is the 1x4 box
    assert occupancy_adjacency([(1, c) for c in range(4)]) == \
        adjacency_quality(4, (1, 4))
    # holes in the bounding box = scatter
    assert occupancy_adjacency([(0, 0), (0, 2)]) == 0
    assert occupancy_adjacency([(0, 0), (1, 1)]) == 0


def test_congruent_up_to_axis_order_and_unit_dims():
    assert congruent((4, 2), (2, 4))
    assert congruent((1, 2, 4), (2, 4))
    assert congruent((4,), (1, 4))
    assert not congruent((2, 2), (1, 4))
    assert not congruent((2, 4), (2, 2))


def test_congruent_first_is_a_stable_partition():
    shapes = [(2, 2), (1, 4), (4, 1), (2, 4), (4, 2)]
    out = congruent_first(shapes, (4, 2))
    assert out == [(2, 4), (4, 2), (2, 2), (1, 4), (4, 1)]
    assert sorted(out) == sorted(shapes)
    # a shape-blind request order is untouched by an all-miss partition
    assert congruent_first(shapes, (3, 3)) == shapes


# -- native ABI v7 topo-cycle parity ------------------------------------------

def _random_node(rng):
    n = rng.choice([4, 8, 16])
    shape = MeshTopology.for_chip_count(n).shape
    topo = MeshTopology(shape)
    total = rng.choice([8192, 16276])
    chips = [
        ChipView(i, topo.coords(i), total, rng.randrange(0, total + 1),
                 healthy=rng.random() > 0.15)
        for i in range(n)
    ]
    rng.shuffle(chips)
    return chips, topo


def _random_mesh_req(rng):
    count = rng.choice([2, 4, 4, 8])
    factorizations = [dims for dims in _all_factorizations(count)]
    mesh = tuple(rng.choice(factorizations))
    return PlacementRequest(
        hbm_mib=rng.choice([0, 512, 2048, 8138]),
        chip_count=count,
        allow_scatter=rng.random() < 0.5,
        mesh_shape=mesh,
    )


@pytest.mark.skipif(not native_engine.topo_cycle_supported(),
                    reason="ABI v7 native topo cycle unavailable")
def test_topo_cycle_parity_randomized_fleets():
    """ABI v7 cycle_fleet_topo vs the Python spec on randomized
    fleets: per node, the same (score, chip set, box, adjacency) —
    including the congruent-first box walk the mesh shape triggers."""
    rng = random.Random(1811)
    for trial in range(60):
        nodes = [_random_node(rng)
                 for _ in range(rng.randrange(1, 10))]
        req = _random_mesh_req(rng)
        fleet = native_engine.cycle_fleet_topo(nodes, req)
        assert len(fleet) == len(nodes)
        # materialization is winner-only (like cycle_fleet): the one
        # Placement in the result belongs to the best-scoring node
        scores = [s for s, _p, _a in fleet if s is not None]
        winners = [ni for ni, (_s, p, _a) in enumerate(fleet)
                   if p is not None]
        assert len(winners) == (1 if scores else 0), (trial, req)
        for ni, (chips, topo) in enumerate(nodes):
            py = select_chips_py(chips, topo, req)
            score, placement, adj = fleet[ni]
            if py is None:
                assert (score, placement, adj) == (None, None, -1), \
                    (trial, ni, req)
            else:
                assert score == py.score, (trial, ni, req)
                assert adj == py.adjacency, (trial, ni, req)
                if placement is not None:
                    # lowest score = tightest fit wins materialization
                    assert score == min(scores), (trial, ni, req)
                    assert placement.chip_ids == py.chip_ids, \
                        (trial, ni, req)
                    assert placement.box == py.box, (trial, ni, req)


def test_mesh_shape_never_changes_admissibility():
    """The declared shape is a soft preference: a node fits with the
    mesh shape iff it fits without it (only the box choice may move)."""
    rng = random.Random(77)
    for trial in range(200):
        chips, topo = _random_node(rng)
        req = _random_mesh_req(rng)
        blind = select_chips_py(
            chips, topo,
            PlacementRequest(hbm_mib=req.hbm_mib,
                             chip_count=req.chip_count,
                             allow_scatter=req.allow_scatter))
        aware = select_chips_py(chips, topo, req)
        assert (blind is None) == (aware is None), (trial, req)


# -- annotation grammar + Filter strict rejection -----------------------------

def _mesh_pod(shape_raw, count=4, hbm=2048, name="mesh-p"):
    return make_pod(hbm=hbm, count=count, name=name,
                    ann={contract.ANN_MESH_SHAPE: shape_raw})


def test_pod_mesh_shape_grammar():
    assert contract.pod_mesh_shape(make_pod(hbm=1024)) is None
    assert contract.pod_mesh_shape(_mesh_pod("2x4", count=8),
                                   chip_count=8) == (2, 4)
    assert contract.pod_mesh_shape(_mesh_pod(" 1x4 "),
                                   chip_count=4) == (1, 4)
    with pytest.raises(ValueError, match="integers joined by 'x'"):
        contract.pod_mesh_shape(_mesh_pod("2xtwo"), chip_count=4)
    with pytest.raises(ValueError, match="non-positive"):
        contract.pod_mesh_shape(_mesh_pod("0x4"), chip_count=4)
    with pytest.raises(ValueError, match="covers 8 chip"):
        contract.pod_mesh_shape(_mesh_pod("2x4"), chip_count=4)


def test_request_from_pod_strict_vs_lenient(monkeypatch):
    bad = _mesh_pod("3x3")
    lenient = request_from_pod(bad)
    assert lenient is not None and lenient.mesh_shape is None
    with pytest.raises(ValueError):
        request_from_pod(bad, strict_mesh=True)
    # the escape hatch ignores the annotation entirely, even strict
    monkeypatch.setenv("TPUSHARE_NO_TOPO_SCORE", "1")
    hatch = request_from_pod(bad, strict_mesh=True)
    assert hatch is not None and hatch.mesh_shape is None
    good = request_from_pod(_mesh_pod("2x2"))
    assert good.mesh_shape is None  # hatch still on
    monkeypatch.delenv("TPUSHARE_NO_TOPO_SCORE")
    assert request_from_pod(_mesh_pod("2x2")).mesh_shape == (2, 2)


def _filter_rig():
    fc = FakeCluster()
    for n in ("n0", "n1"):
        fc.add_tpu_node(n, chips=8, hbm_per_chip_mib=HBM, mesh="2x4")
    cache = SchedulerCache(fc)
    cache.build_cache()
    registry = Registry()
    return (fc, cache, FilterHandler(cache, registry),
            PrioritizeHandler(cache, registry))


def test_filter_rejects_malformed_mesh_shape_with_distinct_reason():
    _fc, _cache, flt, _prio = _filter_rig()
    before = MESH_SHAPE_REJECTS.value
    out = flt.handle({"Pod": _mesh_pod("3x3"),
                      "NodeNames": ["n0", "n1"]})
    assert out["NodeNames"] == []
    assert set(out["FailedNodes"]) == {"n0", "n1"}
    for reason in out["FailedNodes"].values():
        assert "invalid mesh-shape annotation" in reason
        assert "covers 9 chip" in reason
    assert MESH_SHAPE_REJECTS.value == before + 1


def test_filter_admits_wellformed_mesh_shape():
    _fc, _cache, flt, _prio = _filter_rig()
    before = MESH_SHAPE_REJECTS.value
    out = flt.handle({"Pod": _mesh_pod("2x2"),
                      "NodeNames": ["n0", "n1"]})
    assert sorted(out["NodeNames"]) == ["n0", "n1"]
    assert MESH_SHAPE_REJECTS.value == before


def test_prioritize_is_lenient_on_malformed_mesh_shape():
    """A malformed pod never passed Filter; downstream verbs treat the
    annotation as absent instead of erroring the whole verb."""
    _fc, _cache, _flt, prio = _filter_rig()
    ranked = prio.handle({"Pod": _mesh_pod("3x3"),
                          "NodeNames": ["n0", "n1"]})
    assert {r["Host"] for r in ranked} == {"n0", "n1"}
    clean = prio.handle({"Pod": make_pod(hbm=2048, count=4,
                                         name="mesh-p"),
                         "NodeNames": ["n0", "n1"]})
    assert json.dumps(ranked, sort_keys=True) == \
        json.dumps(clean, sort_keys=True)


# -- serving device-order composition -----------------------------------------

def test_compose_congruent_box_transposes_onto_logical_axes():
    devs = list("abcdefgh")
    # 2x4 box (row-major TPU_VISIBLE_CHIPS order), tp=4 ep=2: each tp
    # group along the last axis is a physically adjacent column pair
    out = compose_mesh_devices(devs, "2x4", (1, 4, 2))
    assert out == [[["a", "e"], ["b", "f"], ["c", "g"], ["d", "h"]]]
    # 2x2 box onto (1, 2, 2) is the identity reshape
    assert compose_mesh_devices(list("abcd"), "2x2", (1, 2, 2)) == \
        [[["a", "b"], ["c", "d"]]]


def test_compose_snake_makes_single_axis_ring_adjacent():
    # one logical axis over a 2x2 box: boustrophedon — every
    # consecutive pair (and the wrap) is one ICI hop apart
    out = compose_mesh_devices(list("abcd"), "2x2", (1, 4))
    assert out == [["a", "b", "d", "c"]]
    coords = {"a": (0, 0), "b": (0, 1), "c": (1, 0), "d": (1, 1)}
    ring = out[0]
    for x, y in zip(ring, ring[1:] + ring[:1]):
        dist = sum(abs(p - q)
                   for p, q in zip(coords[x], coords[y]))
        assert dist == 1, (x, y)


def test_compose_falls_back_to_plain_reshape():
    devs = list("abcd")
    plain = compose_mesh_devices(devs, None, (1, 4))
    assert plain == [["a", "b", "c", "d"]]
    # incongruent box label: no safe mapping, plain reshape
    assert compose_mesh_devices(devs, "3x3", (1, 2, 2)) == \
        [[["a", "b"], ["c", "d"]]]
    assert compose_mesh_devices(list("abcdefgh"), "1x8", (1, 4, 2)) == \
        [[["a", "b"], ["c", "d"], ["e", "f"], ["g", "h"]]]
