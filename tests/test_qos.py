"""QoS tiers (ISSUE 17): admission math, pressure eviction defenses,
the degraded latch, DRF caps — and the acceptance race.

The one scenario that justifies the whole subsystem: a guaranteed bind
lands concurrently with a best-effort oversubscribed admission on the
same chip. Exactly the best-effort borrower is evicted, the guaranteed
reservation is never violated at any sampled instant on apiserver
truth, and cache vs apiserver drift is zero.

Budget/backoff tests drive the monitor on a fake clock, mirroring
tests/test_defrag.py::test_budget_governor_and_backoff.
"""

import threading

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.chaos.invariants import QosInvariantMonitor, qos_violations
from tpushare.k8s import FakeCluster
from tpushare.qos.drf import (admission_would_exceed, dominant_shares,
                              drf_cap, tenant_usage)
from tpushare.qos.pressure import QOS_EVICTIONS, QosPressureMonitor
from tpushare.qos.tiers import (ENV_DRF_CAP, ENV_OVERCOMMIT,
                                TIER_BEST_EFFORT, TIER_BURSTABLE,
                                TIER_GUARANTEED, clear_degraded,
                                effective_overcommit, is_degraded,
                                overcommit, pod_tier, set_degraded,
                                tier_rank)

HBM = 10000


@pytest.fixture(autouse=True)
def _latch_hygiene():
    clear_degraded()
    yield
    clear_degraded()


def tier_pod(name, hbm, tier=None, namespace="default"):
    ann = {contract.ANN_QOS_TIER: tier} if tier else None
    return make_pod(hbm=hbm, name=name, namespace=namespace, ann=ann)


def qos_fleet(monkeypatch, oc="1.5", nodes=1, chips=1):
    monkeypatch.setenv(ENV_OVERCOMMIT, oc)
    fc = FakeCluster()
    for i in range(nodes):
        fc.add_tpu_node(f"n{i}", chips=chips, hbm_per_chip_mib=HBM)
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache


def bind(fc, cache, node, pod):
    info = cache.get_node_info(node)
    info.allocate(fc.create_pod(pod), fc)
    ns, name = pod["metadata"]["namespace"], pod["metadata"]["name"]
    cache.add_or_update_pod(fc.get_pod(ns, name))


def outcome_deltas(fn):
    outcomes = ("completed", "failed", "demoted", "skipped_budget",
                "skipped_backoff", "skipped_inflight")
    before = {o: QOS_EVICTIONS.get(TIER_BEST_EFFORT, o)
              for o in outcomes}
    fn()
    return {o: QOS_EVICTIONS.get(TIER_BEST_EFFORT, o) - before[o]
            for o in outcomes}


# -- tier vocabulary ----------------------------------------------------------

def test_pod_tier_parsing():
    assert pod_tier(tier_pod("p", 100)) == TIER_BURSTABLE
    assert pod_tier(tier_pod("p", 100, "guaranteed")) == TIER_GUARANTEED
    assert pod_tier(tier_pod("p", 100, "best-effort")) == TIER_BEST_EFFORT
    assert pod_tier(tier_pod("p", 100, "  GUARANTEED ")) == TIER_GUARANTEED
    assert pod_tier(tier_pod("p", 100, "platinum")) == TIER_BURSTABLE
    assert pod_tier(None) == TIER_BURSTABLE


def test_tier_rank_orders_eviction():
    assert tier_rank(TIER_BEST_EFFORT) < tier_rank(TIER_BURSTABLE) \
        < tier_rank(TIER_GUARANTEED)
    assert tier_rank("nonsense") == tier_rank(TIER_BURSTABLE)


def test_overcommit_env_clamps(monkeypatch):
    monkeypatch.delenv(ENV_OVERCOMMIT, raising=False)
    assert overcommit() == 1.0
    monkeypatch.setenv(ENV_OVERCOMMIT, "1.5")
    assert overcommit() == 1.5
    monkeypatch.setenv(ENV_OVERCOMMIT, "0.5")   # < 1.0 is meaningless
    assert overcommit() == 1.0
    monkeypatch.setenv(ENV_OVERCOMMIT, "banana")
    assert overcommit() == 1.0


def test_degraded_latch_collapses_effective_overcommit(monkeypatch):
    monkeypatch.setenv(ENV_OVERCOMMIT, "2.0")
    assert effective_overcommit() == 2.0
    set_degraded()
    assert is_degraded()
    assert effective_overcommit() == 1.0   # knob unchanged, gate shut
    assert overcommit() == 2.0
    clear_degraded()
    assert effective_overcommit() == 2.0


# -- admission views ----------------------------------------------------------

def test_best_effort_borrows_beyond_physical(monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="1.5")
    info = cache.get_node_info("n0")
    bind(fc, cache, "n0", tier_pod("be-1", 8000, "best-effort"))
    # 8000 + 6000 = 14000 > 10000 physical but <= 15000 cap
    ok, _ = info.assume_qos(tier_pod("be-2", 6000, "best-effort"))
    assert ok
    # ... and the cap is a hard bound: 8000 + 7001 > 15000
    ok, reason = info.assume_qos(tier_pod("be-3", 7001, "best-effort"))
    assert not ok and reason


def test_guaranteed_counts_reclaimable_but_honors_both_bounds(
        monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="1.5")
    info = cache.get_node_info("n0")
    bind(fc, cache, "n0", tier_pod("be-1", 8000, "best-effort"))
    # guaranteed headroom = min(physical - non-BE used, cap - used)
    #                     = min(10000 - 0, 15000 - 8000) = 7000
    ok, _ = info.assume_qos(tier_pod("g-1", 7000, "guaranteed"))
    assert ok
    ok, _ = info.assume_qos(tier_pod("g-2", 7001, "guaranteed"))
    assert not ok
    # non-BE usage alone can never pass physical, however large the cap
    bind(fc, cache, "n0", tier_pod("g-3", 6000, "guaranteed"))
    ok, _ = info.assume_qos(tier_pod("g-4", 4001, "guaranteed"))
    assert not ok
    ok, _ = info.assume_qos(tier_pod("g-5", 1000, "guaranteed"))
    assert ok


def test_inactive_overcommit_is_legacy_admission(monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="1.0")
    info = cache.get_node_info("n0")
    bind(fc, cache, "n0", tier_pod("be-1", 8000, "best-effort"))
    # no borrowing at oc=1.0 — even best-effort sees physical HBM
    ok, _ = info.assume_qos(tier_pod("be-2", 2001, "best-effort"))
    assert not ok
    ok, _ = info.assume_qos(tier_pod("be-3", 2000, "best-effort"))
    assert ok


def test_pressure_victim_smallest_clearing_else_largest(monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="2.0")
    info = cache.get_node_info("n0")
    bind(fc, cache, "n0", tier_pod("be-small", 3000, "best-effort"))
    bind(fc, cache, "n0", tier_pod("be-big", 5000, "best-effort"))
    assert info.pressure_victim() is None   # pure BE borrow: no pressure
    bind(fc, cache, "n0", tier_pod("g-1", 4000, "guaranteed"))
    # overage 2000: smallest clearing entry is be-small (3000)
    plan = info.pressure_victim()
    assert plan is not None
    key, hbm, chip, _stamp = plan
    victim = cache.pod_by_key(key)
    assert victim["metadata"]["name"] == "be-small"
    assert hbm == 3000 and chip == 0
    # overage 5500: nothing clears -> the largest (be-big) goes first
    bind(fc, cache, "n0", tier_pod("g-2", 3500, "guaranteed"))
    key, hbm, _chip, _stamp = info.pressure_victim()
    assert cache.pod_by_key(key)["metadata"]["name"] == "be-big"
    assert hbm == 5000


# -- DRF tenant caps ----------------------------------------------------------

def test_dominant_shares_over_chips_and_hbm(monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="1.0", chips=2)
    bind(fc, cache, "n0", tier_pod("wide", 1000, namespace="a"))
    bind(fc, cache, "n0", tier_pod("deep", 8000, namespace="b"))
    usage = tenant_usage(cache)
    assert usage["_fleet"] == {"chips": 2.0, "hbm_mib": 20000.0}
    shares = dominant_shares(cache)
    # "a" is chip-dominant (1/2 chips), "b" HBM-dominant would be 0.4
    # but also holds a chip: max(0.5, 0.4) = 0.5
    assert shares["a"] == 0.5
    assert shares["b"] == 0.5


def test_admission_would_exceed_caps_tenant(monkeypatch):
    fc, cache = qos_fleet(monkeypatch, oc="1.0", chips=2)
    bind(fc, cache, "n0", tier_pod("deep", 8000, namespace="b"))
    assert not admission_would_exceed(cache, "b", 0, 4000, cap=0.6)
    assert admission_would_exceed(cache, "b", 0, 4001, cap=0.6)
    assert admission_would_exceed(cache, "b", 1, 0, cap=0.6)  # 2/2 chips
    # cap 1.0 is "off" — never rejects
    assert not admission_would_exceed(cache, "b", 2, 99999, cap=1.0)


def test_drf_cap_env_parsing(monkeypatch):
    monkeypatch.delenv(ENV_DRF_CAP, raising=False)
    assert drf_cap() == 1.0
    monkeypatch.setenv(ENV_DRF_CAP, "0.25")
    assert drf_cap() == 0.25
    monkeypatch.setenv(ENV_DRF_CAP, "1.7")   # out of (0, 1] -> off
    assert drf_cap() == 1.0
    monkeypatch.setenv(ENV_DRF_CAP, "zero")
    assert drf_cap() == 1.0


# -- the pressure monitor on a fake clock -------------------------------------

def pressured_fleet(monkeypatch, nodes=1):
    """Every node's chip 0 is at 14000/10000 with 8000 reclaimable."""
    fc, cache = qos_fleet(monkeypatch, oc="1.5", nodes=nodes)
    for i in range(nodes):
        bind(fc, cache, f"n{i}",
             tier_pod(f"be-{i}", 8000, "best-effort",
                      namespace="batch"))
        bind(fc, cache, f"n{i}", tier_pod(f"g-{i}", 6000, "guaranteed"))
    return fc, cache


def test_budget_governor_and_window_roll(monkeypatch):
    fc, cache = pressured_fleet(monkeypatch, nodes=2)
    now = [1000.0]
    mon = QosPressureMonitor(cache, fc, budget=1, window_s=60.0,
                             backoff_s=30.0, time_fn=lambda: now[0])
    d = outcome_deltas(mon.scan_once)
    # one eviction spends the window's only slot; n1 is deferred
    assert d["completed"] == 1 and d["skipped_budget"] == 1
    assert fc.get_pod("default", "g-0") and fc.get_pod("default", "g-1")
    state = mon.budget_state()
    assert state["used_in_window"] == 1 and state["budget"] == 1
    # the window rolls: the deferred node is now served
    now[0] += 61.0
    d = outcome_deltas(mon.scan_once)
    assert d["completed"] == 1 and d["skipped_budget"] == 0
    assert qos_violations(fc.list_pods(), HBM, 1.5) == ([], [])


class FailingDeletes:
    """Delegates to a FakeCluster; delete_pod raises while armed."""

    def __init__(self, fc):
        self._fc = fc
        self.armed = True

    def __getattr__(self, name):
        return getattr(self._fc, name)

    def delete_pod(self, ns, name, **kw):
        if self.armed:
            raise OSError("evictor transport down")
        return self._fc.delete_pod(ns, name, **kw)


def test_failed_eviction_backs_off_the_node(monkeypatch):
    fc, cache = pressured_fleet(monkeypatch)
    now = [1000.0]
    mon = QosPressureMonitor(cache, FailingDeletes(fc), budget=8,
                             window_s=60.0, backoff_s=30.0,
                             time_fn=lambda: now[0])
    d = outcome_deltas(mon.scan_once)
    assert d["failed"] == 1
    assert mon.budget_state()["backoff_nodes"] == ["n0"]
    # in backoff: the node is skipped, nothing is retried
    d = outcome_deltas(mon.scan_once)
    assert d["skipped_backoff"] == 1 and d["failed"] == 0
    # backoff expires -> retried (and fails again)
    now[0] += 31.0
    d = outcome_deltas(mon.scan_once)
    assert d["failed"] == 1


def test_degraded_latch_stops_oversubscription_until_success(
        monkeypatch):
    fc, cache = pressured_fleet(monkeypatch)
    cluster = FailingDeletes(fc)
    now = [1000.0]
    mon = QosPressureMonitor(cache, cluster, budget=16, window_s=60.0,
                             backoff_s=0.0, time_fn=lambda: now[0])
    info = cache.get_node_info("n0")
    for i in range(3):
        assert not is_degraded()
        assert mon.scan_node("n0", max_evictions=1) == 0
        now[0] += 1.0
    # 3 consecutive transport failures latch degraded fleet-wide ...
    assert is_degraded()
    assert effective_overcommit() == 1.0
    # ... oversubscribed admissions stop (14000 used of 10000 physical)
    ok, _ = info.assume_qos(tier_pod("be-x", 500, "best-effort"))
    assert not ok
    # the first successful eviction clears the latch and reclaims
    cluster.armed = False
    d = outcome_deltas(lambda: mon.scan_node("n0"))
    assert d["completed"] == 1
    assert not is_degraded()
    assert effective_overcommit() == 1.5
    assert fc.get_pod("default", "g-0")


def test_demoted_when_victim_departs_after_planning(monkeypatch):
    fc, cache = pressured_fleet(monkeypatch)

    class VanishingVictim:
        def __init__(self, fc):
            self._fc = fc

        def __getattr__(self, name):
            return getattr(self._fc, name)

    cluster = VanishingVictim(fc)
    mon = QosPressureMonitor(cache, cluster, budget=16)
    # the victim departs between planning and revalidation: stamp moved
    plan = cache.get_node_info("n0").pressure_victim()
    assert plan is not None
    gone = fc.get_pod("batch", "be-0")
    fc.delete_pod("batch", "be-0")
    cache.remove_pod(gone)
    d = outcome_deltas(lambda: mon.scan_node("n0"))
    assert d["demoted"] == 0 and d["completed"] == 0  # no pressure left
    # re-create pressure, then move the stamp AFTER planning via a
    # concurrent bind: _evict_one revalidates and demotes, untouched
    bind(fc, cache, "n0", tier_pod("be-new", 8000, "best-effort",
                                   namespace="batch"))
    orig = type(cache).peek_node
    state = {"n": 0, "busy": False}

    def racy_peek(self, name):
        # peek #1 plans the eviction; a concurrent bind lands before
        # peek #2 (the revalidation), moving the node stamp
        if state["busy"]:
            return orig(self, name)
        state["n"] += 1
        if state["n"] == 2:
            state["busy"] = True
            bind(fc, cache, "n0", tier_pod("g-race", 100, "guaranteed"))
            state["busy"] = False
        return orig(self, name)

    monkeypatch.setattr(type(cache), "peek_node", racy_peek)
    d = outcome_deltas(lambda: mon.scan_node("n0", max_evictions=1))
    assert d["demoted"] == 1 and d["completed"] == 0
    assert fc.get_pod("batch", "be-new")  # victim untouched


# -- the acceptance race ------------------------------------------------------

def test_guaranteed_bind_races_best_effort_admission(monkeypatch):
    """Guaranteed bind concurrent with a best-effort oversubscribed
    admission on the same chip: exactly the best-effort borrower is
    evicted, zero guaranteed violations on sampled apiserver truth,
    zero cache drift."""
    fc, cache = qos_fleet(monkeypatch, oc="1.5")
    bind(fc, cache, "n0", tier_pod("be-old", 8000, "best-effort",
                                   namespace="batch"))
    qmon = QosInvariantMonitor(fc.list_pods, HBM, 1.5,
                               interval_s=0.001).start()
    barrier = threading.Barrier(2)
    errors = []

    def bind_one(pod):
        try:
            barrier.wait(timeout=2.0)
            bind(fc, cache, "n0", pod)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [
        threading.Thread(target=bind_one,
                         args=(tier_pod("g-hot", 6000, "guaranteed"),)),
        threading.Thread(target=bind_one,
                         args=(tier_pod("be-late", 1000, "best-effort",
                                        namespace="batch"),)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)
    assert errors == []
    # chip: 8000 BE + 6000 G + 1000 BE = 15000 granted, 10000 physical
    mon = QosPressureMonitor(cache, fc, budget=16)
    d = outcome_deltas(mon.scan_once)
    # overage 5000: be-old (8000) is the only clearing victim — exactly
    # one eviction makes the chip physically whole (7000 used)
    assert d["completed"] == 1
    assert fc.get_pod("default", "g-hot")
    assert fc.get_pod("batch", "be-late")
    with pytest.raises(Exception):
        fc.get_pod("batch", "be-old")
    report = qmon.stop()
    assert report["samples"] > 0
    assert report["guaranteed_violations"] == []
    assert report["overcommit_violations"] == []
    assert qos_violations(fc.list_pods(), HBM, 1.5) == ([], [])
    # zero drift: cache per-chip sums match apiserver truth annotations
    truth = {}
    for pod in fc.list_pods():
        node = (pod.get("spec") or {}).get("nodeName")
        ids = contract.chip_ids_from_annotations(pod)
        if not node or ids is None:
            continue
        for c in ids:
            truth[c] = truth.get(c, 0) \
                + contract.hbm_from_annotations(pod)
    for node in cache.describe()["nodes"]:
        for chip in node["chips"]:
            assert chip["used_hbm_mib"] == truth.get(chip["idx"], 0)
