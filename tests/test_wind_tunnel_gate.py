"""The pinned wind-tunnel scorecard gate (tier-1).

tests/data/wind_tunnel_golden.json pins the autotune winner's scorecard
on the standard gate trace with per-metric tolerance bands. This test
replays the gate every tier-1 run: a change that degrades placement
QUALITY — not just throughput — reds here. Re-baselining is deliberate:
``python -m tpushare.sim --autotune --pin`` (docs/ops.md)."""

import pytest

from tpushare.sim.autotune import (
    DEFAULT_BANDS, GATE_FLEET, GATE_TRACE, LoopKnobs, check_scorecard,
    gate_scorecard, knob_grid, load_golden)
from tpushare.sim.simulator import Fleet, run_sim, synth_trace


@pytest.fixture(scope="module")
def golden():
    return load_golden()


def test_golden_schema(golden):
    assert set(golden) == {"gate_trace", "gate_fleet", "winner_knobs",
                           "scorecard", "bands"}
    assert set(golden["bands"]) <= set(golden["scorecard"])
    assert all(b > 0 for b in golden["bands"].values())
    # the golden must describe THIS code's gate workload, or the replay
    # below compares apples to oranges after a silent workload edit
    assert golden["gate_trace"]["n_pods"] == GATE_TRACE.n_pods
    assert golden["gate_trace"]["seed"] == GATE_TRACE.seed
    assert golden["gate_fleet"]["nodes"] == GATE_FLEET["nodes"]


def test_gate_scorecard_within_bands(golden):
    """THE regression gate: replay the pinned winner's knobs on the
    gate trace; every banded metric must sit inside its band."""
    got = gate_scorecard(LoopKnobs(**golden["winner_knobs"]))
    violations = check_scorecard(got, golden)
    assert violations == [], "\n".join(violations)


def test_gate_is_falsifiable_by_policy_regression(golden):
    """A deliberate scoring regression must red the gate: worstfit on
    the same gate workload lands outside the bands (if it did not, the
    bands would be too loose to protect anything)."""
    fleet = Fleet.homogeneous(GATE_FLEET["nodes"], GATE_FLEET["chips"],
                              GATE_FLEET["hbm"], GATE_FLEET["mesh"])
    bad = run_sim(fleet, synth_trace(GATE_TRACE), "worstfit").scorecard()
    assert check_scorecard(bad, golden) != []


def test_bands_match_defaults(golden):
    assert golden["bands"] == DEFAULT_BANDS


def test_check_scorecard_mechanics(golden):
    pinned = dict(golden["scorecard"])
    assert check_scorecard(pinned, golden) == []
    for metric, band in golden["bands"].items():
        nudged = dict(pinned, **{metric: pinned[metric] + band * 2})
        bad = check_scorecard(nudged, golden)
        assert len(bad) == 1 and metric in bad[0]
    # a missing metric is a violation, not a silent pass
    dropped = dict(pinned)
    dropped.pop("p99_pending_age_s")
    dropped["p99_pending_age_s"] = None
    assert check_scorecard(dropped, golden) != []


def test_knob_grid_shape():
    """The sweep ranks at least 16 configurations (acceptance floor)
    and every config is a valid, distinct knob point."""
    grid = knob_grid()
    assert len(grid) >= 16
    assert len(set(grid)) == len(grid)


# -- the tiered QoS gate (ISSUE 17) -------------------------------------------
#
# tests/data/qos_wind_tunnel_golden.json pins the oversubscribed
# (overcommit=1.25) tiered-diurnal scorecard AND the single-class
# baseline it must beat. Re-baselining is deliberate:
# ``python -m tpushare.sim --qos --pin``.

from tpushare.sim.qos import (
    GATE_OVERCOMMIT, GUARANTEED, QOS_DEFAULT_BANDS, QOS_GATE_FLEET,
    QOS_GATE_SPEC, load_qos_golden, qos_gate_report)


@pytest.fixture(scope="module")
def qos_golden():
    return load_qos_golden()


@pytest.fixture(scope="module")
def qos_report():
    return qos_gate_report()


def test_qos_golden_schema(qos_golden):
    assert set(qos_golden) == {"gate_spec", "gate_fleet", "overcommit",
                               "scorecard", "qos", "bands"}
    assert qos_golden["overcommit"] == GATE_OVERCOMMIT
    assert qos_golden["bands"] == QOS_DEFAULT_BANDS
    # the golden must describe THIS code's gate workload
    assert qos_golden["gate_spec"]["seed"] == QOS_GATE_SPEC.seed
    assert qos_golden["gate_spec"]["peak_rate"] == QOS_GATE_SPEC.peak_rate
    assert qos_golden["gate_spec"]["n_tiers"] == len(QOS_GATE_SPEC.tiers)
    assert qos_golden["gate_fleet"]["nodes"] == QOS_GATE_FLEET["nodes"]


def test_qos_gate_scorecard_within_bands(qos_golden, qos_report):
    violations = check_scorecard(qos_report.scorecard(), qos_golden)
    assert violations == [], "\n".join(violations)


def test_qos_gate_isolation_invariants(qos_golden, qos_report):
    """The robustness half of the gate: zero guaranteed violations and
    zero beyond-bound grants at EVERY sampled instant, evictions
    governed by the budget — the same three assertions the chaos drill
    makes against apiserver truth."""
    assert qos_report.guaranteed_violations == 0
    assert qos_report.overcommit_violations == 0
    assert qos_report.evictions > 0, \
        "gate workload must actually exercise pressure eviction"
    assert qos_report.max_window_evictions <= 4  # GATE_EVICT_BUDGET
    assert qos_golden["qos"]["guaranteed_violations"] == 0
    assert qos_golden["qos"]["overcommit_violations"] == 0


def test_qos_gate_beats_single_class_baseline(qos_report):
    """What oversubscription must BUY: a time-weighted utilization win
    over the single-class (overcommit=1.0) baseline at equal-or-better
    guaranteed-tier SLO, with best-effort HBM actually reclaimed under
    pressure. If the tiered run cannot beat its own off-switch, the
    subsystem has no reason to exist."""
    base = qos_gate_report(overcommit=1.0)
    assert base.evictions == 0  # the off-switch really is off
    assert base.guaranteed_violations == 0
    tiered = qos_report
    assert tiered.scorecard()["time_weighted_util_pct"] > \
        base.scorecard()["time_weighted_util_pct"]
    assert tiered.by_tier[GUARANTEED]["p99_wait"] <= \
        base.by_tier[GUARANTEED]["p99_wait"]
    assert tiered.reclaimed_mib > 0


def test_qos_gate_is_falsifiable(qos_golden):
    """An unbounded overcommit (2.0) shifts the scorecard outside the
    pinned bands — the bands are tight enough to catch an accidental
    knob regression, not just a policy rewrite."""
    loose = qos_gate_report(overcommit=2.0)
    assert check_scorecard(loose.scorecard(), qos_golden) != []


# -- the mesh-aware placement gate (ISSUE 18) ---------------------------------
#
# tests/data/topo_wind_tunnel_golden.json pins the seed-averaged
# mesh-aware serving scorecard AND the shape-blind baseline it must
# beat: lower serving p99 wait at equal-or-better utilization, bought
# by a strictly better adjacency scorecard. Re-baselining is
# deliberate: ``python -m tpushare.sim --topo --pin``.

from tpushare.sim.topo import (
    GATE_SEEDS, GATE_SLOWDOWN, GATE_TOPO_WEIGHT, TOPO_DEFAULT_BANDS,
    TOPO_GATE_FLEET, TOPO_GATE_SPEC, check_topo, gate_aggregate,
    load_topo_golden)


@pytest.fixture(scope="module")
def topo_golden():
    return load_topo_golden()


@pytest.fixture(scope="module")
def topo_aware():
    return gate_aggregate()


@pytest.fixture(scope="module")
def topo_blind():
    return gate_aggregate(mesh_aware=False)


def test_topo_golden_schema(topo_golden):
    assert set(topo_golden) == {"gate_spec", "gate_fleet", "topo_weight",
                                "slowdown", "scorecard", "adjacency",
                                "serve_p99_wait", "baseline", "bands"}
    assert topo_golden["bands"] == TOPO_DEFAULT_BANDS
    assert topo_golden["topo_weight"] == GATE_TOPO_WEIGHT
    assert topo_golden["slowdown"] == GATE_SLOWDOWN
    # the golden must describe THIS code's gate workload
    assert topo_golden["gate_spec"]["n_pods"] == TOPO_GATE_SPEC.n_pods
    assert topo_golden["gate_spec"]["seeds"] == list(GATE_SEEDS)
    assert topo_golden["gate_fleet"]["nodes"] == TOPO_GATE_FLEET["nodes"]
    assert tuple(topo_golden["gate_fleet"]["mesh"]) == \
        TOPO_GATE_FLEET["mesh"]


def test_topo_gate_within_bands(topo_golden, topo_aware):
    """THE regression gate: replay the seed-averaged mesh-aware leg;
    scorecard within bands, adjacency no worse than pinned, serving
    tail still beating the pinned shape-blind baseline."""
    violations = check_topo(topo_aware, topo_golden)
    assert violations == [], "\n".join(violations)


def test_topo_gate_beats_shape_blind_baseline(topo_golden, topo_aware,
                                              topo_blind):
    """What the blend must BUY (the live replay, not just the pinned
    numbers): a lower serving p99 wait at equal-or-better utilization,
    via a strictly better adjacency scorecard — more congruent boxes,
    higher mean quality, less step-time stretch."""
    assert topo_aware["serve_p99_wait"] < topo_blind["serve_p99_wait"]
    util_band = topo_golden["bands"]["time_weighted_util_pct"]
    assert topo_aware["scorecard"]["time_weighted_util_pct"] >= \
        topo_blind["scorecard"]["time_weighted_util_pct"] - util_band
    assert topo_aware["scorecard"]["rejection_rate"] <= \
        topo_blind["scorecard"]["rejection_rate"]
    a, b = topo_aware["adjacency"], topo_blind["adjacency"]
    assert a["placements"] == b["placements"]  # same admitted work
    assert a["mean_quality"] > b["mean_quality"]
    assert a["congruent_rate"] > b["congruent_rate"]
    assert a["stretch_time"] < b["stretch_time"]
    # and the pinned baseline in the golden is the real blind leg
    assert topo_golden["baseline"]["serve_p99_wait"] == \
        topo_blind["serve_p99_wait"]


def test_topo_gate_is_falsifiable(topo_golden, topo_blind):
    """The shape-blind leg must red the gate on every adjacency
    dimension — otherwise the tolerances are too loose to protect the
    tentpole's actual claim."""
    violations = check_topo(topo_blind, topo_golden)
    assert any("mean_quality" in v for v in violations)
    assert any("congruent_rate" in v for v in violations)
    assert any("stretch_time" in v for v in violations)
    assert any("serve_p99_wait" in v for v in violations)
