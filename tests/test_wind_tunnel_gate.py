"""The pinned wind-tunnel scorecard gate (tier-1).

tests/data/wind_tunnel_golden.json pins the autotune winner's scorecard
on the standard gate trace with per-metric tolerance bands. This test
replays the gate every tier-1 run: a change that degrades placement
QUALITY — not just throughput — reds here. Re-baselining is deliberate:
``python -m tpushare.sim --autotune --pin`` (docs/ops.md)."""

import pytest

from tpushare.sim.autotune import (
    DEFAULT_BANDS, GATE_FLEET, GATE_TRACE, LoopKnobs, check_scorecard,
    gate_scorecard, knob_grid, load_golden)
from tpushare.sim.simulator import Fleet, run_sim, synth_trace


@pytest.fixture(scope="module")
def golden():
    return load_golden()


def test_golden_schema(golden):
    assert set(golden) == {"gate_trace", "gate_fleet", "winner_knobs",
                           "scorecard", "bands"}
    assert set(golden["bands"]) <= set(golden["scorecard"])
    assert all(b > 0 for b in golden["bands"].values())
    # the golden must describe THIS code's gate workload, or the replay
    # below compares apples to oranges after a silent workload edit
    assert golden["gate_trace"]["n_pods"] == GATE_TRACE.n_pods
    assert golden["gate_trace"]["seed"] == GATE_TRACE.seed
    assert golden["gate_fleet"]["nodes"] == GATE_FLEET["nodes"]


def test_gate_scorecard_within_bands(golden):
    """THE regression gate: replay the pinned winner's knobs on the
    gate trace; every banded metric must sit inside its band."""
    got = gate_scorecard(LoopKnobs(**golden["winner_knobs"]))
    violations = check_scorecard(got, golden)
    assert violations == [], "\n".join(violations)


def test_gate_is_falsifiable_by_policy_regression(golden):
    """A deliberate scoring regression must red the gate: worstfit on
    the same gate workload lands outside the bands (if it did not, the
    bands would be too loose to protect anything)."""
    fleet = Fleet.homogeneous(GATE_FLEET["nodes"], GATE_FLEET["chips"],
                              GATE_FLEET["hbm"], GATE_FLEET["mesh"])
    bad = run_sim(fleet, synth_trace(GATE_TRACE), "worstfit").scorecard()
    assert check_scorecard(bad, golden) != []


def test_bands_match_defaults(golden):
    assert golden["bands"] == DEFAULT_BANDS


def test_check_scorecard_mechanics(golden):
    pinned = dict(golden["scorecard"])
    assert check_scorecard(pinned, golden) == []
    for metric, band in golden["bands"].items():
        nudged = dict(pinned, **{metric: pinned[metric] + band * 2})
        bad = check_scorecard(nudged, golden)
        assert len(bad) == 1 and metric in bad[0]
    # a missing metric is a violation, not a silent pass
    dropped = dict(pinned)
    dropped.pop("p99_pending_age_s")
    dropped["p99_pending_age_s"] = None
    assert check_scorecard(dropped, golden) != []


def test_knob_grid_shape():
    """The sweep ranks at least 16 configurations (acceptance floor)
    and every config is a valid, distinct knob point."""
    grid = knob_grid()
    assert len(grid) >= 16
    assert len(set(grid)) == len(grid)
