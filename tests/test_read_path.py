"""Read-path tests: informer/listers, singleflight coalescing, the
cross-verb placement memo, and the apiserver round-trip budget.

The perf claim of the informer/memo work is only real if it is
falsifiable — these tests pin the budget with the same counters bench.py
publishes: a plain bind's hot path issues ZERO synchronous apiserver
reads, a gang member's Allocate issues at most one namespace-scoped pods
LIST, and any cache mutation invalidates the memo.
"""

import threading
import time

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import MEMO_REQUESTS, SchedulerCache
from tpushare.cache.nodeinfo import request_from_pod
from tpushare.extender.handlers import (
    BindHandler, FilterHandler, PrioritizeHandler)
from tpushare.extender.metrics import Registry
from tpushare.k8s import ApiError, FakeCluster
from tpushare.k8s.informer import (
    Informer, LISTER_REQUESTS, PodLister, lister_hit_rate)
from tpushare.k8s.singleflight import SINGLEFLIGHT_TOTAL, Singleflight
from tpushare.k8s.stats import (
    APISERVER_REQUESTS, CountingCluster, READ_VERBS, WRITE_VERBS,
    api_origin, delta)


def cluster_with_node(chips=4, hbm=16000, mesh=None, name="n1"):
    fc = FakeCluster()
    fc.add_tpu_node(name, chips=chips, hbm_per_chip_mib=hbm, mesh=mesh)
    return fc


# -- singleflight -------------------------------------------------------------

def test_singleflight_coalesces_concurrent_callers():
    """Two threads hitting the same key during one burst observe exactly
    one upstream call and share its result."""
    sf = Singleflight()
    calls = []
    entered = threading.Event()
    release = threading.Event()

    def upstream():
        calls.append(threading.get_ident())
        entered.set()
        release.wait(5)
        return "answer"

    results = []

    def worker():
        results.append(sf.do("k", upstream))

    t1 = threading.Thread(target=worker)
    t1.start()
    assert entered.wait(5)  # leader is inside upstream
    t2 = threading.Thread(target=worker)
    t2.start()
    # give the follower time to park on the leader's event
    time.sleep(0.05)
    release.set()
    t1.join(5)
    t2.join(5)
    assert results == ["answer", "answer"]
    assert len(calls) == 1


def test_singleflight_sequential_calls_are_not_cached():
    sf = Singleflight()
    calls = []
    assert sf.do("k", lambda: calls.append(1) or "a") == "a"
    assert sf.do("k", lambda: calls.append(2) or "b") == "b"
    assert len(calls) == 2  # coalescing, not caching


def test_singleflight_shares_the_leaders_exception():
    sf = Singleflight()
    entered = threading.Event()
    release = threading.Event()

    def boom():
        entered.set()
        release.wait(5)
        raise ApiError(404, "gone")

    errors = []

    def worker():
        try:
            sf.do("k", boom)
        except ApiError as e:
            errors.append(e.status)

    t1 = threading.Thread(target=worker)
    t1.start()
    assert entered.wait(5)
    t2 = threading.Thread(target=worker)
    t2.start()
    time.sleep(0.05)
    release.set()
    t1.join(5)
    t2.join(5)
    assert errors == [404, 404]


def test_singleflight_counters_track_leader_and_shared():
    before = SINGLEFLIGHT_TOTAL.snapshot()
    sf = Singleflight()
    gate = threading.Event()
    started = threading.Event()

    def slow():
        started.set()
        gate.wait(5)
        return 1

    t = threading.Thread(target=lambda: sf.do("k", slow))
    t.start()
    assert started.wait(5)
    t2 = threading.Thread(target=lambda: sf.do("k", slow))
    t2.start()
    time.sleep(0.05)
    gate.set()
    t.join(5)
    t2.join(5)
    after = SINGLEFLIGHT_TOTAL.snapshot()
    assert after.get(("leader",), 0) - before.get(("leader",), 0) == 1
    assert after.get(("shared",), 0) - before.get(("shared",), 0) == 1


# -- informer / listers -------------------------------------------------------

def test_pod_lister_indexes_and_unindexes():
    lister = PodLister()
    pod = make_pod(hbm=1024, name="a", node="n1",
                   ann={contract.ANN_GANG: "g1"})
    lister.apply("ADDED", pod)
    assert lister.get("default", "a") is pod
    assert lister.by_uid(pod["metadata"]["uid"]) is pod
    assert lister.on_node("n1") == [pod]
    assert lister.gang_peers("default", "g1") == [pod]
    # gang index is namespace-scoped by construction
    assert lister.gang_peers("other", "g1") == []
    moved = dict(pod, spec=dict(pod["spec"], nodeName="n2"))
    lister.apply("MODIFIED", moved)
    assert lister.on_node("n1") == []
    assert lister.on_node("n2") == [moved]
    lister.apply("DELETED", moved)
    assert lister.get("default", "a") is None
    assert lister.by_uid(pod["metadata"]["uid"]) is None
    assert lister.gang_peers("default", "g1") == []
    assert len(lister) == 0


def test_informer_syncs_and_follows_watch_events():
    fc = cluster_with_node()
    seeded = fc.create_pod(make_pod(hbm=1024, name="pre"))
    informer = Informer(fc).start()
    try:
        # initial LIST is synchronous: both stores are warm at return
        assert informer.synced
        assert informer.nodes.get("n1") is not None
        assert informer.pods.get("default", "pre") is not None
        assert informer.pods.by_uid(seeded["metadata"]["uid"]) is not None
        # watch events flow into the stores
        fc.create_pod(make_pod(hbm=1024, name="post"))
        deadline = time.time() + 5
        while informer.pods.get("default", "post") is None \
                and time.time() < deadline:
            time.sleep(0.01)
        assert informer.pods.get("default", "post") is not None
    finally:
        informer.stop()


def test_informer_relists_after_watch_break():
    """A broken watch stream heals by re-LISTing: objects created while
    the stream was down appear after the relist."""
    fc = cluster_with_node()

    class BreakingCluster:
        """Delegates to FakeCluster but serves each watch stream as an
        immediate EOF — every event must arrive via relist."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def watch_pods(self, stop):
            return iter(())

        def watch_nodes(self, stop):
            return iter(())

    informer = Informer(BreakingCluster(fc))
    informer.BACKOFF_BASE_S = 0.01
    informer.BACKOFF_CAP_S = 0.02
    informer.start()
    try:
        fc.create_pod(make_pod(hbm=1024, name="missed"))
        deadline = time.time() + 5
        while informer.pods.get("default", "missed") is None \
                and time.time() < deadline:
            time.sleep(0.01)
        assert informer.pods.get("default", "missed") is not None
    finally:
        informer.stop()


def test_lister_hit_rate_counts():
    before_h = LISTER_REQUESTS.total(outcome="hit")
    before_m = LISTER_REQUESTS.total(outcome="miss")
    from tpushare.k8s.informer import lookup
    lister = PodLister()
    pod = make_pod(hbm=1024, name="x")
    lister.apply("ADDED", pod)
    assert lookup(lister, "pods", "default", "x") is pod
    assert lookup(lister, "pods", "default", "absent") is None
    assert lookup(None, "pods", "default", "x") is None  # no lister
    assert LISTER_REQUESTS.total(outcome="hit") - before_h == 1
    assert LISTER_REQUESTS.total(outcome="miss") - before_m == 2
    assert lister_hit_rate() is not None


# -- placement memo -----------------------------------------------------------

def rig_handlers(fc, node_lister=None, pod_lister=None):
    cache = SchedulerCache(fc, node_lister=node_lister)
    registry = Registry()
    return (cache,
            FilterHandler(cache, registry),
            PrioritizeHandler(cache, registry),
            BindHandler(cache, fc, registry, pod_lister=pod_lister))


def _memo_score_counts():
    return (MEMO_REQUESTS.get("score", "hit"),
            MEMO_REQUESTS.get("score", "miss"))


def test_prioritize_reuses_filters_memoized_scores():
    fc = cluster_with_node()
    cache, flt, prio, _ = rig_handlers(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="m1"))
    h0, m0 = _memo_score_counts()
    assert flt.handle({"Pod": pod, "NodeNames": ["n1"]})["NodeNames"] \
        == ["n1"]
    h1, m1 = _memo_score_counts()
    assert (h1 - h0, m1 - m0) == (0, 1)  # Filter computed
    ranked = prio.handle({"Pod": pod, "NodeNames": ["n1"]})
    assert [r["Host"] for r in ranked] == ["n1"]
    h2, m2 = _memo_score_counts()
    assert (h2 - h1, m2 - m1) == (1, 0)  # Prioritize served from memo


@pytest.mark.parametrize("mutate", ["bind", "remove_pod", "node_update"])
def test_memo_invalidated_by_cache_mutations(mutate):
    """A Prioritize served after an intervening allocate/remove_pod/node
    change must recompute — asserted via the memo hit/miss counters."""
    fc = cluster_with_node()
    cache, flt, prio, _ = rig_handlers(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="victim"))
    other = fc.create_pod(make_pod(hbm=4096, name="other"))
    flt.handle({"Pod": pod, "NodeNames": ["n1"]})

    if mutate == "bind":
        info = cache.get_node_info("n1")
        info.allocate(other, fc)
    elif mutate == "remove_pod":
        info = cache.get_node_info("n1")
        info.allocate(other, fc)
        bound = fc.get_pod("default", "other")
        cache.add_or_update_pod(bound)
        cache.remove_pod(bound)
    else:  # node_update: capacity change rebuilds chips
        node = fc.get_node("n1")
        for field in ("capacity", "allocatable"):
            node["status"][field][contract.RESOURCE_HBM] = str(2 * 16000)
            node["status"][field][contract.RESOURCE_COUNT] = "2"
        cache.update_node(node)

    h0, m0 = _memo_score_counts()
    prio.handle({"Pod": pod, "NodeNames": ["n1"]})
    h1, m1 = _memo_score_counts()
    assert (h1 - h0, m1 - m0) == (0, 1), \
        f"stale memo served after {mutate}"


def test_bind_seeds_allocate_from_memoized_placement():
    fc = cluster_with_node()
    cache, flt, prio, bind = rig_handlers(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="s1"))
    flt.handle({"Pod": pod, "NodeNames": ["n1"]})
    prio.handle({"Pod": pod, "NodeNames": ["n1"]})
    seed_h0 = MEMO_REQUESTS.get("seed", "hit")
    out = bind.handle({"PodName": "s1", "PodNamespace": "default",
                       "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    assert not out.get("Error")
    assert MEMO_REQUESTS.get("seed", "hit") - seed_h0 == 1
    bound = fc.get_pod("default", "s1")
    assert contract.chip_ids_from_annotations(bound) is not None


def test_memo_seed_miss_after_intervening_mutation():
    """The seed hint is generation-stamped: a mutation between
    Prioritize and Bind discards it (Bind re-searches, never trusts a
    stale placement)."""
    fc = cluster_with_node()
    cache, flt, prio, bind = rig_handlers(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="s2"))
    other = fc.create_pod(make_pod(hbm=4096, name="s2other"))
    flt.handle({"Pod": pod, "NodeNames": ["n1"]})
    prio.handle({"Pod": pod, "NodeNames": ["n1"]})
    cache.get_node_info("n1").allocate(other, fc)  # bumps generation
    seed_m0 = MEMO_REQUESTS.get("seed", "miss")
    out = bind.handle({"PodName": "s2", "PodNamespace": "default",
                       "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    assert not out.get("Error")
    assert MEMO_REQUESTS.get("seed", "miss") - seed_m0 == 1


def test_memo_differentiates_request_signatures():
    """Same pod key, different request shape (e.g. after a spec edit)
    must not serve the old entry."""
    fc = cluster_with_node()
    cache = SchedulerCache(fc)
    pod = fc.create_pod(make_pod(hbm=2048, name="sig"))
    req = request_from_pod(pod)
    scores, _ = cache.score_nodes(pod, req, ["n1"])
    assert scores["n1"] is not None
    import dataclasses
    bigger = dataclasses.replace(req, hbm_mib=4096)
    h0, m0 = _memo_score_counts()
    cache.score_nodes(pod, bigger, ["n1"])
    h1, m1 = _memo_score_counts()
    assert (h1 - h0, m1 - m0) == (0, 1)


# -- apiserver round-trip budget ---------------------------------------------

def test_plain_bind_hot_path_issues_zero_apiserver_reads():
    """The acceptance bar: with the informer wired, a plain (non-gang,
    non-HA) filter->prioritize->bind cycle issues 0 synchronous reads
    and at most 2 writes (placement PATCH + binding POST)."""
    fc = cluster_with_node()
    counting = CountingCluster(fc)
    informer = Informer(counting).start()
    try:
        cache, flt, prio, bind = rig_handlers(
            counting, node_lister=informer.nodes,
            pod_lister=informer.pods)
        pod = fc.create_pod(make_pod(hbm=2048, name="hot"))
        # wait for the watch to deliver the pod (deployment steady state:
        # the informer has seen every pod by the time kube-scheduler
        # calls the webhook for it)
        deadline = time.time() + 5
        while informer.pods.get("default", "hot") is None \
                and time.time() < deadline:
            time.sleep(0.01)
        before = APISERVER_REQUESTS.snapshot()
        flt.handle({"Pod": pod, "NodeNames": ["n1"]})
        prio.handle({"Pod": pod, "NodeNames": ["n1"]})
        out = bind.handle({"PodName": "hot", "PodNamespace": "default",
                           "PodUID": pod["metadata"]["uid"],
                           "Node": "n1"})
        after = APISERVER_REQUESTS.snapshot()
        assert not out.get("Error")
        hot_origins = ("filter", "prioritize", "bind")
        reads = sum(delta(before, after, verbs=READ_VERBS, origin=o)
                    for o in hot_origins)
        writes = sum(delta(before, after, verbs=WRITE_VERBS, origin=o)
                     for o in hot_origins)
        assert reads == 0, f"hot path issued {reads} apiserver reads"
        assert writes <= 2, f"hot path issued {writes} apiserver writes"
    finally:
        informer.stop()


def test_bind_pod_fetch_falls_back_on_lister_miss():
    """A pod the informer has not seen yet still binds — via exactly one
    coalesced apiserver GET."""
    fc = cluster_with_node()
    counting = CountingCluster(fc)
    # informer deliberately NOT started: every lister read misses
    empty = Informer(counting)
    cache, flt, prio, bind = rig_handlers(
        counting, node_lister=empty.nodes, pod_lister=empty.pods)
    pod = fc.create_pod(make_pod(hbm=2048, name="cold"))
    flt.handle({"Pod": pod, "NodeNames": ["n1"]})
    before = APISERVER_REQUESTS.snapshot()
    out = bind.handle({"PodName": "cold", "PodNamespace": "default",
                       "PodUID": pod["metadata"]["uid"], "Node": "n1"})
    after = APISERVER_REQUESTS.snapshot()
    assert not out.get("Error")
    assert delta(before, after, verbs=frozenset({"get_pod"}),
                 origin="bind") == 1


def test_gang_allocate_issues_at_most_one_namespace_scoped_list():
    """ISSUE acceptance: a gang member's Allocate without listers wired
    issues at most ONE pods LIST, namespace-scoped — never the two
    cluster-wide LISTs the old _gang_env paid."""
    from tests.test_deviceplugin import _gang_rig
    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc, hosts = _gang_rig()
    counting = CountingCluster(fc)
    plugin = DevicePlugin(counting, hosts[1],
                          FakeEnumerator(4, 16000, "2x2"))
    before = APISERVER_REQUESTS.snapshot()
    resp = plugin.allocate_exclusive(4)
    after = APISERVER_REQUESTS.snapshot()
    assert resp["env"][contract.ENV_GANG_ID] == "gj"
    assert delta(before, after, verbs=frozenset({"list_pods"})) == 0, \
        "gang allocate issued a cluster-wide pods LIST"
    assert delta(before, after,
                 verbs=frozenset({"list_pods_ns"})) <= 1


def test_gang_allocate_with_listers_issues_zero_pod_lists():
    from tests.test_deviceplugin import _gang_rig
    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc, hosts = _gang_rig()
    counting = CountingCluster(fc)
    informer = Informer(counting).start()
    try:
        plugin = DevicePlugin(counting, hosts[0],
                              FakeEnumerator(4, 16000, "2x2"),
                              pod_lister=informer.pods,
                              node_lister=informer.nodes)
        before = APISERVER_REQUESTS.snapshot()
        resp = plugin.allocate_exclusive(4)
        after = APISERVER_REQUESTS.snapshot()
        assert resp["env"][contract.ENV_GANG_ID] == "gj"
        lists = delta(before, after, verbs=frozenset(
            {"list_pods", "list_pods_ns", "list_pods_node"}))
        assert lists == 0, f"lister-wired allocate issued {lists} LISTs"
        assert delta(before, after,
                     verbs=frozenset({"get_node"})) == 0
    finally:
        informer.stop()


def test_allocate_falls_back_past_watch_lag():
    """A placement stamped AFTER the lister's last sync still allocates:
    the rendezvous miss triggers one real LIST."""
    from tests.test_deviceplugin import place
    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc = cluster_with_node()
    stale = Informer(fc)  # never started: permanently empty listers
    plugin = DevicePlugin(fc, "n1", FakeEnumerator(4, 16000, "2x2"),
                          pod_lister=stale.pods,
                          node_lister=stale.nodes)
    place(fc, "lagged", hbm=2048)
    resp = plugin.allocate(hbm_mib=2048)
    assert resp["pod"]["name"] == "lagged"


def test_gang_duplicate_rank_prefers_plan_host():
    """A stale same-rank pod (e.g. Terminating predecessor in the SAME
    namespace) must not hijack the rank's address: the pod on the
    stamped plan's host wins."""
    from tests.test_deviceplugin import _gang_rig
    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc, hosts = _gang_rig()
    # impostor claims rank 1, sits on no plan host, newest timestamp
    fc.create_pod({
        "metadata": {"name": "impostor", "namespace": "default",
                     "creationTimestamp": "2099-01-01T00:00:00Z",
                     "annotations": {
                         contract.ANN_GANG: "gj",
                         contract.ANN_GANG_SIZE: "8",
                         contract.ANN_GANG_RANK: "1",
                     }},
        "spec": {"hostname": "impostor", "subdomain": "gj",
                 "containers": [{"name": "c",
                                 "resources": {"limits": {}}}]},
    })
    plugin = DevicePlugin(fc, hosts[0], FakeEnumerator(4, 16000, "2x2"))
    env = plugin.allocate_exclusive(4)["env"]
    port = contract.GANG_COORDINATOR_PORT
    assert env[contract.ENV_TPU_PROCESS_ADDRESSES] == \
        f"gj-0.gj:{port},gj-1.gj:{port}"


def test_gang_peers_scoped_to_namespace():
    """A same-gang-id pod in ANOTHER namespace is invisible to peer
    discovery (the cross-namespace wrong-plan hazard)."""
    from tests.test_deviceplugin import _gang_rig
    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc, hosts = _gang_rig()
    foreign = fc.create_pod({
        "metadata": {"name": "foreign", "namespace": "other-ns",
                     "annotations": {
                         contract.ANN_GANG: "gj",
                         contract.ANN_GANG_SIZE: "8",
                         contract.ANN_GANG_RANK: "0",
                     }},
        "spec": {"hostname": "evil-0", "subdomain": "gj",
                 "containers": [{"name": "c",
                                 "resources": {"limits": {}}}]},
    })
    assert foreign["metadata"]["namespace"] == "other-ns"
    plugin = DevicePlugin(fc, hosts[0], FakeEnumerator(4, 16000, "2x2"))
    env = plugin.allocate_exclusive(4)["env"]
    port = contract.GANG_COORDINATOR_PORT
    # rank 0's address resolves to the real member, not the foreign pod
    assert env[contract.ENV_COORDINATOR_ADDRESS] == f"gj-0.gj:{port}"


def test_gang_env_warns_when_process_grid_cannot_fill(caplog):
    """When the member count cannot fill the process grid the box/local
    ratio implies, the TPU_PROCESS_BOUNDS pair is omitted WITH a warning
    (silent omission was the round-5 finding)."""
    import json as jsonlib
    import logging

    from tpushare.deviceplugin import DevicePlugin, FakeEnumerator

    fc = FakeCluster()
    fc.add_tpu_node("h0", chips=4, hbm_per_chip_mib=16000, mesh="2x2",
                    slice_id="s", slice_origin="0x0")
    plugin = DevicePlugin(fc, "h0", FakeEnumerator(4, 16000, "2x2"))
    # a 2x4 gang box over 2x2 local boxes implies a 2-process grid, but
    # the stamped plan lists only ONE member
    plan = {"box": [2, 4], "origin": [0, 0],
            "members": [{"host": "h0", "box": [2, 2],
                         "origin": [0, 0]}]}
    chosen = make_pod(count=4, name="lone", ann={
        contract.ANN_GANG: "g-under",
        contract.ANN_GANG_SIZE: "8",
        contract.ANN_GANG_RANK: "0",
        contract.ANN_GANG_PLAN: jsonlib.dumps(plan),
    })
    with caplog.at_level(logging.WARNING, "tpushare.deviceplugin"):
        env = plugin._gang_env(chosen)
    assert contract.ENV_TPU_PROCESS_BOUNDS not in env
    assert any("cannot fill" in r.message for r in caplog.records)


# -- serve engine shutdown drain (satellite) ---------------------------------

def test_serve_frontend_rejects_requests_after_stop():
    from tpushare.workloads.serve import _EngineFrontend

    class IdleEngine:
        free_slots = 0
        resident = ()

    fe = _EngineFrontend(IdleEngine())
    fe.start()
    fe.stop()
    fe.join(5)
    # a late generate_many fails fast with the shutdown error instead of
    # parking until the client timeout
    t0 = time.time()
    with pytest.raises(ValueError, match="shutting down"):
        fe.generate_many([[1, 2]], max_new=4, timeout=30)
    assert time.time() - t0 < 5
    with pytest.raises(ValueError, match="shutting down"):
        list(fe.generate_stream([1, 2], max_new=4, timeout=30))
