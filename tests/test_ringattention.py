"""Ring-attention (sequence-parallel) parity tests on the 8-device CPU mesh.

The reference has no sequence parallelism (SURVEY.md §5.7 ABSENT); this
covers the TPU build's long-context workload path: K/V chunks rotating
around the sp ring via ppermute, online-softmax combine per hop.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpushare.workloads.attention import attention_reference
from tpushare.workloads.ringattention import ring_attention


def sp_mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("sp",))


def rand_qkv(key, B=2, H=4, S=256, D=64, dtype=jnp.bfloat16):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (B, H, S, D), dtype),
            jax.random.normal(kk, (B, H, S, D), dtype),
            jax.random.normal(kv, (B, H, S, D), dtype))


def assert_close(a, b, atol=2e-2):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               atol=atol, rtol=2e-2)


@pytest.mark.tpu_kernel
def test_ring_matches_reference_causal():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(0))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    assert out.shape == q.shape and out.dtype == q.dtype
    assert_close(out, attention_reference(q, k, v, causal=True))


@pytest.mark.tpu_kernel
def test_ring_matches_reference_non_causal():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(1), S=128)
    out = ring_attention(q, k, v, mesh, causal=False)
    assert_close(out, attention_reference(q, k, v, causal=False))


@pytest.mark.tpu_kernel
def test_ring_fp32_tight_tolerance():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(2), S=64, dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(attention_reference(q, k, v)),
        atol=1e-5, rtol=1e-5)


@pytest.mark.tpu_kernel
def test_ring_output_stays_sequence_sharded():
    # the result must come back sharded over sp — no hidden all-gather
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(3), S=128)
    spec = NamedSharding(mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    assert out.sharding.is_equivalent_to(spec, out.ndim)


@pytest.mark.tpu_kernel
def test_ring_smaller_ring_sizes():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devs[:4]), ("sp",))
    q, k, v = rand_qkv(jax.random.key(4), S=96)  # 24 per shard
    out = ring_attention(q, k, v, mesh)
    assert_close(out, attention_reference(q, k, v, causal=True))


def test_ring_rejects_indivisible_seq():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(5), S=100)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh)


def test_ring_rejects_mismatched_kv():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.key(6), S=128)
    with pytest.raises(ValueError, match="equal q/kv lengths"):
        ring_attention(q, k[:, :, :64], v[:, :, :64], mesh)


def test_zigzag_order_roundtrip():
    from tpushare.workloads.ringattention import zigzag_inverse, zigzag_order
    S, n = 32, 4
    fwd = np.asarray(zigzag_order(S, n))
    inv = np.asarray(zigzag_inverse(S, n))
    x = np.arange(S)
    assert (x[fwd][inv] == x).all()
    # rank 0 holds halves 0 and 2n-1 (positions 0..3 and 28..31)
    assert list(fwd[:8]) == [0, 1, 2, 3, 28, 29, 30, 31]


@pytest.mark.tpu_kernel
def test_zigzag_matches_reference_causal():
    from tpushare.workloads.ringattention import (
        ring_attention, zigzag_inverse, zigzag_order)
    mesh = sp_mesh()
    n = mesh.shape["sp"]
    B, H, S, D = 2, 2, 64, 16
    q, k, v = rand_qkv(jax.random.key(11), B=B, H=H, S=S, D=D)
    perm = zigzag_order(S, n)
    inv = zigzag_inverse(S, n)
    out_z = ring_attention(q[:, :, perm], k[:, :, perm], v[:, :, perm],
                           mesh, causal=True, zigzag=True)
    out = out_z[:, :, inv]
    ref = attention_reference(q, k, v, causal=True)
    assert_close(out, ref)


@pytest.mark.tpu_kernel
def test_zigzag_matches_reference_noncausal():
    # NOTE: with causal=False the position bookkeeping is inert, so this
    # only checks permutation equivariance of the non-causal ring — the
    # causal tests are what exercise the zigzag math.
    from tpushare.workloads.ringattention import (
        ring_attention, zigzag_inverse, zigzag_order)
    mesh = sp_mesh()
    n = mesh.shape["sp"]
    B, H, S, D = 1, 2, 48, 8
    q, k, v = rand_qkv(jax.random.key(12), B=B, H=H, S=S, D=D)
    perm = zigzag_order(S, n)
    inv = zigzag_inverse(S, n)
    out_z = ring_attention(q[:, :, perm], k[:, :, perm], v[:, :, perm],
                           mesh, causal=False, zigzag=True)
    assert_close(out_z[:, :, inv],
                 attention_reference(q, k, v, causal=False))


@pytest.mark.tpu_kernel
def test_zigzag_matches_reference_causal_small_ring():
    # second causal shape on a SMALLER ring (n=2): different half-chunk
    # arithmetic ((2n-1-r) offsets) than the n=8 case
    from tpushare.workloads.ringattention import (
        ring_attention, zigzag_inverse, zigzag_order)
    mesh = sp_mesh(2)
    B, H, S, D = 2, 3, 40, 8
    q, k, v = rand_qkv(jax.random.key(14), B=B, H=H, S=S, D=D)
    perm = zigzag_order(S, 2)
    inv = zigzag_inverse(S, 2)
    out_z = ring_attention(q[:, :, perm], k[:, :, perm], v[:, :, perm],
                           mesh, causal=True, zigzag=True)
    assert_close(out_z[:, :, inv],
                 attention_reference(q, k, v, causal=True))


def test_zigzag_rejects_odd_chunk():
    from tpushare.workloads.ringattention import ring_attention
    mesh = sp_mesh()
    n = mesh.shape["sp"]
    S = n * 3  # odd per-rank chunk
    q, k, v = rand_qkv(jax.random.key(13), B=1, H=1, S=S, D=8)
    with pytest.raises(ValueError, match="zigzag"):
        ring_attention(q, k, v, mesh, causal=True, zigzag=True)


@pytest.mark.tpu_kernel
def test_ring_gqa_native_matches_expanded_reference():
    """GQA-native ring: k/v carry the SMALL head count through the ring
    (1/G of the ppermute bytes per hop) and must match the reference on
    expanded heads — causal, zigzag, and non-causal."""
    from tpushare.workloads.attention import attention_reference
    from tpushare.workloads.ringattention import zigzag_inverse, zigzag_order

    mesh = sp_mesh()
    B, H, Hkv, S, D = 2, 8, 2, 128, 16
    ks = jax.random.split(jax.random.key(40), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32)
    g = H // Hkv
    kx, vx = jnp.repeat(k, g, 1), jnp.repeat(v, g, 1)

    for causal in (True, False):
        ref = attention_reference(q, kx, vx, causal=causal)
        out = jax.jit(lambda q, k, v, c=causal: ring_attention(
            q, k, v, mesh, causal=c))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"causal={causal}")

    n = mesh.shape["sp"]
    perm, inv = zigzag_order(S, n), zigzag_inverse(S, n)
    ref = attention_reference(q, kx, vx, causal=True)
    out_z = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True, zigzag=True))(
        q[:, :, perm], k[:, :, perm], v[:, :, perm])
    np.testing.assert_allclose(np.asarray(out_z[:, :, inv]),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)
