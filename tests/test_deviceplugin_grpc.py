"""Kubelet gRPC device-plugin tests.

A FakeKubelet (real grpcio server speaking v1beta1.Registration) drives the
plugin's real gRPC endpoints end to end the way kubelet does on a node:
Register -> GetDevicePluginOptions -> ListAndWatch stream -> Allocate with
kubelet-chosen device IDs. This covers the transport the reference's
sibling plugin serves (/root/reference/docs/designs/designs.md:95-101,
/root/reference/config/device-plugin-ds.yaml:27-44); the JSON socket in
transport.py is debug-only.
"""

import threading
import time

import grpc
import pytest

from tests.test_deviceplugin import place, rig
from tpushare import contract
from tpushare.contract.constants import (
    ENV_HBM_LIMIT,
    ENV_MEM_FRACTION,
    ENV_VISIBLE_CHIPS,
    RESOURCE_COUNT,
    RESOURCE_HBM,
    UNHEALTHY_CM_KEY,
    UNHEALTHY_CM_NAMESPACE,
    UNHEALTHY_CM_PREFIX,
)
from tpushare.deviceplugin.enumerator import FakeEnumerator
from tpushare.deviceplugin.grpc_server import (
    HEALTHY,
    UNHEALTHY,
    CountResource,
    DevicePluginService,
    FakeKubelet,
    HBMResource,
)
from tpushare.deviceplugin.plugin import DevicePlugin


@pytest.fixture
def plugin_dir(tmp_path):
    d = tmp_path / "dp"
    d.mkdir()
    return str(d)


@pytest.fixture
def stack(plugin_dir):
    """fake cluster + plugin + fake kubelet + running gRPC service."""
    fc, plugin = rig(chips=4, hbm=64, mesh="2x2")
    kubelet = FakeKubelet(plugin_dir)
    kubelet.start()
    service = DevicePluginService(plugin, plugin_dir)
    service.start(kubelet_socket=kubelet.socket_path)
    yield fc, plugin, kubelet, service
    service.stop()
    kubelet.stop()


def test_register_and_listandwatch(stack):
    fc, plugin, kubelet, service = stack
    assert set(kubelet.registered) == {RESOURCE_HBM, RESOURCE_COUNT}
    # hbm: one Device per MiB per chip; count: one Device per chip
    hbm_devs = kubelet.wait_for_devices(RESOURCE_HBM)
    count_devs = kubelet.wait_for_devices(RESOURCE_COUNT)
    assert len(hbm_devs) == 4 * 64
    assert {d.ID for d in count_devs} == {f"chip-{i}" for i in range(4)}
    assert all(d.health == HEALTHY for d in hbm_devs + count_devs)
    # both plugins advertise GetPreferredAllocation
    assert all(o.get_preferred_allocation_available
               for o in kubelet.options.values())


def test_hbm_allocate_end_to_end(stack):
    fc, plugin, kubelet, service = stack
    pod = place(fc, "w1", hbm=8)
    kubelet.wait_for_devices(RESOURCE_HBM)

    resp = kubelet.allocate(RESOURCE_HBM, 8)
    assert len(resp.container_responses) == 1
    envs = dict(resp.container_responses[0].envs)
    assert envs[ENV_HBM_LIMIT] == "8"
    granted = contract.chip_ids_from_annotations(pod)
    assert envs[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in granted)
    assert float(envs[ENV_MEM_FRACTION]) == pytest.approx(8 / 64, abs=1e-3)
    # the device passthrough mounts the extender-chosen chip
    specs = resp.container_responses[0].devices
    assert [s.host_path for s in specs] == [
        plugin.chips[i].device_path for i in granted]
    # runtime handoff completed: assigned flipped to true on the apiserver
    assert contract.is_assigned(fc.get_pod("default", "w1"))


def test_allocate_without_pending_pod_is_not_found(stack):
    fc, plugin, kubelet, service = stack
    kubelet.wait_for_devices(RESOURCE_HBM)
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.allocate(RESOURCE_HBM, 8)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_count_allocate_exclusive_steered_by_preferred(stack):
    fc, plugin, kubelet, service = stack
    pod = place(fc, "excl", hbm=0, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)

    resp = kubelet.allocate(RESOURCE_COUNT, 2)
    envs = dict(resp.container_responses[0].envs)
    granted = contract.chip_ids_from_annotations(pod)
    assert envs[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in granted)
    # exclusive pods get the whole chip: no XLA fraction cap
    assert ENV_MEM_FRACTION not in envs
    assert contract.is_assigned(fc.get_pod("default", "excl"))


def test_count_allocate_noops_for_shared_pod(stack):
    """A container requesting both tpu-hbm and tpu-count triggers one
    kubelet Allocate per resource; the count side must not steal or fail
    the rendezvous owned by the hbm side."""
    fc, plugin, kubelet, service = stack
    place(fc, "shared", hbm=8, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)

    resp = kubelet.allocate(RESOURCE_COUNT, 2)  # no-op, not an error
    assert dict(resp.container_responses[0].envs) == {}
    assert not contract.is_assigned(fc.get_pod("default", "shared"))

    resp = kubelet.allocate(RESOURCE_HBM, 8)  # the real rendezvous
    assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "8"
    assert contract.is_assigned(fc.get_pod("default", "shared"))


def test_count_allocate_noops_after_hbm_side_assigned(stack):
    """Kubelet's per-resource Allocate order is unspecified: when the
    tpu-hbm call lands first and assigns the dual-resource pod, the later
    tpu-count call must still no-op (not NOT_FOUND) or container start
    wedges permanently."""
    fc, plugin, kubelet, service = stack
    place(fc, "dual", hbm=8, count=2)
    kubelet.wait_for_devices(RESOURCE_HBM)

    resp = kubelet.allocate(RESOURCE_HBM, 8)  # hbm side first
    assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "8"
    assert contract.is_assigned(fc.get_pod("default", "dual"))

    resp = kubelet.allocate(RESOURCE_COUNT, 2)  # count side after: no-op
    assert dict(resp.container_responses[0].envs) == {}


def test_allocate_loses_to_concurrent_reclaim(stack):
    """The assigned-marking CAS: if the stale-placement reclaim strips the
    annotations between Allocate's match and its write, the Allocate must
    fail — not assign a placement-less pod whose chips were re-granted."""
    fc, plugin, kubelet, service = stack
    place(fc, "racy", hbm=8, now_ns=1)

    real_get = fc.get_pod
    calls = {"n": 0}

    def get_hook(ns, name):
        """The reclaim lands right after _mark_assigned's freshness read,
        so its CAS PUT must lose with 409 and re-validation must fail."""
        pod = real_get(ns, name)
        if name == "racy":
            calls["n"] += 1
            if calls["n"] == 1:
                fc.replace_pod(ns, name, contract.strip_placement(pod))
        return pod

    fc.get_pod = get_hook
    try:
        from tpushare.deviceplugin.plugin import AllocateError
        with pytest.raises(AllocateError):
            plugin.allocate(hbm_mib=8)
    finally:
        fc.get_pod = real_get
    # pod stayed unassigned and placement-free
    pod = fc.get_pod("default", "racy")
    assert contract.chip_ids_from_annotations(pod) is None
    assert not contract.is_assigned(pod)


def test_health_change_streams_unhealthy_devices(stack):
    fc, plugin, kubelet, service = stack
    kubelet.wait_for_devices(RESOURCE_HBM)
    # chip 3 vanishes from enumeration
    plugin._enumerator._chips = 3  # FakeEnumerator: shrink the host
    missing = service.health_tick()
    assert missing == {3}

    def chip3_unhealthy(devs):
        sick = {d.ID for d in devs if d.health == UNHEALTHY}
        return sick and all(i.startswith("hbm-c3-") for i in sick)

    devs = kubelet.wait_for_devices(RESOURCE_HBM, predicate=chip3_unhealthy)
    assert sum(d.health == UNHEALTHY for d in devs) == 64
    count_devs = kubelet.wait_for_devices(
        RESOURCE_COUNT,
        predicate=lambda ds: any(d.health == UNHEALTHY for d in ds))
    assert {d.ID for d in count_devs if d.health == UNHEALTHY} == {"chip-3"}
    # and the extender-facing configmap was written too
    cm = fc.get_configmap(UNHEALTHY_CM_NAMESPACE, UNHEALTHY_CM_PREFIX + "n1")
    assert cm["data"][UNHEALTHY_CM_KEY] == "3"


def test_gib_unit_mode(plugin_dir):
    """unit_mib=1024 is the reference's --memory-unit=GiB deployment mode
    (device-plugin-ds.yaml:33): the WHOLE stack — node capacity, pod
    requests, annotations, kubelet device count — is GiB-denominated, and
    only the container env converts back to real MiB."""
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    # GiB-denominated cluster: capacity 16 units/chip
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16, mesh="2x1")
    enum = FakeEnumerator(2, 16 * 1024, "2x1")  # real chips: 16 GiB HBM
    plugin = DevicePlugin(fc, "n1", enum, unit_mib=1024)
    kubelet = FakeKubelet(plugin_dir)
    kubelet.start()
    service = DevicePluginService(plugin, plugin_dir)
    try:
        service.start(kubelet_socket=kubelet.socket_path)
        devs = kubelet.wait_for_devices(RESOURCE_HBM)
        assert len(devs) == 2 * 16
        # node resource report is unit-denominated too
        report = plugin.resource_report()
        assert report["status"]["capacity"][RESOURCE_HBM] == "32"
        # pod asks for 2 GiB -> kubelet sends 2 device IDs -> env in MiB
        place(fc, "w1", hbm=2)
        resp = kubelet.allocate(RESOURCE_HBM, 2)
        envs = dict(resp.container_responses[0].envs)
        assert envs[ENV_HBM_LIMIT] == "2048"
        assert float(envs[ENV_MEM_FRACTION]) == pytest.approx(
            2048 / 16384, abs=1e-3)
    finally:
        service.stop()
        kubelet.stop()


def test_multicontainer_pod_allocates_idempotently(stack):
    """Kubelet issues one Allocate per container; the second call for the
    same pod must return the same env, not NOT_FOUND."""
    fc, plugin, kubelet, service = stack
    place(fc, "mc", hbm=8)
    kubelet.wait_for_devices(RESOURCE_HBM)
    first = kubelet.allocate(RESOURCE_HBM, 8)
    second = kubelet.allocate(RESOURCE_HBM, 8)  # rematch, no re-patch
    assert dict(first.container_responses[0].envs) == dict(
        second.container_responses[0].envs)
    assert contract.is_assigned(fc.get_pod("default", "mc"))


def test_exclusive_allocate_unmatched_count_errors(stack):
    """A count request no pod explains must fail container start, not
    silently run without TPUs."""
    fc, plugin, kubelet, service = stack
    place(fc, "excl", hbm=0, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.allocate(RESOURCE_COUNT, 3)  # pod wants 2, not 3
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_kubelet_restart_reregisters(stack):
    import os

    fc, plugin, kubelet, service = stack
    first = dict(kubelet.registered)
    # kubelet restart wipes the device-plugins dir
    for s in service.servers:
        os.unlink(s.socket_path)
    kubelet.registered.clear()

    stop = threading.Event()
    t = threading.Thread(
        target=service.run,
        kwargs={"stop": stop, "health_interval": 0.05,
                "kubelet_socket": kubelet.socket_path},
        daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and set(
                kubelet.registered) != set(first):
            time.sleep(0.05)
        assert set(kubelet.registered) == set(first)
        # endpoints serve again after the restart
        pod = place(fc, "after-restart", hbm=4)
        resp = kubelet.allocate(RESOURCE_HBM, 4)
        assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "4"
        assert contract.is_assigned(fc.get_pod("default", "after-restart"))
        del pod
    finally:
        stop.set()
        t.join(timeout=5)


def test_allocate_storm_vs_reclaim_under_chaos():
    """Concurrent Allocates and the stale-placement reclaim race over the
    same pods while the apiserver randomly fails writes. Core invariant of
    the CAS protocol: a pod is never left assigned=true without its
    placement annotations (that would mean a container got chips the
    extender no longer accounts)."""
    import threading

    from tpushare.deviceplugin.plugin import AllocateError, DevicePlugin
    from tpushare.k8s import ChaosCluster, FakeCluster

    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=64, mesh="2x2")
    chaos = ChaosCluster(fc, seed=11)
    enum = FakeEnumerator(4, 64, "2x2")
    plugin = DevicePlugin(chaos, "n1", enum)
    for i in range(8):
        place(fc, f"racer-{i}", hbm=4, now_ns=1)  # all immediately stale

    chaos.fail("replace_pod", probability=0.25, times=None)
    chaos.fail("get_pod", probability=0.05, times=None)

    stop = threading.Event()
    errors: list[Exception] = []

    def storm_allocate():
        while not stop.is_set():
            try:
                plugin.allocate(hbm_mib=4)
            except (AllocateError, Exception):  # noqa: BLE001 — chaos
                pass

    def storm_gc():
        while not stop.is_set():
            try:
                plugin.gc_stale_assignments(max_pending_seconds=0.001)
            except Exception:  # noqa: BLE001 — chaos
                pass

    threads = [threading.Thread(target=storm_allocate) for _ in range(3)]
    threads.append(threading.Thread(target=storm_gc))
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)

    assert sum(chaos.injected.values()) > 0, "storm injected nothing"
    assigned_without_placement = []
    resolved = 0
    for i in range(8):
        pod = fc.get_pod("default", f"racer-{i}")
        has_placement = contract.chip_ids_from_annotations(pod) is not None
        if contract.is_assigned(pod) and not has_placement:
            assigned_without_placement.append(pod["metadata"]["name"])
        if contract.is_assigned(pod) or not has_placement:
            resolved += 1
    assert assigned_without_placement == []
    assert resolved > 0, "storm resolved nothing (allocate and gc both idle)"
    del errors


def test_hbm_preferred_allocation_fungible():
    fc, plugin = rig(chips=2, hbm=8, mesh="2x1")
    res = HBMResource(plugin)
    got = res.preferred([f"hbm-c0-u{i}" for i in range(8)],
                        ["hbm-c1-u0"], 3)
    assert len(got) == 3 and got[0] == "hbm-c1-u0"


def test_count_preferred_matches_extender_choice():
    fc, plugin = rig(chips=4, hbm=64, mesh="2x2")
    pod = place(fc, "excl", hbm=0, count=2)
    granted = contract.chip_ids_from_annotations(pod)
    res = CountResource(plugin)
    got = res.preferred([f"chip-{i}" for i in range(4)], [], 2)
    assert got == [f"chip-{i}" for i in granted]
