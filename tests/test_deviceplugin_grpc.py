"""Kubelet gRPC device-plugin tests.

A FakeKubelet (real grpcio server speaking v1beta1.Registration) drives the
plugin's real gRPC endpoints end to end the way kubelet does on a node:
Register -> GetDevicePluginOptions -> ListAndWatch stream -> Allocate with
kubelet-chosen device IDs. This covers the transport the reference's
sibling plugin serves (/root/reference/docs/designs/designs.md:95-101,
/root/reference/config/device-plugin-ds.yaml:27-44); the JSON socket in
transport.py is debug-only.
"""

import threading
import time

import grpc
import pytest

from tests.test_deviceplugin import place, rig
from tpushare import contract
from tpushare.contract.constants import (
    ENV_HBM_LIMIT,
    ENV_MEM_FRACTION,
    ENV_VISIBLE_CHIPS,
    RESOURCE_COUNT,
    RESOURCE_HBM,
    UNHEALTHY_CM_KEY,
    UNHEALTHY_CM_NAMESPACE,
    UNHEALTHY_CM_PREFIX,
)
from tpushare.deviceplugin.enumerator import FakeEnumerator
from tpushare.deviceplugin.grpc_server import (
    HEALTHY,
    UNHEALTHY,
    CountResource,
    DevicePluginService,
    FakeKubelet,
    HBMResource,
)
from tpushare.deviceplugin.plugin import DevicePlugin
from tpushare.deviceplugin.protos import deviceplugin_pb2 as pb
from tpushare.k8s import FakeCluster


@pytest.fixture
def plugin_dir(tmp_path):
    d = tmp_path / "dp"
    d.mkdir()
    return str(d)


@pytest.fixture
def stack(plugin_dir):
    """fake cluster + plugin + fake kubelet + running gRPC service."""
    fc, plugin = rig(chips=4, hbm=64, mesh="2x2")
    kubelet = FakeKubelet(plugin_dir)
    kubelet.start()
    service = DevicePluginService(plugin, plugin_dir)
    service.start(kubelet_socket=kubelet.socket_path)
    yield fc, plugin, kubelet, service
    service.stop()
    kubelet.stop()


def test_register_and_listandwatch(stack):
    fc, plugin, kubelet, service = stack
    assert set(kubelet.registered) == {RESOURCE_HBM, RESOURCE_COUNT}
    # hbm: one Device per MiB per chip; count: one Device per chip
    hbm_devs = kubelet.wait_for_devices(RESOURCE_HBM)
    count_devs = kubelet.wait_for_devices(RESOURCE_COUNT)
    assert len(hbm_devs) == 4 * 64
    assert {d.ID for d in count_devs} == {f"chip-{i}" for i in range(4)}
    assert all(d.health == HEALTHY for d in hbm_devs + count_devs)
    # both plugins advertise GetPreferredAllocation
    assert all(o.get_preferred_allocation_available
               for o in kubelet.options.values())


def test_hbm_allocate_end_to_end(stack):
    fc, plugin, kubelet, service = stack
    pod = place(fc, "w1", hbm=8)
    kubelet.wait_for_devices(RESOURCE_HBM)

    resp = kubelet.allocate(RESOURCE_HBM, 8)
    assert len(resp.container_responses) == 1
    envs = dict(resp.container_responses[0].envs)
    assert envs[ENV_HBM_LIMIT] == "8"
    granted = contract.chip_ids_from_annotations(pod)
    assert envs[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in granted)
    assert float(envs[ENV_MEM_FRACTION]) == pytest.approx(8 / 64, abs=1e-3)
    # the device passthrough mounts the extender-chosen chip
    specs = resp.container_responses[0].devices
    assert [s.host_path for s in specs] == [
        plugin.chips[i].device_path for i in granted]
    # runtime handoff completed: assigned flipped to true on the apiserver
    assert contract.is_assigned(fc.get_pod("default", "w1"))


def test_allocate_without_pending_pod_is_not_found(stack):
    fc, plugin, kubelet, service = stack
    kubelet.wait_for_devices(RESOURCE_HBM)
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.allocate(RESOURCE_HBM, 8)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_count_allocate_exclusive_steered_by_preferred(stack):
    fc, plugin, kubelet, service = stack
    pod = place(fc, "excl", hbm=0, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)

    resp = kubelet.allocate(RESOURCE_COUNT, 2)
    envs = dict(resp.container_responses[0].envs)
    granted = contract.chip_ids_from_annotations(pod)
    assert envs[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in granted)
    # exclusive pods get the whole chip: no XLA fraction cap
    assert ENV_MEM_FRACTION not in envs
    assert contract.is_assigned(fc.get_pod("default", "excl"))


def test_count_allocate_noops_for_shared_pod(stack):
    """A container requesting both tpu-hbm and tpu-count triggers one
    kubelet Allocate per resource; the count side must not steal or fail
    the rendezvous owned by the hbm side."""
    fc, plugin, kubelet, service = stack
    place(fc, "shared", hbm=8, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)

    resp = kubelet.allocate(RESOURCE_COUNT, 2)  # no-op, not an error
    assert dict(resp.container_responses[0].envs) == {}
    assert not contract.is_assigned(fc.get_pod("default", "shared"))

    resp = kubelet.allocate(RESOURCE_HBM, 8)  # the real rendezvous
    assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "8"
    assert contract.is_assigned(fc.get_pod("default", "shared"))


def test_count_allocate_noops_after_hbm_side_assigned(stack):
    """Kubelet's per-resource Allocate order is unspecified: when the
    tpu-hbm call lands first and assigns the dual-resource pod, the later
    tpu-count call must still no-op (not NOT_FOUND) or container start
    wedges permanently."""
    fc, plugin, kubelet, service = stack
    place(fc, "dual", hbm=8, count=2)
    kubelet.wait_for_devices(RESOURCE_HBM)

    resp = kubelet.allocate(RESOURCE_HBM, 8)  # hbm side first
    assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "8"
    assert contract.is_assigned(fc.get_pod("default", "dual"))

    resp = kubelet.allocate(RESOURCE_COUNT, 2)  # count side after: no-op
    assert dict(resp.container_responses[0].envs) == {}


def test_allocate_loses_to_concurrent_reclaim(stack):
    """The assigned-marking CAS: if the stale-placement reclaim strips the
    annotations between Allocate's match and its write, the Allocate must
    fail — not assign a placement-less pod whose chips were re-granted."""
    fc, plugin, kubelet, service = stack
    place(fc, "racy", hbm=8, now_ns=1)

    real_get = fc.get_pod
    calls = {"n": 0}

    def get_hook(ns, name):
        """The reclaim lands right after _mark_assigned's freshness read,
        so its CAS PUT must lose with 409 and re-validation must fail."""
        pod = real_get(ns, name)
        if name == "racy":
            calls["n"] += 1
            if calls["n"] == 1:
                fc.replace_pod(ns, name, contract.strip_placement(pod))
        return pod

    fc.get_pod = get_hook
    try:
        from tpushare.deviceplugin.plugin import AllocateError
        with pytest.raises(AllocateError):
            plugin.allocate(hbm_mib=8)
    finally:
        fc.get_pod = real_get
    # pod stayed unassigned and placement-free
    pod = fc.get_pod("default", "racy")
    assert contract.chip_ids_from_annotations(pod) is None
    assert not contract.is_assigned(pod)


def test_health_change_streams_unhealthy_devices(stack):
    fc, plugin, kubelet, service = stack
    kubelet.wait_for_devices(RESOURCE_HBM)
    # chip 3 vanishes from enumeration
    plugin._enumerator._chips = 3  # FakeEnumerator: shrink the host
    missing = service.health_tick()
    assert missing == {3}

    def chip3_unhealthy(devs):
        sick = {d.ID for d in devs if d.health == UNHEALTHY}
        return sick and all(i.startswith("hbm-c3-") for i in sick)

    devs = kubelet.wait_for_devices(RESOURCE_HBM, predicate=chip3_unhealthy)
    assert sum(d.health == UNHEALTHY for d in devs) == 64
    count_devs = kubelet.wait_for_devices(
        RESOURCE_COUNT,
        predicate=lambda ds: any(d.health == UNHEALTHY for d in ds))
    assert {d.ID for d in count_devs if d.health == UNHEALTHY} == {"chip-3"}
    # and the extender-facing configmap was written too
    cm = fc.get_configmap(UNHEALTHY_CM_NAMESPACE, UNHEALTHY_CM_PREFIX + "n1")
    assert cm["data"][UNHEALTHY_CM_KEY] == "3"


def test_gib_unit_mode(plugin_dir):
    """unit_mib=1024 is the reference's --memory-unit=GiB deployment mode
    (device-plugin-ds.yaml:33): the WHOLE stack — node capacity, pod
    requests, annotations, kubelet device count — is GiB-denominated, and
    only the container env converts back to real MiB."""
    from tpushare.k8s import FakeCluster

    fc = FakeCluster()
    # GiB-denominated cluster: capacity 16 units/chip
    fc.add_tpu_node("n1", chips=2, hbm_per_chip_mib=16, mesh="2x1")
    enum = FakeEnumerator(2, 16 * 1024, "2x1")  # real chips: 16 GiB HBM
    plugin = DevicePlugin(fc, "n1", enum, unit_mib=1024)
    kubelet = FakeKubelet(plugin_dir)
    kubelet.start()
    service = DevicePluginService(plugin, plugin_dir)
    try:
        service.start(kubelet_socket=kubelet.socket_path)
        devs = kubelet.wait_for_devices(RESOURCE_HBM)
        assert len(devs) == 2 * 16
        # node resource report is unit-denominated too
        report = plugin.resource_report()
        assert report["status"]["capacity"][RESOURCE_HBM] == "32"
        # pod asks for 2 GiB -> kubelet sends 2 device IDs -> env in MiB
        place(fc, "w1", hbm=2)
        resp = kubelet.allocate(RESOURCE_HBM, 2)
        envs = dict(resp.container_responses[0].envs)
        assert envs[ENV_HBM_LIMIT] == "2048"
        assert float(envs[ENV_MEM_FRACTION]) == pytest.approx(
            2048 / 16384, abs=1e-3)
    finally:
        service.stop()
        kubelet.stop()


def test_multicontainer_pod_allocates_idempotently(stack):
    """Kubelet issues one Allocate per container; the second call for the
    same pod must return the same env, not NOT_FOUND."""
    fc, plugin, kubelet, service = stack
    place(fc, "mc", hbm=8)
    kubelet.wait_for_devices(RESOURCE_HBM)
    first = kubelet.allocate(RESOURCE_HBM, 8)
    second = kubelet.allocate(RESOURCE_HBM, 8)  # rematch, no re-patch
    assert dict(first.container_responses[0].envs) == dict(
        second.container_responses[0].envs)
    assert contract.is_assigned(fc.get_pod("default", "mc"))


def test_exclusive_allocate_unmatched_count_errors(stack):
    """A count request no pod explains must fail container start, not
    silently run without TPUs."""
    fc, plugin, kubelet, service = stack
    place(fc, "excl", hbm=0, count=2)
    kubelet.wait_for_devices(RESOURCE_COUNT)
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.allocate(RESOURCE_COUNT, 3)  # pod wants 2, not 3
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_kubelet_restart_reregisters(stack):
    import os

    fc, plugin, kubelet, service = stack
    first = dict(kubelet.registered)
    # kubelet restart wipes the device-plugins dir
    for s in service.servers:
        os.unlink(s.socket_path)
    kubelet.registered.clear()

    stop = threading.Event()
    t = threading.Thread(
        target=service.run,
        kwargs={"stop": stop, "health_interval": 0.05,
                "kubelet_socket": kubelet.socket_path},
        daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and set(
                kubelet.registered) != set(first):
            time.sleep(0.05)
        assert set(kubelet.registered) == set(first)
        # endpoints serve again after the restart
        pod = place(fc, "after-restart", hbm=4)
        resp = kubelet.allocate(RESOURCE_HBM, 4)
        assert dict(resp.container_responses[0].envs)[ENV_HBM_LIMIT] == "4"
        assert contract.is_assigned(fc.get_pod("default", "after-restart"))
        del pod
    finally:
        stop.set()
        t.join(timeout=5)


def test_allocate_storm_vs_reclaim_under_chaos():
    """Concurrent Allocates and the stale-placement reclaim race over the
    same pods while the apiserver randomly fails writes. Core invariant of
    the CAS protocol: a pod is never left assigned=true without its
    placement annotations (that would mean a container got chips the
    extender no longer accounts)."""
    import threading

    from tpushare.deviceplugin.plugin import AllocateError, DevicePlugin
    from tpushare.k8s import ChaosCluster, FakeCluster

    fc = FakeCluster()
    fc.add_tpu_node("n1", chips=4, hbm_per_chip_mib=64, mesh="2x2")
    chaos = ChaosCluster(fc, seed=11)
    enum = FakeEnumerator(4, 64, "2x2")
    plugin = DevicePlugin(chaos, "n1", enum)
    for i in range(8):
        place(fc, f"racer-{i}", hbm=4, now_ns=1)  # all immediately stale

    chaos.fail("replace_pod", probability=0.25, times=None)
    chaos.fail("get_pod", probability=0.05, times=None)

    stop = threading.Event()
    errors: list[Exception] = []

    def storm_allocate():
        while not stop.is_set():
            try:
                plugin.allocate(hbm_mib=4)
            except (AllocateError, Exception):  # noqa: BLE001 — chaos
                pass

    def storm_gc():
        while not stop.is_set():
            try:
                plugin.gc_stale_assignments(max_pending_seconds=0.001)
            except Exception:  # noqa: BLE001 — chaos
                pass

    threads = [threading.Thread(target=storm_allocate) for _ in range(3)]
    threads.append(threading.Thread(target=storm_gc))
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)

    assert sum(chaos.injected.values()) > 0, "storm injected nothing"
    assigned_without_placement = []
    resolved = 0
    for i in range(8):
        pod = fc.get_pod("default", f"racer-{i}")
        has_placement = contract.chip_ids_from_annotations(pod) is not None
        if contract.is_assigned(pod) and not has_placement:
            assigned_without_placement.append(pod["metadata"]["name"])
        if contract.is_assigned(pod) or not has_placement:
            resolved += 1
    assert assigned_without_placement == []
    assert resolved > 0, "storm resolved nothing (allocate and gc both idle)"
    del errors


def test_hbm_preferred_allocation_fungible():
    fc, plugin = rig(chips=2, hbm=8, mesh="2x1")
    res = HBMResource(plugin)
    got = res.preferred([f"hbm-c0-u{i}" for i in range(8)],
                        ["hbm-c1-u0"], 3)
    assert len(got) == 3 and got[0] == "hbm-c1-u0"


def test_count_preferred_matches_extender_choice():
    fc, plugin = rig(chips=4, hbm=64, mesh="2x2")
    pod = place(fc, "excl", hbm=0, count=2)
    granted = contract.chip_ids_from_annotations(pod)
    res = CountResource(plugin)
    got = res.preferred([f"chip-{i}" for i in range(4)], [], 2)
    assert got == [f"chip-{i}" for i in granted]


# -- same-size rendezvous at the gRPC layer (VERDICT r2 item 4) ---------------

def test_placement_unit_ranges_disjoint_and_stable():
    fc, plugin = rig(chips=4, hbm=64, mesh="2x2")
    place(fc, "fill", hbm=50, now_ns=1)
    place(fc, "a", hbm=8, now_ns=2)
    place(fc, "b", hbm=8, now_ns=3)

    ranges = plugin.placement_unit_ranges()
    assert [p["metadata"]["name"] for p, _ in ranges] == ["fill", "a", "b"]
    sets = [r for _, r in ranges]
    assert all(len(r) > 0 for r in sets)
    for i in range(len(sets)):
        for j in range(i + 1, len(sets)):
            assert not (sets[i] & sets[j]), "unit ranges overlap"
    # stable across calls (kubelet may ask repeatedly)
    again = plugin.placement_unit_ranges()
    assert [r for _, r in again] == sets


def test_same_size_concurrent_starts_never_double_assign(stack):
    """THE reference's known weak joint (designs.md:97-99): two pods with
    identical HBM requests, containers started in reverse assume-time
    order. Amount-only matching sends BOTH container starts to the
    earliest pod — double-occupying its chips while the other placement
    leaks. With range identification each Allocate consumes exactly one
    placement: the two grants are disjoint, each env matches a distinct
    pod's annotation, and both pods end up assigned.

    (kubelet's v1beta1 Allocate carries no pod identity, so WHICH
    container got which same-size placement is unknowable at this layer —
    the invariant that matters is one-grant-per-placement, envs
    consistent with the granted devices.)
    """
    fc, plugin, kubelet, service = stack
    # pre-fill chip space so the two same-size pods land on DIFFERENT
    # chips and a mix-up would be observable in TPU_VISIBLE_CHIPS
    place(fc, "fill", hbm=50, now_ns=1)
    kubelet.allocate(RESOURCE_HBM, 50)
    pod_a = place(fc, "a", hbm=8, now_ns=2)
    pod_b = place(fc, "b", hbm=8, now_ns=3)
    chips_a = contract.chip_ids_from_annotations(pod_a)
    chips_b = contract.chip_ids_from_annotations(pod_b)
    assert chips_a != chips_b, "test setup: placements must differ"

    # two same-amount container starts ("b"'s container may well be
    # first — kubelet cannot say and the plugin cannot ask)
    env1 = dict(kubelet.allocate(RESOURCE_HBM, 8)
                .container_responses[0].envs)
    env2 = dict(kubelet.allocate(RESOURCE_HBM, 8)
                .container_responses[0].envs)

    got = {env1[ENV_VISIBLE_CHIPS], env2[ENV_VISIBLE_CHIPS]}
    want = {",".join(str(i) for i in chips_a),
            ",".join(str(i) for i in chips_b)}
    assert got == want, "each placement granted exactly once, no double"
    assert env1[ENV_HBM_LIMIT] == env2[ENV_HBM_LIMIT] == "8"
    assert contract.is_assigned(fc.get_pod("default", "a"))
    assert contract.is_assigned(fc.get_pod("default", "b"))


def test_same_size_kubelet_retry_is_idempotent(stack):
    """A kubelet retry re-sends the SAME devicesIDs after a dropped
    response: the exact-range match must return the same environment
    without stealing the sibling placement."""
    fc, plugin, kubelet, service = stack
    # prefill pins "a" to chip 0's remainder and pushes "b" to another
    # chip, so a cross-rendezvous would be visible in TPU_VISIBLE_CHIPS
    place(fc, "fill", hbm=50, now_ns=1)
    kubelet.allocate(RESOURCE_HBM, 50)
    pod_a = place(fc, "a", hbm=8, now_ns=2)
    pod_b = place(fc, "b", hbm=8, now_ns=3)
    chips_a = ",".join(str(i) for i in
                       contract.chip_ids_from_annotations(pod_a))
    chips_b = ",".join(str(i) for i in
                       contract.chip_ids_from_annotations(pod_b))
    assert chips_a != chips_b, "test setup: placements must differ"
    kubelet.wait_for_devices(RESOURCE_HBM)

    ranges = {p["metadata"]["name"]: r
              for p, r in plugin.placement_unit_ranges()}
    stub = kubelet._stubs[RESOURCE_HBM]

    def alloc(ids):
        resp = stub.Allocate(pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devicesIDs=sorted(ids))]),
            timeout=5.0)
        return dict(resp.container_responses[0].envs)

    first = alloc(ranges["a"])
    assert first[ENV_VISIBLE_CHIPS] == chips_a
    retry = alloc(ranges["a"])          # dropped-response retry
    assert first == retry
    # the sibling's range still rendezvouses with the sibling
    other = alloc(ranges["b"])
    assert other[ENV_VISIBLE_CHIPS] == chips_b
    assert contract.is_assigned(fc.get_pod("default", "a"))
    assert contract.is_assigned(fc.get_pod("default", "b"))


def test_same_size_gc_reclaim_mid_flight_fails_not_swaps(stack):
    """gc reclaims pod "a"'s never-started placement between the two
    container starts. The surviving pod "b" still allocates correctly,
    pod "a" stays unassigned, and a straggler Allocate replaying "a"'s
    old (now ownerless) unit range never resurrects the reclaimed
    placement — it must either fail or rendezvous with a still-valid
    placement, never return the reclaimed chips."""
    fc, plugin, kubelet, service = stack
    # prefill so "a" and "b" land on different chips and the reclaimed
    # chips are distinguishable in TPU_VISIBLE_CHIPS
    place(fc, "fill", hbm=50, now_ns=1)
    kubelet.allocate(RESOURCE_HBM, 50)
    pod_a = place(fc, "a", hbm=8, now_ns=2)
    pod_b = place(fc, "b", hbm=8, now_ns=3)
    chips_a = ",".join(str(i) for i in
                       contract.chip_ids_from_annotations(pod_a))
    chips_b = ",".join(str(i) for i in
                       contract.chip_ids_from_annotations(pod_b))
    assert chips_a != chips_b, "test setup: placements must differ"
    ranges = {p["metadata"]["name"]: r
              for p, r in plugin.placement_unit_ranges()}

    # reclaim "a" (stale placement) before any container start
    stale = fc.get_pod("default", "a")
    fc.replace_pod("default", "a", contract.strip_placement(stale))

    env = dict(kubelet.allocate(RESOURCE_HBM, 8)
               .container_responses[0].envs)
    assert env[ENV_VISIBLE_CHIPS] == chips_b
    assert contract.is_assigned(fc.get_pod("default", "b"))
    assert not contract.is_assigned(fc.get_pod("default", "a"))

    # straggler start replaying "a"'s old range (those units are still
    # free in kubelet's accounting — "a"'s container never started)
    stub = kubelet._stubs[RESOURCE_HBM]
    try:
        resp = stub.Allocate(pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(
                devicesIDs=sorted(ranges["a"]))]), timeout=5.0)
    except grpc.RpcError as e:
        assert e.code() == grpc.StatusCode.NOT_FOUND  # clean failure: ok
    else:
        # amount-fallback rematch of an assigned same-size pod is legal
        # v1beta1 behavior (indistinguishable from a multi-container
        # sibling) — but the RECLAIMED chips must never come back
        envs = dict(resp.container_responses[0].envs)
        assert envs[ENV_VISIBLE_CHIPS] != chips_a
    assert not contract.is_assigned(fc.get_pod("default", "a"))


def test_multichip_pod_range_sized_to_per_chip_grant(stack):
    """kubelet's Allocate for a dual-resource multi-chip pod carries the
    container's tpu-hbm limit — the PER-CHIP grant, not grant x chips
    (reference semantics: gpu-mem is per-device). The identifying range
    must be sized accordingly or preferred allocation skips the earlier
    multi-chip pod and cross-wires it with a later same-size single-chip
    pod."""
    fc, plugin, kubelet, service = stack
    pod_m = place(fc, "multi", hbm=8, count=2, now_ns=1)   # 2 chips @ 8
    pod_s = place(fc, "single", hbm=8, count=1, now_ns=2)  # 1 chip @ 8
    chips_m = contract.chip_ids_from_annotations(pod_m)
    chips_s = contract.chip_ids_from_annotations(pod_s)
    assert len(chips_m) == 2 and len(chips_s) == 1

    ranges = plugin.placement_unit_ranges()
    assert [p["metadata"]["name"] for p, _ in ranges] == ["multi", "single"]
    sizes = [len(r) for _, r in ranges]
    assert sizes == [8, 8], "range length == kubelet allocation_size"
    assert not (ranges[0][1] & ranges[1][1])

    # earliest pending pod wins the first same-size container start
    env1 = dict(kubelet.allocate(RESOURCE_HBM, 8)
                .container_responses[0].envs)
    assert env1[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in chips_m)
    env2 = dict(kubelet.allocate(RESOURCE_HBM, 8)
                .container_responses[0].envs)
    assert env2[ENV_VISIBLE_CHIPS] == ",".join(str(i) for i in chips_s)
    assert contract.is_assigned(fc.get_pod("default", "multi"))
    assert contract.is_assigned(fc.get_pod("default", "single"))


# -- v5p-scale device enumeration guard (VERDICT r2 item 8) -------------------

V5P_HBM_MIB = 95 * 1024  # 95 GiB/chip


def test_v5p_mib_unit_overflows_kubelet_cap_and_auto_selects_gib():
    from tpushare.deviceplugin.plugin import (
        KUBELET_GRPC_MSG_CAP,
        estimate_listandwatch_bytes,
        select_unit_mib,
    )
    chips = FakeEnumerator(4, V5P_HBM_MIB, "2x2").enumerate()
    assert estimate_listandwatch_bytes(chips, 1) > KUBELET_GRPC_MSG_CAP, \
        "v5p @ MiB must be recognized as over the 4MB cap"
    assert estimate_listandwatch_bytes(chips, 1024) < \
        KUBELET_GRPC_MSG_CAP * 0.75
    assert select_unit_mib(chips) == 1024


def test_v5p_explicit_mib_unit_fails_loud(plugin_dir):
    fc = FakeCluster()
    fc.add_tpu_node("v5p", chips=4, hbm_per_chip_mib=V5P_HBM_MIB, mesh="2x2")
    enum = FakeEnumerator(4, V5P_HBM_MIB, "2x2")
    # the transport-agnostic core tolerates it (JSON debug transport has
    # no cap) but the kubelet-facing service must refuse to start
    plugin = DevicePlugin(fc, "v5p", enum, unit_mib=1)
    service = DevicePluginService(plugin, plugin_dir)
    with pytest.raises(ValueError, match="gRPC cap"):
        service.start(register=False)
    # auto mode starts fine and lands on GiB
    plugin = DevicePlugin(fc, "v5p", enum, unit_mib="auto")
    assert plugin.unit_mib == 1024
    assert plugin.resource_report()["status"]["capacity"][
        RESOURCE_HBM] == str(4 * 95)
    service = DevicePluginService(plugin, plugin_dir)
    service.start(register=False)
    service.stop()


def test_v5p_real_serialized_listandwatch_under_cap():
    """Not just the estimate: serialize the actual ListAndWatchResponse
    proto at v5p scale with the auto-selected unit and measure it."""
    from tpushare.deviceplugin.plugin import KUBELET_GRPC_MSG_CAP
    fc = FakeCluster()
    fc.add_tpu_node("v5p", chips=4, hbm_per_chip_mib=V5P_HBM_MIB, mesh="2x2")
    plugin = DevicePlugin(fc, "v5p", FakeEnumerator(4, V5P_HBM_MIB, "2x2"),
                          unit_mib="auto")
    devs = HBMResource(plugin).devices(set())
    msg = pb.ListAndWatchResponse(devices=devs)
    assert len(msg.SerializeToString()) < KUBELET_GRPC_MSG_CAP * 0.75
    # estimate really is an upper bound for the serialized truth
    from tpushare.deviceplugin.plugin import estimate_listandwatch_bytes
    assert len(msg.SerializeToString()) <= estimate_listandwatch_bytes(
        plugin.chips, plugin.unit_mib)


def test_v5e_auto_stays_mib():
    fc = FakeCluster()
    fc.add_tpu_node("v5e", chips=4, hbm_per_chip_mib=16 * 1024, mesh="2x2")
    plugin = DevicePlugin(fc, "v5e", FakeEnumerator(4, 16 * 1024, "2x2"),
                          unit_mib="auto")
    assert plugin.unit_mib == 1, "v5e-class chips keep MiB granularity"


def test_gang_member_allocate_carries_mesh_env(plugin_dir):
    """The kubelet v1beta1 wire carries the gang runtime env end to end
    (VERDICT r4 item 4): a bound gang member's AllocateResponse contains
    the plan-derived geometry + JAX rendezvous + libtpu sub-slice env."""
    from tests.test_deviceplugin import _gang_rig

    fc, hosts = _gang_rig()
    plugin = DevicePlugin(fc, hosts[1],
                          FakeEnumerator(4, 16000, "2x2"))
    kubelet = FakeKubelet(plugin_dir)
    kubelet.start()
    service = DevicePluginService(plugin, plugin_dir)
    service.start(kubelet_socket=kubelet.socket_path)
    try:
        kubelet.wait_for_devices(RESOURCE_COUNT)
        resp = kubelet.allocate(RESOURCE_COUNT, 4)
        envs = dict(resp.container_responses[0].envs)
        port = contract.GANG_COORDINATOR_PORT
        assert envs[contract.ENV_GANG_ID] == "gj"
        assert envs[contract.ENV_PROCESS_ID] == "1"
        assert envs[contract.ENV_NUM_PROCESSES] == "2"
        assert envs[contract.ENV_COORDINATOR_ADDRESS] == f"gj-0.gj:{port}"
        assert envs[contract.ENV_TPU_PROCESS_BOUNDS] == "1,2,1"
        assert envs[contract.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] == "2,2,1"
        assert envs[contract.ENV_GANG_BOX] == "2x4"
        # per-member origin is HOST-local (the member takes its host's
        # whole 2x2 box); the member's place in the gang grid is carried
        # by PROCESS_ID + TPU_PROCESS_BOUNDS
        assert envs[contract.ENV_GANG_LOCAL_ORIGIN] == "0x0"
    finally:
        service.stop()
        kubelet.stop()
