"""QoS eviction-under-pressure drill (ISSUE 17 acceptance).

One seeded oversubscription scenario replayed through BOTH legs:

- the tiered wind tunnel (``tpushare.sim.qos.run_qos_sim``) — pure
  in-memory replay, deterministic, asserts the same invariants the
  live monitor samples;
- a live hermetic fleet (``tpushare.chaos.qos_drill``) — real
  FilterHandler/BindHandler/SchedulerCache/QosPressureMonitor over a
  FakeCluster while a ChaosConductor storm runs, with the
  QosInvariantMonitor sampling apiserver truth at every instant.

The shared verdict: guaranteed reservations are never violated at any
sampled instant, oversubscription never exceeds the declared bound,
and eviction storms stay inside the budget window.
"""

from tpushare.chaos.qos_drill import (assert_qos_drill_invariants,
                                      run_qos_drill)
from tpushare.sim.qos import run_qos_sim
from tpushare.sim.simulator import Fleet
from tpushare.sim.traces import DiurnalSpec, PodTier, synth_diurnal

# A compact tiered mix that forces borrowing AND reclamation inside a
# short trace: best-effort batch saturates the valley, guaranteed
# serving spikes at the peak.
DRILL_TIERS = (
    PodTier("g-serve", 0.35, 6144, mean_duration=0.2,
            qos_tier="guaranteed"),
    PodTier("b-dev", 0.25, 4096, mean_duration=0.3),
    PodTier("be-batch", 0.40, 8192, mean_duration=0.8,
            qos_tier="best-effort"),
)
DRILL_SPEC = DiurnalSpec(hours=1.0, period=1.0, base_rate=120.0,
                         peak_rate=360.0, tiers=DRILL_TIERS, seed=77)
DRILL_OVERCOMMIT = 1.25
DRILL_BUDGET = 4


def _drill_sim():
    fleet = Fleet.homogeneous(4, 4, 16384, (2, 2))
    return run_qos_sim(fleet, synth_diurnal(DRILL_SPEC),
                       overcommit=DRILL_OVERCOMMIT,
                       evict_budget=DRILL_BUDGET,
                       evict_window=0.25)


def test_sim_leg_isolation_invariants():
    r = _drill_sim()
    assert r.guaranteed_violations == 0
    assert r.overcommit_violations == 0
    # The scenario is only probative if borrowing actually happened
    # and pressure actually reclaimed some of it.
    assert r.reclaimed_mib > 0
    assert r.evictions >= 1
    assert r.max_window_evictions <= DRILL_BUDGET
    # Every pod eventually runs: evicted best-effort work requeues
    # (placed counts re-placements, so it can exceed pods).
    assert r.never_placed == 0
    assert r.placed >= r.pods


def test_sim_leg_is_deterministic():
    a, b = _drill_sim(), _drill_sim()
    assert a.to_json() == b.to_json()


def test_live_leg_drill_invariants():
    r = run_qos_drill()
    assert_qos_drill_invariants(r)


def test_live_leg_budget_governs_storm():
    r = run_qos_drill(evict_budget=2)
    assert_qos_drill_invariants(r)
    assert r["max_window_evictions"] <= 2
    # A tighter budget defers work instead of breaching the window.
    assert r["evictions"]["skipped_budget"] >= 1
